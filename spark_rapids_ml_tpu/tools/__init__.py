"""Operator CLIs (``python -m spark_rapids_ml_tpu.tools.<name>``).

``top`` and ``trace`` are deliberately thin shells over the wire ops any
client can speak (``health`` / ``metrics``, docs/protocol.md) — the same
numbers a real scrape pipeline would collect, rendered for a human
terminal. ``perfcheck`` gates bench records against the BENCH_r*
trajectory, and ``analyze`` (srml-check, docs/static_analysis.md) is the
AST invariant analyzer for the lock/donation/determinism/wire contracts
— both are CI gates first, CLIs second.
"""
