"""Spark ML Params/Estimator/Model contract, reproduced for the TPU framework.

The reference plugs into Spark's own machinery (``RapidsPCAParams`` extends
``PCAParams``, reference RapidsPCA.scala:34-46; ``copy(extra)`` at :86,177-180;
``DefaultParamsWritable/Readable`` at :53,90). Since this framework is
Python/JAX-first (no JVM in the loop), we reproduce the *contract* — typed
params with defaults, user-set vs default maps, fluent setters, ``copy(extra)``,
``explainParams`` and JSON persistence — so estimators behave like Spark ML
estimators and a PySpark shim can later delegate 1:1.

Design notes (intentionally NOT a port): params are declared as class
attributes and bound per-instance at construction, matching Spark's
parent-uid binding so ``copy()``/persistence round-trips preserve uids.
"""

from __future__ import annotations

import threading
import uuid
from typing import Any, Callable, Dict, Generic, Iterator, List, Optional, TypeVar

T = TypeVar("T")

_uid_lock = threading.Lock()
_uid_counters: Dict[str, int] = {}


def _random_uid(prefix: str) -> str:
    # Spark uses {prefix}_{12-hex}; keep a short monotonic suffix for readable
    # test output plus entropy for uniqueness across processes.
    with _uid_lock:
        _uid_counters[prefix] = _uid_counters.get(prefix, 0) + 1
        n = _uid_counters[prefix]
    return f"{prefix}_{uuid.uuid4().hex[:8]}{n:04x}"


class TypeConverters:
    """Value converters mirroring pyspark.ml.param.TypeConverters."""

    @staticmethod
    def toInt(value: Any) -> int:
        if isinstance(value, bool):
            raise TypeError(f"cannot convert bool {value!r} to int param")
        iv = int(value)
        if iv != value:
            raise TypeError(f"cannot losslessly convert {value!r} to int")
        return iv

    @staticmethod
    def toFloat(value: Any) -> float:
        if isinstance(value, bool):
            raise TypeError(f"cannot convert bool {value!r} to float param")
        return float(value)

    @staticmethod
    def toBoolean(value: Any) -> bool:
        if not isinstance(value, bool):
            raise TypeError(f"expected bool, got {type(value).__name__}")
        return value

    @staticmethod
    def toString(value: Any) -> str:
        if not isinstance(value, str):
            raise TypeError(f"expected str, got {type(value).__name__}")
        return value

    @staticmethod
    def toListFloat(value: Any) -> List[float]:
        return [TypeConverters.toFloat(v) for v in value]

    @staticmethod
    def identity(value: Any) -> Any:
        return value


class _Cmp:
    """Picklable predicate (lambdas would break shipping params/models to
    Spark executors through stdlib pickle)."""

    __slots__ = ("op", "a", "b")

    def __init__(self, op, a, b=None):
        self.op, self.a, self.b = op, a, b

    def __call__(self, v):
        if self.op == "gt":
            return v > self.a
        if self.op == "gtEq":
            return v >= self.a
        if self.op == "lt":
            return v < self.a
        if self.op == "ltEq":
            return v <= self.a
        if self.op == "inRange":
            return self.a <= v <= self.b
        return v in self.a  # inList

    def __getstate__(self):
        return (self.op, self.a, self.b)

    def __setstate__(self, state):
        self.op, self.a, self.b = state


class ParamValidators:
    """Value-validity predicates, mirroring org.apache.spark.ml.param.ParamValidators
    (the reference's inherited ``k`` uses ``gt(0)`` via Spark's PCAParams)."""

    @staticmethod
    def gt(lower):
        return _Cmp("gt", lower)

    @staticmethod
    def gtEq(lower):
        return _Cmp("gtEq", lower)

    @staticmethod
    def lt(upper):
        return _Cmp("lt", upper)

    @staticmethod
    def ltEq(upper):
        return _Cmp("ltEq", upper)

    @staticmethod
    def inRange(lower, upper):
        return _Cmp("inRange", lower, upper)

    @staticmethod
    def inList(allowed):
        return _Cmp("inList", tuple(allowed))


class Param(Generic[T]):
    """A named, documented, typed parameter owned by a :class:`Params` instance.

    Mirrors ``org.apache.spark.ml.param.Param`` (used by the reference's
    ``meanCentering`` BooleanParam, RapidsPCA.scala:40-41).
    """

    __slots__ = ("parent", "name", "doc", "typeConverter", "validator")

    def __init__(
        self,
        parent: "Params",
        name: str,
        doc: str,
        typeConverter: Callable[[Any], T] = TypeConverters.identity,
        validator: Optional[Callable[[T], bool]] = None,
    ):
        self.parent = parent.uid if isinstance(parent, Params) else str(parent)
        self.name = name
        self.doc = doc
        self.typeConverter = typeConverter
        self.validator = validator

    def _convert(self, value: T) -> T:
        """Convert + validate, raising the Spark-style error on rejection."""
        converted = self.typeConverter(value)
        if self.validator is not None and not self.validator(converted):
            raise ValueError(
                f"{self.parent} parameter {self.name} given invalid value "
                f"{converted!r}."
            )
        return converted

    def __repr__(self) -> str:
        return f"{self.parent}__{self.name}"

    def __hash__(self) -> int:
        return hash(repr(self))

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, Param) and repr(self) == repr(other)


class _ParamDecl:
    """Class-level declaration of a param; bound to an instance Param at init.

    Usage in an estimator class body::

        k = _ParamDecl("k", "number of principal components", TypeConverters.toInt)
    """

    __slots__ = ("name", "doc", "typeConverter", "validator")

    def __init__(self, name, doc, typeConverter=TypeConverters.identity, validator=None):
        self.name = name
        self.doc = doc
        self.typeConverter = typeConverter
        self.validator = validator


# Public alias used by model classes when declaring params.
ParamDecl = _ParamDecl


class Params:
    """Base class carrying a uid, param registry, user-set and default maps.

    Subclasses declare params with :class:`ParamDecl` class attributes; the
    constructor binds them to per-instance :class:`Param` objects (so the
    param's ``parent`` is this instance's uid, as in Spark).
    """

    # Prefix for generated uids; subclasses override.
    _uid_prefix = "params"

    def __init__(self, uid: Optional[str] = None):
        self.uid = uid or _random_uid(self._uid_prefix)
        self._params: Dict[str, Param] = {}
        self._paramMap: Dict[Param, Any] = {}
        self._defaultParamMap: Dict[Param, Any] = {}
        # Bind declared params (walk the MRO so mixins contribute).
        seen = set()
        for klass in type(self).__mro__:
            for attr_name, decl in vars(klass).items():
                if isinstance(decl, _ParamDecl) and decl.name not in seen:
                    seen.add(decl.name)
                    p = Param(
                        self, decl.name, decl.doc, decl.typeConverter, decl.validator
                    )
                    setattr(self, attr_name, p)
                    self._params[decl.name] = p

    # Attribute names reset when pickling (estimators/models ship to Spark
    # executors inside transform/feed tasks): jitted-closure caches, device
    # arrays, and mesh handles are process-local and rebuild lazily on the
    # other side. Names ending in ``_cache`` reset to {} automatically;
    # subclasses extend this tuple for other device-resident state.
    _transient_attrs: tuple = ("_mesh",)

    def __getstate__(self):
        state = dict(self.__dict__)
        for name in list(state):
            if name.endswith("_cache"):
                state[name] = {} if isinstance(state[name], dict) else None
            elif name in self._transient_attrs:
                state[name] = None
        return state

    # -- registry ----------------------------------------------------------
    @property
    def params(self) -> List[Param]:
        return [self._params[name] for name in sorted(self._params)]

    def hasParam(self, paramName: str) -> bool:
        return paramName in self._params

    def getParam(self, paramName: str) -> Param:
        if not self.hasParam(paramName):
            raise AttributeError(f"{type(self).__name__} has no param {paramName!r}")
        return self._params[paramName]

    def _resolveParam(self, param) -> Param:
        if isinstance(param, Param):
            # Accept a param belonging to a same-shaped instance (Spark
            # requires identical parent; we re-resolve by name which is what
            # user code actually needs).
            return self.getParam(param.name)
        return self.getParam(param)

    # -- set/get -----------------------------------------------------------
    def set(self, param, value) -> "Params":  # noqa: A003
        p = self._resolveParam(param)
        self._paramMap[p] = p._convert(value)
        return self

    def _set(self, **kwargs) -> "Params":
        for name, value in kwargs.items():
            p = self.getParam(name)
            self._paramMap[p] = p._convert(value)
        return self

    def setDefault(self, **kwargs) -> "Params":
        for name, value in kwargs.items():
            p = self.getParam(name)
            self._defaultParamMap[p] = p._convert(value)
        return self

    def isSet(self, param) -> bool:
        return self._resolveParam(param) in self._paramMap

    def hasDefault(self, param) -> bool:
        return self._resolveParam(param) in self._defaultParamMap

    def isDefined(self, param) -> bool:
        return self.isSet(param) or self.hasDefault(param)

    def get(self, param) -> Any:  # noqa: A003
        return self.getOrDefault(param)

    def getOrDefault(self, param) -> Any:
        p = self._resolveParam(param)
        if p in self._paramMap:
            return self._paramMap[p]
        if p in self._defaultParamMap:
            return self._defaultParamMap[p]
        raise KeyError(f"param {p.name!r} is neither set nor has a default")

    def clear(self, param) -> "Params":
        self._paramMap.pop(self._resolveParam(param), None)
        return self

    # -- copy / extract ----------------------------------------------------
    def copy(self, extra: Optional[Dict[Param, Any]] = None) -> "Params":
        """Shallow-copy with the same uid, applying ``extra`` overrides.

        Matches the ``copy(extra: ParamMap)`` contract the reference
        implements at RapidsPCA.scala:86 and :177-180.
        """
        that = type(self)(uid=self.uid) if self._accepts_uid() else type(self)()
        that.uid = self.uid
        for name, p in self._params.items():
            tp = that._params[name]
            if p in self._paramMap:
                that._paramMap[tp] = self._paramMap[p]
            if p in self._defaultParamMap:
                that._defaultParamMap[tp] = self._defaultParamMap[p]
        that._copy_extra_state(self)
        if extra:
            for param, value in extra.items():
                if isinstance(param, Param):
                    # pyspark semantics: ParamMaps key by (parent uid, name).
                    # A Param-keyed extra applies only to the instance whose
                    # uid it was bound to — a grid built on one Pipeline
                    # stage's params must pass through the Pipeline's own
                    # copy unharmed and must NOT hit same-named params on
                    # other stages (e.g. LinearRegression.maxIter vs
                    # KMeans.maxIter, or 'k' on PCA vs KMeans). Copies
                    # preserve uids, so parent-uid equality is the right key.
                    if that.hasParam(param.name) and param.parent == that.uid:
                        that.set(param, value)
                else:
                    # String keys keep the typo guard: unknown names raise.
                    that.set(param, value)
        return that

    @classmethod
    def _accepts_uid(cls) -> bool:
        import inspect

        try:
            return "uid" in inspect.signature(cls.__init__).parameters
        except (TypeError, ValueError):
            return False

    def _copy_extra_state(self, source: "Params") -> None:
        """Hook for models to copy non-param state (e.g. fitted matrices)."""

    def _copy_params_to(self, target: "Params") -> "Params":
        """Copy set and default params onto ``target`` (by name), skipping
        params the target doesn't declare. Used by Estimator._fit to flow
        parent params to the produced Model (Spark Model.copy semantics)."""
        for name, p in self._params.items():
            if not target.hasParam(name):
                continue
            if p in self._defaultParamMap:
                target.setDefault(**{name: self._defaultParamMap[p]})
            if p in self._paramMap:
                target._set(**{name: self._paramMap[p]})
        return target

    def extractParamMap(self, extra=None) -> Dict[Param, Any]:
        out = dict(self._defaultParamMap)
        out.update(self._paramMap)
        if extra:
            out.update(extra)
        return out

    def explainParam(self, param) -> str:
        p = self._resolveParam(param)
        if p in self._paramMap:
            state = f"current: {self._paramMap[p]!r}"
        elif p in self._defaultParamMap:
            state = f"default: {self._defaultParamMap[p]!r}"
        else:
            state = "undefined"
        return f"{p.name}: {p.doc} ({state})"

    def explainParams(self) -> str:
        return "\n".join(self.explainParam(p) for p in self.params)


# ---------------------------------------------------------------------------
# Shared param mixins (pyspark.ml.param.shared equivalents). The reference
# inherits inputCol/outputCol/k from Spark's PCAParams (RapidsPCA.scala:34).
# ---------------------------------------------------------------------------


class HasInputCol(Params):
    inputCol = ParamDecl("inputCol", "input column name", TypeConverters.toString)

    def getInputCol(self) -> str:
        return self.getOrDefault(self.inputCol)

    def setInputCol(self, value: str):
        return self._set(inputCol=value)


class HasOutputCol(Params):
    outputCol = ParamDecl("outputCol", "output column name", TypeConverters.toString)

    def getOutputCol(self) -> str:
        return self.getOrDefault(self.outputCol)

    def setOutputCol(self, value: str):
        return self._set(outputCol=value)


class HasFeaturesCol(Params):
    featuresCol = ParamDecl(
        "featuresCol", "features column name", TypeConverters.toString
    )

    def getFeaturesCol(self) -> str:
        return self.getOrDefault(self.featuresCol)

    def setFeaturesCol(self, value: str):
        return self._set(featuresCol=value)


class HasLabelCol(Params):
    labelCol = ParamDecl("labelCol", "label column name", TypeConverters.toString)

    def getLabelCol(self) -> str:
        return self.getOrDefault(self.labelCol)

    def setLabelCol(self, value: str):
        return self._set(labelCol=value)


class HasPredictionCol(Params):
    predictionCol = ParamDecl(
        "predictionCol", "prediction column name", TypeConverters.toString
    )

    def getPredictionCol(self) -> str:
        return self.getOrDefault(self.predictionCol)

    def setPredictionCol(self, value: str):
        return self._set(predictionCol=value)


class HasProbabilityCol(Params):
    probabilityCol = ParamDecl(
        "probabilityCol",
        "column of predicted class conditional probabilities",
        TypeConverters.toString,
    )

    def getProbabilityCol(self) -> str:
        return self.getOrDefault(self.probabilityCol)

    def setProbabilityCol(self, value: str):
        return self._set(probabilityCol=value)


class HasRawPredictionCol(Params):
    rawPredictionCol = ParamDecl(
        "rawPredictionCol",
        "raw prediction (confidence / margin) column name",
        TypeConverters.toString,
    )

    def getRawPredictionCol(self) -> str:
        return self.getOrDefault(self.rawPredictionCol)

    def setRawPredictionCol(self, value: str):
        return self._set(rawPredictionCol=value)


class HasSeed(Params):
    seed = ParamDecl("seed", "random seed", TypeConverters.toInt)

    def getSeed(self) -> int:
        return self.getOrDefault(self.seed)

    def setSeed(self, value: int):
        return self._set(seed=value)


class HasMaxIter(Params):
    maxIter = ParamDecl(
        "maxIter",
        "maximum number of iterations (>= 0)",
        TypeConverters.toInt,
        validator=ParamValidators.gtEq(0),
    )

    def getMaxIter(self) -> int:
        return self.getOrDefault(self.maxIter)

    def setMaxIter(self, value: int):
        return self._set(maxIter=value)


class HasTol(Params):
    tol = ParamDecl(
        "tol",
        "convergence tolerance (>= 0)",
        TypeConverters.toFloat,
        validator=ParamValidators.gtEq(0),
    )

    def getTol(self) -> float:
        return self.getOrDefault(self.tol)

    def setTol(self, value: float):
        return self._set(tol=value)


class HasRegParam(Params):
    regParam = ParamDecl(
        "regParam",
        "regularization parameter (>= 0)",
        TypeConverters.toFloat,
        validator=ParamValidators.gtEq(0),
    )

    def getRegParam(self) -> float:
        return self.getOrDefault(self.regParam)

    def setRegParam(self, value: float):
        return self._set(regParam=value)


class HasElasticNetParam(Params):
    elasticNetParam = ParamDecl(
        "elasticNetParam",
        "ElasticNet mixing: 0 = L2 penalty, 1 = L1 penalty",
        TypeConverters.toFloat,
        validator=ParamValidators.inRange(0.0, 1.0),
    )

    def getElasticNetParam(self) -> float:
        return self.getOrDefault(self.elasticNetParam)

    def setElasticNetParam(self, value: float):
        return self._set(elasticNetParam=value)


class HasFitIntercept(Params):
    fitIntercept = ParamDecl(
        "fitIntercept", "whether to fit an intercept term", TypeConverters.toBoolean
    )

    def getFitIntercept(self) -> bool:
        return self.getOrDefault(self.fitIntercept)

    def setFitIntercept(self, value: bool):
        return self._set(fitIntercept=value)


# ---------------------------------------------------------------------------
# Estimator / Model
# ---------------------------------------------------------------------------


class Estimator(Params):
    """fit(dataset) -> Model. Mirrors org.apache.spark.ml.Estimator."""

    def fit(self, dataset, params: Optional[Dict[Param, Any]] = None):
        if params:
            return self.copy(params).fit(dataset)
        return self._fit(dataset)

    def _fit(self, dataset):
        raise NotImplementedError


class Model(Params):
    """Transformer produced by an Estimator. Mirrors org.apache.spark.ml.Model."""

    def transform(self, dataset, params: Optional[Dict[Param, Any]] = None):
        if params:
            return self.copy(params).transform(dataset)
        return self._transform(dataset)

    def _transform(self, dataset):
        raise NotImplementedError
