"""The TPU-host data-plane daemon (see package docstring for the role).

Threading model: one acceptor thread + one thread per connection (Spark
task). Concurrent feeds to the same job serialize on the job's lock around
the device fold — the accumulate is associative, so arrival order doesn't
matter (the property the reference's ``RDD.reduce`` relied on,
RapidsRowMatrix.scala:139). Feeds to different jobs interleave freely.

Jobs: "pca" folds (count, Σx, XᵀX); "linreg" folds (XᵀX, Xᵀy, Σx, Σy,
Σy², n). ``finalize`` runs the algorithm's shared finalize (eigensolve /
normal-equations solve) and streams the result arrays back.
"""

from __future__ import annotations

import socket
import threading
from typing import Any, Dict, Optional

import jax
import numpy as np

from spark_rapids_ml_tpu.ops import gram as gram_ops
from spark_rapids_ml_tpu.parallel.mesh import DATA_AXIS, default_mesh
from spark_rapids_ml_tpu.parallel.sharding import row_sharding
from spark_rapids_ml_tpu.serve import protocol
from spark_rapids_ml_tpu.utils.logging import get_logger

logger = get_logger("serve.daemon")


class _Job:
    """One accumulation job: device state + its fold function + a lock."""

    def __init__(self, algo: str, n_cols: int, mesh):
        self.algo = algo
        self.n_cols = n_cols
        self.mesh = mesh
        self.lock = threading.Lock()
        self.rows = 0
        self.dropped = False
        self.n_data = mesh.shape[DATA_AXIS]
        self.x_sharding = row_sharding(mesh)
        self.v_sharding = row_sharding(mesh, ndim=1)
        if algo == "pca":
            self.state = gram_ops.init_stats(n_cols)
            self.update = gram_ops.streaming_update(mesh)
        elif algo == "linreg":
            from spark_rapids_ml_tpu.models.linear_regression import (
                init_normal_eq_stats,
                streaming_normal_eq_update,
            )

            self.state = init_normal_eq_stats(n_cols)
            self.update = streaming_normal_eq_update(mesh)
        else:
            raise ValueError(f"unknown algo {algo!r} (pca|linreg)")

    def _bucket(self, n: int) -> int:
        """Pad target: next power of two (≥ data-axis size).

        Spark partitions are rarely equal-sized; padding each batch to its
        exact multiple-of-n_data size would compile one donated update per
        distinct shape — unbounded in a long-lived daemon. Power-of-two
        buckets bound compilations to ~log2(max_rows) shapes; the row mask
        keeps padded rows out of the statistics."""
        b = max(self.n_data, 1)
        while b < n:
            b <<= 1
        return b

    def fold(self, x: np.ndarray, y: Optional[np.ndarray]) -> None:
        if x.shape[1] != self.n_cols:
            raise ValueError(f"batch width {x.shape[1]} != job n_cols {self.n_cols}")
        if self.algo == "linreg" and y is None:
            raise ValueError("linreg feed needs a label column")
        n = x.shape[0]
        target = self._bucket(n)
        xb = np.zeros((target,) + x.shape[1:], dtype=x.dtype)
        xb[:n] = x
        mb = np.zeros((target,), dtype=np.float32)
        mb[:n] = 1.0
        with self.lock:
            if self.dropped:
                raise KeyError("job was finalized/dropped; rows not accepted")
            xs = jax.device_put(xb, self.x_sharding)
            ms = jax.device_put(mb, self.v_sharding)
            if self.algo == "pca":
                self.state = self.update(self.state, xs, ms)
            else:
                yb = np.zeros((target,), dtype=np.asarray(y).dtype)
                yb[:n] = np.asarray(y).reshape(-1)
                ys = jax.device_put(yb, self.v_sharding)
                self.state = self.update(self.state, xs, ys, ms)
            self.rows += n

    def finalize(self, params: Dict[str, Any], drop: bool = False) -> Dict[str, np.ndarray]:
        with self.lock:
            result = self._finalize_locked(params)
            if drop:
                # set under the same lock acquisition so a straggler feed
                # blocked on it sees the flag and errors instead of folding
                # rows into a model that was already returned
                self.dropped = True
            return result

    def _finalize_locked(self, params: Dict[str, Any]) -> Dict[str, np.ndarray]:
        if self.algo == "pca":
            from spark_rapids_ml_tpu.models.pca import finalize_pca_stats

            sol = finalize_pca_stats(
                self.state,
                k=int(params["k"]),
                mean_center=bool(params.get("mean_center", True)),
                mesh=self.mesh,
                n_true=self.rows,
                solver=params.get("solver"),
            )
            return {
                "pc": sol.pc,
                "explained_variance": sol.explained_variance,
                "sigma": sol.sigma,
                "mean": sol.mean,
            }
        from spark_rapids_ml_tpu.models.linear_regression import (
            finalize_normal_eq_stats,
        )

        sol = finalize_normal_eq_stats(
            self.state,
            reg=float(params.get("reg", 0.0)),
            elastic_net=float(params.get("elastic_net", 0.0)),
            fit_intercept=bool(params.get("fit_intercept", True)),
            max_iter=int(params.get("max_iter", 500)),
            tol=float(params.get("tol", 1e-6)),
            n_true=self.rows,
        )
        return {
            "coefficients": sol.coefficients,
            "intercept": np.asarray([sol.intercept]),
            "rmse": np.asarray([sol.summary.rmse]),
            "r2": np.asarray([sol.summary.r2]),
        }


class DataPlaneDaemon:
    """Arrow-over-TCP accumulation server on the TPU host.

    Binds loopback by default; on a cluster, bind the host's NIC and keep
    the port executor-reachable only (the daemon trusts its callers the
    way the reference trusts its executors).
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0, mesh=None):
        self._host, self._port = host, port
        self._mesh = mesh
        self._jobs: Dict[str, _Job] = {}
        self._jobs_lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        self._mesh = self._mesh or default_mesh()
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((self._host, self._port))
        s.listen(64)
        self._sock = s
        self._port = s.getsockname()[1]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="srml-dataplane-accept", daemon=True
        )
        self._accept_thread.start()
        logger.info("data-plane daemon listening on %s:%d", self._host, self._port)
        return self

    @property
    def address(self):
        return self._host, self._port

    def stop(self) -> None:
        self._stop.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- serving -----------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, addr = self._sock.accept()
            except OSError:
                return  # socket closed
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True,
                name=f"srml-dataplane-{addr[1]}",
            ).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        with conn:
            while True:
                try:
                    req = protocol.recv_json(conn)
                except protocol.ProtocolError as e:
                    protocol.send_json(conn, {"ok": False, "error": str(e)})
                    return
                if req is None:
                    return  # client done
                try:
                    self._dispatch(conn, req)
                except Exception as e:  # surface to the caller, keep serving
                    logger.exception("request failed: %s", req.get("op"))
                    try:
                        protocol.send_json(conn, {"ok": False, "error": str(e)})
                    except OSError:
                        return

    def _dispatch(self, conn, req: Dict[str, Any]) -> None:
        op = req.get("op")
        if op == "feed":
            self._op_feed(conn, req)
        elif op == "finalize":
            self._op_finalize(conn, req)
        elif op == "status":
            job = self._get_job(req)
            protocol.send_json(
                conn, {"ok": True, "rows": job.rows, "algo": job.algo, "n_cols": job.n_cols}
            )
        elif op == "drop":
            with self._jobs_lock:
                job = self._jobs.pop(str(req.get("job")), None)
            if job is not None:
                with job.lock:
                    job.dropped = True
            protocol.send_json(conn, {"ok": True, "dropped": job is not None})
        elif op == "ping":
            protocol.send_json(conn, {"ok": True})
        else:
            raise ValueError(f"unknown op {op!r}")

    def _get_job(self, req) -> _Job:
        name = str(req.get("job"))
        with self._jobs_lock:
            if name not in self._jobs:
                raise KeyError(f"no such job {name!r}")
            return self._jobs[name]

    def _op_feed(self, conn, req: Dict[str, Any]) -> None:
        import pyarrow as pa

        from spark_rapids_ml_tpu.bridge.arrow import table_column_to_matrix

        payload = protocol.recv_frame(conn)
        if payload is None:
            raise protocol.ProtocolError("connection closed before feed payload")
        with pa.ipc.open_stream(payload) as reader:
            table = reader.read_all()
        name = str(req["job"])
        input_col = req.get("input_col", "features")
        x = table_column_to_matrix(table, input_col, req.get("n_cols"))
        req_algo = str(req.get("algo", "pca"))
        # Validate the batch BEFORE registering a job, so a rejected first
        # feed doesn't leave an orphan empty job (with its d×d device
        # buffers) parked under the name forever.
        y = None
        if req_algo == "linreg":
            label_col = req.get("label_col", "label")
            if label_col not in table.column_names:
                raise KeyError(f"label column {label_col!r} not in batch")
            y = np.asarray(table.column(label_col).to_numpy(zero_copy_only=False))
        with self._jobs_lock:
            job = self._jobs.get(name)
            if job is None:
                job = _Job(req_algo, x.shape[1], self._mesh)
                self._jobs[name] = job
        if job.algo != req_algo:
            raise ValueError(
                f"job {name!r} is algo {job.algo!r}; feed requested {req_algo!r}"
            )
        job.fold(x, y)
        protocol.send_json(conn, {"ok": True, "rows": job.rows})

    def _op_finalize(self, conn, req: Dict[str, Any]) -> None:
        job = self._get_job(req)
        drop = bool(req.get("drop", True))
        arrays = job.finalize(req.get("params", {}), drop=drop)
        # Unregister BEFORE sending: if the client disconnects mid-response
        # the name must not stay poisoned (dropped=True) in _jobs forever.
        if drop:
            with self._jobs_lock:
                self._jobs.pop(str(req.get("job")), None)
        protocol.send_arrays(conn, arrays, {"ok": True, "rows": job.rows})
