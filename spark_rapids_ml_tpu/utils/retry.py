"""Host-side failure handling for the data-feeding path.

The reference delegates all fault tolerance to Spark task retry — its
map/reduce stages are pure and recompute-safe (SURVEY.md §5 "failure
detection"). In this framework the equivalents are:

* the sharded fit programs are pure functions of their inputs (recompute-
  safe by construction — rerunning a failed fit is always sound);
* the host-side feeding loop (Arrow IO, host→device transfer) is the part
  that sees transient failures (storage hiccups, preemptions), handled
  here with bounded retries + backoff.
"""

from __future__ import annotations

import time
from typing import Callable, Tuple, Type, TypeVar

from spark_rapids_ml_tpu.utils.logging import get_logger

_logger = get_logger(__name__)

T = TypeVar("T")


def with_retries(
    fn: Callable[[], T],
    max_attempts: int = 3,
    retry_on: Tuple[Type[BaseException], ...] = (OSError, IOError),
    base_delay_s: float = 0.5,
    backoff: float = 2.0,
) -> T:
    """Run ``fn`` with bounded retries and exponential backoff.

    Analogous to ``spark.task.maxFailures`` for the host feeding loop;
    only exceptions in ``retry_on`` are retried, everything else raises
    immediately (a deterministic error will not fix itself).
    """
    attempt = 0
    delay = base_delay_s
    while True:
        try:
            return fn()
        except retry_on as e:
            attempt += 1
            if attempt >= max_attempts:
                raise
            _logger.warning(
                "retryable failure (attempt %d/%d): %s", attempt, max_attempts, e
            )
            time.sleep(delay)
            delay *= backoff
