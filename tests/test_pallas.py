"""Pallas kernel parity tests (interpret mode — no TPU needed)."""

import numpy as np
import pytest

from spark_rapids_ml_tpu.ops.pallas_kernels import assign_min_dist_pallas, gram_pallas


def test_gram_parity(rng):
    n, d = 1024, 256
    x = rng.normal(size=(n, d)).astype(np.float32)
    mask = np.ones((n,), dtype=np.float32)
    mask[-37:] = 0.0  # padding rows
    out = np.asarray(gram_pallas(x, mask, block_n=256, block_d=128, interpret=True))
    xm = x * mask[:, None]
    ref = xm.T @ xm
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-2)


def test_gram_block_validation(rng):
    x = rng.normal(size=(100, 64)).astype(np.float32)
    with pytest.raises(ValueError, match="not divisible"):
        gram_pallas(x, np.ones(100, np.float32), block_n=64, block_d=64, interpret=True)


def test_assign_parity(rng):
    m, d, k = 512, 32, 128
    x = rng.normal(size=(m, d)).astype(np.float32)
    centers = rng.normal(size=(k, d)).astype(np.float32)
    idx, part_d = assign_min_dist_pallas(
        x, centers, block_m=128, block_k=64, interpret=True
    )
    d2 = (
        np.sum(x**2, 1)[:, None]
        - 2 * x @ centers.T
        + np.sum(centers**2, 1)[None, :]
    )
    ref_idx = np.argmin(d2, axis=1)
    np.testing.assert_array_equal(np.asarray(idx), ref_idx)
    # partial distance + ||x||^2 == true min distance
    full = np.asarray(part_d) + np.sum(x**2, 1)
    np.testing.assert_allclose(full, d2.min(axis=1), rtol=1e-4, atol=1e-2)
