"""Fit-level crash recovery (ISSUE 4): durable job state, incarnation
fencing, and pass replay.

The claim under test: a daemon that DIES mid-fit (SIGKILL, not a polite
stop) and comes back — same address, same state directory — resurrects
its jobs at the last pass boundary, and the fit completes with a model
BITWISE-identical to the uninterrupted run. Three layers of evidence:

* daemon-level: snapshot/restore semantics, durable identity, snapshot
  deletion on drop/finalize (in-process, fast);
* flagship subprocess runs: a worker process SIGKILLed between two
  kmeans (and logreg) passes, restarted against the same state dir —
  the documented acceptance scenario (marked ``slow`` + ``recovery``);
* estimator-level: a Spark-driven fit (sparksim: real OS-process tasks,
  real TCP) whose daemon crashes at a pass boundary and is restarted by
  a supervisor — the driver's recovery ledger replays the pass and the
  fitted model matches the clean run exactly.

With recovery DISABLED the same deaths still fail loudly (stale-pass /
split-brain errors) — never silent wrong answers.
"""

import os
import socket

import numpy as np
import pytest

from spark_rapids_ml_tpu.serve import DataPlaneClient, DataPlaneDaemon
from spark_rapids_ml_tpu.utils import faults
from spark_rapids_ml_tpu.utils import metrics as metrics_mod
from spark_rapids_ml_tpu.utils.faults import FaultPlan

pytestmark = pytest.mark.recovery


@pytest.fixture(autouse=True)
def _no_plan_leaks():
    yield
    faults.deactivate()
    assert faults.active_plan() is None


def _client(daemon_or_addr, **kw):
    addr = (
        daemon_or_addr.address
        if hasattr(daemon_or_addr, "address") else daemon_or_addr
    )
    kw.setdefault("timeout", 15.0)
    kw.setdefault("backoff_base_s", 0.02)
    kw.setdefault("backoff_max_s", 0.5)
    kw.setdefault("max_op_attempts", 12)
    return DataPlaneClient(*addr, **kw)


def _counter_total(snap, name):
    return sum(
        float(s.get("value", 0.0))
        for s in (snap.get(name) or {}).get("samples", [])
    )


def _blobs(rng, n, d, k, scale=3.0):
    x = (
        rng.normal(size=(n, d))
        + scale * rng.integers(0, k, size=(n, 1))
    ).astype(np.float64)
    return x


# ------------------------- daemon-level semantics ----------------------------


def test_boot_id_stamped_on_acks_and_exposed(mesh8, rng):
    data = rng.normal(size=(32, 4))
    with DataPlaneDaemon(mesh=mesh8) as d:
        with _client(d) as c:
            info = c.server_info()
            assert info["id"] == d.instance_id
            assert info["boot_id"] == d.boot_id
            h = c.health()
            assert h["boot_id"] == d.boot_id
            assert h["durable"] is False
            c.feed("bj", data, algo="pca", partition=0)
            c.commit("bj", partition=0)
            # every ack carried the one incarnation this daemon ever had
            assert c.seen_boot_ids == {d.boot_id}
            c.drop("bj")


def test_durable_identity_survives_restart_boot_id_does_not(tmp_path, mesh8):
    state = str(tmp_path / "state")
    d1 = DataPlaneDaemon(mesh=mesh8, state_dir=state).start()
    id1, boot1 = d1.instance_id, d1.boot_id
    d1.stop()
    d2 = DataPlaneDaemon(mesh=mesh8, state_dir=state).start()
    try:
        assert d2.instance_id == id1  # identity persisted: same daemon
        assert d2.boot_id != boot1    # incarnation fresh: restart visible
        with _client(d2) as c:
            assert c.health()["durable"] is True
    finally:
        d2.stop()


def test_kmeans_job_resurrected_at_pass_boundary(tmp_path, mesh8, rng):
    """Seed + one full pass + step on daemon #1; daemon #2 over the same
    state dir must resurrect the job at pass 1 with bitwise-identical
    centers and the committed-row total — then serve pass 1 normally."""
    state = str(tmp_path / "state")
    x = _blobs(rng, 120, 5, 3)
    parts = [np.ascontiguousarray(p) for p in np.array_split(x, 3)]
    params = {"k": 3, "seed": 7}
    d1 = DataPlaneDaemon(mesh=mesh8, state_dir=state).start()
    with _client(d1) as c:
        c.seed_kmeans("rj", x[:30], k=3, params=params)
        for pid, p in enumerate(parts):
            c.feed("rj", p, algo="kmeans", partition=pid, pass_id=0,
                   params=params)
            c.commit("rj", partition=pid, pass_id=0)
        c.step("rj")
        centers1, it1 = c.get_iterate("rj")
    d1.stop()  # in-memory registry dies with the daemon

    d2 = DataPlaneDaemon(mesh=mesh8, state_dir=state).start()
    try:
        with _client(d2) as c:
            st = c.status("rj")  # first mention: lazy restore
            assert st["rows"] == x.shape[0]
            centers2, it2 = c.get_iterate("rj")
            assert it2 == it1 == 1
            np.testing.assert_array_equal(
                centers2["centers"], centers1["centers"]
            )
            # the restored job serves the next pass as if nothing happened
            for pid, p in enumerate(parts):
                c.feed("rj", p, algo="kmeans", partition=pid, pass_id=1,
                       params=params)
                c.commit("rj", partition=pid, pass_id=1)
            info = c.step("rj")
            assert info["iteration"] == 2
            assert info["pass_rows"] == x.shape[0]
            c.drop("rj")
        snap = metrics_mod.snapshot()
        assert _counter_total(snap, "srml_daemon_job_restores_total") >= 1
    finally:
        d2.stop()


def _job_snapshots(state_dir):
    return [n for n in os.listdir(state_dir) if n.startswith("job-")]


def test_drop_and_finalize_delete_the_snapshot(tmp_path, mesh8, rng):
    """A finalized or dropped job must not resurrect: its snapshot goes
    with it — and `drop` clears a snapshot even with no live job (abort
    must not leave a resurrectable ghost)."""
    state = str(tmp_path / "state")
    x = _blobs(rng, 90, 4, 3)
    params = {"k": 3, "seed": 1}
    with DataPlaneDaemon(mesh=mesh8, state_dir=state) as d:
        with _client(d) as c:
            c.seed_kmeans("dj", x[:30], k=3, params=params)
            assert _job_snapshots(state)  # seeding is the pass-0 boundary
            c.drop("dj")
            assert _job_snapshots(state) == []

            c.seed_kmeans("fj", x[:30], k=3, params=params)
            c.feed("fj", x, algo="kmeans", pass_id=0, params=params)
            c.step("fj")
            assert _job_snapshots(state)
            c.finalize("fj", {})  # default drop=True
            assert _job_snapshots(state) == []


def test_reaper_sweeps_orphan_snapshots(tmp_path, mesh8, rng):
    """A crashed fit whose driver also died leaves a snapshot no op will
    ever mention: the TTL reaper must sweep it (stale mtime, no live
    job) while leaving an in-flight job's fresh snapshot alone."""
    state = str(tmp_path / "state")
    x = _blobs(rng, 60, 4, 3)
    d = DataPlaneDaemon(
        mesh=mesh8, state_dir=state, ttl=0.5, reap_interval=0.05
    ).start()
    try:
        with _client(d) as c:
            c.seed_kmeans("live", x[:30], k=3, params={"k": 3, "seed": 1})
            # Plant an orphan: a snapshot from a "previous incarnation"
            # whose fit was abandoned, mtime well past the TTL.
            orphan = os.path.join(state, "job-ghost-0123456789.npz")
            with open(orphan, "wb") as f:
                f.write(b"npz-ish")
            os.utime(orphan, (1.0, 1.0))
            # ...and a .tmp from a writer SIGKILLed mid-snapshot (the
            # atomic-rename never happened, the except-cleanup never ran).
            litter = os.path.join(state, "tmpdead01.tmp")
            with open(litter, "wb") as f:
                f.write(b"partial")
            os.utime(litter, (1.0, 1.0))
            # ...and a served-model snapshot whose owner never called
            # drop_model, evicted (mtime) far beyond the 8x-TTL disk
            # retention window.
            ghost_model = os.path.join(state, "model-ghost-0123456789.npz")
            with open(ghost_model, "wb") as f:
                f.write(b"npz-ish")
            os.utime(ghost_model, (1.0, 1.0))
            import time as _time
            for _ in range(100):
                if not (os.path.exists(orphan) or os.path.exists(litter)
                        or os.path.exists(ghost_model)):
                    break
                c.status("live")  # keep the live job warm (not evicted)
                _time.sleep(0.05)
            assert not os.path.exists(orphan), "orphan snapshot not swept"
            assert not os.path.exists(litter), "crashed .tmp not swept"
            assert not os.path.exists(ghost_model), (
                "stale served-model snapshot not swept"
            )
            live_path = d._job_state_path("live")
            assert os.path.exists(live_path), "live job's snapshot swept!"
    finally:
        d.stop()


def test_set_iterate_creates_job_for_recovery(mesh8, rng):
    """The driver-ledger path that needs NO daemon-side durability: a
    recovery set_iterate carrying algo/n_cols/params recreates a lost
    job at the pushed iterate and pass counter."""
    k, d_cols = 3, 5
    centers = rng.normal(size=(k, d_cols))
    x = rng.normal(size=(60, d_cols))
    with DataPlaneDaemon(mesh=mesh8) as d:
        with _client(d) as c:
            # without the creation fields an unknown job stays an error
            with pytest.raises(RuntimeError, match="no such job"):
                c.set_iterate("lost", {"centers": centers}, 2)
            c.set_iterate(
                "lost", {"centers": centers}, 2, algo="kmeans",
                n_cols=d_cols, params={"k": k, "seed": 0},
            )
            got, it = c.get_iterate("lost")
            assert it == 2
            np.testing.assert_allclose(got["centers"], centers, atol=0)
            # the recreated job serves the reopened pass
            c.feed("lost", x, algo="kmeans", partition=0, pass_id=2,
                   params={"k": k})
            c.commit("lost", partition=0, pass_id=2)
            info = c.step("lost")
            assert info["iteration"] == 3 and info["pass_rows"] == 60
            c.drop("lost")


def test_top_render_shows_boot_and_restores():
    """ISSUE 4 satellite: an operator sees a restart at a glance —
    boot id + durability + resurrected-job/recovery counts."""
    from spark_rapids_ml_tpu.tools.top import render

    health = {
        "id": "abcdef", "boot_id": "b00t1d", "durable": True,
        "uptime_s": 4.2, "queue_depth": 1, "staged_bytes": 0,
        "active_jobs": 1, "served_models": 0, "busy": False,
    }
    snap = {
        "srml_daemon_job_restores_total": {
            "type": "counter", "help": "",
            "samples": [{"labels": {"algo": "kmeans"}, "value": 2}],
        },
        "srml_fit_recoveries_total": {
            "type": "counter", "help": "",
            "samples": [{"labels": {"algo": "kmeans"}, "value": 1}],
        },
    }
    screen = render(health, snap)
    assert "boot b00t1d (durable)" in screen
    assert "jobs restored 2" in screen
    assert "fit recoveries 1" in screen
    # absent fields must not render a ghost line
    assert "boot" not in render({"id": "x"}, {})


# --------------------- estimator-level recovery (sparksim) -------------------


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture
def kmeans_blob_data(rng):
    k, d = 3, 5
    centers_true = rng.normal(size=(k, d)) * 8
    x = np.concatenate(
        [centers_true[i] + rng.normal(size=(120, d)) * 0.3 for i in range(k)]
    ).astype(np.float32)
    return x[rng.permutation(len(x))]


def _supervised_daemon(port, mesh, state_dir):
    """A restartable in-process daemon at a FIXED address — the
    supervisor role a production deployment gives systemd/k8s."""
    holder = {}

    def start():
        holder["d"] = DataPlaneDaemon(
            host="127.0.0.1", port=port, mesh=mesh, state_dir=state_dir
        ).start()

    def restart():
        holder["d"].stop()
        start()

    start()
    return holder, restart


def test_spark_kmeans_fit_recovers_from_boundary_crash_bitwise(
    tmp_path, mesh8, monkeypatch, kmeans_blob_data
):
    """The estimator-level proof: the daemon dies AT a pass boundary
    (fault site daemon.pass_boundary — step applied, snapshot written,
    ack unsent), a supervisor restarts it, and the fit — recovery
    enabled — replays the pass from the driver ledger and produces the
    clean run's model bit-for-bit, same iteration count."""
    from sparksim import SimDataFrame, simdf_from_numpy
    from spark_rapids_ml_tpu.spark import estimator as spark_est
    from spark_rapids_ml_tpu.spark.estimator import SparkKMeans

    spark_est.register_dataframe_type(SimDataFrame)
    port = _free_port()
    holder, restart = _supervised_daemon(
        port, mesh8, str(tmp_path / "state")
    )
    monkeypatch.setenv("SRML_DAEMON_ADDRESS", f"127.0.0.1:{port}")
    x = kmeans_blob_data
    try:
        def fit():
            # concurrency=1: bitwise f32 fold comparison needs ordered
            # commits (same caveat as the determinism suite).
            df = simdf_from_numpy(x, n_partitions=3, concurrency=1)
            return SparkKMeans().setK(3).setMaxIter(4).setSeed(5).fit(df)

        m_clean = fit()

        monkeypatch.setenv("SRML_FIT_RECOVERY_ATTEMPTS", "2")
        plan = (
            FaultPlan(seed=3)
            .rule("daemon.pass_boundary", "crash", after=1, times=1)
            .on_crash(restart)
        )
        with faults.active(plan):
            m_rec = fit()
        assert plan.fired.get("daemon.pass_boundary") == 1, (
            "the boundary crash never fired — the run proved nothing"
        )
        np.testing.assert_array_equal(m_clean.centers, m_rec.centers)
        assert m_clean.summary.numIter == m_rec.summary.numIter
        assert m_clean.summary.trainingCost == m_rec.summary.trainingCost
        snap = metrics_mod.snapshot()
        assert _counter_total(snap, "srml_fit_recoveries_total") >= 1
        assert _counter_total(snap, "srml_daemon_job_restores_total") >= 1
    finally:
        holder["d"].stop()


def test_spark_kmeans_boundary_crash_without_recovery_fails_loudly(
    tmp_path, mesh8, monkeypatch, kmeans_blob_data
):
    """Recovery disabled (the default): the same death still fails with
    a clear error — never a silently wrong model."""
    from sparksim import SimDataFrame, simdf_from_numpy
    from spark_rapids_ml_tpu.spark import estimator as spark_est
    from spark_rapids_ml_tpu.spark.estimator import SparkKMeans

    spark_est.register_dataframe_type(SimDataFrame)
    port = _free_port()
    holder, restart = _supervised_daemon(
        port, mesh8, str(tmp_path / "state")
    )
    monkeypatch.setenv("SRML_DAEMON_ADDRESS", f"127.0.0.1:{port}")
    monkeypatch.delenv("SRML_FIT_RECOVERY_ATTEMPTS", raising=False)
    try:
        plan = (
            FaultPlan(seed=3)
            .rule("daemon.pass_boundary", "crash", after=1, times=1)
            .on_crash(restart)
        )
        with faults.active(plan):
            df = simdf_from_numpy(
                kmeans_blob_data, n_partitions=3, concurrency=1
            )
            with pytest.raises(
                RuntimeError,
                match="no rows fed this pass|row-count mismatch|"
                      "restarted mid-pass",
            ):
                SparkKMeans().setK(3).setMaxIter(4).setSeed(5).fit(df)
    finally:
        holder["d"].stop()


# ------------------- flagship: SIGKILL a daemon process ----------------------
#
# Worker spawning is centralized in conftest.py (spawn_daemon_worker /
# stop_daemon_worker — the f64-pinned env every bitwise contract needs),
# and the fault-free REFERENCE runs share the module-scoped
# worker_daemon_pair instead of paying a fresh ~4 s jax import per
# flagship (VERDICT carry #7). Only the crash VICTIMS are spawned here.

from conftest import spawn_daemon_worker, stop_daemon_worker  # noqa: E402


def _drive_kmeans_passes(c, job, parts, params, passes):
    for it in passes:
        for pid, p in enumerate(parts):
            c.feed(job, p, algo="kmeans", partition=pid, pass_id=it,
                   params=params)
            c.commit(job, partition=pid, pass_id=it)
        c.step(job)


@pytest.mark.slow
def test_flagship_sigkill_between_kmeans_passes_bitwise(
    tmp_path, rng, worker_daemon_pair
):
    """THE acceptance scenario: SIGKILL the daemon process strictly
    between two kmeans passes (after a step's ack); restart it at the
    same address over the same state_dir. The restarted daemon
    resurrects the job and the fitted model equals the uninterrupted
    fit's bit-for-bit. The uninterrupted reference runs on the module's
    shared worker (it is never killed — unique job name)."""
    x = _blobs(rng, 160, 5, 3, scale=2.0)
    parts = [np.ascontiguousarray(p) for p in np.array_split(x, 4)]
    params = {"k": 3, "seed": 11}
    seed_batch = np.concatenate(parts)[:30]
    procs = []
    try:
        # Uninterrupted reference from the shared clean worker.
        _, port_r = worker_daemon_pair[0]
        with _client(("127.0.0.1", port_r)) as c:
            c.seed_kmeans("km-ref", seed_batch, k=3, params=params)
            _drive_kmeans_passes(c, "km-ref", parts, params, range(3))
            base, _ = c.finalize("km-ref", {}, drop=False)
            c.drop("km-ref")

        # Crash run: pass 0, SIGKILL, restart, passes 1-2.
        port = _free_port()
        state = str(tmp_path / "state")
        proc1, _ = spawn_daemon_worker(port, state_dir=state)
        procs.append(proc1)
        with _client(("127.0.0.1", port)) as c:
            c.seed_kmeans("km", seed_batch, k=3, params=params)
            _drive_kmeans_passes(c, "km", parts, params, [0])
            proc1.kill()  # SIGKILL: no shutdown hooks, no flush
            proc1.wait(timeout=30)
            proc2, _ = spawn_daemon_worker(port, state_dir=state)
            procs.append(proc2)
            # The healed client resumes pass 1 against the RESURRECTED
            # job — the daemon restores it lazily at first mention.
            _drive_kmeans_passes(c, "km", parts, params, [1, 2])
            healed, _ = c.finalize("km", {}, drop=False)
            c.drop("km")
            assert len(c.seen_boot_ids) >= 2, (
                "the fit never spanned two incarnations — no crash proven"
            )
            snap = c.metrics()
            assert _counter_total(
                snap, "srml_daemon_job_restores_total"
            ) >= 1, "the job was recreated, not restored"
        np.testing.assert_array_equal(healed["centers"], base["centers"])
        assert int(healed["n_iter"][0]) == int(base["n_iter"][0])
    finally:
        for p in procs:
            stop_daemon_worker(p)


def _drive_logreg_passes(c, job, xs, ys, step_params, passes):
    info = None
    for it in passes:
        for pid in range(len(xs)):
            c.feed(job, (xs[pid], ys[pid]), algo="logreg", partition=pid,
                   pass_id=it)
            c.commit(job, partition=pid, pass_id=it)
        info = c.step(job, params=step_params)
    return info


@pytest.mark.slow
def test_flagship_sigkill_between_logreg_passes_bitwise(
    tmp_path, rng, worker_daemon_pair
):
    """The logreg half of the flagship: Newton state (w, b) survives the
    SIGKILL via the pass-boundary snapshot; the final coefficients are
    bitwise-equal to the uninterrupted fit (reference on the module's
    shared worker — never killed, unique job name)."""
    n, d = 180, 6
    x = rng.normal(size=(n, d))
    w = rng.normal(size=d)
    y = (x @ w > 0).astype(np.float64)
    xs = [np.ascontiguousarray(p) for p in np.array_split(x, 3)]
    ys = [np.ascontiguousarray(p) for p in np.array_split(y, 3)]
    step_params = {"reg": 1e-2, "fit_intercept": True}
    procs = []
    try:
        _, port_r = worker_daemon_pair[1]
        with _client(("127.0.0.1", port_r)) as c:
            _drive_logreg_passes(c, "lr-ref", xs, ys, step_params, range(3))
            base, _ = c.finalize("lr-ref", {}, drop=False)
            c.drop("lr-ref")

        port = _free_port()
        state = str(tmp_path / "state")
        proc1, _ = spawn_daemon_worker(port, state_dir=state)
        procs.append(proc1)
        with _client(("127.0.0.1", port)) as c:
            _drive_logreg_passes(c, "lr", xs, ys, step_params, [0])
            proc1.kill()
            proc1.wait(timeout=30)
            proc2, _ = spawn_daemon_worker(port, state_dir=state)
            procs.append(proc2)
            _drive_logreg_passes(c, "lr", xs, ys, step_params, [1, 2])
            healed, _ = c.finalize("lr", {}, drop=False)
            c.drop("lr")
            assert len(c.seen_boot_ids) >= 2
        np.testing.assert_array_equal(
            healed["coefficients"], base["coefficients"]
        )
        np.testing.assert_array_equal(healed["intercept"], base["intercept"])
        assert int(healed["n_iter"][0]) == int(base["n_iter"][0])
    finally:
        for p in procs:
            stop_daemon_worker(p)


@pytest.mark.slow
def test_flagship_sigkill_without_state_dir_fails_loudly(tmp_path, rng):
    """The other half of the acceptance criterion: with durability OFF,
    the restarted daemon cannot join the fit mid-flight — the next
    pass's traffic is rejected with the existing clear error (the fit
    fails; it never silently returns a model missing pass 0)."""
    n, d = 120, 4
    x = rng.normal(size=(n, d))
    y = (x @ rng.normal(size=d) > 0).astype(np.float64)
    xs = [np.ascontiguousarray(p) for p in np.array_split(x, 2)]
    ys = [np.ascontiguousarray(p) for p in np.array_split(y, 2)]
    procs = []
    try:
        port = _free_port()
        proc1, _ = spawn_daemon_worker(port)  # NO state_dir
        procs.append(proc1)
        with _client(("127.0.0.1", port)) as c:
            _drive_logreg_passes(c, "lr", xs, ys, {"reg": 0.0}, [0])
            proc1.kill()
            proc1.wait(timeout=30)
            proc2, _ = spawn_daemon_worker(port)
            procs.append(proc2)
            with pytest.raises(RuntimeError, match="behind the fit"):
                c.feed("lr", (xs[0], ys[0]), algo="logreg", partition=0,
                       pass_id=1)
    finally:
        for p in procs:
            stop_daemon_worker(p)


# ---------------- durable daemon-built KNN/ANN index snapshots ---------------
#
# VERDICT Missing #2 follow-through: a daemon-built index was the ONE
# registration a restart could not bring back ("not re-creatable" — held
# 8x the TTL in memory as a workaround). With a state_dir the finalize
# now write-ahead-snapshots the built shard (core/checkpoint.py atomic
# tmp+rename) and a restarted daemon resurrects it at first mention,
# exactly like iterative jobs — so the special case retires: durable
# registrations reap at the PLAIN TTL and come back from disk on the
# next query.


def test_knn_index_snapshot_restores_bitwise_after_kill(tmp_path, mesh8, rng):
    """Kill-and-restart: an exact-KNN shard built on daemon #1 (with a
    row_id_base — the sharded-serve id map must survive too) answers
    kneighbors on daemon #2 over the same state_dir BITWISE-identically
    to the pre-kill answers; drop_model deletes the snapshot."""
    state = str(tmp_path / "state")
    x = rng.normal(size=(200, 8)).astype(np.float64)
    q = x[:16] + 0.01 * rng.normal(size=(16, 8))
    parts = [np.ascontiguousarray(p) for p in np.array_split(x, 2)]
    d1 = DataPlaneDaemon(mesh=mesh8, state_dir=state).start()
    with _client(d1) as c:
        for pid, p in enumerate(parts):
            c.feed("kj", p, algo="knn", partition=pid)
            c.commit("kj", partition=pid)
        c.finalize_knn("kj", register_as="kidx", mode="exact",
                       row_id_base={0: 1000, 1: 5000})
        base_d, base_i = c.kneighbors("kidx", q, k=5)
    assert (base_i >= 1000).all()  # the id map is live pre-kill
    assert [f for f in os.listdir(state) if f.startswith("model-")]
    d1.stop()  # in-memory registry dies with the daemon

    d2 = DataPlaneDaemon(mesh=mesh8, state_dir=state).start()
    try:
        with _client(d2) as c:
            got_d, got_i = c.kneighbors("kidx", q, k=5)  # lazy restore
            np.testing.assert_array_equal(got_i, base_i)
            np.testing.assert_array_equal(got_d, base_d)
            assert c.drop_model("kidx")
        assert not [f for f in os.listdir(state) if f.startswith("model-")], (
            "drop_model left a resurrectable snapshot behind"
        )
    finally:
        d2.stop()


def test_ivf_index_snapshot_restores_bitwise_after_kill(tmp_path, mesh8, rng):
    """The ANN variant: centroids, padded lists, the baked-in fit
    metric AND the serving params (nprobe) all ride the snapshot — the
    restored shard's approximate answers are bitwise-identical."""
    state = str(tmp_path / "state")
    kc, d_cols = 4, 6
    centers = rng.normal(size=(kc, d_cols)) * 10
    x = np.concatenate(
        [c_ + rng.normal(size=(60, d_cols)) for c_ in centers]
    ).astype(np.float32)
    q = x[:24]
    d1 = DataPlaneDaemon(mesh=mesh8, state_dir=state).start()
    with _client(d1) as c:
        c.feed("aj", x, algo="knn", partition=0)
        c.commit("aj", partition=0)
        c.finalize_knn("aj", register_as="aidx", mode="ivf",
                       nlist=kc, nprobe=2, seed=3)
        base_d, base_i = c.kneighbors("aidx", q, k=5)
    d1.stop()

    d2 = DataPlaneDaemon(mesh=mesh8, state_dir=state).start()
    try:
        with _client(d2) as c:
            got_d, got_i = c.kneighbors("aidx", q, k=5)
            np.testing.assert_array_equal(got_i, base_i)
            np.testing.assert_array_equal(got_d, base_d)
            c.drop_model("aidx")
    finally:
        d2.stop()


def test_durable_index_reaps_at_plain_ttl_volatile_keeps_8x(
    tmp_path, mesh8, rng
):
    """The retired special case, pinned: a durable daemon's built index
    is re-creatable (from disk) and holds the PLAIN ttl_scale; a
    volatile daemon keeps the 8x hold — eviction there is forever."""
    x = rng.normal(size=(60, 4)).astype(np.float64)
    with DataPlaneDaemon(mesh=mesh8,
                         state_dir=str(tmp_path / "s")) as durable:
        with _client(durable) as c:
            c.feed("dj", x, algo="knn", partition=0)
            c.commit("dj", partition=0)
            c.finalize_knn("dj", register_as="didx", mode="exact")
        assert durable._models["didx"].ttl_scale == 1.0
    with DataPlaneDaemon(mesh=mesh8) as volatile:
        with _client(volatile) as c:
            c.feed("vj", x, algo="knn", partition=0)
            c.commit("vj", partition=0)
            c.finalize_knn("vj", register_as="vidx", mode="exact")
        assert volatile._models["vidx"].ttl_scale == 8.0


def test_evicted_durable_index_resurrects_on_query(tmp_path, mesh8, rng):
    """TTL/LRU eviction of a durable index frees the memory but not the
    answer: the next kneighbors restores it from the snapshot, bitwise."""
    state = str(tmp_path / "state")
    x = rng.normal(size=(80, 5)).astype(np.float64)
    q = x[:8] + 0.01 * rng.normal(size=(8, 5))
    with DataPlaneDaemon(mesh=mesh8, state_dir=state) as d:
        with _client(d) as c:
            c.feed("ej", x, algo="knn", partition=0)
            c.commit("ej", partition=0)
            c.finalize_knn("ej", register_as="eidx", mode="exact")
            base_d, base_i = c.kneighbors("eidx", q, k=4)
            # Simulate the reaper's eviction (memory reclaimed, disk
            # retention clock restarted).
            with d._models_lock:
                del d._models["eidx"]
            d._touch_model_state("eidx")
            got_d, got_i = c.kneighbors("eidx", q, k=4)
            np.testing.assert_array_equal(got_i, base_i)
            np.testing.assert_array_equal(got_d, base_d)
            assert "eidx" in d._models  # restored registration is live
            c.drop_model("eidx")


def test_live_index_snapshot_mtime_refreshed_by_reaper(tmp_path, mesh8, rng):
    """A LIVE durable index must never lose its snapshot to the 8×-TTL
    sweep: the reaper refreshes live registrations' snapshot mtimes each
    tick, so the retention clock counts from eviction (or death), never
    from the build — a SIGKILL after a long serving life stays
    restorable."""
    state = str(tmp_path / "state")
    x = rng.normal(size=(40, 4)).astype(np.float64)
    d = DataPlaneDaemon(
        mesh=mesh8, state_dir=state, ttl=0.5, reap_interval=0.05
    ).start()
    try:
        with _client(d) as c:
            c.feed("lj", x, algo="knn", partition=0)
            c.commit("lj", partition=0)
            c.finalize_knn("lj", register_as="lidx", mode="exact")
            path = d._model_state_path("lidx")
            # Backdate the snapshot FAR past the retention window while
            # the model stays live (queries keep touching it).
            os.utime(path, (1.0, 1.0))
            import time as _time
            deadline = _time.monotonic() + 5.0
            while (_time.monotonic() < deadline
                   and os.path.getmtime(path) < 1000.0):
                c.kneighbors("lidx", x[:4], k=2)  # keep it live
                _time.sleep(0.05)
            assert os.path.exists(path), (
                "the sweep reclaimed a LIVE index's snapshot"
            )
            assert os.path.getmtime(path) > 1000.0, (
                "the reaper never refreshed the live snapshot's mtime"
            )
            c.drop_model("lidx")
    finally:
        d.stop()
