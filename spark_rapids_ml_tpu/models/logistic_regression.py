"""LogisticRegression — placeholder, implemented in the breadth pass."""

from spark_rapids_ml_tpu.core.params import Estimator, Model


class LogisticRegression(Estimator):
    _uid_prefix = "LogisticRegression"


class LogisticRegressionModel(Model):
    _uid_prefix = "LogisticRegressionModel"
