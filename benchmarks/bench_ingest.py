"""End-to-end ingest: Arrow batches host→device, overlapped with compute.

SURVEY.md §7 hard-part (a) — the JVM↔TPU-host data plane. The headline
bench (bench.py) isolates compute by design; this one measures the full
feed path a Spark-fed fit actually exercises:

    pyarrow list column → bridge.arrow.table_column_to_matrix (zero-copy /
    native threaded cast) → jax.device_put (row-sharded) → streaming Gram
    fold (donated accumulator)

reporting sustained rows/s for (1) the bridge alone (host-side), (2) the
full ingest+compute pipeline, and comparing against (3) the compute-only
rate on device-resident data. The pipeline overlaps naturally: device_put
and the fold dispatch async while the host converts the next batch; a
>30% gap between (2) and min(1, 3) would indicate a serialization stall.

Caveat (documented, not hidden): on the axon-tunneled dev chip,
``device_put`` crosses a network tunnel, so (2) here is a LOWER bound —
on a real TPU host the transfer is local PCIe/DMA.

Baseline: an A100's effective H2D is ~20 GB/s (PCIe4 x16 measured); at
d=512 f32 that is ~9.8M rows/s. vs_baseline compares the full-pipeline
rate against that.
"""

import os
import sys

if __package__ in (None, ""):
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import time

import numpy as np

D = int(os.environ.get("SRML_BENCH_D", 512))
BATCH_ROWS = int(os.environ.get("SRML_BENCH_BATCH_ROWS", 1 << 17))  # 128k
N_BATCHES = int(os.environ.get("SRML_BENCH_BATCHES", 8))

A100_H2D_ROWS_PER_SEC = 20e9 / (D * 4)


def main() -> None:
    from benchmarks import emit, setup_platform, sync

    setup_platform()
    import jax
    import pyarrow as pa

    from spark_rapids_ml_tpu import config
    from spark_rapids_ml_tpu.bridge.arrow import (
        matrix_to_list_column,
        table_column_to_matrix,
    )
    from spark_rapids_ml_tpu.ops import gram as gram_ops
    from spark_rapids_ml_tpu.parallel.mesh import make_mesh
    from spark_rapids_ml_tpu.parallel.sharding import row_sharding

    config.set("compute_dtype", "bfloat16")
    config.set("accum_dtype", "float32")

    mesh = make_mesh(model=1)
    x_sh = row_sharding(mesh)
    m_sh = row_sharding(mesh, ndim=1)

    # Host-side Arrow batches (f32, fixed_size_list — what a configured
    # Spark Arrow exporter ships). Built once; the bench loops over them.
    rng = np.random.default_rng(0)
    host = rng.standard_normal((BATCH_ROWS, D), dtype=np.float32)
    tables = [
        pa.table({"features": matrix_to_list_column(host)}) for _ in range(2)
    ]  # two distinct buffers so no cache effects collapse the loop
    mask = np.ones((BATCH_ROWS,), np.float32)

    update = gram_ops.streaming_update(mesh)
    state = gram_ops.init_stats(D)

    # Warm: compile the fold once.
    xs = jax.device_put(host, x_sh)
    ms = jax.device_put(mask, m_sh)
    state = update(state, xs, ms)
    sync(state)

    # (1) bridge-only host rate (arrow -> contiguous matrix).
    t0 = time.perf_counter()
    for i in range(N_BATCHES):
        mat = table_column_to_matrix(tables[i % 2], "features")
    bridge_dt = (time.perf_counter() - t0) / N_BATCHES
    assert mat.shape == (BATCH_ROWS, D)

    # (3) compute-only rate on device-resident data (same fold).
    t0 = time.perf_counter()
    for _ in range(N_BATCHES):
        state = update(state, xs, ms)
    sync(state)
    compute_dt = (time.perf_counter() - t0) / N_BATCHES

    # (2) full pipeline: convert + device_put + fold, loop overlapped
    # (no per-batch sync — dispatch runs ahead while the host converts).
    t0 = time.perf_counter()
    for i in range(N_BATCHES):
        mat = table_column_to_matrix(tables[i % 2], "features")
        xb = jax.device_put(mat, x_sh)
        state = update(state, xb, ms)
    sync(state)
    pipe_dt = (time.perf_counter() - t0) / N_BATCHES

    # Transfer-only timing for the tunneled flag: a device_put of one
    # batch, synced. On the axon dev harness this crosses a network tunnel
    # at single-digit MB/s — the pipeline number then measures the TUNNEL,
    # not the architecture. Deriving the flag from the pipeline rate would
    # also fire on compute-bound smoke runs; measure the hop itself.
    t0 = time.perf_counter()
    xt = jax.device_put(host, x_sh)
    sync(xt)
    transfer_dt = time.perf_counter() - t0
    transfer_bps = BATCH_ROWS * D * 4 / transfer_dt

    pipeline_rate = BATCH_ROWS / pipe_dt
    emit(
        f"ingest_pipeline_rows_per_sec_d{D}",
        pipeline_rate,
        "rows/s",
        pipeline_rate / A100_H2D_ROWS_PER_SEC,
        bridge_rows_per_sec=round(BATCH_ROWS / bridge_dt, 1),
        compute_rows_per_sec=round(BATCH_ROWS / compute_dt, 1),
        # host→device below PCIe-class ⇒ a tunnel sits in the path and the
        # pipeline number is not an architecture measurement. Only judged
        # when the probe transfer is big enough (≥16 MB) to amortize the
        # fixed sync round-trip — tiny smoke batches would false-positive.
        tunneled=bool(BATCH_ROWS * D * 4 >= (1 << 24) and transfer_bps < 1e9),
    )


if __name__ == "__main__":
    main()
