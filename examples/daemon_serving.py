"""Serving from the data-plane daemon: TPU-resident transform + KNN.

Round-3 surface (docs/protocol.md, "Model-serving ops"): a fitted model
registers ONCE on the TPU-host daemon and then scores batches with its
arrays device-resident — the accelerator-resident columnar UDF of the
reference (RapidsPCA.scala:128-161) without its per-batch matrix
re-upload (rapidsml_jni.cu:85). KNN goes further: the executors stream
raw rows, the daemon builds the index ON ITS DEVICES, and queries are
served remotely — neither the dataset nor the dataset-sized index ever
exists on the driver.

Run: python examples/daemon_serving.py
"""

import os
import sys

if __package__ in (None, ""):  # direct script run
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from spark_rapids_ml_tpu.models.pca import PCA
from spark_rapids_ml_tpu.serve import DataPlaneClient, DataPlaneDaemon


def main() -> None:
    rng = np.random.default_rng(0)
    x = rng.normal(size=(20_000, 64)).astype(np.float32)

    with DataPlaneDaemon() as daemon:  # on the TPU host; ttl/token in prod
        host, port = daemon.address

        # --- serve a fitted model's transform from the TPU -------------
        model = PCA().setK(8).fit({"features": x})
        with DataPlaneClient(host, port) as c:
            c.ensure_model("pca-serve", "pca", model._model_data())
            # ... each executor task then scores its batches remotely:
            out = c.transform("pca-serve", x[:4096])
            print("served projection:", out["output"].shape)  # (4096, 8)

        # --- daemon-built KNN index (never driver-resident) -------------
        with DataPlaneClient(host, port) as c:
            for pid, part in enumerate(np.array_split(x, 4)):
                c.feed("knn-fit", part, algo="knn", partition=pid)
                c.commit("knn-fit", partition=pid)
            stats = c.finalize_knn(
                "knn-fit", register_as="knn-index", mode="ivf",
                nlist=64, nprobe=16,
            )
            print("index built on daemon:", {k: v.tolist() for k, v in stats.items()})
            dists, ids = c.kneighbors("knn-index", x[:8], k=5)
            print("self-nearest:", ids[:, 0].tolist())
            c.drop_model("knn-index")
            c.drop_model("pca-serve")


if __name__ == "__main__":
    main()
