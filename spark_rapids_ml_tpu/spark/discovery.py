"""TPU resource discovery for Spark's resource scheduling.

The reference relies on Spark GPU scheduling: a discovery script announces
each executor's GPUs and tasks read their assignment from
``TaskContext.resources()("gpu")`` (reference README.md:108-113,
RapidsRowMatrix.scala:125-126). Spark's discovery protocol is generic over
resource names: the script prints one JSON object
``{"name": <resource>, "addresses": [...]}`` on stdout.

``discovery_payload()`` probes TPUs on this host (JAX device enumeration,
falling back to the libtpu device files) and returns that JSON;
``write_discovery_script`` materializes a self-contained shell script for
``spark.worker.resource.tpu.discoveryScript``.
"""

from __future__ import annotations

import glob
import json
import os
import stat
from typing import List

RESOURCE_NAME = "tpu"

_SCRIPT = """#!/usr/bin/env bash
# TPU discovery script for Spark resource scheduling
# (spark.worker.resource.tpu.discoveryScript). Prints
# {"name": "tpu", "addresses": [...]} per Spark's discovery protocol.
exec python3 -m spark_rapids_ml_tpu.spark.discovery
"""


def _probe_device_files() -> List[str]:
    """Enumerate TPU chips via their device files (no jax init needed).

    Only /dev/accel* is trusted: VFIO group nodes are not TPU-specific
    (GPU passthrough creates them too, and /dev/vfio/vfio is a control
    node, not a device), so they are not counted."""
    paths = sorted(glob.glob("/dev/accel[0-9]*"))
    return [str(i) for i in range(len(paths))]


def _probe_jax() -> List[str]:
    try:
        import jax

        # Honor JAX_PLATFORMS even when a sitecustomize pre-set the config
        # (the env var is how operators scope discovery, e.g. to "cpu" on
        # non-TPU workers).
        plats = os.environ.get("JAX_PLATFORMS")
        if plats:
            try:
                jax.config.update("jax_platforms", plats)
            except RuntimeError:
                pass
        return [str(d.id) for d in jax.devices() if d.platform != "cpu"]
    except Exception:  # noqa: BLE001 - discovery must never crash the worker
        return []


def discovery_payload() -> dict:
    """The JSON object Spark's discovery protocol expects on stdout."""
    addresses = _probe_device_files() or _probe_jax()
    return {"name": RESOURCE_NAME, "addresses": addresses}


def write_discovery_script(path: str) -> str:
    """Write the executable discovery script; returns the path."""
    with open(path, "w") as f:
        f.write(_SCRIPT)
    os.chmod(path, os.stat(path).st_mode | stat.S_IXUSR | stat.S_IXGRP | stat.S_IXOTH)
    return path


if __name__ == "__main__":
    print(json.dumps(discovery_payload()))
