"""Histogram tree ensembles (ISSUE 11): RandomForest on binned features.

Evidence layers:

* ops/model units: quantile binning, deterministic bootstrap bags,
  feature-subset strategies, spec validation, the histogram capacity
  gate, and differential accuracy against the oracle (tests/oracles.py
  — sklearn RandomForest, or the independent exact-split CART fallback).
* daemon plane (the acceptance bar): a fixed-seed 2-daemon sparksim fit
  is BITWISE-equal on the collective (reduce_mesh) and hub
  (export/merge) reduce paths AND to the single-daemon oracle; an
  unconfigured peer fails loudly (the kmeans-seed contract); a
  ``daemon.pass_boundary`` crash mid-fit recovers bitwise through the
  PR 4 ledger machinery with ZERO edits to it.
* serving plane: the fitted forest registers, warms, transforms
  bitwise through the daemon, and rides the fleet register→flip→drain
  rollout (serve/fleet.py) unchanged.
* flagship: two REAL OS-process daemons (the shared worker pair) split
  a fit whose result equals the in-process single-daemon oracle
  bitwise.
"""

import numpy as np
import pytest

from spark_rapids_ml_tpu import config
from spark_rapids_ml_tpu.models.random_forest import (
    ForestCapacityError,
    RandomForestClassificationModel,
    RandomForestClassifier,
    RandomForestRegressionModel,
    RandomForestRegressor,
    fit_random_forest_classifier,
    fit_random_forest_regressor,
    forest_spec_from_params,
    row_identity_keys,
    subset_size,
)
from spark_rapids_ml_tpu.ops import histogram as hist_ops
from spark_rapids_ml_tpu.serve.client import DataPlaneClient
from spark_rapids_ml_tpu.serve.daemon import DataPlaneDaemon
from spark_rapids_ml_tpu.spark import estimator as spark_est
from spark_rapids_ml_tpu.spark.estimator import (
    SparkRandomForestClassifier,
    SparkRandomForestRegressor,
)
from spark_rapids_ml_tpu.utils import faults
from spark_rapids_ml_tpu.utils import metrics as metrics_mod
from spark_rapids_ml_tpu.utils.faults import FaultPlan

import oracles
from sparksim import SimDataFrame, SimSparkSession, simdf_from_numpy

spark_est.register_dataframe_type(SimDataFrame)

pytestmark = pytest.mark.forest


@pytest.fixture(autouse=True)
def _no_plan_leaks():
    yield
    faults.deactivate()
    assert faults.active_plan() is None


def _addr(daemon) -> str:
    return f"{daemon.address[0]}:{daemon.address[1]}"


def _counter_total(snap, name):
    return sum(
        float(s.get("value", 0.0))
        for s in (snap.get(name) or {}).get("samples", [])
    )


def _blobs(rng, n=400, d=6, classes=3, spread=4):
    """Integer-valued separable blobs: every histogram statistic is
    exact in f64, so daemon fold order cannot perturb the trees and
    equality checks are bitwise (the multidaemon suite's convention)."""
    centers = rng.integers(-10, 11, size=(classes, d)) * spread
    y = rng.integers(0, classes, size=n)
    x = (centers[y] + rng.integers(-1, 2, size=(n, d))).astype(np.float64)
    return x, y.astype(np.float64)


# ---------------------------------------------------------------------------
# ops/histogram units
# ---------------------------------------------------------------------------


def test_quantile_bin_edges_and_binning(rng):
    x = rng.normal(size=(500, 4))
    edges = hist_ops.quantile_bin_edges(x, 16)
    assert edges.shape == (4, 15)
    assert np.all(np.diff(edges, axis=1) >= 0)  # monotone per feature
    import jax.numpy as jnp

    bins = np.asarray(hist_ops.bin_matrix(jnp.asarray(x), jnp.asarray(edges)))
    assert bins.shape == (500, 4)
    assert bins.min() >= 0 and bins.max() <= 15
    # roughly uniform occupancy is the quantile property
    occ = np.bincount(bins[:, 0], minlength=16)
    assert occ.min() > 0


def test_bin_edges_validation():
    with pytest.raises(ValueError, match="max_bins"):
        hist_ops.quantile_bin_edges(np.zeros((10, 2)), 1)
    with pytest.raises(ValueError, match="max_bins"):
        hist_ops.quantile_bin_edges(np.zeros((10, 2)), 257)
    with pytest.raises(ValueError, match="n > 0"):
        hist_ops.quantile_bin_edges(np.zeros((0, 2)), 8)


def test_bootstrap_weights_deterministic_and_poisson_like():
    keys = row_identity_keys(3, 100, 4096)
    w1 = np.asarray(hist_ops.bootstrap_weights(keys, 4, seed=7))
    w2 = np.asarray(hist_ops.bootstrap_weights(keys, 4, seed=7))
    np.testing.assert_array_equal(w1, w2)  # pure function of identity
    assert w1.shape == (4, 4096)
    # Poisson(1): mean ~1, ~37% zeros; trees draw DIFFERENT bags.
    assert 0.9 < w1.mean() < 1.1
    zeros = (w1 == 0).mean()
    assert 0.30 < zeros < 0.44
    assert not np.array_equal(w1[0], w1[1])
    # A batch split cannot change a row's weight: keys are positional.
    k_a = row_identity_keys(3, 100, 10)
    k_b = row_identity_keys(3, 110, 10)
    np.testing.assert_array_equal(
        np.concatenate([k_a, k_b]), row_identity_keys(3, 100, 20)
    )


def test_subset_size_strategies():
    assert subset_size("all", 12, True) == 12
    assert subset_size("sqrt", 12, True) == 4
    assert subset_size("onethird", 12, False) == 4
    assert subset_size("log2", 12, True) == 3
    assert subset_size("auto", 12, True) == 4       # sqrt for clf
    assert subset_size("auto", 12, False) == 4      # onethird for reg
    assert subset_size("5", 12, True) == 5
    assert subset_size("0.5", 12, True) == 6
    with pytest.raises(ValueError, match="featureSubsetStrategy"):
        subset_size("bogus", 12, True)


def test_forest_spec_validation():
    with pytest.raises(ValueError, match="max_depth"):
        forest_spec_from_params({"max_depth": 17}, 4)
    with pytest.raises(ValueError, match="max_bins"):
        forest_spec_from_params({"max_bins": 300}, 4)
    with pytest.raises(ValueError, match="n_classes"):
        forest_spec_from_params({"n_classes": 1}, 4)
    with pytest.raises(ValueError, match="num_trees"):
        forest_spec_from_params({"num_trees": -1}, 4)
    spec = forest_spec_from_params({"n_classes": 3, "num_trees": 7}, 9)
    assert spec.n_stats == 3 and spec.max_nodes == 63
    assert spec.subset_m == 3  # sqrt(9)


def test_hist_capacity_gate(rng):
    x, y = _blobs(rng, n=64)
    with config.option("forest_hist_budget_mb", 1):
        with pytest.raises(ForestCapacityError, match="forest_hist_budget_mb"):
            # 64 trees x 256 bins x 16 cols blows 1 MiB at depth 0.
            fit_random_forest_classifier(
                np.tile(x, (1, 3))[:, :16], y, num_trees=64, max_bins=256,
            )


# ---------------------------------------------------------------------------
# In-memory fit: differential accuracy + determinism
# ---------------------------------------------------------------------------


def test_classifier_accuracy_vs_oracle(rng):
    centers = rng.normal(size=(3, 8)) * 8
    y = rng.integers(0, 3, size=900)
    x = centers[y] + rng.normal(size=(900, 8))
    xtr, ytr, xte, yte = x[:600], y[:600], x[600:], y[600:]
    sol = fit_random_forest_classifier(
        xtr, ytr, num_trees=15, max_depth=6, max_bins=32, seed=3
    )
    model = RandomForestClassificationModel(arrays=sol.arrays)
    acc = float(np.mean(model.predict(xte) == yte))
    ref = oracles.forest_accuracy(xtr, ytr, xte, yte, max_depth=6, seed=3)
    assert acc >= ref - 0.05, (acc, ref)
    assert model.numClasses == 3
    proba = model.predict_proba(xte[:16])
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, atol=1e-9)


def test_regressor_r2(rng):
    x = rng.normal(size=(800, 6))
    y = x @ rng.normal(size=6)
    sol = fit_random_forest_regressor(
        x, y, num_trees=15, max_depth=6, max_bins=32, seed=1
    )
    model = RandomForestRegressionModel(arrays=sol.arrays)
    pred = model.predict(x)
    r2 = 1 - np.sum((pred - y) ** 2) / np.sum((y - y.mean()) ** 2)
    assert r2 > 0.7, r2
    assert model.numClasses == 0


def test_fit_deterministic_and_seed_sensitive(rng):
    x, y = _blobs(rng)
    a = fit_random_forest_classifier(x, y, num_trees=8, max_depth=4, seed=3)
    b = fit_random_forest_classifier(x, y, num_trees=8, max_depth=4, seed=3)
    for k in a.arrays:
        np.testing.assert_array_equal(a.arrays[k], b.arrays[k])
    c = fit_random_forest_classifier(x, y, num_trees=8, max_depth=4, seed=4)
    assert any(
        not np.array_equal(a.arrays[k], c.arrays[k]) for k in a.arrays
    ), "seed had no effect on the forest"


def test_model_data_roundtrip(rng):
    x, y = _blobs(rng, n=200)
    sol = fit_random_forest_classifier(x, y, num_trees=5, max_depth=3)
    m1 = RandomForestClassificationModel(arrays=sol.arrays)
    m2 = RandomForestClassificationModel._from_model_data(
        "rt", m1._model_data()
    )
    np.testing.assert_array_equal(m1.predict(x), m2.predict(x))
    assert m2.numClasses == m1.numClasses


def test_estimator_surface(rng):
    import pyarrow as pa

    from spark_rapids_ml_tpu.bridge.arrow import matrix_to_list_column

    x, y = _blobs(rng, n=240)
    est = (
        RandomForestClassifier()
        .setNumTrees(6).setMaxDepth(3).setMaxBins(16)
        .setFeatureSubsetStrategy("all").setBootstrap(False)
        .setMinInstancesPerNode(2).setSeed(9)
    )
    assert est.getNumTrees() == 6 and est.getMaxBins() == 16
    assert not est.getBootstrap()
    tbl = pa.table({
        "features": matrix_to_list_column(x), "label": pa.array(y),
    })
    model = est.fit(tbl)
    assert model.getNumTrees() == 6  # fitted tree count, param surface
    out = model.transform(tbl)
    pred = np.asarray([r.as_py() for r in out.column("prediction")])
    assert np.mean(pred == y) > 0.9
    reg = RandomForestRegressor().setNumTrees(4).setMaxDepth(3)
    assert reg.getFeatureSubsetStrategy() == "auto"


# ---------------------------------------------------------------------------
# Daemon plane: the determinism satellite + serving
# ---------------------------------------------------------------------------


def _rf_est():
    return (
        SparkRandomForestClassifier()
        .setNumTrees(6).setMaxDepth(4).setSeed(7)
    )


@pytest.fixture
def two_daemons():
    with DataPlaneDaemon(ttl=600.0) as a, DataPlaneDaemon(ttl=600.0) as b:
        yield a, b


def _split_session(primary, peer, n_partitions=4, addresses=True):
    conf = {"spark.srml.daemon.address": _addr(primary)}
    if addresses:
        conf["spark.srml.daemon.addresses"] = f"{_addr(primary)},{_addr(peer)}"
    session = SimSparkSession(conf)
    env_plan = {
        pid: {"SRML_DAEMON_ADDRESS": _addr(peer)}
        for pid in range(n_partitions // 2, n_partitions)
    }
    return session, env_plan


@pytest.mark.parametrize("collective", [True, False],
                         ids=["collective", "hub"])
def test_forest_two_daemons_bitwise_equal(rng, mesh8, two_daemons,
                                          collective):
    """The acceptance bar: a fixed-seed 2-daemon split fit is
    bitwise-equal to the single-daemon oracle on BOTH reduce paths
    (histograms are additive integer-exact statistics; the fold order
    is pinned by the sorted-id contract like PCA/kmeans)."""
    a, b = two_daemons
    x, y = _blobs(rng)

    single = simdf_from_numpy(
        x, n_partitions=4, label=y,
        session=SimSparkSession({"spark.srml.daemon.address": _addr(a)}),
    )
    m_single = _rf_est().fit(single)

    session, env_plan = _split_session(a, b)
    split = simdf_from_numpy(x, n_partitions=4, label=y, session=session,
                             env_plan=env_plan)
    with config.option("mesh_collectives", collective):
        m_split = _rf_est().fit(split)

    for k in m_single.arrays:
        np.testing.assert_array_equal(
            m_single.arrays[k], m_split.arrays[k], err_msg=k
        )
    # both daemons' jobs were consumed (no leaked device state)
    assert not a._jobs and not b._jobs


def test_forest_regressor_two_daemons_bitwise_equal(rng, mesh8, two_daemons):
    """Variance-split trees over the same plane: integer labels make
    (count, Σy, Σy²) exact, so the regressor contract is bitwise too."""
    a, b = two_daemons
    x, _ = _blobs(rng)
    y = (x @ rng.integers(-3, 4, size=x.shape[1])).astype(np.float64)

    single = simdf_from_numpy(
        x, n_partitions=4, label=y,
        session=SimSparkSession({"spark.srml.daemon.address": _addr(a)}),
    )
    est = lambda: (  # noqa: E731
        SparkRandomForestRegressor().setNumTrees(5).setMaxDepth(4).setSeed(2)
    )
    m_single = est().fit(single)
    session, env_plan = _split_session(a, b)
    split = simdf_from_numpy(x, n_partitions=4, label=y, session=session,
                             env_plan=env_plan)
    m_split = est().fit(split)
    for k in m_single.arrays:
        np.testing.assert_array_equal(
            m_single.arrays[k], m_split.arrays[k], err_msg=k
        )


def test_forest_unseeded_peer_fails_loudly(rng, mesh8, two_daemons):
    """A peer daemon NOT listed in spark.srml.daemon.addresses never got
    the (bin edges + tables) iterate: its feeds must fail naming the
    seeding contract — never bin differently and return a silently
    diverged forest (the kmeans-seed contract)."""
    a, b = two_daemons
    x, y = _blobs(rng, n=240)
    session, env_plan = _split_session(a, b, addresses=False)
    df = simdf_from_numpy(x, n_partitions=4, label=y, session=session,
                          env_plan=env_plan)
    with pytest.raises(Exception, match="set_iterate|iterate"):
        _rf_est().fit(df)


def test_forest_daemon_transform_bitwise_and_serves(rng, mesh8, two_daemons):
    """The fitted model's daemon-served transform equals the local
    predict bitwise, and the registration rides ensure_model + warmup
    like every served model (zero serving-plane edits)."""
    a, _ = two_daemons
    x, y = _blobs(rng)
    df = simdf_from_numpy(
        x, n_partitions=4, label=y,
        session=SimSparkSession({"spark.srml.daemon.address": _addr(a)}),
    )
    model = _rf_est().fit(df)
    rows = model.transform(
        simdf_from_numpy(
            x[:48], n_partitions=2,
            session=SimSparkSession(
                {"spark.srml.daemon.address": _addr(a)}),
        )
    ).collect()
    got = np.asarray([r["prediction"] for r in rows])
    np.testing.assert_array_equal(
        got, np.asarray(model.predict(x[:48]), np.float64)
    )
    # Direct client serving: ensure_model + transform + warmup ladder.
    with DataPlaneClient(*a.address) as c:
        c.ensure_model("rf-serve", "rf_classifier", model._model_data())
        out = c.transform("rf-serve", x[:16])
        np.testing.assert_array_equal(
            np.asarray(out["prediction"]),
            np.asarray(model.predict(x[:16]), np.float64),
        )
        info = c.warmup("rf-serve", n_cols=x.shape[1])
        assert info.get("enabled") in (True, False)  # honest either way
        c.drop_model("rf-serve")


def test_forest_served_through_fleet_rollout(rng, mesh8):
    """The fleet acceptance: a forest registers on every replica,
    serves through the routed client, and a v1→v2 rollout flips
    atomically — serve/fleet.py and serve/router.py untouched."""
    from spark_rapids_ml_tpu.serve.fleet import ModelFleet

    x, y = _blobs(rng, n=300)
    v1 = fit_random_forest_classifier(x, y, num_trees=5, max_depth=3, seed=1)
    v2 = fit_random_forest_classifier(x, y, num_trees=5, max_depth=3, seed=2)
    m1 = RandomForestClassificationModel(arrays=v1.arrays)
    m2 = RandomForestClassificationModel(arrays=v2.arrays)
    q = x[:32]
    ref1 = np.asarray(m1.predict(q), np.float64)
    ref2 = np.asarray(m2.predict(q), np.float64)
    with DataPlaneDaemon(ttl=600.0) as d1, DataPlaneDaemon(ttl=600.0) as d2:
        eps = [d1.address, d2.address]
        with ModelFleet(eps) as fleet:
            fleet.register("rfm", "rf_classifier", v1.arrays, warm=False)
            with fleet.client() as fc:
                out = fc.transform("rfm", q)
                np.testing.assert_array_equal(
                    np.asarray(out["prediction"]), ref1
                )
            res = fleet.rollout("rfm", "rf_classifier", v2.arrays,
                                warm=False)
            assert res["version"] == 2 and res["drained"] is True
            with fleet.client() as fc:
                out = fc.transform("rfm", q)
                np.testing.assert_array_equal(
                    np.asarray(out["prediction"]), ref2
                )


# ---------------------------------------------------------------------------
# Recovery: pass-boundary crash replays bitwise (PR 4 machinery, no edits)
# ---------------------------------------------------------------------------


def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _supervised_daemon(port, mesh, state_dir):
    holder = {}

    def start():
        holder["d"] = DataPlaneDaemon(
            host="127.0.0.1", port=port, mesh=mesh, state_dir=state_dir
        ).start()

    def restart():
        holder["d"].stop()
        start()

    start()
    return holder, restart


@pytest.mark.recovery
def test_forest_fit_recovers_from_boundary_crash_bitwise(
    tmp_path, mesh8, monkeypatch, rng
):
    """The daemon dies AT a forest pass boundary (fault site
    daemon.pass_boundary — the level's splits applied, snapshot
    written, ack unsent), a supervisor restarts it, and the fit —
    recovery enabled — replays the depth from the driver ledger and
    produces the clean run's forest bit-for-bit. The recovery machinery
    is byte-identical to what kmeans/logreg use."""
    x, y = _blobs(rng, n=300)
    port = _free_port()
    holder, restart = _supervised_daemon(port, mesh8, str(tmp_path / "state"))
    monkeypatch.setenv("SRML_DAEMON_ADDRESS", f"127.0.0.1:{port}")
    try:
        def fit():
            df = simdf_from_numpy(x, n_partitions=3, label=y, concurrency=1)
            return _rf_est().fit(df)

        m_clean = fit()

        monkeypatch.setenv("SRML_FIT_RECOVERY_ATTEMPTS", "2")
        plan = (
            FaultPlan(seed=3)
            .rule("daemon.pass_boundary", "crash", after=1, times=1)
            .on_crash(restart)
        )
        with faults.active(plan):
            m_rec = fit()
        assert plan.fired.get("daemon.pass_boundary") == 1, (
            "the boundary crash never fired — the run proved nothing"
        )
        for k in m_clean.arrays:
            np.testing.assert_array_equal(
                m_clean.arrays[k], m_rec.arrays[k], err_msg=k
            )
        snap = metrics_mod.snapshot()
        assert _counter_total(snap, "srml_fit_recoveries_total") >= 1
        assert _counter_total(snap, "srml_daemon_job_restores_total") >= 1
    finally:
        holder["d"].stop()


@pytest.mark.recovery
def test_forest_boundary_crash_without_recovery_fails_loudly(
    tmp_path, mesh8, monkeypatch, rng
):
    """Recovery disabled (the default): the same death still fails with
    a clear error — never a silently truncated forest."""
    x, y = _blobs(rng, n=240)
    port = _free_port()
    holder, restart = _supervised_daemon(port, mesh8, str(tmp_path / "state"))
    monkeypatch.setenv("SRML_DAEMON_ADDRESS", f"127.0.0.1:{port}")
    monkeypatch.delenv("SRML_FIT_RECOVERY_ATTEMPTS", raising=False)
    try:
        plan = (
            FaultPlan(seed=3)
            .rule("daemon.pass_boundary", "crash", after=1, times=1)
            .on_crash(restart)
        )
        with faults.active(plan):
            df = simdf_from_numpy(x, n_partitions=3, label=y, concurrency=1)
            with pytest.raises(
                RuntimeError,
                match="no rows fed this pass|row-count mismatch|"
                      "restarted mid-pass",
            ):
                _rf_est().fit(df)
    finally:
        holder["d"].stop()


# ---------------------------------------------------------------------------
# CI/tooling: the FOREST_r* perfcheck gate
# ---------------------------------------------------------------------------


def _forest_record(**over):
    rec = {
        "metric": "forest_fit_rows_per_s_n1000_d8_t4_depth3_b16",
        "unit": "rows/s",
        "mode": "forest",
        "value": 50000.0,
        "passes": 3,
        "transform_rows_per_s": 200000.0,
        "accuracy": 0.99,
        "accuracy_ok": True,
        "baseline": {"impl": "sklearn", "accuracy": 0.99},
    }
    rec.update(over)
    return rec


def test_perfcheck_forest_gate_units():
    from spark_rapids_ml_tpu.tools.perfcheck import check_forest

    # No history: accuracy gates absolutely, throughput SKIPs (never a
    # silent pass).
    ok, lines = check_forest(_forest_record(), [])
    assert ok and any("[SKIP]" in ln for ln in lines)
    # Accuracy failure is absolute — history cannot save it.
    ok, lines = check_forest(
        _forest_record(accuracy=0.5, accuracy_ok=False),
        [_forest_record()],
    )
    assert not ok and any("accuracy [FAIL]" in ln for ln in lines)
    # An empty fit fails regardless of history.
    ok, _ = check_forest(_forest_record(passes=0, value=0.0), [])
    assert not ok
    # Throughput regression beyond the floor fails; within it passes.
    hist = [_forest_record(value=100000.0)]
    ok, lines = check_forest(_forest_record(value=50000.0), hist)
    assert not ok and any("REGRESSION" in ln for ln in lines)
    ok, _ = check_forest(_forest_record(value=95000.0), hist)
    assert ok
    # Transform regression gates too.
    hist = [_forest_record(transform_rows_per_s=1000000.0)]
    ok, _ = check_forest(_forest_record(), hist)
    assert not ok
    # Backends never mix in one trajectory (the multichip
    # simulated/real rule): a TPU median must not gate a CPU record.
    hist = [_forest_record(value=1e7, backend="tpu")]
    ok, lines = check_forest(_forest_record(value=50000.0,
                                            backend="cpu"), hist)
    assert ok and any("[SKIP]" in ln for ln in lines)
    # Wrong mode is rejected outright.
    ok, _ = check_forest({"mode": "serve"}, [])
    assert not ok


def test_perfcheck_forest_real_record_parses():
    """The shipped FOREST_r01.json is a valid record for the gate (the
    trajectory every future round is judged against)."""
    import json
    from pathlib import Path

    from spark_rapids_ml_tpu.tools.perfcheck import check_forest, parse_record

    path = Path(__file__).resolve().parent.parent / "FOREST_r01.json"
    rec = parse_record(json.loads(path.read_text()))
    assert rec["mode"] == "forest" and rec["metric"].startswith("forest_")
    ok, lines = check_forest(rec, [rec])
    assert ok, lines


# ---------------------------------------------------------------------------
# Flagship: real OS-process daemons (shared worker pair)
# ---------------------------------------------------------------------------


def test_forest_two_worker_processes_bitwise_equal(rng, mesh8,
                                                   worker_daemon_pair):
    """Two daemons in two separate OS PROCESSES (separate JAX runtimes —
    two 'TPU hosts', the shared never-killed worker pair), executor
    tasks splitting their feeds between them over real TCP, driver
    reducing per depth over the hub: the forest must equal the
    in-process single-daemon oracle bitwise."""
    (_, port_a), (_, port_b) = worker_daemon_pair
    addr_a, addr_b = f"127.0.0.1:{port_a}", f"127.0.0.1:{port_b}"
    x, y = _blobs(rng, n=320)

    with DataPlaneDaemon(ttl=600.0) as oracle:
        single = simdf_from_numpy(
            x, n_partitions=4, label=y,
            session=SimSparkSession(
                {"spark.srml.daemon.address": _addr(oracle)}),
        )
        m_single = _rf_est().fit(single)

    session = SimSparkSession({
        "spark.srml.daemon.address": addr_a,
        "spark.srml.daemon.addresses": f"{addr_a},{addr_b}",
    })
    env_plan = {2: {"SRML_DAEMON_ADDRESS": addr_b},
                3: {"SRML_DAEMON_ADDRESS": addr_b}}
    split = simdf_from_numpy(x, n_partitions=4, label=y, session=session,
                             env_plan=env_plan)
    m_split = _rf_est().fit(split)
    for k in m_single.arrays:
        np.testing.assert_array_equal(
            m_single.arrays[k], m_split.arrays[k], err_msg=k
        )
