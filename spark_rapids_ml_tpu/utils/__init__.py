from spark_rapids_ml_tpu.utils.profiling import trace_span, Timer
from spark_rapids_ml_tpu.utils.logging import get_logger
from spark_rapids_ml_tpu.utils import journal, metrics

__all__ = ["trace_span", "Timer", "get_logger", "journal", "metrics"]
