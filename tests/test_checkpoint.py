"""core/checkpoint.py unit coverage (ISSUE 4 satellite).

The checkpoint module is now load-bearing twice over: the streaming-fit
resume path AND the daemon's durable job snapshots (serve/daemon.py
crash recovery) both ride ``save_state``/``load_state``. These tests pin
the properties those callers lean on: byte/dtype/meta fidelity through a
round trip, the atomic tmp+rename contract (a crash mid-checkpoint
leaves the previous resume point intact and no temp litter), and the
single-process no-op of the multi-host visibility guard.
"""

import os

import numpy as np
import pytest

from spark_rapids_ml_tpu.core import checkpoint


@pytest.fixture
def arrays(rng):
    return {
        "gram": rng.normal(size=(8, 8)).astype(np.float64),
        "colsum": rng.normal(size=(8,)).astype(np.float32),
        "count": np.asarray([12345], np.int64),
        "flags": np.asarray([[1, 0], [0, 1]], np.uint8),
    }


META = {
    "algo": "pca",
    "n_cols": 8,
    "rows": 12345,
    "params": {"k": 3, "seed": 7, "init": "k-means++"},
    "nested": {"list": [1, 2.5, "three"], "none": None},
}


def test_save_load_roundtrip_bitwise_and_meta_fidelity(tmp_path, arrays):
    path = str(tmp_path / "ckpt.npz")
    checkpoint.save_state(path, arrays, META)
    loaded = checkpoint.load_state(path)
    assert loaded is not None
    got_arrays, got_meta = loaded
    assert set(got_arrays) == set(arrays)
    for name, want in arrays.items():
        got = got_arrays[name]
        assert got.dtype == want.dtype, name
        assert got.shape == want.shape, name
        np.testing.assert_array_equal(got, want)  # bitwise, not approx
    assert got_meta == META  # JSON round trip preserves structure


def test_load_absent_checkpoint_returns_none(tmp_path):
    assert checkpoint.load_state(str(tmp_path / "nope.npz")) is None


def test_save_creates_nested_directories(tmp_path, arrays):
    path = str(tmp_path / "a" / "b" / "ckpt.npz")
    checkpoint.save_state(path, arrays, {"ok": 1})
    assert checkpoint.load_state(path) is not None


def test_crash_mid_checkpoint_keeps_old_resume_point(tmp_path, arrays, monkeypatch):
    """The atomicity contract: a writer dying mid-save must leave the
    PREVIOUS checkpoint fully intact (the rename never happened) and no
    .tmp litter behind (the except-path unlink ran)."""
    path = str(tmp_path / "ckpt.npz")
    v1_meta = {"version": 1}
    checkpoint.save_state(path, arrays, v1_meta)

    real_savez = np.savez

    def dying_savez(f, **payload):
        # Write a partial, plausible-looking prefix then die — the shape
        # of a disk-full / SIGKILL mid-write failure.
        f.write(b"PK\x03\x04 partial zip prefix")
        raise OSError("injected crash mid-checkpoint")

    monkeypatch.setattr(np, "savez", dying_savez)
    v2 = {k: v + 1 for k, v in arrays.items()}
    with pytest.raises(OSError, match="injected crash"):
        checkpoint.save_state(path, v2, {"version": 2})
    monkeypatch.setattr(np, "savez", real_savez)

    loaded = checkpoint.load_state(path)
    assert loaded is not None
    got_arrays, got_meta = loaded
    assert got_meta == v1_meta  # the OLD resume point survived, intact
    np.testing.assert_array_equal(got_arrays["gram"], arrays["gram"])
    leftovers = [n for n in os.listdir(tmp_path) if n.endswith(".tmp")]
    assert leftovers == [], f"tmp litter after crashed save: {leftovers}"


def test_discard_state_is_idempotent(tmp_path, arrays):
    path = str(tmp_path / "ckpt.npz")
    checkpoint.save_state(path, arrays, {"v": 1})
    checkpoint.discard_state(path)
    assert checkpoint.load_state(path) is None
    checkpoint.discard_state(path)  # absent: still a no-op, no raise


def test_require_consistent_visibility_single_process_noop():
    """jax.process_count() == 1 in every test environment here: both the
    restored and the not-restored verdicts must pass through without
    touching multihost collectives."""
    assert checkpoint.require_consistent_visibility(None) is None
    assert checkpoint.require_consistent_visibility(({"a": 1}, {})) is None
