"""Gossiped fleet control-plane tests (serve/gossip.py + the bootstrap
and crash-safe-rollout planes of serve/router.py / serve/fleet.py).

The load-bearing claims, in test order:

* **merge rule** — ``(epoch, boot_id)`` dominance is a total order, the
  merge is commutative (two islands converge on ONE winner), tombstones
  never resurrect a retired replica/version, and a record NEWER than a
  tombstone re-deploying the same version number survives the merge;
* **bootstrap** — one seed address yields the whole fleet (ring, active
  versions, live intent); dead seeds fail over down the seed list on
  the backoff ladder; a faulted ``fleet.bootstrap`` attempt retries the
  next seed;
* **resync** — a bootstrapped client that straddles a rollout heals
  in-band from the answering daemon instead of erroring;
* **partition heal** — two gossip islands with divergent model records
  converge after one bridged push: the dominant-epoch record wins
  everywhere and the retired version stays tombstoned on every view;
* **crash-safe rollouts** — a controller dying BEFORE the flip is
  aborted by its successor (the old version never stops serving,
  bitwise); dying AT/AFTER the flip is completed (the new version
  serves, bitwise); aborted-then-retried re-deploys of the SAME version
  number work despite the tombstone;
* **chaos flagships** — a traffic client SIGKILLed mid-stream is
  replaced by a successor booted from ONE different seed with zero
  failures; the acceptance flagship kills a REAL subprocess controller
  (``SRML_FAULT_PLAN`` crash, exit 17) mid-rollout under live traffic —
  the successor finishes the rollout from the gossiped intent and no
  request fails or spans versions.

Also here: the ``tools.top --fleet`` gossiped-panel unit.
"""

import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from spark_rapids_ml_tpu.serve import (
    DataPlaneClient,
    DataPlaneDaemon,
    FleetClient,
    FleetUnavailable,
    FleetView,
    ModelFleet,
    bootstrap_table,
)
from spark_rapids_ml_tpu.serve.gossip import dominates
from spark_rapids_ml_tpu.utils import faults
from spark_rapids_ml_tpu.utils import metrics as metrics_mod

pytestmark = pytest.mark.gossip

D = 16


@pytest.fixture(autouse=True)
def _no_plan_leaks():
    yield
    faults.deactivate()
    assert faults.active_plan() is None


def _counter(name, **labels):
    snap = metrics_mod.snapshot()
    total = 0.0
    for s in (snap.get(name) or {}).get("samples", []):
        if all(s.get("labels", {}).get(k) == v for k, v in labels.items()):
            total += float(s.get("value", 0.0))
    return total


@pytest.fixture
def pca_v1_v2(rng, mesh8):
    """Two DIFFERENT fitted PCA versions + their transform oracles for a
    fixed query batch: the bitwise ground truth per version."""
    from spark_rapids_ml_tpu.models.pca import PCA

    basis = rng.normal(size=(D, D)) * np.logspace(0, -1.5, D)
    data = rng.normal(size=(400, D)) @ basis
    m1 = PCA(mesh=mesh8).setK(3).fit({"features": data})
    m2 = PCA(mesh=mesh8).setK(2).fit({"features": data})
    q = rng.normal(size=(12, D))
    return {
        "q": q,
        "v1": m1._model_data(),
        "v2": m2._model_data(),
        "ref1": np.asarray(m1.transform_matrix(q)["output"]),
        "ref2": np.asarray(m2.transform_matrix(q)["output"]),
    }


@pytest.fixture
def trio(mesh8):
    """Three in-process replica daemons + their seed address strings."""
    daemons = [DataPlaneDaemon(mesh=mesh8).start() for _ in range(3)]
    try:
        yield daemons, [f"{h}:{p}" for h, p in (d.address for d in daemons)]
    finally:
        for d in daemons:
            d.stop()


def _endpoints(addrs):
    return [(a.rsplit(":", 1)[0], int(a.rsplit(":", 1)[1])) for a in addrs]


def _launch_worker(args, fault_spec=None):
    """One tests/rollout_worker.py subprocess with the shared f64 parity
    env (same profile as conftest's daemon workers — the worker's routed
    responses are compared bitwise against in-session oracles)."""
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {k: v for k, v in os.environ.items() if not k.startswith("SRML_")}
    env["JAX_PLATFORMS"] = "cpu"
    env["JAX_ENABLE_X64"] = "True"
    env["SRML_TPU_ACCUM_DTYPE"] = "float64"
    env["SRML_TPU_COMPUTE_DTYPE"] = "float64"
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (repo_root, env.get("PYTHONPATH")) if p
    )
    if fault_spec:
        env["SRML_FAULT_PLAN"] = fault_spec
    argv = [
        sys.executable,
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "rollout_worker.py"),
    ] + [str(a) for a in args]
    return subprocess.Popen(
        argv, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        cwd=repo_root, env=env, text=True,
    )


# ---------------------------------------------------------------------------
# FleetView merge rule: dominance, commutativity, tombstones
# ---------------------------------------------------------------------------


def test_dominance_is_a_total_order():
    assert dominates(2, "a", 1, "z")  # higher epoch wins outright
    assert not dominates(1, "z", 2, "a")
    assert dominates(3, "b", 3, "a")  # tie breaks on boot_id
    assert not dominates(3, "a", 3, "b")
    assert not dominates(3, "a", 3, "a")  # equal records: neither wins


def test_merge_is_commutative_and_converges():
    """Two views with a conflicting model record converge to the SAME
    winner whichever direction the wires flow — the property that lets
    two healed islands agree without a coordinator."""
    a, b = FleetView(), FleetView()
    a.set_model("m", 1, 1, "ctl-a")
    b.set_model("m", 2, 2, "ctl-b")  # later write on the shared clock
    a.observe_replica("s1", "h:1", "boot1")
    b.observe_replica("s2", "h:2", "boot2")
    wa, wb = a.to_wire(), b.to_wire()
    a.merge(wb)
    b.merge(wa)
    ra, rb = a.model("m"), b.model("m")
    assert ra == rb
    assert ra["active_version"] == 2 and ra["boot_id"] == "ctl-b"
    assert {r["server_id"] for r in a.replicas()} == {"s1", "s2"}
    assert {r["server_id"] for r in b.replicas()} == {"s1", "s2"}
    # Idempotent: re-merging the same wire adopts nothing.
    assert a.merge(b.to_wire()) == 0


def test_replica_tombstone_never_resurrects():
    a, b = FleetView(), FleetView()
    a.observe_replica("s1", "h:1", "boot1")
    stale = a.to_wire()  # an island's last sight of s1 alive
    b.merge(stale)
    a.tombstone_replica("s1")
    b.merge(a.to_wire())
    assert b.replicas(liveness="tombstone")[0]["server_id"] == "s1"
    # The stale "up" record arrives AFTER the tombstone (partition
    # heal): its epoch is older, so the tombstone holds.
    b.merge(stale)
    assert b.replicas(liveness="up") == []
    assert b.replicas(liveness="tombstone")[0]["server_id"] == "s1"


def test_model_tombstone_degrades_only_stale_actives():
    """A record whose active version is tombstoned at a NEWER epoch
    degrades to no-active (never resurrect); a record written AFTER the
    tombstone that re-activates the same version number is a genuine
    re-deploy and survives."""
    v = FleetView(tombstone_ttl_s=0)  # keep tombstones forever
    # Stale active (record epoch 3) vs newer tombstone (epoch 5).
    v.merge({"epoch": 5, "models": {"m": {
        "active_version": 2, "fleet_epoch": 1, "epoch": 3,
        "boot_id": "ctl-x", "tombstones": {"2": {"epoch": 5, "at": 1.0}},
    }}})
    assert v.model("m")["active_version"] is None
    # Re-deploy: record epoch 9 beats the tombstone's 5.
    v.merge({"epoch": 9, "models": {"m": {
        "active_version": 2, "fleet_epoch": 2, "epoch": 9,
        "boot_id": "ctl-y", "tombstones": {"2": {"epoch": 5, "at": 1.0}},
    }}})
    rec = v.model("m")
    assert rec["active_version"] == 2
    assert "2" in rec["tombstones"]  # the tombstone itself still gossips


def test_tombstone_ttl_prunes_after_the_window():
    now = [100.0]
    v = FleetView(tombstone_ttl_s=10.0, clock=lambda: now[0])
    v.observe_replica("s1", "h:1", "boot1")
    v.tombstone_replica("s1")
    v.set_model("m", 2, 1, "ctl-a", tombstone_versions=(1,))
    now[0] += 5.0
    v.merge({})  # prune runs on every merge: inside the window, kept
    assert v.replicas(liveness="tombstone")
    assert "1" in v.model("m")["tombstones"]
    now[0] += 20.0
    v.merge({})
    assert v.replicas() == []
    assert v.model("m")["tombstones"] == {}


def test_top_renders_gossiped_fleet_panel():
    from spark_rapids_ml_tpu.tools.top import render_fleet_view

    view = {
        "wire_v": 1, "epoch": 7,
        "replicas": {
            "s1": {"server_id": "s1", "addr": "127.0.0.1:7001",
                   "boot_id": "boot1", "liveness": "up", "epoch": 5,
                   "last_seen": 0.0},
            "s2": {"server_id": "s2", "addr": "127.0.0.1:7002",
                   "boot_id": "boot2", "liveness": "tombstone",
                   "epoch": 6, "last_seen": 0.0},
        },
        "models": {"m": {
            "model": "m", "active_version": 2, "fleet_epoch": 3,
            "epoch": 7, "boot_id": "ctl-x",
            "intent": {"model": "m", "from_version": 1, "to_version": 2,
                       "phase": "draining", "by": "ctl-x", "at": 0.0},
            "tombstones": {"1": {"epoch": 7, "at": 0.0}},
        }},
    }
    txt = render_fleet_view(
        view, healths={"127.0.0.1:7001": {"busy": False}}
    )
    assert "view epoch 7" in txt
    assert "tombstone:1" in txt and "up:1" in txt
    assert "draining v1→v2 by ctl-x" in txt
    assert "v1" in txt.splitlines()[-1]  # the tombstone column


# ---------------------------------------------------------------------------
# bootstrap: one seed → whole fleet; seed failover; resync
# ---------------------------------------------------------------------------


@pytest.mark.fleet
def test_bootstrap_from_one_seed_builds_the_whole_ring(trio, pca_v1_v2):
    daemons, addrs = trio
    with ModelFleet(_endpoints(addrs)) as fleet:
        fleet.register("bm", "pca", pca_v1_v2["v1"], version=1)
        t = bootstrap_table([addrs[0]])  # ONE seed, no roster
        assert len(t.replicas()) == 3
        assert t.snapshot("bm") == (1, 1, "bm@v1")
        with FleetClient.from_seeds([addrs[2]]) as fc:
            out = np.asarray(
                fc.transform("bm", pca_v1_v2["q"])["output"]
            )
        assert np.array_equal(out, pca_v1_v2["ref1"])


@pytest.mark.fleet
@pytest.mark.chaos
def test_bootstrap_fails_over_dead_and_faulted_seeds(trio, pca_v1_v2):
    daemons, addrs = trio
    with ModelFleet(_endpoints(addrs)) as fleet:
        fleet.register("sm", "pca", pca_v1_v2["v1"], version=1)
        dead = "127.0.0.1:9"  # no listener: refused instantly
        metrics_mod.reset()
        # A dead first seed falls through to the live second.
        t = bootstrap_table([dead, addrs[0]])
        assert len(t.replicas()) == 3
        # An INJECTED drop on the first attempt does the same.
        plan = faults.FaultPlan().rule("fleet.bootstrap", "drop", times=1)
        with faults.active(plan):
            t = bootstrap_table([dead, addrs[1]])
        assert t.snapshot("sm") == (1, 1, "sm@v1")
        assert _counter("srml_fleet_bootstraps_total", outcome="ok") == 2
        assert _counter("srml_fleet_bootstraps_total", outcome="error") >= 2
        # All seeds dead: FleetUnavailable after the pass budget.
        with pytest.raises(FleetUnavailable):
            bootstrap_table([dead], passes=1)


@pytest.mark.fleet
def test_stale_bootstrapped_client_resyncs_across_a_rollout(trio, pca_v1_v2):
    """A client bootstrapped BEFORE a rollout keeps serving across it:
    its first post-rollout request hits the version fence / dropped
    registration, pulls the view from the answering daemon, re-pins,
    and answers bitwise from the NEW version — no surfaced error."""
    daemons, addrs = trio
    q = pca_v1_v2["q"]
    with ModelFleet(_endpoints(addrs)) as fleet:
        fleet.register("rm", "pca", pca_v1_v2["v1"], version=1)
        with FleetClient.from_seeds([addrs[0]]) as fc:
            out = np.asarray(fc.transform("rm", q)["output"])
            assert np.array_equal(out, pca_v1_v2["ref1"])
            metrics_mod.reset()
            fleet.rollout("rm", "pca", pca_v1_v2["v2"], version=2,
                          warm=False)
            out = np.asarray(fc.transform("rm", q)["output"])
            assert np.array_equal(out, pca_v1_v2["ref2"])
            assert _counter(
                "srml_fleet_bootstraps_total", outcome="resync"
            ) >= 1


# ---------------------------------------------------------------------------
# partition heal: two islands converge, dominant epoch wins
# ---------------------------------------------------------------------------


@pytest.mark.fleet
def test_partition_heal_converges_and_never_resurrects(mesh8, pca_v1_v2):
    """Two 2-daemon gossip islands with DIVERGENT model records (island
    B last saw v1 active; island A rolled to v2 and tombstoned v1) heal
    through one bridged push + anti-entropy ticks: every daemon's view
    converges on island A's dominant-epoch record, and the tombstoned
    v1 never comes back anywhere."""
    daemons = [
        DataPlaneDaemon(mesh=mesh8, gossip_interval_s=0).start()
        for _ in range(4)
    ]
    try:
        addrs = [f"{h}:{p}" for h, p in (d.address for d in daemons)]
        island_a, island_b = _endpoints(addrs[:2]), _endpoints(addrs[2:])
        # Island B writes first (older epochs): v1 active.
        with ModelFleet(island_b) as fb:
            fb.register("pm", "pca", pca_v1_v2["v1"], version=1)
        # Island A writes later (dominant epochs): v1 → v2, v1 tombstoned.
        with ModelFleet(island_a) as fa:
            fa.register("pm", "pca", pca_v1_v2["v1"], version=1)
            res = fa.rollout("pm", "pca", pca_v1_v2["v2"], version=2,
                            warm=False)
            assert res["drained"]
        rec_b = daemons[2].fleet_view.model("pm")
        assert rec_b["active_version"] == 1  # divergence before the heal

        def converged():
            wires = [d.fleet_view.to_wire() for d in daemons]
            recs = [w["models"].get("pm") for w in wires]
            if any(r is None for r in recs):
                return False
            if any(r["active_version"] != 2 for r in recs):
                return False
            if len({r["epoch"] for r in recs}) != 1:
                return False
            if any("1" not in (r["tombstones"] or {}) for r in recs):
                return False
            return all(
                len([x for x in w["replicas"].values()
                     if x["liveness"] == "up"]) == 4
                for w in wires
            )

        # The heal: ONE bridged push introduces the islands...
        with DataPlaneClient(*island_b[0]) as c:
            c.gossip_push(daemons[0].fleet_view.to_wire())
        # ...and plain anti-entropy ticks finish the convergence.
        deadline = time.time() + 15.0
        while not converged() and time.time() < deadline:
            for d in daemons:
                d._gossip_tick()
        assert converged(), [
            d.fleet_view.model("pm") for d in daemons
        ]
        assert _counter("srml_gossip_ticks_total") > 0
    finally:
        for d in daemons:
            d.stop()


# ---------------------------------------------------------------------------
# crash-safe rollouts: interrupted controllers, successors
# ---------------------------------------------------------------------------


@pytest.mark.fleet
@pytest.mark.chaos
def test_interrupted_rollout_before_flip_aborts_cleanly(trio, pca_v1_v2):
    """Controller dies right after gossiping the ``registering`` intent:
    nothing flipped, so the successor ABORTS — v1 never stops serving
    (bitwise), v2 is tombstoned... and a RETRIED rollout to the same
    version number still works (the re-deploy rule)."""
    daemons, addrs = trio
    q = pca_v1_v2["q"]
    with ModelFleet(_endpoints(addrs)) as fleet:
        fleet.register("am", "pca", pca_v1_v2["v1"], version=1)
        plan = faults.FaultPlan().rule("fleet.rollout", "drop", times=1)
        with faults.active(plan):
            with pytest.raises(ConnectionError):
                fleet.rollout("am", "pca", pca_v1_v2["v2"], version=2,
                              warm=False)
    with ModelFleet.from_seeds([addrs[0]]) as successor:
        intent = successor.table.intent("am")
        assert intent and intent["phase"] == "registering"
        res = successor.resume_rollout("am")
        assert res["action"] == "aborted" and res["version"] == 2
        assert successor.table.snapshot("am") == (1, 1, "am@v1")
        with successor.client() as fc:
            out = np.asarray(fc.transform("am", q)["output"])
        assert np.array_equal(out, pca_v1_v2["ref1"])
        # Retried re-deploy of the SAME version number despite its
        # tombstone: a record newer than the tombstone wins.
        res = successor.resume_rollout("am")
        assert res["action"] == "none"  # intent is gone
        successor.rollout("am", "pca", pca_v1_v2["v2"], version=2,
                          warm=False)
        with successor.client() as fc:
            out = np.asarray(fc.transform("am", q)["output"])
        assert np.array_equal(out, pca_v1_v2["ref2"])
    rec = daemons[0].fleet_view.model("am")
    assert rec["active_version"] == 2 and rec["intent"] is None


@pytest.mark.fleet
@pytest.mark.chaos
def test_interrupted_rollout_after_flip_completes(trio, pca_v1_v2):
    """Controller dies at the ``draining`` intent: the flip is durable
    in the view, so the successor COMPLETES — v2 serves bitwise, v1 is
    drained, dropped and tombstoned everywhere."""
    daemons, addrs = trio
    q = pca_v1_v2["q"]
    with ModelFleet(_endpoints(addrs)) as fleet:
        fleet.register("cm", "pca", pca_v1_v2["v1"], version=1)
        # Checkpoints with warm=False: registering(1), flipped(2),
        # draining(3) — after=2 dies at the third.
        plan = faults.FaultPlan().rule("fleet.rollout", "drop",
                                       after=2, times=1)
        with faults.active(plan):
            with pytest.raises(ConnectionError):
                fleet.rollout("cm", "pca", pca_v1_v2["v2"], version=2,
                              warm=False)
    with ModelFleet.from_seeds([addrs[1]]) as successor:
        intent = successor.table.intent("cm")
        assert intent and intent["phase"] == "draining"
        res = successor.resume_rollout("cm")
        assert res["action"] == "completed"
        assert res["version"] == 2 and res["drained"]
        with successor.client() as fc:
            out = np.asarray(fc.transform("cm", q)["output"])
        assert np.array_equal(out, pca_v1_v2["ref2"])
    rec = daemons[0].fleet_view.model("cm")
    assert rec["active_version"] == 2 and rec["intent"] is None
    assert "1" in rec["tombstones"]


# ---------------------------------------------------------------------------
# chaos flagships: SIGKILLed client, SIGKILLed controller
# ---------------------------------------------------------------------------


@pytest.mark.fleet
@pytest.mark.chaos
def test_client_sigkilled_mid_traffic_successor_boots_from_one_seed(
        trio, pca_v1_v2, tmp_path):
    """A REAL subprocess client (its own bootstrap, its own routing
    table) is SIGKILLed mid-stream; a successor built from ONE
    *different* seed address resumes routing with zero failed requests
    and bitwise-identical responses — client state is disposable."""
    daemons, addrs = trio
    q = pca_v1_v2["q"]
    with ModelFleet(_endpoints(addrs)) as fleet:
        fleet.register("km", "pca", pca_v1_v2["v1"], version=1)
        npz = tmp_path / "km.npz"
        np.savez(npz, q=q, ref=pca_v1_v2["ref1"])
        proc = _launch_worker(["traffic", addrs[0], npz, "km", 0])
        try:
            lines = [proc.stdout.readline().strip() for _ in range(3)]
            assert all(ln.startswith("OK") for ln in lines), lines
            proc.kill()  # SIGKILL, mid-traffic
            proc.wait(timeout=30)
        finally:
            if proc.poll() is None:  # pragma: no cover
                proc.kill()
        assert proc.returncode == -signal.SIGKILL
        with FleetClient.from_seeds([addrs[1]]) as fc:
            for i in range(10):
                out = np.asarray(
                    fc.transform("km", q,
                                 route_key=f"k{i}")["output"]
                )
                assert np.array_equal(out, pca_v1_v2["ref1"])


@pytest.mark.fleet
@pytest.mark.chaos
def test_flagship_controller_dies_mid_rollout_successor_finishes(
        trio, pca_v1_v2, tmp_path):
    """THE acceptance flagship: a 3-replica fleet under live traffic; a
    subprocess controller — itself bootstrapped from one seed — dies
    abruptly (exit 17) at the ``flipped`` intent checkpoint, AFTER the
    flip intent gossiped but BEFORE the flip ran. A successor
    controller bootstraps from a DIFFERENT single seed, finishes the
    rollout from the gossiped intent, and across the whole timeline
    zero requests fail and every response is bitwise v1 or bitwise v2
    — none ever spans versions, and each client's stream flips
    monotonically."""
    daemons, addrs = trio
    q, ref1, ref2 = pca_v1_v2["q"], pca_v1_v2["ref1"], pca_v1_v2["ref2"]
    with ModelFleet(_endpoints(addrs)) as boss:
        boss.register("gm", "pca", pca_v1_v2["v1"], version=1)
    # The boss is GONE (closed): everything below runs on gossiped
    # state alone.
    npz = tmp_path / "gm.npz"
    np.savez(npz, **{
        f"v2.{k}": np.asarray(v) for k, v in pca_v1_v2["v2"].items()
    })

    n_workers = 3
    stop = threading.Event()
    results = [[] for _ in range(n_workers)]

    def pound(i):
        with FleetClient.from_seeds([addrs[i % len(addrs)]]) as fc:
            def one():
                try:
                    out = np.asarray(fc.transform(
                        "gm", q, route_key=f"w{i}"
                    )["output"])
                except Exception as e:  # noqa: BLE001 - tallied below
                    results[i].append(("fail", repr(e)))
                    return
                if np.array_equal(out, ref1):
                    results[i].append("v1")
                elif np.array_equal(out, ref2):
                    results[i].append("v2")
                else:
                    results[i].append(("mixed", out.shape))
            while not stop.is_set():
                one()
                time.sleep(0.01)
            one()  # one guaranteed post-resume request per worker

    threads = [threading.Thread(target=pound, args=(i,))
               for i in range(n_workers)]
    for t in threads:
        t.start()
    try:
        # Checkpoints with warm=False: registering(1), flipped(2) —
        # after=1 dies at the second, with v2 registered everywhere
        # and the flip intent gossiped but the flip NOT executed.
        proc = _launch_worker(
            ["rollout", addrs[0], npz, "gm", 2],
            fault_spec="fleet.rollout:crash:after=1,times=1",
        )
        assert proc.wait(timeout=180) == 17  # a real mid-rollout death
        with ModelFleet.from_seeds([addrs[1]]) as successor:
            intent = successor.table.intent("gm")
            assert intent, "the rollout intent did not survive its controller"
            assert intent["phase"] == "flipped"
            assert intent["to_version"] == 2
            res = successor.resume_rollout("gm")
        assert res["action"] == "completed"
        assert res["version"] == 2 and res["drained"]
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=60)
    flat = [r for rs in results for r in rs]
    fails = [r for r in flat if not isinstance(r, str)]
    assert fails == [], fails[:5]  # ZERO failed / mixed-version responses
    for rs in results:
        assert rs, "a worker routed nothing"
        assert rs[-1] == "v2"  # every stream ends on the new version
        cut = rs.index("v2")
        assert all(r == "v1" for r in rs[:cut])
        assert all(r == "v2" for r in rs[cut:])  # monotone flip
    # The gossiped record agrees from ANY daemon: v2 active, v1
    # tombstoned, no intent left behind.
    h, p = _endpoints(addrs)[2]
    with DataPlaneClient(h, p) as c:
        rec = c.gossip_pull()["models"]["gm"]
    assert rec["active_version"] == 2
    assert rec["intent"] is None
    assert "1" in rec["tombstones"]


# ------------- bench --chaos-partition + perfcheck gate ---------------------


def test_perfcheck_chaos_partition_gates():
    """The partition-heal gate's unit matrix (mirror of the chaos
    elastic/grow ones): correctness — four-view convergence, zero
    failed/wobbling requests inside the split (with at least one
    routed), every view tombstoning the losing island's version — is
    ABSOLUTE; time-to-converge gates against the metric-matched
    trajectory and SKIPs — never passes — without history; the other
    chaos families sharing the CHAOS_r* glob never pollute the
    partition trajectory."""
    from spark_rapids_ml_tpu.tools import perfcheck

    good = {
        "metric": "chaos_partition_converge_d4",
        "mode": "chaos_partition", "value": 0.09,
        "time_to_converge_s": 0.09, "converged": True,
        "routed_during_partition": 13, "failed_during_partition": 0,
        "mismatched_during_partition": 0, "tombstones_clean": True,
        "n_daemons": 4, "gossip_interval_s": 0.05, "gossip_fanout": 2,
    }
    ok, lines = perfcheck.check_chaos_partition(good, [])
    assert ok and any("SKIP" in ln for ln in lines)
    ok, lines = perfcheck.check_chaos_partition(
        dict(good, converged=False), []
    )
    assert not ok and any("FAIL" in ln for ln in lines)
    ok, _ = perfcheck.check_chaos_partition(
        dict(good, routed_during_partition=0), [good]
    )
    assert not ok  # the split's data plane was never exercised
    ok, _ = perfcheck.check_chaos_partition(
        dict(good, failed_during_partition=3), [good]
    )
    assert not ok  # a partition must never fail requests
    ok, _ = perfcheck.check_chaos_partition(
        dict(good, mismatched_during_partition=1), [good]
    )
    assert not ok  # ... nor wobble their bytes
    ok, _ = perfcheck.check_chaos_partition(
        dict(good, tombstones_clean=False), [good]
    )
    assert not ok  # the heal could resurrect the losing version
    ok, _ = perfcheck.check_chaos_partition(dict(good, value=0.5), [good])
    assert not ok  # convergence got slower than the ceiling
    ok, _ = perfcheck.check_chaos_partition(dict(good), [good])
    assert ok  # healthy vs its own trajectory
    # Degrade/grow records sharing the glob are filtered out: the
    # partition gate still SKIPs rather than compare across families.
    elastic = {
        "metric": "chaos_elastic_replay_rows_per_s_d64_k8",
        "mode": "chaos_elastic", "value": 1000.0,
    }
    ok, lines = perfcheck.check_chaos_partition(good, [elastic])
    assert ok and any("SKIP" in ln for ln in lines)
    ok, _ = perfcheck.check_chaos_partition({"metric": "x"}, [])
    assert not ok  # not a chaos-partition record at all
