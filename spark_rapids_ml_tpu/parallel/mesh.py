"""Device mesh construction and naming conventions.

Axes:
  * ``data``  — row/batch parallelism (the reference's one-task-per-partition
    data parallelism, RapidsRowMatrix.scala:122-137, made device-native).
  * ``model`` — feature/model parallelism (the upgrade the reference lacks:
    it assumes the n×n covariance fits one device, RapidsRowMatrix.scala:74-86;
    sharding features over ``model`` lifts that limit).

Multi-host: ``jax.devices()`` already spans all hosts in a multi-host
runtime, so the same mesh code scales from 1 chip to a pod; XLA routes
``psum`` over ICI within a slice and DCN across slices.
"""

from __future__ import annotations

import threading
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from spark_rapids_ml_tpu import config

DATA_AXIS = "data"
MODEL_AXIS = "model"

_default_mesh: Optional[Mesh] = None
_default_mesh_key: Optional[tuple] = None
#: Guards the default-mesh cache: daemon connection threads reach
#: default_mesh() through the model fit/serve paths under DIFFERENT
#: locks (job lock here, model lock there), so the check-then-build
#: below would otherwise interleave and build the mesh twice — or hand
#: one caller a mesh mid-replacement (srml-check thread-shared-state
#: notes "some lock held" is not "the SAME lock held"; this makes it
#: the same lock).
_mesh_lock = threading.Lock()


def make_mesh(
    data: Optional[int] = None,
    model: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a (data, model) mesh over the given (default: all) devices."""
    devs = list(devices) if devices is not None else list(jax.devices())
    n = len(devs)
    if data is None:
        if n % model != 0:
            raise ValueError(f"{n} devices not divisible by model={model}")
        data = n // model
    if data * model > n:
        raise ValueError(f"mesh {data}x{model} needs {data * model} devices, have {n}")
    devs = devs[: data * model]
    arr = np.array(devs).reshape(data, model)
    return Mesh(arr, (DATA_AXIS, MODEL_AXIS))


def default_mesh() -> Mesh:
    """Process-wide default mesh (all devices on the data axis unless
    overridden by config ``mesh_data_axis``/``mesh_model_axis``).

    Rebuilt when the axis config changes or the live device set changes."""
    global _default_mesh, _default_mesh_key
    key = (config.get("mesh_data_axis"), config.get("mesh_model_axis") or 1)
    with _mesh_lock:
        if (
            _default_mesh is None
            or key != _default_mesh_key
            or _mesh_is_stale(_default_mesh)
        ):
            _default_mesh = make_mesh(data=key[0], model=key[1])
            _default_mesh_key = key
        return _default_mesh


def _mesh_is_stale(mesh: Mesh) -> bool:
    # Tests flip between CPU/TPU backends in one process; rebuild if the
    # mesh's devices are no longer the live ones.
    try:
        live = set(jax.devices())
    except RuntimeError:  # pragma: no cover
        return True
    return not set(mesh.devices.flat).issubset(live)


def reset_default_mesh() -> None:
    global _default_mesh, _default_mesh_key
    with _mesh_lock:
        _default_mesh = None
        _default_mesh_key = None


def mesh_shape(mesh: Mesh) -> tuple:
    return tuple(mesh.shape[a] for a in (DATA_AXIS, MODEL_AXIS))
