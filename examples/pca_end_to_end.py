"""Minimal end-to-end PCA: fit, transform, persist, reload.

Runs on whatever backend is available (TPU if attached, else CPU; for a
virtual multi-device mesh run with
XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu).
"""

import os
import sys

if __package__ in (None, ""):  # runnable without installation
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import tempfile

import numpy as np

import spark_rapids_ml_tpu as srml

rng = np.random.default_rng(0)
x = (rng.normal(size=(100_000, 256)) * np.logspace(0, -2, 256)).astype(np.float32)

model = srml.PCA().setInputCol("features").setOutputCol("pca").setK(8).fit(
    {"features": x}
)
out = model.transform({"features": x})["pca"]
print("components:", model.pc.shape, "explained:", model.explainedVariance[:4])

path = tempfile.mkdtemp() + "/pca_model"
model.save(path)
reloaded = srml.PCAModel.load(path)
assert np.allclose(reloaded.transform({"features": x})["pca"], out)
print("persistence round-trip OK ->", path)
