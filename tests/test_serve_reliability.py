"""Daemon reliability: exactly-once under retry, eviction, auth, failures.

The reference delegates all of this to Spark (task retry recomputes pure
map stages; the transport is Spark's own RPC — RapidsRowMatrix.scala:122-139).
This framework owns its transport, so it must own the failure semantics:
these tests kill feeders mid-stream, replay retried attempts, race
speculative duplicates, expire abandoned jobs, and reject unauthenticated
callers — asserting the final model is bit-identical to the single-shot
in-memory fit every time.
"""

import socket
import threading
import time

import numpy as np
import pytest

from spark_rapids_ml_tpu.models.pca import fit_pca
from spark_rapids_ml_tpu.serve import DataPlaneClient, DataPlaneDaemon
from spark_rapids_ml_tpu.serve import protocol


@pytest.fixture
def daemon(mesh8):
    with DataPlaneDaemon(mesh=mesh8) as d:
        yield d


def _client(daemon, **kw):
    return DataPlaneClient(*daemon.address, **kw)


@pytest.fixture
def data(rng):
    n, d = 480, 16
    basis = rng.normal(size=(d, d)) * np.logspace(0, -1.5, d)
    return rng.normal(size=(n, d)) @ basis


def _assert_matches_batch_fit(daemon, data, mesh8, job, k=3):
    with _client(daemon) as c:
        out = c.finalize_pca(job, k=k)
    ref = fit_pca(data, k=k, mesh=mesh8)
    np.testing.assert_allclose(np.abs(out["pc"]), np.abs(ref.pc), atol=1e-8)
    np.testing.assert_allclose(out["mean"], ref.mean, atol=1e-10)


# ------------------------- staged commit protocol ---------------------------


def test_partitioned_feed_commit_matches_batch_fit(daemon, data, mesh8):
    parts = np.array_split(data, 4)

    def task(pid, part):
        with _client(daemon) as c:
            for sub in np.array_split(part, 2):
                c.feed("j", sub, algo="pca", partition=pid)
            c.commit("j", partition=pid)

    threads = [threading.Thread(target=task, args=(i, p)) for i, p in enumerate(parts)]
    [t.start() for t in threads]
    [t.join() for t in threads]
    with _client(daemon) as c:
        assert c.status("j")["rows"] == data.shape[0]
    _assert_matches_batch_fit(daemon, data, mesh8, "j")


def test_uncommitted_stage_never_counts(daemon, data, mesh8):
    """A task that fed its stage but died before commit contributes nothing."""
    parts = np.array_split(data, 3)
    with _client(daemon) as c:
        # partition 0: feeds WRONG data (a doomed attempt), never commits
        c.feed("j", np.full_like(parts[0], 1e6), algo="pca", partition=0, attempt=0)
        # retry of partition 0 with the real data, new attempt
        c.feed("j", parts[0], algo="pca", partition=0, attempt=1)
        c.commit("j", partition=0, attempt=1)
        for pid, part in enumerate(parts[1:], start=1):
            c.feed("j", part, algo="pca", partition=pid)
            c.commit("j", partition=pid)
        assert c.status("j")["rows"] == data.shape[0]
    _assert_matches_batch_fit(daemon, data, mesh8, "j")


def test_duplicate_feed_and_commit_discarded(daemon, data, mesh8):
    """Speculative duplicate of a committed task must not double-count."""
    parts = np.array_split(data, 2)
    with _client(daemon) as c:
        c.feed("j", parts[0], algo="pca", partition=0)
        c.commit("j", partition=0)
        # duplicate task replays the same partition (same + newer attempt)
        c.feed("j", parts[0], algo="pca", partition=0, attempt=0)
        c.feed("j", parts[0], algo="pca", partition=0, attempt=7)
        c.commit("j", partition=0, attempt=7)
        c.feed("j", parts[1], algo="pca", partition=1)
        c.commit("j", partition=1)
        assert c.status("j")["rows"] == data.shape[0]
    _assert_matches_batch_fit(daemon, data, mesh8, "j")


def test_concurrent_speculative_attempts_interleaved(daemon, data, mesh8):
    """Spark speculation runs a duplicate attempt ALONGSIDE the original.
    Interleaved feeds from two live attempts must accumulate independently
    (per-(partition, attempt) stages); whichever commits first wins with
    its COMPLETE data, the loser is discarded."""
    parts = np.array_split(data, 2)
    sub = np.array_split(parts[0], 2)
    with _client(daemon) as c:
        # interleave: A0 feeds half, A1 feeds half, A0 feeds rest, A1 rest
        c.feed("j", sub[0], algo="pca", partition=0, attempt=0)
        c.feed("j", sub[0], algo="pca", partition=0, attempt=1)
        c.feed("j", sub[1], algo="pca", partition=0, attempt=0)
        c.feed("j", sub[1], algo="pca", partition=0, attempt=1)
        # original commits first — must carry BOTH its batches
        c.commit("j", partition=0, attempt=0)
        # speculative duplicate commits late — discarded
        c.commit("j", partition=0, attempt=1)
        c.feed("j", parts[1], algo="pca", partition=1)
        c.commit("j", partition=1)
        assert c.status("j")["rows"] == data.shape[0]
    _assert_matches_batch_fit(daemon, data, mesh8, "j")


def test_seed_with_bad_token_keeps_framing(mesh8, data):
    """A rejected seed op (payload-carrying) must drain its payload so the
    connection stays usable for subsequent framed requests."""
    with DataPlaneDaemon(mesh=mesh8, token="tk") as d:
        with DataPlaneClient(*d.address, token="bad") as c:
            with pytest.raises(RuntimeError, match="unauthorized"):
                c.seed_kmeans("km", data, k=3)
            # same connection: framing intact, next op parses correctly
            with pytest.raises(RuntimeError, match="unauthorized"):
                c.ping()
        with DataPlaneClient(*d.address, token="tk") as c:
            assert c.ping()


def test_commit_without_stage_rejected(daemon, data):
    with _client(daemon) as c:
        c.feed("j", data, algo="pca", partition=0)
        with pytest.raises(RuntimeError, match="no staged feed"):
            c.commit("j", partition=3)


def test_commit_attempt_mismatch_rejected(daemon, data):
    with _client(daemon) as c:
        c.feed("j", data, algo="pca", partition=0, attempt=2)
        with pytest.raises(RuntimeError, match="attempt"):
            c.commit("j", partition=0, attempt=1)
        # the stage survives a bad commit; the right attempt still lands
        assert c.commit("j", partition=0, attempt=2) == data.shape[0]


def test_feeder_killed_mid_frame_leaves_job_consistent(daemon, data, mesh8):
    """A feeder whose socket dies mid-Arrow-payload must not corrupt the
    job: the daemon drops the half-read connection, the stage is absent,
    and a clean retry produces the exact model."""
    parts = np.array_split(data, 2)
    with _client(daemon) as c:
        c.feed("j", parts[0], algo="pca", partition=0)
        c.commit("j", partition=0)

    # raw socket: send the feed JSON + a truncated payload frame, then die
    s = socket.create_connection(daemon.address, timeout=10)
    protocol.send_json(
        s, {"op": "feed", "job": "j", "algo": "pca", "partition": 1}
    )
    s.sendall((123456).to_bytes(4, "big"))  # promises 123456 bytes...
    s.sendall(b"x" * 1000)  # ...delivers 1000
    s.close()
    time.sleep(0.2)

    with _client(daemon) as c:
        assert c.status("j")["rows"] == parts[0].shape[0]  # nothing leaked in
        c.feed("j", parts[1], algo="pca", partition=1, attempt=1)
        c.commit("j", partition=1, attempt=1)
        assert c.status("j")["rows"] == data.shape[0]
    _assert_matches_batch_fit(daemon, data, mesh8, "j")


def test_daemon_restart_mid_job_retry_converges(mesh8, data):
    """A daemon process dies AFTER a staged-but-uncommitted feed; a fresh
    daemon comes back at the same address. The self-healing client
    reconnects transparently and a Spark-style retry (new attempt,
    re-feed from scratch) converges to the exact batch-fit model — the
    recompute-safety the whole plane leans on."""
    d1 = DataPlaneDaemon(mesh=mesh8).start()
    host, port = d1.address
    parts = np.array_split(data, 2)
    c = DataPlaneClient(host, port, backoff_base_s=0.01, backoff_max_s=0.1,
                        max_op_attempts=8)
    try:
        c.feed("j", parts[0], algo="pca", partition=0)  # staged, no commit
        d1.stop()  # daemon dies; the stage dies with it
        d2 = DataPlaneDaemon(host=host, port=port, mesh=mesh8).start()
        try:
            for pid, part in enumerate(parts):
                c.feed("j", part, algo="pca", partition=pid, attempt=1)
                c.commit("j", partition=pid, attempt=1)
            assert c.stats["reconnects"] > 0  # the healing actually ran
            assert c.status("j")["rows"] == data.shape[0]
            out = c.finalize_pca("j", k=3)
        finally:
            d2.stop()
    finally:
        c.close()
    ref = fit_pca(data, k=3, mesh=mesh8)
    np.testing.assert_allclose(np.abs(out["pc"]), np.abs(ref.pc), atol=1e-8)
    np.testing.assert_allclose(out["mean"], ref.mean, atol=1e-10)


def test_feed_replay_same_feed_id_not_double_counted(daemon, data, mesh8):
    """Lost-ack replay: the self-healing client resends a feed with the
    SAME feed_id; the daemon folds it at most once per stage."""
    parts = np.array_split(data, 2)
    with _client(daemon) as c:
        payload = c._to_ipc(parts[0], "features", "label")
        req = {"op": "feed", "job": "j", "algo": "pca", "partition": 0,
               "attempt": 0, "feed_id": "dup-1"}
        c._roundtrip(dict(req), payload=payload)
        c._roundtrip(dict(req), payload=payload)  # the replay
        c.commit("j", partition=0)
        c.feed("j", parts[1], algo="pca", partition=1)
        c.commit("j", partition=1)
        assert c.status("j")["rows"] == data.shape[0]
    _assert_matches_batch_fit(daemon, data, mesh8, "j")


def test_unpartitioned_feed_replay_deduped(daemon, data):
    """Direct (unpartitioned) feeds fold immediately — replay dedupe uses
    the job-level feed_id memory instead of a stage's."""
    with _client(daemon) as c:
        payload = c._to_ipc(data, "features", "label")
        req = {"op": "feed", "job": "uj", "algo": "pca", "feed_id": "u-1"}
        assert c._roundtrip(dict(req), payload=payload)[0]["rows"] == data.shape[0]
        assert c._roundtrip(dict(req), payload=payload)[0]["rows"] == data.shape[0]
        assert c.status("uj")["rows"] == data.shape[0]


def test_merge_state_replay_same_merge_id_not_double_applied(daemon, data, mesh8):
    """merge_state folds immediately (like an unpartitioned feed); a
    lost-ack replay carrying the same merge_id must not double-apply the
    peer's partials."""
    parts = np.array_split(data, 2)
    with _client(daemon) as c:
        c.feed("src", parts[1], algo="pca", partition=0)
        c.commit("src", partition=0)
        arrays, meta = c.export_state("src")
        c.feed("dst", parts[0], algo="pca", partition=0)
        c.commit("dst", partition=0)
        req = {
            "op": "merge_state", "job": "dst", "algo": "pca",
            "n_cols": int(meta["n_cols"]), "rows": int(meta["pass_rows"]),
            "merge_id": "m-1",
        }
        assert c._send_arrays_op(dict(req), arrays)["rows"] == data.shape[0]
        # the replay: acked with the same total, nothing folded twice
        assert c._send_arrays_op(dict(req), arrays)["rows"] == data.shape[0]
    _assert_matches_batch_fit(daemon, data, mesh8, "dst")


def test_step_replay_same_step_id_returns_cached_info(daemon, rng):
    """A step replay whose first ack was lost must not double-advance the
    iterate: the same step_id returns the cached convergence info; a
    DIFFERENT step over the empty pass still errors (zombie guard)."""
    x = rng.normal(size=(64, 4)).astype(np.float32)
    with _client(daemon) as c:
        c.seed_kmeans("km", x, k=3, params={"seed": 0})
        c.feed("km", x, algo="kmeans", partition=0, pass_id=0, params={"k": 3})
        c.commit("km", partition=0, pass_id=0)
        r1, _ = c._roundtrip(
            {"op": "step", "job": "km", "params": {}, "step_id": "s-1"}
        )
        r2, _ = c._roundtrip(
            {"op": "step", "job": "km", "params": {}, "step_id": "s-1"}
        )
        assert r1["iteration"] == r2["iteration"] == 1
        assert r2["moved2"] == r1["moved2"]
        with pytest.raises(RuntimeError, match="no rows"):
            c.step("km")


# ------------------------- iterative pass fencing ---------------------------


def test_stale_pass_feed_rejected(daemon, rng):
    x = rng.normal(size=(64, 4)).astype(np.float32)
    with _client(daemon) as c:
        c.seed_kmeans("km", x, k=3, params={"seed": 0})
        c.feed("km", x, algo="kmeans", partition=0, pass_id=0, params={"k": 3})
        c.commit("km", partition=0, pass_id=0)
        c.step("km")
        # a zombie task from pass 0 arrives late
        with pytest.raises(RuntimeError, match="stale pass"):
            c.feed("km", x, algo="kmeans", partition=0, pass_id=0, params={"k": 3})
        with pytest.raises(RuntimeError, match="stale pass"):
            c.commit("km", partition=0, pass_id=0)
        # current-pass traffic flows
        c.feed("km", x, algo="kmeans", partition=0, pass_id=1, params={"k": 3})
        c.commit("km", partition=0, pass_id=1)


def test_first_feed_stale_pass_unregisters_job(daemon, rng):
    """A partition rescheduled mid-fit onto a daemon that never saw the
    job must not leave an orphan pass-0 job parked under the name: the
    rejected first fold unregisters it, and the error names the routing
    fix instead of the bare stale-pass message (round-4 advisor)."""
    x = rng.normal(size=(64, 4)).astype(np.float32)
    with _client(daemon) as c:
        with pytest.raises(RuntimeError, match="sticky"):
            c.feed("fresh", x, algo="pca", partition=0, pass_id=3)
        # The orphan job did NOT stay registered...
        with pytest.raises(RuntimeError, match="no such job"):
            c.status("fresh")
        # ...so a corrected fit can reuse the name from pass 0.
        c.feed("fresh", x, algo="pca", partition=0, pass_id=0)
        c.commit("fresh", partition=0, pass_id=0)
        assert c.status("fresh")["rows"] == 64


def test_array_spec_count_capped_framing_survives(daemon, rng):
    """A request declaring more raw-array frames than any protocol op
    needs is rejected (bounding what one request can make the daemon
    buffer, round-4 advisor) — and the connection stays usable."""
    x = rng.normal(size=(4, 4)).astype(np.float32)
    with _client(daemon) as c:
        arrays = {f"a{i}": x for i in range(17)}
        with pytest.raises(RuntimeError, match="array frames"):
            c._send_arrays_op(
                {"op": "feed_raw", "job": "caps", "algo": "pca"}, arrays
            )
        assert c.ping()  # framing aligned after drain-then-error


def test_array_declared_bytes_capped_framing_survives(daemon):
    """Declared summed bytes are validated against MAX_FRAME BEFORE the
    frames are buffered; undersized actual frames are drained one at a
    time and the connection stays aligned."""
    with _client(daemon) as c:
        sock = c._conn()
        huge = {
            "op": "feed_raw", "job": "caps2", "algo": "pca",
            "v": protocol.PROTOCOL_VERSION,
            "arrays": [
                {"name": "x", "dtype": "float32",
                 "shape": [1 << 20, 1 << 10]},  # 4 GB declared
            ],
        }
        protocol.send_json(sock, huge)
        protocol.send_frame(sock, b"tiny")  # what's actually sent
        resp = protocol.recv_json(sock)
        assert resp is not None and not resp["ok"]
        assert "MAX_FRAME" in resp["error"]
        assert c.ping()


def test_array_frame_size_must_match_spec(daemon):
    """A frame that disagrees with its declared spec size is rejected
    (declare-tiny/send-huge would bypass the buffering cap) and the
    framing stays aligned."""
    with _client(daemon) as c:
        sock = c._conn()
        protocol.send_json(sock, {
            "op": "feed_raw", "job": "caps3", "algo": "pca",
            "v": protocol.PROTOCOL_VERSION,
            "arrays": [{"name": "x", "dtype": "float32", "shape": [2, 2]}],
        })
        protocol.send_frame(sock, b"\x00" * 64)  # declared 16 bytes
        resp = protocol.recv_json(sock)
        assert resp is not None and not resp["ok"]
        assert "declared" in resp["error"]
        assert c.ping()


def test_bad_array_spec_drains_before_error(daemon):
    """A malformed dtype in the spec (easy for from-scratch feed_raw
    clients) errors only AFTER the declared frames are drained, keeping
    the connection framing aligned."""
    with _client(daemon) as c:
        sock = c._conn()
        protocol.send_json(sock, {
            "op": "feed_raw", "job": "caps4", "algo": "pca",
            "v": protocol.PROTOCOL_VERSION,
            "arrays": [{"name": "x", "dtype": "flaot32", "shape": [2, 2]}],
        })
        protocol.send_frame(sock, b"\x00" * 16)
        resp = protocol.recv_json(sock)
        assert resp is not None and not resp["ok"]
        assert "bad array spec" in resp["error"]
        assert c.ping()


def test_seeded_kmeans_deterministic_across_feed_orders(daemon, rng, mesh8):
    """Driver-side seeding makes the fit independent of partition arrival
    order — the reproducibility gap of first-batch-wins seeding."""
    x = rng.normal(size=(240, 6)).astype(np.float32)
    parts = np.array_split(x, 4)
    results = []
    for job, order in (("a", [0, 1, 2, 3]), ("b", [3, 2, 1, 0])):
        with _client(daemon) as c:
            c.seed_kmeans(job, x[:50], k=4, params={"seed": 7})
            for it in range(3):
                for pid in order:
                    c.feed(job, parts[pid], algo="kmeans", partition=pid,
                           pass_id=it, params={"k": 4})
                    c.commit(job, partition=pid, pass_id=it)
                c.step(job)
            results.append(c.finalize_kmeans(job)["centers"])
    np.testing.assert_array_equal(results[0], results[1])


def test_partitioned_kmeans_requires_seed(daemon, rng):
    x = rng.normal(size=(64, 4)).astype(np.float32)
    with _client(daemon) as c:
        with pytest.raises(RuntimeError, match="seed"):
            c.feed("km2", x, algo="kmeans", partition=0, params={"k": 3})


# ------------------------------ ttl eviction --------------------------------


def test_ttl_evicts_abandoned_job(mesh8, data):
    # Injected clock: no wall sleeps — advance fake time past the TTL and
    # wait only for one (50 ms) reaper tick.
    clk = {"t": 0.0}
    with DataPlaneDaemon(
        mesh=mesh8, ttl=60.0, clock=lambda: clk["t"], reap_interval=0.02
    ) as d:
        with DataPlaneClient(*d.address) as c:
            c.feed("abandoned", data, algo="pca")
            assert c.status("abandoned")["rows"] == data.shape[0]
            clk["t"] = 61.0  # job now idle past the TTL
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                try:
                    c.status("abandoned")
                except RuntimeError:
                    break
                time.sleep(0.02)
            else:
                raise AssertionError("idle job was never evicted")
            with pytest.raises(RuntimeError, match="no such job"):
                c.finalize_pca("abandoned", k=2)


def test_active_job_survives_ttl(mesh8, data):
    clk = {"t": 0.0}
    with DataPlaneDaemon(
        mesh=mesh8, ttl=60.0, clock=lambda: clk["t"], reap_interval=0.02
    ) as d:
        with DataPlaneClient(*d.address) as c:
            c.feed("active", data[:200], algo="pca")
            # keep touching just inside the TTL across several reaper
            # ticks — alternating feed and partitioned feed+commit so
            # BOTH touch paths (fold's and commit's exit stamps) are what
            # keeps the job alive
            for i in range(4):
                clk["t"] += 50.0
                if i % 2 == 0:
                    c.feed("active", data[:50], algo="pca")
                else:
                    c.feed(
                        "active", data[200 + i * 50 : 250 + i * 50],
                        algo="pca", partition=i,
                    )
                    clk["t"] += 50.0
                    c.commit("active", partition=i)
                time.sleep(0.05)  # several reaper ticks at the fake time
            arrays = c.finalize_pca("active", k=2)
            assert arrays["pc"].shape == (data.shape[1], 2)


def test_token_required_when_configured(mesh8, data):
    with DataPlaneDaemon(mesh=mesh8, token="s3cret") as d:
        with DataPlaneClient(*d.address) as c:
            with pytest.raises(RuntimeError, match="unauthorized"):
                c.ping()
        with DataPlaneClient(*d.address, token="wrong") as c:
            with pytest.raises(RuntimeError, match="unauthorized"):
                c.feed("j", data, algo="pca")
        with DataPlaneClient(*d.address, token="s3cret") as c:
            assert c.ping()
            c.feed("j", data, algo="pca")
            out = c.finalize_pca("j", k=2)
            assert out["pc"].shape == (data.shape[1], 2)


def test_no_token_daemon_ignores_client_token(daemon):
    with _client(daemon, token="anything") as c:
        assert c.ping()


def test_raw_moments_finalize_for_scaler(daemon, data, mesh8):
    """A scaler fit rides the pca job protocol: finalize with raw_moments
    returns the accumulated (count, colsum, gram diagonal) without an
    eigensolve — the moments SparkStandardScaler derives mean/std from."""
    parts = np.array_split(data, 3)
    with _client(daemon) as c:
        for pid, part in enumerate(parts):
            c.feed("sc", part, algo="pca", partition=pid)
            c.commit("sc", partition=pid)
        arrays, rows = c.finalize("sc", {"raw_moments": True})
    assert rows == data.shape[0]
    assert float(arrays["count"][0]) == data.shape[0]
    np.testing.assert_allclose(arrays["colsum"], data.sum(axis=0), rtol=1e-10)
    np.testing.assert_allclose(
        arrays["gram_diag"], (data * data).sum(axis=0), rtol=1e-10
    )
