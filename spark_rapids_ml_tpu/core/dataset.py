"""Dataset abstraction: column access over host data containers.

The reference consumes Spark DataFrames with an ``ArrayType`` vector column
(README.md:26-37 — the API change vs. stock Spark ML, which uses ``Vector``).
This framework is host-framework-agnostic: estimators address columns by name
over any of

* ``pyarrow.Table`` / ``pyarrow.RecordBatch`` (the columnar interchange
  format a Spark executor ships to a TPU host — list column = ArrayType),
* ``pandas.DataFrame`` (vector column = column of array-likes, or 2-D),
* ``dict`` of name → array,
* bare ``numpy.ndarray`` (2-D; column names ignored — the "matrix in hand"
  path used by tests and the pure-JAX API).

``with_column`` returns the same container kind with the output column
appended, mirroring ``df.withColumn(outputCol, ...)`` (RapidsPCA.scala:165).
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

try:
    import pyarrow as pa
except ImportError:  # pragma: no cover
    pa = None

try:
    import pandas as pd
except ImportError:  # pragma: no cover
    pd = None

from spark_rapids_ml_tpu.bridge import arrow as _arrow_bridge


def _is_arrow(dataset: Any) -> bool:
    return pa is not None and isinstance(dataset, (pa.Table, pa.RecordBatch))


def _is_pandas(dataset: Any) -> bool:
    return pd is not None and isinstance(dataset, pd.DataFrame)


def num_rows(dataset: Any) -> int:
    if _is_arrow(dataset):
        return dataset.num_rows
    if _is_pandas(dataset):
        return len(dataset)
    if isinstance(dataset, dict):
        if not dataset:
            return 0
        return len(next(iter(dataset.values())))
    arr = np.asarray(dataset)
    return arr.shape[0]


def as_matrix(dataset: Any, col: Optional[str] = None, n_cols: Optional[int] = None) -> np.ndarray:
    """Extract a column of fixed-width vectors as an (n, d) ndarray."""
    if _is_arrow(dataset):
        assert col is not None, "column name required for Arrow datasets"
        if isinstance(dataset, pa.RecordBatch):
            dataset = pa.Table.from_batches([dataset])
        return _arrow_bridge.table_column_to_matrix(dataset, col, n_cols)
    if _is_pandas(dataset):
        assert col is not None, "column name required for pandas datasets"
        series = dataset[col]
        mat, _ = _arrow_bridge.matrix_from_any(series.to_numpy())
        return mat
    if isinstance(dataset, dict):
        assert col is not None, "column name required for dict datasets"
        mat, _ = _arrow_bridge.matrix_from_any(dataset[col])
        return mat
    arr = np.asarray(dataset)
    if arr.ndim != 2:
        raise ValueError(f"expected a 2-D matrix dataset, got shape {arr.shape}")
    return arr


def has_column(dataset: Any, col: str) -> bool:
    """Whether the dataset carries a column named ``col``."""
    if _is_arrow(dataset):
        return col in dataset.schema.names
    if _is_pandas(dataset):
        return col in dataset.columns
    if isinstance(dataset, dict):
        return col in dataset
    return False


def as_column(dataset: Any, col: str) -> np.ndarray:
    """Extract a scalar column (labels, weights) as a 1-D ndarray."""
    if _is_arrow(dataset):
        if isinstance(dataset, pa.RecordBatch):
            dataset = pa.Table.from_batches([dataset])
        return np.asarray(dataset.column(col))
    if _is_pandas(dataset):
        return dataset[col].to_numpy()
    if isinstance(dataset, dict):
        return np.asarray(dataset[col])
    raise TypeError(
        f"cannot extract named column {col!r} from a bare array dataset; "
        "pass a dict/arrow/pandas container"
    )


def take_rows(dataset: Any, indices: np.ndarray) -> Any:
    """Row-subset the dataset by integer indices, preserving container kind.

    The fold-split primitive for CrossValidator/TrainValidationSplit
    (tuning.py) — mirrors ``df.filter`` + randomSplit semantics without a
    query engine."""
    indices = np.asarray(indices)
    if _is_arrow(dataset):
        if isinstance(dataset, pa.RecordBatch):
            dataset = pa.Table.from_batches([dataset])
        return dataset.take(pa.array(indices))
    if _is_pandas(dataset):
        return dataset.iloc[indices].reset_index(drop=True)
    if isinstance(dataset, dict):
        return {k: np.asarray(v)[indices] for k, v in dataset.items()}
    return np.asarray(dataset)[indices]


def with_column(dataset: Any, name: str, values: np.ndarray) -> Any:
    """Return the dataset with ``values`` appended as column ``name``.

    2-D values become a vector column in the container's native vector
    representation (Arrow fixed_size_list / pandas object column of arrays).
    """
    values = np.asarray(values)
    if _is_arrow(dataset):
        if isinstance(dataset, pa.RecordBatch):
            dataset = pa.Table.from_batches([dataset])
        if values.ndim == 2:
            col = _arrow_bridge.matrix_to_list_column(values)
        else:
            col = pa.array(values)
        if name in dataset.column_names:
            dataset = dataset.drop_columns([name])
        return dataset.append_column(name, col)
    if _is_pandas(dataset):
        out = dataset.copy()
        if values.ndim == 2:
            out[name] = list(values)
        else:
            out[name] = values
        return out
    if isinstance(dataset, dict):
        out = dict(dataset)
        out[name] = values
        return out
    # Bare ndarray in, bare ndarray out (the pure-matrix API).
    return values
