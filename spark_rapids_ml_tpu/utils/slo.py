"""SLO burn-rate evaluation over the metrics registry (utils/metrics.py).

The autoscaler judges raw watermarks (queue depth, shed deltas); this
module judges OBJECTIVES — "99% of transforms under 50 ms", "error rate
under 0.1%" — the way SRE practice does: as **multi-window burn rates**
over the error budget. For each declared objective the evaluator
computes, from deltas of the cumulative daemon histograms/counters, the
fraction of requests that violated the objective over a FAST window and
a SLOW window, divides each by the budget (the allowed violating
fraction) to get a burn rate (1.0 = burning exactly the budget), and
raises a breach only when BOTH windows burn above
``slo_burn_threshold`` — the fast window catches a storm in seconds,
the slow window keeps a momentary blip from paging.

Objectives are declared in config (``slo_objectives``, env
``SRML_SLO_OBJECTIVES``) as semicolon-separated specs::

    <op>:<kind>[=<target>][@<budget>]

with ``kind`` one of:

* ``p99_ms`` — latency objective: at most ``budget`` (default 0.01) of
  requests slower than ``target`` milliseconds, judged against the
  ``srml_daemon_request_seconds{op=…}`` histogram (interpolated inside
  the target's bucket);
* ``error`` — at most ``budget`` (default 0.001) of requests with
  outcome ``error``/``transport`` (``srml_daemon_requests_total``);
* ``shed`` — at most ``budget`` (default 0.01) of requests shed
  (``srml_daemon_busy_sheds_total`` + ``srml_scheduler_sheds_total``).

Results are exported as gauges — ``srml_slo_burn_rate{objective,op,
window}`` and ``srml_slo_breach{objective,op}`` — so they ride the
normal scrape path (``metrics`` / ``telemetry_pull`` ops), render as a
``tools/top`` panel, feed the autoscaler as a forced-scale-up signal,
and arm the flight recorder (utils/flight.py). The daemon's telemetry
thread ticks one evaluator per process; tests tick one directly with
synthetic snapshots and explicit ``now`` timestamps.
"""

from __future__ import annotations

import math
import threading
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

from spark_rapids_ml_tpu.utils import metrics as metrics_mod

__all__ = [
    "Objective",
    "SloEvaluator",
    "parse_objectives",
    "count_le",
]

#: Default budgets (allowed violating fraction) per objective kind.
_DEFAULT_BUDGETS = {"p99_ms": 0.01, "error": 0.001, "shed": 0.01}

_G_BURN = metrics_mod.gauge(
    "srml_slo_burn_rate",
    "Error-budget burn rate per objective and window (fast|slow): 1.0 "
    "= burning exactly the budget; breaches need both windows over "
    "slo_burn_threshold",
)
_G_BREACH = metrics_mod.gauge(
    "srml_slo_breach",
    "1 while an objective's fast AND slow burn rates both exceed "
    "slo_burn_threshold, else 0",
)


class Objective:
    """One declared per-op objective. ``target`` is milliseconds for
    ``p99_ms`` and unused for ``error``/``shed``; ``budget`` is the
    allowed violating fraction of requests."""

    def __init__(self, op: str, kind: str, target: Optional[float],
                 budget: float):
        if kind not in _DEFAULT_BUDGETS:
            raise ValueError(f"unknown SLO kind {kind!r} (op {op!r})")
        if kind == "p99_ms" and (target is None or target <= 0):
            raise ValueError(f"p99_ms objective for {op!r} needs =<target_ms>")
        if not 0 < budget < 1:
            raise ValueError(f"SLO budget must be in (0, 1), got {budget!r}")
        self.op = op
        self.kind = kind
        self.target = target
        self.budget = float(budget)

    @property
    def name(self) -> str:
        return f"{self.op}:{self.kind}"

    def __repr__(self) -> str:  # tools/top panel + logs
        t = f"={self.target:g}" if self.target is not None else ""
        return f"{self.op}:{self.kind}{t}@{self.budget:g}"


def parse_objectives(spec: str) -> List[Objective]:
    """Parse the ``slo_objectives`` config string. Empty/whitespace →
    no objectives. Malformed entries raise ``ValueError`` — a typoed
    objective silently evaluating nothing is the worst failure mode an
    SLO layer can have."""
    out: List[Objective] = []
    for raw in (spec or "").split(";"):
        raw = raw.strip()
        if not raw:
            continue
        try:
            op, rest = raw.split(":", 1)
        except ValueError:
            raise ValueError(f"SLO spec {raw!r}: expected <op>:<kind>…")
        budget: Optional[float] = None
        if "@" in rest:
            rest, b = rest.rsplit("@", 1)
            budget = float(b)
        target: Optional[float] = None
        if "=" in rest:
            rest, t = rest.split("=", 1)
            target = float(t)
        kind = rest.strip()
        out.append(Objective(
            op.strip(), kind, target,
            budget if budget is not None else _DEFAULT_BUDGETS.get(kind, 0.01),
        ))
    return out


def count_le(buckets: Dict[str, Any], x: float) -> float:
    """Estimated number of samples ≤ ``x`` from CUMULATIVE le→count
    buckets, linearly interpolated inside x's bucket. Past the largest
    finite bound the whole +Inf tail counts as violations (conservative
    — nothing inside that bucket is knowable)."""
    pairs: List[Tuple[float, float]] = sorted(
        (math.inf if le == "+Inf" else float(le), float(n))
        for le, n in buckets.items()
    )
    prev_b, prev_n = 0.0, 0.0
    for b, n in pairs:
        if math.isinf(b):
            return prev_n
        if x < b:
            if x <= prev_b:
                return prev_n
            return prev_n + (x - prev_b) / (b - prev_b) * (n - prev_n)
        prev_b, prev_n = b, n
    return prev_n


def _op_stats(snap: Dict[str, Any]) -> Dict[str, Dict[str, Any]]:
    """Per-op cumulative stats out of one registry snapshot: total and
    error request counts, shed count, and the latency buckets."""
    stats: Dict[str, Dict[str, Any]] = {}

    def row(op: str) -> Dict[str, Any]:
        return stats.setdefault(
            op, {"total": 0.0, "err": 0.0, "shed": 0.0, "buckets": None}
        )

    for s in snap.get("srml_daemon_requests_total", {}).get("samples", []):
        op = s["labels"].get("op", "")
        row(op)["total"] += float(s["value"])
        if s["labels"].get("outcome") in ("error", "transport"):
            row(op)["err"] += float(s["value"])
    for s in snap.get("srml_daemon_busy_sheds_total", {}).get("samples", []):
        row(s["labels"].get("op", ""))["shed"] += float(s["value"])
    for s in snap.get("srml_scheduler_sheds_total", {}).get("samples", []):
        row(s["labels"].get("op", ""))["shed"] += float(s["value"])
    for s in snap.get("srml_daemon_request_seconds", {}).get("samples", []):
        row(s["labels"].get("op", ""))["buckets"] = s.get("buckets") or {}
    return stats


def _violations(obj: Objective, then: Dict[str, Any], now: Dict[str, Any]
                ) -> Tuple[float, float]:
    """(violating requests, total requests) for one objective over the
    delta between two cumulative per-op stat rows."""
    total = now["total"] - then["total"]
    if total <= 0:
        return 0.0, 0.0
    if obj.kind == "error":
        return max(0.0, now["err"] - then["err"]), total
    if obj.kind == "shed":
        return max(0.0, now["shed"] - then["shed"]), total
    # p99_ms: violations = requests slower than target over the window.
    b_now, b_then = now.get("buckets"), then.get("buckets")
    if not b_now:
        return 0.0, 0.0
    x = float(obj.target) / 1000.0  # histogram is in seconds
    n_now = float(b_now.get("+Inf", 0.0))
    n_then = float(b_then.get("+Inf", 0.0)) if b_then else 0.0
    window_n = n_now - n_then
    if window_n <= 0:
        return 0.0, 0.0
    ok = count_le(b_now, x) - (count_le(b_then, x) if b_then else 0.0)
    return max(0.0, window_n - ok), window_n


class SloEvaluator:
    """Rings cumulative snapshots and turns deltas into burn rates.

    ``tick(snap, now)`` appends one (ts, per-op stats) point, computes
    every objective's fast/slow burn, publishes the ``srml_slo_*``
    gauges, and returns the evaluation list — one dict per objective
    with ``fast_burn`` / ``slow_burn`` / ``breach``. With fewer than
    ``window`` seconds of history a window burns over the span it has
    (a storm at t=5s must not hide behind an unfilled 60 s window).
    """

    def __init__(
        self,
        objectives: Optional[List[Objective]] = None,
        fast_window_s: Optional[float] = None,
        slow_window_s: Optional[float] = None,
        burn_threshold: Optional[float] = None,
    ):
        from spark_rapids_ml_tpu import config

        if objectives is None:
            objectives = parse_objectives(str(config.get("slo_objectives") or ""))
        self.objectives = list(objectives)
        self.fast_window_s = float(
            fast_window_s if fast_window_s is not None
            else config.get("slo_fast_window_s")
        )
        self.slow_window_s = float(
            slow_window_s if slow_window_s is not None
            else config.get("slo_slow_window_s")
        )
        self.burn_threshold = float(
            burn_threshold if burn_threshold is not None
            else config.get("slo_burn_threshold")
        )
        self._lock = threading.Lock()
        self._history: Deque[Tuple[float, Dict[str, Dict[str, Any]]]] = deque()
        self._last: List[Dict[str, Any]] = []

    def _baseline(self, now_ts: float, window: float
                  ) -> Optional[Tuple[float, Dict[str, Dict[str, Any]]]]:
        """Latest history point at least ``window`` old, else the oldest
        point (partial window); None with no history."""
        best = None
        for ts, stats in self._history:
            if ts <= now_ts - window:
                best = (ts, stats)
            else:
                break
        if best is None and self._history:
            best = self._history[0]
        return best

    def tick(
        self,
        snap: Optional[Dict[str, Any]] = None,
        now: Optional[float] = None,
    ) -> List[Dict[str, Any]]:
        import time as _time

        if snap is None:
            snap = metrics_mod.snapshot()
        if now is None:
            now = _time.time()
        stats = _op_stats(snap)
        empty = {"total": 0.0, "err": 0.0, "shed": 0.0, "buckets": None}
        out: List[Dict[str, Any]] = []
        with self._lock:
            for obj in self.objectives:
                cur = stats.get(obj.op, empty)
                burns = {}
                for win_name, win in (("fast", self.fast_window_s),
                                      ("slow", self.slow_window_s)):
                    base = self._baseline(now, win)
                    prev = base[1].get(obj.op, empty) if base else empty
                    viol, total = _violations(obj, prev, cur)
                    frac = viol / total if total > 0 else 0.0
                    burns[win_name] = frac / obj.budget
                breach = (
                    burns["fast"] >= self.burn_threshold
                    and burns["slow"] >= self.burn_threshold
                )
                _G_BURN.set(burns["fast"], objective=obj.name, op=obj.op,
                            window="fast")
                _G_BURN.set(burns["slow"], objective=obj.name, op=obj.op,
                            window="slow")
                _G_BREACH.set(1.0 if breach else 0.0, objective=obj.name,
                              op=obj.op)
                out.append({
                    "objective": obj.name,
                    "op": obj.op,
                    "kind": obj.kind,
                    "target": obj.target,
                    "budget": obj.budget,
                    "fast_burn": burns["fast"],
                    "slow_burn": burns["slow"],
                    "breach": breach,
                })
            self._history.append((now, stats))
            horizon = now - self.slow_window_s - 1.0
            while len(self._history) > 1 and self._history[1][0] <= horizon:
                self._history.popleft()
            self._last = out
        return out

    def breaches(self) -> List[Dict[str, Any]]:
        """Objectives breaching as of the last tick."""
        with self._lock:
            return [e for e in self._last if e["breach"]]
