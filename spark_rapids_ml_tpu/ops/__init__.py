"""XLA-compiled math kernels — the TPU equivalent of the reference's native
device math core (``native/src/rapidsml_jni.cu``; SURVEY.md §2.2).

Mapping to the reference's native symbols:

* ``dgemmCov`` (partition Gram AᵀA, rapidsml_jni.cu:109-127)  → ``gram.py``
  (fused count/sum/Gram statistics, sharded via shard_map + psum, optional
  Pallas kernel).
* ``calSVD`` (eigendecomposition + reorder + sqrt + sign flip,
  rapidsml_jni.cu:215-269) → ``eigh.py``.
* ``signFlip`` Thrust kernel (rapidsml_jni.cu:35-61) → ``eigh.sign_flip``.
* ``dgemm`` (projection GEMM for transform, rapidsml_jni.cu:75-107) →
  plain ``x @ pc`` under jit (XLA emits the MXU GEMM; no hand kernel needed).
* plus what the reference lacks: pairwise distances (``distances.py``) and
  SPD solves (``linalg.py``) for the KMeans / linear-model / KNN families.
"""

from spark_rapids_ml_tpu.ops.gram import (
    local_stats,
    sharded_stats,
    sharded_stats_2d,
    finalize_gram,
    mm_precision,
)
from spark_rapids_ml_tpu.ops.eigh import (
    eigh_descending,
    sign_flip,
    explained_variance_reference,
    explained_variance_ratio,
    pca_from_gram,
)
from spark_rapids_ml_tpu.ops.distances import sq_euclidean
from spark_rapids_ml_tpu.ops.linalg import solve_spd

__all__ = [
    "local_stats",
    "sharded_stats",
    "sharded_stats_2d",
    "finalize_gram",
    "mm_precision",
    "eigh_descending",
    "sign_flip",
    "explained_variance_reference",
    "explained_variance_ratio",
    "pca_from_gram",
    "sq_euclidean",
    "solve_spd",
]
