"""Multi-host distributed runtime initialization.

The reference's "communication backend" is Spark RPC carrying serialized
matrices (SURVEY.md §2.3) — it never initializes a collective runtime
because it doesn't have one. This framework's backend is XLA collectives
over ICI (intra-slice) and DCN (cross-slice/host); what needs managing is
the multi-process JAX runtime: every host must call
``jax.distributed.initialize`` with a shared coordinator so
``jax.devices()`` spans the pod and one ``shard_map`` program runs SPMD
across all hosts.

``initialize_cluster`` wraps that with environment autodetection:
* On Cloud TPU pods, jax autodetects everything (no arguments needed).
* Under Spark, executors carry rank info in env vars; pass
  ``coordinator_address`` of executor 0.
* Single-process (one host, the tests, local mode): no-op.

After initialization, ``global_mesh()`` builds the (data, model) mesh over
ALL devices in the job — the same mesh code as single-host, which is the
point: SURVEY.md §2.3's "one pmap across a TPU pod" with zero algorithm
changes.
"""

from __future__ import annotations

import os
from typing import Optional

import jax

from spark_rapids_ml_tpu.parallel.mesh import make_mesh
from spark_rapids_ml_tpu.utils.logging import get_logger

_logger = get_logger(__name__)
_initialized = False


def is_initialized() -> bool:
    return _initialized


def initialize_cluster(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> int:
    """Initialize the multi-host JAX runtime; returns this process's index.

    Safe to call when single-process (returns 0 without touching the
    runtime). Arguments default from env vars (SRML_TPU_COORDINATOR,
    SRML_TPU_NUM_PROCS, SRML_TPU_PROC_ID) so a Spark executor launcher can
    configure workers without code changes.
    """
    global _initialized
    coordinator_address = coordinator_address or os.environ.get("SRML_TPU_COORDINATOR")
    num_processes = num_processes or _env_int("SRML_TPU_NUM_PROCS")
    process_id = process_id if process_id is not None else _env_int("SRML_TPU_PROC_ID")

    if coordinator_address is None and num_processes in (None, 1):
        # Single process — on Cloud TPU pods jax.distributed.initialize()
        # with no args would autodetect, but calling it single-host is a
        # no-op need; skip to keep local/test runs hermetic.
        _initialized = True
        return 0

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True
    _logger.info(
        "distributed runtime up: process %s/%s, %d global devices",
        jax.process_index(),
        jax.process_count(),
        len(jax.devices()),
    )
    return jax.process_index()


def _env_int(name: str) -> Optional[int]:
    v = os.environ.get(name)
    return int(v) if v else None


def global_mesh(model: int = 1):
    """(data, model) mesh over every device in the job (all hosts)."""
    return make_mesh(model=model)


def process_local_rows(n_rows: int) -> tuple:
    """[start, stop) row range this process should feed, for host-sharded
    data loading: each host only materializes its slice."""
    p = jax.process_index()
    count = jax.process_count()
    per = (n_rows + count - 1) // count
    return min(p * per, n_rows), min((p + 1) * per, n_rows)
