"""Logging setup — the Spark ``Logging`` trait equivalent.

(Reference: RapidsRowMatrix extends Logging, RapidsRowMatrix.scala:24,32, and
debug breadcrumbs marking which transform path ran, RapidsPCA.scala:131,158.)
"""

from __future__ import annotations

import logging
import os

_CONFIGURED = False


def get_logger(name: str) -> logging.Logger:
    global _CONFIGURED
    if not _CONFIGURED:
        level = os.environ.get("SRML_TPU_LOG_LEVEL", "WARNING").upper()
        logging.basicConfig(
            format="%(asctime)s %(levelname)s %(name)s: %(message)s",
            level=getattr(logging, level, logging.WARNING),
        )
        _CONFIGURED = True
    return logging.getLogger(name)
