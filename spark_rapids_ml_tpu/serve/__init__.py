"""TPU-host data-plane daemon: the executor→TPU-host feeding path.

The reference's data plane is the spark-rapids plugin's device-resident
``ColumnarRdd`` (SURVEY.md §1 L1) — executors and the GPU share an address
space, so partitions reach the math core zero-copy. TPU hosts have no such
free ride from JVM executors (SURVEY.md §7 hard part (a)); the equivalent
component is this daemon: a TCP server on the TPU host that accepts Arrow
IPC record-batch streams from Spark tasks, flattens the vector column
through the columnar bridge (native C++ path when available), and folds
each batch into the on-device sharded accumulator — so the cluster-side
"reduce" is the daemon's psum-backed streaming state, the role the
reference's JVM ``RDD.reduce`` played (RapidsRowMatrix.scala:139).
"""

from spark_rapids_ml_tpu.serve.client import DaemonBusy, DataPlaneClient
from spark_rapids_ml_tpu.serve.daemon import DataPlaneDaemon
from spark_rapids_ml_tpu.serve.fleet import FleetRolloutError, ModelFleet
from spark_rapids_ml_tpu.serve.gossip import FleetView
from spark_rapids_ml_tpu.serve.router import (
    ConsistentHashRing,
    FleetClient,
    FleetUnavailable,
    RoutingTable,
    bootstrap_table,
)
from spark_rapids_ml_tpu.serve.scheduler import RequestScheduler, SchedulerBusy

__all__ = [
    "ConsistentHashRing", "DaemonBusy", "DataPlaneClient", "DataPlaneDaemon",
    "FleetClient", "FleetRolloutError", "FleetUnavailable", "FleetView",
    "ModelFleet", "RequestScheduler", "RoutingTable", "SchedulerBusy",
    "bootstrap_table",
]
