"""Deterministic fault injection for the data plane.

The reference outsources its failure story to Spark task retry (SURVEY
§5); this framework owns its transport, so it must be able to PROVE the
healing works — not with mocks, but by injecting faults into the real
code paths. The client, daemon, wire framing, and Arrow bridge each call
:func:`checkpoint` at named sites; a :class:`FaultPlan` (seedable,
activated explicitly or via the ``SRML_FAULT_PLAN`` environment spec)
decides per call whether to add latency, drop the connection, refuse it,
truncate the in-flight frame, or crash the process — the Podracer
posture (arXiv:2104.06272): hosts fail routinely, the fabric heals.

With no plan active every hook is a module-global load plus an ``is
None`` test — zero-overhead in production.

Sites instrumented today (a rule naming an unknown site simply never
fires):

========================  ====================================================
``client.connect``        before the client's TCP connect (refuse/drop/latency)
``client.op``             before each client request attempt
``daemon.conn``           daemon side, once per accepted connection
``daemon.op``             daemon side, per dispatched request (crash-on-Nth-op)
``daemon.pass_boundary``  after an iterative job's step applied (and its
                          durable snapshot, when armed), before the ack —
                          a crash here is a daemon dying exactly between
                          two passes
``daemon.vanish``         daemon side, at the cross-daemon coordination
                          ops (set_iterate / export_state / reduce_mesh):
                          a crash here is a PEER daemon dying at the
                          moment the fit coordinates across daemons —
                          the permanent-loss site; elastic-fit chaos
                          tests pair it with NO restart
                          (docs/protocol.md "Permanent daemon loss")
``daemon.join``           the mid-fit admission handshake, both ends
                          (spark/estimator.py before the joiner's
                          seeding set_iterate; serve/daemon.py on the
                          job-creating set_iterate path): a vanish/drop
                          here is a daemon dying DURING its admission —
                          the grow chaos tests prove a half-admitted
                          joiner never enters membership
                          (docs/protocol.md "Mid-fit daemon join")
``daemon.scheduler``      serving-scheduler admission (serve/scheduler.py):
                          a drop/refuse here is translated into a shed —
                          the request is answered with the busy/
                          retry_after_s contract, never queued
``gossip.push``           serve/daemon.py, before each per-peer gossip
                          exchange of a tick: a faulted push just drops
                          that peer for that tick — the FleetView is
                          merged only from COMPLETE acks, so a dropped
                          push can delay convergence but never corrupt
                          the view (docs/protocol.md "Fleet gossip &
                          bootstrap")
``fleet.bootstrap``       serve/router.py, before each seed-address
                          pull of a client bootstrap: a faulted seed
                          makes the client retry the NEXT seed with the
                          PR 2 decorrelated-jitter backoff ladder —
                          bootstrap succeeds if ANY seed answers
``fleet.rollout``         serve/fleet.py, after each rollout phase's
                          intent record is gossiped and before the
                          phase runs: a crash here is the controller
                          dying mid-rollout with its intent already on
                          the wire — the crash-safe-rollout chaos tests
                          prove a successor completes or aborts from
                          the gossiped intent, never a half-flipped
                          fleet
``autoscale.action``      serve/autoscaler.py, between a scale decision
                          and its rollout action: a fault here is the
                          controller dying (or being refused) after
                          deciding but before acting — the loop must
                          count the failure and retry on a later tick,
                          never half-scale
``wire.send_frame``       every outbound frame, both directions (partial/drop)
``bridge.to_matrix``      Arrow list column → matrix conversion
``bridge.to_ipc``         matrix/table → Arrow IPC encode (client feed path)
========================  ====================================================

Rule kinds: ``latency`` (sleep ``delay_s``, ±50% jitter from the plan
rng), ``drop`` (raise :class:`InjectedDrop`, a ``ConnectionError``),
``refuse`` (raise :class:`InjectedRefusal`, a ``ConnectionRefusedError``),
``partial`` (at ``wire.send_frame`` only: truncate the frame mid-payload
then drop the connection), ``crash`` (invoke the plan's crash callback
when registered — tests use it to restart an in-process daemon — else
``os._exit(17)``, an abrupt process death).

Determinism: each rule carries its own ``random.Random`` seeded from
``(plan seed, site, kind)`` and its own call counter, so a given rule
fires on the same Nth-arrival sequence regardless of other rules. Under
concurrency the arrival ORDER at a site may interleave differently run
to run — the guarantee chaos tests lean on is stronger anyway: the
healed result must equal the fault-free result exactly, whichever ops
failed.
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Callable, Dict, Optional

__all__ = [
    "FaultPlan",
    "InjectedDrop",
    "InjectedRefusal",
    "activate",
    "deactivate",
    "active_plan",
    "checkpoint",
    "truncation",
    "subscribe",
    "unsubscribe",
]


class InjectedDrop(ConnectionError):
    """An injected connection drop (subclass of ConnectionError so the
    self-healing paths treat it exactly like a real peer failure)."""


class InjectedRefusal(ConnectionRefusedError):
    """An injected connection refusal (daemon 'not accepting')."""


class _Rule:
    __slots__ = (
        "site", "kind", "p", "after", "times", "delay_s", "rng", "lock",
        "calls", "fired",
    )

    def __init__(self, plan_seed: int, site: str, kind: str, p: float,
                 after: int, times: Optional[int], delay_s: float):
        if kind not in ("latency", "drop", "refuse", "partial", "crash"):
            raise ValueError(
                f"unknown fault kind {kind!r} "
                "(latency|drop|refuse|partial|crash)"
            )
        self.site = site
        self.kind = kind
        self.p = float(p)
        self.after = int(after)
        self.times = times if times is None else int(times)
        self.delay_s = float(delay_s)
        # Per-rule rng + counter: a rule's firing sequence depends only on
        # its own arrival stream, not on sibling rules' draws.
        self.rng = random.Random(f"{plan_seed}:{site}:{kind}")
        self.lock = threading.Lock()
        self.calls = 0
        self.fired = 0

    def fires(self) -> bool:
        with self.lock:
            self.calls += 1
            if self.calls <= self.after:
                return False
            if self.times is not None and self.fired >= self.times:
                return False
            if self.p < 1.0 and self.rng.random() >= self.p:
                return False
            self.fired += 1
            return True

    def jittered_delay(self) -> float:
        with self.lock:
            return self.delay_s * (0.5 + self.rng.random())


class FaultPlan:
    """A seeded registry of fault rules, keyed by checkpoint site."""

    def __init__(self, seed: int = 0):
        self.seed = int(seed)
        self._rules: Dict[str, list] = {}
        self._crash_cb: Optional[Callable[[], None]] = None

    def rule(
        self,
        site: str,
        kind: str,
        p: float = 1.0,
        after: int = 0,
        times: Optional[int] = None,
        delay_s: float = 0.0,
    ) -> "FaultPlan":
        """Register one rule; returns self for chaining.

        ``p``: firing probability per eligible call; ``after``: skip the
        first N calls at the site (crash-on-Nth-op = ``after=N-1,
        times=1``); ``times``: total firing budget (None = unbounded);
        ``delay_s``: base sleep for ``latency`` rules.
        """
        if kind == "partial" and site != "wire.send_frame":
            # Frame truncation only exists at the framing layer; a
            # partial rule anywhere else would silently never fire — the
            # exact "chaos test that proves nothing" failure mode this
            # module exists to prevent. Refuse loudly.
            raise ValueError(
                f"'partial' rules only apply at site 'wire.send_frame', "
                f"not {site!r} (use 'drop' for connection-level faults)"
            )
        r = _Rule(self.seed, site, kind, p, after, times, delay_s)
        self._rules.setdefault(site, []).append(r)
        return self

    def on_crash(self, cb: Callable[[], None]) -> "FaultPlan":
        """Callback for ``crash`` rules (in-process tests restart their
        daemon here). Unset, a crash rule ``os._exit(17)``s — the honest
        simulation for a daemon running as its own process."""
        self._crash_cb = cb
        return self

    @property
    def fired(self) -> Dict[str, int]:
        """site → total fired count, for chaos-test assertions that the
        plan actually exercised the healing paths."""
        out: Dict[str, int] = {}
        for site, rules in self._rules.items():
            n = sum(r.fired for r in rules)
            if n:
                out[site] = out.get(site, 0) + n
        return out

    @classmethod
    def from_spec(cls, spec: str) -> "FaultPlan":
        """Parse the ``SRML_FAULT_PLAN`` grammar::

            seed=7;client.op:drop:p=0.1;daemon.op:crash:after=20,times=1

        Semicolon-separated entries; an optional leading ``seed=N``; each
        rule is ``site:kind[:key=val,...]`` with keys ``p``, ``after``,
        ``times``, ``delay_s``.
        """
        entries = [e.strip() for e in spec.split(";") if e.strip()]
        seed = 0
        rules = []
        for e in entries:
            if e.startswith("seed="):
                seed = int(e[len("seed="):])
                continue
            parts = e.split(":")
            if len(parts) < 2:
                raise ValueError(
                    f"bad fault rule {e!r}: want site:kind[:key=val,...]"
                )
            site, kind = parts[0], parts[1]
            kw: Dict[str, float] = {}
            if len(parts) > 2:
                for item in parts[2].split(","):
                    k, _, v = item.partition("=")
                    if k not in ("p", "after", "times", "delay_s"):
                        raise ValueError(f"bad fault rule key {k!r} in {e!r}")
                    kw[k] = float(v)
            plan_kw = {
                "p": kw.get("p", 1.0),
                "after": int(kw.get("after", 0)),
                "times": None if "times" not in kw else int(kw["times"]),
                "delay_s": kw.get("delay_s", 0.0),
            }
            rules.append((site, kind, plan_kw))
        plan = cls(seed=seed)
        for site, kind, plan_kw in rules:
            plan.rule(site, kind, **plan_kw)
        return plan

    # -- execution ---------------------------------------------------------

    def _perform(self, rule: _Rule, site: str) -> None:
        if rule.kind == "latency":
            time.sleep(rule.jittered_delay())
        elif rule.kind == "drop":
            raise InjectedDrop(f"injected fault: connection dropped at {site}")
        elif rule.kind == "refuse":
            raise InjectedRefusal(f"injected fault: connection refused at {site}")
        elif rule.kind == "crash":
            cb = self._crash_cb
            if cb is not None:
                cb()
                raise InjectedDrop(f"injected fault: daemon crashed at {site}")
            os._exit(17)  # a real process death, as a real crash would be

    def hit(self, site: str) -> None:
        for rule in self._rules.get(site, ()):
            if rule.kind != "partial" and rule.fires():
                # Notify BEFORE performing: a crash-kind rule may end
                # the process inside _perform, and the flight recorder
                # (the main subscriber) wants its bundle on disk first.
                _notify(site, rule.kind)
                self._perform(rule, site)

    def cut(self, site: str, n: int) -> Optional[int]:
        for rule in self._rules.get(site, ()):
            if rule.kind == "partial" and rule.fires():
                with rule.lock:
                    return rule.rng.randrange(0, max(n, 1))
        return None


# -- process-wide activation -------------------------------------------------

#: The active plan. None = every hook is a no-op (the zero-overhead
#: production state). Set via activate()/active()/SRML_FAULT_PLAN.
_PLAN: Optional[FaultPlan] = None

#: Fired-fault subscribers: ``cb(site, kind)`` called when a rule FIRES
#: (not on every checkpoint pass), before the fault is performed. The
#: flight recorder (utils/flight.py) subscribes so an injected fault
#: auto-captures an incident bundle. Subscriber errors are swallowed —
#: observability must never change what the fault does.
_SUBSCRIBERS: list = []


def subscribe(cb) -> None:
    """Register a fired-fault callback ``cb(site, kind)``."""
    if cb not in _SUBSCRIBERS:
        _SUBSCRIBERS.append(cb)


def unsubscribe(cb) -> None:
    try:
        _SUBSCRIBERS.remove(cb)
    except ValueError:
        pass


def _notify(site: str, kind: str) -> None:
    for cb in list(_SUBSCRIBERS):
        try:
            cb(site, kind)
        except Exception:
            pass


def activate(plan: FaultPlan) -> FaultPlan:
    global _PLAN
    _PLAN = plan
    return plan


def deactivate() -> None:
    global _PLAN
    _PLAN = None


def active_plan() -> Optional[FaultPlan]:
    return _PLAN


class active:
    """``with faults.active(plan): ...`` — scoped activation for tests."""

    def __init__(self, plan: FaultPlan):
        self._plan = plan
        self._prev: Optional[FaultPlan] = None

    def __enter__(self) -> FaultPlan:
        global _PLAN
        self._prev, _PLAN = _PLAN, self._plan
        return self._plan

    def __exit__(self, *exc) -> None:
        global _PLAN
        _PLAN = self._prev


def checkpoint(site: str) -> None:
    """Fault hook: no-op unless a plan is active and has a rule here.

    May sleep (latency), raise :class:`InjectedDrop` /
    :class:`InjectedRefusal`, or crash the process — exactly the failure
    modes the healing paths must absorb.
    """
    plan = _PLAN
    if plan is None:
        return
    plan.hit(site)


def truncation(site: str, n: int) -> Optional[int]:
    """Partial-frame hook for the wire layer: None (fast path) or the
    number of payload bytes to actually send before dropping the
    connection."""
    plan = _PLAN
    if plan is None:
        return None
    return plan.cut(site, n)


# Env activation: one parse at import. The data plane is long-lived
# (daemon processes, executor workers); a spec in the environment at
# process start is the deployment-shaped way to chaos-test a real
# multi-process topology (tests/daemon_worker.py inherits it).
_spec = os.environ.get("SRML_FAULT_PLAN")
if _spec:
    activate(FaultPlan.from_spec(_spec))
del _spec
