"""Fused second-moment statistics: count / column-sum / Gram matrix.

This is the framework's hot loop, replacing the reference's per-partition
``dgemmCov`` cuBLAS AᵀA (rapidsml_jni.cu:109-127) *and* fixing its known gap:
mean-centering in the reference is a stubbed TODO pushed to upstream ETL
(RapidsRowMatrix.scala:111-117; SURVEY.md §2.4). Here every pass computes the
row count, the column sums, and the Gram matrix in one fused kernel, so a
centered Gram is available for free via G_c = G − n·μμᵀ — one extra rank-1
update instead of a second data pass.

Sharding: rows over the ``data`` mesh axis; partials combine with
``jax.lax.psum`` over ICI — the device-plane reduction the reference's JVM
``RDD.reduce`` (RapidsRowMatrix.scala:139) approximates, and the device-side
combiner its never-implemented ``accumulateCov`` intended (SURVEY.md §2.4).
A 2-D variant shards features over ``model`` as well, lifting the reference's
one-device covariance assumption (RapidsRowMatrix.scala:74-86).

Padded rows are masked out, so stats are exact for any row count.
"""

from __future__ import annotations

import contextlib
import functools
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from spark_rapids_ml_tpu import config
from spark_rapids_ml_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS
from spark_rapids_ml_tpu.parallel.compat import shard_map
from spark_rapids_ml_tpu.parallel import mapreduce as mr
from spark_rapids_ml_tpu.utils.xprof import ledgered_jit

Stats = Tuple[jax.Array, jax.Array, jax.Array]  # (count, colsum, gram)

#: Per-device byte budget for a RESIDENT (d, d) Gram accumulator — the
#: ops/pallas_kernels.GRAM_COLSUM_VMEM_BUDGET idea generalized from one
#: kernel's VMEM tile to the fit path's device footprint: the accumulator
#: lives on device for the whole fit (donated streaming state, fused fit
#: program) alongside the row batches, so a width that blows this budget
#: must be SHARDED over the ``model`` axis, not attempted and OOMed.
#: Override via SRML_GRAM_DEVICE_BUDGET_MB (0 = unlimited).
GRAM_DEVICE_BUDGET_BYTES = (
    int(os.environ.get("SRML_GRAM_DEVICE_BUDGET_MB", 256)) << 20
)


class GramCapacityError(ValueError):
    """A (d, d) accumulator does not fit the per-device budget on this
    mesh — raised at fit entry instead of an opaque device OOM mid-pass."""


def require_gram_capacity(n_cols: int, mesh: Mesh, accum_dtype=None) -> bool:
    """Check the (d, d) accumulator against the per-device budget.

    Returns True when the fit MUST keep the Gram model-sharded end to end
    (the full matrix busts the budget but the per-device (d/n_model, d)
    slab fits — the docs/mesh.md model-parallel path); False when a
    replicated accumulator is fine. Raises :class:`GramCapacityError`
    when even the sharded slab is too big (grow ``mesh_model_axis``)."""
    _, ad = _dtypes()
    ad = jnp.dtype(accum_dtype) if accum_dtype is not None else ad
    if not GRAM_DEVICE_BUDGET_BYTES:
        return False
    n_model = mesh.shape.get(MODEL_AXIS, 1)
    full = n_cols * n_cols * ad.itemsize
    if full <= GRAM_DEVICE_BUDGET_BYTES:
        return False
    slab = -(-n_cols // n_model) * n_cols * ad.itemsize
    if slab > GRAM_DEVICE_BUDGET_BYTES:
        need = -(-full // GRAM_DEVICE_BUDGET_BYTES)
        raise GramCapacityError(
            f"the ({n_cols}, {n_cols}) {ad.name} Gram accumulator is "
            f"{full >> 20} MiB — over the {GRAM_DEVICE_BUDGET_BYTES >> 20} "
            f"MiB per-device budget even sharded {n_model}-way over the "
            f"'model' axis ({slab >> 20} MiB/device). Use a mesh with "
            f"mesh_model_axis >= {need} (docs/mesh.md 'Model-parallel "
            "Gram/eigh'), or raise SRML_GRAM_DEVICE_BUDGET_MB."
        )
    return True


def mm_precision(*dtypes):
    """Trace-time context: full-precision matmuls when any operand dtype is
    float32/float64.

    TPU's DEFAULT dot precision computes f32 contractions with single-pass
    bf16 mantissas, silently giving "float32 compute" only bf16 accuracy —
    for PCA that surfaces as eigenvector error ~ rounding/eigengap, percent
    level on close spectra. bfloat16 compute paths are unaffected by this
    context (there is no decomposition to control), so it costs nothing on
    the speed-oriented paths.
    """
    if any(
        d is not None and jnp.dtype(d) in (jnp.dtype(jnp.float32), jnp.dtype(jnp.float64))
        for d in dtypes
    ):
        return jax.default_matmul_precision("float32")
    return contextlib.nullcontext()


def _dtypes():
    return jnp.dtype(config.get("compute_dtype")), jnp.dtype(config.get("accum_dtype"))


def _pallas_backend_ok(use_pallas: Optional[bool] = None) -> bool:
    """Shared Pallas-gate preamble: flag on (None = read config) + TPU backend."""
    if not (config.get("use_pallas") if use_pallas is None else use_pallas):
        return False
    try:
        return jax.default_backend() != "cpu"
    except RuntimeError:  # pragma: no cover
        return False


def _pallas_gram_applicable(shape, cd, ad, use_pallas: Optional[bool] = None) -> bool:
    """Pallas Gram path: TPU backend, f32 in/accum, tile-divisible shapes."""
    if not _pallas_backend_ok(use_pallas):
        return False
    n, d = shape
    return (
        jnp.dtype(cd) == jnp.float32
        and jnp.dtype(ad) == jnp.float32
        and n % 512 == 0
        and d % 256 == 0
    )


def local_stats(
    x: jax.Array,
    mask: Optional[jax.Array] = None,
    compute_dtype=None,
    accum_dtype=None,
    use_pallas: Optional[bool] = None,
) -> Stats:
    """Single-block fused stats. x: (m, d); mask: (m,) of {0,1} or None.

    The GEMM runs in ``compute_dtype`` (bfloat16 engages the MXU at full
    rate) and accumulates in ``accum_dtype`` via ``preferred_element_type``.
    With ``config.use_pallas`` on a TPU backend and tile-divisible shapes,
    the Gram uses the hand-tiled Pallas kernel (mask fused into the load).
    """
    cd, ad = _dtypes()
    cd = jnp.dtype(compute_dtype) if compute_dtype is not None else cd
    ad = jnp.dtype(accum_dtype) if accum_dtype is not None else ad
    xc = x.astype(cd)
    if mask is not None:
        xm = xc * mask.astype(cd)[:, None]
        # Integer sum: an f32 sum of ones saturates at 2^24 rows.
        count = jnp.sum(mask.astype(jnp.int32)).astype(ad)
    else:
        xm = xc
        count = jnp.asarray(x.shape[0], dtype=ad)
    colsum = jnp.sum(xm.astype(ad), axis=0)
    if mask is not None and _pallas_gram_applicable(x.shape, cd, ad, use_pallas):
        from spark_rapids_ml_tpu.ops.pallas_kernels import gram_pallas

        gram = gram_pallas(xc, mask.astype(cd))
    else:
        with mm_precision(cd):
            gram = jax.lax.dot_general(
                xm,
                xm,
                (((0,), (0,)), ((), ())),  # contract over rows: xᵀx
                preferred_element_type=ad,
            )
    return count, colsum, gram


def _stats_shard(x, mask, compute_dtype, accum_dtype, use_pallas=None):
    count, colsum, gram = local_stats(
        x,
        mask,
        compute_dtype=compute_dtype,
        accum_dtype=accum_dtype,
        use_pallas=use_pallas,
    )
    count = mr.reduce_sum(count, DATA_AXIS)
    colsum = mr.reduce_sum(colsum, DATA_AXIS)
    gram = mr.reduce_sum(gram, DATA_AXIS)
    return count, colsum, gram


def sharded_stats(mesh: Mesh, compute_dtype=None, accum_dtype=None):
    """Build a jitted fn(x_rowsharded, mask) -> replicated (count, colsum, gram).

    One compiled SPMD program: per-shard fused stats + psum over ``data``.
    """
    f = shard_map(
        functools.partial(
            _stats_shard, compute_dtype=compute_dtype, accum_dtype=accum_dtype
        ),
        mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS)),
        out_specs=(P(), P(), P()),
    )
    return ledgered_jit("gram.sharded_stats", f)


def _stats_shard_2d(x, mask, compute_dtype, accum_dtype):
    """2-D sharded stats: x block is (rows/data, d/model).

    all_gather the feature blocks along ``model`` so each device computes its
    (d/model, d) horizontal slab of the Gram; psum slabs over ``data``. The
    result stays feature-sharded — the full n×n never materializes on one
    device (the upgrade over RapidsRowMatrix.scala:74-86).
    """
    cd, ad = _dtypes()
    cd = jnp.dtype(compute_dtype) if compute_dtype is not None else cd
    ad = jnp.dtype(accum_dtype) if accum_dtype is not None else ad
    xc = x.astype(cd) * mask.astype(cd)[:, None]
    # (m_local, d_full) — ICI all-gather of feature blocks.
    x_full = mr.all_concat(xc, MODEL_AXIS, axis=1)
    count = mr.reduce_sum(jnp.sum(mask.astype(jnp.int32)).astype(ad), DATA_AXIS)
    colsum = mr.reduce_sum(jnp.sum(x_full.astype(ad), axis=0), DATA_AXIS)
    with mm_precision(cd):
        slab = jax.lax.dot_general(
            xc, x_full, (((0,), (0,)), ((), ())), preferred_element_type=ad
        )
    gram_slab = mr.reduce_sum(slab, DATA_AXIS)
    return count, colsum, gram_slab


def sharded_stats_2d(mesh: Mesh, compute_dtype=None, accum_dtype=None):
    """fn(x_2dsharded, mask) -> (count repl, colsum repl, gram model-sharded)."""
    f = shard_map(
        functools.partial(
            _stats_shard_2d, compute_dtype=compute_dtype, accum_dtype=accum_dtype
        ),
        mesh=mesh,
        in_specs=(P(DATA_AXIS, MODEL_AXIS), P(DATA_AXIS)),
        out_specs=(P(), P(), P(MODEL_AXIS, None)),
        # count/colsum are value-replicated over `model` after the
        # all_gather, which VMA inference can't prove statically.
        check_vma=False,
    )
    return ledgered_jit("gram.sharded_stats_2d", f)


def _stats_shard_ring(x, mask, compute_dtype, accum_dtype, n_model):
    """Ring-collective 2-D sharded stats (the ring-attention pattern applied
    to the Gram): instead of all_gather-ing the full feature width onto
    every device (peak memory m_local×d, _stats_shard_2d), feature blocks
    rotate around the ``model``-axis ring via ``lax.ppermute``. Each step
    computes one (d_local, d_local) off-diagonal Gram block while the next
    block is in flight on ICI; peak extra memory is one block, and total
    comm equals the all_gather but pipelined. This is the long-feature
    analogue of sequence parallelism (SURVEY.md §5 "long-context": the
    reference has no such axis; here it is first-class).
    """
    cd, ad = _dtypes()
    cd = jnp.dtype(compute_dtype) if compute_dtype is not None else cd
    ad = jnp.dtype(accum_dtype) if accum_dtype is not None else ad
    xc = x.astype(cd) * mask.astype(cd)[:, None]
    d_local = x.shape[1]
    count = mr.reduce_sum(jnp.sum(mask.astype(jnp.int32)).astype(ad), DATA_AXIS)
    my_colsum = jnp.sum(xc.astype(ad), axis=0)  # (d_local,)
    colsum = mr.all_concat(my_colsum, MODEL_AXIS, axis=0)  # (d,) tiny
    colsum = mr.reduce_sum(colsum, DATA_AXIS)
    idx = jax.lax.axis_index(MODEL_AXIS)
    perm = [(i, (i + 1) % n_model) for i in range(n_model)]

    def block_at(s, slab, held):
        with mm_precision(cd):
            block = jax.lax.dot_general(
                xc, held, (((0,), (0,)), ((), ())), preferred_element_type=ad
            )  # (d_local, d_local): G[my_block, held_block]
        col = (((idx - s) % n_model) * d_local).astype(jnp.int32)
        return jax.lax.dynamic_update_slice(slab, block, (jnp.int32(0), col))

    def body(s, carry):
        held, slab = carry
        slab = block_at(s, slab, held)
        held = mr.ring_shift(held, MODEL_AXIS, perm)
        return held, slab

    slab0 = jnp.zeros((d_local, n_model * d_local), dtype=ad)
    # n_model-1 (compute + permute) steps, then the final block without the
    # last permute — its result would be discarded, and the block is the
    # big (m_local, d_local) buffer this path exists to avoid moving.
    held, slab = jax.lax.fori_loop(0, n_model - 1, body, (xc, slab0))
    slab = block_at(n_model - 1, slab, held)
    gram_slab = mr.reduce_sum(slab, DATA_AXIS)
    return count, colsum, gram_slab


def sharded_stats_ring(mesh: Mesh, compute_dtype=None, accum_dtype=None):
    """fn(x_2dsharded, mask) -> (count repl, colsum repl, gram model-sharded),
    computed with the ppermute ring instead of all_gather."""
    n_model = mesh.shape[MODEL_AXIS]
    f = shard_map(
        functools.partial(
            _stats_shard_ring,
            compute_dtype=compute_dtype,
            accum_dtype=accum_dtype,
            n_model=n_model,
        ),
        mesh=mesh,
        in_specs=(P(DATA_AXIS, MODEL_AXIS), P(DATA_AXIS)),
        out_specs=(P(), P(), P(MODEL_AXIS, None)),
        check_vma=False,
    )
    return ledgered_jit("gram.sharded_stats_ring", f)


def streaming_update(mesh: Mesh, compute_dtype=None, accum_dtype=None):
    """Jitted (state, x_batch, mask) -> state for out-of-HBM datasets.

    State (count, colsum, gram) lives replicated on device; host streams
    row-sharded batches in. Donation makes the accumulate in-place. This is
    the path for BASELINE.json config #2 (100M×2048 ≫ HBM).
    """
    dcd, dad = _dtypes()
    cd = jnp.dtype(compute_dtype) if compute_dtype is not None else dcd
    ad = jnp.dtype(accum_dtype) if accum_dtype is not None else dad
    # use_pallas is read by local_stats at trace time, so it must be part of
    # the cache key (same reason as _fit_fn's).
    return _streaming_update_cached(mesh, cd.name, ad.name, bool(config.get("use_pallas")))


@functools.lru_cache(maxsize=32)
def _streaming_update_cached(mesh: Mesh, compute_dtype, accum_dtype, use_pallas: bool):
    # Cached per (mesh, dtypes, pallas flag): returning a fresh jitted
    # closure per call would force a full XLA recompile for every job in a
    # long-lived daemon (jit caches are keyed on the function object). The
    # snapshot is threaded to the trace-time gate so a config flip between
    # builder call and first trace can't cache the wrong executable.

    def shard_update(count, colsum, gram, x, mask):
        c, s, g = _stats_shard(x, mask, compute_dtype, accum_dtype, use_pallas)
        return count + c, colsum + s, gram + g

    f = shard_map(
        shard_update,
        mesh=mesh,
        in_specs=(P(), P(), P(), P(DATA_AXIS, None), P(DATA_AXIS)),
        out_specs=(P(), P(), P()),
    )

    @functools.partial(ledgered_jit, "gram.streaming_update", donate_argnums=(0,))
    def update(state, x, mask):
        return f(state[0], state[1], state[2], x, mask)

    return update


def _pallas_rows_applicable(shape, cd, use_pallas: Optional[bool] = None) -> bool:
    """gram_colsum_pallas gate: TPU backend, lane-aligned d, block-divisible
    rows, and a (d, d) f32 accumulator that fits the kernel's VMEM budget
    (constants imported from the kernel so the two can't drift)."""
    if not _pallas_backend_ok(use_pallas):
        return False
    from spark_rapids_ml_tpu.ops.pallas_kernels import (
        GRAM_COLSUM_BLOCK_N,
        GRAM_COLSUM_VMEM_BUDGET,
    )

    m, d = shape
    return (
        jnp.dtype(cd) in (jnp.dtype(jnp.bfloat16), jnp.dtype(jnp.float32))
        and d % 128 == 0
        and m % GRAM_COLSUM_BLOCK_N == 0
        and d * d * 4 <= GRAM_COLSUM_VMEM_BUDGET
    )


def streaming_update_rows(mesh: Mesh, compute_dtype=None, accum_dtype=None):
    """Jitted (state, x_batch, n_valid) -> state — the fast streaming path.

    Like :func:`streaming_update` but the padding mask is a single scalar:
    rows ≥ ``n_valid`` (a *global* row count over the whole batch, rows laid
    out contiguously across the ``data`` axis) are ignored. x arrives already
    in the compute dtype — the ingest stage casts once at host→device
    placement (halving transfer bytes for bfloat16) so the hot loop never
    touches float32 row data. On TPU with ``use_pallas`` the per-shard stats
    use the single-HBM-pass fused kernel
    (:func:`~spark_rapids_ml_tpu.ops.pallas_kernels.gram_colsum_pallas`),
    which emits count/colsum/gram together; on a single-data-device mesh
    with float32 accumulation the donated streaming state is additionally
    SEEDED into the kernel's VMEM accumulators, so the whole per-batch
    ``state += batch_stats`` is one Pallas dispatch — the separate XLA add
    that round-tripped the (d, d) state through HBM per batch is gone.
    Elsewhere an iota-derived mask reuses the XLA path.
    """
    dcd, dad = _dtypes()
    cd = jnp.dtype(compute_dtype) if compute_dtype is not None else dcd
    ad = jnp.dtype(accum_dtype) if accum_dtype is not None else dad
    return _streaming_update_rows_cached(
        mesh, cd.name, ad.name, bool(config.get("use_pallas"))
    )


@functools.lru_cache(maxsize=32)
def _streaming_update_rows_cached(
    mesh: Mesh, compute_dtype, accum_dtype, use_pallas: bool
):
    # use_pallas is the snapshot taken when the builder was called — the gate
    # must use it (not re-read config at trace time) or a config flip between
    # builder call and first trace would cache the wrong executable forever.
    cd = jnp.dtype(compute_dtype)
    ad = jnp.dtype(accum_dtype)
    # The seeded one-dispatch path folds the donated state INSIDE the
    # kernel, which is only correct when no cross-shard psum sits between
    # the partial and the state add — i.e. a single data device — and when
    # the state dtype is the kernel's f32 accumulator dtype.
    n_data = mesh.shape[DATA_AXIS]

    def shard_update(count, colsum, gram, x, n_valid):
        m = x.shape[0]
        offset = jax.lax.axis_index(DATA_AXIS).astype(jnp.int32) * m
        nv_local = jnp.clip(n_valid.astype(jnp.int32) - offset, 0, m)
        xc = x.astype(cd)
        if _pallas_rows_applicable(x.shape, cd, use_pallas):
            from spark_rapids_ml_tpu.ops.pallas_kernels import gram_colsum_pallas

            if n_data == 1 and ad == jnp.dtype(jnp.float32):
                g, cs, c = gram_colsum_pallas(
                    xc, nv_local, state=(gram, colsum, count)
                )
                return c, cs, g
            g, cs, _ = gram_colsum_pallas(xc, nv_local)
            g = g.astype(ad)
            cs = cs.astype(ad)
        else:
            rows = jax.lax.broadcasted_iota(jnp.int32, (m,), 0)
            mask = (rows < nv_local).astype(cd)
            _, cs, g = local_stats(
                xc,
                mask,
                compute_dtype=compute_dtype,
                accum_dtype=accum_dtype,
                use_pallas=use_pallas,
            )
        c = mr.reduce_sum(nv_local.astype(ad), DATA_AXIS)
        cs = mr.reduce_sum(cs, DATA_AXIS)
        g = mr.reduce_sum(g, DATA_AXIS)
        return count + c, colsum + cs, gram + g

    f = shard_map(
        shard_update,
        mesh=mesh,
        in_specs=(P(), P(), P(), P(DATA_AXIS, None), P()),
        out_specs=(P(), P(), P()),
        # pallas_call outputs carry no VMA annotation; the post-psum values
        # are replicated, which VMA inference can't prove (same as the 2-D
        # variant above).
        check_vma=False,
    )

    @functools.partial(
        ledgered_jit, "gram.streaming_update_rows", donate_argnums=(0,)
    )
    def update(state, x, n_valid):
        return f(state[0], state[1], state[2], x, jnp.asarray(n_valid, jnp.int32))

    return update


def init_stats(n_cols: int, accum_dtype=None) -> Stats:
    _, ad = _dtypes()
    ad = jnp.dtype(accum_dtype) if accum_dtype is not None else ad
    return (
        jnp.zeros((), dtype=ad),
        jnp.zeros((n_cols,), dtype=ad),
        jnp.zeros((n_cols, n_cols), dtype=ad),
    )


def finalize_gram(
    count: jax.Array,
    colsum: jax.Array,
    gram: jax.Array,
    mean_center: bool,
) -> Tuple[jax.Array, jax.Array]:
    """(count, colsum, gram) -> (G, mean).

    ``mean_center=True``: G = Σxxᵀ − n·μμᵀ, the Gram of centered data — the
    real fused fix for the reference's ETL-preprocess stub (SURVEY.md §2.4).
    ``False``: raw Gram, byte-for-byte the reference's ``cov.reduce(_+_)``
    semantics (RapidsRowMatrix.scala:139 — no centering, no normalization).
    """
    n = jnp.maximum(count, 1)
    mean = colsum / n
    if mean_center:
        g = gram - jnp.outer(mean, colsum)
    else:
        g = gram
    return g, mean
