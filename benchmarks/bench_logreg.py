"""LogisticRegression Newton-step throughput — BASELINE.json config #4
(normal-equations-class Gram psum, IRLS flavor).

Times the binomial Newton fit (`_newton_fn`: per-iteration predict +
weighted Gram Hessian + psum + d×d solve) for a fixed iteration count on
device-resident data, reporting row-iterations/s/chip.

Baseline: each Newton iteration is Hessian-Gram-bound at ~2·d² flops/row;
A100 at ~110 TFLOP/s → 110e12/(2·1024²) ≈ 52.5e6 row-iters/s.
vs_baseline >= 0.5 matches the north-star "within 2×".
"""

import os
import sys

if __package__ in (None, ""):  # direct script run: python benchmarks/bench_*.py
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

D = int(os.environ.get("SRML_BENCH_D", 1024))
ROWS = int(os.environ.get("SRML_BENCH_BATCH_ROWS", 1 << 19))
ITERS = int(os.environ.get("SRML_BENCH_ITERS", 8))

A100_ROW_ITERS_PER_SEC = 110e12 / (2 * D * D)


def main() -> None:
    from benchmarks import setup_platform

    setup_platform()
    import jax
    import jax.numpy as jnp

    from benchmarks import emit
    from spark_rapids_ml_tpu import config
    from spark_rapids_ml_tpu.models.logistic_regression import _newton_fn
    from spark_rapids_ml_tpu.parallel.mesh import make_mesh

    config.set("compute_dtype", "bfloat16")
    config.set("accum_dtype", "float32")
    config.set("use_pallas", True)  # fused single-HBM-pass Newton step

    n_chips = len(jax.devices())
    mesh = make_mesh(model=1)
    key = jax.random.key(0)
    x = jax.random.normal(key, (ROWS, D), dtype=jnp.float32)
    w_true = jax.random.normal(jax.random.key(1), (D,), dtype=jnp.float32) / np.sqrt(D)
    y = (jax.nn.sigmoid(x @ w_true) > 0.5).astype(jnp.float64)
    if n_chips > 1:
        from jax.sharding import NamedSharding, PartitionSpec as P

        x = jax.device_put(x, NamedSharding(mesh, P("data", None)))
        y = jax.device_put(y, NamedSharding(mesh, P("data")))
    mask = jnp.ones((ROWS,), dtype=jnp.float32)

    # tol=0 → exactly n Newton steps: throughput, not convergence. Two
    # iteration counts + slope_dt cancel the fixed sync overhead.
    from benchmarks import slope_dt, sync

    fns = {
        n: _newton_fn(mesh, 1e-4, True, n, 0.0, "float32")
        for n in (ITERS, 2 * ITERS)
    }

    def run(n):
        w, b, n_iter, loss = fns[n](x, y, mask)
        sync(w)
        assert int(n_iter) == n and np.isfinite(float(loss))
        return w

    dt_per_iter = slope_dt(run, ITERS, 2 * ITERS)

    # -- multinomial MM-Newton pass (streamed-protocol kernel) -------------
    # Per pass: gradient GEMM + C per-class weighted Grams ≈ 2·C·n·d²
    # flops; the same A100 sustained-GEMM convention gives the baseline.
    from spark_rapids_ml_tpu.models.logistic_regression import (
        _stream_multinomial_step_fn,
        _stream_softmax_stats_fn,
        stream_softmax_zero_state,
    )

    C = int(os.environ.get("SRML_BENCH_CLASSES", 32))
    rows_mm = int(os.environ.get("SRML_BENCH_MM_ROWS", ROWS // 4))
    x_mm = jax.random.normal(jax.random.key(2), (rows_mm, D), dtype=jnp.float32)
    y_mm = jax.random.randint(jax.random.key(3), (rows_mm,), 0, C).astype(
        jnp.float32
    )
    mask_mm = jnp.ones((rows_mm,), jnp.float32)
    if n_chips > 1:
        from jax.sharding import NamedSharding, PartitionSpec as P

        x_mm = jax.device_put(x_mm, NamedSharding(mesh, P("data", None)))
        y_mm = jax.device_put(y_mm, NamedSharding(mesh, P("data")))
        mask_mm = jax.device_put(mask_mm, NamedSharding(mesh, P("data")))
    mm_step = _stream_multinomial_step_fn(1e-4, True, "float32")

    def mm_timer(update):
        def run_mm(n):
            W = jnp.zeros((D, C), jnp.float32)
            b = jnp.zeros((C,), jnp.float32)
            for _ in range(n):
                state = stream_softmax_zero_state(D, C, jnp.float32)
                gw, gb, hw, hwb, hbb, _, nn = update(
                    state, W, b, x_mm, y_mm, mask_mm
                )
                W, b, _ = mm_step(gw, gb, hw, hwb, hbb, nn, W, b)
            sync(W)
            return W

        mm_iters = max(2, ITERS // 2)
        return slope_dt(run_mm, mm_iters, 2 * mm_iters)

    # Same-run A/B: the shared-tile Pallas curvature kernel (use_pallas
    # snapshot True — the shipped TPU profile) vs the XLA per-class loop.
    from spark_rapids_ml_tpu.models.logistic_regression import (
        _stream_softmax_stats_cached,
    )

    dt_mm = mm_timer(_stream_softmax_stats_fn(mesh, C, "float32"))
    dt_mm_xla = mm_timer(
        _stream_softmax_stats_cached(mesh, C, "float32", "bfloat16", False)
    )
    a100_mm = 110e12 / (2 * C * D * D)
    emit(
        f"logreg_newton_row_iters_per_sec_per_chip_d{D}",
        ROWS / dt_per_iter / n_chips,
        "row_iters/s/chip",
        (ROWS / dt_per_iter / n_chips) / A100_ROW_ITERS_PER_SEC,
    )
    # Its own line (VERDICT r3 #8): the multinomial MM-Newton pass is a
    # peer workload, not a footnote on the binary number.
    emit(
        f"logreg_multinomial_row_iters_per_sec_per_chip_d{D}_C{C}",
        rows_mm / dt_mm / n_chips,
        "row_iters/s/chip",
        (rows_mm / dt_mm / n_chips) / a100_mm,
        classes=C,
        ab_xla_row_iters_per_sec=round(rows_mm / dt_mm_xla / n_chips, 1),
        kernel_speedup=round(dt_mm_xla / dt_mm, 4),
    )


if __name__ == "__main__":
    main()
