"""srml-check engine tests (spark_rapids_ml_tpu/tools/analyze.py).

Three layers, mirroring the analyzer's contract (docs/static_analysis.md):

1. Per-rule FIXTURES — for every rule, a positive snippet that must flag
   and a negative twin that must not. The fixtures are tiny synthetic
   projects (dict of relpath → source), so each rule's semantic model
   (lock stacks, jit-handle resolution, constant folding) is pinned
   independently of the real tree.
2. SUPPRESSION — inline ``# srml: disable=`` pragmas, the baseline
   round-trip (finding → baselined → code removed → stale-entry warning),
   and the seeded-violation gate: a deliberate device dispatch outside
   ``_DEVICE_LOCK`` spliced into a scratch copy of daemon.py must be
   caught.
3. The WHOLE-PACKAGE run — the tier-1 gate: zero unsuppressed findings
   over the real tree, plus the ``--json`` CLI contract.

No jax import anywhere in this file: the analyzer is stdlib-only and
must stay runnable before the environment can even build a device.
"""

import json
import subprocess
import sys
from pathlib import Path

import pytest

from spark_rapids_ml_tpu.tools import analyze
from spark_rapids_ml_tpu.tools.analyze import Baseline, Project

REPO = Path(__file__).resolve().parent.parent

#: Minimal ops module defining a donating streaming factory — gives the
#: fixtures a realistic jit registry (the daemon fixtures bind from it).
GRAM_FIXTURE = '''
import functools
from spark_rapids_ml_tpu.utils.xprof import ledgered_jit

def streaming_update(mesh):
    @functools.partial(ledgered_jit, "gram.streaming_update", donate_argnums=(0,))
    def update(state, x, mask):
        return state
    return update
'''


def run_rules(files, *rules, **kw):
    project = Project(files=dict(files), **kw)
    return project, project.run_raw(rules=list(rules))


_PKG_PROJECT = []


def pkg_project() -> Project:
    """One parsed real-tree Project shared by the whole-package tests —
    runs are stateless (matched counts and notes reset per run), so the
    read+parse+registry cost is paid once per session."""
    if not _PKG_PROJECT:
        _PKG_PROJECT.append(Project.from_package())
    return _PKG_PROJECT[0]


def rule_ids(findings):
    return [f.rule for f in findings]


# ---------------------------------------------------------------------------
# family 1: lock discipline
# ---------------------------------------------------------------------------


def _daemon(src: str) -> dict:
    return {"ops/gram.py": GRAM_FIXTURE, "serve/daemon.py": src}


def test_device_lock_flags_dispatch_outside_lock():
    _, found = run_rules(_daemon('''
import threading
from spark_rapids_ml_tpu.ops.gram import streaming_update
_DEVICE_LOCK = threading.Lock()

class Job:
    def __init__(self, mesh):
        self.update = streaming_update(mesh)
    def fold(self, state, xs, ms):
        state = self.update(state, xs, ms)
        return state
'''), "device-lock")
    assert rule_ids(found) == ["device-lock"]
    assert "self.update" in found[0].message


def test_device_lock_passes_dispatch_under_lock():
    _, found = run_rules(_daemon('''
import threading
from spark_rapids_ml_tpu.ops.gram import streaming_update
_DEVICE_LOCK = threading.Lock()

class Job:
    def __init__(self, mesh):
        self.update = streaming_update(mesh)
    def fold(self, state, xs, ms):
        with _DEVICE_LOCK:
            state = self.update(state, xs, ms)
        return state
'''), "device-lock")
    assert found == []


def test_device_lock_flags_block_until_ready_and_fn_handles():
    _, found = run_rules(_daemon('''
import jax

def wait(out):
    return jax.block_until_ready(out)

def serve(q, _exact_knn_fn):
    return _exact_knn_fn(q)
'''), "device-lock")
    assert rule_ids(found) == ["device-lock", "device-lock"]


def test_device_lock_locked_helper_convention():
    # Inside a *_locked helper the caller holds the lock — exempt; but a
    # CALL site of a *_locked helper carries the obligation: a helper
    # that DISPATCHES needs _DEVICE_LOCK there specifically (a model
    # lock alone must not smuggle a dispatch past the gate), and any
    # *_locked helper needs at least some lock.
    src = '''
import threading
import jax
_DEVICE_LOCK = threading.Lock()

class Job:
    lock = threading.Lock()
    def _finalize_locked(self):
        return jax.device_get(self.state)
    def _prune_locked(self):
        self.stale = None
    def finalize(self):
        with self.lock:
            with _DEVICE_LOCK:
                return self._finalize_locked()
    def model_lock_only(self):
        with self.lock:
            return self._finalize_locked()
    def broken(self):
        return self._finalize_locked()
    def prune(self):
        with self.lock:
            self._prune_locked()
'''
    _, found = run_rules(_daemon(src), "device-lock")
    assert [(f.symbol, "without _DEVICE_LOCK" in f.message) for f in found] == [
        ("Job.model_lock_only", True),
        ("Job.broken", True),
    ]


def test_device_lock_allows_locked_to_locked_delegation():
    # A *_locked helper delegating to another *_locked helper is the
    # convention working as designed: the OUTER caller holds the lock.
    _, found = run_rules(_daemon('''
class Job:
    def _cleanup_locked(self):
        pass
    def _finalize_locked(self):
        return self._cleanup_locked()
'''), "device-lock")
    assert found == []


def test_compile_outside_lock_twins():
    bad = _daemon('''
import threading
_DEVICE_LOCK = threading.Lock()

def warm(jit_obj, args):
    with _DEVICE_LOCK:
        jit_obj.aot_prime(*args)
''')
    good = _daemon('''
import threading
_DEVICE_LOCK = threading.Lock()

def warm(jit_obj, args):
    jit_obj.aot_prime(*args)
''')
    _, found = run_rules(bad, "compile-outside-lock")
    assert rule_ids(found) == ["compile-outside-lock"]
    _, found = run_rules(good, "compile-outside-lock")
    assert found == []


def test_lock_order_flags_acquisition_under_device_lock():
    _, found = run_rules(_daemon('''
import threading
_DEVICE_LOCK = threading.Lock()

class D:
    _models_lock = threading.Lock()
    def bad(self):
        with _DEVICE_LOCK:
            with self._models_lock:
                pass
    def good(self):
        with self._models_lock:
            with _DEVICE_LOCK:
                pass
'''), "lock-order")
    assert len(found) == 1
    assert found[0].symbol == "D.bad"


def test_lock_order_is_lexical_only():
    # The general A→B/B→A inversion moved to lock-graph-cycle (where it
    # is a graph cycle); lock-order keeps only the _DEVICE_LOCK-innermost
    # lexical contract.
    _, found = run_rules({"serve/fleet.py": '''
import threading

class F:
    _a_lock = threading.Lock()
    _b_lock = threading.Lock()
    def one(self):
        with self._a_lock:
            with self._b_lock:
                pass
    def two(self):
        with self._b_lock:
            with self._a_lock:
                pass
'''}, "lock-order")
    assert found == []


def test_lock_order_sees_multi_item_with():
    # `with A, B:` acquires B while holding A — the single-statement
    # spelling must flag exactly like the nested one.
    _, found = run_rules(_daemon('''
import threading
_DEVICE_LOCK = threading.Lock()

class D:
    _models_lock = threading.Lock()
    def bad(self):
        with _DEVICE_LOCK, self._models_lock:
            pass
'''), "lock-order")
    assert len(found) == 1
    assert "_models_lock" in found[0].message


# ---------------------------------------------------------------------------
# family 2: use-after-donate
# ---------------------------------------------------------------------------


def test_use_after_donate_flags_read_after_donation():
    _, found = run_rules({
        "ops/gram.py": GRAM_FIXTURE,
        "models/pca.py": '''
from spark_rapids_ml_tpu.ops.gram import streaming_update

def fit(mesh, batches, state):
    update = streaming_update(mesh)
    out = update(state, batches[0], None)
    return state, out  # state was donated: this read is a use-after-free
''',
    }, "use-after-donate")
    assert rule_ids(found) == ["use-after-donate"]
    assert "state" in found[0].message


def test_use_after_donate_passes_rebinding_fold():
    _, found = run_rules({
        "ops/gram.py": GRAM_FIXTURE,
        "models/pca.py": '''
from spark_rapids_ml_tpu.ops.gram import streaming_update

def fit(mesh, batches, state):
    update = streaming_update(mesh)
    for b in batches:
        state = update(state, b, None)
    return state
''',
    }, "use-after-donate")
    assert found == []


def test_use_after_donate_flags_loop_without_rebind():
    _, found = run_rules({
        "ops/gram.py": GRAM_FIXTURE,
        "models/pca.py": '''
from spark_rapids_ml_tpu.ops.gram import streaming_update

def fit(mesh, batches, state):
    update = streaming_update(mesh)
    for b in batches:
        update(state, b, None)  # next iteration re-reads the dead buffer
''',
    }, "use-after-donate")
    assert rule_ids(found) == ["use-after-donate"]
    assert "loop" in found[0].message


def test_use_after_donate_ignores_mutually_exclusive_branch():
    # A read of the donated name in the ELSE arm of the branch holding
    # the donating call can never see the dead buffer — not a finding;
    # a read AFTER the whole if (reachable from the donating arm) is.
    files = {
        "ops/gram.py": GRAM_FIXTURE,
        "models/pca.py": '''
from spark_rapids_ml_tpu.ops.gram import streaming_update

def fit(mesh, b, state, fast):
    update = streaming_update(mesh)
    if fast:
        out = update(state, b, None)
        return out
    else:
        return state
''',
    }
    _, found = run_rules(files, "use-after-donate")
    assert found == []
    files["models/pca.py"] = '''
from spark_rapids_ml_tpu.ops.gram import streaming_update

def fit(mesh, b, state, fast):
    update = streaming_update(mesh)
    if fast:
        out = update(state, b, None)
    return state  # reachable after the donating arm: use-after-free
'''
    _, found = run_rules(files, "use-after-donate")
    assert rule_ids(found) == ["use-after-donate"]


def test_use_after_donate_tuple_unpack_rebind_heals():
    # Multi-output donated folds rebind via tuple unpack — healed.
    _, found = run_rules({
        "ops/gram.py": GRAM_FIXTURE,
        "models/pca.py": '''
from spark_rapids_ml_tpu.ops.gram import streaming_update

def fit(mesh, batches, state):
    update = streaming_update(mesh)
    n = 0
    for b in batches:
        state, n = update(state, b, None)
    return state, n
''',
    }, "use-after-donate")
    assert found == []


def test_use_after_donate_sees_finally_block():
    # try/finally: the finally body executes AFTER the donating call —
    # a read of the donated name there is a real use-after-free.
    _, found = run_rules({
        "ops/gram.py": GRAM_FIXTURE,
        "models/pca.py": '''
from spark_rapids_ml_tpu.ops.gram import streaming_update

def fit(mesh, b, state, log):
    update = streaming_update(mesh)
    try:
        out = update(state, b, None)
    finally:
        log(state.shape)
    return out
''',
    }, "use-after-donate")
    assert rule_ids(found) == ["use-after-donate"]


def test_device_lock_closure_does_not_inherit_enclosing_with():
    # A closure DEFINED under `with _DEVICE_LOCK` runs later, when the
    # lock is long released: the dispatch inside it must still flag.
    _, found = run_rules(_daemon('''
import threading
from spark_rapids_ml_tpu.ops.gram import streaming_update
_DEVICE_LOCK = threading.Lock()

class Job:
    def __init__(self, mesh):
        self.update = streaming_update(mesh)
    def defer(self, schedule, s, x, m):
        with _DEVICE_LOCK:
            def cb():
                return self.update(s, x, m)
            schedule(cb)
'''), "device-lock")
    assert rule_ids(found) == ["device-lock"]
    assert found[0].symbol == "Job.defer.cb"


# ---------------------------------------------------------------------------
# family 3: determinism
# ---------------------------------------------------------------------------


def test_unsorted_iter_twins():
    bad = {"ops/fold.py": '''
def merge(parts):
    total = 0
    for k, v in parts.items():
        total += v
    return total
'''}
    good = {"ops/fold.py": '''
def merge(parts):
    total = 0
    for k, v in sorted(parts.items()):
        total += v
    return total
'''}
    _, found = run_rules(bad, "unsorted-iter")
    assert rule_ids(found) == ["unsorted-iter"]
    _, found = run_rules(good, "unsorted-iter")
    assert found == []


def test_unsorted_iter_scope_and_precision():
    # Outside the bitwise modules (and off the daemon fold paths) the
    # rule is silent; literal-ordered local dicts and key-addressed
    # dict→dict rebuilds are deterministic by construction.
    _, found = run_rules({
        "serve/client.py": '''
def render(d):
    return [v for _, v in d.items()]
''',
        "ops/tables.py": '''
def build(arrays):
    want = {"a": 1, "b": 2}
    out = []
    for name, shape in want.items():
        out.append((name, shape))
    rekeyed = {k: float(v) for k, v in arrays.items()}
    return out, rekeyed
''',
    }, "unsorted-iter")
    assert found == []


def test_unsorted_iter_flags_set_iteration_on_fold_path():
    _, found = run_rules({"serve/daemon.py": '''
def merge_peers(peers):
    acc = []
    for p in set(peers):
        acc.append(p)
    return acc
'''}, "unsorted-iter")
    assert rule_ids(found) == ["unsorted-iter"]


def test_wallclock_entropy_twins():
    bad = {"models/kmeans.py": '''
import time
import numpy as np

def fit(x):
    t = time.time()
    noise = np.random.rand(4)
    return t, noise
'''}
    good = {"models/kmeans.py": '''
import numpy as np

def fit(x, seed):
    rng = np.random.default_rng(seed)
    return rng.random(4)
'''}
    _, found = run_rules(bad, "wallclock-entropy")
    assert sorted(rule_ids(found)) == ["wallclock-entropy", "wallclock-entropy"]
    _, found = run_rules(good, "wallclock-entropy")
    assert found == []


def test_wallclock_entropy_ignores_non_bitwise_modules():
    _, found = run_rules({"serve/client.py": '''
import time

def backoff():
    return time.time()
'''}, "wallclock-entropy")
    assert found == []


# ---------------------------------------------------------------------------
# family 4: wire contract
# ---------------------------------------------------------------------------

DAEMON_WIRE = '''
_KNOWN_OPS = frozenset(("ping", "feed"))

def dispatch(op, conn):
    if op == "ping":
        protocol.send_json(conn, {"ok": True})
    elif op == "fe" + "ed":
        protocol.send_json(conn, {"ok": True, "rows": 1})
    elif op == f"fin{'alize'}":
        protocol.send_json(conn, {"ok": True})
'''


def test_wire_op_clamp_sees_through_concatenation_and_fstrings():
    project, found = run_rules(
        {"serve/daemon.py": DAEMON_WIRE},
        "wire-op-clamp",
        protocol_doc="ping feed",
    )
    msgs = [f.message for f in found]
    # "finalize" (built via f-string) is neither clamped nor documented;
    # "feed" (built via concatenation) is both.
    assert any('"finalize" is dispatched but missing' in m for m in msgs)
    assert any("absent from docs/protocol.md" in m for m in msgs)
    assert not any('"feed"' in m for m in msgs)


def test_wire_op_clamp_clean_when_clamped_and_documented():
    src = DAEMON_WIRE.replace('("ping", "feed")', '("ping", "feed", "finalize")')
    _, found = run_rules(
        {"serve/daemon.py": src},
        "wire-op-clamp",
        protocol_doc="ping feed finalize",
    )
    assert found == []


def test_ack_contract_flags_removed_field_only():
    files = {"serve/daemon.py": '''
def _identity(self):
    return {"id": 1, "boot_id": 2}

def answer(self, conn):
    protocol.send_json(conn, {"ok": True, "rows": 3, **self._identity()})
'''}
    # A snapshot field the daemon no longer answers → finding.
    _, found = run_rules(
        files, "ack-contract",
        contract={"version": 1, "ack_fields": ["ok", "rows", "id", "boot_id", "gone"]},
    )
    assert rule_ids(found) == ["ack-contract"]
    assert '"gone"' in found[0].message
    # Additive drift (code answers MORE than the snapshot) → note, not a
    # finding: the contract is "only ever add".
    project, found = run_rules(
        files, "ack-contract",
        contract={"version": 1, "ack_fields": ["ok", "rows"]},
    )
    assert found == []
    assert any("additive" in n for n in project.notes)


def test_ack_field_collection_precision():
    """Variable-bound acks (the health/model_status shape) ARE collected
    — literal assignment plus dict-grown keys on the sent name — while
    subscript stores on UNRELATED dicts are NOT: over-collection would
    mask a removed ack field behind any identically-named key."""
    from spark_rapids_ml_tpu.tools.analyze import Module, collect_ack_fields

    mod = Module("serve/daemon.py", '''
def answer(self, conn, m):
    status = {"ok": True, "exists": m is not None}
    if m is not None:
        status["aot"] = 1
    unrelated = {}
    unrelated["rows"] = 3
    protocol.send_json(conn, status)
''')
    assert collect_ack_fields(mod) == {"ok", "exists", "aot"}


def test_package_contract_snapshot_is_in_sync():
    """The checked-in snapshot must stay a subset of what the daemon
    answers (removal = break) AND must not silently rot: every snapshot
    field is still answered today."""
    contract = json.loads(analyze.CONTRACT_PATH.read_text())
    project = pkg_project()
    daemon = [m for m in project.modules if m.relpath == "serve/daemon.py"][0]
    have = analyze.collect_ack_fields(daemon)
    assert set(contract["ack_fields"]) <= have
    assert len(contract["ack_fields"]) >= 20  # the real ack surface


# ---------------------------------------------------------------------------
# ported regex gates
# ---------------------------------------------------------------------------


def test_bare_print_twins():
    _, found = run_rules({
        "core/x.py": 'def f():\n    print("hi")\n',
        "tools/cli.py": 'def f():\n    print("hi")\n',
        "spark/entry.py": 'if __name__ == "__main__":\n    print("hi")\n',
    }, "bare-print")
    assert [f.file for f in found] == ["core/x.py"]


def test_bare_collective_twins():
    _, found = run_rules({
        "ops/gram.py": 'def f(x):\n    return lax.psum(x, "data")\n',
        "parallel/mapreduce.py": 'def f(x):\n    return lax.psum(x, "data")\n',
        "ops/doc.py": '"""mentions lax.psum in prose only"""\n',
    }, "bare-collective")
    assert [f.file for f in found] == ["ops/gram.py"]


def test_socket_timeout_twins():
    _, found = run_rules({"serve/client.py": '''
import socket

def bad(addr):
    return socket.create_connection(addr)

def good(addr):
    return socket.create_connection(addr, timeout=5.0)

def also_good(addr, t):
    return socket.create_connection(addr, t)
'''}, "socket-timeout")
    assert len(found) == 1
    assert found[0].symbol == "bad"


# ---------------------------------------------------------------------------
# the interprocedural engine: call-graph resolution units
# ---------------------------------------------------------------------------

CALLGRAPH_FILES = {
    "ops/util.py": '''
def leaf():
    import time
    time.sleep(0.1)

def mid():
    leaf()
''',
    "models/user.py": '''
from spark_rapids_ml_tpu.ops import util as util_ops
from spark_rapids_ml_tpu.ops.util import mid

class Runner:
    def run_all(self):
        self.helper()

    def helper(self):
        mid()

    def aliased(self):
        util_ops.leaf()

    def local(self):
        def inner():
            mid()
        inner()
''',
}


def test_callgraph_resolves_methods_imports_aliases_and_nested_defs():
    project = Project(files=dict(CALLGRAPH_FILES))
    g = project.graph
    def callees(key):
        return sorted(s.callee for s in g.calls_out.get(key, []))
    # self-method resolution
    assert callees(("models/user.py", "Runner.run_all")) == [
        ("models/user.py", "Runner.helper")
    ]
    # from-import function resolution
    assert callees(("models/user.py", "Runner.helper")) == [
        ("ops/util.py", "mid")
    ]
    # module-alias resolution
    assert callees(("models/user.py", "Runner.aliased")) == [
        ("ops/util.py", "leaf")
    ]
    # nested-def resolution: `local` calls its own `inner`
    assert callees(("models/user.py", "Runner.local")) == [
        ("models/user.py", "Runner.local.inner")
    ]


def test_callgraph_may_block_fixpoint_chains_to_the_primitive():
    project = Project(files=dict(CALLGRAPH_FILES))
    g = project.graph
    # leaf blocks directly; mid and every caller inherit it through the
    # fixpoint, each with a witness chain that bottoms out at time.sleep.
    assert ("ops/util.py", "leaf") in g.may_block
    assert ("ops/util.py", "mid") in g.may_block
    chain = g.may_block[("models/user.py", "Runner.run_all")]
    assert "time.sleep" in chain[-1][3]
    assert len(chain) >= 3  # run_all → helper → mid → leaf's primitive


def test_callgraph_attr_dispatch_respects_visibility_and_affinity():
    files = {
        "serve/a.py": '''
class Timer:
    def halt(self):
        pass

class Daemon:
    def halt(self):
        import time
        time.sleep(5)

def use(timer):
    timer.halt()
''',
        "spark/far.py": '''
class Unrelated:
    def halt(self):
        import time
        time.sleep(5)
''',
    }
    project = Project(files=files)
    g = project.graph
    callees = {s.callee for s in g.calls_out.get(("serve/a.py", "use"), [])}
    # receiver `timer` has name affinity with class Timer → the Daemon
    # candidate is dropped; Unrelated lives in a module neither side
    # imports → invisible.
    assert callees == {("serve/a.py", "Timer.halt")}


def test_callgraph_resolves_inherited_methods_through_aliased_base_imports():
    # `from ... import Base as RenamedBase; class Child(RenamedBase)`:
    # the base must resolve under its ORIGINAL name in the source
    # module, or inherited-method facts silently vanish.
    files = {
        "ops/base.py": '''
class Base:
    def blocky(self):
        import time
        time.sleep(1)
''',
        "serve/child.py": '''
from spark_rapids_ml_tpu.ops.base import Base as RenamedBase

class Child(RenamedBase):
    def go(self):
        self.blocky()
''',
    }
    project = Project(files=files)
    g = project.graph
    assert [s.callee for s in g.calls_out[("serve/child.py", "Child.go")]] == [
        ("ops/base.py", "Base.blocky")
    ]
    assert ("serve/child.py", "Child.go") in g.may_block


def test_long_held_scan_ignores_closures_defined_under_the_lock():
    # A blocking call inside a nested def defined under `with lock:`
    # runs AFTER the lock is released — it must not mark the lock
    # long-held (the same closure rule held_locks documents).
    files = _daemon('''
import threading
import time
_DEVICE_LOCK = threading.Lock()

class D:
    _cb_lock = threading.Lock()
    def defer(self, schedule):
        with self._cb_lock:
            def later():
                time.sleep(1)
            schedule(later)
    def bump(self):
        with self._cb_lock:
            self.n = 1
    def fold(self):
        with _DEVICE_LOCK:
            self.bump()
''')
    _, found = run_rules(files, "blocking-under-device-lock")
    assert found == []


def test_callgraph_entered_holding_propagates_through_calls():
    files = {"serve/d.py": '''
import threading

class D:
    _a_lock = threading.Lock()
    def outer(self):
        with self._a_lock:
            self.inner()
    def inner(self):
        pass
'''}
    project = Project(files=files)
    g = project.graph
    assert g.entered_holding.get(("serve/d.py", "D.inner")) == {
        "serve/d.py:_a_lock"
    }


# ---------------------------------------------------------------------------
# family: interprocedural lock rules
# ---------------------------------------------------------------------------


def test_blocking_under_device_lock_flags_direct_and_transitive():
    bad = _daemon('''
import threading
import time
_DEVICE_LOCK = threading.Lock()

class D:
    def direct(self):
        with _DEVICE_LOCK:
            time.sleep(0.5)
    def transitive(self):
        with _DEVICE_LOCK:
            self._notify()
    def _notify(self):
        self._sock.sendall(b"x")
''')
    _, found = run_rules(bad, "blocking-under-device-lock")
    assert rule_ids(found) == [
        "blocking-under-device-lock", "blocking-under-device-lock"
    ]
    direct, transitive = found
    assert "time.sleep" in direct.message
    # the transitive finding carries the call-chain witness to the
    # socket primitive, and the family lands in the JSON payload
    assert transitive.chain and "sendall" in transitive.chain[-1][2]
    assert transitive.family == "lock"
    payload = transitive.as_dict()
    assert payload["family"] == "lock"
    assert payload["chain"][-1]["note"]


def test_blocking_under_device_lock_contended_lock_twins():
    # A `with lock:` acquisition blocks ONLY when that lock is
    # LONG-HELD — some holder's critical section itself transitively
    # blocks. Contending on a micro-lock (holders never block inside)
    # is a bounded stall and must NOT flood the rule: config.get's
    # registry lock is the canonical benign case.
    contended = _daemon('''
import threading
import time
_DEVICE_LOCK = threading.Lock()

class D:
    _stats_lock = threading.Lock()
    def flush(self):
        with self._stats_lock:
            self._sock.sendall(b"stats")  # long holder: blocks inside
    def bump(self):
        with self._stats_lock:
            self.n = 1
    def fold(self):
        with _DEVICE_LOCK:
            self.bump()  # can wait for flush()'s socket send
''')
    _, found = run_rules(contended, "blocking-under-device-lock")
    assert rule_ids(found) == ["blocking-under-device-lock"]
    assert found[0].symbol == "D.fold"
    notes = " ".join(n for _, _, n in found[0].chain)
    assert "wait on a holder" in notes and "sendall" in notes
    micro = _daemon('''
import threading
_DEVICE_LOCK = threading.Lock()

class D:
    _stats_lock = threading.Lock()
    def bump(self):
        with self._stats_lock:
            self.n = 1  # every holder is O(ns): bounded micro-stall
    def fold(self):
        with _DEVICE_LOCK:
            self.bump()
''')
    _, found = run_rules(micro, "blocking-under-device-lock")
    assert found == []


def test_thread_shared_state_sees_timer_and_positional_targets():
    # threading.Timer's callable is POSITIONAL (`function`, not
    # `target=`) — a Timer-spawned unlocked write must still flag.
    files = {"serve/worker.py": '''
import threading

class W:
    def arm(self):
        threading.Timer(5.0, self._tick).start()
    def _tick(self):
        self.n = 1
'''}
    _, found = run_rules(files, "thread-shared-state")
    assert rule_ids(found) == ["thread-shared-state"]
    assert "self.n" in found[0].message


def test_blocking_under_device_lock_exempts_device_waits():
    # Blocking on the DEVICE is the lock's purpose: block_until_ready /
    # device_get under _DEVICE_LOCK is the encoded exemption, not a
    # finding (srml-check would otherwise flag every legal dispatch).
    good = _daemon('''
import threading
import jax
_DEVICE_LOCK = threading.Lock()

class D:
    def dispatch(self, out):
        with _DEVICE_LOCK:
            return jax.block_until_ready(out)
    def unlocked_sleep(self):
        import time
        time.sleep(0.5)
''')
    _, found = run_rules(good, "blocking-under-device-lock")
    assert found == []


def test_lock_graph_cycle_twins_lexical():
    bad = {"serve/fleet.py": '''
import threading

class F:
    _a_lock = threading.Lock()
    _b_lock = threading.Lock()
    def one(self):
        with self._a_lock:
            with self._b_lock:
                pass
    def two(self):
        with self._b_lock:
            with self._a_lock:
                pass
'''}
    good = {"serve/fleet.py": '''
import threading

class F:
    _a_lock = threading.Lock()
    _b_lock = threading.Lock()
    def one(self):
        with self._a_lock:
            with self._b_lock:
                pass
    def two(self):
        with self._a_lock:
            with self._b_lock:
                pass
'''}
    _, found = run_rules(bad, "lock-graph-cycle")
    assert rule_ids(found) == ["lock-graph-cycle"]
    assert "_a_lock" in found[0].message and "_b_lock" in found[0].message
    assert len(found[0].chain) == 2  # both edges of the 2-cycle
    _, found = run_rules(good, "lock-graph-cycle")
    assert found == []


def test_lock_graph_cycle_through_call_edges():
    # The interprocedural shape PR 14's per-function analyzer was blind
    # to: neither function nests two `with` statements — the ordering
    # only exists across call edges.
    files = {"serve/fleet.py": '''
import threading

class F:
    _a_lock = threading.Lock()
    _b_lock = threading.Lock()
    def path_one(self):
        with self._a_lock:
            self._grab_b()
    def _grab_b(self):
        with self._b_lock:
            pass
    def path_two(self):
        with self._b_lock:
            self._grab_a()
    def _grab_a(self):
        with self._a_lock:
            pass
'''}
    _, found = run_rules(files, "lock-graph-cycle")
    assert rule_ids(found) == ["lock-graph-cycle"]
    assert "caller on the path" in " ".join(n for _, _, n in found[0].chain)


def test_seeded_lock_cycle_drill_in_scratch_module():
    """The acceptance-criteria drill: splice an A→B/B→A pair (linked only
    through call edges) into a scratch module of the REAL package and the
    cycle gate must catch it."""
    files = Project.package_files()
    files["serve/_scratch_cycle.py"] = '''
import threading

class Scratch:
    _alpha_lock = threading.Lock()
    _beta_lock = threading.Lock()
    def forward(self):
        with self._alpha_lock:
            self._take_beta()
    def _take_beta(self):
        with self._beta_lock:
            pass
    def backward(self):
        with self._beta_lock:
            self._take_alpha()
    def _take_alpha(self):
        with self._alpha_lock:
            pass
'''
    project = Project(files=files)
    found = project.run(rules=["lock-graph-cycle"], baseline=Baseline.load())
    assert len(found) == 1
    assert "_alpha_lock" in found[0].message
    assert found[0].file == "serve/_scratch_cycle.py"


# ---------------------------------------------------------------------------
# family: thread-shared-state
# ---------------------------------------------------------------------------


def test_thread_shared_state_twins():
    bad = {"serve/worker.py": '''
import threading

class W:
    def start(self):
        threading.Thread(target=self._loop, daemon=True).start()
    def _loop(self):
        self.count = 0
'''}
    good = {"serve/worker.py": '''
import threading

class W:
    _lock = threading.Lock()
    def start(self):
        threading.Thread(target=self._loop, daemon=True).start()
    def _loop(self):
        with self._lock:
            self.count = 0
'''}
    _, found = run_rules(bad, "thread-shared-state")
    assert rule_ids(found) == ["thread-shared-state"]
    assert "self.count" in found[0].message
    _, found = run_rules(good, "thread-shared-state")
    assert found == []


def test_thread_shared_state_respects_lock_on_the_call_path():
    # The write itself is lexically unlocked, but EVERY path from the
    # thread entry passes a lock-holding call site — not a finding: the
    # lock is held on the access path.
    files = {"serve/worker.py": '''
import threading

class W:
    _lock = threading.Lock()
    def start(self):
        threading.Thread(target=self._loop, daemon=True).start()
    def _loop(self):
        with self._lock:
            self._flush()
    def _flush(self):
        self.pending = []
'''}
    _, found = run_rules(files, "thread-shared-state")
    assert found == []


def test_thread_shared_state_flags_module_globals_and_skips_init():
    files = {"serve/worker.py": '''
import threading

_COUNTER = 0

class W:
    def __init__(self):
        self.ok = True  # pre-publication: exempt
    def start(self):
        threading.Thread(target=self._loop, daemon=True).start()
    def _loop(self):
        global _COUNTER
        _COUNTER += 1
'''}
    _, found = run_rules(files, "thread-shared-state")
    assert rule_ids(found) == ["thread-shared-state"]
    assert "_COUNTER" in found[0].message


# ---------------------------------------------------------------------------
# family: per-op wire schemas
# ---------------------------------------------------------------------------

WIRE_SCHEMA_DAEMON = '''
class Daemon:
    def _dispatch(self, conn, req):
        op = req.get("op")
        if op == "ping":
            protocol.send_json(conn, {"ok": True, "v": 1})
        elif op == "feed":
            self._op_feed(conn, req)

    def _op_feed(self, conn, req):
        rows = int(req["rows"])
        batch = req.get("batch_id")
        protocol.send_json(conn, {"ok": True, "rows": rows})
'''

WIRE_SCHEMA_DOC = "### ping\n\n### feed\n"


def _wire_contract(**ops):
    return {"version": 2, "common": {"req": [], "ack": []}, "ops": ops}


def test_wire_schema_extraction_is_per_op():
    from spark_rapids_ml_tpu.tools.analyze import collect_op_schemas

    project = Project(files={"serve/daemon.py": WIRE_SCHEMA_DAEMON})
    mod = project.modules[0]
    ops, common = collect_op_schemas(project, mod)
    assert sorted(ops) == ["feed", "ping"]
    assert ops["ping"]["ack"] == {"ok", "v"}
    # the handler is followed through the self._op_feed(conn, req) call
    assert ops["feed"]["req"] == {"rows", "batch_id"}
    assert ops["feed"]["ack"] == {"ok", "rows"}
    assert "op" in common["req"]


def test_wire_schema_round_trip_additive_passes():
    # Snapshot == code → clean; code answering MORE than the snapshot →
    # a note, never a finding (the contract only ever grows).
    snap = _wire_contract(
        ping={"req": [], "ack": ["ok"]},
        feed={"req": ["rows"], "ack": ["ok"]},
    )
    project, found = run_rules(
        {"serve/daemon.py": WIRE_SCHEMA_DAEMON},
        "wire-schema",
        contract=snap,
        protocol_doc=WIRE_SCHEMA_DOC,
    )
    assert found == []
    assert any("grew (additive, allowed)" in n for n in project.notes)


def test_wire_schema_flags_removed_ack_and_req_fields():
    snap = _wire_contract(
        ping={"req": [], "ack": ["ok", "v", "boot_id"]},
        feed={"req": ["rows", "batch_id", "pass_id"], "ack": ["ok", "rows"]},
    )
    _, found = run_rules(
        {"serve/daemon.py": WIRE_SCHEMA_DAEMON},
        "wire-schema",
        contract=snap,
        protocol_doc=WIRE_SCHEMA_DOC,
    )
    msgs = " | ".join(f.message for f in found)
    assert 'op "ping" no longer answers ack field "boot_id"' in msgs
    assert 'op "feed" no longer reads request field "pass_id"' in msgs
    assert len(found) == 2


def test_wire_schema_flags_removed_op_and_doc_drift():
    snap = _wire_contract(
        ping={"req": [], "ack": ["ok"]},
        feed={"req": [], "ack": ["ok"]},
        legacy={"req": [], "ack": ["ok"]},
    )
    # docs lost feed's catalog heading (the word surviving in prose is
    # not enough), and the snapshot still promises a "legacy" op.
    _, found = run_rules(
        {"serve/daemon.py": WIRE_SCHEMA_DAEMON},
        "wire-schema",
        contract=snap,
        protocol_doc="### ping\n\nfeed is mentioned only in prose\n",
    )
    msgs = " | ".join(f.message for f in found)
    assert 'op "legacy" is in the wire-schema snapshot but no longer' in msgs
    assert 'no "### feed" catalog entry' in msgs
    assert len(found) == 2


def test_package_wire_schema_snapshot_is_in_sync():
    """The checked-in v2 snapshot matches the tree: per-op extraction
    yields every snapshot op with at least the snapshot's fields, and
    the gate reports zero findings."""
    contract = json.loads(analyze.CONTRACT_PATH.read_text())
    assert contract["version"] == 2
    assert len(contract["ops"]) >= 15
    project = pkg_project()
    found = [
        f for f in project.run_raw(rules=["wire-schema"])
    ]
    assert found == [], "\n" + analyze.format_findings(found)
    # and the op catalog matches docs/protocol.md section-for-section
    for op in contract["ops"]:
        assert f"### {op}" in project.protocol_doc or any(
            line.startswith(f"### {op}")
            for line in project.protocol_doc.splitlines()
        ), op


# ---------------------------------------------------------------------------
# ported gates: jit-ledger + hot-path-span
# ---------------------------------------------------------------------------


def test_jit_ledger_twins():
    bad = {"ops/kern.py": '''
import jax
f = jax.jit(lambda x: x)
g = ledgered_jit("kern", lambda x: x)
''',
           "models/other.py": '''
h = ledgered_jit("kern.step", lambda x: x)
''',
           "ops/dup.py": '''
k = ledgered_jit("kern.step", lambda x: x)
'''}
    _, found = run_rules(bad, "jit-ledger")
    msgs = " | ".join(f.message for f in found)
    assert "bare jax.jit()" in msgs
    assert 'ledger name "kern" is not <area>.<fn>' in msgs
    assert "also registered in" in msgs
    assert len(found) == 3
    good = {"ops/kern.py": '''
g = ledgered_jit("kern.fold", lambda x: x)
g2 = ledgered_jit("kern.fold", lambda x: x)  # same-file reuse pools
'''}
    _, found = run_rules(good, "jit-ledger")
    assert found == []


def test_hot_path_span_twins():
    bad = {"models/thing.py": '''
def fit_thing(x):
    return x

class ThingModel:
    def transform_matrix(self, x):
        return x
'''}
    good = {"models/thing.py": '''
from spark_rapids_ml_tpu.utils.profiling import trace_span

def fit_thing(x):
    with trace_span("fit"):
        return x

class ThingModel:
    def transform_matrix(self, x):
        with trace_span("transform"):
            return x

def plan_thing(x):  # not a hot path: neither fit_* nor a hot method
    return x
'''}
    _, found = run_rules(bad, "hot-path-span")
    assert sorted(f.message.split("(")[0] for f in found) == [
        "model hot path fit_thing", "model hot path transform_matrix",
    ]
    _, found = run_rules(good, "hot-path-span")
    assert found == []


# ---------------------------------------------------------------------------
# --changed-only scoping
# ---------------------------------------------------------------------------


def test_reverse_dependents_follow_the_import_graph():
    project = Project(files=dict(CALLGRAPH_FILES))
    # models/user.py imports ops/util.py → changing util must pull user
    # into the report scope; changing user pulls nothing else.
    assert analyze.reverse_dependents(project, ["ops/util.py"]) == [
        "models/user.py", "ops/util.py",
    ]
    assert analyze.reverse_dependents(project, ["models/user.py"]) == [
        "models/user.py",
    ]
    # unknown paths are ignored rather than crashing the pre-commit hook
    assert analyze.reverse_dependents(project, ["nope/gone.py"]) == []


@pytest.mark.analyze
def test_cli_changed_only_smoke():
    proc = subprocess.run(
        [sys.executable, "-m", "spark_rapids_ml_tpu.tools.analyze",
         "--changed-only", "HEAD", "--json"],
        capture_output=True,
        text=True,
        cwd=REPO,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "--changed-only HEAD" in proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["ok"] is True


# ---------------------------------------------------------------------------
# analyzer performance gate
# ---------------------------------------------------------------------------


@pytest.mark.analyze
def test_whole_package_analysis_stays_under_budget():
    """The interprocedural fixpoints must not quietly make tier-1
    unaffordable: a fresh whole-package parse + call graph + every rule
    stays under the pinned budget, and no fixpoint hit its iteration cap
    (the cap is loud by contract)."""
    import time as _time

    t0 = _time.perf_counter()
    project = Project.from_package()
    project.graph  # force the call graph + dataflow fixpoints
    findings = project.run(baseline=Baseline.load())
    elapsed = _time.perf_counter() - t0
    assert elapsed < 10.0, (
        f"whole-package analysis took {elapsed:.1f}s (budget 10s) — the "
        "interprocedural passes regressed; profile CallGraph._link/_solve"
    )
    assert findings == []
    cap_hits = [n for n in project.notes if "fixpoint cap" in n]
    assert cap_hits == [], "\n".join(cap_hits)


# ---------------------------------------------------------------------------
# suppression: pragmas, baseline round-trip, seeded violation
# ---------------------------------------------------------------------------


def test_inline_pragma_suppresses_exactly_its_rule():
    files = {"ops/fold.py": '''
def merge(parts):
    total = 0
    for k, v in parts.items():  # srml: disable=unsorted-iter
        total += v
    for k, v in parts.items():
        total += v
    return total
'''}
    project = Project(files=files)
    found = project.run(rules=["unsorted-iter"])
    assert len(found) == 1
    assert found[0].line == 6  # only the un-pragma'd loop


def test_baseline_round_trip_and_stale_warning():
    bad = {"ops/fold.py": '''
def merge(parts):
    return [v for k, v in parts.items()]
'''}
    clean = {"ops/fold.py": '''
def merge(parts):
    return [v for k, v in sorted(parts.items())]
'''}
    # 1. finding exists
    project = Project(files=bad)
    raw = project.run(rules=["unsorted-iter"])
    assert len(raw) == 1
    # 2. accepted into the baseline → suppressed
    accepted = Baseline.from_findings(raw)
    project = Project(files=bad)
    assert project.run(rules=["unsorted-iter"], baseline=accepted) == []
    assert project.notes == []
    # 3. offending code removed → the baseline entry goes stale (warned,
    #    so the ratchet only ever shrinks)
    project = Project(files=clean)
    stale_base = Baseline.from_findings(raw)
    assert project.run(rules=["unsorted-iter"], baseline=stale_base) == []
    assert any("stale baseline entry" in n for n in project.notes)
    # 4. a NEW finding in an already-baselined symbol still fails: the
    #    count bounds acceptance.
    two = {"ops/fold.py": '''
def merge(parts):
    a = [v for k, v in parts.items()]
    b = [k for k, v in parts.items()]
    return a + b
'''}
    project = Project(files=two)
    found = project.run(rules=["unsorted-iter"], baseline=Baseline.from_findings(raw))
    assert len(found) == 1


def test_baseline_is_reusable_across_runs():
    # Matched counts are per-run state: one loaded Baseline must keep
    # suppressing when reused (the natural way to script the API).
    files = {"ops/fold.py": '''
def merge(parts):
    return [v for k, v in parts.items()]
'''}
    accepted = Baseline.from_findings(Project(files=files).run(rules=["unsorted-iter"]))
    for _ in range(2):
        project = Project(files=files)
        assert project.run(rules=["unsorted-iter"], baseline=accepted) == []
        assert project.notes == []


def test_rewrite_baseline_preserves_out_of_scope_entries():
    """A --rule-restricted --write-baseline must not un-accept entries
    of rules it never evaluated (or files a path filter excluded)."""
    files = {"ops/fold.py": '''
def merge(parts):
    return [v for k, v in parts.items()]
'''}
    project = Project(files=files)
    accepted = Baseline(entries=[
        # Out of scope below: a different rule, and a file not analyzed.
        {"rule": "device-lock", "file": "serve/daemon.py",
         "symbol": "Job.fold", "count": 2},
        # In scope and still live: kept at its matched count.
        {"rule": "unsorted-iter", "file": "ops/fold.py",
         "symbol": "merge", "count": 1},
        # In scope but stale: dropped by the rewrite (the ratchet).
        {"rule": "unsorted-iter", "file": "ops/fold.py",
         "symbol": "gone_fn", "count": 1},
    ])
    findings = project.run(rules=["unsorted-iter"], baseline=accepted)
    assert findings == []
    merged = analyze.rewrite_baseline(
        project, accepted, findings, selected_rules=["unsorted-iter"]
    )
    assert merged.entries == {
        ("device-lock", "serve/daemon.py", "Job.fold"): 2,
        ("unsorted-iter", "ops/fold.py", "merge"): 1,
    }


def test_seeded_violation_in_scratch_daemon_is_caught():
    """The acceptance-criteria drill: splice a device dispatch outside
    _DEVICE_LOCK into a scratch copy of the REAL daemon.py and the gate
    must catch it."""
    files = Project.package_files()
    files["serve/daemon.py"] += '''

def _scratch_unlocked_dispatch(self, state, xs, ms):
    return self.update(state, xs, ms)
'''
    project = Project(files=files)
    found = project.run(rules=["device-lock"], baseline=Baseline.load())
    assert len(found) == 1
    assert found[0].symbol == "_scratch_unlocked_dispatch"


# ---------------------------------------------------------------------------
# the tier-1 gate + CLI
# ---------------------------------------------------------------------------


@pytest.mark.analyze
def test_whole_package_zero_unsuppressed_findings():
    """THE gate: every rule over the real tree, pragmas + baseline
    honored — a new violation anywhere in the package fails tier-1 here
    exactly like the historical lint gates."""
    project = pkg_project()
    findings = project.run(baseline=Baseline.load())
    assert findings == [], "\n" + analyze.format_findings(findings)


@pytest.mark.analyze
def test_baseline_has_no_stale_entries():
    """The ratchet: accepted findings whose code has been fixed must be
    removed from tools/analyze_baseline.json, so acceptance only shrinks."""
    project = pkg_project()
    project.run(baseline=Baseline.load())
    stale = [n for n in project.notes if "stale baseline entry" in n]
    assert stale == [], "\n".join(stale)


@pytest.mark.analyze
def test_cli_json_output():
    """The machine interface CI consumes: exit 0 + well-formed JSON."""
    proc = subprocess.run(
        [sys.executable, "-m", "spark_rapids_ml_tpu.tools.analyze", "--json"],
        capture_output=True,
        text=True,
        cwd=REPO,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["ok"] is True
    assert payload["findings"] == []
    assert len(payload["rules"]) >= 17


def test_rule_catalog_is_documented():
    """Every registered rule appears in docs/static_analysis.md (the
    operator-facing catalog) — a rule cannot land undocumented."""
    doc = (REPO / "docs" / "static_analysis.md").read_text()
    missing = [rid for rid in analyze.RULES if f"`{rid}`" not in doc]
    assert missing == [], f"rules missing from docs/static_analysis.md: {missing}"
