"""KMeans differential tests: sklearn oracle + sharding invariance."""

import numpy as np
import pytest

from spark_rapids_ml_tpu import KMeans, KMeansModel
from spark_rapids_ml_tpu.models.kmeans import fit_kmeans
from spark_rapids_ml_tpu.parallel.mesh import make_mesh


@pytest.fixture
def blobs(rng):
    # 4 well-separated gaussian blobs in 8-d.
    centers = rng.normal(size=(4, 8)) * 10.0
    pts = np.concatenate(
        [c + rng.normal(size=(200, 8)) for c in centers], axis=0
    )
    perm = rng.permutation(len(pts))
    return pts[perm], centers


def _match_centers(found, true):
    """Greedy-match found centers to true ones; return max distance."""
    found = found.copy()
    worst = 0.0
    for t in true:
        d = np.linalg.norm(found - t, axis=1)
        i = int(np.argmin(d))
        worst = max(worst, d[i])
        found[i] = np.inf
    return worst


def test_recovers_blob_centers(blobs, mesh8):
    pts, centers = blobs
    sol = fit_kmeans(pts, k=4, max_iter=50, seed=1, mesh=mesh8)
    assert sol.n_rows == len(pts)
    assert sol.n_iter > 0
    # Each true center recovered to within ~3/sqrt(200) stderr.
    assert _match_centers(sol.centers, centers) < 0.5


def test_matches_oracle_cost(blobs, mesh8):
    from oracles import kmeans_inertia

    pts, _ = blobs
    ref_inertia = kmeans_inertia(pts, k=4, n_init=3, seed=0)
    sol = fit_kmeans(pts, k=4, max_iter=50, seed=1, mesh=mesh8)
    # Same local optimum on well-separated blobs: inertia within 1%.
    assert sol.cost <= ref_inertia * 1.01


def test_shard_invariance(blobs):
    pts, _ = blobs
    sols = [
        fit_kmeans(pts, k=4, max_iter=30, seed=7, mesh=make_mesh(data=n, model=1))
        for n in (1, 8)
    ]
    np.testing.assert_allclose(sols[0].centers, sols[1].centers, atol=1e-7)
    assert abs(sols[0].cost - sols[1].cost) < 1e-6 * max(1.0, sols[0].cost)


def test_uneven_rows(mesh8, rng):
    pts = rng.normal(size=(101, 5))
    sol = fit_kmeans(pts, k=3, max_iter=10, seed=0, mesh=mesh8)
    assert sol.centers.shape == (3, 5)
    assert np.all(np.isfinite(sol.centers))


def test_estimator_api(blobs, mesh8):
    pts, _ = blobs
    ds = {"features": pts}
    km = KMeans(mesh=mesh8).setK(4).setMaxIter(30).setSeed(3)
    model = km.fit(ds)
    assert model.clusterCenters().shape == (4, 8)
    assert model.trainingCost is not None and model.trainingCost > 0
    out = model.transform(ds)
    preds = out["prediction"]
    assert preds.shape == (len(pts),)
    assert set(np.unique(preds)) <= set(range(4))
    # Points in the same blob get the same cluster: check self-consistency
    # between predict() and the training assignment structure.
    p2 = model.predict(pts)
    np.testing.assert_array_equal(preds, p2)


def test_model_persistence(blobs, mesh8, tmp_path):
    pts, _ = blobs
    model = KMeans(mesh=mesh8).setK(4).fit({"features": pts})
    path = str(tmp_path / "km")
    model.save(path)
    loaded = KMeansModel.load(path)
    np.testing.assert_allclose(loaded.centers, model.centers, atol=1e-12)
    np.testing.assert_array_equal(loaded.predict(pts[:50]), model.predict(pts[:50]))


def test_k_validation(mesh8, rng):
    pts = rng.normal(size=(10, 3))
    with pytest.raises(ValueError):
        fit_kmeans(pts, k=0, mesh=mesh8)
    with pytest.raises(ValueError):
        fit_kmeans(pts, k=11, mesh=mesh8)
    with pytest.raises(ValueError):
        fit_kmeans(pts, k=3, init="bogus", mesh=mesh8)


def test_empty_cluster_keeps_center(mesh8):
    # Force an empty cluster: k=3 but only 2 distinct points.
    pts = np.array([[0.0, 0.0], [10.0, 10.0]] * 50)
    sol = fit_kmeans(pts, k=3, max_iter=5, init="random", seed=0, mesh=mesh8)
    assert np.all(np.isfinite(sol.centers))


def test_streaming_matches_batch(blobs, mesh8):
    # Same init sample + same seed -> streaming Lloyd must land on the same
    # centers as the in-memory fit (both see identical data each scan).
    from spark_rapids_ml_tpu.models.kmeans import fit_kmeans_stream

    pts, _ = blobs

    def source():
        for i in range(0, len(pts), 200):
            yield pts[i : i + 200]

    sol_b = fit_kmeans(pts, k=4, max_iter=30, seed=1, mesh=mesh8)
    sol_s = fit_kmeans_stream(
        source, k=4, n_cols=8, max_iter=30, seed=1, mesh=mesh8,
        init_sample_rows=len(pts),
    )
    assert sol_s.n_rows == len(pts)
    np.testing.assert_allclose(
        np.sort(sol_s.centers, axis=0), np.sort(sol_b.centers, axis=0),
        atol=1e-3,
    )
    np.testing.assert_allclose(sol_s.cost, sol_b.cost, rtol=1e-4)


def test_streaming_checkpoint_resume(blobs, mesh8, tmp_path):
    from spark_rapids_ml_tpu.models.kmeans import fit_kmeans_stream

    pts, _ = blobs
    ck = str(tmp_path / "km.ckpt")

    def source():
        for i in range(0, len(pts), 200):
            yield pts[i : i + 200]

    full = fit_kmeans_stream(
        source, k=4, n_cols=8, max_iter=20, seed=1, mesh=mesh8,
        init_sample_rows=len(pts),
    )

    # Interrupt after 3 iterations (simulated preemption: max_iter=3 leaves
    # the checkpoint file behind only if we stop it from deleting — run with
    # tol=0 so it cannot converge, then kill by exhausting max_iter).
    class Stop(Exception):
        pass

    calls = {"n": 0}

    def flaky_source():
        calls["n"] += 1
        if calls["n"] == 4:  # fail during the 4th scan (iteration 4)
            raise Stop()
        return iter(pts[i : i + 200] for i in range(0, len(pts), 200))

    try:
        fit_kmeans_stream(
            lambda: flaky_source(), k=4, n_cols=8, max_iter=20, seed=1,
            mesh=mesh8, checkpoint_path=ck, init_sample_rows=len(pts),
        )
    except Stop:
        pass
    import os

    assert os.path.exists(ck)  # interrupted mid-fit -> checkpoint kept
    resumed = fit_kmeans_stream(
        source, k=4, n_cols=8, max_iter=20, seed=999,  # seed ignored on resume
        mesh=mesh8, checkpoint_path=ck, init_sample_rows=len(pts),
    )
    assert not os.path.exists(ck)  # success -> checkpoint cleaned up
    np.testing.assert_allclose(
        np.sort(resumed.centers, axis=0), np.sort(full.centers, axis=0),
        atol=1e-3,
    )
