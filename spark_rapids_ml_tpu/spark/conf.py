"""spark-submit configuration builder for TPU-accelerated ML.

Mirrors the reference's cluster recipe (README.md:103-113: plugin class,
``spark.executor.resource.gpu.amount``, per-task fractions, discovery
script) with ``tpu`` as the resource name and no CUDA in the loop.
"""

from __future__ import annotations

from typing import Dict, Optional

from spark_rapids_ml_tpu.spark.discovery import RESOURCE_NAME


def tpu_session_conf(
    executor_tpus: int = 1,
    tasks_per_tpu: int = 1,
    discovery_script: Optional[str] = None,
    executor_memory: str = "30G",
    driver_memory: str = "20G",
    max_result_size: str = "8G",
    arrow_batch_rows: int = 1 << 16,
) -> Dict[str, str]:
    """Build the conf dict for a TPU-accelerated Spark session.

    ``tasks_per_tpu`` > 1 oversubscribes tasks onto one chip the way the
    reference runs ~12 tasks/GPU (gpu.amount=0.08, README.md:111) — tasks
    feed batches; the chip pipelines them.
    """
    conf = {
        "spark.driver.memory": driver_memory,
        "spark.executor.memory": executor_memory,
        "spark.driver.maxResultSize": max_result_size,
        f"spark.executor.resource.{RESOURCE_NAME}.amount": str(executor_tpus),
        f"spark.task.resource.{RESOURCE_NAME}.amount": str(
            round(1.0 / tasks_per_tpu, 4)
        ),
        # Arrow is the columnar interchange with the TPU host process.
        "spark.sql.execution.arrow.pyspark.enabled": "true",
        "spark.sql.execution.arrow.maxRecordsPerBatch": str(arrow_batch_rows),
    }
    if discovery_script:
        conf[f"spark.worker.resource.{RESOURCE_NAME}.discoveryScript"] = discovery_script
        conf[f"spark.driver.resource.{RESOURCE_NAME}.discoveryScript"] = discovery_script
    return conf
