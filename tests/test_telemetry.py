"""Fleet telemetry plane (docs/protocol.md "Telemetry plane ops",
docs/observability.md): wire-native export, SLO burn rates, and the
flight recorder.

The load-bearing claims, in test order:

* **exemplars** — the latency histogram keeps the worst sample of the
  window per (series, bucket) with its ``{run, span}`` trace identity;
  ``render_openmetrics`` ships it as an OpenMetrics exemplar suffix and
  terminates with ``# EOF``, while the frozen Prometheus exposition
  stays byte-compatible (no suffixes);
* **wire round-trip** — one ``telemetry_pull`` answers text + JSON
  snapshot + xprof + config fingerprint, and the exemplar it carries
  names a span that the SAME daemon's ``trace_pull`` ring contains: the
  scraped p99 tail links to its trace with zero filesystem access;
* **cursor contract** — repeated ``trace_pull`` with the acked ``seq``
  as the next cursor streams without duplication; cursor 0 replays
  whatever the bounded ring still holds;
* **journal seq + rotation** — every journal line carries a dense
  per-process ``seq`` (the merge tie-breaker), size-capped journals
  rotate logrotate-style, and ``journal.read``/``tools/trace.py`` read
  the rotated segments transparently;
* **SLO burn-rate units** — synthetic cumulative snapshots with
  explicit timestamps produce exact fast/slow burns (violating
  fraction / budget), breaches require BOTH windows over the
  threshold, and the ``srml_slo_*`` gauges publish the numbers;
* **flight recorder** — a seeded deadline-breach storm makes the
  daemon's telemetry thread dump an incident bundle on its own; the
  bundle is atomic, complete (span ring, metrics WITH the exemplar
  whose span is in that same ring, xprof, gossip view, fingerprint),
  rotated at the cap, and loads in tools/trace.py as a trace source; a
  fleet rollout abort records one through the process-default recorder;
* **autoscaler coupling** — a burning SLO forces scale-up BEFORE any
  raw watermark (queue, sheds, p99) trips: the burn is budget-relative,
  the watermarks are not;
* **flagships (slow)** — a SIGKILL-style crash-kind fault leaves a
  loadable ``fault_site`` bundle behind (faults notify pre-perform);
  a 3-replica fleet of real OS-process daemons is stitched into one
  cross-replica trace tree from ONE gossip seed with zero file access,
  while an error storm on a replica drives its ``srml_slo_breach``
  gauge over the wire.
"""

import os
import time

import numpy as np
import pytest

from spark_rapids_ml_tpu import config
from spark_rapids_ml_tpu.serve import DataPlaneClient, DataPlaneDaemon, ModelFleet
from spark_rapids_ml_tpu.serve.autoscaler import AutoScaler
from spark_rapids_ml_tpu.serve.fleet import FleetRolloutError
from spark_rapids_ml_tpu.serve.gossip import FleetView
from spark_rapids_ml_tpu.serve import scheduler as scheduler_mod
from spark_rapids_ml_tpu.tools import top, trace
from spark_rapids_ml_tpu.utils import flight, journal, slo
from spark_rapids_ml_tpu.utils import metrics as metrics_mod

from conftest import (  # noqa: E402
    _launch_daemon_worker,
    _read_ready,
    spawn_daemon_worker,
    stop_daemon_worker,
)

pytestmark = pytest.mark.telemetry

D = 8


@pytest.fixture(autouse=True)
def _closed_journal():
    """Every test starts and ends with the journal closed (complete
    lines on disk, no handle reuse across tests)."""
    journal.close()
    yield
    journal.close()


def _phase_span_ids(events):
    return {
        e.get("span_id") for e in events if e.get("event") == "phase"
    }


# ---------------------------------------------------------------------------
# exemplars: worst-of-window capture + OpenMetrics rendering
# ---------------------------------------------------------------------------


def test_exemplar_keeps_worst_sample_of_window_per_bucket():
    metrics_mod.reset()
    h = metrics_mod.histogram(
        "srml_telemetrytest_seconds", "exemplar unit", buckets=(0.1, 1.0)
    )
    h.observe(0.5, exemplar={"run": "r1", "span": "s1"}, op="x")
    # Same bucket, smaller sample: the worst of the window stays.
    h.observe(0.3, exemplar={"run": "r2", "span": "s2"}, op="x")
    ex = h.exemplars(op="x")
    vals = {e["run"]: e["value"] for e in ex.values()}
    assert vals == {"r1": 0.5}
    # Same bucket, worse sample: replaced.
    h.observe(0.7, exemplar={"run": "r3", "span": "s3"}, op="x")
    # Different bucket: its own slot.
    h.observe(0.05, exemplar={"run": "r4", "span": "s4"}, op="x")
    ex = h.exemplars(op="x")
    vals = {e["run"]: e["value"] for e in ex.values()}
    assert vals == {"r3": 0.7, "r4": 0.05}
    # The JSON snapshot ships them per sample, keyed by the bucket le.
    snap = metrics_mod.snapshot()
    sample = snap["srml_telemetrytest_seconds"]["samples"][0]
    assert {e["run"] for e in sample["exemplars"].values()} == {"r3", "r4"}


def test_openmetrics_render_has_exemplars_prometheus_stays_frozen():
    metrics_mod.reset()
    h = metrics_mod.histogram(
        "srml_telemetrytest_seconds", "exemplar unit", buckets=(0.1, 1.0)
    )
    h.observe(0.5, exemplar={"run": "rr", "span": "ss"}, op="x")
    om = metrics_mod.render_openmetrics()
    assert 'run="rr"' in om and 'span="ss"' in om
    assert " # {" in om  # the exemplar suffix, not a comment line
    assert om.rstrip().endswith("# EOF")
    prom = metrics_mod.render_prometheus()
    assert " # {" not in prom and "# EOF" not in prom
    assert "srml_telemetrytest_seconds_bucket" in prom


# ---------------------------------------------------------------------------
# wire round-trip: telemetry_pull / trace_pull against a live daemon
# ---------------------------------------------------------------------------


def test_telemetry_pull_exemplar_links_a_span_in_trace_pull(mesh8, tmp_path):
    """The acceptance linkage at unit scale: the histogram exemplar a
    telemetry_pull ships names {run, span}; the run is the CALLER's
    journal run and the span is a daemon op span that the same daemon's
    trace_pull ring still holds."""
    metrics_mod.reset()
    p = tmp_path / "driver.jsonl"
    with DataPlaneDaemon(mesh=mesh8) as d:
        with config.option("run_journal", str(p)):
            fp = config.fingerprint()  # effective config at pull time
            with journal.run("telemetry-demo") as run_id:
                with DataPlaneClient(*d.address) as c:
                    c.feed("texj", np.ones((8, D)), algo="pca")
                    pull = c.telemetry_pull()
                    traced = c.trace_pull()
    # Envelope: identity + every export surface in one cursor-free ack.
    assert pull["boot_id"] == d.boot_id
    assert pull["fingerprint"] == fp
    assert pull["uptime_s"] >= 0.0
    assert isinstance(pull["xprof"], dict)
    assert pull["text"].rstrip().endswith("# EOF")
    assert "srml_daemon_requests_total" in pull["text"]
    # The exemplar: run is the caller's run, span is a ringed op span.
    lat = pull["metrics"]["srml_daemon_request_seconds"]
    feed_samples = [
        s for s in lat["samples"] if s["labels"].get("op") == "feed"
    ]
    assert feed_samples
    exemplars = feed_samples[0].get("exemplars") or {}
    assert exemplars, "journaled feed must carry an exemplar"
    ex = next(iter(exemplars.values()))
    assert ex["run"] == run_id
    assert ex["span"] in _phase_span_ids(traced["events"])


def test_trace_pull_cursor_streams_without_duplication(mesh8, tmp_path):
    with DataPlaneDaemon(mesh=mesh8) as d:
        with config.option("run_journal", str(tmp_path / "j.jsonl")):
            with journal.run("cursor-demo"):
                with DataPlaneClient(*d.address) as c:
                    c.feed("tcur-a", np.ones((8, D)), algo="pca")
                    first = c.trace_pull()
                    assert first["seq"] > 0 and first["events"]
                    assert first["boot_id"] == d.boot_id
                    # Feeding the acked seq back returns ONLY newer
                    # events (possibly none).
                    second = c.trace_pull(cursor=first["seq"])
                    assert all(
                        e["seq"] > first["seq"] for e in second["events"]
                    )
                    c.feed("tcur-b", np.ones((8, D)), algo="pca")
                    third = c.trace_pull(cursor=second["seq"])
                    names = {e.get("name") for e in third["events"]}
                    assert "daemon.feed" in names
                    assert all(
                        e["seq"] > second["seq"] for e in third["events"]
                    )
                    # Cursor 0 replays everything the ring still holds —
                    # a superset of every incremental pull.
                    replay = c.trace_pull(cursor=0)
                    seen = {e["seq"] for e in replay["events"]}
                    for pull in (first, second, third):
                        assert {e["seq"] for e in pull["events"]} <= seen


# ---------------------------------------------------------------------------
# journal seq + rotation
# ---------------------------------------------------------------------------


def test_journal_lines_carry_dense_monotonic_seq(tmp_path):
    p = tmp_path / "seq.jsonl"
    with config.option("run_journal", str(p)):
        with journal.run("seq-demo"):
            for i in range(5):
                journal.mark("tick", i=i)
    journal.close()
    events = journal.read(str(p))
    seqs = [e["seq"] for e in events]
    assert seqs == sorted(seqs)
    assert len(set(seqs)) == len(seqs)  # dense, never reused
    assert all(e["pid"] == os.getpid() for e in events)


def test_journal_rotation_is_transparent_to_readers(tmp_path):
    p = tmp_path / "rot.jsonl"
    with config.option("run_journal", str(p)), \
            config.option("run_journal_max_bytes", 2000), \
            config.option("run_journal_keep", 3):
        with journal.run("rotation-demo"):
            for i in range(120):
                journal.mark("tick", i=i)
    journal.close()
    segs = journal.segments(str(p))
    assert len(segs) >= 2, "journal never rotated"
    assert segs[-1] == str(p)  # live file last
    assert len(segs) <= 4  # keep=3 rotated + live
    events = journal.read(str(p))
    marks = [e for e in events if e.get("name") == "tick"]
    idx = [e["i"] for e in marks]
    # Oldest segments may be reaped; the surviving tail is contiguous,
    # ordered, and ends at the last write.
    assert idx == list(range(idx[0], 120))
    seqs = [e["seq"] for e in events]
    assert seqs == sorted(seqs)
    # tools/trace.py reads the same rotated set through load() (order
    # differs only where it should: a run_end line carries the run's
    # START ts, so the ts-major merge may move it — never drop it).
    loaded = trace.load([str(p)])
    assert sorted(e["seq"] for e in loaded) == seqs


def test_trace_merge_orders_by_ts_then_pid_then_seq():
    ev = lambda ts, pid, seq: {"ts": ts, "pid": pid, "seq": seq}  # noqa: E731
    shuffled = [
        ev(2.0, 1, 9), ev(1.0, 2, 3), ev(1.0, 2, 1), ev(1.0, 1, 7),
    ]
    ordered = sorted(shuffled, key=trace._sort_key)
    assert ordered == [
        ev(1.0, 1, 7), ev(1.0, 2, 1), ev(1.0, 2, 3), ev(2.0, 1, 9),
    ]


# ---------------------------------------------------------------------------
# SLO burn-rate units (synthetic snapshots, explicit clocks)
# ---------------------------------------------------------------------------


def _snap(total, err, buckets=None):
    """One synthetic cumulative registry snapshot for op=transform."""
    snap = {
        "srml_daemon_requests_total": {"samples": [
            {"labels": {"op": "transform", "outcome": "ok"},
             "value": float(total - err)},
            {"labels": {"op": "transform", "outcome": "error"},
             "value": float(err)},
        ]},
    }
    if buckets is not None:
        snap["srml_daemon_request_seconds"] = {"samples": [
            {"labels": {"op": "transform"}, "buckets": buckets,
             "sum": 0.0, "count": buckets.get("+Inf", 0.0)},
        ]}
    return snap


def test_parse_objectives_grammar_and_rejections():
    objs = slo.parse_objectives(
        "transform:p99_ms=50@0.01; kneighbors:error ;transform:shed@0.05"
    )
    assert [o.name for o in objs] == [
        "transform:p99_ms", "kneighbors:error", "transform:shed",
    ]
    assert objs[0].target == 50.0 and objs[0].budget == 0.01
    assert objs[1].budget == 0.001  # kind default
    assert objs[2].budget == 0.05
    assert slo.parse_objectives("  ") == []
    with pytest.raises(ValueError):
        slo.parse_objectives("transform")  # no kind
    with pytest.raises(ValueError):
        slo.parse_objectives("transform:p99_ms")  # latency needs =target
    with pytest.raises(ValueError):
        slo.parse_objectives("transform:error@2.0")  # budget out of (0,1)


def test_count_le_interpolates_and_counts_inf_tail_as_violations():
    buckets = {"0.1": 50.0, "0.5": 90.0, "+Inf": 100.0}
    assert slo.count_le(buckets, 0.3) == pytest.approx(70.0)
    assert slo.count_le(buckets, 0.1) == pytest.approx(50.0)
    # Past the largest finite bound the +Inf tail stays violating.
    assert slo.count_le(buckets, 5.0) == pytest.approx(90.0)


def test_error_burn_rates_fast_and_slow_windows_exact():
    metrics_mod.reset()
    ev = slo.SloEvaluator(
        objectives=[slo.Objective("transform", "error", None, 0.001)],
        fast_window_s=60.0, slow_window_s=300.0, burn_threshold=14.4,
    )
    t0 = 1000.0
    out = ev.tick(_snap(1000, 0), now=t0)
    assert out[0]["fast_burn"] == 0.0 and not out[0]["breach"]
    # 100 new requests, 3 errors: 3% violating / 0.1% budget = burn 30
    # in BOTH windows (the slow window is still partial) → breach.
    out = ev.tick(_snap(1100, 3), now=t0 + 60.0)
    assert out[0]["fast_burn"] == pytest.approx(30.0)
    assert out[0]["slow_burn"] == pytest.approx(30.0)
    assert out[0]["breach"] is True
    # The storm stops: the fast window forgives (burn 0), the slow one
    # still remembers (3 errors / 200 requests = 1.5% → burn 15) — no
    # breach, because a breach needs BOTH windows burning.
    out = ev.tick(_snap(1200, 3), now=t0 + 120.0)
    assert out[0]["fast_burn"] == pytest.approx(0.0)
    assert out[0]["slow_burn"] == pytest.approx(15.0)
    assert out[0]["breach"] is False
    # The gauges published the latest evaluation.
    snap = metrics_mod.snapshot()
    burns = {
        s["labels"]["window"]: s["value"]
        for s in snap["srml_slo_burn_rate"]["samples"]
    }
    assert burns == {
        "fast": pytest.approx(0.0), "slow": pytest.approx(15.0)
    }
    breach = snap["srml_slo_breach"]["samples"][0]
    assert breach["labels"]["objective"] == "transform:error"
    assert breach["value"] == 0.0


def test_p99_burn_interpolates_violations_inside_the_bucket():
    metrics_mod.reset()
    ev = slo.SloEvaluator(
        objectives=[slo.Objective("transform", "p99_ms", 50.0, 0.01)],
        fast_window_s=60.0, slow_window_s=300.0, burn_threshold=14.4,
    )
    ev.tick(_snap(100, 0, {"0.025": 100.0, "0.1": 100.0, "+Inf": 100.0}),
            now=0.0)
    # 100 new requests, 90 of them in (25ms, 100ms]: linear
    # interpolation puts 30 under the 50 ms target → 70 violations →
    # 70% violating / 1% budget = burn 70.
    out = ev.tick(
        _snap(200, 0, {"0.025": 100.0, "0.1": 190.0, "+Inf": 200.0}),
        now=60.0,
    )
    assert out[0]["fast_burn"] == pytest.approx(70.0)
    assert out[0]["breach"] is True


# ---------------------------------------------------------------------------
# flight recorder: automatic capture, rotation, trace-source loading
# ---------------------------------------------------------------------------


def test_incident_bundle_is_a_trace_source(tmp_path):
    """A bundle's events are ordinary journal lines: tools/trace.py
    merges file + bundle sources into one ordered stream."""
    p = tmp_path / "j.jsonl"
    with config.option("run_journal", str(p)):
        with journal.run("file-run"):
            journal.mark("from-file")
    journal.close()
    journal.ring_arm(100)
    try:
        with journal.run("ring-run"):
            journal.mark("from-ring")
        rec = flight.FlightRecorder(state_dir=str(tmp_path))
        bpath = rec.trigger("fault_site", {"site": "unit"})
    finally:
        journal.ring_disarm()
    assert bpath and os.path.exists(bpath)
    b = flight.load_bundle(bpath)
    assert b["kind"] == "srml_incident_bundle" and b["v"] == 1
    assert b["reason"] == "fault_site" and b["detail"] == {"site": "unit"}
    merged = trace.load([str(p), bpath])
    names = [e.get("name") for e in merged]
    # One ordered stream: the file run's events precede the (later)
    # ring run's, whichever source they came from.
    assert names.index("from-file") < names.index("from-ring")
    assert merged == sorted(merged, key=trace._sort_key)
    # A non-bundle .json and a missing path still fail loudly.
    with pytest.raises(ValueError):
        flight.load_bundle(str(p))


def test_trigger_debounce_and_directory_rotation(tmp_path):
    journal.ring_arm(16)
    try:
        rec = flight.FlightRecorder(state_dir=str(tmp_path))
        with config.option("incident_min_interval_s", 3600.0):
            assert rec.trigger("shed_storm") is not None
            assert rec.trigger("shed_storm") is None  # debounced
            assert rec.trigger("deadline_breach") is not None  # per reason
        with config.option("incident_min_interval_s", 0.0), \
                config.option("incident_max_bundles", 2):
            for _ in range(4):
                assert rec.trigger("slo_breach") is not None
                time.sleep(0.002)  # distinct unix-ms filenames
        bundles = sorted(os.listdir(tmp_path / "incidents"))
        assert len(bundles) == 2  # capped, oldest deleted
        assert all(b.startswith("incident-") for b in bundles)
    finally:
        journal.ring_disarm()


def test_daemon_auto_captures_deadline_breach_storm(mesh8, tmp_path):
    """The flagship trigger path, in process: seeded deadline sheds
    cross ``incident_deadline_rate`` and the daemon's OWN telemetry
    thread dumps a bundle — span ring, metrics with the exemplar whose
    span is in that same ring, xprof, gossip view, fingerprint."""
    metrics_mod.reset()
    sd = tmp_path / "sd"
    with config.option("telemetry_eval_interval_s", 0.05), \
            config.option("incident_deadline_rate", 1.0), \
            config.option("incident_min_interval_s", 0.0), \
            config.option("run_journal", str(tmp_path / "j.jsonl")):
        fp = config.fingerprint()
        with DataPlaneDaemon(mesh=mesh8, state_dir=str(sd)) as d:
            with journal.run("storm-demo") as run_id:
                with DataPlaneClient(*d.address) as c:
                    c.feed("storm-job", np.ones((8, D)), algo="pca")
                # The storm: deadline sheds at ~4000/s against a cap of
                # 1/s (cross-connection scheduler counters are process-
                # global, so the test seeds them directly).
                for _ in range(200):
                    scheduler_mod._M_SHEDS.inc(
                        op="transform", reason="deadline"
                    )
                inc_dir = sd / "incidents"
                deadline = time.time() + 10.0
                bundle = None
                while time.time() < deadline and bundle is None:
                    if inc_dir.is_dir():
                        hits = [
                            f for f in os.listdir(inc_dir)
                            # .json only: the recorder stages bundles as
                            # .json.tmp before the atomic replace, and a
                            # poll can catch that window.
                            if "deadline_breach" in f and f.endswith(".json")
                        ]
                        if hits:
                            bundle = inc_dir / sorted(hits)[0]
                            break
                    time.sleep(0.02)
                assert bundle is not None, "storm never dumped a bundle"
                b = flight.load_bundle(str(bundle))
    assert b["reason"] == "deadline_breach"
    assert b["detail"]["breaches"] >= 200.0
    assert b["fingerprint"] == fp
    assert b["identity"]["boot_id"] == d.boot_id
    assert d.instance_id in b["gossip"]["replicas"]
    sheds = b["metrics"]["srml_scheduler_sheds_total"]["samples"]
    assert any(s["labels"].get("reason") == "deadline" for s in sheds)
    # The exemplar in the bundle's metrics links to a span in the
    # bundle's OWN event ring — the incident is self-describing.
    lat = b["metrics"]["srml_daemon_request_seconds"]["samples"]
    feed = next(s for s in lat if s["labels"].get("op") == "feed")
    ex = next(iter(feed["exemplars"].values()))
    assert ex["run"] == run_id
    assert ex["span"] in _phase_span_ids(b["events"])
    # And the bundle stitches as a trace source.
    tr = trace.tree(trace.load([str(bundle)]))
    assert tr, "bundle events built no trace tree"


def test_fleet_rollout_abort_records_an_incident(mesh8, tmp_path, rng):
    from spark_rapids_ml_tpu.models.pca import PCA

    data = rng.normal(size=(64, D))
    arrays = PCA(mesh=mesh8).setK(2).fit({"features": data})._model_data()
    d = DataPlaneDaemon(mesh=mesh8).start()
    try:
        fleet = ModelFleet([d.address])
        fleet.register("tm", "pca", arrays, version=1, warm=False)
    finally:
        d.stop()  # every replica dead → the rollout must abort
    rec = flight.FlightRecorder(state_dir=str(tmp_path))
    flight.set_default(rec)
    try:
        with pytest.raises(FleetRolloutError):
            fleet.rollout("tm", "pca", arrays, warm=False)
    finally:
        flight.set_default(None)
        fleet.close()
    bundles = os.listdir(tmp_path / "incidents")
    assert len(bundles) == 1 and "rollout_abort" in bundles[0]
    b = flight.load_bundle(str(tmp_path / "incidents" / bundles[0]))
    assert b["detail"]["model"] == "tm"
    assert b["detail"]["phase"] == "registering"
    assert b["detail"]["version"] == 2


# ---------------------------------------------------------------------------
# autoscaler + tools/top coupling
# ---------------------------------------------------------------------------


def test_autoscaler_slo_breach_forces_up_before_raw_watermarks():
    """The acceptance ordering: with the queue idle, zero sheds, and
    p99 under the deadline — every raw watermark reading "down" — one
    burning SLO still forces scale-up, reason ``slo``."""
    a = AutoScaler(fleet=None, spawn=lambda: None)
    calm = {
        "replicas": 3, "queued": 0.0, "sheds_total": 0.0, "p99_s": 0.001,
    }
    assert a.evaluate(dict(calm))["verdict"] == "down"
    d = a.evaluate(dict(calm, slo_breaches=1))
    assert (d["verdict"], d["reason"]) == ("up", "slo")


def test_top_renders_slo_panel_with_breach_state():
    metrics_mod.reset()
    ev = slo.SloEvaluator(
        objectives=[slo.Objective("transform", "error", None, 0.001)],
        fast_window_s=60.0, slow_window_s=300.0, burn_threshold=14.4,
    )
    ev.tick(_snap(100, 0), now=0.0)
    ev.tick(_snap(200, 50), now=60.0)  # 50% errors: burn 500, breach
    body = top.render({"uptime_s": 1.0}, metrics_mod.snapshot())
    assert "slo objective" in body
    assert "transform:error" in body
    assert "BREACH" in body


def test_top_fleet_telemetry_panel_flags_down_and_config_drift():
    pulls = {
        "127.0.0.1:7001": {
            "id": "aaa", "fingerprint": "f1" * 8, "uptime_s": 5.0,
            "metrics": _snap(100, 3),
        },
        "127.0.0.1:7002": {
            "id": "bbb", "fingerprint": "f2" * 8, "uptime_s": 5.0,
            "metrics": _snap(80, 0),
        },
        "127.0.0.1:7003": None,
    }
    body = top.render_fleet_telemetry(pulls)
    assert "2/3 replicas up" in body
    assert "CONFIG DRIFT: 2 distinct fingerprints" in body
    assert "DOWN" in body
    drifted = dict(pulls)
    drifted["127.0.0.1:7002"] = dict(
        pulls["127.0.0.1:7002"], fingerprint="f1" * 8
    )
    assert "CONFIG DRIFT" not in top.render_fleet_telemetry(drifted)


# ---------------------------------------------------------------------------
# flagships (slow): real OS-process daemons
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_crashed_worker_leaves_a_loadable_fault_site_bundle(tmp_path):
    """The black-box property: a crash-kind fault kills the worker with
    a REAL process death (exit 17, no teardown), yet the bundle is
    already on disk — faults notify subscribers pre-perform, so the
    recorder dumps while the process still lives."""
    proc, port = spawn_daemon_worker(
        state_dir=str(tmp_path),
        fault_spec="daemon.op:crash:after=2,times=1",
        extra_env={"SRML_INCIDENT_MIN_INTERVAL_S": "0"},
    )
    try:
        with pytest.raises(Exception):
            with DataPlaneClient(
                "127.0.0.1", port, timeout=10.0, max_op_attempts=1
            ) as c:
                for i in range(10):
                    c.feed(f"fs-{i}", np.ones((8, D)), algo="pca")
        proc.wait(timeout=30)
        assert proc.returncode == 17  # a real crash, not an exit path
    finally:
        stop_daemon_worker(proc)
    inc_dir = tmp_path / "incidents"
    hits = [f for f in os.listdir(inc_dir) if "fault_site" in f]
    assert hits, f"no fault_site bundle in {os.listdir(inc_dir)}"
    b = flight.load_bundle(str(inc_dir / hits[0]))
    assert b["detail"] == {"site": "daemon.op", "fault": "crash"}
    # The dead daemon's span ring survived it: the bundle holds its
    # pre-crash op spans and loads as a trace source.
    names = {e.get("name") for e in b["events"]}
    assert "daemon.feed" in names
    assert all(e["pid"] == b["pid"] for e in b["events"])
    assert trace.load([str(inc_dir / hits[0])])


@pytest.mark.slow
def test_fleet_trace_stitch_and_slo_breach_from_one_seed(tmp_path):
    """THE flagship: three real OS-process replicas under live traffic.
    ``trace.fleet_load`` stitches every replica's spans under the
    driver's span from ONE gossip seed with zero filesystem access, and
    an error storm on the seed replica drives its ``srml_slo_breach``
    gauge over the wire — the burn crosses while the queue watermarks
    (no queueing at all here) never would."""
    slo_env = {
        "SRML_SLO_OBJECTIVES": "transform:error@0.001",
        "SRML_TELEMETRY_EVAL_INTERVAL_S": "0.05",
    }
    procs = [_launch_daemon_worker(extra_env=slo_env) for _ in range(3)]
    try:
        ports = [_read_ready(p) for p in procs]
        seed = f"127.0.0.1:{ports[0]}"
        # Live traffic under ONE driver span: the client stamps its
        # journal frame on every request; each daemon adopts it.
        infos = {}
        with config.option("run_journal", str(tmp_path / "driver.jsonl")):
            with journal.run("fleet-demo") as run_id:
                with journal.span("drive") as drive_span:
                    for port in ports:
                        with DataPlaneClient("127.0.0.1", port) as c:
                            infos[port] = c.server_info()
                            c.feed(
                                f"fl-{port}", np.ones((8, D)), algo="pca"
                            )
        journal.close()
        # Gossip: ONE push teaches the seed the whole replica set.
        view = FleetView()
        for port in ports:
            view.observe_replica(
                infos[port]["id"], f"127.0.0.1:{port}",
                infos[port]["boot_id"],
            )
        with DataPlaneClient("127.0.0.1", ports[0]) as c:
            c.gossip_push(view.to_wire())
        # Zero file access from here: one seed → the whole fleet.
        events = trace.fleet_load(seed)
        pids = {e["pid"] for e in events}
        assert len(pids) >= 3, f"expected 3 replica pids, got {pids}"
        assert os.getpid() not in pids  # wire-pulled, not local
        feeds = [
            e for e in events
            if e.get("event") == "phase" and e.get("name") == "daemon.feed"
        ]
        assert len(feeds) == 3
        assert {e["run_id"] for e in feeds} == {run_id}  # one stitched run
        assert {e["parent_id"] for e in feeds} == {drive_span}
        assert trace.tree(events)
        # Error storm on the seed replica: all-error traffic at a 0.1%
        # budget burns ~1000× — the wire-exported breach gauge crosses.
        with DataPlaneClient(
            "127.0.0.1", ports[0], timeout=10.0, max_op_attempts=1
        ) as c:
            for _ in range(20):
                try:
                    c.transform("no-such-model", np.ones((4, D)))
                except Exception:
                    pass
            breached = False
            deadline = time.time() + 10.0
            while time.time() < deadline and not breached:
                pull = c.telemetry_pull()
                breach = pull["metrics"].get("srml_slo_breach") or {}
                breached = any(
                    s["value"] >= 1.0
                    and s["labels"]["objective"] == "transform:error"
                    for s in breach.get("samples", [])
                )
                time.sleep(0.05)
            assert breached, "SLO breach gauge never crossed on the wire"
            # Same-config fleet: every replica answers one fingerprint.
            fp = pull["fingerprint"]
        for port in ports[1:]:
            with DataPlaneClient("127.0.0.1", port) as c:
                assert c.telemetry_pull()["fingerprint"] == fp
    finally:
        for p in procs:
            stop_daemon_worker(p)
