"""Observability layer: metrics registry, run journal, daemon telemetry.

Covers the tentpole contracts: registry exactness under thread hammering,
the Prometheus text exposition byte-for-byte, journal round-trips with
run/span/parent nesting, zero-overhead disabled states, the daemon's
additive ``metrics`` op under real load, chaos faults landing in the
client healing counters, and the ``tools.top`` renderer.
"""

import json
import os
import threading

import numpy as np
import pytest

from spark_rapids_ml_tpu import config
from spark_rapids_ml_tpu.utils import faults, journal, metrics
from spark_rapids_ml_tpu.utils.profiling import trace_span


@pytest.fixture(autouse=True)
def _clean_registry():
    """Isolate: the registry is process-wide and other suites feed it."""
    metrics.reset()
    yield
    metrics.reset()


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------


def test_counter_gauge_histogram_basics():
    c = metrics.counter("srml_t1_ops_total", "ops")
    c.inc(op="feed")
    c.inc(2.5, op="feed")
    c.inc(op="commit")
    assert c.value(op="feed") == 3.5
    assert c.value(op="commit") == 1.0
    assert c.value(op="never") == 0.0

    g = metrics.gauge("srml_t1_depth", "depth")
    g.set(7)
    g.inc()
    g.dec(3)
    assert g.value() == 5.0

    h = metrics.histogram("srml_t1_wait_seconds", "w", buckets=(0.1, 1.0))
    h.observe(0.1)   # le semantics: lands in the 0.1 bucket
    h.observe(0.5)
    h.observe(99.0)  # +Inf overflow
    buckets, total, count = h.series()
    assert buckets == {"0.1": 1, "1": 2, "+Inf": 3}
    assert count == 3
    assert abs(total - 99.6) < 1e-9
    assert h.series(op="other") is None


def test_registry_get_or_create_and_kind_collision():
    a = metrics.counter("srml_t2_x_total", "first")
    b = metrics.counter("srml_t2_x_total", "second registration ignored")
    assert a is b and a.help == "first"
    with pytest.raises(ValueError, match="already registered"):
        metrics.gauge("srml_t2_x_total")


def test_registry_concurrency_is_exact():
    """N threads hammering one counter + histogram: totals must be EXACT
    (a lost increment means a lock is missing, and every number the
    daemon reports becomes untrustworthy)."""
    c = metrics.counter("srml_t3_hammer_total")
    h = metrics.histogram("srml_t3_hammer_seconds", buckets=(0.5,))
    threads, per = 16, 2000

    def hammer(i):
        for k in range(per):
            c.inc(op=f"op{i % 4}")
            h.observe(0.25 if k % 2 else 0.75)

    ts = [threading.Thread(target=hammer, args=(i,)) for i in range(threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    total = sum(
        s["value"]
        for s in metrics.snapshot()["srml_t3_hammer_total"]["samples"]
    )
    assert total == threads * per
    buckets, _, count = h.series()
    assert count == threads * per
    assert buckets["0.5"] == threads * per // 2
    assert buckets["+Inf"] == threads * per


def test_prometheus_exposition_golden():
    """Byte-exact v0.0.4 exposition: sorted metrics, sorted series,
    cumulative buckets, minimal number formatting, escaped labels —
    scrapers parse this text, so its shape is an API."""
    c = metrics.counter("srml_t4_ops_total", "Demo ops")
    c.inc(3, op="feed")
    c.inc(op="commit")
    g = metrics.gauge("srml_t4_depth", "Demo depth")
    g.set(2)
    h = metrics.histogram("srml_t4_wait_seconds", "Demo waits", buckets=(0.1, 1.0))
    h.observe(0.05, op="a")
    h.observe(0.5, op="a")
    h.observe(5.0, op="a")
    e = metrics.counter("srml_t4_weird_total", "Escapes")
    e.inc(err='he said "hi"\nback\\slash')
    expected = (
        '# HELP srml_t4_depth Demo depth\n'
        '# TYPE srml_t4_depth gauge\n'
        'srml_t4_depth 2\n'
        '# HELP srml_t4_ops_total Demo ops\n'
        '# TYPE srml_t4_ops_total counter\n'
        'srml_t4_ops_total{op="commit"} 1\n'
        'srml_t4_ops_total{op="feed"} 3\n'
        '# HELP srml_t4_wait_seconds Demo waits\n'
        '# TYPE srml_t4_wait_seconds histogram\n'
        'srml_t4_wait_seconds_bucket{le="0.1",op="a"} 1\n'
        'srml_t4_wait_seconds_bucket{le="1",op="a"} 2\n'
        'srml_t4_wait_seconds_bucket{le="+Inf",op="a"} 3\n'
        'srml_t4_wait_seconds_sum{op="a"} 5.55\n'
        'srml_t4_wait_seconds_count{op="a"} 3\n'
        '# HELP srml_t4_weird_total Escapes\n'
        '# TYPE srml_t4_weird_total counter\n'
        'srml_t4_weird_total{err="he said \\"hi\\"\\nback\\\\slash"} 1\n'
    )
    assert metrics.render_prometheus() == expected


def test_snapshot_is_json_round_trippable():
    metrics.counter("srml_t5_a_total").inc(op="x")
    metrics.histogram("srml_t5_b_seconds").observe(0.01)
    snap = metrics.snapshot()
    again = json.loads(json.dumps(snap))
    assert again == snap
    assert again["srml_t5_a_total"]["type"] == "counter"
    assert again["srml_t5_b_seconds"]["samples"][0]["count"] == 1


def test_disabled_metrics_record_nothing():
    c = metrics.counter("srml_t6_off_total")
    h = metrics.histogram("srml_t6_off_seconds")
    with config.option("metrics", False):
        c.inc(op="x")
        h.observe(1.0)
        with trace_span("invisible"):
            pass
    assert c.value(op="x") == 0.0
    assert h.series() is None
    snap = metrics.snapshot()
    assert "srml_t6_off_total" not in snap
    assert not any(
        s["labels"].get("phase") == "invisible"
        for s in snap.get("srml_phase_duration_seconds", {}).get("samples", [])
    )


def test_trace_span_feeds_phase_histogram():
    with trace_span("obs test phase"):
        pass
    samples = metrics.snapshot()["srml_phase_duration_seconds"]["samples"]
    mine = [s for s in samples if s["labels"] == {"phase": "obs test phase"}]
    assert len(mine) == 1 and mine[0]["count"] == 1
    assert mine[0]["sum"] >= 0.0


# ---------------------------------------------------------------------------
# run journal
# ---------------------------------------------------------------------------


def test_journal_round_trip_with_nesting(tmp_path):
    path = str(tmp_path / "run.jsonl")
    with config.option("run_journal", path):
        assert journal.enabled()
        with journal.run("fit", estimator="T", algo="pca") as run_id:
            with trace_span("compute cov"):
                with trace_span("inner"):
                    pass
            journal.mark("note", detail=7)
    journal.close()
    events = journal.read(path)
    by_name = {(e["event"], e["name"]): e for e in events}
    assert [(e["event"], e["name"]) for e in events] == [
        ("run_start", "fit"),
        ("phase", "inner"),
        ("phase", "compute cov"),
        ("mark", "note"),
        ("run_end", "fit"),
    ]
    start = by_name[("run_start", "fit")]
    assert start["run_id"] == run_id
    assert start["parent_id"] is None
    assert start["estimator"] == "T" and start["algo"] == "pca"
    assert all(e["run_id"] == run_id for e in events)
    assert all(e["pid"] == os.getpid() for e in events)
    outer = by_name[("phase", "compute cov")]
    inner = by_name[("phase", "inner")]
    assert outer["parent_id"] == start["span_id"]
    assert inner["parent_id"] == outer["span_id"]
    assert 0.0 <= inner["duration_s"] <= outer["duration_s"]
    assert by_name[("run_end", "fit")]["duration_s"] >= outer["duration_s"]
    assert by_name[("mark", "note")]["detail"] == 7


def test_journal_disabled_is_zero_io(tmp_path):
    """The production state: no path configured → no file, no lines,
    enabled() False — the zero-allocation promise."""
    assert config.get("run_journal") is None
    assert not journal.enabled()
    with journal.run("fit") as rid:
        assert rid is None
        with trace_span("quiet"):
            pass
    assert list(tmp_path.iterdir()) == []


def test_journal_bad_path_self_disables_without_breaking_the_workload(tmp_path):
    """An unwritable journal path is an observability problem, never a
    workload problem: the first failed write warns + self-disables, the
    span's own exception (raised while the journal line was being
    emitted from the finally) propagates unmasked, and later spans are
    silent no-ops. close() re-arms."""
    bad = str(tmp_path / "no-such-dir" / "j.jsonl")
    try:
        with config.option("run_journal", bad):
            with pytest.raises(RuntimeError, match="the real failure"):
                with trace_span("phase under a broken journal"):
                    raise RuntimeError("the real failure")
            assert not journal.enabled()  # latched off for the process
            with trace_span("quiet"):  # and harmless from here on
                pass
    finally:
        journal.close()  # re-arm for the rest of the suite
    assert not (tmp_path / "no-such-dir").exists()


def test_journal_standalone_span_roots_itself(tmp_path):
    path = str(tmp_path / "solo.jsonl")
    with config.option("run_journal", path):
        with trace_span("daemon-side phase"):
            pass
    journal.close()
    (ev,) = journal.read(path)
    assert ev["event"] == "phase" and ev["parent_id"] is None
    assert ev["run_id"] and ev["span_id"]


def test_journal_concurrent_writers_emit_whole_lines(tmp_path):
    path = str(tmp_path / "threads.jsonl")

    def worker(i):
        with journal.run(f"run{i}"):
            for _ in range(50):
                with journal.span("work", worker=i):
                    pass

    with config.option("run_journal", path):
        ts = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    journal.close()
    events = journal.read(path)  # raises on any torn line
    assert len(events) == 8 * (2 + 50)
    phases = [e for e in events if e["event"] == "phase"]
    # Per-thread nesting survived the interleaving: every span parents
    # to its own thread's run, never a sibling's.
    run_span = {
        e["run_id"]: e["span_id"] for e in events if e["event"] == "run_start"
    }
    assert all(e["parent_id"] == run_span[e["run_id"]] for e in phases)


def test_kmeans_fit_journal_covers_every_phase(tmp_path, mesh8):
    """Acceptance: a kmeans fit with the journal on yields a parseable
    per-phase breakdown — both Lloyd phases present, each with a
    duration."""
    from spark_rapids_ml_tpu.models.kmeans import fit_kmeans

    rng = np.random.default_rng(0)
    x = np.concatenate(
        [rng.normal(c, 0.1, (40, 3)) for c in (0.0, 5.0)]
    ).astype(np.float64)
    path = str(tmp_path / "kmeans.jsonl")
    with config.option("run_journal", path):
        with journal.run("fit", estimator="KMeans", algo="kmeans"):
            fit_kmeans(x, k=2, max_iter=5, seed=0, mesh=mesh8)
    journal.close()
    events = journal.read(path)
    phases = {e["name"] for e in events if e["event"] == "phase"}
    assert {"kmeans init", "lloyd"} <= phases
    run_ids = {e["run_id"] for e in events}
    assert len(run_ids) == 1  # every phase nested under THE fit run
    assert all(
        e["duration_s"] >= 0.0 for e in events if e["event"] == "phase"
    )


# ---------------------------------------------------------------------------
# daemon telemetry plane
# ---------------------------------------------------------------------------


def test_daemon_metrics_op_under_load(mesh8):
    """Acceptance: a daemon under (modest) load reports non-zero per-op
    latency histograms and byte counters through the additive `metrics`
    op, in both formats, and tools.top renders them."""
    from spark_rapids_ml_tpu.serve import DataPlaneClient, DataPlaneDaemon
    from spark_rapids_ml_tpu.tools.top import render

    rng = np.random.default_rng(1)
    x = rng.standard_normal((256, 4))
    with DataPlaneDaemon(mesh=mesh8) as d:
        with DataPlaneClient(*d.address) as c:
            for part in range(3):
                c.feed("obs", x, algo="pca", partition=part)
                c.commit("obs", partition=part)
            arrays = c.finalize_pca("obs", k=2)
            assert arrays["pc"].shape == (4, 2)
            health = c.health()
            snap = c.metrics()
            text = c.metrics(format="prometheus")

    reqs = {
        (s["labels"]["op"], s["labels"]["outcome"]): s["value"]
        for s in snap["srml_daemon_requests_total"]["samples"]
    }
    assert reqs[("feed", "ok")] == 3
    assert reqs[("commit", "ok")] == 3
    lat = {
        s["labels"]["op"]: s
        for s in snap["srml_daemon_request_seconds"]["samples"]
    }
    assert lat["feed"]["count"] == 3 and lat["feed"]["sum"] > 0
    assert lat["finalize"]["count"] == 1
    rx = {
        s["labels"]["op"]: s["value"]
        for s in snap["srml_daemon_rx_bytes_total"]["samples"]
    }
    assert rx["feed"] > 0
    tx = {
        s["labels"]["op"]: s["value"]
        for s in snap["srml_daemon_tx_bytes_total"]["samples"]
    }
    assert tx["finalize"] > 0
    assert snap["srml_wire_rx_bytes_total"]["samples"][0]["value"] > 0
    # Prometheus side of the same scrape.
    assert "# TYPE srml_daemon_requests_total counter" in text
    assert 'srml_daemon_request_seconds_bucket{le="+Inf",op="feed"} 3' in text
    # tools.top renders the same snapshot without a live socket.
    screen = render(health, snap, None, None)
    assert "feed" in screen and "finalize" in screen
    assert "daemon" in screen.splitlines()[0]


def test_daemon_replay_and_shed_counters(mesh8):
    """Dedupe replays and busy sheds are counted: re-feeding a committed
    partition hits `committed_partition`, and a staged-bytes watermark
    shed lands in srml_daemon_busy_sheds_total."""
    from spark_rapids_ml_tpu.serve import DaemonBusy, DataPlaneClient, DataPlaneDaemon

    rng = np.random.default_rng(2)
    x = rng.standard_normal((64, 3))
    replays = metrics.REGISTRY.counter("srml_daemon_replay_hits_total")
    sheds = metrics.REGISTRY.counter("srml_daemon_busy_sheds_total")
    with DataPlaneDaemon(mesh=mesh8) as d:
        with DataPlaneClient(*d.address) as c:
            c.feed("rj", x, algo="pca", partition=0)
            c.commit("rj", partition=0)
            c.feed("rj", x, algo="pca", partition=0)  # post-commit duplicate
            assert replays.value(kind="committed_partition") >= 1
            c.drop("rj")
    with DataPlaneDaemon(mesh=mesh8, max_staged_bytes=1, retry_after_s=0.05) as d:
        with DataPlaneClient(
            *d.address, max_busy_wait_s=0.0, max_op_attempts=1
        ) as c:
            c.feed("sj", x, algo="pca", partition=0)  # stages past watermark
            with pytest.raises(DaemonBusy):
                c.feed("sj", x, algo="pca", partition=1)
    assert sheds.value(op="feed") >= 1


def test_chaos_faults_show_in_client_counters(mesh8):
    """Acceptance: injected faults are COUNTABLE — a healed chaos run
    leaves its trace in srml_client_fault_trips_total / _reconnects_total
    (and the per-instance stats agree)."""
    from spark_rapids_ml_tpu.serve import DataPlaneClient, DataPlaneDaemon

    fault_trips = metrics.REGISTRY.counter("srml_client_fault_trips_total")
    reconnects = metrics.REGISTRY.counter("srml_client_reconnects_total")
    rng = np.random.default_rng(3)
    x = rng.standard_normal((64, 3))
    plan = faults.FaultPlan(seed=7).rule("client.op", "drop", times=3)
    with DataPlaneDaemon(mesh=mesh8) as d:
        with DataPlaneClient(*d.address, backoff_base_s=0.01,
                             backoff_max_s=0.05) as c:
            with faults.active(plan):
                c.feed("cj", x, algo="pca")
                arrays = c.finalize_pca("cj", k=2)
    assert arrays["pc"].shape == (3, 2)
    assert plan.fired.get("client.op") == 3
    assert fault_trips.value(op="feed") + fault_trips.value(
        op="finalize"
    ) + fault_trips.value(op="ping") + fault_trips.value(op="drop") >= 3
    assert sum(
        s["value"]
        for s in metrics.snapshot()["srml_client_reconnects_total"]["samples"]
    ) >= 3
    assert c.stats["reconnects"] >= 3


# ---------------------------------------------------------------------------
# tools.top
# ---------------------------------------------------------------------------


def test_top_quantile_interpolation():
    from spark_rapids_ml_tpu.tools.top import quantile_from_buckets

    # 10 observations: 4 ≤ 0.1, 6 more ≤ 1.0 (cumulative 10).
    buckets = {"0.1": 4, "1": 10, "+Inf": 10}
    assert quantile_from_buckets(buckets, 0.4) == pytest.approx(0.1)
    # p70 → target 7: 3 of 6 into the (0.1, 1.0] bucket → 0.1 + 0.5·0.9
    assert quantile_from_buckets(buckets, 0.7) == pytest.approx(0.55)
    # everything in +Inf clamps to the largest finite bound
    assert quantile_from_buckets({"0.5": 0, "+Inf": 3}, 0.9) == 0.5
    assert quantile_from_buckets({}, 0.5) is None
    assert quantile_from_buckets({"1": 0, "+Inf": 0}, 0.5) is None


def test_top_render_rates_from_deltas():
    from spark_rapids_ml_tpu.tools.top import render

    health = {
        "id": "abc", "uptime_s": 10.0, "queue_depth": 2,
        "staged_bytes": 2048, "active_jobs": 1, "served_models": 0,
        "busy": True, "busy_reason": "too many connections",
    }

    def snap_at(n):
        return {
            "srml_daemon_requests_total": {
                "type": "counter", "help": "", "samples": [
                    {"labels": {"op": "feed", "outcome": "ok"}, "value": n},
                ],
            },
            "srml_daemon_request_seconds": {
                "type": "histogram", "help": "", "samples": [
                    {"labels": {"op": "feed"},
                     "buckets": {"0.1": n, "+Inf": n}, "sum": 0.01 * n,
                     "count": n},
                ],
            },
        }

    screen = render(health, snap_at(30), snap_at(10), dt=2.0)
    assert "BUSY: too many connections" in screen
    line = [ln for ln in screen.splitlines() if ln.startswith("feed")][0]
    assert "30" in line          # total
    assert "10.0" in line        # (30-10)/2 per second
    assert "2.0KB" in screen     # staged bytes humanized
