// Minimal dependency-free client for the spark_rapids_ml_tpu data-plane
// daemon, written from docs/protocol.md ALONE — no Arrow, no JSON
// library, nothing beyond POSIX sockets and the C++ standard library.
// It exists as the existence proof for the "~100 lines in any language"
// interop claim (README "Scope: PySpark, not Scala"): the feeding logic
// itself is ~100 lines; the rest is a tiny JSON value scanner.
//
// Protocol recap (docs/protocol.md):
//   frame    = 4-byte big-endian length + payload
//   request  = one JSON frame [+ raw array frames for `feed_raw`]
//   response = one JSON frame [+ one raw little-endian C-contiguous
//              buffer frame per entry of its "arrays" spec, in order]
//
// Session: ping → feed_raw two partitions (+ commit: the exactly-once
// path) → finalize PCA → print the returned arrays for the caller to
// check (tests/test_cpp_client.py compares against the local oracle).
//
// Usage: minimal_client HOST PORT [N D K]

#include <arpa/inet.h>
#include <netdb.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

static int die(const std::string& msg) {
  std::fprintf(stderr, "minimal_client: %s\n", msg.c_str());
  std::exit(1);
}

// ---- framing ----------------------------------------------------------

static void send_all(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t w = ::send(fd, p, n, 0);
    if (w <= 0) die("send failed");
    p += w;
    n -= static_cast<size_t>(w);
  }
}

static void recv_all(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) die("connection closed mid-frame");
    p += r;
    n -= static_cast<size_t>(r);
  }
}

static void send_frame(int fd, const void* payload, size_t n) {
  uint32_t be = htonl(static_cast<uint32_t>(n));
  send_all(fd, &be, 4);
  send_all(fd, payload, n);
}

static std::string recv_frame(int fd) {
  uint32_t be = 0;
  recv_all(fd, &be, 4);
  std::string payload(ntohl(be), '\0');
  if (!payload.empty()) recv_all(fd, payload.data(), payload.size());
  return payload;
}

// ---- a tiny JSON value scanner (enough for the daemon's responses) ----

// Returns the raw JSON value text for `"key":` at the top level of an
// object (daemon responses are flat except for the "arrays" list).
static std::string json_value(const std::string& js, const std::string& key,
                              size_t from = 0) {
  const std::string needle = "\"" + key + "\"";
  size_t k = js.find(needle, from);
  if (k == std::string::npos) return "";
  size_t i = js.find(':', k + needle.size());
  if (i == std::string::npos) return "";
  ++i;
  while (i < js.size() && js[i] == ' ') ++i;
  size_t start = i;
  int depth = 0;
  bool in_str = false;
  for (; i < js.size(); ++i) {
    char c = js[i];
    if (in_str) {
      if (c == '\\') ++i;
      else if (c == '"') in_str = false;
      continue;
    }
    if (c == '"') in_str = true;
    else if (c == '[' || c == '{') ++depth;
    else if (c == ']' || c == '}') {
      if (depth == 0) break;
      --depth;
    } else if ((c == ',') && depth == 0) break;
  }
  return js.substr(start, i - start);
}

struct ArraySpec {
  std::string name, dtype;
  std::vector<long> shape;
};

// Parse the ordered "arrays" spec list: [{"name": .., "dtype": ..,
// "shape": [..]}, ...]
static std::vector<ArraySpec> parse_specs(const std::string& js) {
  std::vector<ArraySpec> out;
  std::string list = json_value(js, "arrays");
  size_t pos = 0;
  while (true) {
    size_t open = list.find('{', pos);
    if (open == std::string::npos) break;
    size_t close = list.find('}', open);
    std::string obj = list.substr(open, close - open + 1);
    ArraySpec spec;
    std::string nm = json_value(obj, "name");
    spec.name = nm.substr(1, nm.size() - 2);  // strip quotes
    std::string dt = json_value(obj, "dtype");
    spec.dtype = dt.substr(1, dt.size() - 2);
    std::string sh = json_value(obj, "shape");
    for (size_t i = 1; i < sh.size();) {  // inside [ ... ]
      char* end = nullptr;
      long v = std::strtol(sh.c_str() + i, &end, 10);
      if (end == sh.c_str() + i) break;
      spec.shape.push_back(v);
      i = static_cast<size_t>(end - sh.c_str()) + 1;
    }
    out.push_back(spec);
    pos = close + 1;
  }
  return out;
}

static std::string roundtrip_json(int fd, const std::string& req) {
  send_frame(fd, req.data(), req.size());
  std::string resp = recv_frame(fd);
  if (json_value(resp, "ok") != "true")
    die("daemon error: " + json_value(resp, "error") + " for " + req);
  return resp;
}

int main(int argc, char** argv) {
  if (argc < 3) die("usage: minimal_client HOST PORT [N D K]");
  long N = argc > 3 ? std::strtol(argv[3], nullptr, 10) : 512;
  long D = argc > 4 ? std::strtol(argv[4], nullptr, 10) : 8;
  long K = argc > 5 ? std::strtol(argv[5], nullptr, 10) : 2;

  addrinfo hints{}, *res = nullptr;
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  if (getaddrinfo(argv[1], argv[2], &hints, &res) != 0 || !res)
    die("cannot resolve host");
  int fd = ::socket(res->ai_family, res->ai_socktype, res->ai_protocol);
  if (fd < 0 || ::connect(fd, res->ai_addr, res->ai_addrlen) != 0)
    die("cannot connect");
  freeaddrinfo(res);

  // 1. ping — the version handshake (v-exempt; server echoes its v).
  std::string pong = roundtrip_json(fd, "{\"op\": \"ping\"}");
  if (json_value(pong, "v") != "1") die("server does not speak v1");
  std::printf("ping ok v=1\n");

  // 2. Deterministic integer data (LCG; mirrored by the test's oracle),
  //    fed as TWO partitions through the exactly-once feed_raw/commit
  //    path in float64 raw frames.
  std::vector<double> x(static_cast<size_t>(N) * D);
  uint32_t state = 12345;
  for (auto& v : x) {
    state = state * 1664525u + 1013904223u;  // Numerical Recipes LCG
    v = static_cast<double>(static_cast<long>((state >> 16) % 17) - 8);
  }
  long half = N / 2;
  for (int part = 0; part < 2; ++part) {
    long rows = part == 0 ? half : N - half;
    const double* ptr = x.data() + (part == 0 ? 0 : half * D);
    char head[512];
    std::snprintf(head, sizeof(head),
                  "{\"v\": 1, \"op\": \"feed_raw\", \"job\": \"cpp-demo\", "
                  "\"algo\": \"pca\", \"n_cols\": %ld, \"partition\": %d, "
                  "\"arrays\": [{\"name\": \"x\", \"dtype\": \"float64\", "
                  "\"shape\": [%ld, %ld]}]}",
                  D, part, rows, D);
    send_frame(fd, head, std::strlen(head));
    send_frame(fd, ptr, static_cast<size_t>(rows) * D * sizeof(double));
    std::string resp = recv_frame(fd);
    if (json_value(resp, "ok") != "true")
      die("feed_raw rejected: " + json_value(resp, "error"));
    char commit[256];
    std::snprintf(commit, sizeof(commit),
                  "{\"v\": 1, \"op\": \"commit\", \"job\": \"cpp-demo\", "
                  "\"partition\": %d}", part);
    roundtrip_json(fd, commit);
  }

  // 3. finalize → JSON header + one raw frame per spec entry, in order.
  char fin[256];
  std::snprintf(fin, sizeof(fin),
                "{\"v\": 1, \"op\": \"finalize\", \"job\": \"cpp-demo\", "
                "\"params\": {\"k\": %ld}}", K);
  std::string header = roundtrip_json(fd, fin);
  std::printf("rows %s\n", json_value(header, "rows").c_str());
  for (const ArraySpec& spec : parse_specs(header)) {
    std::string buf = recv_frame(fd);
    if (spec.dtype != "float64") die("unexpected dtype " + spec.dtype);
    std::printf("array %s", spec.name.c_str());
    for (long s : spec.shape) std::printf(" %ld", s);
    std::printf(" :");
    const double* vals = reinterpret_cast<const double*>(buf.data());
    for (size_t i = 0; i < buf.size() / sizeof(double); ++i)
      std::printf(" %.17g", vals[i]);
    std::printf("\n");
  }
  ::close(fd);
  return 0;
}
