"""sparksim — a test double for the PySpark DataFrame surface the Spark
wrappers use, with REAL task isolation.

pyspark cannot be installed in this environment (no package installs; see
README "Spark integration testing" for the policy), so the integration
tests execute the wrappers' executor-side closures through this harness
instead. It is deliberately NOT a mock: partition tasks run in separate
OS processes (spawned, nothing shared with the driver), get their task
identity the same way a real executor does (``SRML_PARTITION_ID`` /
``SRML_ATTEMPT`` — the documented fallback of
spark.daemon_session.task_context), talk to the daemon over real TCP, and
are retried on failure with a bumped attempt number exactly like Spark's
at-least-once task scheduler. Failure injection (die after N feeds) and
duplicate/speculative execution are first-class so the exactly-once
commit protocol is exercised the way Spark would exercise it.

Surface implemented (what spark/estimator.py touches):
``sparkSession.conf.get``, ``select``, ``limit``, ``persist``/
``unpersist``, ``columns``, ``toArrow``, ``mapInArrow(fn, schema)`` +
``collect``, ``count``. Rows returned by ``collect`` support ``row[key]``.
"""

from __future__ import annotations

import multiprocessing as mp
import os
import threading
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np
import pyarrow as pa

_FORK_CTX = None


def _task_mp_context():
    """Forkserver with the heavy imports preloaded: each simulated task
    still gets a fresh OS process (nothing shared with the driver), but
    forks from a template that already paid the ~3 s jax/pyarrow import —
    the round-2 review measured the per-task import tax as the dominant
    cost of this suite (445 s for 10 tests)."""
    global _FORK_CTX
    if _FORK_CTX is None:
        ctx = mp.get_context("forkserver")
        ctx.set_forkserver_preload(
            [
                "numpy",
                "pyarrow",
                "jax",
                "spark_rapids_ml_tpu",
                "spark_rapids_ml_tpu.spark.estimator",
                "spark_rapids_ml_tpu.serve.client",
            ]
        )
        _FORK_CTX = ctx
    return _FORK_CTX


class SimRow(dict):
    """Row supporting row["col"] and row.col."""

    __getattr__ = dict.__getitem__


class _SimConf:
    def __init__(self, conf: Dict[str, str]):
        self._conf = dict(conf)

    def get(self, key: str, default=None):
        if key in self._conf:
            return self._conf[key]
        if default is not None:
            return default
        raise KeyError(key)

    def set(self, key: str, value: str):
        self._conf[key] = value


class SimSparkSession:
    def __init__(self, conf: Optional[Dict[str, str]] = None):
        self.conf = _SimConf(conf or {})
        # rows shipped driver-side via toArrow/toPandas/plain collect —
        # the "no collect-to-driver" assertions read this
        self.driver_rows_materialized = 0


def _dying_iter(batches, fail_after):
    """Deliver ``fail_after`` batches, then die MID-ITERATION — the way a
    real executor loss looks to the task body: the feed loop has staged
    rows at the daemon and never reaches its commit."""
    for i, b in enumerate(batches):
        if i >= fail_after:
            raise RuntimeError("injected executor death mid-partition")
        yield b
    raise RuntimeError("injected executor death at partition end")


def _run_task(fn, batches, pid, attempt, fail_after, out_q, env=None):
    """Worker-process entry: impersonate one Spark task.

    ``env``: driver-side SRML_*/JAX_* snapshot taken at task LAUNCH.
    Forkserver children freeze os.environ at forkserver start (unlike
    spawn), so without this pass-through a test's monkeypatched executor
    env var (e.g. SRML_DAEMON_ADDRESS) would silently not reach tasks —
    and a var UNSET driver-side must be unset here too, or a frozen
    template value leaks into later tests (order-dependent greens)."""
    env = env or {}
    for k in list(os.environ):
        if k.startswith(("SRML_", "JAX_")) and k not in env:
            del os.environ[k]
    for k, v in env.items():
        os.environ[k] = v
    os.environ["SRML_PARTITION_ID"] = str(pid)
    os.environ["SRML_ATTEMPT"] = str(attempt)
    # The dev image's sitecustomize pins jax to the tunneled TPU platform,
    # beating the JAX_PLATFORMS env the test session set — re-pin here so
    # worker-side transforms run on the same (virtual CPU) backend as the
    # test session instead of compiling over the tunnel.
    import jax

    jax.config.update("jax_platforms", os.environ.get("JAX_PLATFORMS", "cpu"))
    if os.environ.get("JAX_ENABLE_X64", "").lower() in ("true", "1"):
        jax.config.update("jax_enable_x64", True)
    try:
        it = (
            _dying_iter(batches, fail_after)
            if fail_after is not None
            else iter(batches)
        )
        results = [b for b in fn(it)]
        out_q.put(("ok", pid, [b.to_pydict() for b in results]))
    except Exception as e:  # noqa: BLE001 — faithfully report any task death
        out_q.put(("err", pid, repr(e)))


class SimDataFrame:
    """An in-memory, partitioned DataFrame executing tasks in processes."""

    def __init__(
        self,
        partitions: Sequence[pa.Table],
        session: Optional[SimSparkSession] = None,
        fail_plan: Optional[Dict[int, List[Optional[int]]]] = None,
        speculative: Optional[Sequence[int]] = None,
        max_attempts: int = 4,
        env_plan: Optional[Dict[int, Dict[str, str]]] = None,
        concurrency: Optional[int] = None,
    ):
        self._parts = [
            p if isinstance(p, pa.Table) else pa.Table.from_batches([p])
            for p in partitions
        ]
        self.sparkSession = session or SimSparkSession()
        # fail_plan: partition -> list of per-attempt injections; entry i is
        # "fail after N batches" for attempt i (None = run to completion).
        self._fail_plan = fail_plan or {}
        # speculative: partitions to ALSO run a duplicate copy of after the
        # primary succeeds (Spark speculation: same partition, new attempt).
        self._speculative = list(speculative or [])
        self._max_attempts = max_attempts
        # env_plan: partition -> extra task env (models executors on
        # DIFFERENT hosts: e.g. a per-executor SRML_DAEMON_ADDRESS that
        # routes the task to its host-local daemon). A LIST value is
        # per-ATTEMPT env — attempt i gets entry min(i, last) — which
        # models Spark rescheduling a failed task onto a different host
        # (the elastic-fit suite reroutes a dead daemon's partitions to
        # survivors this way).
        self._env_plan = env_plan or {}
        # Partition tasks run CONCURRENTLY like Spark's scheduler (each
        # still its own OS process); retries stay sequential within a
        # partition. concurrency=1 restores strictly ordered commits —
        # the mode the float-data bitwise-determinism tests need, since
        # concurrent commit arrival reorders f32 folds exactly as real
        # Spark would.
        self._concurrency = (
            concurrency if concurrency is not None
            else min(4, max(1, len(self._parts)))
        )
        self._mapped: Optional[Callable] = None

    # -- the DataFrame surface the wrappers use ---------------------------

    @property
    def columns(self) -> List[str]:
        return list(self._parts[0].schema.names)

    def select(self, *cols) -> "SimDataFrame":
        out = SimDataFrame(
            [p.select(list(cols)) for p in self._parts],
            self.sparkSession,
            self._fail_plan,
            self._speculative,
            self._max_attempts,
            self._env_plan,
            self._concurrency,
        )
        return out

    def limit(self, n: int) -> "SimDataFrame":
        taken, remaining = [], n
        for p in self._parts:
            if remaining <= 0:
                break
            t = p.slice(0, min(remaining, p.num_rows))
            taken.append(t)
            remaining -= t.num_rows
        return SimDataFrame(taken or [self._parts[0].slice(0, 0)], self.sparkSession)

    def persist(self) -> "SimDataFrame":
        return self

    def unpersist(self) -> "SimDataFrame":
        return self

    def count(self) -> int:
        return sum(p.num_rows for p in self._parts)

    def toArrow(self) -> pa.Table:
        t = pa.concat_tables(self._parts)
        self.sparkSession.driver_rows_materialized += t.num_rows
        return t

    def toPandas(self):
        return self.toArrow().to_pandas()

    def mapInArrow(self, fn, schema) -> "SimDataFrame":
        out = SimDataFrame(
            self._parts, self.sparkSession, self._fail_plan,
            self._speculative, self._max_attempts, self._env_plan,
            self._concurrency,
        )
        out._mapped = fn
        return out

    def collect(self) -> List[SimRow]:
        if self._mapped is None:
            table = self.toArrow()
            return [SimRow(r) for r in table.to_pylist()]
        return self._run_tasks()

    # -- the task scheduler ------------------------------------------------

    def _run_tasks(self) -> List[SimRow]:
        ctx = _task_mp_context()
        results: List[Optional[List[SimRow]]] = [None] * len(self._parts)
        errors: List[BaseException] = []
        gate = threading.Semaphore(self._concurrency)

        def run_partition(pid: int, part: pa.Table) -> None:
            with gate:
                try:
                    batches = part.to_batches(
                        max_chunksize=max(1, part.num_rows // 2 or 1)
                    )
                    plan = self._fail_plan.get(pid, [])
                    result, last_err = None, None
                    for attempt in range(self._max_attempts):
                        fail_after = plan[attempt] if attempt < len(plan) else None
                        result, last_err = self._one_attempt(
                            ctx, pid, attempt, batches, fail_after
                        )
                        if result is not None:
                            break
                    if result is None:
                        # Spark's job-abort message carries the most recent
                        # task failure — the operator must see WHY (e.g. a
                        # peer daemon rejecting unseeded kmeans feeds), not
                        # just that attempts ran out.
                        raise RuntimeError(
                            f"partition {pid} failed {self._max_attempts} "
                            "attempts (Spark would abort the job here); "
                            f"most recent failure: {last_err}"
                        )
                    results[pid] = result
                    if pid in self._speculative:
                        # a speculative duplicate finishing AFTER the
                        # original — its output is discarded (Spark keeps
                        # the first winner), but its daemon traffic
                        # happens for real
                        self._one_attempt(ctx, pid, attempt + 1, batches, None)
                except BaseException as e:  # noqa: BLE001 — surface on main
                    errors.append(e)

        threads = [
            threading.Thread(target=run_partition, args=(pid, part))
            for pid, part in enumerate(self._parts)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]
        rows: List[SimRow] = []
        for result in results:
            rows.extend(result or [])
        return rows

    def _one_attempt(self, ctx, pid, attempt, batches, fail_after):
        q = ctx.Queue()
        env = {
            k: v for k, v in os.environ.items()
            if k.startswith(("SRML_", "JAX_"))
        }
        extra = self._env_plan.get(pid, {})
        if isinstance(extra, (list, tuple)):
            extra = extra[min(attempt, len(extra) - 1)] if extra else {}
        env.update(extra)
        proc = ctx.Process(
            target=_run_task,
            args=(self._mapped, list(batches), pid, attempt, fail_after, q, env),
        )
        proc.start()
        try:
            status, rpid, payload = q.get(timeout=120)
        except Exception:
            proc.terminate()
            raise
        finally:
            proc.join(timeout=30)
        if status != "ok":
            return None, payload  # payload = repr of the task's exception
        out = []
        for d in payload:
            n = len(next(iter(d.values()))) if d else 0
            for i in range(n):
                out.append(SimRow({k: v[i] for k, v in d.items()}))
        return out, None


def simdf_from_numpy(
    x: np.ndarray,
    n_partitions: int,
    features_col: str = "features",
    label: Optional[np.ndarray] = None,
    label_col: str = "label",
    session: Optional[SimSparkSession] = None,
    **kw,
) -> SimDataFrame:
    """Build a partitioned SimDataFrame with an ArrayType-like features
    column (list<float>), the reference's input contract (README.md:26-37)."""
    from spark_rapids_ml_tpu.bridge.arrow import matrix_to_list_column

    parts = []
    xs = np.array_split(np.asarray(x), n_partitions)
    ys = (
        np.array_split(np.asarray(label), n_partitions)
        if label is not None
        else [None] * n_partitions
    )
    for xi, yi in zip(xs, ys):
        cols = {features_col: matrix_to_list_column(xi)}
        if yi is not None:
            cols[label_col] = pa.array(np.asarray(yi).reshape(-1))
        parts.append(pa.table(cols))
    return SimDataFrame(parts, session=session, **kw)
