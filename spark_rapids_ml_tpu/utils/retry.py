"""Host-side failure handling for the data-feeding path.

The reference delegates all fault tolerance to Spark task retry — its
map/reduce stages are pure and recompute-safe (SURVEY.md §5 "failure
detection"). In this framework the equivalents are:

* the sharded fit programs are pure functions of their inputs (recompute-
  safe by construction — rerunning a failed fit is always sound);
* the host-side feeding loop (Arrow IO, host→device transfer) is the part
  that sees transient failures (storage hiccups, preemptions), handled
  here with bounded retries + backoff.

Backoff is decorrelated-jittered (the AWS "exponential backoff and
jitter" result): pure exponential backoff synchronizes retries across a
fleet of executors — after a daemon restart every task would hammer it
again on the same schedule (thundering herd). Jittered delays decorrelate
the herd; ``max_delay_s`` caps the wait so a long outage doesn't park
tasks for minutes; ``deadline_s`` bounds the TOTAL time an op may spend
retrying (Spark's own task timeout should fire on the task, not on a
retry loop that never gives up).
"""

from __future__ import annotations

import random
import time
from typing import Callable, Optional, Tuple, Type, TypeVar

from spark_rapids_ml_tpu.utils.logging import get_logger

_logger = get_logger(__name__)

T = TypeVar("T")


def decorrelated_jitter(
    prev_delay_s: float,
    base_delay_s: float,
    max_delay_s: float,
    rng: Optional[random.Random] = None,
) -> float:
    """Next backoff delay: ``min(cap, uniform(base, prev * 3))``.

    The decorrelated-jitter rule — each client's sequence wanders
    independently instead of marching in lockstep powers of two, so
    retries from many executors spread out instead of arriving in waves.
    """
    draw = (rng or random).uniform(
        base_delay_s, max(prev_delay_s, base_delay_s) * 3.0
    )
    return min(max_delay_s, draw)


def with_retries(
    fn: Callable[[], T],
    max_attempts: int = 3,
    retry_on: Tuple[Type[BaseException], ...] = (OSError, IOError),
    base_delay_s: float = 0.5,
    backoff: float = 2.0,
    max_delay_s: float = 30.0,
    deadline_s: Optional[float] = None,
    rng: Optional[random.Random] = None,
) -> T:
    """Run ``fn`` with bounded retries and decorrelated-jitter backoff.

    Analogous to ``spark.task.maxFailures`` for the host feeding loop;
    only exceptions in ``retry_on`` are retried, everything else raises
    immediately (a deterministic error will not fix itself).

    ``backoff`` is kept for signature compatibility but the delay
    sequence is decorrelated-jittered and capped at ``max_delay_s`` (see
    module docstring — pure exponential backoff synchronizes executors).
    ``deadline_s`` bounds total time across all attempts: when the next
    sleep would cross it, the last error raises instead. ``rng``: a
    seeded ``random.Random`` for deterministic tests.
    """
    attempt = 0
    delay = base_delay_s
    start = time.monotonic()
    while True:
        try:
            return fn()
        except retry_on as e:
            attempt += 1
            if attempt >= max_attempts:
                raise
            delay = decorrelated_jitter(delay, base_delay_s, max_delay_s, rng)
            if (
                deadline_s is not None
                and time.monotonic() - start + delay > deadline_s
            ):
                _logger.warning(
                    "retry deadline %.1fs exhausted after %d attempts: %s",
                    deadline_s, attempt, e,
                )
                raise
            _logger.warning(
                "retryable failure (attempt %d/%d, next in %.2fs): %s",
                attempt, max_attempts, delay, e,
            )
            time.sleep(delay)
