"""Nearest-neighbor tests: exact vs sklearn brute force, IVF recall."""

import jax.numpy as jnp
import numpy as np
import pytest

from spark_rapids_ml_tpu import (
    ApproximateNearestNeighbors,
    NearestNeighbors,
    NearestNeighborsModel,
)
from spark_rapids_ml_tpu.parallel.mesh import make_mesh


@pytest.fixture
def db_and_queries(rng):
    db = rng.normal(size=(500, 16))
    queries = rng.normal(size=(20, 16))
    return db, queries


def _sklearn_knn(db, queries, k):
    from oracles import knn_brute

    return knn_brute(db, queries, k)


def test_exact_matches_sklearn(db_and_queries, mesh8):
    db, queries = db_and_queries
    k = 7
    model = NearestNeighbors(mesh=mesh8).setK(k).fit({"features": db})
    dists, idx = model.kneighbors(queries)
    ref_d, ref_i = _sklearn_knn(db, queries, k)
    np.testing.assert_array_equal(idx, ref_i)
    np.testing.assert_allclose(dists, ref_d, atol=1e-8)


def test_exact_shard_invariance(db_and_queries):
    db, queries = db_and_queries
    k = 5
    outs = []
    for n in (1, 8):
        model = NearestNeighbors(mesh=make_mesh(data=n, model=1)).setK(k).fit(
            {"features": db}
        )
        outs.append(model.kneighbors(queries))
    np.testing.assert_array_equal(outs[0][1], outs[1][1])
    np.testing.assert_allclose(outs[0][0], outs[1][0], atol=1e-8)


def test_exact_uneven_db_rows(mesh8, rng):
    # 101 rows: padding rows must never appear as neighbors.
    db = rng.normal(size=(101, 4))
    queries = db[:10]
    model = NearestNeighbors(mesh=mesh8).setK(3).fit({"features": db})
    dists, idx = model.kneighbors(queries)
    assert np.all(idx < 101)
    # Self is always the nearest neighbor at distance 0.
    np.testing.assert_array_equal(idx[:, 0], np.arange(10))
    # Gram-trick distances: ‖x‖²+‖y‖²−2xy is only ~eps-accurate at 0.
    np.testing.assert_allclose(dists[:, 0], 0.0, atol=1e-6)


def test_exact_k_exceeds_shard_size(mesh8, rng):
    # Regression: k larger than the per-device shard (ceil(100/8)=13) must
    # work as long as k <= total rows.
    db = rng.normal(size=(100, 6))
    queries = rng.normal(size=(5, 6))
    model = NearestNeighbors(mesh=mesh8).setK(20).fit({"features": db})
    dists, idx = model.kneighbors(queries)
    ref_d, ref_i = _sklearn_knn(db, queries, 20)
    np.testing.assert_array_equal(idx, ref_i)
    np.testing.assert_allclose(dists, ref_d, atol=1e-8)


def test_ann_k_validation(rng, mesh8):
    db = rng.normal(size=(160, 8))
    ann = (
        ApproximateNearestNeighbors(mesh=mesh8)
        .setK(5)
        .setNlist(16)
        .setNprobe(1)
        .fit({"features": db})
    )
    with pytest.raises(ValueError):
        ann.kneighbors(db[:3], k=0)
    with pytest.raises(ValueError):
        ann.kneighbors(db[:3], k=161)
    # Regression: candidate pool (nprobe*maxlen) too small for k must raise
    # with actionable advice, not crash in top_k.
    with pytest.raises(ValueError, match="nprobe"):
        ann.kneighbors(db[:3], k=100)


def test_exact_k_validation(db_and_queries, mesh8):
    db, queries = db_and_queries
    model = NearestNeighbors(mesh=mesh8).setK(5).fit({"features": db})
    with pytest.raises(ValueError):
        model.kneighbors(queries, k=0)
    with pytest.raises(ValueError):
        model.kneighbors(queries, k=len(db) + 1)


def test_exact_persistence(db_and_queries, mesh8, tmp_path):
    db, queries = db_and_queries
    model = NearestNeighbors(mesh=mesh8).setK(4).fit({"features": db})
    path = str(tmp_path / "nn")
    model.save(path)
    loaded = NearestNeighborsModel.load(path)
    a = model.kneighbors(queries)
    b = loaded.kneighbors(queries)
    np.testing.assert_array_equal(a[1], b[1])


def test_ivf_flat_recall(rng, mesh8):
    # Clustered data (IVF's favorable case): recall@10 should be high.
    centers = rng.normal(size=(16, 24)) * 8
    db = np.concatenate([c + rng.normal(size=(120, 24)) for c in centers])
    queries = np.concatenate([c + rng.normal(size=(3, 24)) for c in centers])
    k = 10
    ann = (
        ApproximateNearestNeighbors(mesh=mesh8)
        .setK(k)
        .setNlist(16)
        .setNprobe(4)
        .fit({"features": db})
    )
    dists, idx = ann.kneighbors(queries)
    ref_d, ref_i = _sklearn_knn(db, queries, k)
    recall = np.mean(
        [len(set(idx[i]) & set(ref_i[i])) / k for i in range(len(queries))]
    )
    assert recall > 0.9, f"IVF recall@{k} too low: {recall}"
    # Distances for true positives must agree.
    assert np.all(np.isfinite(dists))


def test_ivf_large_k_exceeds_block_width(rng):
    # k larger than one scan block's candidate pool (LIST_BLOCK * maxlen):
    # the per-block top-k must clamp to the block width and recover full k
    # in the cross-block merge, not crash. A hand-built index pins maxlen=2
    # so the clamp branch (blk_k = 64 < k = 100) is guaranteed to trigger —
    # a fitted quantizer can't promise that.
    from spark_rapids_ml_tpu.models.knn import IVFFlatIndex, _ivf_query_fn

    db = rng.normal(size=(256, 8)).astype(np.float32)
    queries = rng.normal(size=(5, 8)).astype(np.float32)
    k, nlist, maxlen = 100, 128, 2
    lists = db.reshape(nlist, maxlen, 8)
    list_ids = np.arange(256, dtype=np.int64).reshape(nlist, maxlen)
    index = IVFFlatIndex(
        centroids=lists.mean(axis=1),
        lists=lists,
        list_ids=list_ids,
        list_mask=np.ones((nlist, maxlen), np.float32),
    )
    query = _ivf_query_fn(k, nlist, "float32", "float32")  # probe all lists
    dists, idx = query(
        jnp.asarray(index.centroids),
        jnp.asarray(index.lists),
        jnp.asarray(index.list_ids),
        jnp.asarray(index.list_mask),
        jnp.asarray(queries),
    )
    _, ref_i = _sklearn_knn(db, queries, k)
    np.testing.assert_array_equal(np.sort(idx, axis=1), np.sort(ref_i, axis=1))


def test_ivf_nprobe_all_is_exact(rng, mesh8):
    db = rng.normal(size=(200, 8))
    queries = rng.normal(size=(10, 8))
    k = 5
    ann = (
        ApproximateNearestNeighbors(mesh=mesh8)
        .setK(k)
        .setNlist(8)
        .setNprobe(8)  # probe everything -> exact
        .fit({"features": db})
    )
    _, idx = ann.kneighbors(queries)
    _, ref_i = _sklearn_knn(db, queries, k)
    np.testing.assert_array_equal(np.sort(idx, axis=1), np.sort(ref_i, axis=1))


def test_ivf_bucketed_matches_dense_no_drops(rng):
    # With slack high enough that C == q, no (query, list) pair can be
    # dropped, so on this CPU test backend (where approx_min_k lowers to an
    # exact sort) the bucketed executor must return exactly the dense
    # executor's neighbor sets. On real TPUs the bucketed shortlist is
    # approximate by design (recall_target=0.95 + exact rerank) and only a
    # recall bound holds — this equality is a CPU-only algebraic check of
    # the bucketing/gather-back plumbing, not a cross-backend contract.
    from spark_rapids_ml_tpu.models.knn import build_ivf_flat, _ivf_query_fn

    db = rng.normal(size=(2048, 16)).astype(np.float32)
    queries = jnp.asarray(rng.normal(size=(64, 16)).astype(np.float32))
    index = build_ivf_flat(db, nlist=64, seed=0)
    dev = [
        jnp.asarray(index.centroids, jnp.float32),
        jnp.asarray(index.lists),
        jnp.asarray(index.list_ids),
        jnp.asarray(index.list_mask),
    ]
    k, nprobe = 10, 8  # nprobe*4 < nlist -> auto would pick bucketed
    dense = _ivf_query_fn(k, nprobe, "float32", "float32", mode="dense")
    bucketed = _ivf_query_fn(
        k, nprobe, "float32", "float32", mode="bucketed", slack=1e9
    )
    dd, di = dense(*dev, queries)
    bd, bi = bucketed(*dev, queries)
    np.testing.assert_array_equal(
        np.sort(np.asarray(di), axis=1), np.sort(np.asarray(bi), axis=1)
    )
    np.testing.assert_allclose(
        np.sort(np.asarray(dd), axis=1), np.sort(np.asarray(bd), axis=1),
        rtol=1e-5, atol=1e-5,
    )


@pytest.mark.parametrize("rerank", [False, True])
@pytest.mark.skipif(
    __import__("jax").default_backend() != "cpu",
    reason="CPU-only algebraic check: on real TPUs the XLA arm's "
    "approx_min_k probe/selection are genuinely approximate, so exact "
    "equality with the fused (exact) arm only holds where they lower to "
    "exact sorts",
)
def test_ivf_bucketed_fused_matches_xla(rng, rerank):
    # The fused Pallas scan+selection (interpret mode off-TPU) must agree
    # with the XLA einsum+approx_min_k path wherever the latter is exact:
    # on CPU approx_min_k lowers to an exact sort, so with no capacity
    # drops (slack=1e9) both paths return identical neighbor sets. Also
    # covers lists holding FEWER valid rows than the selection width
    # (nlist=128 over 1024 rows -> sparse lists), where the kernel emits
    # sentinel rows that must map to the (+inf, -1) missing contract.
    from spark_rapids_ml_tpu.models.knn import build_ivf_flat, _ivf_query_fn

    db = rng.normal(size=(1024, 16)).astype(np.float32)
    queries = jnp.asarray(rng.normal(size=(64, 16)).astype(np.float32))
    index = build_ivf_flat(db, nlist=128, seed=0)
    dev = [
        jnp.asarray(index.centroids, jnp.float32),
        jnp.asarray(index.lists),
        jnp.asarray(index.list_ids),
        jnp.asarray(index.list_mask),
    ]
    k, nprobe = 10, 16
    kw = dict(mode="bucketed", slack=1e9, rerank=rerank)
    xla = _ivf_query_fn(k, nprobe, "float32", "float32", fused="off", **kw)
    fus = _ivf_query_fn(k, nprobe, "float32", "float32", fused="on", **kw)
    xd, xi = xla(*dev, queries)
    fd, fi = fus(*dev, queries)
    np.testing.assert_array_equal(
        np.sort(np.asarray(xi), axis=1), np.sort(np.asarray(fi), axis=1)
    )
    # Value tolerance covers the fused kernels' packed-key mantissa floor
    # (probe_d2 and scan scores are floored within a relative
    # 2^(ceil(log2(n))-24) — ops/pallas_kernels.py; neighbor IDS above
    # must still match exactly).
    finite = np.isfinite(np.asarray(xd))
    np.testing.assert_allclose(
        np.sort(np.asarray(fd), axis=1)[finite],
        np.sort(np.asarray(xd), axis=1)[finite],
        rtol=5e-4, atol=5e-4,
    )


def test_ivf_bucketed_recall_default_slack(rng):
    # Clustered data + clustered queries (the capacity-pressure case):
    # default slack must still deliver high recall through the estimator
    # path, which auto-selects the bucketed executor (nprobe*4 < nlist).
    centers = rng.normal(size=(32, 24)) * 8
    db = np.concatenate([c + rng.normal(size=(120, 24)) for c in centers])
    queries = np.concatenate([c + rng.normal(size=(4, 24)) for c in centers])
    k = 10
    ann = (
        ApproximateNearestNeighbors()
        .setK(k)
        .setNlist(32)
        .setNprobe(4)
        .fit({"features": db})
    )
    dists, idx = ann.kneighbors(queries)
    _, ref_i = _sklearn_knn(db, queries, k)
    recall = np.mean(
        [len(set(idx[i]) & set(ref_i[i])) / k for i in range(len(queries))]
    )
    assert recall > 0.85, f"bucketed IVF recall@{k} too low: {recall}"


def test_ivf_bucketed_correlated_queries_degrade_gracefully(rng):
    # 256 IDENTICAL queries all probing the same nprobe lists: per-list
    # capacity (C=64) cannot hold them all, but the rotated eviction order
    # must leave every query covering at least one probed list — no query
    # may come back empty (all -1), the old failure mode.
    from spark_rapids_ml_tpu.models.knn import build_ivf_flat, _ivf_query_fn

    centers = rng.normal(size=(32, 12)) * 10
    db = np.concatenate([c + rng.normal(size=(100, 12)) for c in centers]).astype(
        np.float32
    )
    queries = np.broadcast_to(centers[0].astype(np.float32), (256, 12)).copy()
    index = build_ivf_flat(db, nlist=32, seed=0)
    q = _ivf_query_fn(10, 4, "float32", "float32", mode="bucketed")
    _, idx = q(
        jnp.asarray(index.centroids, jnp.float32),
        jnp.asarray(index.lists),
        jnp.asarray(index.list_ids),
        jnp.asarray(index.list_mask),
        jnp.asarray(queries),
    )
    idx = np.asarray(idx)
    assert np.all(idx >= 0), f"{np.sum(np.all(idx < 0, axis=1))} queries empty"


def test_ivf_padding_queries_do_not_evict_real_ones(rng):
    # 65 real queries pad internally to 128; the 63 zero-vector pad rows
    # all probe the lists nearest the origin and must lose every capacity
    # contest (rank forced past nprobe), leaving real queries' results
    # identical to an unpadded 64-query call on the shared prefix.
    centers = rng.normal(size=(32, 12)) * 10
    db = np.concatenate([c + rng.normal(size=(100, 12)) for c in centers]).astype(
        np.float32
    )
    queries = (centers[rng.integers(0, 32, size=65)] + rng.normal(size=(65, 12))).astype(
        np.float32
    )
    ann = (
        ApproximateNearestNeighbors()
        .setK(10)
        .setNlist(32)
        .setNprobe(4)
        .fit({"features": db})
    )
    _, idx65 = ann.kneighbors(queries)  # padded to 128 internally
    _, idx64 = ann.kneighbors(queries[:64])  # no padding
    np.testing.assert_array_equal(idx65[:64], idx64)
    assert np.all(np.asarray(idx65) >= 0)


def test_ivf_sharded_index_matches_unsharded(rng, mesh8):
    # Lists sharded over 8 devices: results must match the single-device
    # bucketed executor (CPU backend: both exact given no capacity drops).
    centers = rng.normal(size=(24, 16)) * 8  # 24 lists: pads to 8-multiple
    db = np.concatenate([c + rng.normal(size=(160, 16)) for c in centers]).astype(
        np.float32
    )
    queries = np.concatenate([c + rng.normal(size=(3, 16)) for c in centers]).astype(
        np.float32
    )
    k = 10
    ann = (
        ApproximateNearestNeighbors(mesh=mesh8)
        .setK(k)
        .setNlist(24)
        .setNprobe(4)
        .fit({"features": db})
    )
    model = ann  # fit() returned the model
    d_plain, i_plain = model.kneighbors(queries)
    model.shard_index(mesh8)
    d_shard, i_shard = model.kneighbors(queries)
    np.testing.assert_array_equal(
        np.sort(i_plain, axis=1), np.sort(i_shard, axis=1)
    )
    np.testing.assert_allclose(
        np.sort(d_plain, axis=1), np.sort(d_shard, axis=1), rtol=1e-5
    )
    # And recall against brute force stays high.
    _, ref_i = _sklearn_knn(db, queries, k)
    recall = np.mean(
        [len(set(i_shard[i]) & set(ref_i[i])) / k for i in range(len(queries))]
    )
    assert recall > 0.85, recall


def test_ivf_sharded_fused_matches_unsharded(rng, mesh8):
    # The fused Pallas scan+selection must compose with the shard_map
    # sharded executor (interpret mode on the CPU mesh): sharded results
    # must match the single-device fused executor's.
    from spark_rapids_ml_tpu import config

    centers = rng.normal(size=(16, 12)) * 8
    db = np.concatenate([c + rng.normal(size=(120, 12)) for c in centers]).astype(
        np.float32
    )
    queries = np.concatenate([c + rng.normal(size=(2, 12)) for c in centers]).astype(
        np.float32
    )
    k = 5
    with config.option("ann_fused_scan", "on"):
        model = (
            ApproximateNearestNeighbors(mesh=mesh8)
            .setK(k)
            .setNlist(16)
            .setNprobe(4)
            .fit({"features": db})
        )
        d_plain, i_plain = model.kneighbors(queries)
        model.shard_index(mesh8)
        d_shard, i_shard = model.kneighbors(queries)
    np.testing.assert_array_equal(
        np.sort(i_plain, axis=1), np.sort(i_shard, axis=1)
    )
    np.testing.assert_allclose(
        np.sort(d_plain, axis=1), np.sort(d_shard, axis=1), rtol=1e-5
    )


def test_ivf_sharded_model_copy_preserves_sharding(rng, mesh8):
    # Copying a sharded model must re-establish the padded sharded index
    # (nlist=30 is not divisible by 8 devices — regression for the lost
    # padding invariant on copy).
    db = rng.normal(size=(900, 8)).astype(np.float32)
    queries = rng.normal(size=(10, 8)).astype(np.float32)
    model = (
        ApproximateNearestNeighbors(mesh=mesh8)
        .setK(5)
        .setNlist(30)
        .setNprobe(5)
        .fit({"features": db})
    )
    model.shard_index(mesh8)
    a = model.kneighbors(queries)
    b = model.copy().kneighbors(queries)
    np.testing.assert_array_equal(a[1], b[1])


def test_ivf_build_bounded_training(rng):
    # train_rows caps quantizer training; assignment still covers all rows.
    from spark_rapids_ml_tpu.models.knn import build_ivf_flat

    db = rng.normal(size=(4096, 8)).astype(np.float32)
    index = build_ivf_flat(db, nlist=16, seed=0, train_rows=512)
    assert int(index.list_mask.sum()) == 4096  # every row bucketed
    assert sorted(index.list_ids[index.list_ids >= 0].tolist()) == list(range(4096))


def test_build_ivf_flat_device_invariants(rng):
    """Device-side build: rows partition exactly once across lists, slots
    agree with the mask, and each row lands in its argmin-centroid list."""
    import jax.numpy as jnp

    from spark_rapids_ml_tpu.models.knn import build_ivf_flat_device

    n, d, nlist = 512, 16, 8
    centers = (rng.normal(size=(nlist, d)) * 10).astype(np.float32)
    lab = rng.integers(0, nlist, size=n)
    x = (centers[lab] + 0.01 * rng.normal(size=(n, d))).astype(np.float32)
    idx = build_ivf_flat_device(jnp.asarray(x), nlist=nlist, seed=1)
    ids = np.asarray(idx.list_ids)
    mask = np.asarray(idx.list_mask)
    got = np.sort(ids[ids >= 0])
    np.testing.assert_array_equal(got, np.arange(n))  # exact partition
    np.testing.assert_array_equal((ids >= 0).astype(np.float32), mask)
    # membership is distance-optimal w.r.t. the returned quantizer (index
    # equality is too strict: collapsed/near-duplicate centroids tie, and
    # f32 device math may break the tie differently than f64 numpy)
    cents = np.asarray(idx.centroids)
    lists = np.asarray(idx.lists)
    d2 = ((x[:, None, :] - cents[None]) ** 2).sum(-1)
    dmin = d2.min(1)
    for li in range(nlist):
        for slot in np.nonzero(ids[li] >= 0)[0]:
            rid = ids[li, slot]
            assert d2[rid, li] <= dmin[rid] + 1e-2 * (1 + dmin[rid]), (rid, li)
            np.testing.assert_allclose(lists[li, slot], x[rid], atol=0)


def test_build_ivf_flat_device_query_recall(rng):
    """End-to-end: device-built index + bucketed query reaches high recall
    on clustered data vs brute force."""
    import jax.numpy as jnp

    from spark_rapids_ml_tpu.models.knn import ApproximateNearestNeighborsModel, build_ivf_flat_device

    # nprobe*4 < nlist so the auto dispatch picks the BUCKETED executor —
    # the path whose residual cache this test exists to cover.
    n, d, nlist = 2048, 32, 32
    centers = (rng.normal(size=(nlist, d)) * 8).astype(np.float32)
    lab = rng.integers(0, nlist, size=n)
    x = (centers[lab] + 0.05 * rng.normal(size=(n, d))).astype(np.float32)
    idx = build_ivf_flat_device(jnp.asarray(x), nlist=nlist, seed=2)
    model = ApproximateNearestNeighborsModel(index=idx)
    model.set("k", 5)
    model.set("nprobe", 6)
    q = x[:64]
    dists, ids = model.kneighbors(q)
    d2 = ((q[:, None, :] - x[None]) ** 2).sum(-1)
    ref = np.argsort(d2, axis=1)[:, :5]
    recall = np.mean([len(set(ids[i]) & set(ref[i])) / 5 for i in range(64)])
    assert recall > 0.85, recall


def test_balance_assignments_caps_and_preserves_rows():
    from spark_rapids_ml_tpu.models.knn import _balance_assignments

    rng = np.random.default_rng(0)
    n, nlist, cap = 10_000, 64, 200
    # adversarial: every row's first choice is list 0
    cand = np.zeros((n, 4), np.int32)
    for t in (1, 2, 3):
        cand[:, t] = rng.integers(0, nlist, n)
    a = _balance_assignments(cand, nlist, cap)
    assert (a >= 0).all() and (a < nlist).all()
    assert np.bincount(a, minlength=nlist).max() <= cap
    # rows keep their most-preferred list that had room
    assert np.bincount(a, minlength=nlist)[0] == cap


def test_clustered_build_bounds_maxlen_and_keeps_recall(rng, mesh8):
    """Heavily clustered data (the IVF use case) must not blow up the
    padded (nlist, maxlen, d) layout — round-1 builds produced maxlen
    20-30x the mean there (a 24 GB index for 3 GB of rows). Spill-balanced
    assignment caps maxlen at IVF_MAX_LOAD_FACTOR x mean while keeping
    every row indexed exactly once and recall high."""
    from spark_rapids_ml_tpu.models.knn import (
        IVF_MAX_LOAD_FACTOR,
        build_ivf_flat,
    )

    n, d, nlist = 4096, 16, 64
    cc = rng.normal(size=(8, d)) * 10  # 8 natural clusters >> 64 lists
    x = (cc[rng.integers(0, 8, n)] + 0.3 * rng.normal(size=(n, d))).astype(
        np.float32
    )
    idx = build_ivf_flat(x, nlist=nlist, seed=0)
    cap = max(int(np.ceil(IVF_MAX_LOAD_FACTOR * n / nlist)), -(-n // nlist))
    assert idx.lists.shape[1] <= cap
    ids = idx.list_ids[idx.list_ids >= 0]
    assert sorted(ids.tolist()) == list(range(n))  # every row, exactly once

    # recall vs brute force at a moderate nprobe stays high despite spill
    from oracles import knn_brute
    from spark_rapids_ml_tpu.models.knn import _ivf_query_fn

    q = x[:128]
    _, gt = knn_brute(x, q, 10)
    query = _ivf_query_fn(10, 16, "float64", "float64")
    import jax.numpy as jnp

    _, got = query(
        jnp.asarray(idx.centroids), jnp.asarray(idx.lists),
        jnp.asarray(idx.list_ids), jnp.asarray(idx.list_mask),
        jnp.asarray(q),
    )
    got = np.asarray(got)
    recall = np.mean(
        [len(set(got[i]) & set(gt[i])) / 10 for i in range(len(q))]
    )
    assert recall >= 0.9


def test_exact_cosine_matches_sklearn(db_and_queries, mesh8):
    from sklearn.neighbors import NearestNeighbors as SkNN

    db, queries = db_and_queries
    k = 7
    model = NearestNeighbors(mesh=mesh8).setK(k).setMetric("cosine").fit(
        {"features": db}
    )
    dists, idx = model.kneighbors(queries)
    sk = SkNN(n_neighbors=k, metric="cosine", algorithm="brute").fit(db)
    ref_d, ref_i = sk.kneighbors(queries)
    np.testing.assert_array_equal(idx, ref_i)
    np.testing.assert_allclose(dists, ref_d, rtol=1e-5, atol=1e-5)


def test_exact_sqeuclidean_is_squared_euclidean(db_and_queries, mesh8):
    db, queries = db_and_queries
    m_e = NearestNeighbors(mesh=mesh8).setK(5).fit({"features": db})
    m_s = (
        NearestNeighbors(mesh=mesh8).setK(5).setMetric("sqeuclidean").fit(
            {"features": db}
        )
    )
    d_e, i_e = m_e.kneighbors(queries)
    d_s, i_s = m_s.kneighbors(queries)
    np.testing.assert_array_equal(i_e, i_s)
    np.testing.assert_allclose(d_e**2, d_s, rtol=1e-5, atol=1e-6)


def test_exact_inner_product_descending_vs_numpy(db_and_queries, mesh8):
    db, queries = db_and_queries
    k = 6
    model = (
        NearestNeighbors(mesh=mesh8).setK(k).setMetric("inner_product").fit(
            {"features": db}
        )
    )
    sims, idx = model.kneighbors(queries)
    ip = queries @ db.T
    ref_i = np.argsort(-ip, axis=1, kind="stable")[:, :k]
    np.testing.assert_array_equal(idx, ref_i)
    np.testing.assert_allclose(
        sims, np.take_along_axis(ip, ref_i, axis=1), rtol=1e-5, atol=1e-5
    )
    assert np.all(np.diff(sims, axis=1) <= 1e-6)  # descending similarities


def test_exact_metric_switch_rebuilds_index(db_and_queries, mesh8):
    # Same model queried under two metrics: the cached (possibly
    # normalized) device index must rebuild on the switch.
    db, queries = db_and_queries
    model = NearestNeighbors(mesh=mesh8).setK(5).fit({"features": db})
    d_e, _ = model.kneighbors(queries)
    model._set(metric="cosine")
    d_c, _ = model.kneighbors(queries)
    assert np.all(d_c <= 2.0 + 1e-6)  # cosine distances, not L2
    model._set(metric="euclidean")
    d_e2, _ = model.kneighbors(queries)
    np.testing.assert_allclose(d_e, d_e2, rtol=1e-6)


def test_ann_cosine_recall(rng, mesh8):
    # Clustered directions: IVF on unit-normalized rows must recover the
    # brute-force cosine neighbors.
    centers = rng.normal(size=(16, 24))
    db = np.concatenate(
        [c * rng.uniform(0.5, 2.0, size=(150, 1)) + 0.05 * rng.normal(size=(150, 24)) for c in centers]
    ).astype(np.float32)
    queries = np.concatenate(
        [c * rng.uniform(0.5, 2.0, size=(3, 1)) + 0.05 * rng.normal(size=(3, 24)) for c in centers]
    ).astype(np.float32)
    k = 10
    ann = (
        ApproximateNearestNeighbors()
        .setK(k)
        .setNlist(16)
        .setNprobe(8)
        .setMetric("cosine")
        .fit({"features": db})
    )
    dists, idx = ann.kneighbors(queries)
    # brute cosine ground truth
    dbn = db / np.linalg.norm(db, axis=1, keepdims=True)
    qn = queries / np.linalg.norm(queries, axis=1, keepdims=True)
    ref_i = np.argsort(1 - qn @ dbn.T, axis=1, kind="stable")[:, :k]
    recall = np.mean(
        [len(set(idx[i]) & set(ref_i[i])) / k for i in range(len(queries))]
    )
    assert recall > 0.9, recall
    assert np.all(dists >= -1e-6) and np.all(dists[np.isfinite(dists)] <= 2 + 1e-6)


def test_ann_inner_product_rejected(rng):
    with pytest.raises(ValueError, match="inner_product"):
        ApproximateNearestNeighbors().setMetric("inner_product").fit(
            {"features": rng.normal(size=(100, 8)).astype(np.float32)}
        )


def test_metric_param_persists(db_and_queries, mesh8, tmp_path):
    db, queries = db_and_queries
    model = (
        NearestNeighbors(mesh=mesh8).setK(4).setMetric("cosine").fit(
            {"features": db}
        )
    )
    path = str(tmp_path / "nn_cosine")
    model.save(path)
    loaded = NearestNeighborsModel.load(path)
    assert loaded.getMetric() == "cosine"
    d0, i0 = model.kneighbors(queries)
    d1, i1 = loaded.kneighbors(queries)
    np.testing.assert_array_equal(i0, i1)
    np.testing.assert_allclose(d0, d1, rtol=1e-6)


def test_ivf_fused_bf16_recall(rng):
    # The production configuration: bfloat16 residual scan through the
    # fused kernel (interpret mode on CPU). Recall on clustered data must
    # hold — covers the packed-key selection on genuinely noisy bf16
    # scores, not just the exact-f32 algebraic checks above.
    from spark_rapids_ml_tpu import config
    from spark_rapids_ml_tpu.models.knn import build_ivf_flat, _ivf_query_fn

    centers = rng.normal(size=(16, 24)) * 8
    db = np.concatenate([c + rng.normal(size=(120, 24)) for c in centers]).astype(
        np.float32
    )
    queries = np.concatenate([c + rng.normal(size=(4, 24)) for c in centers]).astype(
        np.float32
    )
    k = 10
    index = build_ivf_flat(db, nlist=16, seed=0)
    dev = [
        jnp.asarray(index.centroids, jnp.float32),
        jnp.asarray(index.lists),
        jnp.asarray(index.list_ids),
        jnp.asarray(index.list_mask),
    ]
    fn = _ivf_query_fn(
        k, 6, "bfloat16", "float32", mode="bucketed", rerank=True, fused="on"
    )
    _, idx = fn(*dev, jnp.asarray(queries))
    from oracles import knn_brute

    _, ref_i = knn_brute(db, queries, k)
    recall = np.mean(
        [len(set(np.asarray(idx)[i]) & set(ref_i[i])) / k for i in range(len(queries))]
    )
    assert recall > 0.9, recall


def test_cosine_zero_vectors_match_sklearn(mesh8, rng):
    # Zero rows and zero queries: the augmented-normalization embedding
    # must reproduce sklearn's normalize()-then-dot semantics exactly —
    # a zero vector sits at cosine distance 1 from everything (NOT the
    # 0.5 a plain zero-stays-zero embedding reports, which would rank it
    # above genuinely dissimilar neighbors).
    from sklearn.preprocessing import normalize

    db = rng.normal(size=(60, 8)).astype(np.float64)
    db[7] = 0.0  # a zero database row
    queries = rng.normal(size=(6, 8)).astype(np.float64)
    queries[2] = 0.0  # a zero query
    k = 60  # full ranking: the zero row's position matters
    model = NearestNeighbors(mesh=mesh8).setK(k).setMetric("cosine").fit(
        {"features": db}
    )
    dists, idx = model.kneighbors(queries)
    sim = normalize(queries) @ normalize(db).T  # sklearn zero -> zero
    ref = 1.0 - sim
    # Compare the full distance-by-db-row matrix.
    by_row = np.empty((6, 60))
    for i in range(6):
        by_row[i, idx[i]] = dists[i]
    np.testing.assert_allclose(by_row, ref, rtol=1e-6, atol=1e-6)


def test_ann_metric_switch_after_fit_rejected(rng):
    db = rng.normal(size=(200, 8)).astype(np.float32)
    ann = ApproximateNearestNeighbors().setK(5).setNlist(8).setNprobe(8).fit(
        {"features": db}
    )
    ann._set(metric="cosine")
    with pytest.raises(ValueError, match="built under"):
        ann.kneighbors(db[:4])


def test_ann_metric_switch_after_load_rejected(rng, tmp_path):
    # The fit metric travels WITH the index (not re-derived from the
    # mutable param): a loaded model whose metric param is flipped before
    # its first query must hit the built-under guard, not silently score
    # cosine-normalized (d+2)-wide lists against raw queries.
    from spark_rapids_ml_tpu.models.knn import ApproximateNearestNeighborsModel

    db = rng.normal(size=(200, 8)).astype(np.float32)
    ann = (
        ApproximateNearestNeighbors()
        .setK(5).setNlist(8).setNprobe(8).setMetric("cosine")
        .fit({"features": db})
    )
    # The persisted ordinal contract: these positions are on-disk format.
    from spark_rapids_ml_tpu.models.knn import KNN_METRICS

    assert KNN_METRICS[:4] == (
        "euclidean", "sqeuclidean", "cosine", "inner_product"
    )
    path = str(tmp_path / "ann_cosine")
    ann.save(path)
    loaded = ApproximateNearestNeighborsModel.load(path)
    assert loaded._index_metric == "cosine"
    loaded._set(metric="euclidean")
    with pytest.raises(ValueError, match="built under"):
        loaded.kneighbors(db[:4])
    # Pickle round-trip (executor shipping) preserves it too.
    import pickle

    clone = pickle.loads(pickle.dumps(ann))
    clone._set(metric="sqeuclidean")
    with pytest.raises(ValueError, match="built under"):
        clone.kneighbors(db[:4])


def test_merge_topk_preserves_shard_dtype(rng):
    """ADVICE r5(c) regression: f32 shard distances must merge to f32 —
    the single-daemon path returns the query dtype, and a multi-daemon
    kneighbors answer must not silently widen to f64 (schema-visible to
    every Spark consumer). The merge still compares exactly (internally
    f64) and the selected values are bit-identical to the shard's own
    answer after the cast."""
    from spark_rapids_ml_tpu.models.knn import merge_topk

    k = 5
    d_a = rng.random((7, k)).astype(np.float32)
    d_b = rng.random((7, k)).astype(np.float32)
    i_a = rng.integers(0, 100, (7, k)).astype(np.int64)
    i_b = rng.integers(100, 200, (7, k)).astype(np.int64)
    dists, ids = merge_topk([d_a, d_b], [i_a, i_b], k)
    assert dists.dtype == np.float32
    assert ids.dtype == np.int64
    # Every merged distance is one of the shard values, bit-for-bit.
    pool = np.concatenate([d_a, d_b], axis=1)
    for r in range(dists.shape[0]):
        assert np.isin(dists[r], pool[r]).all()
    # f64 shards still merge to f64 (dtype follows the shards, not a cast
    # hardcoded to f32).
    dists64, _ = merge_topk(
        [d_a.astype(np.float64), d_b.astype(np.float64)], [i_a, i_b], k
    )
    assert dists64.dtype == np.float64
    np.testing.assert_array_equal(dists64.astype(np.float32), dists)


def test_ivf_build_trains_on_explicit_cross_shard_sample(rng):
    """ADVICE r5(b) unit: ``train_data`` replaces the local sample as the
    quantizer training pool. A shard whose rows live in region A, handed
    a training pool that also covers region B, must place centroids in
    BOTH regions — the cross-daemon fix's core property (under the bug,
    training on the local shard alone left region B uncovered)."""
    from spark_rapids_ml_tpu.models.knn import (
        build_ivf_flat,
        build_ivf_flat_device,
    )

    region_a = rng.normal(size=(400, 6)).astype(np.float32)          # ~0
    region_b = (rng.normal(size=(400, 6)) + 40.0).astype(np.float32)  # ~+40
    pool = np.concatenate([region_a, region_b])

    for build in (build_ivf_flat, build_ivf_flat_device):
        index = build(region_a, nlist=8, seed=0, train_data=pool)
        cent = np.asarray(index.centroids)
        assert (cent.mean(axis=1) > 20).any(), (
            f"{build.__name__}: no centroid covers region B — train_data "
            "pool ignored"
        )
        assert (cent.mean(axis=1) < 20).any()  # region A still covered
        # The DATABASE bucketed is still only this shard's rows.
        assert int(index.list_mask.sum()) == len(region_a)

    # Validation: a training pool narrower than the database is a hard
    # error, not a silent mis-shaped quantizer.
    with pytest.raises(ValueError, match="train_data"):
        build_ivf_flat(region_a, nlist=8, seed=0, train_data=pool[:, :4])
    with pytest.raises(ValueError, match="train_data"):
        build_ivf_flat(region_a, nlist=8, seed=0, train_data=pool[:4])


# ---------------------------------------------------------------------------
# Fused streaming distance+top-k exact path (dist_topk_pallas, interpret)
# ---------------------------------------------------------------------------


@pytest.mark.kernels
def test_exact_knn_fused_matches_xla(mesh8, rng):
    """The fused shard scan (use_pallas=True, interpret off-TPU) must be
    bitwise-index-equal and tolerance-distance-equal to the XLA
    sq_euclidean→top_k two-step on the sharded mesh."""
    import jax

    from spark_rapids_ml_tpu.models.knn import _exact_knn_fn
    from spark_rapids_ml_tpu.parallel.sharding import replicated_array, shard_rows

    n, d, q, k = 640, 24, 64, 6
    db = rng.normal(size=(n, d)).astype(np.float32)
    qs = rng.normal(size=(q, d)).astype(np.float32)
    dbs, mask, _ = shard_rows(db, mesh8)
    ids, _, _ = shard_rows(
        np.arange(1, n + 1, dtype=np.int32), mesh8, with_mask=False
    )
    qrep = replicated_array(qs, mesh8)
    dx, ix = jax.device_get(
        _exact_knn_fn(mesh8, k, "float32", "float32", "l2", use_pallas=False)(
            dbs, mask, ids - 1, qrep
        )
    )
    dp, ip = jax.device_get(
        _exact_knn_fn(mesh8, k, "float32", "float32", "l2", use_pallas=True)(
            dbs, mask, ids - 1, qrep
        )
    )
    np.testing.assert_array_equal(ix, ip)
    np.testing.assert_allclose(dx, dp, rtol=1e-5, atol=1e-4)


@pytest.mark.kernels
def test_fused_topk_tie_break_matches_merge_topk(rng):
    """The duplicate-distance rider: the fused kernel's (distance, id)
    tie order must agree with merge_topk's host lexsort, so the sharded
    (per-daemon merge) and single-daemon fused paths stay
    bitwise-comparable. Crafted duplicate rows force exact ties that
    straddle the shard split."""
    import jax

    from spark_rapids_ml_tpu.models.knn import _exact_knn_fn, merge_topk
    from spark_rapids_ml_tpu.parallel.sharding import replicated_array, shard_rows

    n, d, q, k = 240, 12, 16, 8
    db = rng.normal(size=(n, d)).astype(np.float32)
    # Duplicates across the future split point AND inside each half.
    db[5] = db[200]
    db[30] = db[31]
    db[130] = db[131]
    qs = db[rng.integers(0, n, size=q)] + 0.01 * rng.normal(size=(q, d)).astype(
        np.float32
    )
    m1 = make_mesh(data=1, model=1, devices=jax.devices()[:1])
    fn = _exact_knn_fn(m1, k, "float32", "float32", "l2", use_pallas=True)

    def run(part, lo):
        s, msk, _ = shard_rows(part, m1)
        pid, _, _ = shard_rows(
            np.arange(lo + 1, lo + part.shape[0] + 1, dtype=np.int32),
            m1, with_mask=False,
        )
        return jax.device_get(fn(s, msk, pid - 1, replicated_array(qs, m1)))

    d_full, i_full = run(db, 0)
    d_a, i_a = run(db[:120], 0)
    d_b, i_b = run(db[120:], 120)
    md, mi = merge_topk([d_a, d_b], [i_a, i_b], k)
    np.testing.assert_array_equal(mi, i_full.astype(np.int64))
    np.testing.assert_array_equal(md, d_full)  # bitwise, not allclose


@pytest.mark.kernels
def test_fused_kneighbors_peak_memory_receipt(rng):
    """The acceptance receipt: under SRML_DEVICE_TIMING the jit ledger's
    memory_analysis must show the fused kneighbors program peaking BELOW
    the unfused one (which materializes the full (q, m_local) distance
    matrix between sq_euclidean and top_k)."""
    import jax

    from spark_rapids_ml_tpu.models.knn import _exact_knn_fn
    from spark_rapids_ml_tpu.parallel.sharding import replicated_array, shard_rows

    # Compile-only (nothing executes): a shape whose (q, m) matrix dwarfs
    # the fused kernel's per-block temporaries even under the interpret
    # lowering (on TPU the block tiles are VMEM-resident and don't show
    # in temp bytes at all).
    n, d, q, k = 16384, 64, 1024, 4
    db = rng.normal(size=(n, d)).astype(np.float32)
    qs = rng.normal(size=(q, d)).astype(np.float32)
    m1 = make_mesh(data=1, model=1, devices=jax.devices()[:1])
    s, msk, _ = shard_rows(db, m1)
    pid, _, _ = shard_rows(np.arange(n, dtype=np.int32), m1, with_mask=False)
    qrep = replicated_array(qs, m1)

    def peak(use_pallas):
        # The same memory_analysis the ledger harvests under
        # SRML_DEVICE_TIMING, taken through the AOT lowering directly:
        # both variants register under ONE ledger name ("knn.exact_topk")
        # and signature, so the entry-cached analysis cannot tell them
        # apart — the receipt must come from each program's own compile.
        fn = _exact_knn_fn(m1, k, "float32", "float32", "l2",
                           use_pallas=use_pallas)
        try:
            ma = fn.lower(s, msk, pid, qrep).compile().memory_analysis()
            return int(ma.temp_size_in_bytes)
        except Exception:
            return None

    fused, unfused = peak(True), peak(False)
    if fused is None or unfused is None:
        pytest.skip("backend reports no memory_analysis")
    matrix_bytes = q * n * 4
    assert fused < unfused, (fused, unfused)
    assert unfused >= matrix_bytes  # the two-step really held the matrix
    assert fused < matrix_bytes, (
        f"fused peak {fused} holds the (q, m) matrix ({matrix_bytes}B)"
    )
    # And the ledger's own SRML_DEVICE_TIMING harvest sees the same fused
    # peak (a fresh analysis cache so the fused program — not a cached
    # variant under the shared entry name — is what gets analyzed).
    from spark_rapids_ml_tpu import config
    from spark_rapids_ml_tpu.utils import xprof

    entry = xprof.LEDGER.entry("knn.exact_topk")
    with entry.lock:
        entry.analysis.clear()
        entry.records.clear()
    fn = _exact_knn_fn(m1, k, "float32", "float32", "l2", use_pallas=True)
    with config.option("device_timing", True):
        jax.block_until_ready(fn(s, msk, pid, qrep))
    recs = xprof.snapshot()["knn.exact_topk"]["signatures"]
    ledger_peaks = [r["peak_bytes"] for r in recs if r["peak_bytes"] is not None]
    assert ledger_peaks and max(ledger_peaks) < matrix_bytes, ledger_peaks
