"""Serving scheduler: cross-connection micro-batching for the inference plane.

Without it, every ``transform``/``kneighbors`` request runs alone on its
connection thread: N concurrent Spark tasks (or online callers) serialize
on ``_DEVICE_LOCK`` with batch-size-1 device dispatches, and every novel
row count jit-compiles a fresh program. This module is the missing layer
between "correct daemon" and "heavy traffic" (ROADMAP north star): a
per-daemon scheduler that COALESCES concurrent serving requests — across
connections, per model — into padded micro-batches before the one device
dispatch, the Podracer move of centralizing accelerator dispatch behind a
batching actor (PAPERS.md, arXiv:2104.06272).

Core pieces (docs/protocol.md "Serving scheduler"):

* **Admission control** — a bounded per-model queue. Overflow, and
  requests whose ``deadline_s`` the current backlog would already miss,
  are shed with :class:`SchedulerBusy`, which the daemon answers with
  the existing ``busy``/``retry_after_s`` contract — graceful shedding
  beats queueing to death, and every existing client already retries.
* **Shape bucketing** — coalesced rows are padded up to a small fixed
  ladder of bucket sizes (config ``serve_batch_buckets``, env
  ``SRML_SERVE_BATCH_BUCKETS``), so jit compilations are BOUNDED by the
  ladder size and counted (``srml_scheduler_compile_misses_total``).
  Padding is exact by construction for every BATCHED path: transform
  and exact-KNN serving are row-wise (``run_bucketed`` / the KNN query
  bucketer already pad), so a padded row can never contaminate a real
  row's output — batched results are bitwise-equal to solo requests
  (tested across bucket boundaries in tests/test_serve_scheduler.py).
  IVF/ANN ``kneighbors`` is the carve-out the daemon enforces: its
  capacity-bucketed candidate search shares per-list query slots
  across a batch (a padding or co-batched row can EVICT a real
  query's candidates), so those requests always dispatch solo
  (``srml_scheduler_bypass_total``; the index's internal query
  bucketer still bounds their compiles).
* **Batching loop** — one dispatcher thread drains the queues: a batch
  goes to the device when its oldest request has waited
  ``serve_batch_window_ms`` or the coalesced rows reach
  ``serve_max_batch_rows``, dispatches ONCE under the model lock +
  ``_DEVICE_LOCK`` (via ``_ServedModel``), and scatters per-request row
  slices back to the waiting connection threads. (The lock discipline
  here is machine-checked by srml-check — docs/static_analysis.md: the
  lexical lock rules, plus the interprocedural passes that follow this
  dispatcher thread's call graph: ``thread-shared-state`` proves every
  ``_Request``/EWMA/ledger mutation happens with a lock on the access
  path, ``lock-graph-cycle`` keeps ``_cv`` acyclic against the daemon's
  model/job locks, and ``blocking-under-device-lock`` keeps host-side
  blocking out of the device sections ``_dispatch`` enters.)
* **Warmup** — :meth:`RequestScheduler.warmup` pre-compiles the bucket
  ladder for a served model (the additive ``warmup`` wire op), so
  first-request latency is predictable instead of hiding a compile.

Batches only ever mix requests with identical (model, kind, k, dtype,
row width) — anything else would change numerics or shapes. A single
request larger than the coalescing cap bypasses the scheduler entirely
(``srml_scheduler_bypass_total``); its solo dispatch is one device
program, and the model-side bucketer (``run_bucketed`` / the KNN query
bucketer) keeps even bypass compiles bounded.

Fault site ``daemon.scheduler`` (utils/faults.py): an injected fault at
admission is translated into a shed — the chaos suite proves shed
requests retry to exact results through the ordinary busy contract.

Default: ON since the fleet PR (``serve_batching`` / the
``SRML_SERVE_BATCHING=0`` opt-out). The burn-in that earned the flip:
the frozen protocol goldens replay unchanged and the PR 5
batched-vs-solo matrix stays bitwise under the default configuration
(tests/test_serve_scheduler.py, tests/test_protocol_golden.py).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, Optional, Tuple

import numpy as np

from spark_rapids_ml_tpu.utils import faults
from spark_rapids_ml_tpu.utils import metrics as metrics_mod
from spark_rapids_ml_tpu.utils import xprof
from spark_rapids_ml_tpu.utils.logging import get_logger

logger = get_logger("serve.scheduler")

__all__ = ["RequestScheduler", "SchedulerBusy", "parse_buckets"]

#: Scheduler telemetry (docs/observability.md catalogs all of these).
_M_QUEUE_DEPTH = metrics_mod.gauge(
    "srml_scheduler_queue_depth",
    "Queued serving requests, by model (refreshed at scrape)",
)
_M_BATCHES = metrics_mod.counter(
    "srml_scheduler_batches_total", "Micro-batches dispatched, by op"
)
_M_BATCHED_REQUESTS = metrics_mod.counter(
    "srml_scheduler_batched_requests_total",
    "Requests served through micro-batches, by op",
)
_M_BATCH_ROWS = metrics_mod.histogram(
    "srml_scheduler_batch_rows",
    "Real (unpadded) rows per dispatched micro-batch, by op — the "
    "occupancy distribution; mean occupancy = sum/count",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096),
)
_M_BATCH_SECONDS = metrics_mod.histogram(
    "srml_scheduler_batch_seconds", "Micro-batch device dispatch latency, by op"
)
_M_PADDED_ROWS = metrics_mod.counter(
    "srml_scheduler_padded_rows_total",
    "Padding rows added to reach the bucket size, by op (waste ratio = "
    "padded / (padded + batch_rows sum))",
)
_M_SHEDS = metrics_mod.counter(
    "srml_scheduler_sheds_total",
    "Requests shed at admission, by op and reason "
    "(queue_full|deadline|fault|stopping)",
)
_M_COMPILE_MISSES = metrics_mod.counter(
    "srml_scheduler_compile_misses_total",
    "First dispatches of a novel (model, op, bucket, k, dtype) shape — "
    "each one is at most one jit compile, bounded by the bucket ladder",
)
_M_COMPILE_HITS = metrics_mod.counter(
    "srml_scheduler_compile_hits_total",
    "Dispatches that reused an already-seen batch shape, by op",
)
_M_BYPASS = metrics_mod.counter(
    "srml_scheduler_bypass_total",
    "Requests larger than the coalescing cap (serve_max_batch_rows "
    "floored to a bucket, at most the top bucket) served solo, by op",
)

#: Fallback ladder when the config string fails to parse — matches the
#: config.py default so a typo degrades to the documented behavior.
_DEFAULT_BUCKETS = (64, 256, 1024, 4096)


class SchedulerBusy(RuntimeError):
    """Admission shed the request; the daemon answers the existing
    ``busy``/``retry_after_s`` contract and the client retries."""

    def __init__(self, message: str, retry_after_s: float):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)


def parse_buckets(spec) -> Tuple[int, ...]:
    """``serve_batch_buckets`` value → ascending positive ints. Accepts a
    comma-separated string or any int iterable; falls back to the default
    ladder (with a warning) on garbage — a typo'd env var must degrade,
    not kill the daemon."""
    try:
        if isinstance(spec, str):
            vals = [int(p) for p in spec.replace(";", ",").split(",") if p.strip()]
        else:
            vals = [int(v) for v in spec]
        vals = sorted(set(vals))
        if not vals or vals[0] <= 0:
            raise ValueError(f"buckets must be positive ints, got {spec!r}")
        return tuple(vals)
    except (TypeError, ValueError) as e:
        logger.warning(
            "bad serve_batch_buckets %r (%s); using default %s",
            spec, e, _DEFAULT_BUCKETS,
        )
        return _DEFAULT_BUCKETS


class _Request:
    """One enqueued serving request: rows in, a slice of the batch out."""

    __slots__ = ("x", "rows", "event", "result", "error", "enq_t")

    def __init__(self, x: np.ndarray, enq_t: float):
        self.x = x
        self.rows = int(x.shape[0])
        self.event = threading.Event()
        self.result: Any = None
        self.error: Optional[BaseException] = None
        self.enq_t = enq_t


class RequestScheduler:
    """Cross-connection micro-batching for ``transform``/``kneighbors``.

    Thread model: connection threads :meth:`submit` and block on their
    request's event; ONE dispatcher thread owns every device dispatch
    (batches from different models still single-file — the device set is
    one resource, exactly what ``_DEVICE_LOCK`` enforces anyway — so one
    loop thread costs no throughput and keeps the batching logic
    race-free by construction). The loop never holds the queue lock
    across a dispatch: queues keep filling while the device runs.
    """

    def __init__(
        self,
        window_ms: Optional[float] = None,
        max_batch_rows: Optional[int] = None,
        buckets=None,
        queue_depth: Optional[int] = None,
        retry_after_s: float = 1.0,
    ):
        from spark_rapids_ml_tpu import config

        self._window_s = float(
            config.get("serve_batch_window_ms") if window_ms is None
            else window_ms
        ) / 1000.0
        self._max_rows = int(
            config.get("serve_max_batch_rows") if max_batch_rows is None
            else max_batch_rows
        )
        self._buckets = parse_buckets(
            config.get("serve_batch_buckets") if buckets is None else buckets
        )
        self._queue_depth = int(
            config.get("serve_queue_depth") if queue_depth is None
            else queue_depth
        )
        self._retry_after_s = float(retry_after_s)
        # Coalescing cap: a batch must fit the top bucket AND the row
        # cap — floored to a bucket boundary, because a batch coalesced
        # past one would pad UP to the next bucket, dispatching more
        # device rows than the operator's cap (and at a shape warmup
        # never compiled). A cap below the smallest bucket stands as-is:
        # those batches pad to the smallest bucket, which warmup covers
        # via _bucket_for.
        cap = min(self._max_rows, self._buckets[-1])
        for b in reversed(self._buckets):
            if b <= cap:
                cap = b
                break
        self._cap_rows = cap
        self._cv = threading.Condition()
        #: (model, kind, k, dtype, width, id(served)) → deque[_Request].
        #: The full key guards numerics: mixing dtypes would promote,
        #: mixing k would change output widths, and id(served) pins the
        #: batch to ONE registered model instance even across a racing
        #: drop_model + ensure_model under the same name.
        self._queues: Dict[tuple, deque] = {}
        #: served instance per key (the dispatch target).
        self._served: Dict[tuple, Any] = {}
        #: model name → queued request count (the admission bound).
        self._depth: Dict[str, int] = {}
        #: model name → queued rows (the deadline estimator's backlog).
        self._qrows: Dict[str, int] = {}
        #: queue key → queued rows: a running total, so the dispatcher's
        #: due-scan is O(#keys), not O(#queued requests), under the lock.
        self._krows: Dict[tuple, int] = {}
        #: model names the queue-depth gauge was last refreshed with
        #: (snapshot-thread only; pruned names get a final 0).
        self._gauged: set = set()
        #: EWMA of batch dispatch seconds (deadline admission input).
        self._ewma_s = 0.0
        self._batches = 0
        self._stopping = False
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "RequestScheduler":
        self._thread = threading.Thread(
            target=self._loop, name="srml-serve-scheduler", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        """Fail every pending request and stop the loop: a stopping
        daemon must unblock its connection threads, not strand them."""
        with self._cv:
            self._stopping = True
            pending = [r for q in self._queues.values() for r in q]
            self._queues.clear()
            self._served.clear()
            self._depth.clear()
            self._qrows.clear()
            self._krows.clear()
            self._cv.notify_all()
        for r in pending:
            r.error = SchedulerBusy("scheduler stopping", self._retry_after_s)
            r.event.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    # -- admission + submit ------------------------------------------------

    def eligible(self, n_rows: int) -> bool:
        """Whether a request of this size belongs in a micro-batch: one
        larger than the coalescing cap is already a full device dispatch
        on its own (and would never fit a bucket)."""
        return 0 < n_rows <= self._cap_rows

    def submit(
        self,
        model: str,
        served,
        kind: str,
        x: np.ndarray,
        k: Optional[int] = None,
        deadline_s: Optional[float] = None,
    ):
        """Enqueue one request and block until its batch dispatched.

        Returns the request's slice of the batch result: the role-keyed
        output dict for ``transform``, a ``(distances, indices)`` pair
        for ``kneighbors``. Raises :class:`SchedulerBusy` when admission
        sheds it, or the dispatch's exception verbatim.
        """
        x = np.ascontiguousarray(x)
        key = (model, kind, k, str(x.dtype), int(x.shape[1]), id(served))
        # The chaos hook (before the lock: a latency rule must not stall
        # every other submitter): an injected fault HERE models a
        # scheduler under pressure — translated to a shed so the client
        # walks the ordinary busy-retry path (and the chaos suite can
        # assert retried results are exact).
        try:
            faults.checkpoint("daemon.scheduler")
        except (ConnectionError, OSError) as e:
            _M_SHEDS.inc(op=kind, reason="fault")
            raise SchedulerBusy(
                f"scheduler shed (injected fault: {e})",
                self._retry_after_s,
            ) from e
        with self._cv:
            if self._stopping:
                _M_SHEDS.inc(op=kind, reason="stopping")
                raise SchedulerBusy("scheduler stopping", self._retry_after_s)
            depth = self._depth.get(model, 0)
            if depth >= self._queue_depth:
                _M_SHEDS.inc(op=kind, reason="queue_full")
                raise SchedulerBusy(
                    f"{depth} requests queued for model {model!r} "
                    f"(cap {self._queue_depth})",
                    self._retry_after_s,
                )
            if deadline_s is not None and self._ewma_s > 0.0:
                # Backlog-aware estimate: the batches ahead of us plus
                # our own, each costing ~EWMA seconds. Requests that
                # would expire IN the queue are shed now — the client's
                # wait is spent retrying, not queueing to death.
                backlog = self._qrows.get(model, 0) / max(self._cap_rows, 1)
                est = self._ewma_s * (1.0 + backlog)
                if est > float(deadline_s):
                    _M_SHEDS.inc(op=kind, reason="deadline")
                    raise SchedulerBusy(
                        f"estimated wait {est:.3f}s exceeds the request "
                        f"deadline {float(deadline_s):.3f}s",
                        self._retry_after_s,
                    )
            req = _Request(x, time.monotonic())
            q = self._queues.get(key)
            if q is None:
                q = self._queues[key] = deque()
            self._served[key] = served
            q.append(req)
            self._depth[model] = depth + 1
            self._qrows[model] = self._qrows.get(model, 0) + req.rows
            self._krows[key] = self._krows.get(key, 0) + req.rows
            self._cv.notify_all()
        # Block outside the lock. The dispatcher sets the event; the
        # liveness check is a backstop for a dead loop thread (a bug,
        # not a load condition) — requests must never hang a connection
        # forever.
        while not req.event.wait(timeout=1.0):
            if self._thread is None or not self._thread.is_alive():
                raise RuntimeError(
                    "serving scheduler dispatcher died with requests "
                    "in flight"
                )
        if req.error is not None:
            raise req.error
        return req.result

    def note_bypass(self, kind: str) -> None:
        """Account a request too large for the ladder that the daemon
        served solo (the scheduler never saw its rows)."""
        _M_BYPASS.inc(op=kind)

    # -- warmup ------------------------------------------------------------

    def reachable_buckets(self) -> list:
        """The REACHABLE bucket ladder: every bucket some coalesced batch
        can map to, i.e. up to ``_bucket_for(cap)`` — covers a cap below
        the smallest bucket, where batches still pad to that bucket.
        Buckets above the coalescing cap can never hold a coalesced batch
        (oversize singles bypass the scheduler), so warming or AOT-
        compiling them would be pure dead weight."""
        top = self._bucket_for(self._cap_rows)
        return [b for b in self._buckets if b <= top]

    def premark_shapes(self, served, shape_keys) -> None:
        """Mark AOT-warmed shapes in the served instance's compile-shape
        ledger UNDER THE SCHEDULER'S LOCK — ``_dispatch`` creates/reads
        the same ``_sched_seen`` set under ``_cv``, so an unlocked
        mutation from the warmup thread could race a first live dispatch
        and lose shapes in either direction (the compile-hit signal the
        pre-mark exists to produce)."""
        with self._cv:
            ledger = getattr(served, "_sched_seen", None)
            if ledger is None:
                ledger = set()
                served._sched_seen = ledger
            ledger.update(shape_keys)

    def warmup(
        self,
        model: str,
        served,
        n_cols: int,
        kind: str = "transform",
        k: Optional[int] = None,
        dtype: str = "float32",
    ) -> Dict[str, Any]:
        """Pre-compile the bucket ladder for one served model: dispatch a
        full zero batch per bucket through the exact batched path, so the
        jit caches (and the compile ledger) are primed before the first
        real request. Only the REACHABLE ladder is warmed — buckets above
        ``serve_max_batch_rows`` can never hold a coalesced batch
        (oversize singles bypass the scheduler), so compiling them would
        be pure dead weight. Returns ``{"buckets", "compiled"}`` —
        ``compiled`` counts the shapes this call saw for the first
        time."""
        ladder = self.reachable_buckets()
        compiled = 0
        for bucket in ladder:
            x = np.zeros((bucket, int(n_cols)), dtype=np.dtype(dtype))
            key = (model, kind, k, str(x.dtype), int(n_cols), id(served))
            req = _Request(x, time.monotonic())
            if self._dispatch(key, [req], served, record=False):
                compiled += 1
            if req.error is not None:
                raise req.error
        return {"buckets": ladder, "compiled": compiled}

    # -- observability -----------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """The `health` op's scheduler block (and the gauge refresher):
        config echo + live queue depths (models with queued work only)
        + dispatch totals."""
        with self._cv:
            models = {m: d for m, d in self._depth.items()}
            batches = self._batches
            # A model seen at the last scrape but pruned since must read
            # 0, not freeze at its final queued value. All under the
            # lock: health and metrics ops snapshot from concurrent
            # connection threads.
            for m in self._gauged - set(models):
                _M_QUEUE_DEPTH.set(0, model=m)
            self._gauged = set(models)
            for m, d in models.items():
                _M_QUEUE_DEPTH.set(d, model=m)
        return {
            "enabled": True,
            "window_ms": self._window_s * 1000.0,
            "max_batch_rows": self._max_rows,
            "buckets": list(self._buckets),
            "queue_depth_cap": self._queue_depth,
            "queued": sum(models.values()),
            "models": models,
            "batches": batches,
        }

    # -- batching loop -----------------------------------------------------

    def _bucket_for(self, n: int) -> int:
        for b in self._buckets:
            if n <= b:
                return b
        return self._buckets[-1]  # unreachable: coalescing caps at top

    def _loop(self) -> None:
        while True:
            with self._cv:
                key = self._next_due_locked()
                while key is None:
                    if self._stopping:
                        return
                    self._cv.wait(timeout=self._wait_s_locked())
                    key = self._next_due_locked()
                batch, served = self._pop_batch_locked(key)
            if batch:
                self._dispatch(key, batch, served)
            # Loop locals must not pin the served model (or the batch
            # payloads) across the next idle wait.
            batch = served = None

    def _wait_s_locked(self) -> Optional[float]:
        """Sleep until the oldest pending request's window expires (None
        = nothing pending, wait for a submit's notify)."""
        oldest = None
        for q in self._queues.values():
            if q and (oldest is None or q[0].enq_t < oldest):
                oldest = q[0].enq_t
        if oldest is None:
            return None
        return max(oldest + self._window_s - time.monotonic(), 0.001)

    def _next_due_locked(self) -> Optional[tuple]:
        """The dispatchable key whose head request is oldest: due when
        the window elapsed or the coalesced rows already fill a batch."""
        now = time.monotonic()
        due, due_t = None, None
        for key, q in self._queues.items():
            if not q:
                continue
            rows = self._krows.get(key, 0)
            if now - q[0].enq_t >= self._window_s or rows >= self._cap_rows:
                if due_t is None or q[0].enq_t < due_t:
                    due, due_t = key, q[0].enq_t
        return due

    def _pop_batch_locked(self, key: tuple):
        q = self._queues.get(key)
        if not q:
            return [], None
        model = key[0]
        batch = [q.popleft()]
        total = batch[0].rows
        while q and total + q[0].rows <= self._cap_rows:
            r = q.popleft()
            batch.append(r)
            total += r.rows
        served = self._served.get(key)
        if not q:
            # Drop the drained queue AND its served-model reference: the
            # scheduler must never pin a dropped/evicted _ServedModel
            # (daemon-built KNN indexes are dataset-sized) past its last
            # queued request — submit() re-registers on the next one.
            del self._queues[key]
            self._served.pop(key, None)
            self._krows.pop(key, None)
        else:
            self._krows[key] = self._krows.get(key, 0) - total
        # Prune zeroed accounting entries: per-model dicts (and the
        # health "models" map built from them) must not grow one dead
        # key per model name ever served — snapshot() zeroes the gauge
        # for names that vanish.
        if self._depth.get(model, 0) - len(batch) <= 0:
            self._depth.pop(model, None)
            self._qrows.pop(model, None)
        else:
            self._depth[model] -= len(batch)
            self._qrows[model] = self._qrows.get(model, 0) - total
        return batch, served

    def _dispatch(self, key: tuple, batch, served, record: bool = True) -> bool:
        """Pad the coalesced rows to the bucket, run ONE device dispatch
        through the served model (its lock + ``_DEVICE_LOCK``), scatter
        per-request slices, wake the waiters. Never raises: a dispatch
        failure lands on every request in the batch. Returns whether the
        batch shape was novel (a compile miss). Shared-state mutations
        (``_seen``, ``_ewma_s``, ``_batches``) take the lock — warmup
        runs this on a connection thread concurrently with the loop."""
        model, kind, k, dtype, width = key[0], key[1], key[2], key[3], key[4]
        total = sum(r.rows for r in batch)
        bucket = self._bucket_for(total)
        shape_key = (kind, k, dtype, width, bucket)
        with self._cv:
            # The compile ledger lives ON the served instance: it dies
            # with the model (no growth across model churn), and a
            # re-registration under an old name correctly counts misses
            # — its jit caches are fresh too.
            ledger = getattr(served, "_sched_seen", None)
            if ledger is None:
                ledger = set()
                served._sched_seen = ledger
            fresh = shape_key not in ledger
            if fresh:
                ledger.add(shape_key)
        if fresh:
            _M_COMPILE_MISSES.inc(op=kind)
        else:
            _M_COMPILE_HITS.inc(op=kind)
        xb = np.zeros((bucket, width), dtype=np.dtype(dtype))
        offsets = []
        off = 0
        for r in batch:
            xb[off:off + r.rows] = r.x
            offsets.append(off)
            off += r.rows
        t0 = time.perf_counter()
        try:
            # Jit-ledger attribution for the bucket dispatch: the model's
            # inner jits are ledgered individually; any compile they do
            # NOT own (fresh bucket shapes included) lands under the
            # scheduler's name instead of nowhere (utils/xprof.py).
            with xprof.annotate(f"scheduler.{kind}"):
                if kind == "transform":
                    outs = served.transform(xb)
                    for r, o in zip(batch, offsets):
                        r.result = {
                            name: np.asarray(v)[o:o + r.rows]
                            for name, v in outs.items()
                        }
                elif kind == "kneighbors":
                    dists, idx = served.kneighbors(xb, k)
                    dists, idx = np.asarray(dists), np.asarray(idx)
                    for r, o in zip(batch, offsets):
                        r.result = (dists[o:o + r.rows], idx[o:o + r.rows])
                else:  # pragma: no cover - submit() enqueues only these
                    raise ValueError(f"unknown scheduler kind {kind!r}")
        except BaseException as e:  # noqa: BLE001 - every waiter must wake
            for r in batch:
                r.error = e
        finally:
            dt = time.perf_counter() - t0
            with self._cv:
                # Decorrelated-enough smoothing for the deadline
                # estimator. Fresh shapes are EXCLUDED: a first dispatch
                # includes the jit compile (seconds), and an estimate
                # poisoned by compile time would shed every deadline-
                # carrying request forever — the EWMA only ever updates
                # on a dispatch, so it could never decay back down.
                if not fresh:
                    self._ewma_s = dt if self._ewma_s == 0.0 else (
                        0.8 * self._ewma_s + 0.2 * dt
                    )
                if record:
                    self._batches += 1
            if record:
                _M_BATCHES.inc(op=kind)
                _M_BATCHED_REQUESTS.inc(len(batch), op=kind)
                _M_BATCH_ROWS.observe(total, op=kind)
                _M_PADDED_ROWS.inc(bucket - total, op=kind)
                _M_BATCH_SECONDS.observe(dt, op=kind)
            for r in batch:
                r.event.set()
        return fresh
