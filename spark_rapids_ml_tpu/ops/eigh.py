"""Eigendecomposition finalize stage: the reference's ``calSVD`` in XLA.

The reference's native ``calSVD`` (rapidsml_jni.cu:215-269) runs, on one GPU:
cuSOLVER ``eigDC`` on the n×n Gram → column/row reversal to descending order
→ ``seqRoot`` (σ = √λ) → ``signFlip``. This module is the XLA equivalent —
``jnp.linalg.eigh`` plus pure-functional reorder/sqrt/sign-flip, all fused
under one jit. Where the reference serializes this to a dedicated
single-task Spark job shipping the matrix over the wire
(RapidsRowMatrix.scala:74-86), here the Gram is already on device and the
finalize compiles into the same program as the reduction.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def eigh_descending(a: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Symmetric eigendecomposition, eigenvalues descending.

    Equivalent of eigDC + colReverse/rowReverse (rapidsml_jni.cu:251-253);
    ``jnp.linalg.eigh`` returns ascending order, so flip.
    """
    w, v = jnp.linalg.eigh(a)
    return w[::-1], v[:, ::-1]


def sign_flip(u: jax.Array) -> jax.Array:
    """Deterministic eigenvector signs: flip any column whose largest-|x|
    element is negative.

    Exact semantics of the reference's Thrust kernel (rapidsml_jni.cu:35-61):
    scan for the max absolute value with strict ``>`` (first occurrence wins,
    matching ``argmax``), flip the column iff that element is < 0 (an
    all-zero column is left alone).
    """
    idx = jnp.argmax(jnp.abs(u), axis=0)
    vals = u[idx, jnp.arange(u.shape[1])]
    signs = jnp.where(vals < 0, -1.0, 1.0).astype(u.dtype)
    return u * signs[None, :]


def explained_variance_reference(eigvals: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Reference semantics: σ = √λ (clipped at 0), ratio = σᵢ / Σσ.

    The reference normalizes the *square roots* of the Gram eigenvalues
    (seqRoot at rapidsml_jni.cu:254, then ``s.data.map(_ / eigenSum)`` at
    RapidsRowMatrix.scala:91-93). Note this differs from Spark MLlib's CPU
    PCA, which normalizes covariance eigenvalues; we reproduce the reference
    exactly and expose the eigenvalue ratio separately.
    """
    s = jnp.sqrt(jnp.clip(eigvals, 0.0))
    return s, s / jnp.sum(s)


def explained_variance_ratio(eigvals: jax.Array) -> jax.Array:
    """Spark MLlib / sklearn semantics: λᵢ / Σλ (for cross-checking)."""
    w = jnp.clip(eigvals, 0.0)
    return w / jnp.sum(w)


def pca_from_gram(gram: jax.Array, k: int) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Full calSVD-equivalent finalize: Gram → (pc (n,k), explained_var (k,), σ (n,)).

    Output contract matches computePrincipalComponentsAndExplainedVariance
    (RapidsRowMatrix.scala:59-102): top-k eigenvector columns, sign-flipped;
    explained variance = σ/Σσ sliced to k.
    """
    w, v = eigh_descending(gram)
    v = sign_flip(v)
    s, ev = explained_variance_reference(w)
    return v[:, :k], ev[:k], s


def topk_eig_subspace(
    gram: jax.Array,
    k: int,
    oversample: int = 32,
    iters: int = 12,
    seed: int = 0,
) -> Tuple[jax.Array, jax.Array]:
    """Top-(k+p) eigenpairs of a PSD matrix by blocked subspace iteration
    (randomized PCA, Halko et al. 2011, alg. 4.4 specialized to a Gram).

    TPU-native alternative to the d×d ``eigh``: the only O(d²·m) work is
    ``G @ V`` — a large dense matmul the MXU runs at full rate — plus a thin
    (d, m) QR re-orthonormalization per iteration and one m×m Rayleigh–Ritz
    ``eigh`` at the end. Nothing larger than (d, m) is ever factorized, and
    the full decomposition the reference serialized to one GPU
    (``calSVD``, rapidsml_jni.cu:215-269) never runs.

    Convergence is (λ_{m}/λ_k)^iters for the k-th eigenvector — fast for
    the decaying spectra PCA users have, inaccurate for a flat spectrum
    (where principal directions are ill-defined anyway). Returns
    ``(ritz_vals (m,) descending, vectors (d, m))`` with m = k+oversample
    clamped to d.
    """
    from spark_rapids_ml_tpu.ops.gram import mm_precision

    d = gram.shape[0]
    m = min(k + oversample, d)
    v0 = jax.random.normal(jax.random.key(seed), (d, m), dtype=gram.dtype)

    with mm_precision(gram.dtype):

        def body(_, v):
            w = gram @ v
            q, _ = jnp.linalg.qr(w)
            return q

        v = jax.lax.fori_loop(0, iters, body, jnp.linalg.qr(v0)[0])
        gv = gram @ v
        b = v.T @ gv
        b = 0.5 * (b + b.T)
        wb, qb = jnp.linalg.eigh(b)  # m×m — tiny
        wb, qb = wb[::-1], qb[:, ::-1]
        return wb, v @ qb


def pca_from_gram_randomized(
    gram: jax.Array,
    k: int,
    oversample: int = 32,
    iters: int = 12,
    seed: int = 0,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """:func:`pca_from_gram` contract via :func:`topk_eig_subspace`.

    Stays entirely on device and computes only a rank-(k+p) decomposition,
    so on TPU the finalize is a handful of MXU matmuls instead of a host
    round-trip carrying the d×d Gram. The reference-semantics explained
    variance (σᵢ/Σσ over ALL d values, rapidsml_jni.cu:254 +
    RapidsRowMatrix.scala:91-93) needs the unseen tail of the spectrum; it
    is estimated from the trace — residual Σλ spread uniformly over the
    d−m tail, a concave (upper-bound) approximation that vanishes for
    decaying spectra. Returned σ is (d,) with the tail filled by that
    uniform estimate.
    """
    d = gram.shape[0]
    wb, u = topk_eig_subspace(gram, k, oversample=oversample, iters=iters, seed=seed)
    m = wb.shape[0]
    u = sign_flip(u)
    w_top = jnp.clip(wb, 0.0)
    s_top = jnp.sqrt(w_top)
    resid = jnp.clip(jnp.trace(gram) - jnp.sum(w_top), 0.0)
    n_tail = max(d - m, 0)
    tail_each = jnp.where(n_tail > 0, jnp.sqrt(resid / jnp.maximum(n_tail, 1)), 0.0)
    sigma_sum = jnp.sum(s_top) + n_tail * tail_each
    ev = s_top / jnp.maximum(sigma_sum, jnp.finfo(gram.dtype).tiny)
    s_full = jnp.concatenate(
        [s_top, jnp.full((n_tail,), tail_each, dtype=s_top.dtype)]
    )
    return u[:, :k], ev[:k], s_full


def pca_from_gram_model_sharded(
    gram: jax.Array,
    k: int,
    mesh,
    oversample: int = 32,
    iters: int = 12,
    seed: int = 0,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Model-parallel finalize (2112.09017-style distributed linear
    algebra): the (d, d) Gram stays sharded over the ``model`` mesh axis
    through the WHOLE eigensolve. Each device holds a (d/n_model, d)
    horizontal slab (exactly what ``gram.sharded_stats_2d``/``_ring``
    produce), ``G @ V`` runs as slab matmuls whose (d, k+p) results are
    the only full-width panels ever replicated, and the Rayleigh–Ritz
    system is m×m. This is how a d ≥ 8192 PCA fits where the replicated
    accumulator busts the per-device budget
    (:data:`~spark_rapids_ml_tpu.ops.gram.GRAM_DEVICE_BUDGET_BYTES`, the
    fit-path generalization of the Pallas ``GRAM_COLSUM_VMEM_BUDGET``
    ceiling) — sharding instead of rejection.

    Must run under jit (the sharding constraint is a trace-time
    annotation); same contract as :func:`pca_from_gram_randomized`.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    from spark_rapids_ml_tpu.parallel.mesh import MODEL_AXIS

    gram = jax.lax.with_sharding_constraint(
        gram, NamedSharding(mesh, P(MODEL_AXIS, None))
    )
    return pca_from_gram_randomized(
        gram, k, oversample=oversample, iters=iters, seed=seed
    )


def pca_from_gram_host(gram, k: int):
    """Host (NumPy/LAPACK, float64) version of :func:`pca_from_gram`.

    Used when the mesh's devices execute eigh poorly (TPU: eigh is an
    iterative algorithm that XLA compiles/executes badly for large d, while
    the d×d Gram is tiny to fetch). Architecturally this matches the
    reference, where the eig ran as its own single-device stage separate
    from the distributed reduction (RapidsRowMatrix.scala:70-86).
    """
    import numpy as np

    a = np.asarray(gram, dtype=np.float64)
    w, v = np.linalg.eigh(a)
    w, v = w[::-1], v[:, ::-1]
    idx = np.argmax(np.abs(v), axis=0)
    signs = np.where(v[idx, np.arange(v.shape[1])] < 0, -1.0, 1.0)
    v = v * signs
    s = np.sqrt(np.clip(w, 0, None))
    ev = s / max(s.sum(), 1e-300)
    return v[:, :k], ev[:k], s
