"""PCA.transform p50 latency — the second BASELINE.json headline metric.

The reference's transform re-uploads the PC matrix host→device on every
batch (rapidsml_jni.cu:85 — flagged in SURVEY.md §3.2 as the optimization
target); here the PC matrix is device-resident across batches and the
per-batch work is one (batch, d) × (d, k) MXU GEMM.

Baseline: an A100 cuML batch transform at 65536×2048 × 2048×32 is ~8.6
GFLOP ≈ 0.08 ms of GEMM plus per-batch PC upload (~0.25 ms for 0.5 MB
over PCIe effective ~2 GB/s with launch overhead) ≈ 0.35 ms. vs_baseline =
baseline_p50 / our_p50 (higher is better, >1 beats the A100 path).
"""

import os
import sys
import time

if __package__ in (None, ""):  # direct script run: python benchmarks/bench_*.py
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

BASELINE_P50_MS = 0.35

D = int(os.environ.get("SRML_BENCH_D", 2048))
K = int(os.environ.get("SRML_BENCH_K", 32))
BATCH = int(os.environ.get("SRML_BENCH_BATCH_ROWS", 65536))
CALLS = int(os.environ.get("SRML_BENCH_CALLS", 50))


def main() -> None:
    from benchmarks import setup_platform

    setup_platform()
    import jax
    import jax.numpy as jnp

    from benchmarks import emit

    rng = np.random.default_rng(0)
    pc = jnp.asarray(rng.normal(size=(D, K)), dtype=jnp.float32)
    x = jnp.asarray(rng.normal(size=(BATCH, D)), dtype=jnp.float32)

    @jax.jit
    def transform(pc, x):
        return jax.lax.dot_general(
            x, pc, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    jax.block_until_ready(transform(pc, x))  # compile
    lat = []
    for _ in range(CALLS):
        t0 = time.perf_counter()
        jax.block_until_ready(transform(pc, x))
        lat.append((time.perf_counter() - t0) * 1e3)
    p50 = float(np.percentile(lat, 50))
    emit(
        f"pca_transform_p50_ms_batch{BATCH}_d{D}_k{K}",
        p50,
        "ms",
        BASELINE_P50_MS / p50,
    )


if __name__ == "__main__":
    main()
