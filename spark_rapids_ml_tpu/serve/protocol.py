"""Wire framing for the data-plane daemon.

Every message is a 4-byte big-endian length prefix + payload. A request is
one JSON frame, optionally followed by one Arrow IPC stream frame (op
"feed"). A response is one JSON frame, optionally followed by raw-buffer
frames for each array listed in the JSON's ``arrays`` spec (op
"finalize"). Max frame size bounds a malformed/hostile length prefix.

**The protocol is FROZEN at PROTOCOL_VERSION** (see ``docs/protocol.md``
for the full op-by-op frame contract — the document third-party clients,
e.g. a Scala/JVM implementation, build against). Every request carries a
``"v"`` field; the daemon rejects mismatches with a message naming the
version it speaks. ``ping`` is version-exempt and echoes the server
version, so a client can discover it before committing to a dialect.
Any change to frames, fields, or semantics of existing ops bumps the
version; additive new ops keep it. ``tests/test_protocol_golden.py``
replays a recorded v1 byte transcript against a live daemon — if that
test fails, the frozen contract broke.

The serving scheduler (serve/scheduler.py) is invisible at this layer by
design: micro-batched ``transform``/``kneighbors`` responses are
byte-identical to solo ones, and the additive ``warmup`` op is a plain
JSON round-trip — no new framing shapes.
"""

from __future__ import annotations

import json
import struct
from typing import Any, Dict, Optional

import numpy as np

from spark_rapids_ml_tpu.utils import faults
from spark_rapids_ml_tpu.utils import metrics as _metrics

#: Wire-level byte accounting (length prefixes included). Both roles of
#: this process count here — a daemon process's numbers are the daemon's,
#: a Spark executor's are its client's (one process, one role in any
#: real deployment; the daemon additionally keeps per-op byte counters).
_TX_BYTES = _metrics.counter(
    "srml_wire_tx_bytes_total", "Frame bytes sent by this process"
)
_RX_BYTES = _metrics.counter(
    "srml_wire_rx_bytes_total", "Frame bytes received by this process"
)

#: Frozen wire-protocol version. Bump ONLY on breaking changes to
#: existing ops' frames or semantics; new ops are additive under the
#: same version.
PROTOCOL_VERSION = 1

MAX_FRAME = 1 << 31  # 2 GB — one Spark partition's batch comfortably fits

#: Frames up to this size send prefix+payload as ONE buffer (one
#: syscall, one TCP segment chain); larger frames skip the concat copy.
_SEND_COALESCE_MAX = 1 << 20

_LEN = struct.Struct(">I")


class ProtocolError(RuntimeError):
    pass


class FrameTooLarge(ProtocolError):
    """Sender-side MAX_FRAME rejection: deterministic (the payload will
    never fit), so retry loops must surface it instead of replaying."""


def send_frame(sock, payload: bytes) -> None:
    if len(payload) > MAX_FRAME:
        # fail fast sender-side instead of shipping GBs the peer will reject
        raise FrameTooLarge(
            f"frame of {len(payload)} bytes exceeds MAX_FRAME {MAX_FRAME}; "
            "split the batch"
        )
    faults.checkpoint("wire.send_frame")
    cut = faults.truncation("wire.send_frame", len(payload))
    if cut is not None:
        # Chaos path: promise the full frame, deliver a prefix, die — the
        # peer sees exactly what a mid-frame process death produces.
        sock.sendall(_LEN.pack(len(payload)))
        sock.sendall(payload[:cut])
        try:
            sock.close()
        except OSError:
            pass
        raise faults.InjectedDrop(
            f"injected fault: frame truncated at {cut}/{len(payload)} bytes"
        )
    if len(payload) <= _SEND_COALESCE_MAX:
        # One sendall for prefix + payload: the byte stream is identical
        # (the frozen goldens replay unchanged) but the 4-byte prefix no
        # longer goes out as its own syscall — and, under TCP_NODELAY,
        # as its own wire segment. At fleet request rates the header
        # segments were half the packet count of the whole serving path.
        sock.sendall(_LEN.pack(len(payload)) + payload)
    else:
        # Huge frames (multi-MB feeds): skip the concatenation copy —
        # two sendalls are noise next to the payload itself.
        sock.sendall(_LEN.pack(len(payload)))
        sock.sendall(payload)
    _TX_BYTES.inc(_LEN.size + len(payload))


def recv_exact(sock, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(n - len(buf), 1 << 20))
        if not chunk:
            return None  # peer closed
        buf.extend(chunk)
    return bytes(buf)


def recv_frame(sock) -> Optional[bytes]:
    header = recv_exact(sock, _LEN.size)
    if header is None:
        return None
    (n,) = _LEN.unpack(header)
    if n > MAX_FRAME:
        raise ProtocolError(f"frame of {n} bytes exceeds MAX_FRAME {MAX_FRAME}")
    payload = recv_exact(sock, n)
    if payload is not None:
        _RX_BYTES.inc(_LEN.size + n)
    return payload


def send_json(sock, obj: Dict[str, Any]) -> None:
    send_frame(sock, json.dumps(obj).encode())


def recv_json(sock) -> Optional[Dict[str, Any]]:
    frame = recv_frame(sock)
    if frame is None:
        return None
    try:
        obj = json.loads(frame)
    except ValueError as e:
        raise ProtocolError(f"bad JSON frame: {e}") from e
    if not isinstance(obj, dict):
        raise ProtocolError(f"expected JSON object, got {type(obj).__name__}")
    return obj


def send_arrays(sock, arrays: Dict[str, np.ndarray], meta: Dict[str, Any]) -> None:
    """JSON header (meta + array specs) then one raw frame per array."""
    spec = [
        {"name": k, "dtype": str(v.dtype), "shape": list(v.shape)}
        for k, v in arrays.items()
    ]
    send_json(sock, {**meta, "arrays": spec})
    for v in arrays.values():
        send_frame(sock, np.ascontiguousarray(v).tobytes())


def recv_arrays(sock, header: Dict[str, Any]) -> Dict[str, np.ndarray]:
    out = {}
    for spec in header.get("arrays", []):
        frame = recv_frame(sock)
        if frame is None:
            raise ProtocolError("connection closed mid-array")
        arr = np.frombuffer(frame, dtype=np.dtype(spec["dtype"]))
        # frombuffer over the received bytes is read-only; callers own the
        # result (model coefficients) and may mutate — copy.
        out[spec["name"]] = arr.reshape(spec["shape"]).copy()
    return out
