"""Differential-test oracles with NumPy/SciPy fallbacks.

The reference's test strategy is the differential oracle: the accelerated
path is compared against an independent CPU implementation, sign/permutation
invariant, tolerance-based (PCASuite.scala:58-87; SURVEY.md §4). sklearn is
the preferred oracle when installed; every function here falls back to a
pure NumPy/SciPy implementation of the *same objective* so the differential
tests still run (instead of skipping) on images without sklearn.
"""

from __future__ import annotations

import numpy as np

try:  # pragma: no cover - exercised implicitly by which branch runs
    import sklearn  # noqa: F401

    HAVE_SKLEARN = True
except ImportError:
    HAVE_SKLEARN = False


# ---------------------------------------------------------------------------
# k-nearest neighbors (sklearn.neighbors.NearestNeighbors, brute force)
# ---------------------------------------------------------------------------


def knn_brute(db: np.ndarray, queries: np.ndarray, k: int):
    """Exact euclidean kNN: (distances, indices), each (n_queries, k)."""
    if HAVE_SKLEARN:
        from sklearn.neighbors import NearestNeighbors

        nn = NearestNeighbors(n_neighbors=k, algorithm="brute").fit(db)
        return nn.kneighbors(queries)
    # Exact pairwise distances without the Gram trick (the system under test
    # uses ‖x‖²+‖y‖²−2xy; the oracle must be independent of it).
    diff = queries[:, None, :] - db[None, :, :]
    d2 = np.einsum("qnd,qnd->qn", diff, diff)
    idx = np.argsort(d2, axis=1, kind="stable")[:, :k]
    d = np.sqrt(np.take_along_axis(d2, idx, axis=1))
    return d, idx


# ---------------------------------------------------------------------------
# Ridge (sklearn.linear_model.Ridge: min ‖y−Xw−b‖² + alpha‖w‖², b unpenalized)
# ---------------------------------------------------------------------------


def ridge(x: np.ndarray, y: np.ndarray, alpha: float, fit_intercept: bool = True):
    """Returns (coef, intercept) minimizing ‖y−Xw−b‖² + alpha‖w‖²."""
    if HAVE_SKLEARN:
        from sklearn.linear_model import Ridge

        m = Ridge(alpha=alpha, fit_intercept=fit_intercept).fit(x, y)
        return m.coef_, float(m.intercept_) if fit_intercept else 0.0
    x = np.asarray(x, dtype=np.float64)
    y = np.asarray(y, dtype=np.float64)
    if fit_intercept:
        xm, ym = x.mean(axis=0), y.mean()
        xc, yc = x - xm, y - ym
    else:
        xc, yc = x, y
    d = x.shape[1]
    w = np.linalg.solve(xc.T @ xc + alpha * np.eye(d), xc.T @ yc)
    b = float(ym - xm @ w) if fit_intercept else 0.0
    return w, b


# ---------------------------------------------------------------------------
# Lasso / ElasticNet (sklearn objective:
#   1/(2n)‖y−Xw−b‖² + alpha·l1_ratio‖w‖₁ + alpha(1−l1_ratio)/2‖w‖²)
# ---------------------------------------------------------------------------


def elastic_net(
    x: np.ndarray,
    y: np.ndarray,
    alpha: float,
    l1_ratio: float = 1.0,
    fit_intercept: bool = True,
    max_iter: int = 10000,
    tol: float = 1e-12,
):
    """Returns (coef, intercept) via cyclic coordinate descent."""
    if HAVE_SKLEARN:
        from sklearn.linear_model import ElasticNet, Lasso

        if l1_ratio == 1.0:
            m = Lasso(alpha=alpha, fit_intercept=fit_intercept, max_iter=max_iter)
        else:
            m = ElasticNet(
                alpha=alpha,
                l1_ratio=l1_ratio,
                fit_intercept=fit_intercept,
                max_iter=max_iter,
            )
        m.fit(x, y)
        return m.coef_, float(m.intercept_) if fit_intercept else 0.0
    x = np.asarray(x, dtype=np.float64)
    y = np.asarray(y, dtype=np.float64)
    n, d = x.shape
    if fit_intercept:
        xm, ym = x.mean(axis=0), y.mean()
        xc, yc = x - xm, y - ym
    else:
        xm, ym = np.zeros(d), 0.0
        xc, yc = x, y
    l1 = alpha * l1_ratio
    l2 = alpha * (1.0 - l1_ratio)
    col_sq = (xc * xc).sum(axis=0) / n  # (1/n)‖x_j‖²
    w = np.zeros(d)
    r = yc.copy()  # residual y − Xw
    for _ in range(max_iter):
        w_max = 0.0
        dw_max = 0.0
        for j in range(d):
            if col_sq[j] == 0.0:
                continue
            wj = w[j]
            rho = xc[:, j] @ r / n + col_sq[j] * wj
            wn = np.sign(rho) * max(abs(rho) - l1, 0.0) / (col_sq[j] + l2)
            if wn != wj:
                r += xc[:, j] * (wj - wn)
                w[j] = wn
            w_max = max(w_max, abs(wn))
            dw_max = max(dw_max, abs(wn - wj))
        if w_max == 0.0 or dw_max / max(w_max, 1e-30) < tol:
            break
    b = float(ym - xm @ w) if fit_intercept else 0.0
    return w, b


# ---------------------------------------------------------------------------
# Logistic regression (sklearn lbfgs objective:
#   C·Σᵢ logloss(xᵢ, yᵢ) + ½‖w‖², intercept unpenalized; multinomial softmax)
# ---------------------------------------------------------------------------


class _LogRegResult:
    def __init__(self, coef, intercept, classes):
        self.coef_ = coef
        self.intercept_ = intercept
        self.classes_ = classes

    def predict(self, x):
        z = x @ self.coef_.T + self.intercept_
        if z.shape[1] == 1:
            return self.classes_[(z[:, 0] > 0).astype(int)]
        return self.classes_[np.argmax(z, axis=1)]

    def score(self, x, y):
        return float(np.mean(self.predict(x) == np.asarray(y)))


def logreg(x: np.ndarray, y: np.ndarray, C: float, tol: float = 1e-10, max_iter: int = 5000):
    """sklearn-LogisticRegression-shaped result (coef_, intercept_, score)."""
    if HAVE_SKLEARN:
        from sklearn.linear_model import LogisticRegression

        return LogisticRegression(C=C, tol=tol, max_iter=max_iter).fit(x, y)
    from scipy.optimize import minimize
    from scipy.special import logsumexp

    x = np.asarray(x, dtype=np.float64)
    y = np.asarray(y)
    classes = np.unique(y)
    n, d = x.shape
    n_classes = len(classes)
    if n_classes == 2:
        t = (y == classes[1]).astype(np.float64) * 2.0 - 1.0  # ±1

        def obj(p):
            w, b = p[:d], p[d]
            z = t * (x @ w + b)
            # log(1+e^{−z}) stably
            loss = np.logaddexp(0.0, -z).sum()
            sig = 1.0 / (1.0 + np.exp(np.clip(z, -700, 700)))
            g_z = -t * sig
            gw = C * (x.T @ g_z) + w
            gb = C * g_z.sum()
            return C * loss + 0.5 * w @ w, np.concatenate([gw, [gb]])

        res = minimize(obj, np.zeros(d + 1), jac=True, method="L-BFGS-B",
                       tol=tol, options={"maxiter": max_iter})
        w, b = res.x[:d], res.x[d]
        return _LogRegResult(w[None, :], np.array([b]), classes)
    onehot = (y[:, None] == classes[None, :]).astype(np.float64)

    def obj(p):
        W = p[: d * n_classes].reshape(n_classes, d)
        b = p[d * n_classes:]
        z = x @ W.T + b  # (n, c)
        lse = logsumexp(z, axis=1)
        loss = (lse - (z * onehot).sum(axis=1)).sum()
        p_soft = np.exp(z - lse[:, None])
        g_z = p_soft - onehot  # (n, c)
        gW = C * (g_z.T @ x) + W
        gb = C * g_z.sum(axis=0)
        return C * loss + 0.5 * (W * W).sum(), np.concatenate([gW.ravel(), gb])

    res = minimize(obj, np.zeros(d * n_classes + n_classes), jac=True,
                   method="L-BFGS-B", tol=tol, options={"maxiter": max_iter})
    W = res.x[: d * n_classes].reshape(n_classes, d)
    b = res.x[d * n_classes:]
    return _LogRegResult(W, b, classes)


# ---------------------------------------------------------------------------
# Random forest / decision tree accuracy
# (sklearn.ensemble.RandomForestClassifier; fallback: one exact-split
# Gini CART tree — an INDEPENDENT implementation: exhaustive real-valued
# thresholds, recursive, no binning — the differential point being that
# the histogram approximation should not cost accuracy on easy data)
# ---------------------------------------------------------------------------


def _np_tree_fit(x, y, n_classes, depth, min_rows=2):
    counts = np.bincount(y.astype(int), minlength=n_classes)
    leaf = ("leaf", int(np.argmax(counts)))
    if depth == 0 or len(y) < min_rows or counts.max() == len(y):
        return leaf
    n, d = x.shape
    parent = 1.0 - np.sum((counts / len(y)) ** 2)
    best = (0.0, None)
    for j in range(d):
        order = np.argsort(x[:, j], kind="stable")
        xs, ys = x[order, j], y[order].astype(int)
        # candidate thresholds: midpoints between distinct neighbors
        distinct = np.nonzero(np.diff(xs))[0]
        if distinct.size > 64:  # bound the scan; keep the oracle honest
            distinct = distinct[:: max(1, distinct.size // 64)]
        onehot = np.eye(n_classes)[ys]
        cum = np.cumsum(onehot, axis=0)
        for i in distinct:
            cl = cum[i]
            cr = counts - cl
            nl, nr = i + 1.0, n - i - 1.0
            gl = 1.0 - np.sum((cl / nl) ** 2)
            gr = 1.0 - np.sum((cr / nr) ** 2)
            gain = parent - (nl * gl + nr * gr) / n
            if gain > best[0] + 1e-12:
                best = (gain, (j, (xs[i] + xs[i + 1]) / 2.0))
    if best[1] is None:
        return leaf
    j, thr = best[1]
    mask = x[:, j] <= thr
    return (
        "split", j, thr,
        _np_tree_fit(x[mask], y[mask], n_classes, depth - 1, min_rows),
        _np_tree_fit(x[~mask], y[~mask], n_classes, depth - 1, min_rows),
    )


def _np_tree_predict(tree, x):
    out = np.empty(x.shape[0], dtype=np.int64)
    for i, row in enumerate(x):
        node = tree
        while node[0] == "split":
            _, j, thr, left, right = node
            node = left if row[j] <= thr else right
        out[i] = node[1]
    return out


def forest_accuracy(
    x_train, y_train, x_test, y_test, n_estimators=20, max_depth=8, seed=0
):
    """Oracle test accuracy for a classification problem: sklearn's
    RandomForestClassifier when installed, else one exact-split CART
    tree (same Gini objective, no binning, no bagging — a fair accuracy
    bar on the easy synthetic data the differential tests use)."""
    x_train = np.asarray(x_train, np.float64)
    x_test = np.asarray(x_test, np.float64)
    y_train = np.asarray(y_train).astype(int)
    y_test = np.asarray(y_test).astype(int)
    if HAVE_SKLEARN:
        from sklearn.ensemble import RandomForestClassifier

        m = RandomForestClassifier(
            n_estimators=n_estimators, max_depth=max_depth, random_state=seed
        ).fit(x_train, y_train)
        return float(m.score(x_test, y_test))
    n_classes = int(max(y_train.max(), y_test.max())) + 1
    tree = _np_tree_fit(x_train, y_train, n_classes, max_depth)
    return float(np.mean(_np_tree_predict(tree, x_test) == y_test))


# ---------------------------------------------------------------------------
# KMeans inertia (sklearn.cluster.KMeans with n_init restarts)
# ---------------------------------------------------------------------------


def kmeans_inertia(pts: np.ndarray, k: int, n_init: int = 3, seed: int = 0) -> float:
    """Best inertia over n_init Lloyd runs with kmeans++-style seeding."""
    if HAVE_SKLEARN:
        from sklearn.cluster import KMeans

        return float(KMeans(n_clusters=k, n_init=n_init, random_state=seed).fit(pts).inertia_)
    rng = np.random.default_rng(seed)
    pts = np.asarray(pts, dtype=np.float64)
    best = np.inf
    for _ in range(n_init):
        # kmeans++ seeding
        centers = [pts[rng.integers(len(pts))]]
        for _ in range(k - 1):
            d2 = np.min(
                ((pts[:, None, :] - np.asarray(centers)[None]) ** 2).sum(-1), axis=1
            )
            p = d2 / d2.sum()
            centers.append(pts[rng.choice(len(pts), p=p)])
        c = np.asarray(centers)
        for _ in range(300):
            d2 = ((pts[:, None, :] - c[None]) ** 2).sum(-1)
            assign = np.argmin(d2, axis=1)
            newc = np.array(
                [
                    pts[assign == j].mean(axis=0) if np.any(assign == j) else c[j]
                    for j in range(k)
                ]
            )
            if np.allclose(newc, c):
                c = newc
                break
            c = newc
        inertia = float(((pts - c[assign]) ** 2).sum())
        best = min(best, inertia)
    return best
