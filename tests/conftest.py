"""Test harness: virtual 8-device CPU mesh + float64 parity mode.

This is the "fake backend" testing capability the reference lacks
(SURVEY.md §4): multi-device sharding tests with no hardware, via
``--xla_force_host_platform_device_count``. Environment must be set before
jax import, hence the top-of-conftest placement.

float64 is enabled so differential tests against NumPy/sklearn oracles can
assert at the reference's absTol 1e-5 (PCASuite.scala:80-87); a separate
test exercises the float32 TPU-native mode with wider tolerance.
"""

import os
import tempfile

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the image pre-sets a TPU platform
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "True")
# Per-SESSION persistent compilation cache, inherited by every spawned
# worker process (daemon workers, multiproc ranks, forkserver tasks): the
# 2-OS-process tests compile identical programs in both workers — a shared
# cache turns the twin's compile into a disk hit. Ephemeral dir: a fresh
# ``pytest`` run measures honest first-compile cost once, not stale state.
if "JAX_COMPILATION_CACHE_DIR" not in os.environ:
    os.environ["JAX_COMPILATION_CACHE_DIR"] = tempfile.mkdtemp(
        prefix="srml-jax-cache-"
    )
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")
# Package dtype defaults for parity testing (overridden per-test via
# config.option for float32-mode tests).
os.environ.setdefault("SRML_TPU_ACCUM_DTYPE", "float64")
os.environ.setdefault("SRML_TPU_COMPUTE_DTYPE", "float64")

import jax  # noqa: E402

# The image's sitecustomize registers the TPU backend and sets
# jax.config.jax_platforms directly, which beats the env var — override the
# config itself (must happen before the first backend touch).
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402

from spark_rapids_ml_tpu.parallel.mesh import make_mesh  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {devs}"
    return devs


@pytest.fixture(scope="session")
def mesh8(devices):
    return make_mesh(data=8, model=1)


@pytest.fixture(scope="session")
def mesh4x2(devices):
    return make_mesh(data=4, model=2)


@pytest.fixture(scope="session")
def mesh1(devices):
    return make_mesh(data=1, model=1, devices=devices[:1])


@pytest.fixture
def rng():
    return np.random.default_rng(42)
