"""Within-run IVF query stage profile (dev-chip drift-proof).

Cross-run comparisons on the shared dev chip are invalid (documented
within-session speed decay: an unchanged control fell 127→109k q/s in an
hour), so this profiler interleaves ALL stages' measurements in one
process — cycle 1 measures probe/bucket/scan/full back-to-back, then
cycle 2, ... — and reports per-stage medians. Stage cuts are the
``_debug_stage`` hooks in models/knn.py: each cut keeps everything up to
that point live (data-dependent outputs, no DCE) and drops the rest.

Run: python benchmarks/profile_ivf_stages.py   (same env knobs as
bench_knn). Prints one JSON line with per-stage ms and deltas.
"""

import json
import os
import sys

if __package__ in (None, ""):
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

D = int(os.environ.get("SRML_BENCH_D", 768))
N_BASE = int(os.environ.get("SRML_BENCH_BASE_ROWS", 1 << 20))
N_QUERY = int(os.environ.get("SRML_BENCH_QUERIES", 4096))
K = int(os.environ.get("SRML_BENCH_K", 10))
NLIST = int(os.environ.get("SRML_BENCH_NLIST", 1024))
NPROBE = int(os.environ.get("SRML_BENCH_NPROBE", 32))
NCLUST = int(os.environ.get("SRML_BENCH_CLUSTERS", 4096))
REPS = int(os.environ.get("SRML_BENCH_REPS", 8))
CYCLES = int(os.environ.get("SRML_BENCH_CYCLES", 5))


def main() -> None:
    from benchmarks import setup_platform, slope_dt, sync

    setup_platform()
    import jax
    import jax.numpy as jnp

    from spark_rapids_ml_tpu import config
    from spark_rapids_ml_tpu.models.knn import (
        _ivf_query_fn,
        _residual_index_data,
        build_ivf_flat_device,
    )

    config.set("compute_dtype", "bfloat16")
    config.set("accum_dtype", "float32")
    config.set("use_pallas", True)

    cc = jax.random.normal(jax.random.key(7), (NCLUST, D), jnp.float32)
    assign = jax.random.randint(jax.random.key(8), (N_BASE,), 0, NCLUST)
    base = cc[assign] + 0.35 * jax.random.normal(
        jax.random.key(9), (N_BASE, D), jnp.float32
    )
    qassign = jax.random.randint(jax.random.key(10), (N_QUERY,), 0, NCLUST)
    queries = cc[qassign] + 0.35 * jax.random.normal(
        jax.random.key(11), (N_QUERY, D), jnp.float32
    )
    index = build_ivf_flat_device(base, nlist=NLIST, seed=0)
    del base
    dev = [
        jnp.asarray(index.centroids, dtype=jnp.float32),
        jnp.asarray(index.lists, dtype=jnp.float32),
        jnp.asarray(index.list_ids),
        jnp.asarray(index.list_mask),
    ]
    norms, lists_lo = _residual_index_data(dev[1], dev[0], jnp.bfloat16)

    stages = [
        ("dispatch", dict(rerank=False, _debug_stage="dispatch")),
        ("probe", dict(rerank=False, _debug_stage="probe")),
        ("bucket", dict(rerank=False, _debug_stage="bucket")),
        ("scan_nosel", dict(rerank=False, _debug_stage="scan_nosel")),
        ("scan", dict(rerank=False, _debug_stage="scan")),
        ("full_norerank", dict(rerank=False)),
        ("rerank_norescore", dict(rerank=True, _debug_stage="rerank_norescore")),
        ("full_rerank", dict(rerank=True)),
    ]
    fns = {
        name: _ivf_query_fn(K, NPROBE, "bfloat16", "float32", **kw)
        for name, kw in stages
    }

    def make_run(fn):
        def run(n):
            out = None
            for _ in range(n):
                _, out = fn(*dev, queries, resid_norms=norms, lists_lo=lists_lo)
            sync(out)
            return out
        return run

    runs = {name: make_run(fn) for name, fn in fns.items()}
    for r in runs.values():  # compile + warm both sizes, outside samples
        r(REPS)
        r(3 * REPS)
    samples = {name: [] for name, _ in stages}
    for _ in range(CYCLES):  # interleave so drift hits all stages alike
        for name, _ in stages:
            samples[name].append(
                slope_dt(runs[name], REPS, 3 * REPS, warm=False) * 1e3
            )
    med = {name: float(np.median(v)) for name, v in samples.items()}
    out = {
        "metric": "ivf_stage_profile_ms_per_call",
        **{f"{n}_ms": round(v, 3) for n, v in med.items()},
        "probe_minus_dispatch_ms": round(med["probe"] - med["dispatch"], 3),
        "bucket_minus_probe_ms": round(med["bucket"] - med["probe"], 3),
        "scan_nosel_minus_bucket_ms": round(med["scan_nosel"] - med["bucket"], 3),
        "scan_minus_bucket_ms": round(med["scan"] - med["bucket"], 3),
        "sel_in_scan_ms": round(med["scan"] - med["scan_nosel"], 3),
        "select_minus_scan_ms": round(med["full_norerank"] - med["scan"], 3),
        "rerank_extra_ms": round(med["full_rerank"] - med["full_norerank"], 3),
        "rescore_in_graph_ms": round(
            med["full_rerank"] - med["rerank_norescore"], 3
        ),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
