"""Doc-sufficiency test: the from-scratch C++ client (built ONLY from
docs/protocol.md — no Arrow, no JSON library) must interoperate with a
live daemon: ping handshake, feed_raw through the exactly-once
partition/commit path, PCA finalize, and numerically-correct results.

If this fails after a protocol change, the spec and the implementation
drifted — the frozen-contract promise broke for every third-party
client (the JVM interop story rides on exactly this, README "Scope").
"""

import os
import shutil
import subprocess

import numpy as np
import pytest

from spark_rapids_ml_tpu.serve.daemon import DataPlaneDaemon

SRC = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "examples", "cpp_client", "minimal_client.cpp",
)


def _lcg_matrix(n, d):
    """The client's Numerical Recipes LCG, mirrored exactly: integer
    values in [-8, 8] so every statistic is f32-exact."""
    out = np.empty(n * d, dtype=np.float64)
    state = 12345
    for i in range(n * d):
        state = (state * 1664525 + 1013904223) & 0xFFFFFFFF
        out[i] = float(((state >> 16) % 17) - 8)
    return out.reshape(n, d)


@pytest.fixture(scope="module")
def client_bin(tmp_path_factory):
    gxx = shutil.which("g++")
    if gxx is None:
        pytest.skip("no g++ on this host")
    exe = str(tmp_path_factory.mktemp("cppclient") / "minimal_client")
    subprocess.run([gxx, "-O2", "-o", exe, SRC], check=True)
    return exe


def test_cpp_client_full_session(client_bin, mesh8):
    n, d, k = 512, 8, 2
    with DataPlaneDaemon(mesh=mesh8) as daemon:
        host, port = daemon.address
        out = subprocess.run(
            [client_bin, host, str(port), str(n), str(d), str(k)],
            capture_output=True, text=True, timeout=300, check=True,
        ).stdout
    lines = out.strip().splitlines()
    assert lines[0] == "ping ok v=1"
    assert lines[1] == f"rows {n}"
    arrays = {}
    for line in lines[2:]:
        assert line.startswith("array ")
        head, vals = line.split(" :", 1)
        parts = head.split()
        name, shape = parts[1], tuple(int(s) for s in parts[2:])
        arrays[name] = np.fromstring(vals, sep=" ").reshape(shape)
    assert set(arrays) == {"pc", "explained_variance", "sigma", "mean"}
    assert arrays["pc"].shape == (d, k)

    x = _lcg_matrix(n, d)
    np.testing.assert_allclose(arrays["mean"], x.mean(axis=0), atol=1e-9)
    xc = x - x.mean(axis=0)
    evals, evecs = np.linalg.eigh(xc.T @ xc / (n - 1))
    order = np.argsort(evals)[::-1]
    np.testing.assert_allclose(
        np.abs(arrays["pc"]), np.abs(evecs[:, order[:k]]), atol=1e-8
    )
    # Reference semantics (rapidsml_jni.cu:254 seqRoot): the ratio
    # normalizes the SQUARE ROOTS of the eigenvalues, σᵢ/Σσ.
    s = np.sqrt(np.clip(evals[order], 0, None))
    np.testing.assert_allclose(
        arrays["explained_variance"], s[:k] / s.sum(), atol=1e-8
    )
