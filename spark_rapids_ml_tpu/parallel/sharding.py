"""Host array -> sharded device array placement helpers.

The reference's data placement is Spark's: partitions land wherever tasks are
scheduled and each task grabs its assigned GPU (TaskContext.resources(),
RapidsRowMatrix.scala:125-126). Here placement is explicit: rows are padded
to a multiple of the data-axis size and placed with a NamedSharding, so the
whole fit is one SPMD program.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_rapids_ml_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS


def pad_rows(x: np.ndarray, multiple: int) -> Tuple[np.ndarray, np.ndarray]:
    """Pad rows with zeros to a multiple; returns (padded, row_mask).

    The mask rides along into the sharded stats kernels so padded rows
    contribute nothing to counts/sums/Grams — the moment-based algorithms
    stay exact under padding (tested by shard-count invariance, SURVEY.md §4).
    """
    n = x.shape[0]
    n_pad = (-n) % multiple
    mask = np.ones((n,), dtype=np.float32)
    if n_pad:
        x = np.concatenate([x, np.zeros((n_pad,) + x.shape[1:], dtype=x.dtype)], axis=0)
        mask = np.concatenate([mask, np.zeros((n_pad,), dtype=np.float32)])
    return x, mask


def row_sharding(mesh: Mesh, ndim: int = 2) -> NamedSharding:
    """Rows over the data axis, everything else replicated."""
    spec = P(DATA_AXIS, *([None] * (ndim - 1)))
    return NamedSharding(mesh, spec)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_rows(
    x: np.ndarray,
    mesh: Mesh,
    dtype: Optional[Any] = None,
    with_mask: bool = True,
):
    """Pad + place a host matrix row-sharded on the mesh.

    Returns (x_sharded, mask_sharded, n_true_rows). ``jax.device_put`` with a
    NamedSharding splits the host buffer across devices without staging the
    full array on any single device.
    """
    n_true = x.shape[0]
    n_data = mesh.shape[DATA_AXIS]
    x = np.asarray(x)
    if dtype is not None and x.dtype != np.dtype(dtype):
        if x.dtype == np.float64 and np.dtype(dtype) == np.float32:
            from spark_rapids_ml_tpu.bridge import native as _native

            cast = _native.cast_f64_to_f32(x)  # threaded native cast
            x = cast if cast is not None else x.astype(np.float32)
        else:
            x = x.astype(dtype)
    x, mask = pad_rows(x, n_data)
    xs = jax.device_put(x, row_sharding(mesh, x.ndim))
    ms = jax.device_put(mask, row_sharding(mesh, 1)) if with_mask else None
    return xs, ms, n_true
