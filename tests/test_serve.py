"""Data-plane daemon tests: executor-fed accumulation over real sockets.

The distributed-feeding coverage the reference lacks entirely (SURVEY.md
§4: no multi-executor test) — here N concurrent "executors" (threads)
stream Arrow IPC partitions to the daemon over TCP and the finalized model
must equal the single-shot in-memory fit (associativity of the fold).
"""

import threading

import numpy as np
import pytest

from spark_rapids_ml_tpu.models.linear_regression import fit_linear_regression
from spark_rapids_ml_tpu.models.pca import fit_pca
from spark_rapids_ml_tpu.serve import DataPlaneClient, DataPlaneDaemon


@pytest.fixture
def daemon(mesh8):
    with DataPlaneDaemon(mesh=mesh8) as d:
        yield d


def _client(daemon):
    return DataPlaneClient(*daemon.address)


@pytest.fixture
def data(rng):
    n, d = 600, 24
    basis = rng.normal(size=(d, d)) * np.logspace(0, -1.5, d)
    return rng.normal(size=(n, d)) @ basis


def test_ping(daemon):
    with _client(daemon) as c:
        assert c.ping()


def test_pca_concurrent_executors_match_batch_fit(daemon, data, mesh8):
    k = 4
    parts = np.array_split(data, 4)
    errs = []

    def executor(part):
        try:
            with _client(daemon) as c:
                # two sub-batches per partition: exercises repeat feeds on
                # one connection
                for sub in np.array_split(part, 2):
                    c.feed("job-pca", sub, algo="pca")
        except Exception as e:  # pragma: no cover
            errs.append(e)

    threads = [threading.Thread(target=executor, args=(p,)) for p in parts]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    with _client(daemon) as c:
        assert c.status("job-pca")["rows"] == data.shape[0]
        out = c.finalize_pca("job-pca", k=k)
    ref = fit_pca(data, k=k, mesh=mesh8)
    np.testing.assert_allclose(np.abs(out["pc"]), np.abs(ref.pc), atol=1e-8)
    np.testing.assert_allclose(
        out["explained_variance"], ref.explained_variance, atol=1e-10
    )
    np.testing.assert_allclose(out["mean"], ref.mean, atol=1e-10)


def test_linreg_feed_finalize(daemon, data, mesh8, rng):
    w_true = rng.normal(size=(data.shape[1],))
    y = data @ w_true + 0.5 + 0.01 * rng.normal(size=data.shape[0])
    with _client(daemon) as c:
        for xs, ys in zip(np.array_split(data, 3), np.array_split(y, 3)):
            c.feed("job-lr", (xs, ys), algo="linreg")
        out = c.finalize_linreg("job-lr", reg=1e-6)
    ref = fit_linear_regression(data, y, reg=1e-6, mesh=mesh8)
    np.testing.assert_allclose(out["coefficients"], ref.coefficients, atol=1e-6)
    np.testing.assert_allclose(out["intercept"][0], ref.intercept, atol=1e-6)
    np.testing.assert_allclose(out["r2"][0], ref.summary.r2, atol=1e-8)


def test_finalize_drops_job_by_default(daemon, data):
    with _client(daemon) as c:
        c.feed("ephemeral", data)
        c.finalize_pca("ephemeral", k=2)
        with pytest.raises(RuntimeError, match="no such job"):
            c.status("ephemeral")


def test_two_jobs_interleave(daemon, data):
    a, b = data[:300], data[300:]
    with _client(daemon) as c:
        c.feed("a", a)
        c.feed("b", b)
        c.feed("a", a)
        assert c.status("a")["rows"] == 2 * a.shape[0]
        assert c.status("b")["rows"] == b.shape[0]
        assert c.drop("a")
        assert not c.drop("a")  # already gone


def test_feed_width_mismatch_rejected(daemon, data):
    with _client(daemon) as c:
        c.feed("w", data)
        with pytest.raises(RuntimeError, match="width"):
            c.feed("w", data[:, :10])
        # the error must not kill the connection: next op still works
        assert c.status("w")["rows"] == data.shape[0]


def test_unknown_op_and_unknown_job(daemon):
    with _client(daemon) as c:
        with pytest.raises(RuntimeError, match="unknown op"):
            c._roundtrip({"op": "nope"})
        with pytest.raises(RuntimeError, match="no such job"):
            c.status("never-created")


def test_linreg_missing_label_rejected(daemon, data):
    with _client(daemon) as c:
        with pytest.raises(RuntimeError, match="label"):
            c.feed("lr2", data, algo="linreg")


def test_algo_conflict_rejected(daemon, data, rng):
    y = rng.normal(size=data.shape[0])
    with _client(daemon) as c:
        c.feed("conf", data, algo="pca")
        with pytest.raises(RuntimeError, match="algo"):
            c.feed("conf", (data, y), algo="linreg")


def test_straggler_fold_after_finalize_rejected(daemon, data):
    # Straggler protection: a task holding the OLD job object (grabbed
    # before finalize popped it) must error on fold, not silently lose its
    # rows into a model that was already returned. (A new feed under the
    # same name legitimately starts a fresh job.)
    with _client(daemon) as c:
        c.feed("s", data)
        straggler_job = daemon._jobs["s"]
        c.finalize_pca("s", k=2)
    with pytest.raises(KeyError, match="finalized"):
        straggler_job.fold(data, None)


def test_finalize_k_out_of_range(daemon, data):
    with _client(daemon) as c:
        c.feed("kk", data)
        with pytest.raises(RuntimeError, match="out of range"):
            c.finalize_pca("kk", k=data.shape[1] + 1)


def test_result_arrays_writable(daemon, data):
    with _client(daemon) as c:
        c.feed("wr", data)
        out = c.finalize_pca("wr", k=2)
    out["pc"] *= -1.0  # callers own the result; must not be read-only


def test_bucket_padding_preserves_stats(daemon, data, mesh8):
    # Odd-sized batches land in power-of-two buckets; masked padding must
    # keep the statistics exact.
    parts = [data[:7], data[7:100], data[100:]]
    with _client(daemon) as c:
        for p in parts:
            c.feed("bp", p)
        out = c.finalize_pca("bp", k=3)
    ref = fit_pca(data, k=3, mesh=mesh8)
    np.testing.assert_allclose(np.abs(out["pc"]), np.abs(ref.pc), atol=1e-8)


def test_randomized_solver_over_the_wire(daemon, data, mesh8):
    with _client(daemon) as c:
        c.feed("rnd", data)
        out = c.finalize_pca("rnd", k=3, solver="randomized")
    ref = fit_pca(data, k=3, mesh=mesh8, solver="full")
    np.testing.assert_allclose(np.abs(out["pc"]), np.abs(ref.pc), atol=1e-6)


def test_kmeans_iterative_job_matches_stream_fit(daemon, rng, mesh8):
    # Daemon-side Lloyd must match fit_kmeans_stream given the same init
    # (both seed centers from the head of the data with the same rng).
    from spark_rapids_ml_tpu.models.kmeans import fit_kmeans_stream

    centers_true = rng.normal(size=(4, 12)) * 10
    pts = np.concatenate(
        [c + rng.normal(size=(200, 12)) for c in centers_true]
    ).astype(np.float32)
    perm = rng.permutation(len(pts))
    pts = pts[perm]
    parts = np.array_split(pts, 4)
    k, seed, passes = 4, 7, 5

    with _client(daemon) as c:
        for it in range(passes):
            for p in parts:
                c.feed(
                    "job-km", p, algo="kmeans",
                    params={"k": k, "seed": seed, "init": "random"},
                )
            info = c.step("job-km")
            assert info["iteration"] == it + 1
            assert info["pass_rows"] == len(pts)
        # one extra unstepped pass so finalize reports the final cost
        for p in parts:
            c.feed("job-km", p, algo="kmeans", params={"k": k})
        out = c.finalize_kmeans("job-km")

    # Reference: fit_kmeans_stream with random init over the first batch,
    # same seed -> same init rows (daemon seeds from its first batch).
    def source():
        return iter(parts)

    ref = fit_kmeans_stream(
        source, k=k, n_cols=12, max_iter=passes, tol=0.0, seed=seed,
        init="random", init_sample_rows=len(parts[0]), mesh=mesh8,
    )
    np.testing.assert_allclose(
        np.sort(out["centers"], axis=0), np.sort(ref.centers, axis=0), atol=1e-3
    )
    np.testing.assert_allclose(out["cost"][0], ref.cost, rtol=1e-5)


def test_logreg_iterative_job_matches_stream_fit(daemon, rng, mesh8):
    from spark_rapids_ml_tpu.models.logistic_regression import fit_logistic_stream

    w_true = rng.normal(size=10)
    x = rng.normal(size=(1200, 10)).astype(np.float32)
    y = (x @ w_true + 0.2 > 0).astype(np.float32)
    parts = [(x[i : i + 300], y[i : i + 300]) for i in range(0, 1200, 300)]
    reg, passes = 1e-3, 6

    with _client(daemon) as c:
        for it in range(passes):
            for px, py in parts:
                c.feed("job-lr", (px, py), algo="logreg")
            info = c.step("job-lr", params={"reg": reg})
            assert info["iteration"] == it + 1
        out = c.finalize_logreg("job-lr")

    def source():
        return iter(parts)

    ref = fit_logistic_stream(
        source, n_cols=10, reg=reg, max_iter=passes, tol=0.0, mesh=mesh8
    )
    np.testing.assert_allclose(out["coefficients"], ref.coefficients, atol=1e-5)
    np.testing.assert_allclose(out["intercept"][0], ref.intercept, atol=1e-5)


def test_step_on_single_pass_job_rejected(daemon, rng):
    with _client(daemon) as c:
        c.feed("job-p", rng.normal(size=(64, 6)), algo="pca")
        with pytest.raises(RuntimeError, match="single-pass"):
            c.step("job-p")


def test_step_with_empty_pass_rejected(daemon, rng):
    # A duplicate/premature step must error, not corrupt the iterate.
    with _client(daemon) as c:
        c.feed("job-km2", rng.normal(size=(64, 6)), algo="kmeans", params={"k": 4})
        c.step("job-km2")  # legitimate pass boundary
        with pytest.raises(RuntimeError, match="no rows fed"):
            c.step("job-km2")


def test_kmeans_first_batch_smaller_than_k_rejected_cleanly(daemon, rng):
    with _client(daemon) as c:
        with pytest.raises(RuntimeError, match="seeds the centers"):
            c.feed("job-km3", rng.normal(size=(3, 6)), algo="kmeans", params={"k": 8})
        # The rejected first feed must not leave an orphan job: a retry
        # with a proper batch (and its params) succeeds from scratch.
        c.feed("job-km3", rng.normal(size=(64, 6)), algo="kmeans", params={"k": 8})
        assert c.step("job-km3")["iteration"] == 1


def test_logreg_nonbinary_labels_rejected(daemon, rng):
    x = rng.normal(size=(32, 4))
    y = rng.integers(0, 3, size=32).astype(np.float64)
    with _client(daemon) as c:
        with pytest.raises(RuntimeError, match="binary"):
            c.feed("job-lr2", (x, y), algo="logreg")


def test_model_serving_roundtrip(daemon, data, mesh8):
    """ensure_model/transform/drop_model: the daemon's served copy must
    reproduce the core model's transform exactly, stay registered across
    calls, and reject transforms after drop."""
    from spark_rapids_ml_tpu.models.pca import PCA

    model = PCA(mesh=mesh8).setK(3).fit({"features": data})
    with _client(daemon) as c:
        assert c.ensure_model("srv", "pca", model._model_data()) is True
        # idempotent re-register: first copy wins
        assert c.ensure_model("srv", "pca", model._model_data()) is False
        assert c.model_exists("srv")
        outs = c.transform("srv", data[:100])
        np.testing.assert_allclose(
            outs["output"], model.transform_matrix(data[:100])["output"],
            atol=1e-12,
        )
        # batches of a different size reuse the registration
        outs2 = c.transform("srv", data[100:350])
        assert outs2["output"].shape == (250, 3)
        assert c.drop_model("srv") is True
        assert not c.model_exists("srv")
        with pytest.raises(RuntimeError, match="no such model"):
            c.transform("srv", data[:10])


def test_model_serving_algo_conflict_rejected(daemon, data, mesh8):
    from spark_rapids_ml_tpu.models.pca import PCA

    model = PCA(mesh=mesh8).setK(2).fit({"features": data})
    with _client(daemon) as c:
        c.ensure_model("conflicted", "pca", model._model_data())
        with pytest.raises(RuntimeError, match="algo"):
            c.ensure_model("conflicted", "kmeans", model._model_data())


def test_model_serving_params_configure_the_served_copy(daemon, data, mesh8):
    """Scaler withMean rides the registration params — the served copy
    must scale exactly like the configured local model."""
    from spark_rapids_ml_tpu.models.scaler import StandardScaler

    model = (
        StandardScaler(mesh=mesh8).setWithMean(True).fit({"features": data})
    )
    with _client(daemon) as c:
        c.ensure_model(
            "scl", "scaler", model._model_data(),
            params={"withMean": True, "withStd": True},
        )
        outs = c.transform("scl", data[:64])
        np.testing.assert_allclose(
            outs["output"], model.transform_matrix(data[:64])["output"], atol=0
        )


def test_multinomial_iterative_job_matches_stream_fit(daemon, rng, mesh8):
    """logreg job with n_classes>2 runs the multinomial MM-Newton
    protocol; the daemon-driven loop must match fit_multinomial_stream."""
    from spark_rapids_ml_tpu.models.logistic_regression import (
        fit_multinomial_stream,
    )

    n, d, C = 480, 5, 3
    x = rng.normal(size=(n, d))
    w = rng.normal(size=(d, C)) * 2
    y = np.argmax(x @ w, axis=1).astype(np.float64)
    reg, iters = 0.02, 6

    def src():
        return iter([(x[i : i + 120], y[i : i + 120]) for i in range(0, n, 120)])

    ref = fit_multinomial_stream(
        src, d, C, reg=reg, max_iter=iters, tol=0.0, mesh=mesh8
    )
    params = {"n_classes": C}
    with _client(daemon) as c:
        for it in range(iters):
            for i in range(0, n, 120):
                c.feed(
                    "mm-job", (x[i : i + 120], y[i : i + 120]), algo="logreg",
                    params=params, pass_id=it,
                )
            info = c.step("mm-job", params={"reg": reg, "fit_intercept": True})
        assert info["iteration"] == iters
        arrays = c.finalize_logreg("mm-job")
    assert arrays["coefficients"].shape == (C, d)
    np.testing.assert_allclose(arrays["coefficients"], ref.coefficients, atol=1e-9)
    np.testing.assert_allclose(arrays["intercept"], ref.intercept, atol=1e-9)
    assert int(arrays["n_iter"][0]) == iters


def test_logreg_n_classes_mismatch_rejected(daemon, rng):
    x = rng.normal(size=(60, 4))
    y = (x[:, 0] > 0).astype(np.float64)
    with _client(daemon) as c:
        c.feed("cls-job", (x, y), algo="logreg", params={"n_classes": 3})
        with pytest.raises(RuntimeError, match="n_classes"):
            c.feed("cls-job", (x, y), algo="logreg", params={"n_classes": 4})


def test_daemon_ivf_build_shards_over_full_mesh(daemon, rng, mesh8):
    """VERDICT r3 missing #4: the daemon-built ANN index must shard its
    inverted lists over the daemon's WHOLE mesh (the config-#5 capacity
    path), and sharded serving must match the unsharded oracle build on
    the same rows."""
    from spark_rapids_ml_tpu.models.knn import (
        ApproximateNearestNeighborsModel,
        build_ivf_flat,
    )

    kc, d, k = 8, 12, 5
    centers = rng.normal(size=(kc, d)) * 10
    x = np.concatenate([c + rng.normal(size=(70, d)) for c in centers]).astype(
        np.float32
    )
    q = x[:32]
    with _client(daemon) as c:
        for pid, part in enumerate(np.array_split(x, 3)):
            c.feed("shard-knn", part, algo="knn", partition=pid)
            c.commit("shard-knn", partition=pid)
        info = c.finalize_knn(
            "shard-knn", register_as="shard-idx", mode="ivf",
            nlist=kc, nprobe=kc, seed=0,
        )
        assert int(info["sharded"][0]) == 1
        served = daemon._models["shard-idx"].model
        assert served._shard_mesh is not None
        # every device holds only its list shard, not the whole index
        lists = served._dev_index[1]
        shard_rows_per_dev = {
            db.shape[0] for db in [s.data for s in lists.addressable_shards]
        }
        assert max(shard_rows_per_dev) < lists.shape[0]
        dists, idx = c.kneighbors("shard-idx", q, k=k)
    # unsharded oracle on the same rows (same build seed → same lists)
    oracle = ApproximateNearestNeighborsModel(
        index=build_ivf_flat(x, nlist=kc, seed=0)
    )
    oracle._set(nprobe=kc)
    od, oi = oracle.kneighbors(q, k=k)
    # probe-all → both are exact within padded lists; allow boundary ties
    recall = np.mean([len(set(idx[i]) & set(oi[i])) / k for i in range(len(q))])
    assert recall > 0.95, recall
    np.testing.assert_allclose(np.sort(dists, 1), np.sort(od, 1), atol=1e-3)


def test_cross_daemon_sharded_ivf_protocol(rng, mesh8):
    """The sharded-index finalize extensions at the protocol level (no
    Spark layer): two daemons each hold some partitions; daemon A's
    build trains and returns the quantizer, B buckets against the same
    frozen centroids; row_id_base globalizes ids; the caller merges
    per-shard kneighbors. Probe-all + rerank ⇒ the merged answer is the
    exact brute-force top-k."""
    from spark_rapids_ml_tpu.models.knn import merge_topk
    from spark_rapids_ml_tpu.serve import DataPlaneClient, DataPlaneDaemon

    kc, d, k = 6, 10, 4
    centers = rng.normal(size=(kc, d)) * 10
    x = np.concatenate(
        [c + rng.normal(size=(50, d)) for c in centers]
    ).astype(np.float32)
    x = x[rng.permutation(len(x))]
    q = x[:24]
    parts = np.array_split(x, 4)
    base = {i: int(sum(len(p) for p in parts[:i])) for i in range(4)}
    with DataPlaneDaemon(mesh=mesh8) as da, DataPlaneDaemon(mesh=mesh8) as db:
        ca, cb = DataPlaneClient(*da.address), DataPlaneClient(*db.address)
        for pid, c in ((0, ca), (1, ca), (2, cb), (3, cb)):
            c.feed("j", parts[pid], algo="knn", partition=pid)
            c.commit("j", partition=pid)
        info_a = ca.finalize_knn(
            "j", register_as="sharded", mode="ivf", nlist=kc, nprobe=kc,
            row_id_base={0: base[0], 1: base[1]}, return_centroids=True,
        )
        assert int(info_a["n_rows"][0]) == len(parts[0]) + len(parts[1])
        cent = info_a["centroids"]
        assert cent.shape == (kc, d)
        info_b = cb.finalize_knn(
            "j", register_as="sharded", mode="ivf", nlist=kc, nprobe=kc,
            row_id_base={2: base[2], 3: base[3]}, centroids=cent,
        )
        assert int(info_b["n_rows"][0]) == len(parts[2]) + len(parts[3])
        # both shards bucket against bitwise-identical centroids
        np.testing.assert_array_equal(
            np.asarray(da._models["sharded"].model.index.centroids), cent
        )
        np.testing.assert_array_equal(
            np.asarray(db._models["sharded"].model.index.centroids), cent
        )
        d_a, i_a = ca.kneighbors("sharded", q, k=k)
        d_b, i_b = cb.kneighbors("sharded", q, k=k)
        ca.close(), cb.close()
    dists, idx = merge_topk([d_a, d_b], [i_a, i_b], k)
    d2 = ((q[:, None, :].astype(np.float64) - x[None, :, :]) ** 2).sum(-1)
    want = np.argsort(d2, axis=1, kind="stable")[:, :k]
    np.testing.assert_array_equal(np.sort(idx, 1), np.sort(want, 1))


def test_daemon_ivf_host_build_path(daemon, rng, monkeypatch):
    """Past the device-build HBM cap, the build runs host-side and the
    sharded placement never lands a full copy on one device. Forced here
    via the cap env knob = 0 (build='auto' → host)."""
    from spark_rapids_ml_tpu.serve import daemon as daemon_mod

    monkeypatch.setattr(daemon_mod, "_IVF_DEVICE_BUILD_MAX_BYTES", 0)
    kc, d, k = 6, 8, 4
    centers = rng.normal(size=(kc, d)) * 8
    x = np.concatenate([c + rng.normal(size=(50, d)) for c in centers]).astype(
        np.float32
    )
    with _client(daemon) as c:
        c.feed("host-knn", x, algo="knn")
        info = c.finalize_knn(
            "host-knn", register_as="host-idx", mode="ivf",
            nlist=kc, nprobe=kc, seed=1,
        )
        assert int(info["sharded"][0]) == 1
        dists, idx = c.kneighbors("host-idx", x[:16], k=k)
    assert idx.shape == (16, k)
    # self is among the neighbors (exact within probed lists, probe-all)
    assert all(i in set(idx[i]) for i in range(16))


def test_sample_rows_op(daemon, rng):
    """The cross-daemon quantizer-training primitive (ADVICE r5(b)):
    seeded, deterministic, uniform over every COMMITTED row across
    partitions, clamped to the committed total — and refused for non-knn
    jobs (they hold O(d²) statistics, not rows)."""
    x = rng.normal(size=(300, 6)).astype(np.float64)
    with _client(daemon) as c:
        for pid, part in enumerate(np.array_split(x, 3)):
            c.feed("samp", part, algo="knn", partition=pid)
            c.commit("samp", partition=pid)
        s1 = c.sample_rows("samp", 50, seed=7)
        s2 = c.sample_rows("samp", 50, seed=7)
        assert s1.shape == (50, 6)
        np.testing.assert_array_equal(s1, s2)  # seeded replay
        assert not np.array_equal(s1, c.sample_rows("samp", 50, seed=8))
        # Every sampled row is one of the committed rows, and distinct
        # (sampling is without replacement).
        fed = {row.tobytes() for row in np.asarray(x, s1.dtype)}
        got = [row.tobytes() for row in s1]
        assert set(got) <= fed
        assert len(set(got)) == len(got)
        # n past the committed total clamps (never errors, never pads).
        assert c.sample_rows("samp", 10_000, seed=0).shape[0] == 300
        # Sampling is read-only: the job still finalizes with every row.
        info = c.finalize_knn("samp", register_as="samp-idx", mode="exact")
        assert int(info["n_rows"][0]) == 300
        # Non-knn jobs refuse.
        c.feed("samp-pca", x, algo="pca")
        with pytest.raises(RuntimeError, match="knn"):
            c.sample_rows("samp-pca", 10)


def test_sample_rows_rejects_nonpositive_n(daemon, rng):
    with _client(daemon) as c:
        c.feed("sampz", rng.normal(size=(32, 4)), algo="knn", partition=0)
        c.commit("sampz", partition=0)
        with pytest.raises(RuntimeError, match="positive"):
            c.sample_rows("sampz", 0)
        with pytest.raises(RuntimeError, match="positive"):
            c.sample_rows("sampz", -5)


# ---------------------------------------------------------------------------
# AOT at registration (docs/protocol.md "AOT at registration")
# ---------------------------------------------------------------------------


def test_aot_on_register_zero_compile_misses(mesh8, rng):
    """The AOT acceptance claim: after ensure_model with AOT on, the
    first client transform at EVERY reachable bucket reports zero
    compile misses in the served instance's compile ledger (every
    dispatch runs a held executable), and the registration ack's
    warmup object carries aot: true."""
    from spark_rapids_ml_tpu import config
    from spark_rapids_ml_tpu.models.pca import PCA
    from spark_rapids_ml_tpu.serve import DataPlaneClient, DataPlaneDaemon

    d, k = 24, 4
    data = rng.standard_normal((256, d)).astype(np.float32)
    arrays = PCA().setK(k).fit({"features": data})._model_data()
    with config.option("serve_batching", True), \
            config.option("serve_warmup_on_register", True), \
            config.option("serve_aot", True):
        with DataPlaneDaemon(mesh=mesh8) as daemon:
            with DataPlaneClient(*daemon.address) as c:
                c.ensure_model("aot-m", "pca", arrays)
                served = daemon._models["aot-m"]
                st = served.aot_status()
                # Distinct device programs: sub-256 buckets collapse onto
                # the 256-row floor shape run_bucketed dispatches.
                want = len({max(256, b) for b in st["buckets"]})
                assert st is not None and st["compiled"] == want, st
                assert st["hits"] == 0 and st["misses"] == 0
                solo = PCA().setK(k).fit({"features": data})
                for bucket in st["buckets"]:
                    q = rng.standard_normal((bucket, d)).astype(np.float32)
                    out = c.transform("aot-m", q)["output"]
                    ref = solo.transform({"features": q})
                    np.testing.assert_allclose(
                        out, np.asarray(ref["pca_features"], out.dtype),
                        rtol=1e-5, atol=1e-6,
                    )
                st = served.aot_status()
                assert st["misses"] == 0, st
                assert st["hits"] >= len(st["buckets"]), st


def test_aot_warmup_op_ack_field(mesh8, rng):
    """The `warmup` op's ack gains the additive aot field: true when the
    ladder was AOT-compiled, false on the trace fallback (serve_aot
    off) — and a model without a plan degrades, never fails."""
    from spark_rapids_ml_tpu import config
    from spark_rapids_ml_tpu.models.pca import PCA
    from spark_rapids_ml_tpu.serve import DataPlaneClient, DataPlaneDaemon

    d = 16
    arrays = PCA().setK(2).fit(
        {"features": rng.standard_normal((64, d)).astype(np.float32)}
    )._model_data()
    with config.option("serve_batching", True):
        with DataPlaneDaemon(mesh=mesh8) as daemon:
            with DataPlaneClient(*daemon.address) as c:
                c.ensure_model("m", "pca", arrays)
                with config.option("serve_aot", True):
                    info = c.warmup("m", n_cols=d, dtype="float32")
                assert info["aot"] is True
                with config.option("serve_aot", False):
                    info = c.warmup("m", n_cols=d, dtype="float32")
                assert info["aot"] is False


def test_aot_primed_shapes_keep_cost_analysis(rng):
    """aot_prime pre-records its signature so later calls aren't fresh
    misses — but the ledger's cost analysis must still be populated for
    primed shapes, or the roofline reads flops/bytes-less for exactly
    the AOT-served hot entries."""
    import jax

    from spark_rapids_ml_tpu.utils.xprof import ledgered_jit, snapshot

    @ledgered_jit("test_serve.aot_analysis")
    def f(x):
        return x @ x.T

    assert f.aot_prime(
        jax.ShapeDtypeStruct((64, 8), np.dtype("float32"))
    ) is True
    f(rng.normal(size=(64, 8)).astype(np.float32))
    rec = snapshot()["test_serve.aot_analysis"]["signatures"][0]
    assert rec["flops"] is not None
    assert rec["bytes_accessed"] is not None


def test_knn_aot_plan_pads_like_kneighbors(mesh8, rng):
    """A sub-64 (or non-pow2) serve bucket must prime the shape the
    query path actually dispatches — kneighbors pads queries to
    max(64, next-pow2), not the raw scheduler bucket."""
    import jax

    from spark_rapids_ml_tpu.models.knn import NearestNeighbors
    from spark_rapids_ml_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(data=1, model=1, devices=jax.devices()[:1])
    db = rng.normal(size=(320, 16)).astype(np.float32)
    m = NearestNeighbors(mesh=mesh).setK(5).fit({"features": db})
    for bucket in (8, 32, 48):
        ((jit_obj, args),) = m._serve_aot_plan(bucket, 16, dtype="float32")
        assert args[-1].shape[0] == 64  # the real padded query shape
        jit_obj.aot_prime(*args)
    h0, m0 = jit_obj.aot_hits, jit_obj.aot_misses
    m.kneighbors(rng.normal(size=(8, 16)).astype(np.float32))
    m.kneighbors(rng.normal(size=(40, 16)).astype(np.float32))
    assert jit_obj.aot_hits == h0 + 2
    assert jit_obj.aot_misses == m0


def test_aot_warmup_wrong_width_still_errors(mesh8, rng):
    """A warmup with a wrong n_cols must keep erroring to the client
    (the pre-AOT contract): the plan's width check raises, AOT degrades
    to trace warmup, and the zero-batch dispatch surfaces the shape
    mismatch — never a success ack that pre-marks a shape no real
    traffic can produce."""
    from spark_rapids_ml_tpu import config
    from spark_rapids_ml_tpu.models.pca import PCA

    d = 16
    arrays = PCA().setK(2).fit(
        {"features": rng.standard_normal((64, d)).astype(np.float32)}
    )._model_data()
    with config.option("serve_batching", True):
        with DataPlaneDaemon(mesh=mesh8) as daemon:
            with _client(daemon) as c:
                c.ensure_model("m", "pca", arrays)
                with pytest.raises(RuntimeError):
                    c.warmup("m", n_cols=d - 6, dtype="float32")
