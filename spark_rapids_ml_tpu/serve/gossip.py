"""Gossiped fleet state: the daemon-resident ``FleetView``.

PR 9 made the routing table live per CLIENT process — the serve plane's
source of truth died with whichever client happened to hold it. This
module moves that state into the daemons themselves, Podracer-style
(PAPERS.md 2104.06272): every :class:`~.daemon.DataPlaneDaemon` keeps a
:class:`FleetView` — replica records plus the per-model version table —
and exchanges it with ``gossip_fanout`` peers per ``gossip_interval_s``
tick over the additive ``gossip_push``/``gossip_pull`` wire ops
(docs/protocol.md "Fleet gossip & bootstrap"). Clients become stateless
observers: a :class:`~.router.FleetClient` bootstraps its whole routing
table from ONE seed daemon's view and resyncs from whichever replica
answers it.

Anti-entropy merge rule — per record, ``(epoch, boot_id)`` dominance:

* Every record carries the ``epoch`` it was written at, minted from the
  existing membership epoch plane (``parallel/membership.py`` — gossip
  writes and join/leave/reboot bumps share ONE Lamport counter per
  process, and :meth:`FleetView.merge` runs the Lamport receive rule so
  local clocks always advance past every remote record they have seen).
* On merge, the record with the strictly higher epoch wins; an epoch
  tie breaks on ``boot_id`` (lexicographic — arbitrary but the SAME
  arbitrary everywhere, so two islands healing a partition converge on
  one winner instead of flapping).
* Deletions are TOMBSTONES, never absences: a retired replica keeps a
  ``liveness="tombstone"`` record and a retired model version an entry
  in the model record's ``tombstones`` map, each at the epoch of its
  retirement. A tombstone dominates like any record — resurrecting a
  retired replica/version requires a strictly newer epoch (a genuine
  re-join), so a stale island can never gossip a dead thing back to
  life. Tombstones are pruned only after ``gossip_tombstone_ttl_s``
  (config), which must exceed any plausible partition length.

Convergence: each tick every daemon pushes its view to ``gossip_fanout``
peers and merges the peer's view from the ack (push-pull in one RTT),
so a write reaches the whole fleet within ``gossip_interval_s ×
ring-diameter`` ticks — with fanout ≥ 2 the diameter is O(log N).

Thread model: a ``FleetView`` is shared between the daemon's connection
threads (``gossip_push``/``gossip_pull`` ops), its gossip thread, and
in-process control planes. ALL state lives behind ``self._lock``, a leaf
lock: no method calls out (no sockets, no device work, no other locks)
while holding it — the ``blocking-under-device-lock`` /
``lock-graph-cycle`` srml-check rules hold by construction. Epoch minting
(``membership.tick``/``observe``) happens OUTSIDE the view lock.
"""

from __future__ import annotations

import copy
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from spark_rapids_ml_tpu.parallel import membership as membership_mod
from spark_rapids_ml_tpu.utils import metrics as metrics_mod

__all__ = ["FleetView", "dominates"]

#: Gossip telemetry (docs/observability.md).
_M_MERGES = metrics_mod.counter(
    "srml_gossip_merges_total",
    "FleetView records adopted from a merged remote view, by kind "
    "(replica|model) — zero-adoption merges mean the views agree",
)
_M_VIEW_EPOCH = metrics_mod.gauge(
    "srml_gossip_view_epoch",
    "Highest record epoch in this process's FleetView (converged "
    "fleets report one value everywhere)",
)

#: Liveness states a replica record may carry. ``tombstone`` is the
#: retired state — it gossips like any record and never resurrects.
_LIVENESS = ("up", "down", "tombstone")


def dominates(a_epoch: int, a_boot: str, b_epoch: int, b_boot: str) -> bool:
    """The ONE merge rule: does record A dominate record B?
    ``(epoch, boot_id)`` lexicographic — strictly higher epoch wins,
    ties break on boot_id so every process picks the same winner."""
    return (int(a_epoch), str(a_boot)) > (int(b_epoch), str(b_boot))


class FleetView:
    """One process's view of the fleet: replica records keyed by
    ``server_id`` plus the per-model version table, every record
    stamped ``(epoch, boot_id)`` for the dominance merge.

    ``epoch_source``: the shared Lamport clock — anything with
    ``tick()``/``observe()`` (defaults to the process-wide
    :func:`~spark_rapids_ml_tpu.parallel.membership.registry`).
    """

    #: Wire-format version of ``to_wire`` (additive evolution only,
    #: like the protocol itself).
    WIRE_V = 1

    def __init__(
        self,
        epoch_source=None,
        tombstone_ttl_s: Optional[float] = None,
        clock=time.time,
    ):
        from spark_rapids_ml_tpu import config

        self._epochs = (
            membership_mod.registry() if epoch_source is None else epoch_source
        )
        self._ttl = float(
            config.get("gossip_tombstone_ttl_s")
            if tombstone_ttl_s is None else tombstone_ttl_s
        )
        self._clock = clock
        self._lock = threading.Lock()
        #: server_id → {"server_id","addr","boot_id","liveness",
        #:              "last_seen","epoch"}
        self._replicas: Dict[str, Dict[str, Any]] = {}
        #: model → {"model","active_version","fleet_epoch","intent",
        #:          "tombstones": {str(version): {"epoch","at"}},
        #:          "epoch","boot_id"}
        self._models: Dict[str, Dict[str, Any]] = {}

    # -- local writes (each mints a fresh epoch OUTSIDE the lock) -----------

    def observe_replica(
        self,
        server_id: str,
        addr: str,
        boot_id: str,
        liveness: str = "up",
        epoch: Optional[int] = None,
    ) -> Dict[str, Any]:
        """Write (or refresh) one replica record at a freshly minted
        epoch. ``epoch`` overrides only for record REPLAY (tests, the
        control plane echoing a record it already holds)."""
        if liveness not in _LIVENESS:
            raise ValueError(
                f"unknown liveness {liveness!r} (one of {_LIVENESS})"
            )
        e = self._epochs.tick() if epoch is None else int(epoch)
        rec = {
            "server_id": str(server_id),
            "addr": str(addr),
            "boot_id": str(boot_id),
            "liveness": liveness,
            "last_seen": float(self._clock()),
            "epoch": e,
        }
        with self._lock:
            self._replicas[str(server_id)] = rec
            self._refresh_epoch_gauge_locked()
        return dict(rec)

    def tombstone_replica(self, server_id: str) -> None:
        """Retire a replica: its record flips to a tombstone at a fresh
        epoch (it keeps gossiping — absence would let a stale island
        resurrect it)."""
        e = self._epochs.tick()
        with self._lock:
            rec = self._replicas.get(str(server_id))
            if rec is None:
                rec = {
                    "server_id": str(server_id), "addr": "",
                    "boot_id": "", "liveness": "tombstone",
                    "last_seen": float(self._clock()), "epoch": e,
                }
                self._replicas[str(server_id)] = rec
            else:
                rec["liveness"] = "tombstone"
                rec["last_seen"] = float(self._clock())
                rec["epoch"] = e
            self._refresh_epoch_gauge_locked()

    def set_model(
        self,
        model: str,
        active_version: Optional[int],
        fleet_epoch: int,
        boot_id: str,
        intent: Optional[Dict[str, Any]] = None,
        tombstone_versions: Tuple[int, ...] = (),
    ) -> Dict[str, Any]:
        """Write one model's version-table record (active version, the
        model's own fleet epoch from the rollout flip, and the current
        ``rollout_intent`` — None when no rollout is in flight) at a
        fresh gossip epoch. ``tombstone_versions`` adds retired
        versions to the record's tombstone map (they never re-install
        on a bootstrap)."""
        e = self._epochs.tick()
        now = float(self._clock())
        with self._lock:
            prev = self._models.get(str(model)) or {}
            tombs = dict(prev.get("tombstones") or {})
            for v in tombstone_versions:
                tombs[str(int(v))] = {"epoch": e, "at": now}
            rec = {
                "model": str(model),
                "active_version": (
                    None if active_version is None else int(active_version)
                ),
                "fleet_epoch": int(fleet_epoch),
                "intent": copy.deepcopy(intent) if intent else None,
                "tombstones": tombs,
                "epoch": e,
                "boot_id": str(boot_id),
            }
            self._models[str(model)] = rec
            self._refresh_epoch_gauge_locked()
        return copy.deepcopy(rec)

    # -- reads ---------------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """Deep copy of the whole view (tools/top, tests)."""
        with self._lock:
            return {
                "epoch": self._max_epoch_locked(),
                "replicas": copy.deepcopy(self._replicas),
                "models": copy.deepcopy(self._models),
            }

    def replicas(self, liveness: Optional[str] = None) -> List[Dict[str, Any]]:
        with self._lock:
            recs = [copy.deepcopy(r) for r in self._replicas.values()]
        if liveness is not None:
            recs = [r for r in recs if r["liveness"] == liveness]
        return sorted(recs, key=lambda r: r["server_id"])

    def model(self, model: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            rec = self._models.get(str(model))
            return None if rec is None else copy.deepcopy(rec)

    def epoch(self) -> int:
        """Highest record epoch held — the convergence probe: two views
        that agree report the same value (srml_gossip_view_epoch)."""
        with self._lock:
            return self._max_epoch_locked()

    def _max_epoch_locked(self) -> int:
        epochs = [int(r["epoch"]) for r in self._replicas.values()]
        epochs += [int(m["epoch"]) for m in self._models.values()]
        for m in self._models.values():
            epochs += [int(t["epoch"]) for t in (m.get("tombstones") or {}).values()]
        return max(epochs, default=0)

    def _refresh_epoch_gauge_locked(self) -> None:
        _M_VIEW_EPOCH.set(self._max_epoch_locked())

    # -- wire codec ----------------------------------------------------------

    def to_wire(self) -> Dict[str, Any]:
        """JSON-safe view for the ``gossip_push``/``gossip_pull`` acks
        (docs/protocol.md has the schema)."""
        snap = self.snapshot()
        return {
            "wire_v": self.WIRE_V,
            "epoch": snap["epoch"],
            "replicas": snap["replicas"],
            "models": snap["models"],
        }

    # -- anti-entropy merge --------------------------------------------------

    def merge(self, wire: Dict[str, Any]) -> int:
        """Fold a remote view in under ``(epoch, boot_id)`` dominance;
        returns how many records were adopted (0 = the views already
        agreed on everything the remote carried). Malformed records are
        skipped — one bad peer must not poison the view. Runs the
        Lamport receive rule on the shared epoch plane FIRST (outside
        the view lock), so every local write after this merge dominates
        every record the remote view carried."""
        if not isinstance(wire, dict):
            return 0
        self._epochs.observe(int(wire.get("epoch") or 0))
        adopted_replicas = 0
        adopted_models = 0
        remote_reps = wire.get("replicas")
        remote_models = wire.get("models")
        with self._lock:
            if isinstance(remote_reps, dict):
                for sid, rec in remote_reps.items():
                    if self._merge_replica_locked(str(sid), rec):
                        adopted_replicas += 1
            if isinstance(remote_models, dict):
                for name, rec in remote_models.items():
                    if self._merge_model_locked(str(name), rec):
                        adopted_models += 1
            self._prune_tombstones_locked()
            self._refresh_epoch_gauge_locked()
        if adopted_replicas:
            _M_MERGES.inc(adopted_replicas, kind="replica")
        if adopted_models:
            _M_MERGES.inc(adopted_models, kind="model")
        return adopted_replicas + adopted_models

    def _merge_replica_locked(self, sid: str, rec: Any) -> bool:
        if not isinstance(rec, dict):
            return False
        try:
            incoming = {
                "server_id": sid,
                "addr": str(rec.get("addr") or ""),
                "boot_id": str(rec.get("boot_id") or ""),
                "liveness": str(rec.get("liveness") or "up"),
                "last_seen": float(rec.get("last_seen") or 0.0),
                "epoch": int(rec.get("epoch") or 0),
            }
        except (TypeError, ValueError):
            return False
        if incoming["liveness"] not in _LIVENESS:
            return False
        held = self._replicas.get(sid)
        if held is not None and not dominates(
            incoming["epoch"], incoming["boot_id"],
            held["epoch"], held["boot_id"],
        ):
            return False
        self._replicas[sid] = incoming
        return True

    def _merge_model_locked(self, name: str, rec: Any) -> bool:
        if not isinstance(rec, dict):
            return False
        try:
            av = rec.get("active_version")
            incoming = {
                "model": name,
                "active_version": None if av is None else int(av),
                "fleet_epoch": int(rec.get("fleet_epoch") or 0),
                "intent": (
                    copy.deepcopy(rec["intent"])
                    if isinstance(rec.get("intent"), dict) else None
                ),
                "tombstones": {},
                "epoch": int(rec.get("epoch") or 0),
                "boot_id": str(rec.get("boot_id") or ""),
            }
        except (TypeError, ValueError):
            return False
        held = self._models.get(name)
        # Tombstones merge by UNION-at-max-epoch regardless of which
        # record wins: a version retirement seen by EITHER side holds —
        # this is what "tombstones never resurrect" means across a
        # partition heal.
        tombs: Dict[str, Dict[str, Any]] = dict(
            (held or {}).get("tombstones") or {}
        )
        for v, t in (rec.get("tombstones") or {}).items():
            try:
                te = int((t or {}).get("epoch") or 0)
                ta = float((t or {}).get("at") or 0.0)
            except (TypeError, ValueError):
                continue
            mine = tombs.get(str(v))
            if mine is None or te > int(mine["epoch"]):
                tombs[str(v)] = {"epoch": te, "at": ta}
        adopted = held is None or dominates(
            incoming["epoch"], incoming["boot_id"],
            held["epoch"], held["boot_id"],
        )
        winner = incoming if adopted else held
        winner["tombstones"] = tombs
        # A STALE record pointing at a retired version degrades to "no
        # active version" rather than resurrecting it — but only when
        # the tombstone is NEWER than the record (Lamport order): a
        # record written after the tombstone that re-activates the same
        # version number is a genuine re-deploy, not a resurrection.
        av = winner.get("active_version")
        if av is not None:
            t = tombs.get(str(int(av)))
            if t is not None and int(t["epoch"]) > int(winner["epoch"]):
                winner["active_version"] = None
        self._models[name] = winner
        return bool(adopted)

    def _prune_tombstones_locked(self) -> None:
        """Drop tombstones older than the ttl (measured from their
        write time): they exist to outlive partitions, not forever. A
        ttl of 0 keeps them indefinitely."""
        if self._ttl <= 0:
            return
        cutoff = float(self._clock()) - self._ttl
        for sid in [
            s for s, r in self._replicas.items()
            if r["liveness"] == "tombstone" and r["last_seen"] < cutoff
        ]:
            del self._replicas[sid]
        for rec in self._models.values():
            tombs = rec.get("tombstones") or {}
            for v in [v for v, t in tombs.items() if float(t["at"]) < cutoff]:
                del tombs[v]
