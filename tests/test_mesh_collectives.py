"""Pod-scale fit: on-mesh collective reductions replacing the driver hub.

Covers the collective layer end to end (docs/mesh.md):

* ``parallel/mapreduce.py`` primitives (map_fn/reduce_sum/all_concat/
  reduce_topk) against numpy oracles on the 8-device mesh;
* mesh membership: epoch bumps on join/leave/REBOOT, the ``mesh_info``
  wire op, the ``health`` mesh block;
* the ``reduce_mesh`` wire op: epoch fencing, the (boot_id, pass_rows)
  pre-reduce handshake, partition-accounting guards, replay dedupe;
* the flagship parity contract: a 2-daemon Spark-sim fit reduced on the
  mesh is BITWISE-identical to the same fit forced through the driver
  export/merge hub (``mesh_collectives`` off) — the fallback and the
  fast path may never drift;
* daemon reboot mid-fit under collectives: epoch bump → the PR 4 ledger
  replays the pass → bitwise-equal model;
* capacity: d over the per-device Gram budget raises on a 1-device mesh
  and fits via the model-parallel Gram/eigh (sharding instead of
  rejection), including the real d=8192 acceptance shape;
* satellites: warmup-on-register, the persistent compile cache +
  ``srml_xla_persistent_cache_hits_total``, and perfcheck's MULTICHIP
  gating (dryrun = skip-not-pass; efficiency floor).
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from spark_rapids_ml_tpu import config
from spark_rapids_ml_tpu.ops import gram as gram_ops
from spark_rapids_ml_tpu.parallel import mapreduce as mr
from spark_rapids_ml_tpu.parallel import membership
from spark_rapids_ml_tpu.parallel.mesh import DATA_AXIS, make_mesh
from spark_rapids_ml_tpu.serve.client import DataPlaneClient
from spark_rapids_ml_tpu.serve.daemon import DataPlaneDaemon, _Job
from spark_rapids_ml_tpu.spark import estimator as spark_est
from spark_rapids_ml_tpu.spark.estimator import SparkKMeans, SparkPCA
from spark_rapids_ml_tpu.utils import metrics as metrics_mod

from sparksim import SimDataFrame, SimSparkSession, simdf_from_numpy

spark_est.register_dataframe_type(SimDataFrame)


def _addr(daemon) -> str:
    return f"{daemon.address[0]}:{daemon.address[1]}"


def _counter_total(snap, name, **labels):
    total = 0
    for s in snap.get(name, {}).get("samples", []):
        if all(s["labels"].get(k) == v for k, v in labels.items()):
            total += s["value"]
    return total


def _int_matrix(rng, n, d):
    """Integer rows: every statistic is exact in f32/f64, so bitwise
    equality is a real invariant, not a tolerance blur."""
    return rng.integers(-8, 9, size=(n, d)).astype(np.float64)


def _split_session(primary, peer, n_partitions=4):
    session = SimSparkSession({"spark.srml.daemon.address": _addr(primary)})
    env_plan = {
        pid: {"SRML_DAEMON_ADDRESS": _addr(peer)}
        for pid in range(n_partitions // 2, n_partitions)
    }
    return session, env_plan


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ------------------------- mapreduce primitives ------------------------------


def test_reduce_sum_matches_numpy(rng, mesh8):
    x = rng.standard_normal((64, 16))
    xs = jax.device_put(x, NamedSharding(mesh8, P(DATA_AXIS, None)))
    f = mr.map_fn(
        lambda b: mr.reduce_sum(jnp.sum(b, axis=0), DATA_AXIS),
        mesh8,
        in_specs=P(DATA_AXIS, None),
        out_specs=P(),
    )
    np.testing.assert_allclose(
        np.asarray(jax.jit(f)(xs)), x.sum(axis=0), rtol=1e-12
    )


def test_all_concat_matches_numpy(rng, mesh8):
    x = rng.standard_normal((16, 8))
    xs = jax.device_put(x, NamedSharding(mesh8, P(DATA_AXIS, None)))
    f = mr.map_fn(
        lambda b: mr.all_concat(b, DATA_AXIS, axis=0),
        mesh8,
        in_specs=P(DATA_AXIS, None),
        out_specs=P(None, None),
        check_vma=False,
    )
    np.testing.assert_array_equal(np.asarray(jax.jit(f)(xs)), x)


def test_reduce_topk_is_exact(rng, mesh8):
    """Per-shard local top-k merged by reduce_topk == global top-k."""
    q, n, k = 5, 64, 4
    d2 = rng.standard_normal((q, n)) ** 2
    ids = np.broadcast_to(np.arange(n, dtype=np.int64), (q, n)).copy()
    d2s = jax.device_put(d2.T, NamedSharding(mesh8, P(DATA_AXIS, None)))
    ids_s = jax.device_put(ids.T, NamedSharding(mesh8, P(DATA_AXIS, None)))

    def shard(db, di):
        neg, pos = jax.lax.top_k(-db.T, k)  # local top-k per shard
        return mr.reduce_topk(-neg, jnp.take_along_axis(di.T, pos, axis=1), k)

    f = mr.map_fn(
        shard, mesh8,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS, None)),
        out_specs=(P(), P()),
        check_vma=False,
    )
    dist, idx = jax.jit(f)(d2s, ids_s)
    order = np.argsort(d2, axis=1)[:, :k]
    np.testing.assert_array_equal(np.sort(np.asarray(idx)), np.sort(order))
    np.testing.assert_allclose(
        np.asarray(dist), np.take_along_axis(d2, order, axis=1), rtol=1e-12
    )


def test_collective_traces_are_booked(rng, mesh8):
    """The lint gate routes every collective through mapreduce; this pins
    that the routing is observable — tracing a program books the
    counter."""
    before = _counter_total(
        metrics_mod.snapshot(), "srml_parallel_collective_traces_total"
    )
    x = rng.standard_normal((32, 8)).astype(np.float32)
    # A fresh shape signature forces a retrace of the fused stats.
    f = gram_ops.sharded_stats(mesh8)
    xs, mask, _ = __import__(
        "spark_rapids_ml_tpu.parallel.sharding", fromlist=["shard_rows"]
    ).shard_rows(x, mesh8)
    jax.block_until_ready(f(xs, mask))
    after = _counter_total(
        metrics_mod.snapshot(), "srml_parallel_collective_traces_total"
    )
    assert after > before


# ------------------------- membership + wire ops -----------------------------


def test_membership_epoch_bumps_on_join_leave_and_reboot():
    reg = membership.MeshMembership()

    class H:  # a registrable handle
        pass

    h1, h2 = H(), H()
    e0 = reg.epoch
    e1 = reg.register("a", "boot1", h1)
    assert e1 > e0
    e2 = reg.register("b", "boot2", h2)
    assert e2 > e1
    # REBOOT: same id, new boot — must bump (the stale-partial fence).
    e3 = reg.register("a", "boot9", h1)
    assert e3 > e2
    snap = reg.snapshot()
    boots = {m["id"]: m["boot_id"] for m in snap["members"]}
    assert boots == {"a": "boot9", "b": "boot2"}
    e4 = reg.unregister("b")
    assert e4 > e3
    assert reg.unregister("nope") == e4  # unknown id: no silent bump
    assert reg.get("a", boot_id="boot1") is None  # old incarnation gone
    assert reg.get("a", boot_id="boot9") is h1


def test_membership_unregister_is_incarnation_scoped():
    """A superseded daemon object's late stop() must not deregister the
    live successor holding the same durable instance id."""
    reg = membership.MeshMembership()

    class H:
        pass

    a1, a2 = H(), H()
    reg.register("X", "boot1", a1)
    reg.register("X", "boot2", a2)  # successor on the same durable id
    e = reg.epoch
    assert reg.unregister("X", boot_id="boot1") == e  # stale: no-op
    assert reg.get("X", boot_id="boot2") is a2
    assert reg.unregister("X", boot_id="boot2") > e  # the live one leaves
    assert reg.get("X") is None


def test_membership_dead_handles_read_as_absent():
    reg = membership.MeshMembership()

    class H:
        pass

    h = H()
    reg.register("ghost", "b", h)
    del h
    assert reg.get("ghost") is None
    assert reg.snapshot()["members"] == []


def test_mesh_info_op_and_health_mesh_block(mesh8):
    with DataPlaneDaemon(ttl=600.0) as a, DataPlaneDaemon(ttl=600.0) as b:
        with DataPlaneClient(*a.address) as c:
            info = c.mesh_info()
            ids = {m["id"]: m["boot_id"] for m in info["members"]}
            assert ids.get(a.instance_id) == a.boot_id
            assert ids.get(b.instance_id) == b.boot_id
            assert info["epoch"] == membership.registry().epoch
            health = c.health()
            assert health["mesh"]["epoch"] == info["epoch"]
            assert health["mesh"]["members"] >= 2
        epoch_before = membership.registry().epoch
    # both daemons stopped -> two unregistrations
    assert membership.registry().epoch >= epoch_before + 1


def _feed_pca_job(client, job, x, partition=0):
    import pyarrow as pa

    from spark_rapids_ml_tpu.bridge.arrow import matrix_to_list_column

    table = pa.table({"features": matrix_to_list_column(x)})
    client.feed(job, table, algo="pca", input_col="features",
                partition=partition, attempt=0)
    client.commit(job, partition=partition, attempt=0)


def test_reduce_mesh_op_folds_and_fences(rng, mesh8):
    """Protocol-level reduce_mesh: a correct request folds the peer's
    device state into the primary (rows account); a stale epoch, a wrong
    boot_id, and a row-count lie each refuse loudly BEFORE folding."""
    x1 = _int_matrix(rng, 64, 8).astype(np.float32)
    x2 = _int_matrix(rng, 32, 8).astype(np.float32)
    with DataPlaneDaemon(ttl=600.0) as a, DataPlaneDaemon(ttl=600.0) as b:
        with DataPlaneClient(*a.address) as ca, DataPlaneClient(*b.address) as cb:
            _feed_pca_job(ca, "job", x1, partition=0)
            _feed_pca_job(cb, "job", x2, partition=1)
            epoch = ca.mesh_info()["epoch"]
            peers = {
                b.instance_id: {
                    "boot_id": b.boot_id, "rows": 32, "partitions": [1],
                }
            }
            # stale epoch → refused
            with pytest.raises(RuntimeError, match="membership changed"):
                ca.reduce_mesh("job", epoch=epoch - 1, peers=peers)
            # wrong boot → refused (rebooted-peer fence)
            bad = {b.instance_id: {**peers[b.instance_id], "boot_id": "dead"}}
            with pytest.raises(RuntimeError, match="not a co-resident"):
                ca.reduce_mesh("job", epoch=epoch, peers=bad)
            # row-count lie → refused (pre-reduce handshake)
            lie = {b.instance_id: {**peers[b.instance_id], "rows": 31}}
            with pytest.raises(RuntimeError, match="row-count mismatch"):
                ca.reduce_mesh("job", epoch=epoch, peers=lie)
            # orphan partition → refused
            orphan = {
                b.instance_id: {**peers[b.instance_id], "partitions": [2]}
            }
            with pytest.raises(RuntimeError, match="partition accounting"):
                ca.reduce_mesh("job", epoch=epoch, peers=orphan)
            # the real thing
            resp = ca.reduce_mesh(
                "job", epoch=epoch, peers=peers, drop_peers=True
            )
            assert resp["rows"] == 96 and resp["reduced"] == 1
            arrays = ca.finalize_pca("job", k=2)
            assert arrays["pc"].shape == (8, 2)
            # peer job dropped daemon-side (drop_peers)
            with pytest.raises(RuntimeError, match="no such job"):
                cb.status("job")


def test_reduce_mesh_replay_after_drop_peers_returns_cached_ack(rng, mesh8):
    """Replay safety (the client's lost-ack retry): a reduce that
    APPLIED — and dropped the peer jobs — must answer its replay from
    the dedupe memory, not re-validate against the now-gone peers (and
    not re-fold). Dedupe runs before the epoch fence too: a replay
    after unrelated membership churn still gets its cached ack."""
    x1 = _int_matrix(rng, 64, 8).astype(np.float32)
    x2 = _int_matrix(rng, 32, 8).astype(np.float32)
    with DataPlaneDaemon(ttl=600.0) as a, DataPlaneDaemon(ttl=600.0) as b:
        with DataPlaneClient(*a.address) as ca, DataPlaneClient(*b.address) as cb:
            _feed_pca_job(ca, "job", x1, partition=0)
            _feed_pca_job(cb, "job", x2, partition=1)
            epoch = ca.mesh_info()["epoch"]
            req = {
                "op": "reduce_mesh", "job": "job", "epoch": epoch,
                "peers": {b.instance_id: {
                    "boot_id": b.boot_id, "rows": 32, "partitions": [1],
                }},
                "algo": "pca", "params": {}, "drop_peers": True,
                "reduce_id": "replay-fixed-1",
            }
            r1, _ = ca._op(dict(req))
            assert r1["rows"] == 96
            r2, _ = ca._op(dict(req))  # identical replay: cached, no refold
            assert r2["rows"] == 96 and r2["reduced"] == 1
            stale = {**req, "epoch": epoch - 1}
            r3, _ = ca._op(dict(stale))  # dedupe beats the epoch fence
            assert r3["rows"] == 96
            arrays = ca.finalize_pca("job", k=2)
            assert arrays["pc"].shape == (8, 2)


def test_unrelated_membership_churn_is_absorbed(rng, mesh8, monkeypatch):
    """The epoch fence is process-global: an UNRELATED daemon joining/
    leaving between the driver's mesh_info and its reduce must cost one
    retry, not the pass — the fit still reduces on the mesh and the
    model is unchanged."""
    x = _int_matrix(rng, 400, 8)
    real = DataPlaneClient.reduce_mesh
    state = {"churn": 0}

    def churny(self, jobname, **kw):
        if state["churn"] == 0:
            state["churn"] = 1
            DataPlaneDaemon(ttl=600.0).start().stop()  # epoch += 2
        return real(self, jobname, **kw)

    with DataPlaneDaemon(ttl=600.0) as a, DataPlaneDaemon(ttl=600.0) as b:
        session, env_plan = _split_session(a, b)

        def fit():
            df = simdf_from_numpy(
                x, n_partitions=4, session=session, env_plan=env_plan
            )
            return SparkPCA().setInputCol("features").setK(3).fit(df)

        monkeypatch.setattr(DataPlaneClient, "reduce_mesh", churny)
        before = _counter_total(
            metrics_mod.snapshot(), "srml_daemon_mesh_reduces_total"
        )
        m_churned = fit()
        assert state["churn"] == 1, "the churn never fired"
        assert _counter_total(
            metrics_mod.snapshot(), "srml_daemon_mesh_reduces_total"
        ) > before, "the fit fell off the collective path"
        monkeypatch.setattr(DataPlaneClient, "reduce_mesh", real)
        m_clean = fit()
    np.testing.assert_array_equal(m_churned.pc, m_clean.pc)


def test_reduce_mesh_against_oracle(rng, mesh8):
    """The folded state equals the single-daemon accumulate of the
    union — the collective reduce is the identity the hub provides."""
    x1 = _int_matrix(rng, 48, 6).astype(np.float32)
    x2 = _int_matrix(rng, 80, 6).astype(np.float32)
    with DataPlaneDaemon(ttl=600.0) as a, DataPlaneDaemon(ttl=600.0) as b:
        with DataPlaneClient(*a.address) as ca, DataPlaneClient(*b.address) as cb:
            _feed_pca_job(ca, "j", x1, partition=0)
            _feed_pca_job(cb, "j", x2, partition=1)
            epoch = ca.mesh_info()["epoch"]
            ca.reduce_mesh(
                "j", epoch=epoch,
                peers={b.instance_id: {
                    "boot_id": b.boot_id, "rows": 80, "partitions": [1],
                }},
                drop_peers=True,
            )
            merged = ca.finalize_pca("j", k=3)
        with DataPlaneDaemon(ttl=600.0) as solo:
            with DataPlaneClient(*solo.address) as cs:
                _feed_pca_job(cs, "j", x1, partition=0)
                _feed_pca_job(cs, "j", x2, partition=1)
                alone = cs.finalize_pca("j", k=3)
    np.testing.assert_array_equal(merged["pc"], alone["pc"])
    np.testing.assert_array_equal(
        merged["explained_variance"], alone["explained_variance"]
    )


# --------------------- flagship: estimator-level parity ----------------------


def test_two_daemon_pca_collective_vs_hub_bitwise(rng, mesh8):
    """THE parity contract: the on-mesh reduction and the driver-hub
    fallback produce bit-for-bit the same model on the same 2-daemon
    dataset — and the collective run really did reduce on the mesh
    (counter evidence), while the hub run really did not."""
    x = _int_matrix(rng, 400, 16)
    with DataPlaneDaemon(ttl=600.0) as a, DataPlaneDaemon(ttl=600.0) as b:
        session, env_plan = _split_session(a, b)

        def fit():
            df = simdf_from_numpy(
                x, n_partitions=4, session=session, env_plan=env_plan
            )
            return SparkPCA().setInputCol("features").setK(4).fit(df)

        before = _counter_total(
            metrics_mod.snapshot(), "srml_daemon_mesh_reduces_total"
        )
        m_mesh = fit()
        mid = metrics_mod.snapshot()
        assert _counter_total(mid, "srml_daemon_mesh_reduces_total") > before, (
            "the collective path never engaged — this parity test proved "
            "nothing"
        )
        with config.option("mesh_collectives", False):
            m_hub = fit()
        after = metrics_mod.snapshot()
        assert _counter_total(
            after, "srml_daemon_mesh_reduces_total"
        ) == _counter_total(mid, "srml_daemon_mesh_reduces_total"), (
            "the hub run reduced on the mesh anyway"
        )
        assert _counter_total(
            after, "srml_fit_mesh_reduce_paths_total", path="hub"
        ) > 0
    np.testing.assert_array_equal(m_mesh.pc, m_hub.pc)
    np.testing.assert_array_equal(
        np.asarray(m_mesh.explainedVariance),
        np.asarray(m_hub.explainedVariance),
    )


def test_two_daemon_kmeans_collective_vs_hub_bitwise(rng, mesh8):
    """Iterative twin: every Lloyd pass reduces on the mesh (one
    reduce_mesh per pass), and the multi-pass model still matches the
    hub path bitwise."""
    x = _int_matrix(rng, 360, 8)
    with DataPlaneDaemon(ttl=600.0) as a, DataPlaneDaemon(ttl=600.0) as b:
        session, env_plan = _split_session(a, b)
        # kmeans: every daemon must be seeded by the driver (the
        # documented addresses contract for iterative fits).
        session.conf.set(
            "spark.srml.daemon.addresses", f"{_addr(a)},{_addr(b)}"
        )

        def fit():
            df = simdf_from_numpy(
                x, n_partitions=4, session=session, env_plan=env_plan,
                concurrency=1,
            )
            return (
                SparkKMeans().setK(3).setFeaturesCol("features")
                .setMaxIter(4).setSeed(7).fit(df)
            )

        before = _counter_total(
            metrics_mod.snapshot(), "srml_daemon_mesh_reduces_total",
            algo="kmeans",
        )
        m_mesh = fit()
        assert _counter_total(
            metrics_mod.snapshot(), "srml_daemon_mesh_reduces_total",
            algo="kmeans",
        ) >= before + 2  # at least iterate passes + the final cost pass
        with config.option("mesh_collectives", False):
            m_hub = fit()
    np.testing.assert_array_equal(m_mesh.centers, m_hub.centers)
    assert m_mesh.summary.trainingCost == m_hub.summary.trainingCost
    assert m_mesh.summary.numIter == m_hub.summary.numIter


def test_estimator_reduce_guard_fails_loudly(rng, mesh8, monkeypatch):
    """The collective twin of the hub's export-shortfall guard: a peer
    whose live accounting disagrees with the task acks fails the fit
    with the row-count mismatch — never a silently wrong model."""
    orig = _Job.peek_pass_state

    def lying_peek(self):
        state, pass_rows, committed, iteration = orig(self)
        return state, pass_rows - 7, committed, iteration

    monkeypatch.setattr(_Job, "peek_pass_state", lying_peek)
    with DataPlaneDaemon(ttl=600.0) as a, DataPlaneDaemon(ttl=600.0) as b:
        session, env_plan = _split_session(a, b)
        df = simdf_from_numpy(_int_matrix(rng, 400, 8), n_partitions=4,
                              session=session, env_plan=env_plan)
        with pytest.raises(RuntimeError, match="row-count mismatch"):
            SparkPCA().setInputCol("features").setK(3).fit(df)


# ------------------- reboot mid-fit: epoch bump → replay ---------------------


def test_peer_reboot_mid_fit_replays_to_bitwise_model(
    rng, mesh8, monkeypatch
):
    """A VOLATILE peer daemon reboots at a pass boundary mid-kmeans:
    its membership re-registration bumps the epoch and mints a new
    boot_id, the driver's next boundary op fails, and — recovery
    enabled — the PR 4 ledger replays the pass; the final model is
    bitwise-equal to the uninterrupted fit and the collective path
    carried the replayed passes too."""
    k, d = 3, 5
    centers_true = rng.normal(size=(k, d)) * 8
    x = np.concatenate(
        [centers_true[i] + rng.normal(size=(90, d)) * 0.3 for i in range(k)]
    ).astype(np.float64)
    x = x[rng.permutation(len(x))]

    peer_port = _free_port()
    holder = {}

    def start_peer():
        holder["d"] = DataPlaneDaemon(
            host="127.0.0.1", port=peer_port, mesh=mesh8
        ).start()

    start_peer()
    with DataPlaneDaemon(ttl=600.0, mesh=mesh8) as primary:
        session = SimSparkSession({
            "spark.srml.daemon.address": _addr(primary),
            "spark.srml.daemon.addresses":
                f"{_addr(primary)},127.0.0.1:{peer_port}",
        })
        env_plan = {
            pid: {"SRML_DAEMON_ADDRESS": f"127.0.0.1:{peer_port}"}
            for pid in (2, 3)
        }

        def fit():
            df = simdf_from_numpy(
                x, n_partitions=4, session=session, env_plan=env_plan,
                concurrency=1,
            )
            return (
                SparkKMeans().setK(3).setFeaturesCol("features")
                .setMaxIter(4).setSeed(5).fit(df)
            )

        try:
            m_clean = fit()

            monkeypatch.setenv("SRML_FIT_RECOVERY_ATTEMPTS", "2")
            fired = {"n": 0}
            real_step = DataPlaneClient.step

            def step_then_reboot_peer(self, job, params=None):
                info = real_step(self, job, params=params)
                if fired["n"] == 0:
                    fired["n"] = 1
                    epoch_before = membership.registry().epoch
                    holder["d"].stop()  # pass-local partials die here
                    start_peer()
                    assert membership.registry().epoch >= epoch_before + 2
                return info

            monkeypatch.setattr(DataPlaneClient, "step", step_then_reboot_peer)
            rec_before = _counter_total(
                metrics_mod.snapshot(), "srml_fit_recoveries_total"
            )
            m_rec = fit()
            assert fired["n"] == 1, "the reboot never fired"
            assert _counter_total(
                metrics_mod.snapshot(), "srml_fit_recoveries_total"
            ) > rec_before, "the fit never recovered — it proved nothing"
        finally:
            holder["d"].stop()
    np.testing.assert_array_equal(m_clean.centers, m_rec.centers)
    assert m_clean.summary.trainingCost == m_rec.summary.trainingCost
    assert m_clean.summary.numIter == m_rec.summary.numIter


# ---------------- capacity: model-parallel Gram/eigh -------------------------


def test_gram_capacity_budget_small(monkeypatch, rng, mesh4x2, mesh1):
    """Budget semantics at a fast shape (budget shrunk): 1-device over
    budget raises; a model axis whose slab fits returns must-shard; a
    slab still over budget raises with the mesh hint."""
    monkeypatch.setattr(gram_ops, "GRAM_DEVICE_BUDGET_BYTES", 64 * 128 * 8)
    with pytest.raises(gram_ops.GramCapacityError, match="model"):
        gram_ops.require_gram_capacity(128, mesh1, accum_dtype="float64")
    assert gram_ops.require_gram_capacity(
        128, mesh4x2, accum_dtype="float64"
    ) is True
    assert gram_ops.require_gram_capacity(
        32, mesh1, accum_dtype="float64"
    ) is False
    with pytest.raises(gram_ops.GramCapacityError, match="mesh_model_axis"):
        gram_ops.require_gram_capacity(1024, mesh4x2, accum_dtype="float64")


def test_fit_pca_model_parallel_small_budget(monkeypatch, rng, mesh4x2, mesh1):
    """Same fit, shrunk budget: the 1-device path rejects, the 2-way
    model mesh fits, and the sharded result matches the unconstrained
    exact fit to solver tolerance."""
    from spark_rapids_ml_tpu.models.pca import fit_pca

    d = 128
    scale = np.exp(-np.arange(d) / 8.0)
    x = (rng.standard_normal((1024, d)) * scale).astype(np.float64)
    ref = fit_pca(x, k=3, mesh=mesh1)  # unconstrained oracle
    monkeypatch.setattr(gram_ops, "GRAM_DEVICE_BUDGET_BYTES", 64 * 128 * 8)
    with pytest.raises(gram_ops.GramCapacityError):
        fit_pca(x, k=3, mesh=mesh1)
    sol = fit_pca(x, k=3, mesh=mesh4x2, solver="randomized")
    dots = np.abs(np.sum(sol.pc * ref.pc, axis=0))
    assert np.all(dots > 1 - 1e-6), dots


def test_daemon_job_over_budget_refuses_at_creation(monkeypatch, rng, mesh8):
    """The Spark fit path's gate: a daemon job whose replicated (d, d)
    accumulator busts the budget refuses at the FIRST FEED with the
    capacity error — never an opaque device OOM mid-pass."""
    import pyarrow as pa

    from spark_rapids_ml_tpu.bridge.arrow import matrix_to_list_column

    monkeypatch.setattr(gram_ops, "GRAM_DEVICE_BUDGET_BYTES", 64 * 64 * 8)
    x = rng.standard_normal((16, 128)).astype(np.float32)
    with DataPlaneDaemon(ttl=600.0) as d:
        with DataPlaneClient(*d.address) as c:
            t = pa.table({"features": matrix_to_list_column(x)})
            with pytest.raises(RuntimeError, match="budget"):
                c.feed("big", t, algo="pca", input_col="features",
                       partition=0, attempt=0)
            # under-budget widths are untouched
            t2 = pa.table({"features": matrix_to_list_column(x[:, :32])})
            c.feed("ok", t2, algo="pca", input_col="features",
                   partition=0, attempt=0)
            c.commit("ok", partition=0, attempt=0)


def test_fit_pca_stream_over_budget_raises(monkeypatch, mesh8):
    from spark_rapids_ml_tpu.models.pca import fit_pca_stream

    monkeypatch.setattr(gram_ops, "GRAM_DEVICE_BUDGET_BYTES", 64 * 64 * 8)
    with pytest.raises(gram_ops.GramCapacityError, match="budget"):
        fit_pca_stream(iter([np.zeros((8, 128))]), k=2, n_cols=128)


def test_fit_pca_d8192_model_parallel_succeeds_where_single_device_raises(
    rng, devices
):
    """The acceptance shape (docs/mesh.md): at d=8192 the float64 (d, d)
    accumulator is 512 MiB — over the 256 MiB default per-device budget
    — so the single-device fit refuses, and the 8-way model-parallel
    Gram/eigh carries it (64 MiB slab/device), returning a finite,
    oracle-aligned top component."""
    from spark_rapids_ml_tpu.models.pca import fit_pca

    d, n, k = 8192, 256, 4
    scale = np.exp(-np.arange(d) / 64.0) + 1e-3
    x = (rng.standard_normal((n, d)) * scale).astype(np.float64)
    m1 = make_mesh(data=1, model=1, devices=devices[:1])
    with pytest.raises(gram_ops.GramCapacityError):
        fit_pca(x, k=k, mesh=m1, solver="randomized")
    m8 = make_mesh(data=1, model=8, devices=devices)
    sol = fit_pca(x, k=k, mesh=m8, solver="randomized")
    assert sol.pc.shape == (d, k) and np.all(np.isfinite(sol.pc))
    xc = x - x.mean(axis=0)
    w, v = np.linalg.eigh(xc.T @ xc)
    ref = v[:, ::-1][:, :1]
    assert abs(float(np.sum(sol.pc[:, :1] * ref))) > 0.99


# --------------------------- satellites --------------------------------------


def test_warmup_on_register_precompiles_ladder(rng):
    """With serve_warmup_on_register on (and batching on), registration
    itself warms the reachable ladder: an explicit warmup afterwards
    compiles NOTHING new. Control: without the flag, the explicit
    warmup is the first to compile."""
    from spark_rapids_ml_tpu.models.pca import PCA

    d = 16
    x = rng.standard_normal((256, d)).astype(np.float32)
    model = PCA().setK(3).fit({"features": x})
    arrays = model._model_data()

    with config.option("serve_batching", True):
        with config.option("serve_warmup_on_register", True):
            with DataPlaneDaemon() as daemon:
                with DataPlaneClient(*daemon.address) as c:
                    assert c.ensure_model("warm", "pca", arrays) is True
                    info = c.warmup("warm", n_cols=d)
                    assert info["enabled"] is True
                    assert info["compiled"] == 0, (
                        "registration should have pre-compiled the ladder"
                    )
        with config.option("serve_warmup_on_register", False):
            with DataPlaneDaemon() as daemon:
                with DataPlaneClient(*daemon.address) as c:
                    assert c.ensure_model("cold", "pca", arrays) is True
                    info = c.warmup("cold", n_cols=d)
                    assert info["compiled"] > 0


def test_warmup_on_register_covers_daemon_built_knn(rng):
    """The kneighbors half of the contract: a daemon-built KNN index
    shard (finalize_knn registration — KNN never rides ensure_model)
    pre-compiles its ladder at registration too; the explicit warmup
    afterwards finds nothing left to compile."""
    import pyarrow as pa

    from spark_rapids_ml_tpu.bridge.arrow import matrix_to_list_column

    x = rng.standard_normal((256, 16)).astype(np.float32)
    with config.option("serve_batching", True):
        with config.option("serve_warmup_on_register", True):
            with DataPlaneDaemon() as d:
                with DataPlaneClient(*d.address) as c:
                    t = pa.table({"features": matrix_to_list_column(x)})
                    c.feed("kj", t, algo="knn", input_col="features",
                           partition=0, attempt=0)
                    c.commit("kj", partition=0, attempt=0)
                    c.finalize_knn("kj", register_as="kidx", mode="exact")
                    info = c.warmup("kidx", n_cols=16)
                    assert info["enabled"] is True
                    assert info["compiled"] == 0, (
                        "the knn registration should have pre-compiled "
                        "the kneighbors ladder"
                    )


def test_warmup_on_register_noop_without_batching(rng):
    """Batching off: the flag must not break registration (nor start a
    scheduler)."""
    from spark_rapids_ml_tpu.models.pca import PCA

    x = rng.standard_normal((64, 8)).astype(np.float32)
    arrays = PCA().setK(2).fit({"features": x})._model_data()
    with config.option("serve_warmup_on_register", True):
        with DataPlaneDaemon() as daemon:
            with DataPlaneClient(*daemon.address) as c:
                assert c.ensure_model("m", "pca", arrays) is True
                out = c.transform("m", x[:4])
                assert out["output"].shape == (4, 2)


@pytest.mark.slow
def test_compile_cache_dir_wires_jax_and_counts_hits(tmp_path):
    """SRML_COMPILE_CACHE_DIR → jax.config.compilation_cache_dir at
    package init; a second process compiling the same program reads the
    disk cache and srml_xla_persistent_cache_hits_total counts it."""
    cache = str(tmp_path / "xla-cache")
    prog = (
        "import os\n"
        "import jax, jax.numpy as jnp\n"
        "import spark_rapids_ml_tpu as s\n"
        "from spark_rapids_ml_tpu.utils import xprof, metrics\n"
        "assert jax.config.jax_compilation_cache_dir == os.environ['SRML_COMPILE_CACHE_DIR']\n"
        "f = xprof.ledgered_jit('test.cache_probe', lambda x: jnp.sin(x) @ x)\n"
        "import numpy as np\n"
        "print(float(np.asarray(f(jnp.ones((64, 64)))).sum()))\n"
        "snap = metrics.snapshot()\n"
        "hits = sum(s['value'] for s in snap.get("
        "'srml_xla_persistent_cache_hits_total', {}).get('samples', []))\n"
        "print('HITS', hits)\n"
    )
    env = {
        k: v for k, v in os.environ.items()
        if k not in ("JAX_COMPILATION_CACHE_DIR",)
    }
    env.update({
        "SRML_COMPILE_CACHE_DIR": cache,
        "JAX_PLATFORMS": "cpu",
        "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS": "0",
        "JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES": "0",
    })
    first = subprocess.run(
        [sys.executable, "-c", prog], env=env, capture_output=True,
        text=True, timeout=300,
    )
    assert first.returncode == 0, first.stderr[-2000:]
    assert os.path.isdir(cache) and os.listdir(cache), (
        "first process wrote nothing to the cache dir"
    )
    second = subprocess.run(
        [sys.executable, "-c", prog], env=env, capture_output=True,
        text=True, timeout=300,
    )
    assert second.returncode == 0, second.stderr[-2000:]
    hits = int(float(second.stdout.strip().splitlines()[-1].split()[-1]))
    assert hits >= 1, second.stdout


# ------------------------- perfcheck: multichip ------------------------------


def _mc_record(eff=0.9, metric="multichip_fit_rows_per_sec_d512_k16", **kw):
    rec = {
        "metric": metric, "value": 100000.0, "unit": "rows/s",
        "n_devices": 8, "simulated": True, "dryrun": False,
        "scaling_efficiency": eff,
        "xla": {"warmup": {}, "steady": {"f": {"compiles": 0}}},
    }
    rec.update(kw)
    return rec


def test_perfcheck_multichip_dryrun_is_skip_not_pass():
    from spark_rapids_ml_tpu.tools import perfcheck

    ok, lines = perfcheck.check_multichip(
        {"n_devices": 8, "rc": 0, "ok": True, "tail": "dryrun OK"}, []
    )
    assert ok is True
    assert any("SKIP" in ln and "NOT a pass" in ln for ln in lines)


def test_perfcheck_multichip_efficiency_floor():
    from spark_rapids_ml_tpu.tools import perfcheck

    ok, lines = perfcheck.check_multichip(_mc_record(eff=0.79), [])
    assert ok is False
    assert any("REGRESSION" in ln for ln in lines)
    ok, _ = perfcheck.check_multichip(_mc_record(eff=0.81), [])
    assert ok is True


def test_perfcheck_multichip_trajectory_ratchets_the_floor():
    from spark_rapids_ml_tpu.tools import perfcheck

    history = [_mc_record(eff=1.2), _mc_record(eff=1.3), _mc_record(eff=1.25)]
    # 0.85 clears the absolute floor but is >15% below median 1.25.
    ok, lines = perfcheck.check_multichip(_mc_record(eff=0.85), history)
    assert ok is False
    ok, _ = perfcheck.check_multichip(_mc_record(eff=1.15), history)
    assert ok is True
    # dryrun history records are excluded, not fatal
    ok, lines = perfcheck.check_multichip(
        _mc_record(eff=0.9), [{"tail": "dryrun", "n_devices": 8}]
    )
    assert ok is True
    assert any("dryrun history" in ln for ln in lines)


def test_perfcheck_multichip_allow_compile_hatch_works():
    """The escape hatch the failure message advertises must actually
    unblock the gate — with the mesh-prefixed name it prints."""
    from spark_rapids_ml_tpu.tools import perfcheck

    rec = _mc_record(
        xla={"warmup": {}, "steady": {"8dev:gram.f": {"compiles": 2}}}
    )
    ok, lines = perfcheck.check_multichip(rec, [])
    assert ok is False and any("8dev:gram.f" in ln for ln in lines)
    ok, _ = perfcheck.check_multichip(rec, [], allow_compiles=("8dev:gram.f",))
    assert ok is True


def test_perfcheck_multichip_real_vs_simulated_do_not_mix():
    from spark_rapids_ml_tpu.tools import perfcheck

    history = [_mc_record(eff=1.4)]  # simulated trajectory
    ok, _ = perfcheck.check_multichip(
        _mc_record(eff=0.85, simulated=False), history
    )
    assert ok is True  # real-pod 0.85 gates on the absolute floor only


def test_perfcheck_non_dict_input_exits_gracefully(tmp_path, capsys):
    """A JSON array (a history file) or bare scalar piped in must get
    the graceful 'no JSON record' exit, not a traceback."""
    from spark_rapids_ml_tpu.tools import perfcheck

    for content in ("[]", "42", '[{"metric": "x"}]'):
        p = tmp_path / "notarecord.json"
        p.write_text(content)
        assert perfcheck.main([str(p)]) == 2
        assert "no JSON record" in capsys.readouterr().err


@pytest.mark.perf
@pytest.mark.slow
def test_multichip_bench_smoke(tmp_path):
    """bench.py --multichip end to end at toy shapes: the record carries
    a real (non-dryrun) scaling number, per-phase timing including the
    raw all-reduce microphase, and a steady ledger the storm gate can
    read."""
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "SRML_BENCH_MULTICHIP_DEVICES": "4",
        "SRML_BENCH_MULTICHIP_D": "64",
        "SRML_BENCH_MULTICHIP_BATCH_ROWS": "2048",
        "SRML_BENCH_MULTICHIP_BATCHES": "4",
        "SRML_BENCH_MULTICHIP_KMEANS_K": "4",
        "SRML_BENCH_MULTICHIP_KMEANS_PASSES": "2",
    })
    env.pop("XLA_FLAGS", None)
    out = subprocess.run(
        [sys.executable, "bench.py", "--multichip"],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=env, capture_output=True, text=True, timeout=560,
    )
    assert out.returncode == 0, out.stderr[-3000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["dryrun"] is False
    assert rec["n_devices"] == 4
    assert rec["scaling_efficiency"] > 0
    for side in ("one_device", "n_device"):
        phases = rec[side]["phases"]
        for phase in ("pca_fold", "pca_finalize", "kmeans_fold",
                      "allreduce_dxd"):
            assert phases[phase] >= 0
    assert isinstance(rec["xla"]["steady"], dict) and rec["xla"]["steady"]
