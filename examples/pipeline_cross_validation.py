"""Pipeline + CrossValidator: scale → reduce → regress, tuned end to end.

Runs on whatever backend is available (TPU if attached, else CPU; for a
virtual multi-device mesh run with
XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu).
"""

import os
import sys

if __package__ in (None, ""):  # runnable without installation
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from spark_rapids_ml_tpu import (
    CrossValidator,
    LinearRegression,
    PCA,
    ParamGridBuilder,
    Pipeline,
    RegressionEvaluator,
    StandardScaler,
)

rng = np.random.default_rng(0)
n, d = 20_000, 64
x = (rng.normal(size=(n, d)) * rng.uniform(0.5, 20.0, size=d)).astype(np.float32)
# Signal lives in the top principal directions, so the 48-component
# projection preserves it and the tuned ridge recovers a near-noise rmse.
xs = (x - x.mean(0)) / x.std(0)
u, s, vt = np.linalg.svd(xs, full_matrices=False)
w = vt[:16].T @ rng.normal(size=(16,))
y = xs @ w + 1.5 + 0.05 * rng.normal(size=n)
ds = {"features": x, "label": y}

# A pipeline: standardize, project to principal components, regress on them.
pipe = Pipeline(stages=[
    StandardScaler().setWithMean(True).setOutputCol("scaled"),
    PCA().setInputCol("scaled").setK(48).setOutputCol("pca"),
    LinearRegression().setFeaturesCol("pca"),
])

# Tune the ridge strength by 3-fold cross-validation on rmse.
lr = pipe.getStages()[2]
grid = ParamGridBuilder().addGrid(lr.getParam("regParam"), [0.0, 1e-3, 1e-1]).build()
cv = CrossValidator(
    estimator=pipe,
    estimatorParamMaps=grid,
    evaluator=RegressionEvaluator(),  # rmse, lower is better
    numFolds=3,
    seed=0,
)
cvm = cv.fit(ds)
print("avg rmse per candidate:", np.round(cvm.avgMetrics, 4))
pred = cvm.transform(ds)["prediction"]
print("refit-on-full rmse:", round(float(np.sqrt(np.mean((pred - y) ** 2))), 4))
