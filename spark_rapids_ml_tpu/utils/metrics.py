"""Process-wide metrics registry: labeled counters, gauges, histograms.

The reference has no metrics story at all — its observability is two NVTX
ranges (RapidsRowMatrix.scala:62,70) readable only inside Nsight. A system
that serves heavy traffic needs numbers a dashboard can scrape, so this
module is the single registry every layer records into: the daemon's
per-op request/latency/byte counters (serve/daemon.py, exposed by the
additive ``metrics`` wire op), the client's healing counters
(serve/client.py), the wire framing's byte totals (serve/protocol.py),
and every ``trace_span`` phase duration (utils/profiling.py).

Zero dependencies by design (the daemon host may have nothing but the
package itself); the Prometheus text exposition (v0.0.4) is ~40 lines,
not a client library. Everything is thread-safe: one lock per metric,
held only for the dict update — the registry sits on the daemon's
request hot path.

Naming convention (lint-enforced, tests/test_lint.py):
``srml_<area>_<name>[_<unit>]`` — counters end ``_total``, histograms end
in their unit (``_seconds``/``_bytes``), gauges are bare quantities
(``srml_daemon_staged_bytes``). Labels are lowercase identifiers.

Disabled state: ``config.set("metrics", False)`` (env
``SRML_TPU_METRICS=0``) turns every record call into an early return —
no label-key allocation, no lock — and ``snapshot()``/
``render_prometheus()`` are only ever executed on demand (a scrape),
never in the background.
"""

from __future__ import annotations

import threading
import time
from bisect import bisect_left
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "REGISTRY",
    "DEFAULT_BUCKETS",
    "counter",
    "gauge",
    "histogram",
    "snapshot",
    "render_prometheus",
    "render_openmetrics",
    "reset",
    "quantile_from_buckets",
]

#: Default latency buckets (seconds): sub-millisecond host ops through
#: the tens-of-seconds first-compile tail the daemon's feed path can hit.
DEFAULT_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


def _enabled() -> bool:
    # Lazy import: config pulls utils.logging; importing it at module load
    # from here would make the utils package order-sensitive. config.peek
    # is a lock-free dict read — this gate sits on the daemon's per-frame
    # hot path, and the disabled state must truly be an early return (no
    # process-wide lock), as the module docstring promises.
    from spark_rapids_ml_tpu import config

    return bool(config.peek("metrics"))


def _exemplar_window() -> float:
    """Seconds an exemplar stays "fresh": inside the window only a worse
    sample evicts it; past it, the next exemplared sample takes the slot
    regardless — so each bucket tracks the worst RECENT trace, not the
    worst ever."""
    from spark_rapids_ml_tpu import config

    try:
        return float(config.peek("telemetry_exemplar_window_s") or 60.0)
    except (TypeError, ValueError):
        return 60.0


def quantile_from_buckets(buckets: Dict[str, int], q: float
                          ) -> Optional[float]:
    """Estimate the q-quantile (0 < q < 1) from CUMULATIVE le→count
    buckets (the snapshot/Prometheus shape), linearly interpolating
    inside the target bucket. None when empty; the +Inf bucket clamps
    to the largest finite bound (no upper edge to interpolate against).
    The ONE estimator both consumers of the snapshot shape use —
    tools/top's latency columns and the serve autoscaler's p99
    objective must read the SAME number from the same histogram."""
    import math

    pairs: List[Tuple[float, int]] = sorted(
        (math.inf if le == "+Inf" else float(le), n)
        for le, n in buckets.items()
    )
    if not pairs or pairs[-1][1] <= 0:
        return None
    total = pairs[-1][1]
    target = q * total
    prev_bound, prev_count = 0.0, 0
    for bound, count in pairs:
        if count >= target:
            if math.isinf(bound):
                return prev_bound
            if count == prev_count:
                return bound
            frac = (target - prev_count) / (count - prev_count)
            return prev_bound + frac * (bound - prev_bound)
        prev_bound, prev_count = (0.0 if math.isinf(bound) else bound), count
    return prev_bound


def _label_key(labels: Dict[str, Any]) -> Tuple[Tuple[str, str], ...]:
    """Canonical hashable form: sorted (name, str(value)) pairs, so
    ``inc(op="feed")`` and ``inc(**{"op": "feed"})`` land in one series."""
    if not labels:
        return ()
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._series: Dict[Tuple[Tuple[str, str], ...], Any] = {}

    def _clear(self) -> None:
        with self._lock:
            self._series.clear()

    def _samples(self) -> List[Tuple[Dict[str, str], Any]]:
        with self._lock:
            return [(dict(k), v) for k, v in sorted(self._series.items())]


class Counter(_Metric):
    kind = "counter"

    def inc(self, amount: float = 1.0, **labels: Any) -> None:
        if not _enabled():
            return
        key = _label_key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount

    def value(self, **labels: Any) -> float:
        """Current value of one series (0.0 when never incremented)."""
        with self._lock:
            return float(self._series.get(_label_key(labels), 0.0))


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, **labels: Any) -> None:
        if not _enabled():
            return
        with self._lock:
            self._series[_label_key(labels)] = float(value)

    def inc(self, amount: float = 1.0, **labels: Any) -> None:
        if not _enabled():
            return
        key = _label_key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels: Any) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels: Any) -> float:
        with self._lock:
            return float(self._series.get(_label_key(labels), 0.0))


class Histogram(_Metric):
    """Fixed-bucket histogram (per-bucket counts + sum + count). Buckets
    are upper bounds with ``le`` (≤) semantics plus an implicit +Inf —
    exactly the Prometheus model, so exposition is a cumulative sum."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "", buckets=DEFAULT_BUCKETS):
        super().__init__(name, help)
        uppers = tuple(float(b) for b in buckets)
        if not uppers or list(uppers) != sorted(set(uppers)):
            raise ValueError(
                f"histogram {name!r} buckets must be distinct and "
                f"ascending, got {buckets!r}"
            )
        self.buckets = uppers
        #: (series key, bucket idx) → (value, ts, trace dict): the worst
        #: sample of the current exemplar window, per bucket.
        self._exemplars: Dict[Tuple[Any, int], Tuple[float, float, Dict[str, str]]] = {}

    def _clear(self) -> None:
        with self._lock:
            self._series.clear()
            self._exemplars.clear()

    def _le(self, idx: int) -> str:
        return _fmt_float(self.buckets[idx]) if idx < len(self.buckets) else "+Inf"

    def exemplars(self, **labels: Any) -> Dict[str, Dict[str, Any]]:
        """Fresh (within-window) exemplars of one series, keyed by the
        bucket's ``le`` bound: ``{le: {"value", "ts", …trace fields}}``."""
        key = _label_key(labels)
        now = time.time()
        window = _exemplar_window()
        with self._lock:
            items = [
                (idx, v, ts, dict(trace))
                for (k, idx), (v, ts, trace) in self._exemplars.items()
                if k == key and now - ts <= window
            ]
        return {
            self._le(idx): {"value": v, "ts": ts, **trace}
            for idx, v, ts, trace in sorted(items)
        }

    def _samples(self):
        # Deep-copy rows under the lock: the base copies the mapping but a
        # row list mutated by a concurrent observe would tear a scrape.
        with self._lock:
            return [
                (dict(k), [list(row[0]), row[1], row[2]])
                for k, row in sorted(self._series.items())
            ]

    def observe(
        self,
        value: float,
        exemplar: Optional[Dict[str, str]] = None,
        **labels: Any,
    ) -> None:
        """Record one sample. ``exemplar`` (optional, additive) is a
        small trace reference — ``{"run": …, "span": …}`` — kept per
        (series, bucket) for the WORST sample of the current exemplar
        window (``telemetry_exemplar_window_s``): a p99 breach on the
        scrape side links straight to the trace that caused it. A label
        literally named ``exemplar`` is therefore reserved."""
        if not _enabled():
            return
        value = float(value)
        idx = bisect_left(self.buckets, value)  # == len(buckets) → +Inf
        key = _label_key(labels)
        with self._lock:
            row = self._series.get(key)
            if row is None:
                row = self._series[key] = [
                    [0] * (len(self.buckets) + 1), 0.0, 0,
                ]
            row[0][idx] += 1
            row[1] += value
            row[2] += 1
            if exemplar:
                now = time.time()
                slot = (key, idx)
                prev = self._exemplars.get(slot)
                if (
                    prev is None
                    or now - prev[1] > _exemplar_window()
                    or value >= prev[0]
                ):
                    self._exemplars[slot] = (value, now, dict(exemplar))

    def series(self, **labels: Any):
        """(cumulative buckets {le_str: n}, sum, count) of one series, or
        None when never observed — test/tool convenience."""
        with self._lock:
            row = self._series.get(_label_key(labels))
            if row is None:
                return None
            counts, total, n = list(row[0]), row[1], row[2]
        return self._cumulate(counts), total, n

    def _cumulate(self, counts: List[int]) -> Dict[str, int]:
        out: Dict[str, int] = {}
        running = 0
        for upper, c in zip(self.buckets, counts):
            running += c
            out[_fmt_float(upper)] = running
        out["+Inf"] = running + counts[-1]
        return out


def _fmt_float(v: float) -> str:
    """Minimal decimal form ("0.005", "1", "60") for bucket bounds and
    sample values — deterministic for the exposition golden test."""
    if v == int(v):
        return str(int(v))
    return repr(v)


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _render_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label(str(v))}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


class Registry:
    """Named metrics, get-or-create. Module-level instances register at
    import; ``reset()`` clears recorded series but keeps the registered
    metric OBJECTS valid (call sites hold direct references)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _get_or_create(self, cls, name: str, help: str, **kw) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as {m.kind}, "
                        f"not {cls.kind}"
                    )
                return m
            m = cls(name, help, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help)

    def histogram(
        self, name: str, help: str = "", buckets=DEFAULT_BUCKETS
    ) -> Histogram:
        return self._get_or_create(Histogram, name, help, buckets=buckets)

    def reset(self) -> None:
        """Clear every recorded series (tests; metric objects survive)."""
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            m._clear()

    def snapshot(self) -> Dict[str, Any]:
        """JSON-able view of every metric with ≥ 1 recorded series — what
        the daemon's ``metrics`` op returns. Histogram buckets are
        CUMULATIVE (Prometheus ``le`` semantics)."""
        with self._lock:
            metrics = sorted(self._metrics.items())
        out: Dict[str, Any] = {}
        for name, m in metrics:
            samples = []
            if isinstance(m, Histogram):
                for labels, row in m._samples():
                    sample = {
                        "labels": labels,
                        "buckets": m._cumulate(row[0]),
                        "sum": row[1],
                        "count": row[2],
                    }
                    ex = m.exemplars(**labels)
                    if ex:
                        sample["exemplars"] = ex
                    samples.append(sample)
            else:
                for labels, v in m._samples():
                    samples.append({"labels": labels, "value": v})
            if samples:
                out[name] = {"type": m.kind, "help": m.help, "samples": samples}
        return out

    def _render(self, exemplars: bool) -> str:
        lines: List[str] = []
        with self._lock:
            metrics = sorted(self._metrics.items())
        for name, m in metrics:
            samples = m._samples()
            if not samples:
                continue
            if m.help:
                lines.append(f"# HELP {name} {m.help}")
            lines.append(f"# TYPE {name} {m.kind}")
            if isinstance(m, Histogram):
                for labels, row in samples:
                    cum = m._cumulate(row[0])
                    ex = m.exemplars(**labels) if exemplars else {}
                    for le, n in cum.items():
                        line = (
                            f"{name}_bucket"
                            f"{_render_labels({**labels, 'le': le})} {n}"
                        )
                        e = ex.get(le)
                        if e is not None:
                            # OpenMetrics exemplar syntax: the trace
                            # labelset, then the sample's value and ts.
                            trace = {
                                k: v for k, v in e.items()
                                if k not in ("value", "ts")
                            }
                            line += (
                                f" # {_render_labels(trace) or '{}'} "
                                f"{_fmt_float(e['value'])} "
                                f"{_fmt_float(e['ts'])}"
                            )
                        lines.append(line)
                    lines.append(
                        f"{name}_sum{_render_labels(labels)} "
                        f"{_fmt_float(row[1])}"
                    )
                    lines.append(
                        f"{name}_count{_render_labels(labels)} {row[2]}"
                    )
            else:
                for labels, v in samples:
                    lines.append(
                        f"{name}{_render_labels(labels)} {_fmt_float(v)}"
                    )
        if exemplars:
            lines.append("# EOF")
        return "\n".join(lines) + ("\n" if lines else "")

    def render_prometheus(self) -> str:
        """Prometheus text exposition format v0.0.4 (the format every
        scraper accepts), metrics and series in sorted order."""
        return self._render(exemplars=False)

    def render_openmetrics(self) -> str:
        """OpenMetrics-style text: the v0.0.4 exposition plus per-bucket
        exemplar suffixes (``… # {run="…",span="…"} value ts``) and the
        terminating ``# EOF`` — what the ``telemetry_pull`` wire op
        ships, so a scraped p99 breach carries the trace that caused
        it."""
        return self._render(exemplars=True)


#: The process-wide registry every layer records into.
REGISTRY = Registry()


def counter(name: str, help: str = "") -> Counter:
    return REGISTRY.counter(name, help)


def gauge(name: str, help: str = "") -> Gauge:
    return REGISTRY.gauge(name, help)


def histogram(name: str, help: str = "", buckets=DEFAULT_BUCKETS) -> Histogram:
    return REGISTRY.histogram(name, help, buckets=buckets)


def snapshot() -> Dict[str, Any]:
    return REGISTRY.snapshot()


def render_prometheus() -> str:
    return REGISTRY.render_prometheus()


def render_openmetrics() -> str:
    return REGISTRY.render_openmetrics()


def reset() -> None:
    REGISTRY.reset()
