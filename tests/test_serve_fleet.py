"""Serving-fleet tests: replicated versioned models, client-side routing,
zero-downtime rollout (serve/fleet.py + serve/router.py).

The load-bearing claims, in test order:

* **routing** — consistent hashing is stable and minimally disruptive;
  sticky keys pin a replica; busy/dead replicas fail over and the answer
  stays bitwise-identical to the single-daemon one;
* **versioning** — a replica refuses a version-mismatched request
  (`serve_version_strict`), acks echo the (version, epoch) pin, and a
  version is immutable under a registration name;
* **rollout** — register v2 → warm → atomic flip → drain v1: concurrent
  traffic never sees a failed or mixed-version response, in-flight v1
  requests complete on v1, and the drain waits for them;
* **chaos flagship** — a rolling v1→v2 swap concurrent with a replica
  SIGKILL (real subprocess daemons) and injected client-side faults
  loses ZERO requests, keeps p99 under the request deadline, and every
  response is bitwise-correct for its version.

Also here: the ADVICE r5 rejected-first-feed orphan race regression, the
serve_batching default-ON burn-in, the tools.top fleet panel, and the
perfcheck fleet gate units.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from spark_rapids_ml_tpu import config
from spark_rapids_ml_tpu.serve import (
    ConsistentHashRing,
    DataPlaneClient,
    DataPlaneDaemon,
    FleetRolloutError,
    ModelFleet,
    RoutingTable,
)
from spark_rapids_ml_tpu.utils import faults
from spark_rapids_ml_tpu.utils import metrics as metrics_mod

D = 16


@pytest.fixture
def pca_v1_v2(rng, mesh8):
    """Two DIFFERENT fitted PCA versions + their transform oracles for a
    fixed query batch: the bitwise ground truth per version."""
    from spark_rapids_ml_tpu.models.pca import PCA

    basis = rng.normal(size=(D, D)) * np.logspace(0, -1.5, D)
    data = rng.normal(size=(400, D)) @ basis
    m1 = PCA(mesh=mesh8).setK(3).fit({"features": data})
    m2 = PCA(mesh=mesh8).setK(2).fit({"features": data})
    q = rng.normal(size=(12, D))
    return {
        "q": q,
        "v1": m1._model_data(),
        "v2": m2._model_data(),
        "ref1": np.asarray(m1.transform_matrix(q)["output"]),
        "ref2": np.asarray(m2.transform_matrix(q)["output"]),
    }


@pytest.fixture
def trio(mesh8):
    """Three in-process replica daemons (one device plane, like the
    multidaemon suites) + their endpoints."""
    daemons = [DataPlaneDaemon(mesh=mesh8).start() for _ in range(3)]
    try:
        yield daemons, [d.address for d in daemons]
    finally:
        for d in daemons:
            d.stop()


# ---------------------------------------------------------------------------
# consistent hashing
# ---------------------------------------------------------------------------


@pytest.mark.fleet
def test_hash_ring_is_stable_and_covers_members():
    """Two independently built rings agree on every key (the digest is
    process-independent — Python's salted hash would not be), and
    ordered() walks every member exactly once, primary first."""
    keys = [f"10.0.0.{i}:7000" for i in range(5)]
    r1 = ConsistentHashRing(keys, vnodes=32)
    r2 = ConsistentHashRing(list(keys), vnodes=32)
    hits = {k: 0 for k in keys}
    for i in range(200):
        k = f"user-{i}"
        assert r1.primary(k) == r2.primary(k)
        order = r1.ordered(k)
        assert sorted(order) == sorted(keys)
        assert order[0] == r1.primary(k)
        hits[order[0]] += 1
    # Uniform-ish spread: every member owns some keys.
    assert all(n > 0 for n in hits.values()), hits


@pytest.mark.fleet
def test_hash_ring_minimal_disruption():
    """Removing one member only moves the keys it owned: every key whose
    primary survives keeps its primary — the property that makes replica
    death cheap for cache affinity."""
    keys = [f"h{i}" for i in range(6)]
    full = ConsistentHashRing(keys, vnodes=64)
    without = ConsistentHashRing(keys[1:], vnodes=64)
    moved = stayed = 0
    for i in range(300):
        k = f"req-{i}"
        p = full.primary(k)
        if p == keys[0]:
            moved += 1
        else:
            assert without.primary(k) == p
            stayed += 1
    assert moved > 0 and stayed > 0


# ---------------------------------------------------------------------------
# routing table: flip atomicity, epoch, drain refcount
# ---------------------------------------------------------------------------


@pytest.mark.fleet
def test_routing_table_flip_and_epoch():
    t = RoutingTable([("127.0.0.1", 1), ("127.0.0.1", 2)], vnodes=8)
    t.install("m", 1, "pca", {}, {})
    with pytest.raises(KeyError):
        t.snapshot("m")  # installed but never activated
    assert t.activate("m", 1) == 1
    assert t.snapshot("m") == (1, 1, "m@v1")
    t.install("m", 2, "pca", {}, {})
    assert t.snapshot("m") == (1, 1, "m@v1")  # install alone routes nothing
    assert t.activate("m", 2) == 2  # the atomic flip bumps the epoch
    assert t.snapshot("m") == (2, 2, "m@v2")
    with pytest.raises(ValueError):
        t.retire("m", 2)  # the ACTIVE version cannot be retired
    t.retire("m", 1)
    assert t.versions("m") == [2]


@pytest.mark.fleet
def test_acquire_pins_atomically_and_reinstall_preserves_inflight():
    """Review findings: (a) a request's snapshot+refcount is ONE lock
    acquisition (`acquire`), so a rollout can never drain-retire the
    version between a read and its pin; (b) re-installing an existing
    version (operator re-seed) must PRESERVE the in-flight count — a
    reset-to-zero would let a later drain yank arrays under live
    requests."""
    t = RoutingTable([("127.0.0.1", 1)], vnodes=8)
    t.install("m", 1, "pca", {}, {})
    t.activate("m", 1)
    assert t.acquire("m") == (1, 1, "m@v1")
    assert t.inflight("m", 1) == 1
    t.install("m", 1, "pca", {}, {})  # re-seed while a request flies
    assert t.inflight("m", 1) == 1  # NOT reset
    assert not t.wait_drained("m", 1, timeout_s=0.05)
    t.done("m", 1)
    assert t.wait_drained("m", 1, timeout_s=1.0)


@pytest.mark.fleet
def test_routing_table_drain_refcount():
    t = RoutingTable([("127.0.0.1", 1)], vnodes=8)
    t.install("m", 1, "pca", {}, {})
    t.activate("m", 1)
    t.begin("m", 1)
    t.begin("m", 1)
    assert t.inflight("m", 1) == 2
    assert not t.wait_drained("m", 1, timeout_s=0.05)
    t.done("m", 1)
    done = threading.Timer(0.1, lambda: t.done("m", 1))
    done.start()
    try:
        assert t.wait_drained("m", 1, timeout_s=5.0)  # wakes on the notify
    finally:
        done.join()
    assert t.inflight("m", 1) == 0


# ---------------------------------------------------------------------------
# routed serving: bitwise, stickiness, failover
# ---------------------------------------------------------------------------


@pytest.mark.fleet
def test_fleet_register_and_routed_transform_bitwise(trio, pca_v1_v2):
    daemons, eps = trio
    with ModelFleet(eps) as fleet:
        res = fleet.register("m", "pca", pca_v1_v2["v1"], version=1)
        assert res == {"version": 1, "epoch": 1, "replicas": 3, "failed": []}
        with fleet.client() as fc:
            for i in range(6):
                out = fc.transform("m", pca_v1_v2["q"], route_key=f"k{i}")
                assert np.array_equal(
                    np.asarray(out["output"]), pca_v1_v2["ref1"]
                )
        # The registration landed on EVERY replica, under the versioned
        # daemon name.
        for host, port in eps:
            with DataPlaneClient(host, port) as c:
                assert c.model_exists("m@v1")


@pytest.mark.fleet
def test_sticky_route_key_pins_one_replica(trio, pca_v1_v2):
    """One sticky key opens exactly one replica connection (cache
    affinity); distinct keys spread across replicas."""
    _, eps = trio
    with ModelFleet(eps) as fleet:
        fleet.register("m", "pca", pca_v1_v2["v1"], warm=False)
        with fleet.client() as fc:
            for _ in range(5):
                fc.transform("m", pca_v1_v2["q"], route_key="user-7")
            primary = fleet.table.ring.primary("user-7")
            assert fc.stats == {primary: 5}  # all five on the ring owner
        with fleet.client() as fc:
            for i in range(30):
                fc.transform("m", pca_v1_v2["q"], route_key=f"user-{i}")
            assert sorted(fc.stats) == sorted(
                fleet.table.ring.members
            )  # uniform keys reach the whole fleet
            assert sum(fc.stats.values()) == 30


@pytest.mark.fleet
def test_failover_on_dead_replica_is_bitwise(trio, pca_v1_v2):
    """Kill the replica that owns a sticky key: the request fails over
    and the answer stays bitwise-identical; the dead replica is marked
    and skipped until its re-probe."""
    daemons, eps = trio
    with ModelFleet(eps) as fleet:
        fleet.register("m", "pca", pca_v1_v2["v1"], warm=False)
        with fleet.client(health_poll_s=30.0) as fc:
            primary = fleet.table.ring.primary("sticky")
            victim = next(
                d for d in daemons
                if f"{d.address[0]}:{d.address[1]}" == primary
            )
            victim.stop()
            out = fc.transform("m", pca_v1_v2["q"], route_key="sticky")
            assert np.array_equal(np.asarray(out["output"]), pca_v1_v2["ref1"])
            assert not fleet.table.replica(primary).alive
            # Subsequent requests skip the corpse without re-dialing it.
            out = fc.transform("m", pca_v1_v2["q"], route_key="sticky")
            assert np.array_equal(np.asarray(out["output"]), pca_v1_v2["ref1"])


@pytest.mark.fleet
@pytest.mark.chaos
def test_failover_on_busy_shed(trio, pca_v1_v2):
    """An injected scheduler fault sheds the first attempt with `busy`;
    the router reroutes instead of waiting, counts the failover, and the
    retried answer is exact."""
    _, eps = trio
    metrics_mod.reset()
    with ModelFleet(eps) as fleet:
        fleet.register("m", "pca", pca_v1_v2["v1"], warm=False)
        plan = faults.FaultPlan(seed=3).rule(
            "daemon.scheduler", "drop", times=1
        )
        with faults.active(plan):
            with fleet.client() as fc:
                out = fc.transform("m", pca_v1_v2["q"], route_key="x")
        assert np.array_equal(np.asarray(out["output"]), pca_v1_v2["ref1"])
        assert plan.fired.get("daemon.scheduler") == 1
    snap = metrics_mod.snapshot()
    failovers = {
        s["labels"]["reason"]: s["value"]
        for s in snap["srml_router_failovers_total"]["samples"]
    }
    assert failovers.get("busy") == 1


# ---------------------------------------------------------------------------
# version fence + echo
# ---------------------------------------------------------------------------


@pytest.mark.fleet
def test_version_fence_refuses_mismatch_and_echoes(trio, pca_v1_v2):
    _, eps = trio
    host, port = eps[0]
    with DataPlaneClient(host, port) as c:
        c.ensure_model("m@v1", "pca", pca_v1_v2["v1"], version=1)
        out, meta = c.transform(
            "m@v1", pca_v1_v2["q"], version=1, fleet_epoch=7, with_meta=True
        )
        assert np.array_equal(np.asarray(out["output"]), pca_v1_v2["ref1"])
        assert meta["version"] == 1 and meta["fleet_epoch"] == 7
        # The fence: a request pinned to v2 must not be answered by v1.
        with pytest.raises(RuntimeError, match="version mismatch"):
            c.transform("m@v1", pca_v1_v2["q"], version=2)
        # Debug mode answers (with a warning) instead of refusing.
        with config.option("serve_version_strict", False):
            out = c.transform("m@v1", pca_v1_v2["q"], version=2)
            assert np.array_equal(np.asarray(out["output"]), pca_v1_v2["ref1"])
        # Unpinned requests (no version field) are untouched.
        out = c.transform("m@v1", pca_v1_v2["q"])
        assert np.array_equal(np.asarray(out["output"]), pca_v1_v2["ref1"])


@pytest.mark.fleet
def test_version_is_immutable_under_a_name(trio, pca_v1_v2):
    _, eps = trio
    host, port = eps[0]
    with DataPlaneClient(host, port) as c:
        c.ensure_model("m@v1", "pca", pca_v1_v2["v1"], version=1)
        with pytest.raises(RuntimeError, match="immutable"):
            c.ensure_model("m@v1", "pca", pca_v1_v2["v2"], version=2)
        # Same version re-register stays the idempotent no-op.
        assert c.ensure_model("m@v1", "pca", pca_v1_v2["v1"], version=1) is False


# ---------------------------------------------------------------------------
# rollout: atomic flip, drain, zero downtime
# ---------------------------------------------------------------------------


@pytest.mark.fleet
def test_rollout_flips_drains_and_drops_v1(trio, pca_v1_v2):
    daemons, eps = trio
    with ModelFleet(eps) as fleet:
        fleet.register("m", "pca", pca_v1_v2["v1"], warm=False)
        res = fleet.rollout("m", "pca", pca_v1_v2["v2"], warm=False)
        assert res["version"] == 2 and res["previous"] == 1
        assert res["epoch"] == 2 and res["drained"] is True
        with fleet.client() as fc:
            out = fc.transform("m", pca_v1_v2["q"])
            assert np.array_equal(np.asarray(out["output"]), pca_v1_v2["ref2"])
        for host, port in eps:
            with DataPlaneClient(host, port) as c:
                assert c.model_exists("m@v2")
                assert not c.model_exists("m@v1")  # drained then dropped
        assert fleet.table.versions("m") == [2]


@pytest.mark.fleet
def test_rollout_drain_timeout_keeps_v1_registered(trio, pca_v1_v2):
    """An in-flight v1 request blocks the drain: the rollout flips (new
    traffic is v2) but leaves v1's registrations up rather than yanking
    arrays out from under the pinned request."""
    _, eps = trio
    with ModelFleet(eps) as fleet:
        fleet.register("m", "pca", pca_v1_v2["v1"], warm=False)
        fleet.table.begin("m", 1)  # a pinned v1 request, still flying
        res = fleet.rollout(
            "m", "pca", pca_v1_v2["v2"], warm=False, drain_timeout_s=0.2
        )
        assert res["drained"] is False
        host, port = eps[0]
        with DataPlaneClient(host, port) as c:
            assert c.model_exists("m@v1")  # survived the timeout
            assert c.model_exists("m@v2")
        fleet.table.done("m", 1)
        assert fleet.table.wait_drained("m", 1, timeout_s=1.0)


@pytest.mark.fleet
def test_rollout_zero_downtime_under_concurrent_traffic(trio, pca_v1_v2):
    """The acceptance shape, in-process: client threads hammer transform
    while the rollout flips v1→v2 — zero failed requests, every response
    bitwise-equal to exactly ONE version's oracle, and the tail is all
    v2."""
    _, eps = trio
    q, ref1, ref2 = pca_v1_v2["q"], pca_v1_v2["ref1"], pca_v1_v2["ref2"]
    with ModelFleet(eps) as fleet:
        fleet.register("m", "pca", pca_v1_v2["v1"], warm=False)
        stop = threading.Event()
        results: list = []
        errors: list = []

        def worker(i: int) -> None:
            try:
                with fleet.client() as fc:
                    n = 0
                    while not stop.is_set():
                        out = fc.transform("m", q, route_key=f"w{i}-{n}")
                        results.append(np.asarray(out["output"]))
                        n += 1
            except Exception as e:  # pragma: no cover - failure path
                errors.append(e)

        threads = [
            threading.Thread(target=worker, args=(i,)) for i in range(4)
        ]
        for t in threads:
            t.start()
        time.sleep(0.3)  # v1 traffic flowing
        fleet.rollout("m", "pca", pca_v1_v2["v2"], warm=False)
        time.sleep(0.3)  # v2 traffic flowing
        stop.set()
        for t in threads:
            t.join()
        assert errors == []
        assert len(results) > 0
        n_v1 = n_v2 = 0
        for out in results:
            if out.shape == ref1.shape and np.array_equal(out, ref1):
                n_v1 += 1
            elif out.shape == ref2.shape and np.array_equal(out, ref2):
                n_v2 += 1
            else:  # pragma: no cover - the mixed-version failure mode
                raise AssertionError(
                    "a response matched NEITHER version's oracle bitwise"
                )
        assert n_v1 > 0 and n_v2 > 0  # the swap happened mid-traffic


@pytest.mark.fleet
def test_register_all_replicas_dead_raises(pca_v1_v2):
    # Ports from the ephemeral range with nothing listening.
    with ModelFleet([("127.0.0.1", 1), ("127.0.0.1", 2)],
                    client_kwargs={"timeout": 0.5, "op_deadline_s": 1.0,
                                   "max_op_attempts": 1}) as fleet:
        with pytest.raises(FleetRolloutError):
            fleet.register("m", "pca", pca_v1_v2["v1"], warm=False)
        with pytest.raises(KeyError):
            fleet.table.snapshot("m")  # nothing activated


@pytest.mark.fleet
def test_router_repairs_restarted_replica(trio, pca_v1_v2):
    """A replica restart loses its (re-creatable) registry; the router's
    in-band repair re-registers the pinned version and the sticky key's
    traffic continues on its home replica."""
    daemons, eps = trio
    metrics_mod.reset()
    with ModelFleet(eps) as fleet:
        fleet.register("m", "pca", pca_v1_v2["v1"], warm=False)
        primary = fleet.table.ring.primary("sticky")
        idx = next(
            i for i, d in enumerate(daemons)
            if f"{d.address[0]}:{d.address[1]}" == primary
        )
        host, port = daemons[idx].address
        daemons[idx].stop()
        daemons[idx] = DataPlaneDaemon(
            host=host, port=port, mesh=daemons[idx]._mesh
        ).start()  # same address, empty registry
        with fleet.client(health_poll_s=30.0) as fc:
            out = fc.transform("m", pca_v1_v2["q"], route_key="sticky")
            assert np.array_equal(np.asarray(out["output"]), pca_v1_v2["ref1"])
        with DataPlaneClient(host, port) as c:
            assert c.model_exists("m@v1")  # the repair re-registered it
    snap = metrics_mod.snapshot()
    repairs = snap.get("srml_router_repairs_total", {}).get("samples", [])
    assert repairs and repairs[0]["value"] >= 1


# ---------------------------------------------------------------------------
# chaos flagship: rolling swap + replica SIGKILL, zero lost requests
# ---------------------------------------------------------------------------


@pytest.mark.fleet
@pytest.mark.chaos
@pytest.mark.slow
def test_chaos_rolling_swap_with_replica_sigkill(pca_v1_v2,
                                                 worker_daemon_pair):
    """The acceptance flagship: 3 subprocess replicas, a rolling v1→v2
    swap concurrent with a SIGKILL of one replica, seeded client-side
    fault injection on top — and still: zero lost requests, p99 under
    the request deadline, every response bitwise-correct FOR ITS
    VERSION. The two SURVIVING replicas are the module's shared worker
    pair (conftest.py — VERDICT carry #7: one spawn pays for the whole
    module); only the SIGKILL victim is spawned here. Model names are
    dropped from the survivors on the way out, whatever happened."""
    from conftest import spawn_daemon_worker

    DEADLINE_S = 30.0  # generous: subprocess CPU daemons jit-compile lazily
    q, ref1, ref2 = pca_v1_v2["q"], pca_v1_v2["ref1"], pca_v1_v2["ref2"]
    victim, victim_port = spawn_daemon_worker()
    procs = [victim]
    eps = [("127.0.0.1", victim_port)] + [
        ("127.0.0.1", port) for _, port in worker_daemon_pair
    ]
    try:
        with ModelFleet(eps) as fleet:
            fleet.register("m", "pca", pca_v1_v2["v1"])
            n_workers, n_reqs = 4, 25
            latencies: list = []
            outputs: list = []
            errors: list = []
            lock = threading.Lock()
            barrier = threading.Barrier(n_workers + 1)

            # Set once fleet.rollout() has returned (the atomic flip is
            # behind it): workers keep traffic flowing UNTIL then — the
            # swap-under-fire overlap is guaranteed, not a race between
            # a fixed request count and the rollout's wall clock — and
            # then issue a couple of guaranteed post-flip requests.
            flipped = threading.Event()

            def worker(i: int) -> None:
                try:
                    with fleet.client() as fc:
                        fc.transform("m", q)  # warm sockets pre-barrier
                        barrier.wait()
                        n = 0
                        while (
                            n < n_reqs or not flipped.is_set()
                        ) and n < n_reqs * 40:
                            t0 = time.perf_counter()
                            out = fc.transform(
                                "m", q, route_key=f"w{i}-{n}",
                                deadline_s=DEADLINE_S,
                            )
                            dt = time.perf_counter() - t0
                            with lock:
                                latencies.append(dt)
                                outputs.append(np.asarray(out["output"]))
                            n += 1
                        for extra in range(2):  # post-flip: must be v2
                            out = fc.transform(
                                "m", q, route_key=f"w{i}-post{extra}",
                                deadline_s=DEADLINE_S,
                            )
                            with lock:
                                outputs.append(np.asarray(out["output"]))
                except Exception as e:  # pragma: no cover - failure path
                    with lock:
                        errors.append(e)

            # Seeded chaos on the CLIENT side too: sporadic connection
            # drops exercise the healing + failover paths during the
            # swap (the daemon side gets the real chaos: SIGKILL).
            plan = faults.FaultPlan(seed=11).rule(
                "client.op", "drop", p=0.03
            )
            threads = [
                threading.Thread(target=worker, args=(i,))
                for i in range(n_workers)
            ]
            with faults.active(plan):
                for t in threads:
                    t.start()
                barrier.wait()
                time.sleep(0.2)  # v1 traffic in flight
                killed = procs[0]
                killed.kill()  # SIGKILL: a replica dies mid-swap
                fleet.rollout("m", "pca", pca_v1_v2["v2"])
                flipped.set()  # workers may now finish (post-flip reqs)
                for t in threads:
                    t.join()
            killed.wait(timeout=10)

        assert errors == [], f"lost {len(errors)} request(s): {errors[:3]}"
        assert len(outputs) >= n_workers * n_reqs  # zero lost requests
        latencies.sort()
        p99 = latencies[min(int(len(latencies) * 0.99), len(latencies) - 1)]
        assert p99 < DEADLINE_S, f"p99 {p99:.3f}s breached the deadline"
        n_v1 = n_v2 = 0
        for out in outputs:
            if out.shape == ref1.shape and np.array_equal(out, ref1):
                n_v1 += 1
            elif out.shape == ref2.shape and np.array_equal(out, ref2):
                n_v2 += 1
            else:  # pragma: no cover - the mixed-version failure mode
                raise AssertionError(
                    "a response matched NEITHER version's oracle bitwise"
                )
        assert n_v2 > 0  # the swap completed under fire
    finally:
        for p in procs:
            try:
                p.stdin.close()
            except OSError:
                pass
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:  # pragma: no cover
                p.kill()
        # The shared pair outlives this test: release the versioned
        # registrations so later users of the pair see a clean slate
        # (drop_model is idempotent — a drained v1 is already gone).
        for _, port in worker_daemon_pair:
            try:
                with DataPlaneClient("127.0.0.1", port, timeout=5.0,
                                     max_op_attempts=2) as dc:
                    dc.drop_model("m@v1")
                    dc.drop_model("m@v2")
            except Exception:
                pass


# ---------------------------------------------------------------------------
# ADVICE r5: rejected-first-feed orphan cleanup race
# ---------------------------------------------------------------------------


@pytest.mark.fleet
def test_feed_into_raced_orphan_cleanup_retries(mesh8, rng, monkeypatch):
    """Deterministic replay of the ADVICE r5 interleaving: a valid first
    feed holds a job object that a concurrent rejected-first-feed
    cleanup has already dropped-and-deleted (empty). The feed must
    transparently retry against the live registry instead of failing
    with a spurious KeyError."""
    from spark_rapids_ml_tpu.serve.daemon import _Job

    with DataPlaneDaemon(mesh=mesh8) as daemon:
        victim = _Job("pca", D, mesh8)
        victim.dropped = True  # the cleanup's tombstone; rows == 0
        real = daemon._lookup_job
        state = {"handed": False}

        def racy_lookup(name):
            if name == "race-job" and not state["handed"]:
                state["handed"] = True
                return victim  # the stale fetch the race produces
            return real(name)

        monkeypatch.setattr(daemon, "_lookup_job", racy_lookup)
        x = rng.normal(size=(8, D))
        with DataPlaneClient(*daemon.address) as c:
            assert c.feed("race-job", x) == 8  # healed, not KeyError
            assert c.status("race-job")["rows"] == 8


@pytest.mark.fleet
def test_feed_into_legitimately_dropped_job_still_fails(mesh8, rng,
                                                        monkeypatch):
    """The retry is scoped to the RACE victim (empty + unregistered): a
    dropped job that holds rows — a finalized fit — still fails the
    late feed loudly instead of silently restarting the job."""
    from spark_rapids_ml_tpu.serve.daemon import _Job

    with DataPlaneDaemon(mesh=mesh8) as daemon:
        stale = _Job("pca", D, mesh8)
        stale.dropped = True
        stale.rows = 100  # NOT the race victim: it held committed rows
        real = daemon._lookup_job
        state = {"handed": False}

        def racy_lookup(name):
            if name == "stale-job" and not state["handed"]:
                state["handed"] = True
                return stale
            return real(name)

        monkeypatch.setattr(daemon, "_lookup_job", racy_lookup)
        with DataPlaneClient(*daemon.address) as c:
            with pytest.raises(RuntimeError, match="dropped"):
                c.feed("stale-job", rng.normal(size=(8, D)))


@pytest.mark.fleet
@pytest.mark.chaos
def test_concurrent_valid_and_rejected_first_feeds(mesh8, rng):
    """Stress the real interleaving: valid first feeds racing rejected
    ones (stale pass_id) under one job name, repeatedly. Valid feeds
    must NEVER fail; total committed rows must account exactly for the
    valid feeds that were acked."""
    x = rng.normal(size=(4, D))
    with DataPlaneDaemon(mesh=mesh8) as daemon:
        host, port = daemon.address
        for round_no in range(8):
            name = f"race-{round_no}"
            errors: list = []
            acked = [0]
            barrier = threading.Barrier(4)

            def worker(i: int, _name=name, _errors=errors, _acked=acked,
                       _barrier=barrier) -> None:
                try:
                    with DataPlaneClient(host, port) as c:
                        _barrier.wait()
                        if i % 2 == 0:
                            c.feed(_name, x)  # valid: must never fail
                            _acked[0] += 1
                        else:
                            try:
                                # Stale pass_id: rejected by _check_pass,
                                # triggering the orphan cleanup when it
                                # created the job.
                                c.feed(_name, x, pass_id=1)
                            except RuntimeError:
                                pass  # the rejection is the point
                except Exception as e:  # pragma: no cover - failure path
                    _errors.append(e)

            threads = [
                threading.Thread(target=worker, args=(i,)) for i in range(4)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert errors == [], f"round {round_no}: {errors}"
            with DataPlaneClient(host, port) as c:
                assert c.status(name)["rows"] == 4 * acked[0]


# ---------------------------------------------------------------------------
# serve_batching default-ON burn-in
# ---------------------------------------------------------------------------


@pytest.mark.fleet
@pytest.mark.serving
def test_serve_batching_defaults_on_and_stays_bitwise(mesh8, pca_v1_v2):
    """The PR's default flip, burned in: a daemon built with NO explicit
    options runs the scheduler (health says so), serves bitwise-equal to
    the in-memory model, and SRML_SERVE_BATCHING=0 remains the opt-out
    (config honors the env spelling)."""
    import spark_rapids_ml_tpu.config as config_mod

    assert config_mod._DEFAULTS["serve_batching"] is True
    assert config.get("serve_batching") is True
    with DataPlaneDaemon(mesh=mesh8) as daemon:  # default config: batching
        with DataPlaneClient(*daemon.address) as c:
            assert c.health()["scheduler"]["enabled"] is True
            c.ensure_model("m", "pca", pca_v1_v2["v1"])
            out = c.transform("m", pca_v1_v2["q"])
            assert np.array_equal(
                np.asarray(out["output"]), pca_v1_v2["ref1"]
            )
            # The warmup op is live under the default too.
            info = c.warmup("m", n_cols=D)
            assert info["enabled"] is True
    with config.option("serve_batching", False):  # the documented opt-out
        with DataPlaneDaemon(mesh=mesh8) as daemon:
            with DataPlaneClient(*daemon.address) as c:
                assert c.health()["scheduler"] == {"enabled": False}
                c.ensure_model("m", "pca", pca_v1_v2["v1"])
                out = c.transform("m", pca_v1_v2["q"])
                assert np.array_equal(
                    np.asarray(out["output"]), pca_v1_v2["ref1"]
                )


# ---------------------------------------------------------------------------
# tools: top fleet panel, perfcheck fleet gate
# ---------------------------------------------------------------------------


@pytest.mark.fleet
def test_top_fleet_panel_renders_up_and_down_replicas():
    from spark_rapids_ml_tpu.tools.top import render_fleet

    healths = {
        "127.0.0.1:7001": {
            "id": "abc123", "boot_id": "boot1", "uptime_s": 12.0,
            "queue_depth": 3, "served_models": 2,
            "scheduler": {"enabled": True, "queued": 5}, "busy": False,
        },
        "127.0.0.1:7002": None,  # unreachable replica
        "127.0.0.1:7003": {
            "id": "def456", "boot_id": "boot2", "uptime_s": 7.0,
            "queue_depth": 0, "served_models": 2,
            "scheduler": {"enabled": True, "queued": 0}, "busy": True,
        },
    }
    body = render_fleet(healths)
    assert "2/3 replicas up" in body
    assert "DOWN" in body
    assert "BUSY" in body
    assert "abc123" in body and "def456" in body


@pytest.mark.fleet
def test_perfcheck_fleet_gate():
    from spark_rapids_ml_tpu.tools.perfcheck import check_serve_fleet

    good = {
        "metric": "serve_fleet_transform_qps_d256_k16_c8_b64",
        "value": 4000.0, "n_replicas": 4, "dryrun": False,
        "scaling_efficiency": 0.85,
    }
    ok, lines = check_serve_fleet(good, [])
    assert ok and any("OK" in ln for ln in lines)

    bad = {**good, "scaling_efficiency": 0.55}
    ok, lines = check_serve_fleet(bad, [])
    assert not ok and any("REGRESSION" in ln for ln in lines)

    # Dryrun (in-process smoke) records SKIP — explicitly not a pass.
    dry = {**good, "dryrun": True}
    ok, lines = check_serve_fleet(dry, [])
    assert ok and any("SKIP" in ln and "NOT a pass" in ln for ln in lines)

    # The trajectory median raises the floor above the absolute 0.7.
    history = [
        {**good, "scaling_efficiency": 0.95, "value": 5000.0}
        for _ in range(3)
    ]
    ok, lines = check_serve_fleet({**good, "scaling_efficiency": 0.75},
                                  history)
    assert not ok  # 0.75 < 0.85 * 0.95

    # wire_limited (the host's transport cannot even carry N x QPS_1):
    # the absolute gate SKIPs — explicitly not a pass — and the
    # fabric-relative efficiency is gated instead.
    wire = {"pairs": 4, "reqs_per_s_1": 600.0, "reqs_per_s_n": 1700.0}
    limited = {
        **good, "scaling_efficiency": 0.45, "wire_limited": True,
        "wire": wire, "fabric_relative_efficiency": 0.76,
    }
    ok, lines = check_serve_fleet(limited, [])
    assert ok
    assert any("SKIP" in ln and "NOT a pass" in ln for ln in lines)
    assert any("fabric-relative [OK]" in ln for ln in lines)
    ok, lines = check_serve_fleet(
        {**limited, "fabric_relative_efficiency": 0.5}, []
    )
    assert not ok and any("REGRESSION" in ln for ln in lines)
    ok, _ = check_serve_fleet(
        {k: v for k, v in limited.items()
         if k != "fabric_relative_efficiency"}, []
    )
    assert not ok  # wire_limited without the relative number cannot pass

    # Missing efficiency = not a fleet record.
    ok, _ = check_serve_fleet({"metric": "serve_fleet_x", "value": 1.0}, [])
    assert not ok


@pytest.mark.fleet
@pytest.mark.perf
@pytest.mark.slow
def test_bench_fleet_smoke_dryrun():
    """End-to-end plumbing of ``bench.py --serve --fleet`` in the
    in-process smoke mode: the record parses, carries the fleet fields,
    and perfcheck reads a dryrun as SKIP, never a pass."""
    from spark_rapids_ml_tpu.tools.perfcheck import check_serve_fleet

    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "SRML_BENCH_FLEET_INPROC": "1",
        "SRML_BENCH_FLEET_REPLICAS": "2",
        "SRML_BENCH_FLEET_CLIENTS": "2",
        "SRML_BENCH_FLEET_REQS": "3",
        "SRML_BENCH_FLEET_D": "32",
        "SRML_BENCH_FLEET_K": "4",
        "SRML_BENCH_FLEET_ROWS": "16",
    }
    out = subprocess.run(
        [sys.executable, "bench.py", "--serve", "--fleet"],
        capture_output=True, text=True, timeout=300, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["metric"].startswith("serve_fleet_transform_qps")
    assert rec["dryrun"] is True
    assert rec["n_replicas"] == 2
    assert set(rec["replicas"]) == {"1", "2"}
    ok, lines = check_serve_fleet(rec, [])
    assert ok and any("SKIP" in ln for ln in lines)
