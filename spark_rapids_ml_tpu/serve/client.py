"""Client side of the data-plane protocol — what a Spark task runs.

A task opens one connection, feeds its partition as one or more Arrow IPC
frames, and closes; the driver (or any one caller) finalizes. Socket-level
work only — no JAX on the executor side, mirroring how the reference keeps
executors JVM-only and the math behind the JNI boundary.

Self-healing: every op runs inside a reconnect loop. A connection-level
failure (``ConnectionError``, ``ProtocolError``, socket timeout, any
``OSError``) drops the cached socket, backs off with decorrelated jitter
(utils/retry.py — pure exponential backoff would synchronize a fleet of
executors into a thundering herd on daemon restart), reconnects, and
replays the op. Replay is exactly-once: ``feed``/``feed_raw`` carry a
client-generated ``feed_id`` and ``step`` a ``step_id`` that the daemon
dedupes, ``commit``/``seed`` are idempotent by design, and reads are
pure. A per-op deadline (``op_deadline_s``) bounds the TOTAL time spent
healing one op, separately from the per-socket-syscall ``timeout``. A
``busy`` response (daemon over its backpressure watermark) is honored by
waiting the daemon's ``retry_after_s`` hint (jittered) without burning a
reconnect attempt. ``finalize`` with ``drop=True`` is the one op replay
cannot make idempotent — see "Client retry obligations" in
docs/protocol.md.
"""

from __future__ import annotations

import random
import socket
import time
import uuid
from typing import Any, Dict, Optional, Tuple

import numpy as np

from spark_rapids_ml_tpu.serve import protocol
from spark_rapids_ml_tpu.utils import faults
from spark_rapids_ml_tpu.utils import journal
from spark_rapids_ml_tpu.utils import metrics as metrics_mod
from spark_rapids_ml_tpu.utils.logging import get_logger
from spark_rapids_ml_tpu.utils.retry import decorrelated_jitter

logger = get_logger("serve.client")

#: Ops whose acks prove rows/state landed on the answering incarnation —
#: the only acks that feed the boot fence. A ping's boot_id is excluded:
#: a restart between a task's identity ping and its first feed is
#: harmless (every row lands on the new incarnation), and counting it
#: would fail a fully consistent pass.
_STATE_ACK_OPS = frozenset((
    "feed", "feed_raw", "seed", "commit", "step", "set_iterate",
    "merge_state", "finalize",
))

#: Client healing telemetry (process-wide registry; per-instance deltas
#: live in ``DataPlaneClient.stats``). A retry storm, a backoff pile-up,
#: or a fault-injection campaign is countable here — PR 2 proved the
#: healing works, these numbers say how often it RUNS.
_M_RECONNECTS = metrics_mod.counter(
    "srml_client_reconnects_total",
    "Connection-level failures healed by reconnecting, by op",
)
_M_REPLAYS = metrics_mod.counter(
    "srml_client_replays_total",
    "Ops replayed after possibly reaching the wire, by op",
)
_M_BACKOFF_SECONDS = metrics_mod.counter(
    "srml_client_backoff_seconds_total",
    "Seconds slept in reconnect backoff (decorrelated jitter)",
)
_M_BUSY_WAITS = metrics_mod.counter(
    "srml_client_busy_waits_total", "busy sheds honored with a wait, by op"
)
_M_BUSY_WAIT_SECONDS = metrics_mod.counter(
    "srml_client_busy_wait_seconds_total",
    "Seconds slept honoring busy retry_after_s hints",
)
_M_DEADLINE_EXPIRIES = metrics_mod.counter(
    "srml_client_deadline_expiries_total",
    "Ops abandoned because the per-op deadline expired, by op",
)
_M_FAULT_TRIPS = metrics_mod.counter(
    "srml_client_fault_trips_total",
    "Injected faults (utils/faults.py) observed by the healing loop, by op",
)


class DaemonBusy(RuntimeError):
    """Daemon shed the op under load; retry after ``retry_after_s``."""

    def __init__(self, message: str, retry_after_s: float):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)


class DataPlaneClient:
    def __init__(
        self,
        host: str,
        port: int,
        timeout: float = 120.0,
        token: Optional[str] = None,
        op_deadline_s: Optional[float] = None,
        max_op_attempts: int = 5,
        backoff_base_s: float = 0.05,
        backoff_max_s: float = 2.0,
        max_busy_wait_s: Optional[float] = None,
        trace_ctx: Optional[Dict[str, str]] = None,
    ):
        """``timeout`` bounds one socket syscall; ``op_deadline_s`` bounds
        one whole op including every reconnect/replay/busy-wait (None =
        attempts alone bound it); ``max_op_attempts`` counts connection
        failures per op; ``max_busy_wait_s`` caps cumulative busy-shed
        waiting per op. Its default (None) resolves to 60 s when NO
        deadline is set and to the deadline alone otherwise (a caller
        who budgeted 300 s must not be silently capped at 60); an
        EXPLICIT value is enforced alongside any deadline — a
        fleet-routed client sets it to 0 so a shed surfaces to the
        router immediately (serve/router.py).

        ``trace_ctx``: a fixed ``{"run", "span"}`` distributed-tracing
        context stamped on every request (additive wire field,
        docs/protocol.md) — how an executor-side client, whose process
        never opened the driver's journal run, still parents the
        daemon's spans into it. None (default): each op stamps the
        calling thread's CURRENT journal frame, so driver-side clients
        trace for free; with the journal off nothing is stamped and the
        wire bytes are exactly the pre-tracing ones."""
        self._addr = (host, int(port))
        self._timeout = timeout
        self._token = token
        self._sock: Optional[socket.socket] = None
        self._op_deadline = op_deadline_s
        self._max_attempts = max(1, int(max_op_attempts))
        self._backoff_base = backoff_base_s
        self._backoff_max = backoff_max_s
        # None = default policy: 60 s cap when no deadline bounds the op,
        # deadline-only otherwise. Explicit values always enforce.
        self._busy_wait_explicit = max_busy_wait_s is not None
        self._max_busy_wait = (
            60.0 if max_busy_wait_s is None else float(max_busy_wait_s)
        )
        self._trace_ctx = trace_ctx
        self._rng = random.Random()
        # Feed/step idempotency nonce: replayed ops carry the same id, so
        # the daemon can discard a duplicate whose first ack was lost.
        self._nonce = uuid.uuid4().hex[:12]
        self._seq = 0
        #: Healing counters (reconnects, replays, busy_waits) — cheap
        #: observability for chaos tests and ops dashboards.
        self.stats: Dict[str, int] = {
            "reconnects": 0, "replays": 0, "busy_waits": 0,
        }
        #: Every daemon incarnation (boot_id) whose STATE-TOUCHING acks
        #: (feed/seed/commit/step/… — see _STATE_ACK_OPS; pings are
        #: excluded) this client has seen. One entry is the normal case;
        #: two means rows/state straddled a restart — the incarnation
        #: fence the Spark estimator's pass replay keys on
        #: (docs/protocol.md "Crash recovery").
        self.seen_boot_ids: set = set()
        #: The instance id of the LAST ack received — live ground truth
        #: that outranks any cached ping: after a volatile restart the
        #: daemon answers with a new identity, and callers that keep an
        #: id cache (the executor-side feed task) must follow it.
        self.last_server_id: Optional[str] = None

    # -- connection --------------------------------------------------------

    def _conn(self, deadline: Optional[float] = None) -> socket.socket:
        if self._sock is None:
            faults.checkpoint("client.connect")
            # The connect syscall honors the op deadline too: a
            # blackholed host (SYNs dropped — the partition case the
            # healing targets) must cost the remaining budget, not the
            # full socket timeout per reconnect attempt.
            timeout = self._timeout
            if deadline is not None:
                timeout = min(timeout, max(deadline - time.monotonic(), 0.01))
            s = socket.create_connection(self._addr, timeout=timeout)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # One client per thread by contract (class docstring); the
            # gossip thread builds a FRESH client per exchange, so this
            # state is thread-local by construction.
            self._sock = s  # srml: disable=thread-shared-state
        return self._sock

    def _reset(self) -> None:
        """Drop the cached socket: after a connection-level error it may
        be desynced mid-frame — reusing it fails confusingly."""
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            # Thread-local by the one-client-per-thread contract (see
            # _conn).
            self._sock = None  # srml: disable=thread-shared-state

    def close(self) -> None:
        # Same as _reset (one behavior, not two): a socket that errors on
        # close inside a `with` block must not mask the exception the
        # block is already unwinding with.
        self._reset()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def _op_id(self) -> str:
        self._seq += 1
        return f"{self._nonce}-{self._seq}"

    def _attempt(
        self,
        req: Dict[str, Any],
        payload: Optional[bytes],
        arrays: Optional[Dict[str, np.ndarray]],
        want_arrays: bool,
        deadline: Optional[float] = None,
        sent: Optional[Dict[str, bool]] = None,
    ):
        """One request/response exchange on the cached connection; reads
        any response array frames INSIDE the attempt so a drop mid-response
        replays the whole op instead of desyncing. ``sent`` (out-param) is
        flipped once request bytes may have reached the wire — the line
        between a retry that merely reconnects and one that REPLAYS."""
        faults.checkpoint("client.op")
        sock = self._conn(deadline=deadline)
        if deadline is not None:
            # The op deadline must bound BLOCKED syscalls too, not just
            # the gaps between attempts: clamp this attempt's socket
            # timeout to the remaining budget (floor 10 ms so an
            # already-expired deadline fails fast instead of raising an
            # invalid-timeout error).
            sock.settimeout(
                min(self._timeout, max(deadline - time.monotonic(), 0.01))
            )
        req = {"v": protocol.PROTOCOL_VERSION, **req}
        if self._token is not None:
            req = {**req, "token": self._token}
        if sent is not None:
            sent["flag"] = True
        if arrays is not None:
            protocol.send_arrays(
                sock, {k: np.asarray(v) for k, v in arrays.items()}, req
            )
        else:
            protocol.send_json(sock, req)
            if payload is not None:
                protocol.send_frame(sock, payload)
        resp = protocol.recv_json(sock)
        if resp is None:
            raise ConnectionError("daemon closed the connection")
        if not resp.get("ok", False):
            if resp.get("busy"):
                raise DaemonBusy(
                    f"daemon busy: {resp.get('error')}",
                    float(resp.get("retry_after_s", 1.0)),
                )
            raise RuntimeError(f"daemon error: {resp.get('error')}")
        boot = resp.get("boot_id")
        if boot is not None and req.get("op") in _STATE_ACK_OPS:
            self.seen_boot_ids.add(str(boot))
        sid = resp.get("id")
        if sid is not None:
            # Thread-local by the one-client-per-thread contract (see
            # _conn).
            self.last_server_id = str(sid)  # srml: disable=thread-shared-state
        outs = protocol.recv_arrays(sock, resp) if want_arrays else None
        return resp, outs

    def _op(
        self,
        req: Dict[str, Any],
        payload: Optional[bytes] = None,
        arrays: Optional[Dict[str, np.ndarray]] = None,
        want_arrays: bool = False,
    ):
        """Run one op through the self-healing loop (module docstring)."""
        # Distributed tracing (additive): stamp the op with the fixed
        # ctor context or the calling thread's current journal frame.
        # Stamped ONCE per op, outside the retry loop, so a replayed
        # request carries the same ctx as its first attempt.
        tc = self._trace_ctx or journal.trace_ctx()
        if tc:
            req = {**req, "trace_ctx": tc}
        start = time.monotonic()
        deadline = None if self._op_deadline is None else start + self._op_deadline
        attempt = 0
        busy_waited = 0.0
        delay = self._backoff_base
        while True:
            sent = {"flag": False}
            try:
                return self._attempt(req, payload, arrays, want_arrays,
                                     deadline=deadline, sent=sent)
            except protocol.FrameTooLarge:
                # Sender-side MAX_FRAME rejection: deterministic — the
                # payload will never fit, replaying cannot help. The JSON
                # header already went out though, so the connection is
                # mid-request: drop it (retry obligation #1) so the next
                # op doesn't have its header eaten as this op's payload.
                self._reset()
                raise
            except DaemonBusy as e:
                # Only the LOAD said no — but holding our connection open
                # through the wait would keep a connection-count watermark
                # pinned above its threshold forever (every shed client
                # parked, none draining). Release the slot, wait the hint
                # with jitter, reconnect on retry.
                self._reset()
                wait = e.retry_after_s * (0.5 + self._rng.random())
                now = time.monotonic()
                if deadline is not None and now + wait > deadline:
                    _M_DEADLINE_EXPIRIES.inc(op=str(req.get("op")))
                    raise
                if (
                    deadline is None or self._busy_wait_explicit
                ) and busy_waited + wait > self._max_busy_wait:
                    # The cap binds when it is the only bound (no
                    # deadline) or the caller set it EXPLICITLY — a
                    # fleet-routed client passes 0 so a shed surfaces
                    # immediately and the ROUTER retries elsewhere,
                    # deadline notwithstanding (serve/router.py). A
                    # default-cap client with a 300 s deadline keeps its
                    # full budget.
                    raise
                self.stats["busy_waits"] += 1
                busy_waited += wait
                _M_BUSY_WAITS.inc(op=str(req.get("op")))
                _M_BUSY_WAIT_SECONDS.inc(wait)
                logger.info(
                    "daemon busy (%s); retrying op %r in %.2fs",
                    self._addr, req.get("op"), wait,
                )
                time.sleep(wait)
            except (protocol.ProtocolError, OSError) as e:
                # Includes ConnectionError and socket timeouts. The cached
                # socket may be mid-frame — always drop it, even on the
                # final raise, so the NEXT op reconnects cleanly.
                self._reset()
                if isinstance(e, (faults.InjectedDrop, faults.InjectedRefusal)):
                    # Chaos accounting: the very faults test_chaos injects
                    # must be countable (the acceptance check that healing
                    # telemetry is real, not decorative).
                    _M_FAULT_TRIPS.inc(op=str(req.get("op")))
                attempt += 1
                if attempt >= self._max_attempts:
                    raise
                delay = decorrelated_jitter(
                    delay, self._backoff_base, self._backoff_max, self._rng
                )
                if deadline is not None and time.monotonic() + delay > deadline:
                    _M_DEADLINE_EXPIRIES.inc(op=str(req.get("op")))
                    raise
                self.stats["reconnects"] += 1
                _M_RECONNECTS.inc(op=str(req.get("op")))
                _M_BACKOFF_SECONDS.inc(delay)
                if sent["flag"]:
                    # Only a request that may have reached the wire is a
                    # REPLAY; a failed connect or pre-send fault is just a
                    # reconnect.
                    self.stats["replays"] += 1
                    _M_REPLAYS.inc(op=str(req.get("op")))
                logger.warning(
                    "connection failure on op %r to %s (attempt %d/%d, "
                    "reconnect in %.2fs): %s",
                    req.get("op"), self._addr, attempt, self._max_attempts,
                    delay, e,
                )
                time.sleep(delay)

    def _roundtrip(self, req: Dict[str, Any], payload: Optional[bytes] = None):
        resp, _ = self._op(req, payload=payload)
        return resp, self._sock

    # -- ops ---------------------------------------------------------------

    def ping(self) -> bool:
        """Hello: liveness + version handshake. ``ping`` is the one
        version-exempt op; the server echoes the protocol version it
        speaks, and a mismatch raises here rather than on the first real
        op (docs/protocol.md)."""
        resp, _ = self._roundtrip({"op": "ping"})
        server_v = resp.get("v")
        if server_v is not None and server_v != protocol.PROTOCOL_VERSION:
            raise protocol.ProtocolError(
                f"daemon speaks protocol v{server_v}; this client speaks "
                f"v{protocol.PROTOCOL_VERSION}"
            )
        return bool(resp["ok"])

    def health(self) -> Dict[str, Any]:
        """Daemon health snapshot (additive op): ``queue_depth`` (active
        connections), ``staged_bytes`` (uncommitted stage memory),
        ``active_jobs``, ``served_models``, ``uptime_s``, and ``busy``
        (True when the daemon is over a backpressure watermark and is
        shedding heavy ops; ``retry_after_s`` carries its hint)."""
        resp, _ = self._roundtrip({"op": "health"})
        return {k: v for k, v in resp.items() if k != "ok"}

    def gossip_push(self, view: Dict[str, Any]) -> Dict[str, Any]:
        """Anti-entropy exchange (additive op): push a FleetView wire
        dict (serve/gossip.py ``to_wire()``); the ack carries the
        daemon's own ``view`` back — push-pull in one round trip — plus
        ``merged`` (records the daemon adopted) and its identity."""
        resp, _ = self._roundtrip({"op": "gossip_push", "view": view})
        return {k: v for k, v in resp.items() if k != "ok"}

    def gossip_pull(self) -> Dict[str, Any]:
        """The daemon's gossiped FleetView wire dict (additive op):
        what a client bootstraps its routing table from given ONE seed
        address (docs/protocol.md "Fleet gossip & bootstrap")."""
        resp, _ = self._roundtrip({"op": "gossip_pull"})
        view = resp.get("view")
        return view if isinstance(view, dict) else {}

    def metrics(self, format: str = "json"):
        """Daemon metrics (additive op): the daemon process's registry
        snapshot — per-op request counts + latency histograms (cumulative
        buckets), rx/tx byte counters, busy sheds, replay hits, phase
        durations (docs/observability.md). ``format="json"`` (default)
        returns the snapshot dict; ``"prometheus"`` returns the text
        exposition (v0.0.4) string."""
        resp, _ = self._roundtrip({"op": "metrics", "format": format})
        if format == "prometheus":
            return str(resp.get("text", ""))
        return resp.get("metrics", {})

    def telemetry_pull(self) -> Dict[str, Any]:
        """One-shot wire-native telemetry export (additive op,
        docs/protocol.md "Telemetry plane ops"): ``text`` (OpenMetrics
        exposition WITH per-bucket exemplars), ``metrics`` (the JSON
        registry snapshot), ``xprof`` (jit-ledger summary),
        ``fingerprint`` (config fingerprint — differing fingerprints
        across a fleet mean differing effective configs), plus identity
        and ``uptime_s``. Cursor-free: every pull is the full current
        state."""
        resp, _ = self._roundtrip({"op": "telemetry_pull"})
        return {k: v for k, v in resp.items() if k != "ok"}

    def trace_pull(self, cursor: int = 0) -> Dict[str, Any]:
        """Journal events from the daemon's in-memory ring with ``seq``
        greater than ``cursor`` (additive op): ``{"events": […],
        "seq": N, "id": …, "boot_id": …}``. Store the returned ``seq``
        as the next call's cursor to stream without duplication; reset
        the cursor to 0 when ``boot_id`` changes (seq is per-boot). The
        ring is bounded — events older than the buffer are gone."""
        resp, _ = self._roundtrip({"op": "trace_pull", "cursor": int(cursor)})
        return {k: v for k, v in resp.items() if k != "ok"}

    def server_id(self) -> Optional[str]:
        """The daemon's self-reported instance id (from ping). Address
        strings alias (localhost vs 127.0.0.1 vs FQDN); this id is how
        callers decide whether two addresses are the same daemon. None
        when talking to a pre-id daemon."""
        resp, _ = self._roundtrip({"op": "ping"})
        sid = resp.get("id")
        return None if sid is None else str(sid)

    def server_info(self) -> Dict[str, Any]:
        """Full ping identity: ``{"v", "id", "boot_id"}``. ``id`` is the
        daemon's durable identity (stable across restarts on a
        state_dir daemon); ``boot_id`` is the incarnation, fresh every
        start — two boot_ids under one id IS a restart."""
        resp, _ = self._roundtrip({"op": "ping"})
        return {k: v for k, v in resp.items() if k != "ok"}

    @staticmethod
    def _to_ipc(data, input_col: str, label_col: str) -> bytes:
        import pyarrow as pa

        from spark_rapids_ml_tpu.bridge.arrow import matrix_to_list_column

        if isinstance(data, tuple):
            x, y = data
            table = pa.table(
                {
                    input_col: matrix_to_list_column(np.asarray(x)),
                    label_col: pa.array(np.asarray(y).reshape(-1)),
                }
            )
        elif isinstance(data, np.ndarray):
            table = pa.table({input_col: matrix_to_list_column(data)})
        elif isinstance(data, pa.RecordBatch):
            table = pa.Table.from_batches([data])
        else:
            table = data
        sink = pa.BufferOutputStream()
        with pa.ipc.new_stream(sink, table.schema) as writer:
            writer.write_table(table)
        return sink.getvalue().to_pybytes()

    def feed(
        self,
        job: str,
        data,
        algo: str = "pca",
        input_col: str = "features",
        label_col: str = "label",
        n_cols: Optional[int] = None,
        params: Optional[Dict[str, Any]] = None,
        partition: Optional[int] = None,
        attempt: int = 0,
        pass_id: Optional[int] = None,
    ) -> int:
        """Feed one batch. ``data``: an Arrow Table/RecordBatch, or an
        (n, d) ndarray (optionally a (x, y) tuple for linreg/logreg).
        ``params`` configures job creation on the first feed (kmeans needs
        {"k": ...}). With ``partition`` set, the batch goes to that
        partition's staged state and only counts after :meth:`commit` —
        the exactly-once path for Spark tasks (retries restart the stage,
        duplicates of committed partitions are discarded). ``pass_id``
        fences iterative feeds to the job's current pass. Returns the
        job's total committed rows."""
        resp, _ = self._roundtrip(
            {
                "op": "feed",
                "job": job,
                "algo": algo,
                "input_col": input_col,
                "label_col": label_col,
                "n_cols": n_cols,
                "params": params or {},
                "partition": partition,
                "attempt": attempt,
                "pass_id": pass_id,
                # Replay dedupe: a reconnect replays this exact feed; the
                # daemon folds a given feed_id at most once per stage.
                "feed_id": self._op_id(),
            },
            payload=self._to_ipc(data, input_col, label_col),
        )
        return int(resp["rows"])

    def feed_raw(
        self,
        job: str,
        x: np.ndarray,
        y: Optional[np.ndarray] = None,
        algo: str = "pca",
        n_cols: Optional[int] = None,
        params: Optional[Dict[str, Any]] = None,
        partition: Optional[int] = None,
        attempt: int = 0,
        pass_id: Optional[int] = None,
    ) -> int:
        """:meth:`feed` semantics with a dependency-free payload: raw
        little-endian buffers instead of Arrow IPC — the op that makes a
        from-scratch client (no Arrow library) ~100 lines in any
        language (docs/protocol.md; examples/cpp_client)."""
        arrays: Dict[str, np.ndarray] = {"x": np.asarray(x)}
        if y is not None:
            arrays["y"] = np.asarray(y).reshape(-1)
        resp = self._send_arrays_op(
            {
                "op": "feed_raw",
                "job": job,
                "algo": algo,
                "n_cols": n_cols,
                "params": params or {},
                "partition": partition,
                "attempt": attempt,
                "pass_id": pass_id,
                "feed_id": self._op_id(),
            },
            arrays,
        )
        return int(resp["rows"])

    def commit(
        self, job: str, partition: int, attempt: int = 0,
        pass_id: Optional[int] = None,
    ) -> int:
        """Commit a partition's staged feeds into the job state
        (idempotent; see :meth:`feed`). Returns total committed rows."""
        resp, _ = self._roundtrip(
            {
                "op": "commit",
                "job": job,
                "partition": partition,
                "attempt": attempt,
                "pass_id": pass_id,
            }
        )
        return int(resp["rows"])

    def seed_kmeans(
        self,
        job: str,
        data,
        k: int,
        input_col: str = "features",
        n_cols: Optional[int] = None,
        params: Optional[Dict[str, Any]] = None,
    ) -> None:
        """Deterministically seed a kmeans job's centers from a
        driver-chosen batch of ≥ k rows (rows are NOT folded — they arrive
        through the partition scan). Idempotent across retries."""
        self._roundtrip(
            {
                "op": "seed",
                "job": job,
                "input_col": input_col,
                "n_cols": n_cols,
                "params": {**(params or {}), "k": k},
            },
            payload=self._to_ipc(data, input_col, "label"),
        )

    def step(self, job: str, params: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        """Pass boundary for iterative jobs (kmeans/logreg): apply the
        Lloyd/Newton update over the pass's accumulated statistics and
        return convergence info ({"iteration", "moved2"|"delta", ...}).
        Carries a ``step_id`` so a replay whose first ack was lost gets
        the cached result of the step it already applied instead of
        double-advancing the iterate."""
        resp, _ = self._roundtrip(
            {"op": "step", "job": job, "params": params or {},
             "step_id": self._op_id()}
        )
        return {k: v for k, v in resp.items() if k != "ok"}

    def status(self, job: str) -> Dict[str, Any]:
        resp, _ = self._roundtrip({"op": "status", "job": job})
        return resp

    def drop(self, job: str) -> bool:
        resp, _ = self._roundtrip({"op": "drop", "job": job})
        return bool(resp["dropped"])

    def finalize(
        self, job: str, params: Dict[str, Any], drop: bool = True,
        arrays: Optional[Dict[str, np.ndarray]] = None,
        with_meta: bool = False,
    ):
        """Finalize a job; returns (result arrays, total rows) — or, with
        ``with_meta=True``, (arrays, rows, meta) where ``meta`` carries
        the response's additive fields (``pass_rows``, ``boot_id``: the
        crash-recovery reconciliation inputs, docs/protocol.md).
        ``arrays`` (optional, additive to protocol v1) sends raw array
        frames with the request — the sharded KNN build ships the shared
        quantizer this way.

        Replay-safe split (retry obligation #4): the wire request always
        carries ``drop: false`` so a reconnect replay after a lost
        response re-reads the same model instead of hitting ``no such
        job``; ``drop=True`` then issues the explicit idempotent ``drop``
        op once the arrays are safely in hand. (KNN finalizes consume the
        job either way — their response loss still needs a refit.)"""
        req = {"op": "finalize", "job": job, "params": params, "drop": False}
        resp, outs = self._op(req, arrays=arrays or None, want_arrays=True)
        if drop:
            self.drop(job)
        if with_meta:
            meta = {
                k: v for k, v in resp.items() if k not in ("ok", "arrays")
            }
            return outs, int(resp["rows"]), meta
        return outs, int(resp["rows"])

    # -- cross-daemon merge (multi-host data plane) -------------------------

    def _send_arrays_op(self, req: Dict[str, Any], arrays: Dict[str, np.ndarray]):
        """Request carrying raw array frames (ensure_model framing)."""
        resp, _ = self._op(req, arrays=arrays)
        return resp

    def export_state(self, job: str) -> Tuple[Dict[str, np.ndarray], Dict[str, Any]]:
        """Snapshot a job's committed O(d²) partials for a cross-daemon
        merge. Returns (state arrays keyed s0..sN in jax tree order,
        meta with rows/pass_rows/iteration/algo/n_cols). Read-only."""
        resp, arrays = self._op({"op": "export_state", "job": job},
                                want_arrays=True)
        meta = {k: v for k, v in resp.items() if k not in ("ok", "arrays")}
        return arrays, meta

    def merge_state(
        self,
        job: str,
        arrays: Dict[str, np.ndarray],
        rows: int,
        algo: str = "pca",
        n_cols: Optional[int] = None,
        params: Optional[Dict[str, Any]] = None,
    ) -> int:
        """Fold a peer daemon's exported state into ``job`` (creating it
        when absent — ``algo``/``n_cols``/``params`` mirror a first feed).
        ``rows`` is the exporter's committed contribution; returns the
        job's new total."""
        resp = self._send_arrays_op(
            {
                "op": "merge_state",
                "job": job,
                "algo": algo,
                "n_cols": n_cols,
                "params": params or {},
                "rows": int(rows),
                # Replay dedupe: merges fold immediately; a reconnect
                # replay with the same id must not double-apply partials.
                "merge_id": self._op_id(),
            },
            arrays,
        )
        return int(resp["rows"])

    def mesh_info(self) -> Dict[str, Any]:
        """Mesh membership snapshot (additive op; docs/mesh.md): the
        daemons co-resident on the server's device plane — ``epoch``
        (fencing counter: bumps on every join/leave/reboot), ``members``
        (``[{"id", "boot_id"}]``) and ``n_devices``. Drivers read this
        per pass to decide the collective reduce vs the export/merge
        hub, and stamp the epoch on :meth:`reduce_mesh`."""
        resp, _ = self._roundtrip({"op": "mesh_info"})
        return {k: v for k, v in resp.items() if k != "ok"}

    def reduce_mesh(
        self,
        job: str,
        *,
        epoch: int,
        peers: Dict[str, Dict[str, Any]],
        algo: str = "pca",
        params: Optional[Dict[str, Any]] = None,
        drop_peers: bool = False,
    ) -> Dict[str, Any]:
        """On-mesh collective reduce (additive op; docs/protocol.md
        "reduce_mesh"): fold every named co-resident peer's committed
        pass partials into ``job`` on the device plane — O(d²) arrays
        never cross the wire. ``peers``: ``{peer_id: {"boot_id",
        "rows", "partitions"}}`` — the driver's task-ack accounting the
        daemon re-validates against live job state before anything
        folds (the pre-reduce (boot_id, pass_rows) handshake). The
        ``epoch`` must be the one :meth:`mesh_info` reported;
        membership changes in between refuse the reduce. A retried
        request replays safely (``reduce_id`` dedupe, like
        merge_state's ``merge_id``)."""
        resp, _ = self._op({
            "op": "reduce_mesh",
            "job": job,
            "epoch": int(epoch),
            "peers": peers,
            "algo": algo,
            "params": params or {},
            "drop_peers": bool(drop_peers),
            "reduce_id": self._op_id(),
        })
        return resp

    def sample_rows(self, job: str, n: int, seed: int = 0) -> np.ndarray:
        """Seeded uniform sample of a knn job's committed rows (additive
        op; read-only). The cross-daemon quantizer-training primitive:
        the driver samples every daemon's shard in proportion to its
        rows and hands the union to the quantizer-owning IVF build, so
        shared centroids cover the whole dataset (ADVICE r5(b))."""
        _, arrays = self._op(
            {"op": "sample_rows", "job": job, "n": int(n), "seed": int(seed)},
            want_arrays=True,
        )
        return arrays["rows"]

    def get_iterate(self, job: str) -> Tuple[Dict[str, np.ndarray], int]:
        """(iterate arrays, iteration) of an iterative job — kmeans
        {"centers"}; logreg {"w", "b"}."""
        resp, arrays = self._op({"op": "get_iterate", "job": job},
                                want_arrays=True)
        return arrays, int(resp["iteration"])

    def set_iterate(
        self, job: str, arrays: Dict[str, np.ndarray], iteration: int,
        algo: Optional[str] = None, n_cols: Optional[int] = None,
        params: Optional[Dict[str, Any]] = None,
    ) -> None:
        """Install a driver-pushed iterate on a daemon's job and open
        pass ``iteration`` (resets the pass statistics and staging).
        With ``n_cols`` (plus ``algo``/``params``, mirroring a first
        feed) the job is CREATED when the daemon does not know it — the
        recovery path that re-seeds a restarted daemon from the driver's
        ledger (docs/protocol.md "Crash recovery")."""
        req: Dict[str, Any] = {
            "op": "set_iterate", "job": job, "iteration": int(iteration),
        }
        if n_cols is None and (algo is not None or params is not None):
            # The caller asked for recreation context without the width:
            # derive it from the iterate itself (centers are (k, d);
            # coefficients are (d,) or (d, C)) rather than silently
            # sending a request the daemon can only answer with
            # "no such job".
            a = arrays.get("centers")
            if a is not None:
                n_cols = int(np.asarray(a).shape[1])
            elif arrays.get("bin_edges") is not None:
                # Forest iterate: edges are (n_cols, max_bins - 1).
                n_cols = int(np.asarray(arrays["bin_edges"]).shape[0])
            elif arrays.get("w") is not None:
                n_cols = int(np.asarray(arrays["w"]).shape[0])
        if n_cols is not None:
            req["algo"] = algo or "pca"
            req["n_cols"] = int(n_cols)
            req["params"] = params or {}
        self._send_arrays_op(req, arrays)

    # -- model serving (daemon-side transform) -----------------------------

    def ensure_model(
        self,
        name: str,
        algo: str,
        arrays: Dict[str, np.ndarray],
        params: Optional[Dict[str, Any]] = None,
        version: Optional[int] = None,
    ) -> bool:
        """Register a fitted model for serving (idempotent; first caller
        wins). ``arrays`` is the model's ``_model_data()`` payload; raw
        array frames follow the JSON header, mirroring the finalize
        response framing. ``version`` (additive) pins the registration
        to a fleet model version — immutable under the name; serving
        requests carrying a different ``version`` are refused
        (docs/protocol.md "Fleet & versioned serving"). Returns True
        when this call created it."""
        resp = self._send_arrays_op(
            {"op": "ensure_model", "model": name, "algo": algo,
             "params": params or {}, "version": version},
            arrays,
        )
        return bool(resp["created"])

    def model_exists(self, name: str) -> bool:
        resp, _ = self._roundtrip({"op": "model_status", "model": name})
        return bool(resp["exists"])

    def transform(
        self,
        name: str,
        data,
        input_col: str = "features",
        n_cols: Optional[int] = None,
        deadline_s: Optional[float] = None,
        version: Optional[int] = None,
        fleet_epoch: Optional[int] = None,
        with_meta: bool = False,
    ) -> Dict[str, np.ndarray]:
        """Run a registered model over one batch on the daemon's devices.
        ``data``: Arrow Table/RecordBatch or (n, d) ndarray. Returns the
        role-keyed output arrays (the model's ``_serve_outputs`` roles,
        e.g. {"output": ...} for PCA, {"prediction": ...} for KMeans).
        ``deadline_s`` (additive): the request's latency budget hint —
        a batching daemon sheds it with `busy` when its backlog would
        already miss it (docs/protocol.md "Serving scheduler").
        ``version``/``fleet_epoch`` (additive): the fleet routing pin —
        a versioned replica REFUSES a mismatched ``version`` instead of
        answering from the wrong model, and echoes both fields on the
        ack (docs/protocol.md "Fleet & versioned serving"). With
        ``with_meta`` the return is ``(arrays, meta)`` where ``meta``
        carries the ack's additive fields (``version``, ``fleet_epoch``)."""
        resp, arrays = self._op(
            {
                "op": "transform",
                "model": name,
                "input_col": input_col,
                "n_cols": n_cols,
                "deadline_s": deadline_s,
                "version": version,
                "fleet_epoch": fleet_epoch,
            },
            payload=self._to_ipc(data, input_col, "label"),
            want_arrays=True,
        )
        if with_meta:
            meta = {k: v for k, v in resp.items() if k not in ("ok", "arrays")}
            return arrays, meta
        return arrays

    def warmup(
        self,
        name: str,
        n_cols: int,
        k: Optional[int] = None,
        dtype: str = "float32",
        kind: Optional[str] = None,
    ) -> Dict[str, Any]:
        """Pre-compile the serving scheduler's bucket ladder for a
        registered model (additive op): after a warmup, first-request
        latency is a dispatch, not a jit compile, and the recompile
        counters are primed for the whole ladder. ``dtype`` must match
        the dtype real query batches will carry (jit caches are
        dtype-keyed); ``kind`` defaults daemon-side to ``kneighbors``
        for KNN/ANN models and ``transform`` otherwise. On a daemon
        without batching enabled this is an honest no-op — the response
        carries ``enabled: false``."""
        resp, _ = self._roundtrip(
            {
                "op": "warmup",
                "model": name,
                "n_cols": int(n_cols),
                "k": k,
                "dtype": dtype,
                "kind": kind,
            }
        )
        return {kk: v for kk, v in resp.items() if kk != "ok"}

    def drop_model(self, name: str) -> bool:
        resp, _ = self._roundtrip({"op": "drop_model", "model": name})
        return bool(resp["dropped"])

    def finalize_knn(
        self,
        job: str,
        register_as: str,
        mode: str = "exact",
        nlist: Optional[int] = None,
        nprobe: Optional[int] = None,
        seed: int = 0,
        metric: str = "euclidean",
        row_id_base: Optional[Dict[Any, int]] = None,
        centroids: Optional[np.ndarray] = None,
        return_centroids: bool = False,
        train_rows_sample: Optional[np.ndarray] = None,
    ) -> Dict[str, np.ndarray]:
        """Build the index from a knn job's accumulated rows ON the daemon
        and register it as ``register_as`` for :meth:`kneighbors` serving.
        Returns only O(1) stats ({"n_rows", "n_cols"[, "nlist",
        "maxlen"]}) — the index itself never crosses the wire.

        Sharded (cross-daemon) builds: ``row_id_base`` maps each partition
        this daemon committed to its global row base (served ids become
        global partition-major positions); ``centroids`` ships a shared
        pretrained quantizer; ``return_centroids`` asks the build to hand
        its trained quantizer back (the driver forwards it to the peers);
        ``train_rows_sample`` ships an explicit quantizer training set
        (the driver's cross-shard ``sample_rows`` union — ADVICE r5(b)).
        """
        params: Dict[str, Any] = {
            "mode": mode, "register_as": register_as, "seed": seed,
            "metric": metric,
        }
        if nlist is not None:
            params["nlist"] = nlist
        if nprobe is not None:
            params["nprobe"] = nprobe
        if row_id_base is not None:
            params["row_id_base"] = {str(p): int(b) for p, b in row_id_base.items()}
        if return_centroids:
            params["return_centroids"] = True
        extra: Dict[str, np.ndarray] = {}
        if centroids is not None:
            extra["centroids"] = np.asarray(centroids, np.float32)
        if train_rows_sample is not None:
            extra["train_rows"] = np.asarray(train_rows_sample)
        arrays, _ = self.finalize(job, params, arrays=extra or None)
        return arrays

    def kneighbors(
        self,
        model: str,
        queries,
        k: Optional[int] = None,
        input_col: str = "features",
        n_cols: Optional[int] = None,
        deadline_s: Optional[float] = None,
        version: Optional[int] = None,
        fleet_epoch: Optional[int] = None,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Query a daemon-registered index: returns (distances (q, k),
        indices (q, k)) with global partition-major row ids.
        ``deadline_s``: latency-budget hint; ``version``/``fleet_epoch``:
        the fleet routing pin — see :meth:`transform`."""
        _, arrays = self._op(
            {
                "op": "kneighbors",
                "model": model,
                "k": k,
                "input_col": input_col,
                "n_cols": n_cols,
                "deadline_s": deadline_s,
                "version": version,
                "fleet_epoch": fleet_epoch,
            },
            payload=self._to_ipc(queries, input_col, "label"),
            want_arrays=True,
        )
        return arrays["distances"], arrays["indices"]

    # -- conveniences ------------------------------------------------------

    def finalize_pca(
        self,
        job: str,
        k: int,
        mean_center: bool = True,
        solver: Optional[str] = None,
    ) -> Dict[str, np.ndarray]:
        arrays, _ = self.finalize(
            job, {"k": k, "mean_center": mean_center, "solver": solver}
        )
        return arrays

    def finalize_linreg(self, job: str, **params) -> Dict[str, np.ndarray]:
        arrays, _ = self.finalize(job, params)
        return arrays

    def finalize_kmeans(self, job: str) -> Dict[str, np.ndarray]:
        """Model after the last ``step``: {"centers", "cost", "n_iter"}.
        ``cost`` is the (unstepped) current pass's accumulated inertia —
        feed one extra pass without stepping to read the final cost."""
        arrays, _ = self.finalize(job, {})
        return arrays

    def finalize_logreg(self, job: str) -> Dict[str, np.ndarray]:
        """Model after the last ``step``: {"coefficients", "intercept", "n_iter"}."""
        arrays, _ = self.finalize(job, {})
        return arrays
