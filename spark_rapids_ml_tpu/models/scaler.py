"""StandardScaler — feature standardization, Spark ML semantics.

The reference leaves mean-centering to "an ETL preprocess upstream"
(RapidsRowMatrix.scala:111-117, the stubbed ``meanCentering`` branch —
SURVEY.md §2.4); this estimator IS that preprocess, done properly on
device: one sharded pass accumulates count/Σx/Σx² with a ``psum`` over
ICI, the model then standardizes batches with a fused elementwise kernel
(XLA fuses (x − μ)·s into the surrounding graph).

Spark parity (``org.apache.spark.ml.feature.StandardScaler``):
``withStd`` defaults true, ``withMean`` defaults false (dense-shift
safety), std is the UNBIASED sample standard deviation (ddof=1), and
zero-variance features scale by 0 exactly like MLlib's
``StandardScalerModel`` (their transformed value is 0/constant, never
NaN).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from spark_rapids_ml_tpu import config
from spark_rapids_ml_tpu.core.dataset import as_matrix, with_column
from spark_rapids_ml_tpu.core.params import (
    Estimator,
    HasInputCol,
    HasOutputCol,
    Model,
    ParamDecl,
    TypeConverters,
)
from spark_rapids_ml_tpu.core.persistence import MLReadable, MLWritable
from spark_rapids_ml_tpu.parallel.mesh import DATA_AXIS, default_mesh
from spark_rapids_ml_tpu.parallel import mapreduce as mr
from spark_rapids_ml_tpu.parallel.sharding import shard_rows
from spark_rapids_ml_tpu.utils.profiling import trace_span
from spark_rapids_ml_tpu.parallel.compat import shard_map
from spark_rapids_ml_tpu.utils.xprof import ledgered_jit


@functools.lru_cache(maxsize=32)
def _moments_fn(mesh: Mesh, ad: str):
    accum = jnp.dtype(ad)

    def shard(x, mask):
        from spark_rapids_ml_tpu.ops.gram import mm_precision

        with mm_precision(accum):
            xc = x.astype(accum) * mask.astype(accum)[:, None]
            n = mr.reduce_sum(
                jnp.sum(mask.astype(jnp.int32)).astype(accum), DATA_AXIS
            )
            s1 = mr.reduce_sum(jnp.sum(xc, axis=0), DATA_AXIS)
            s2 = mr.reduce_sum(jnp.sum(jnp.square(xc), axis=0), DATA_AXIS)
            return n, s1, s2

    f = shard_map(
        shard,
        mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS)),
        out_specs=(P(), P(), P()),
    )
    return ledgered_jit("scaler.stats", f)


class _ScalerParams(HasInputCol, HasOutputCol):
    withMean = ParamDecl(
        "withMean", "center features to zero mean", TypeConverters.toBoolean
    )
    withStd = ParamDecl(
        "withStd", "scale features to unit standard deviation", TypeConverters.toBoolean
    )

    def __init__(self, uid=None):
        super().__init__(uid=uid)
        self.setDefault(
            withMean=False, withStd=True, inputCol="features",
            outputCol="scaled_features",
        )

    def getWithMean(self) -> bool:
        return self.getOrDefault(self.withMean)

    def getWithStd(self) -> bool:
        return self.getOrDefault(self.withStd)


class StandardScaler(Estimator, _ScalerParams, MLWritable, MLReadable):
    """fit() computes per-feature mean/std in one sharded device pass."""

    _uid_prefix = "StandardScaler"

    def __init__(self, uid=None, mesh: Optional[Mesh] = None):
        super().__init__(uid=uid)
        self._mesh = mesh

    def setWithMean(self, value: bool) -> "StandardScaler":
        return self._set(withMean=value)

    def setWithStd(self, value: bool) -> "StandardScaler":
        return self._set(withStd=value)

    def _copy_extra_state(self, source):
        self._mesh = getattr(source, "_mesh", None)

    def _fit(self, dataset) -> "StandardScalerModel":
        x = as_matrix(dataset, self.getInputCol())
        mesh = self._mesh or default_mesh()
        with trace_span("scaler fit"):
            xs, mask, n_true = shard_rows(np.asarray(x, np.float32), mesh)
            n, s1, s2 = jax.device_get(
                _moments_fn(mesh, config.get("accum_dtype"))(xs, mask)
            )
        n = float(n)
        mean = np.asarray(s1, np.float64) / n
        # Unbiased sample variance, numerically floored at 0 (the
        # Σx² − n·μ² form can go -eps for constant features).
        var = (np.asarray(s2, np.float64) - n * mean * mean) / max(n - 1.0, 1.0)
        std = np.sqrt(np.maximum(var, 0.0))
        model = StandardScalerModel(mean=mean, std=std)
        model.uid = self.uid
        self._copy_params_to(model)
        return model


class StandardScalerModel(Model, _ScalerParams, MLWritable, MLReadable):
    _uid_prefix = "StandardScalerModel"

    def __init__(self, mean: Optional[np.ndarray] = None,
                 std: Optional[np.ndarray] = None, uid=None):
        super().__init__(uid=uid)
        self.mean = None if mean is None else np.asarray(mean, np.float64)
        self.std = None if std is None else np.asarray(std, np.float64)

    def _model_data(self):
        return {"mean": self.mean, "std": self.std}

    @classmethod
    def _from_model_data(cls, uid, data):
        return cls(mean=data["mean"], std=data["std"], uid=uid)

    def _copy_extra_state(self, source):
        self.mean = source.mean
        self.std = source.std

    # Daemon serving contract (serve/daemon.py). withMean/withStd ride the
    # registration params so the served copy scales identically — they are
    # the only params that change the served output (_serve_params).
    _serve_algo = "scaler"
    _serve_outputs = (("output", "outputCol", "vec"),)
    _serve_params = ("withMean", "withStd")

    def _serve_aot_plan(self, n_rows, n_cols, dtype="float32", k=None):
        """AOT-at-registration plan (serve/daemon.py): the scaler is host
        elementwise — nothing compiles, so the plan is trivially
        complete (an empty list, not None: AOT "succeeds" with zero
        executables rather than degrading to trace warmup). A wrong
        ``n_cols`` still raises — the ack must not bless a width the
        transform will reject."""
        if self.mean is not None:
            d = int(np.asarray(self.mean).shape[0])
            if int(n_cols) != d:
                raise ValueError(
                    f"warmup n_cols={int(n_cols)} does not match the "
                    f"model's fitted width {d}"
                )
        return []

    def transform_matrix(self, x: np.ndarray) -> dict:
        """Role-keyed transform of a bare matrix (host elementwise — the
        op is bandwidth-trivial relative to any model GEMM)."""
        with trace_span("scaler transform"):
            x = np.asarray(x).astype(np.float64)
            if self.getWithMean():
                x = x - self.mean[None, :]
            if self.getWithStd():
                # MLlib convention: zero-variance features multiply by 0.
                inv = np.where(self.std > 0, 1.0 / np.where(self.std > 0, self.std, 1.0), 0.0)
                x = x * inv[None, :]
            return {"output": x.astype(np.float32)}

    def _transform(self, dataset):
        x = as_matrix(dataset, self.getInputCol())
        return with_column(
            dataset, self.getOutputCol(), self.transform_matrix(x)["output"]
        )
