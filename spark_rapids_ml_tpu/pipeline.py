"""Pipeline — chained estimators/transformers, Spark ML semantics.

Mirrors ``org.apache.spark.ml.Pipeline``: ``fit`` walks the stages in
order, fitting each Estimator on the dataset as transformed by everything
before it (and transforming through the fitted model so later stages see
its output); Models/transformers pass through. The result is a
``PipelineModel`` whose ``transform`` applies every fitted stage in order.

The reference exposes a single drop-in estimator precisely so it can slot
into Spark's own Pipeline machinery (README.md:27-37); since this
framework replaces that machinery host-side, it carries the Pipeline
contract itself.
"""

from __future__ import annotations

import os
from typing import List, Optional

from spark_rapids_ml_tpu.core.params import Estimator, Model, Params
from spark_rapids_ml_tpu.core.persistence import (
    DefaultParamsReader,
    DefaultParamsWriter,
    MLReadable,
    MLWritable,
)


class _StagesMixin(Params):
    def __init__(self, uid=None):
        super().__init__(uid=uid)

    def _copy_extra_state(self, source):
        # Shallow share: copy() below always rebuilds the stage list (it is
        # the only caller path), so no throwaway per-stage copies here.
        self._stages = list(getattr(source, "_stages", []))

    def copy(self, extra=None):
        # Spark semantics: ``extra`` flows into the stage copies, so a
        # CrossValidator grid keyed on a stage's params tunes the stage
        # through the enclosing Pipeline(Model).
        that = super().copy(extra)
        that._stages = [s.copy(extra) for s in self._stages]
        return that

    def _save_stages(self, path: str, stages) -> None:
        if os.path.exists(path):
            raise FileExistsError(f"path {path} already exists")
        os.makedirs(path)
        DefaultParamsWriter.save_metadata(
            self, path, extra={"stageUids": [s.uid for s in stages]}
        )
        for i, stage in enumerate(stages):
            if not isinstance(stage, MLWritable):
                raise TypeError(f"stage {stage.uid} is not MLWritable")
            stage.save(os.path.join(path, "stages", f"{i}_{stage.uid}"))

    @staticmethod
    def _load_stages(path: str, meta) -> list:
        stages_dir = os.path.join(path, "stages")
        loaded = []
        for i, uid in enumerate(meta["stageUids"]):
            loaded.append(
                DefaultParamsReader.load_instance(
                    os.path.join(stages_dir, f"{i}_{uid}")
                )
            )
        return loaded


class Pipeline(Estimator, _StagesMixin, MLWritable, MLReadable):
    _uid_prefix = "Pipeline"

    def __init__(self, stages: Optional[List] = None, uid=None):
        super().__init__(uid=uid)
        self._stages = list(stages or [])

    def setStages(self, stages: List) -> "Pipeline":
        self._stages = list(stages)
        return self

    def getStages(self) -> List:
        return list(self._stages)

    def _fit(self, dataset) -> "PipelineModel":
        fitted = []
        current = dataset
        for i, stage in enumerate(self._stages):
            if isinstance(stage, Estimator):
                model = stage.fit(current)
            elif isinstance(stage, Model):
                model = stage
            else:
                raise TypeError(
                    f"stage {i} ({type(stage).__name__}) is neither an "
                    f"Estimator nor a Model/transformer"
                )
            fitted.append(model)
            if i < len(self._stages) - 1:  # the last output is never consumed
                current = model.transform(current)
        pm = PipelineModel(stages=fitted)
        pm.uid = self.uid
        return pm

    # -- persistence (stages are saved individually, like Spark) ----------
    def save(self, path: str) -> None:
        self._save_stages(path, self._stages)

    @classmethod
    def load(cls, path: str) -> "Pipeline":
        meta = DefaultParamsReader.load_metadata(path)
        obj = cls(stages=cls._load_stages(path, meta))
        obj.uid = meta["uid"]
        return obj


class PipelineModel(Model, _StagesMixin, MLWritable, MLReadable):
    _uid_prefix = "PipelineModel"

    def __init__(self, stages: Optional[List] = None, uid=None):
        super().__init__(uid=uid)
        self._stages = list(stages or [])

    @property
    def stages(self) -> List:
        return list(self._stages)

    def _transform(self, dataset):
        current = dataset
        for stage in self._stages:
            current = stage.transform(current)
        return current

    def save(self, path: str) -> None:
        self._save_stages(path, self._stages)

    @classmethod
    def load(cls, path: str) -> "PipelineModel":
        meta = DefaultParamsReader.load_metadata(path)
        obj = cls(stages=cls._load_stages(path, meta))
        obj.uid = meta["uid"]
        return obj
