"""Per-config benchmark suite for the BASELINE.json workloads.

``bench.py`` at the repo root is the recorded headline (PCA.fit streaming
throughput); the scripts here cover the remaining BASELINE.json configs —
PCA transform latency, KMeans, LinearRegression/LogisticRegression normal
equations, and IVF-Flat approximate KNN. Each prints ONE JSON line
``{"metric", "value", "unit", "vs_baseline"}``; shapes are scaled to a
single chip's HBM (the multi-chip story is sharding-tested in tests/ and
dry-run-compiled via __graft_entry__.dryrun_multichip) and every script has
``SRML_BENCH_*`` env knobs for smoke-testing on small hosts.

``vs_baseline`` denominators are analytic A100 estimates (GEMM-bound at
~110 TFLOP/s sustained TF32, the same convention as bench.py's module
docstring) — the reference repo publishes no numbers (BASELINE.md).
"""

import json
import os


def setup_platform() -> None:
    """Honor SRML_BENCH_PLATFORM=cpu for smoke runs.

    The TPU image's sitecustomize sets ``jax.config.jax_platforms``
    directly, which beats a ``JAX_PLATFORMS`` env var — only a config
    update before the first backend touch overrides it. Call this at the
    top of every bench ``main()``.
    """
    plat = os.environ.get("SRML_BENCH_PLATFORM")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)


def emit(metric: str, value: float, unit: str, vs_baseline: float, **extras) -> None:
    """Print the one-JSON-line bench contract; ``extras`` appends further
    keys (recall, component rates, flags) to the same line."""
    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(value, 4),
                "unit": unit,
                "vs_baseline": round(vs_baseline, 4),
                **extras,
            }
        )
    )


def slope_dt(run, n1: int, n2: int, warm: bool = True) -> float:
    """Seconds per work-unit via a two-point fit: time run(n1) and run(n2),
    return (t2-t1)/(n2-n1).

    Removes fixed per-measurement overhead from the reported rate — on the
    dev tunnel a single host↔device sync round-trip costs ~90 ms, which
    would otherwise swamp any single-call measurement. ``run(n)`` must
    execute n units and block until the device is done. Each size is timed
    twice and the min taken, so a single noisy sample can't invert the
    slope; pass warm=False when the caller has already compiled/warmed both
    sizes (e.g. repeated sampling in a loop).
    """
    import time

    if warm:
        run(n1)  # warm / compile both sizes
        run(n2)

    def timed(n):
        best = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            run(n)
            best = min(best, time.perf_counter() - t0)
        return best

    t1, t2 = timed(n1), timed(n2)
    if t2 <= t1:  # still inverted after min-of-2: fall back to the average
        return t2 / n2
    return (t2 - t1) / (n2 - n1)


def sync(x) -> None:
    """Block until device work producing x is done.

    ``jax.block_until_ready`` does not reliably wait on the dev tunnel's
    remote platform; fetching one element does.
    """
    import jax

    leaf = jax.tree.leaves(x)[-1]
    jax.device_get(leaf[(0,) * getattr(leaf, "ndim", 0)])
