"""Fast source-level lint gates (no imports, no hardware).

These are grep-shaped invariants that a reviewer would otherwise have to
re-check by hand on every PR. They run in milliseconds and fail with the
offending file:line.

Gates that outgrew regex have MIGRATED onto the AST analyzer
(spark_rapids_ml_tpu/tools/analyze.py — "srml-check", tests/test_analyze.py
covers the engine itself): the test names below are preserved as thin
invokers so coverage and CI history stay continuous. A migrated gate now
understands syntax (f-strings and concatenation can't dodge it) and
honors the analyzer's pragma/baseline suppression contract
(docs/static_analysis.md).
"""

import re
from pathlib import Path

PKG = Path(__file__).resolve().parent.parent / "spark_rapids_ml_tpu"


def _py_sources():
    return sorted(PKG.rglob("*.py"))


_PROJECT_CACHE = []


def _rule_clean(*rules: str) -> None:
    """Run srml-check rule(s) over the real package (with the checked-in
    pragma/baseline suppressions) and fail on any finding. The parsed
    Project is cached across invokers — rule runs are stateless, so the
    read+parse+registry work is paid once per pytest session."""
    from spark_rapids_ml_tpu.tools import analyze

    if not _PROJECT_CACHE:
        _PROJECT_CACHE.append(analyze.Project.from_package())
    project = _PROJECT_CACHE[0]
    findings = project.run(rules=list(rules), baseline=analyze.Baseline.load())
    assert findings == [], "\n" + analyze.format_findings(findings)


def test_every_create_connection_has_explicit_timeout():
    """MIGRATED to srml-check: a ``socket.create_connection`` without a
    timeout inherits the global default (None = block forever)."""
    _rule_clean("socket-timeout")


def test_fault_checkpoints_exist_at_contract_sites():
    """The chaos suite's FaultPlan rules target named sites; this pins
    the site names to the source so a refactor that silently drops a
    hook (turning chaos coverage into a no-op) fails loudly."""
    expect = {
        "serve/client.py": ["client.connect", "client.op"],
        "serve/daemon.py": ["daemon.conn", "daemon.op",
                            "daemon.pass_boundary", "daemon.vanish",
                            "daemon.join", "gossip.push"],
        "serve/scheduler.py": ["daemon.scheduler"],
        "serve/protocol.py": ["wire.send_frame"],
        "serve/autoscaler.py": ["autoscale.action"],
        "serve/router.py": ["fleet.bootstrap"],
        "serve/fleet.py": ["fleet.rollout"],
        "spark/estimator.py": ["daemon.join"],
        "bridge/arrow.py": ["bridge.to_matrix", "bridge.to_ipc"],
    }
    for rel, sites in expect.items():
        text = (PKG / rel).read_text()
        for site in sites:
            assert f'"{site}"' in text, (
                f"fault-injection site {site!r} missing from {rel} "
                "(utils/faults.py module docstring lists the contract)"
            )


def test_fault_sites_used_by_tests_exist_in_the_package():
    """The inverse of the gate above, closing its blind spot: a chaos
    test naming a site that NO ``faults.checkpoint(...)`` /
    ``faults.truncation(...)`` call instruments never fires — a renamed
    site silently turns the test into a no-op that proves nothing.
    Every dotted site string used in a test FaultPlan (``.rule("x.y",
    ...)``) or an env-spec string (``"x.y:kind"``) must exist as a
    literal site in the package. Dot-free sites (``"s"``) are
    unit-test-local fixtures of the faults framework itself and exempt."""
    known = set()
    for path in _py_sources():
        text = path.read_text()
        known.update(re.findall(
            r"faults\.checkpoint\(\s*[\"']([a-z_.]+)[\"']", text
        ))
        known.update(re.findall(
            r"faults\.truncation\(\s*[\"']([a-z_.]+)[\"']", text
        ))
    assert len(known) >= 8, (
        f"only {len(known)} instrumented fault sites found — the hook "
        "pattern or this regex regressed"
    )
    used = {}  # site -> first use location
    tests_dir = Path(__file__).resolve().parent
    rule_re = re.compile(r"\.rule\(\s*[\"']([a-z_]+(?:\.[a-z_]+)+)[\"']")
    spec_re = re.compile(
        r"[\"'][^\"']*?\b([a-z_]+(?:\.[a-z_]+)+)"
        r":(?:latency|drop|refuse|partial|crash)\b"
    )
    for path in sorted(tests_dir.glob("*.py")):
        if path.name == Path(__file__).name:
            continue
        text = path.read_text()
        for m in list(rule_re.finditer(text)) + list(spec_re.finditer(text)):
            used.setdefault(m.group(1), path.name)
    assert used, "no FaultPlan sites found in tests — the regex regressed"
    phantoms = sorted(
        f"{site} (first used in {where})"
        for site, where in used.items() if site not in known
    )
    assert phantoms == [], (
        "chaos tests target fault sites that are not instrumented "
        "anywhere in the package (the test is a silent no-op): "
        + ", ".join(phantoms)
    )


def test_model_fit_and_transform_hot_paths_are_spanned():
    """MIGRATED to srml-check (`hot-path-span`): every model hot path —
    module-level ``fit_*`` functions, ``transform_matrix`` and
    ``kneighbors`` methods in models/ — must run under a ``trace_span``:
    spans are the ONLY source of the per-phase breakdown (metrics
    histogram + run journal, docs/observability.md). AST upgrade: the
    def-body extraction is scope-exact instead of indentation-guessed."""
    _rule_clean("hot-path-span")


def test_metric_names_follow_the_convention():
    """Metric names are an API (dashboards/alerts key on them): enforce
    ``srml_<area>_<name>[_unit]`` at every registration site — counters
    end ``_total``, histograms end in their unit, gauges don't carry the
    counter suffix. docs/observability.md is the catalog."""
    name_re = re.compile(r"^srml_[a-z0-9]+(_[a-z0-9]+)+$")
    call_re = re.compile(
        r"\.(?P<kind>counter|gauge|histogram)\(\s*[\"'](?P<name>[^\"']+)[\"']",
        re.S,
    )
    offenders = []
    sources = [p for p in _py_sources() if p.name != "metrics.py"]
    sources.append(PKG.parent / "bench.py")
    found = 0
    for path in sources:
        for m in call_re.finditer(path.read_text()):
            found += 1
            kind, name = m.group("kind"), m.group("name")
            where = f"{path.name}:{name}"
            if not name_re.match(name):
                offenders.append(f"{where} (not srml_<area>_<name>)")
            elif kind == "counter" and not name.endswith("_total"):
                offenders.append(f"{where} (counter must end _total)")
            elif kind == "histogram" and not name.endswith(
                ("_seconds", "_bytes", "_rows")
            ):
                offenders.append(f"{where} (histogram must end in a unit)")
            elif kind == "gauge" and name.endswith("_total"):
                offenders.append(f"{where} (gauge must not end _total)")
    assert found >= 15, (
        f"only {found} metric registrations found — the regex or the "
        "instrumentation regressed"
    )
    assert offenders == [], "metric naming violations: " + ", ".join(offenders)


def test_wire_ops_are_clamped_and_documented():
    """MIGRATED to srml-check (upgraded to AST: op strings built by
    concatenation or f-strings can no longer dodge the clamp): every op
    the daemon dispatches must appear in BOTH ``_KNOWN_OPS`` (the
    metrics-label allowlist) and ``docs/protocol.md`` (the frozen wire
    contract), and answered ack-dict fields may only ever be ADDED
    versus the checked-in tools/analyze_contract.json snapshot — now
    PER OP: `wire-schema` extracts each handler's request/ack fields
    and fails on a removed field, a removed op, or a deleted
    ``### <op>`` catalog entry in docs/protocol.md."""
    _rule_clean("wire-op-clamp", "ack-contract", "wire-schema")


def test_serve_config_keys_have_env_alias_and_docs():
    """Every ``serve_*`` / ``fleet_*`` / ``rf_*`` / ``forest_*`` config
    key is an operator API: it must have its deployment-facing
    ``SRML_<KEY>`` env alias wired in config.py AND appear in
    docs/protocol.md (the "Serving scheduler" / "Fleet & versioned
    serving" / "The `rf` job algo" contracts — the mirror of the
    wire-op clamp+docs gate): a knob cannot be added silently, without
    an env spelling or documentation. The fleet keys (``fleet_*`` +
    ``serve_version_*``) joined the gate with the fleet PR; the forest
    keys (``forest_*``/``rf_*``) with the tree-ensemble PR; the
    elastic-scale keys (``autoscale_*`` + ``fit_daemon_join_*``) with
    the scale-up PR (``fit_daemon_join`` specifically — the older
    ``fit_daemon_loss_tolerance``/``fit_daemon_death_timeout_s`` keys
    predate the gate and use the legacy SRML_TPU_ env prefix); the
    gossip keys (``gossip_*`` + ``fleet_seed_*``) with the gossiped
    control-plane PR; the telemetry-plane keys (``slo_*`` /
    ``telemetry_*`` / ``incident_*``) with the fleet-telemetry PR."""
    text = (PKG / "config.py").read_text()
    keys = sorted(set(re.findall(
        r'^\s+"((?:serve|fleet|rf|forest|autoscale|fit_daemon_join|gossip'
        r'|slo|telemetry|incident)'
        r'_[a-z0-9_]+)"\s*:', text, re.M
    )))
    assert len(keys) >= 5, (
        f"only {len(keys)} serve_*/fleet_*/forest_* config keys found — "
        "the scheduler/fleet/forest config blocks or this regex regressed"
    )
    assert any(k.startswith("fleet_") for k in keys), (
        "no fleet_* config keys found — the fleet config block or this "
        "regex regressed"
    )
    assert any(k.startswith("serve_version_") for k in keys), (
        "no serve_version_* config keys found — the versioned-serving "
        "fence config or this regex regressed"
    )
    assert any(k.startswith(("forest_", "rf_")) for k in keys), (
        "no forest_*/rf_* config keys found — the tree-ensemble config "
        "block or this regex regressed"
    )
    assert any(k.startswith("autoscale_") for k in keys), (
        "no autoscale_* config keys found — the serve-autoscaler config "
        "block or this regex regressed"
    )
    assert any(k.startswith("fit_daemon_join_") for k in keys), (
        "no fit_daemon_join_* config keys found — the mid-fit join "
        "config block or this regex regressed"
    )
    assert any(k.startswith("gossip_") for k in keys), (
        "no gossip_* config keys found — the gossip config block or "
        "this regex regressed"
    )
    assert any(k.startswith("fleet_seed_") for k in keys), (
        "no fleet_seed_* config keys found — the bootstrap-seed config "
        "or this regex regressed"
    )
    for fam in ("slo_", "telemetry_", "incident_"):
        assert any(k.startswith(fam) for k in keys), (
            f"no {fam}* config keys found — the telemetry-plane config "
            "block or this regex regressed"
        )
    docs = (PKG.parent / "docs" / "protocol.md").read_text()
    missing_env = [k for k in keys if f"SRML_{k.upper()}" not in text]
    assert missing_env == [], (
        "serve_*/fleet_*/forest_* config keys without an SRML_ env alias "
        "in config.py: " + ", ".join(missing_env)
    )
    undocumented = [
        k for k in keys
        if not (re.search(rf"\b{k}\b", docs)
                and re.search(rf"\bSRML_{k.upper()}\b", docs))
    ]
    assert undocumented == [], (
        "serve_*/fleet_*/forest_* config keys (or their SRML_ env "
        "aliases) absent from docs/protocol.md: " + ", ".join(undocumented)
    )


def _paren_span(text: str, start: int, window: int = 600) -> str:
    """The balanced-paren argument span of a call starting at ``start``
    (bounded window keeps the lint fast; calls here are short)."""
    span = text[start: start + window]
    depth = 0
    for i, ch in enumerate(span):
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
            if depth == 0:
                return span[: i + 1]
    return span


def test_every_pallas_kernel_has_interpret_golden():
    """Every ``pallas.*`` ledger name in ops/pallas_kernels.py must have
    an ``interpret=True`` golden somewhere in tests/ that compares the
    kernel against the plain-jax path — a fused kernel without a
    bitwise/tolerance oracle is untestable on CPU CI, so a kernel cannot
    land (or be renamed) without its golden following."""
    src = (PKG / "ops" / "pallas_kernels.py").read_text()
    kernels = re.findall(
        r'ledgered_jit[,(]\s*\n?\s*"pallas\.([a-z0-9_]+)"', src
    )
    assert len(kernels) >= 8, (
        f"only {len(kernels)} pallas.* ledger registrations found — the "
        "registration pattern or this regex regressed"
    )
    tests_dir = Path(__file__).resolve().parent
    texts = [p.read_text() for p in sorted(tests_dir.glob("*.py"))]
    missing = []
    for fn in sorted(set(kernels)):
        ok = False
        for text in texts:
            for m in re.finditer(rf"\b{fn}\s*\(", text):
                if "interpret=True" in _paren_span(text, m.start()):
                    ok = True
                    break
            if ok:
                break
        if not ok:
            missing.append(fn)
    assert missing == [], (
        "pallas kernels without an interpret=True golden in tests/ "
        "(add a parity test against the plain-jax oracle): "
        + ", ".join(missing)
    )


def test_no_bare_print_in_package():
    """MIGRATED to srml-check: library code logs through the package
    logger, never print — stdout belongs to the host application (and
    to Spark's worker protocol!). tools/ and ``__main__`` tails exempt."""
    _rule_clean("bare-print")


def test_no_bare_collectives_outside_parallel():
    """MIGRATED to srml-check: every device collective goes through the
    mapreduce layer (``parallel/mapreduce.py``) so the
    ``srml_parallel_collective_traces_total`` booking sees it
    (docs/mesh.md). AST upgrade: only true CALL nodes are flagged."""
    _rule_clean("bare-collective")


def test_every_jit_in_ops_and_models_is_ledgered():
    """MIGRATED to srml-check (`jit-ledger`): every jit entry point in
    ops/ and models/ must register with the jit ledger
    (``ledgered_jit(name, ...)`` — utils/xprof.py), the mirror of the
    hot-path-spanned gate above: a bare ``jax.jit`` is invisible to the
    device-cost attribution (compile seconds, flops, bytes) that every
    perf PR is judged with. Ledger-name hygiene rides along: names are
    ``<area>.<fn>`` and unique ACROSS files (the ledger is process-wide;
    same-file reuse is the deliberate host/device-variant pooling). The
    ≥35-entry self-check floor moved into the rule's strict_floors
    branch. AST upgrade: registrations are found as call nodes, so a
    renamed alias or an oddly-wrapped partial can no longer dodge the
    regex."""
    _rule_clean("jit-ledger")
