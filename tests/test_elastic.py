"""Elastic fit: graceful degradation under PERMANENT daemon loss
(ISSUE 10; docs/protocol.md "Permanent daemon loss").

The claim under test: a multi-daemon fit whose PEER daemon dies and
NEVER comes back — the evicted-host case PR 4's reboot recovery does not
cover — completes anyway when the operator grants a loss budget
(``fit_daemon_loss_tolerance``): the driver classifies the death (probe
within the ``fit_daemon_death_timeout_s`` budget, mesh membership as the
co-resident witness), quarantines the daemon, rewinds survivors to the
last pass boundary via the recovery ledger, and reruns the scan with the
dead daemon's partitions rerouted to survivors (sparksim's per-attempt
env plan models Spark rescheduling onto surviving hosts). The fitted
model must be BITWISE-identical to an uninterrupted fit on the surviving
topology — integer-valued data makes every fold exact, so any lost,
duplicated, or double-merged row is a hard mismatch.

With the DEFAULT tolerance of 0 the same death is today's loud error —
no probe ever runs, byte-for-byte the pre-elastic behavior.

The in-process tests cover BOTH reduce paths (collective `reduce_mesh`
and the driver hub) plus the PCA single-pass variant; the subprocess
flagship SIGKILLs a real daemon process (exit 17, no restart) under the
hub path and is marked ``slow`` per the recovery-flagship convention.
"""

import numpy as np
import pytest

from spark_rapids_ml_tpu import config
from spark_rapids_ml_tpu.serve.daemon import DataPlaneDaemon
from spark_rapids_ml_tpu.spark import estimator as spark_est
from spark_rapids_ml_tpu.spark.estimator import (
    _DAEMON_ID_CACHE,
    _evict_daemon_id_cache,
    SparkKMeans,
    SparkPCA,
)
from spark_rapids_ml_tpu.utils import faults
from spark_rapids_ml_tpu.utils import metrics as metrics_mod
from spark_rapids_ml_tpu.utils.faults import FaultPlan

from conftest import spawn_daemon_worker
from sparksim import SimDataFrame, SimSparkSession, simdf_from_numpy

pytestmark = pytest.mark.elastic

spark_est.register_dataframe_type(SimDataFrame)


@pytest.fixture(autouse=True)
def _no_plan_leaks():
    yield
    faults.deactivate()
    assert faults.active_plan() is None


@pytest.fixture(autouse=True)
def _fast_dead_daemon_clients(monkeypatch):
    """Bound every client's dead-daemon retry cost: the elastic runs
    deliberately talk to a stopped daemon many times (task reroutes,
    boundary syncs, probes), and the default 5-attempt healing would
    dominate the suite's wall clock."""
    monkeypatch.setenv("SRML_DAEMON_OP_ATTEMPTS", "2")
    monkeypatch.setenv("SRML_FIT_DAEMON_DEATH_TIMEOUT_S", "2")


@pytest.fixture
def three_daemons():
    """Primary + two peers, in-process ('three TPU hosts' on one box;
    real TCP, one shared device plane so the collective path applies)."""
    with DataPlaneDaemon(ttl=600.0) as a, DataPlaneDaemon(ttl=600.0) as b, \
            DataPlaneDaemon(ttl=600.0) as c:
        yield a, b, c


def _addr(daemon) -> str:
    return f"{daemon.address[0]}:{daemon.address[1]}"


def _counter_total(name):
    snap = metrics_mod.snapshot()
    return sum(
        float(s.get("value", 0.0))
        for s in (snap.get(name) or {}).get("samples", [])
    )


def _int_blobs(rng, k=3, d=5, per=60):
    """Integer-valued clustered rows: every sufficient statistic is
    exact in the accumulator dtype, so fold order/grouping cannot
    perturb the model — equality checks are bitwise (the multidaemon
    suite's convention)."""
    centers = rng.integers(-12, 13, size=(k, d)) * 4
    x = np.concatenate(
        [centers[i] + rng.integers(-1, 2, size=(per, d)) for i in range(k)]
    ).astype(np.float64)
    return x[rng.permutation(len(x))]


def _reroute_env(addr_dead, addr_survivor, addr_c):
    """Partition routing: 0,1 → primary; 2,3 → the doomed daemon with
    per-ATTEMPT failover to the survivor (Spark rescheduling a lost
    host's tasks); 4,5 → the surviving peer."""
    return {
        2: [{"SRML_DAEMON_ADDRESS": addr_dead},
            {"SRML_DAEMON_ADDRESS": addr_survivor}],
        3: [{"SRML_DAEMON_ADDRESS": addr_dead},
            {"SRML_DAEMON_ADDRESS": addr_survivor}],
        4: {"SRML_DAEMON_ADDRESS": addr_c},
        5: {"SRML_DAEMON_ADDRESS": addr_c},
    }


def _survivor_env(addr_c):
    """The oracle topology: the dead daemon's partitions live on the
    primary, 4,5 on the surviving peer — exactly where the elastic fit
    reroutes them."""
    return {
        4: {"SRML_DAEMON_ADDRESS": addr_c},
        5: {"SRML_DAEMON_ADDRESS": addr_c},
    }


def _fit_kmeans(x, primary_addr, env_plan, addresses):
    session = SimSparkSession({
        "spark.srml.daemon.address": primary_addr,
        "spark.srml.daemon.addresses": addresses,
    })
    df = simdf_from_numpy(x, n_partitions=6, session=session,
                          env_plan=env_plan, concurrency=1)
    return SparkKMeans().setK(3).setMaxIter(3).setSeed(5).fit(df)


def _fit_pca(x, primary_addr, env_plan):
    session = SimSparkSession({"spark.srml.daemon.address": primary_addr})
    df = simdf_from_numpy(x, n_partitions=6, session=session,
                          env_plan=env_plan, concurrency=1)
    return SparkPCA().setInputCol("features").setK(3).fit(df)


@pytest.mark.parametrize("collectives", [True, False],
                         ids=["collective", "hub"])
def test_kmeans_elastic_degrade_bitwise(rng, mesh8, monkeypatch, collectives,
                                        three_daemons):
    """THE tentpole scenario on both reduce paths: 3-daemon iterative
    fit, one peer dies permanently mid-fit (daemon.vanish at a boundary
    sync, stop() with NO restart), tolerance=1 — the fit completes with
    the model bitwise-equal to an uninterrupted fit on the surviving
    2-daemon topology, and the loss/reroute telemetry fired."""
    a, b, c = three_daemons
    x = _int_blobs(rng)
    monkeypatch.setenv("SRML_FIT_DAEMON_LOSS_TOLERANCE", "1")
    losses0 = _counter_total("srml_fit_daemon_losses_total")
    reroutes0 = _counter_total("srml_fit_reroutes_total")
    with config.option("mesh_collectives", collectives):
        oracle = _fit_kmeans(
            x, _addr(a), _survivor_env(_addr(c)),
            addresses=f"{_addr(a)},{_addr(c)}",
        )
        # after=1: the first vanish hit is pass 0's reduce (collective)
        # or a pass-0 export (hub); the SECOND lands inside the pass-0
        # boundary coordination — wherever it fires, the callback kills
        # b for good.
        plan = (
            FaultPlan(seed=2)
            .rule("daemon.vanish", "crash", after=1, times=1)
            .on_crash(b.stop)
        )
        with faults.active(plan):
            m = _fit_kmeans(
                x, _addr(a), _reroute_env(_addr(b), _addr(a), _addr(c)),
                addresses=f"{_addr(a)},{_addr(b)},{_addr(c)}",
            )
    assert plan.fired.get("daemon.vanish") == 1, (
        "the permanent death never fired — the run proved nothing"
    )
    np.testing.assert_array_equal(m.centers, oracle.centers)
    assert m.summary.trainingCost == oracle.summary.trainingCost
    assert m.summary.numIter == oracle.summary.numIter
    # zero lost rows: the dead daemon's partitions were re-fed entirely
    assert m.summary.n_rows == x.shape[0]
    assert _counter_total("srml_fit_daemon_losses_total") - losses0 >= 1
    assert _counter_total("srml_fit_reroutes_total") - reroutes0 >= 1


@pytest.mark.parametrize("collectives", [True, False],
                         ids=["collective", "hub"])
def test_pca_elastic_degrade_single_pass_bitwise(rng, mesh8, monkeypatch,
                                                 collectives, three_daemons):
    """The single-pass variant: no iterate ledger exists, so the rewind
    degenerates to drop-and-rescan — the peer dies at the merge moment
    (first vanish hit: the pass's reduce/export), after its rows were
    already committed and acked, and the whole scan replays on the
    shrunken topology."""
    a, b, c = three_daemons
    x = _int_blobs(rng, k=3, d=8, per=60)
    monkeypatch.setenv("SRML_FIT_DAEMON_LOSS_TOLERANCE", "1")
    losses0 = _counter_total("srml_fit_daemon_losses_total")
    with config.option("mesh_collectives", collectives):
        oracle = _fit_pca(x, _addr(a), _survivor_env(_addr(c)))
        plan = (
            FaultPlan(seed=3)
            .rule("daemon.vanish", "crash", after=0, times=1)
            .on_crash(b.stop)
        )
        with faults.active(plan):
            m = _fit_pca(
                x, _addr(a), _reroute_env(_addr(b), _addr(a), _addr(c))
            )
    assert plan.fired.get("daemon.vanish") == 1
    np.testing.assert_array_equal(m.pc, oracle.pc)
    np.testing.assert_array_equal(m.mean, oracle.mean)
    np.testing.assert_array_equal(m.explainedVariance, oracle.explainedVariance)
    assert _counter_total("srml_fit_daemon_losses_total") - losses0 >= 1


def test_default_zero_tolerance_is_todays_loud_error(rng, mesh8, monkeypatch,
                                                     three_daemons):
    """The acceptance pin: with fit_daemon_loss_tolerance at its default
    0, the same permanent death fails the fit loudly — no probe runs, no
    daemon is amputated, no model is returned."""
    a, b, c = three_daemons
    x = _int_blobs(rng)
    monkeypatch.delenv("SRML_FIT_DAEMON_LOSS_TOLERANCE", raising=False)
    losses0 = _counter_total("srml_fit_daemon_losses_total")
    plan = (
        FaultPlan(seed=2)
        .rule("daemon.vanish", "crash", after=1, times=1)
        .on_crash(b.stop)
    )
    with faults.active(plan):
        with pytest.raises(OSError):
            _fit_kmeans(
                x, _addr(a), _reroute_env(_addr(b), _addr(a), _addr(c)),
                addresses=f"{_addr(a)},{_addr(b)},{_addr(c)}",
            )
    assert plan.fired.get("daemon.vanish") == 1
    assert _counter_total("srml_fit_daemon_losses_total") == losses0


def test_loss_budget_exhausted_fails_loudly(rng, mesh8, monkeypatch,
                                            three_daemons):
    """Losing MORE daemons than the tolerance grants must surface a
    clear budget error, not a silent partial model: both peers die at
    once under tolerance=1."""
    a, b, c = three_daemons
    x = _int_blobs(rng)
    monkeypatch.setenv("SRML_FIT_DAEMON_LOSS_TOLERANCE", "1")
    plan = (
        FaultPlan(seed=2)
        .rule("daemon.vanish", "crash", after=1, times=1)
        .on_crash(lambda: (b.stop(), c.stop()))
    )
    with faults.active(plan):
        with pytest.raises(RuntimeError, match="loss budget"):
            _fit_kmeans(
                x, _addr(a), _reroute_env(_addr(b), _addr(a), _addr(c)),
                addresses=f"{_addr(a)},{_addr(b)},{_addr(c)}",
            )
    assert plan.fired.get("daemon.vanish") == 1


# --------------------- _DAEMON_ID_CACHE lifecycle ----------------------------


def test_evict_daemon_id_cache_semantics():
    """The cache-eviction helper: exact-job sweep, single-address
    eviction (the quarantine path), and uid-prefix sweep (the KNN fit
    shell) — none of them may touch another fit's routes."""
    _DAEMON_ID_CACHE.clear()
    _DAEMON_ID_CACHE[("job-a", "127.0.0.1", 1111)] = "id1"
    _DAEMON_ID_CACHE[("job-a", "127.0.0.1", 2222)] = "id2"
    _DAEMON_ID_CACHE[("job-b", "127.0.0.1", 1111)] = "id3"
    _DAEMON_ID_CACHE[("uid9-deadbeef", "127.0.0.1", 3333)] = "id4"
    _evict_daemon_id_cache("job-a", addr="127.0.0.1:1111")
    assert ("job-a", "127.0.0.1", 1111) not in _DAEMON_ID_CACHE
    assert ("job-a", "127.0.0.1", 2222) in _DAEMON_ID_CACHE
    _evict_daemon_id_cache("job-a")
    assert ("job-a", "127.0.0.1", 2222) not in _DAEMON_ID_CACHE
    assert ("job-b", "127.0.0.1", 1111) in _DAEMON_ID_CACHE
    _evict_daemon_id_cache("uid9-", prefix=True)
    assert ("uid9-deadbeef", "127.0.0.1", 3333) not in _DAEMON_ID_CACHE
    assert ("job-b", "127.0.0.1", 1111) in _DAEMON_ID_CACHE
    # a malformed address is a no-op, never an error (cleanup path)
    _evict_daemon_id_cache("job-b", addr="not-an-address")
    _DAEMON_ID_CACHE.clear()


def test_fit_exit_clears_the_fits_cache_routes(rng, mesh8, monkeypatch):
    """End-to-end lifecycle (the leak fix): entries keyed by this fit's
    job are gone after fit exit — a long-lived driver no longer grows an
    entry per fit, and a recycled job name cannot inherit a stale daemon
    id. The fit's job name is pinned by monkeypatching the uuid suffix."""
    import uuid as uuid_mod

    with DataPlaneDaemon(ttl=600.0) as a:
        fake = uuid_mod.UUID(hex="deadbeef" * 4)
        monkeypatch.setattr(spark_est.uuid, "uuid4", lambda: fake)
        session = SimSparkSession({"spark.srml.daemon.address": _addr(a)})
        df = simdf_from_numpy(_int_blobs(rng, per=40), n_partitions=2,
                              session=session)
        est = SparkPCA().setInputCol("features").setK(2)
        job = f"{est._core.uid}-{fake.hex[:8]}"
        # a stale route from "the fit that used this name before"
        _DAEMON_ID_CACHE[(job, "127.0.0.1", a.address[1])] = "stale-ghost"
        est.fit(df)
        assert not [k for k in _DAEMON_ID_CACHE if k[0] == job], (
            "fit exit left its id-cache routes behind"
        )


# ------------------- flagship: SIGKILL with NO restart -----------------------


@pytest.mark.slow
@pytest.mark.chaos
def test_flagship_sigkill_no_restart_3to2_bitwise(rng, monkeypatch,
                                                  worker_daemon_pair):
    """THE acceptance flagship: three daemon PROCESSES (hub reduce path
    by construction — separate runtimes), a kmeans fit mid-flight, and
    the victim process dies abruptly (env-activated daemon.vanish crash,
    exit 17) with NO restart. The fit must complete with zero lost rows
    and a model bitwise-equal to an uninterrupted fit on the surviving
    2-daemon topology. The two survivors are the module's shared worker
    pair; only the victim is spawned (and killed) here."""
    (_pa, port_a), (_pc, port_c) = worker_daemon_pair
    addr_a, addr_c = f"127.0.0.1:{port_a}", f"127.0.0.1:{port_c}"
    x = _int_blobs(rng)
    monkeypatch.setenv("SRML_FIT_DAEMON_LOSS_TOLERANCE", "1")
    monkeypatch.setenv("SRML_FIT_DAEMON_DEATH_TIMEOUT_S", "4")
    monkeypatch.setenv("SRML_DAEMON_ADDRESS", addr_a)

    def fit(addresses, env_plan):
        session = SimSparkSession({
            "spark.srml.daemon.addresses": addresses,
        })
        df = simdf_from_numpy(x, n_partitions=6, session=session,
                              env_plan=env_plan, concurrency=1)
        return SparkKMeans().setK(3).setMaxIter(3).setSeed(5).fit(df)

    oracle = fit(f"{addr_a},{addr_c}", _survivor_env(addr_c))

    losses0 = _counter_total("srml_fit_daemon_losses_total")
    # The victim dies at its SECOND vanish hit: its first is the pass-0
    # export (hub merge), the second the pass-0 boundary set_iterate —
    # mid-fit, after it committed and acked rows. os._exit(17), the
    # honest process death; nothing ever restarts it.
    victim, port_b = spawn_daemon_worker(
        fault_spec="daemon.vanish:crash:after=1,times=1"
    )
    addr_b = f"127.0.0.1:{port_b}"
    try:
        m = fit(
            f"{addr_a},{addr_b},{addr_c}",
            _reroute_env(addr_b, addr_a, addr_c),
        )
        victim.wait(timeout=30)
        assert victim.returncode == 17, (
            "the injected permanent death never happened"
        )
        np.testing.assert_array_equal(m.centers, oracle.centers)
        assert m.summary.trainingCost == oracle.summary.trainingCost
        assert m.summary.numIter == oracle.summary.numIter
        assert m.summary.n_rows == x.shape[0]  # zero lost rows
        assert _counter_total("srml_fit_daemon_losses_total") - losses0 >= 1
    finally:
        if victim.poll() is None:
            victim.kill()


# ------------------- bench --chaos-elastic + perfcheck gate ------------------


def test_perfcheck_chaos_elastic_gates():
    """The recovery-cost gate's unit matrix: correctness (bitwise vs the
    surviving-topology oracle, nonzero replayed rows) is ABSOLUTE;
    throughput/overhead gate against the metric-matched trajectory and
    SKIP — never pass — without history."""
    from spark_rapids_ml_tpu.tools import perfcheck

    good = {
        "metric": "chaos_elastic_replay_rows_per_s_d64_k8",
        "mode": "chaos_elastic", "value": 1000.0, "replayed_rows": 100,
        "recovery_overhead": 2.0, "bitwise_equal_oracle": True,
        "n_survivors": 2, "time_to_recover_s": 0.5,
    }
    ok, lines = perfcheck.check_chaos_elastic(good, [])
    assert ok and any("SKIP" in ln for ln in lines)
    ok, lines = perfcheck.check_chaos_elastic(
        dict(good, bitwise_equal_oracle=False), []
    )
    assert not ok and any("FAIL" in ln for ln in lines)
    ok, _ = perfcheck.check_chaos_elastic(dict(good, replayed_rows=0), [good])
    assert not ok
    ok, _ = perfcheck.check_chaos_elastic(dict(good, value=500.0), [good])
    assert not ok  # replay throughput regressed past the floor
    ok, _ = perfcheck.check_chaos_elastic(
        dict(good, recovery_overhead=5.0), [good]
    )
    assert not ok  # recovery got relatively MORE expensive
    ok, _ = perfcheck.check_chaos_elastic(dict(good), [good])
    assert ok  # healthy vs its own trajectory
    ok, _ = perfcheck.check_chaos_elastic({"metric": "x"}, [])
    assert not ok  # not a chaos-elastic record at all


@pytest.mark.perf
def test_bench_chaos_elastic_smoke_and_gate(tmp_path):
    """End-to-end: ``bench.py --chaos-elastic`` at toy shapes emits one
    self-verifying JSON record (bitwise_equal_oracle must hold even at
    toy sizes — integer folds are exact at any scale) and the perfcheck
    CLI routes it to the chaos gate: correctness OK, cost SKIP (no
    history), exit 0."""
    import json as json_mod
    import os
    import subprocess
    import sys

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {k: v for k, v in os.environ.items() if not k.startswith("SRML_")}
    env.update({
        "JAX_PLATFORMS": "cpu",
        "SRML_BENCH_ELASTIC_PART_ROWS": "512",
        "SRML_BENCH_ELASTIC_D": "8",
        "SRML_BENCH_ELASTIC_K": "4",
        "SRML_BENCH_ELASTIC_DEATH_TIMEOUT_S": "0.3",
        "PYTHONPATH": os.pathsep.join(
            p for p in (repo_root, env.get("PYTHONPATH")) if p
        ),
    })
    out = subprocess.run(
        [sys.executable, "bench.py", "--chaos-elastic"],
        cwd=repo_root, env=env, capture_output=True, text=True, timeout=240,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    line = out.stdout.strip().splitlines()[-1]
    rec = json_mod.loads(line)
    assert rec["mode"] == "chaos_elastic"
    assert rec["bitwise_equal_oracle"] is True
    assert rec["replayed_rows"] == rec["rows"] > 0
    assert rec["time_to_recover_s"] > 0

    from spark_rapids_ml_tpu.tools import perfcheck

    path = tmp_path / "rec.json"
    path.write_text(line)
    assert perfcheck.main(
        [str(path), "--history", str(tmp_path / "no-history-*.json")]
    ) == 0


def test_feed_task_evicts_quarantined_routes_worker_side(rng, mesh8,
                                                         monkeypatch):
    """The eviction that matters on REAL executors rides the task
    closure (``_FeedTask.evict_routes``): a reused python worker's
    cached ghost id for a quarantined address is dropped at task start,
    so whatever now answers at that address is re-pinged — the driver's
    own cache copy cannot reach the worker's."""
    import pyarrow as pa

    from spark_rapids_ml_tpu.bridge.arrow import matrix_to_list_column
    from spark_rapids_ml_tpu.serve.client import DataPlaneClient
    from spark_rapids_ml_tpu.spark.estimator import _FeedTask

    with DataPlaneDaemon(ttl=600.0) as d:
        h, p = d.address
        job = "evict-task-job"
        # The "reused worker" state: a ghost id cached for the address
        # a quarantined daemon used to hold.
        _DAEMON_ID_CACHE[(job, h, p)] = "ghost-id"
        monkeypatch.setenv("SRML_PARTITION_ID", "0")
        monkeypatch.setenv("SRML_ATTEMPT", "0")
        monkeypatch.delenv("SRML_DAEMON_ADDRESS", raising=False)
        fn = _FeedTask(h, p, None, job, "pca", "features", "label", {},
                       None, evict_routes=(f"{h}:{p}",))
        batch = pa.table(
            {"features": matrix_to_list_column(rng.normal(size=(8, 4)))}
        ).to_batches()[0]
        acks = list(fn([batch]))
        assert _DAEMON_ID_CACHE[(job, h, p)] == d.instance_id, (
            "the ghost id survived the task-borne eviction"
        )
        got = acks[0].column("daemon_id")[0].as_py()
        assert got == d.instance_id  # the ack names the LIVE daemon
        with DataPlaneClient(h, p) as c:
            c.drop(job)
        _evict_daemon_id_cache(job)


# --------------------- mid-fit daemon JOIN (scale-UP) ------------------------
# The grow direction (ISSUE 16; docs/protocol.md "Mid-fit daemon join"):
# a daemon appearing mid-fit is admitted at the next pass boundary —
# never mid-pass — seeded with the recovery-ledger iterate, and the
# replayed pass rebalances partitions onto it. Routing model: partitions
# 2,3 FIRST try the newcomer every pass (per-attempt failover back to
# the primary — before admission the newcomer's unseeded-job rejection
# fails the attempt and the rows land on the primary); the fit's
# configured daemon set grows mid-fit via the fault callback, the way
# Spark dynamic allocation re-points spark.srml.daemon.addresses.


def _grow_env(addr_new, addr_fallback, addr_c):
    return {
        2: [{"SRML_DAEMON_ADDRESS": addr_new},
            {"SRML_DAEMON_ADDRESS": addr_fallback}],
        3: [{"SRML_DAEMON_ADDRESS": addr_new},
            {"SRML_DAEMON_ADDRESS": addr_fallback}],
        4: {"SRML_DAEMON_ADDRESS": addr_c},
        5: {"SRML_DAEMON_ADDRESS": addr_c},
    }


def _grow_session(addr_a, addr_c):
    return SimSparkSession({
        "spark.srml.daemon.address": addr_a,
        "spark.srml.daemon.addresses": f"{addr_a},{addr_c}",
    })


def _fit_kmeans_on(x, session, env_plan):
    df = simdf_from_numpy(x, n_partitions=6, session=session,
                          env_plan=env_plan, concurrency=1)
    return SparkKMeans().setK(3).setMaxIter(3).setSeed(5).fit(df)


def _grow_plan(session, a, b, c, seed=4):
    """A boundary-sync failure (both client attempts dropped) whose
    crash callback is the dynamic-allocation event: the newcomer's
    address joins the fit's configured daemon set mid-fit."""
    return (
        FaultPlan(seed=seed)
        .rule("daemon.vanish", "crash", after=1, times=2)
        .on_crash(lambda: session.conf.set(
            "spark.srml.daemon.addresses",
            f"{_addr(a)},{_addr(b)},{_addr(c)}",
        ))
    )


@pytest.mark.parametrize("collectives", [True, False],
                         ids=["collective", "hub"])
def test_kmeans_mid_fit_join_bitwise(rng, mesh8, monkeypatch, collectives,
                                     three_daemons):
    """THE grow tentpole on both reduce paths: a 2-daemon iterative fit,
    a third daemon appears mid-fit (fault callback re-points the
    configured addresses at a boundary failure), join policy `boundary`
    admits it — seeded from the ledger iterate by ONE creating
    set_iterate — and the replayed pass rebalances partitions 2,3 onto
    it. The grown fit must be BITWISE-equal to a static-topology oracle,
    and the join/rebalance telemetry must count exactly one join and
    exactly the moved rows."""
    a, b, c = three_daemons
    x = _int_blobs(rng)
    monkeypatch.setenv("SRML_FIT_DAEMON_JOIN_POLICY", "boundary")
    joins0 = _counter_total("srml_fit_joins_total")
    rebal0 = _counter_total("srml_fit_rebalanced_rows_total")
    with config.option("mesh_collectives", collectives):
        oracle = _fit_kmeans(
            x, _addr(a), _survivor_env(_addr(c)),
            addresses=f"{_addr(a)},{_addr(c)}",
        )
        session = _grow_session(_addr(a), _addr(c))
        plan = _grow_plan(session, a, b, c)
        with faults.active(plan):
            m = _fit_kmeans_on(
                x, session, _grow_env(_addr(b), _addr(a), _addr(c))
            )
    assert plan.fired.get("daemon.vanish") == 2, (
        "the boundary failure never fired — the run proved nothing"
    )
    np.testing.assert_array_equal(m.centers, oracle.centers)
    assert m.summary.trainingCost == oracle.summary.trainingCost
    assert m.summary.numIter == oracle.summary.numIter
    # zero lost rows: every pass still accounts the full dataset
    assert m.summary.n_rows == x.shape[0]
    assert _counter_total("srml_fit_joins_total") - joins0 == 1
    # partitions 2 and 3 moved onto the joiner on its first acked pass
    assert (_counter_total("srml_fit_rebalanced_rows_total") - rebal0
            == x.shape[0] // 3)


def test_join_policy_default_off_stays_loud(rng, mesh8, monkeypatch,
                                            three_daemons):
    """The acceptance pin: with fit_daemon_join_policy at its default
    `off`, the same mid-fit appearance changes nothing — the boundary
    failure is today's loud error, no daemon is admitted, no join
    telemetry fires."""
    a, b, c = three_daemons
    x = _int_blobs(rng)
    monkeypatch.delenv("SRML_FIT_DAEMON_JOIN_POLICY", raising=False)
    joins0 = _counter_total("srml_fit_joins_total")
    session = _grow_session(_addr(a), _addr(c))
    plan = _grow_plan(session, a, b, c)
    with faults.active(plan):
        with pytest.raises(OSError):
            _fit_kmeans_on(
                x, session, _grow_env(_addr(b), _addr(a), _addr(c))
            )
    assert plan.fired.get("daemon.vanish") == 2
    assert _counter_total("srml_fit_joins_total") == joins0


def test_join_budget_exhausted_fails_loudly(rng, mesh8, monkeypatch,
                                            three_daemons):
    """Admitting MORE daemons than fit_daemon_join_limit grants must
    surface a clear budget error, not a silent unbalanced fit: limit=0
    under policy `boundary`."""
    a, b, c = three_daemons
    x = _int_blobs(rng)
    monkeypatch.setenv("SRML_FIT_DAEMON_JOIN_POLICY", "boundary")
    monkeypatch.setenv("SRML_FIT_DAEMON_JOIN_LIMIT", "0")
    joins0 = _counter_total("srml_fit_joins_total")
    session = _grow_session(_addr(a), _addr(c))
    plan = _grow_plan(session, a, b, c)
    with faults.active(plan):
        with pytest.raises(RuntimeError, match="join budget"):
            _fit_kmeans_on(
                x, session, _grow_env(_addr(b), _addr(a), _addr(c))
            )
    assert _counter_total("srml_fit_joins_total") == joins0


def test_join_fault_during_admission_no_half_join(rng, mesh8, monkeypatch,
                                                  three_daemons):
    """A joiner that fails UNDER the admission handshake (the
    daemon.join fault site sits before its seeding set_iterate) must not
    half-join: the fit surfaces the failure, nothing is registered, no
    join is counted, and the would-be joiner holds no job."""
    a, b, c = three_daemons
    x = _int_blobs(rng)
    monkeypatch.setenv("SRML_FIT_DAEMON_JOIN_POLICY", "boundary")
    joins0 = _counter_total("srml_fit_joins_total")
    session = _grow_session(_addr(a), _addr(c))
    plan = _grow_plan(session, a, b, c).rule(
        "daemon.join", "refuse", times=1
    )
    with faults.active(plan):
        with pytest.raises(OSError):
            _fit_kmeans_on(
                x, session, _grow_env(_addr(b), _addr(a), _addr(c))
            )
    assert plan.fired.get("daemon.join") == 1, (
        "the admission fault never fired — the run proved nothing"
    )
    assert _counter_total("srml_fit_joins_total") == joins0


def test_perfcheck_chaos_grow_gates():
    """The grow-cost gate's unit matrix (mirror of the chaos-elastic
    one): correctness (bitwise vs the static-topology oracle, nonzero
    rebalanced rows) is ABSOLUTE; admission throughput / grow overhead
    gate against the metric-matched trajectory and SKIP — never pass —
    without history; degrade-family records in the shared CHAOS_r* glob
    never pollute the grow trajectory."""
    from spark_rapids_ml_tpu.tools import perfcheck

    good = {
        "metric": "chaos_grow_admit_rows_per_s_d64_k8",
        "mode": "chaos_grow", "value": 1000.0, "rebalanced_rows": 100,
        "grow_overhead": 1.1, "bitwise_equal_oracle": True,
        "n_daemons": 2, "time_to_admit_s": 0.01,
    }
    ok, lines = perfcheck.check_chaos_grow(good, [])
    assert ok and any("SKIP" in ln for ln in lines)
    ok, lines = perfcheck.check_chaos_grow(
        dict(good, bitwise_equal_oracle=False), []
    )
    assert not ok and any("FAIL" in ln for ln in lines)
    ok, _ = perfcheck.check_chaos_grow(dict(good, rebalanced_rows=0), [good])
    assert not ok
    ok, _ = perfcheck.check_chaos_grow(dict(good, value=500.0), [good])
    assert not ok  # admission throughput regressed past the floor
    ok, _ = perfcheck.check_chaos_grow(dict(good, grow_overhead=5.0), [good])
    assert not ok  # growing got relatively MORE expensive
    ok, _ = perfcheck.check_chaos_grow(dict(good), [good])
    assert ok  # healthy vs its own trajectory
    # A degrade-family record sharing the glob is filtered out: the
    # grow gates still SKIP rather than compare across families.
    elastic = {
        "metric": "chaos_elastic_replay_rows_per_s_d64_k8",
        "mode": "chaos_elastic", "value": 10.0,
    }
    ok, lines = perfcheck.check_chaos_grow(good, [elastic])
    assert ok and any("SKIP" in ln for ln in lines)
    ok, _ = perfcheck.check_chaos_grow({"metric": "x"}, [])
    assert not ok  # not a chaos-grow record at all


@pytest.mark.perf
def test_bench_chaos_grow_smoke_and_gate(tmp_path):
    """End-to-end: ``bench.py --chaos-grow`` at toy shapes emits one
    self-verifying JSON record (bitwise_equal_oracle must hold even at
    toy sizes — integer folds are exact at any scale) and the perfcheck
    CLI routes it to the grow gate: correctness OK, cost SKIP (no
    history), exit 0."""
    import json as json_mod
    import os
    import subprocess
    import sys

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {k: v for k, v in os.environ.items() if not k.startswith("SRML_")}
    env.update({
        "JAX_PLATFORMS": "cpu",
        "SRML_BENCH_GROW_PART_ROWS": "512",
        "SRML_BENCH_GROW_D": "8",
        "SRML_BENCH_GROW_K": "4",
        "PYTHONPATH": os.pathsep.join(
            p for p in (repo_root, env.get("PYTHONPATH")) if p
        ),
    })
    out = subprocess.run(
        [sys.executable, "bench.py", "--chaos-grow"],
        cwd=repo_root, env=env, capture_output=True, text=True, timeout=240,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    line = out.stdout.strip().splitlines()[-1]
    rec = json_mod.loads(line)
    assert rec["mode"] == "chaos_grow"
    assert rec["bitwise_equal_oracle"] is True
    assert rec["rebalanced_rows"] > 0
    assert rec["time_to_admit_s"] > 0

    from spark_rapids_ml_tpu.tools import perfcheck

    path = tmp_path / "rec.json"
    path.write_text(line)
    assert perfcheck.main(
        [str(path), "--history", str(tmp_path / "no-history-*.json")]
    ) == 0
