"""Arrow columnar bridge tests (regressions from code review included)."""

import numpy as np
import pytest

pa = pytest.importorskip("pyarrow")

from spark_rapids_ml_tpu.bridge.arrow import (  # noqa: E402
    list_column_to_matrix,
    matrix_to_list_column,
)


def test_fixed_size_list_roundtrip():
    m = np.arange(12, dtype=np.float64).reshape(4, 3)
    col = matrix_to_list_column(m)
    back = list_column_to_matrix(col)
    np.testing.assert_array_equal(back, m)


def test_fixed_size_list_zero_copy():
    m = np.arange(12, dtype=np.float32).reshape(4, 3)
    back = list_column_to_matrix(matrix_to_list_column(m))
    assert back.dtype == np.float32


def test_sliced_fixed_size_list():
    # Regression: sliced FSL arrays must honor the slice offset.
    m = np.arange(20, dtype=np.float64).reshape(5, 4)
    col = matrix_to_list_column(m).slice(2, 2)
    back = list_column_to_matrix(col)
    np.testing.assert_array_equal(back, m[2:4])


def test_sliced_variable_list():
    arr = pa.array([[float(i), float(i + 1)] for i in range(6)])
    back = list_column_to_matrix(arr.slice(1, 3))
    np.testing.assert_array_equal(back, [[1, 2], [2, 3], [3, 4]])


def test_ragged_rejected():
    arr = pa.array([[1.0, 2.0], [3.0]])
    with pytest.raises(ValueError, match="ragged"):
        list_column_to_matrix(arr)


def test_row_nulls_rejected():
    arr = pa.array([[1.0, 2.0], None], type=pa.list_(pa.float64()))
    with pytest.raises(ValueError, match="null"):
        list_column_to_matrix(arr)


def test_inner_nulls_rejected():
    # Regression: nulls *inside* rows must not silently become NaN.
    arr = pa.array([[1.0, None, 3.0], [4.0, 5.0, 6.0]])
    with pytest.raises(ValueError, match="null"):
        list_column_to_matrix(arr)


def test_inner_nulls_rejected_fixed_size_list():
    flat = pa.array([1.0, None, 3.0, 4.0], type=pa.float64())
    arr = pa.FixedSizeListArray.from_arrays(flat, 2)
    with pytest.raises(ValueError, match="null"):
        list_column_to_matrix(arr)


def test_chunked_array():
    m1 = np.ones((2, 3)); m2 = np.zeros((3, 3))
    chunked = pa.chunked_array(
        [matrix_to_list_column(m1), matrix_to_list_column(m2)]
    )
    back = list_column_to_matrix(chunked)
    np.testing.assert_array_equal(back, np.concatenate([m1, m2]))


def test_large_list():
    arr = pa.array([[1.0, 2.0], [3.0, 4.0]], type=pa.large_list(pa.float64()))
    back = list_column_to_matrix(arr)
    np.testing.assert_array_equal(back, [[1, 2], [3, 4]])
