"""KMeans differential tests: sklearn oracle + sharding invariance."""

import numpy as np
import pytest

from spark_rapids_ml_tpu import KMeans, KMeansModel
from spark_rapids_ml_tpu.models.kmeans import fit_kmeans
from spark_rapids_ml_tpu.parallel.mesh import make_mesh


@pytest.fixture
def blobs(rng):
    # 4 well-separated gaussian blobs in 8-d.
    centers = rng.normal(size=(4, 8)) * 10.0
    pts = np.concatenate(
        [c + rng.normal(size=(200, 8)) for c in centers], axis=0
    )
    perm = rng.permutation(len(pts))
    return pts[perm], centers


def _match_centers(found, true):
    """Greedy-match found centers to true ones; return max distance."""
    found = found.copy()
    worst = 0.0
    for t in true:
        d = np.linalg.norm(found - t, axis=1)
        i = int(np.argmin(d))
        worst = max(worst, d[i])
        found[i] = np.inf
    return worst


def test_recovers_blob_centers(blobs, mesh8):
    pts, centers = blobs
    sol = fit_kmeans(pts, k=4, max_iter=50, seed=1, mesh=mesh8)
    assert sol.n_rows == len(pts)
    assert sol.n_iter > 0
    # Each true center recovered to within ~3/sqrt(200) stderr.
    assert _match_centers(sol.centers, centers) < 0.5


def test_matches_oracle_cost(blobs, mesh8):
    from oracles import kmeans_inertia

    pts, _ = blobs
    ref_inertia = kmeans_inertia(pts, k=4, n_init=3, seed=0)
    sol = fit_kmeans(pts, k=4, max_iter=50, seed=1, mesh=mesh8)
    # Same local optimum on well-separated blobs: inertia within 1%.
    assert sol.cost <= ref_inertia * 1.01


def test_shard_invariance(blobs):
    pts, _ = blobs
    sols = [
        fit_kmeans(pts, k=4, max_iter=30, seed=7, mesh=make_mesh(data=n, model=1))
        for n in (1, 8)
    ]
    np.testing.assert_allclose(sols[0].centers, sols[1].centers, atol=1e-7)
    assert abs(sols[0].cost - sols[1].cost) < 1e-6 * max(1.0, sols[0].cost)


def test_uneven_rows(mesh8, rng):
    pts = rng.normal(size=(101, 5))
    sol = fit_kmeans(pts, k=3, max_iter=10, seed=0, mesh=mesh8)
    assert sol.centers.shape == (3, 5)
    assert np.all(np.isfinite(sol.centers))


def test_estimator_api(blobs, mesh8):
    pts, _ = blobs
    ds = {"features": pts}
    km = KMeans(mesh=mesh8).setK(4).setMaxIter(30).setSeed(3)
    model = km.fit(ds)
    assert model.clusterCenters().shape == (4, 8)
    assert model.trainingCost is not None and model.trainingCost > 0
    out = model.transform(ds)
    preds = out["prediction"]
    assert preds.shape == (len(pts),)
    assert set(np.unique(preds)) <= set(range(4))
    # Points in the same blob get the same cluster: check self-consistency
    # between predict() and the training assignment structure.
    p2 = model.predict(pts)
    np.testing.assert_array_equal(preds, p2)


def test_model_persistence(blobs, mesh8, tmp_path):
    pts, _ = blobs
    model = KMeans(mesh=mesh8).setK(4).fit({"features": pts})
    path = str(tmp_path / "km")
    model.save(path)
    loaded = KMeansModel.load(path)
    np.testing.assert_allclose(loaded.centers, model.centers, atol=1e-12)
    np.testing.assert_array_equal(loaded.predict(pts[:50]), model.predict(pts[:50]))


def test_k_validation(mesh8, rng):
    pts = rng.normal(size=(10, 3))
    with pytest.raises(ValueError):
        fit_kmeans(pts, k=0, mesh=mesh8)
    with pytest.raises(ValueError):
        fit_kmeans(pts, k=11, mesh=mesh8)
    with pytest.raises(ValueError):
        fit_kmeans(pts, k=3, init="bogus", mesh=mesh8)


def test_empty_cluster_keeps_center(mesh8):
    # Force an empty cluster: k=3 but only 2 distinct points.
    pts = np.array([[0.0, 0.0], [10.0, 10.0]] * 50)
    sol = fit_kmeans(pts, k=3, max_iter=5, init="random", seed=0, mesh=mesh8)
    assert np.all(np.isfinite(sol.centers))
