"""Fast source-level lint gates (no imports, no hardware).

These are grep-shaped invariants that a reviewer would otherwise have to
re-check by hand on every PR. They run in milliseconds and fail with the
offending file:line.
"""

import re
from pathlib import Path

PKG = Path(__file__).resolve().parent.parent / "spark_rapids_ml_tpu"


def _py_sources():
    return sorted(PKG.rglob("*.py"))


def test_every_create_connection_has_explicit_timeout():
    """A ``socket.create_connection`` without a timeout inherits the
    global default (None = block forever): one unreachable daemon would
    then hang its caller indefinitely instead of failing into the retry/
    healing path. Every call site must pass an explicit timeout."""
    offenders = []
    for path in _py_sources():
        text = path.read_text()
        for m in re.finditer(r"socket\.create_connection\s*\(", text):
            # The call's argument span: everything up to the matching
            # close paren (calls here are short; a 300-char window is
            # generous and keeps the lint trivially fast).
            window = text[m.start(): m.start() + 300]
            depth = 0
            for i, ch in enumerate(window):
                if ch == "(":
                    depth += 1
                elif ch == ")":
                    depth -= 1
                    if depth == 0:
                        window = window[: i + 1]
                        break
            if "timeout" not in window:
                line = text[: m.start()].count("\n") + 1
                offenders.append(f"{path.relative_to(PKG.parent)}:{line}")
    assert not offenders, (
        "socket.create_connection without an explicit timeout= at: "
        + ", ".join(offenders)
    )


def test_fault_checkpoints_exist_at_contract_sites():
    """The chaos suite's FaultPlan rules target named sites; this pins
    the site names to the source so a refactor that silently drops a
    hook (turning chaos coverage into a no-op) fails loudly."""
    expect = {
        "serve/client.py": ["client.connect", "client.op"],
        "serve/daemon.py": ["daemon.conn", "daemon.op"],
        "serve/protocol.py": ["wire.send_frame"],
        "bridge/arrow.py": ["bridge.to_matrix", "bridge.to_ipc"],
    }
    for rel, sites in expect.items():
        text = (PKG / rel).read_text()
        for site in sites:
            assert f'"{site}"' in text, (
                f"fault-injection site {site!r} missing from {rel} "
                "(utils/faults.py module docstring lists the contract)"
            )
