"""IVF-Flat approximate-KNN query throughput — BASELINE.json config #5
(10M×768 SBERT-class embeddings; scaled to one chip's HBM here).

Builds the IVF-Flat index on device (`models.knn.build_ivf_flat_device`:
KMeans coarse quantizer + on-device bucketing), then times batched queries
(`_ivf_query_fn`: centroid GEMM → top-nprobe probe → per-list distance
GEMMs → top-k), reporting queries/s/chip.

Baseline: probing nprobe/nlist of the base ≈ n·nprobe/nlist rows/query at
2·d flops each → 48 MFLOP/query here; an A100 IVF-Flat at this recall
point sustains ~2e5 q/s (RAFT-class, bandwidth-limited — rough published
ballpark, the reference repo itself publishes nothing, BASELINE.md).
"""

import os
import sys

if __package__ in (None, ""):  # direct script run: python benchmarks/bench_*.py
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

D = int(os.environ.get("SRML_BENCH_D", 768))
N_BASE = int(os.environ.get("SRML_BENCH_BASE_ROWS", 1 << 20))  # 1M×768 = 3.2 GB
N_QUERY = int(os.environ.get("SRML_BENCH_QUERIES", 4096))
K = int(os.environ.get("SRML_BENCH_K", 10))
NLIST = int(os.environ.get("SRML_BENCH_NLIST", 1024))
NPROBE = int(os.environ.get("SRML_BENCH_NPROBE", 32))

A100_QUERIES_PER_SEC = 2e5


def main() -> None:
    from benchmarks import setup_platform

    setup_platform()
    import jax
    import jax.numpy as jnp

    from benchmarks import emit
    from spark_rapids_ml_tpu import config
    from spark_rapids_ml_tpu.models.knn import _ivf_query_fn

    config.set("compute_dtype", "bfloat16")
    config.set("accum_dtype", "float32")
    config.set("use_pallas", True)  # fused Lloyd step for the coarse quantizer

    from spark_rapids_ml_tpu.models.knn import build_ivf_flat_device

    n_chips = len(jax.devices())
    rng = np.random.default_rng(0)
    queries = jnp.asarray(rng.standard_normal(size=(N_QUERY, D), dtype=np.float32))

    # Base rows are generated AND bucketed on device (build_ivf_flat_device):
    # the host path's 2×3 GB host↔device round-trip plus host-speed fancy
    # indexing dominates bench wall-clock on slow build hosts, and the
    # timed quantity is the query path either way.
    base = jax.random.normal(jax.random.key(0), (N_BASE, D), jnp.float32)
    index = build_ivf_flat_device(base, nlist=NLIST, seed=0)
    del base  # free 3 GB of HBM — the index alone serves the queries
    dev = [
        jnp.asarray(index.centroids, dtype=jnp.float32),
        jnp.asarray(index.lists, dtype=jnp.float32),
        jnp.asarray(index.list_ids),
        jnp.asarray(index.list_mask),
    ]
    from benchmarks import slope_dt, sync

    query = _ivf_query_fn(K, NPROBE, "bfloat16", "float32")
    # Residual norms + the bf16 residual scan copy are index data:
    # precompute once like a serving deployment would (the model path
    # caches them on device via _ensure_dev_index).
    from spark_rapids_ml_tpu.models.knn import _residual_index_data

    norms, lists_lo = _residual_index_data(dev[1], dev[0], jnp.bfloat16)

    def run(n):
        ids = None
        for _ in range(n):
            dists, ids = query(*dev, queries, resid_norms=norms, lists_lo=lists_lo)
        sync(ids)  # one sync; calls queue on device
        assert np.all(np.asarray(ids) >= 0)
        return ids

    # 8 vs 24 calls: the wider slope keeps tunnel dispatch jitter (which
    # rivals a single call's cost) out of the reported per-call rate.
    reps = int(os.environ.get("SRML_BENCH_REPS", 8))
    dt = slope_dt(run, reps, 3 * reps)
    emit(
        f"ivfflat_queries_per_sec_per_chip_n{N_BASE}_d{D}_k{K}_nprobe{NPROBE}",
        N_QUERY / dt / n_chips,
        "queries/s/chip",
        (N_QUERY / dt / n_chips) / A100_QUERIES_PER_SEC,
    )


if __name__ == "__main__":
    main()
