"""Headline benchmark: PCA.fit throughput, rows/sec/chip.

Measures the full fit step — fused count/colsum/Gram statistics (the
reference's dgemmCov hot loop, rapidsml_jni.cu:120-125) + mean-centered
finalize + eigh/sign-flip/top-k (the reference's calSVD, rapidsml_jni.cu:
215-269) — on the BASELINE.json north-star shape (d=2048, k=32), in the
TPU-native dtype mode (bfloat16 GEMM on the MXU, float32 accumulation).

Data is generated on-device so the benchmark isolates the compute path
(host→device feeding is benchmarked separately in the bridge).

Baseline for ``vs_baseline``: the A100 cuML fit is GEMM-bound at
2·d² flops/row; at ~110 TFLOP/s sustained TF32 that is ~13.1e6 rows/s.
The north-star target (BASELINE.md) is within 2× of A100 per chip, i.e.
vs_baseline >= 0.5.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import time

import numpy as np

A100_CUML_ROWS_PER_SEC = 13.1e6  # GEMM-bound estimate, see module docstring

D = 2048
K = 32
N_ROWS = 1 << 19  # 524288 rows x 2048 f32 = 4.3 GB on device


def main() -> None:
    import jax
    import jax.numpy as jnp

    from spark_rapids_ml_tpu import config
    from spark_rapids_ml_tpu.ops import gram as gram_ops
    from spark_rapids_ml_tpu.ops.eigh import pca_from_gram_host
    from spark_rapids_ml_tpu.parallel.mesh import make_mesh

    config.set("compute_dtype", "bfloat16")
    config.set("accum_dtype", "float32")

    n_chips = len(jax.devices())
    mesh = make_mesh(model=1)

    # On-device data generation (no host transfer in the timed region).
    key = jax.random.key(0)
    x = jax.random.normal(key, (N_ROWS, D), dtype=jnp.float32)
    if n_chips > 1:
        from jax.sharding import NamedSharding, PartitionSpec as P

        x = jax.device_put(x, NamedSharding(mesh, P("data", None)))
    mask = jnp.ones((N_ROWS,), dtype=jnp.float32)

    stats = gram_ops.sharded_stats(mesh, compute_dtype="bfloat16", accum_dtype="float32")

    def fit(x, mask):
        # Device: the data-scaling reduction. Host: the tiny d×d eig
        # finalize (eigh executes poorly on TPU; see config "finalize").
        count, colsum, g = stats(x, mask)
        g = np.asarray(g, dtype=np.float64)
        colsum = np.asarray(colsum, dtype=np.float64)
        n = max(float(count), 1.0)
        g -= np.outer(colsum / n, colsum)
        return pca_from_gram_host(g, K)

    # Warmup / compile.
    fit(x, mask)

    iters = 5
    t0 = time.perf_counter()
    for _ in range(iters):
        pc, ev, _ = fit(x, mask)
    dt = (time.perf_counter() - t0) / iters

    rows_per_sec_per_chip = N_ROWS / dt / n_chips
    print(
        json.dumps(
            {
                "metric": "pca_fit_rows_per_sec_per_chip_d2048_k32",
                "value": round(rows_per_sec_per_chip, 1),
                "unit": "rows/s/chip",
                "vs_baseline": round(rows_per_sec_per_chip / A100_CUML_ROWS_PER_SEC, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
