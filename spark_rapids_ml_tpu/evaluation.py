"""Evaluators — pyspark.ml.evaluation equivalents for model selection.

Host-side numpy metrics over the prediction/label columns of a transformed
dataset: the quantities are O(rows) scalars, not device work. The
``isLargerBetter`` contract matches Spark so CrossValidator's argbest
logic is metric-agnostic.
"""

from __future__ import annotations

import numpy as np

from spark_rapids_ml_tpu.core.dataset import as_column, as_matrix, has_column
from spark_rapids_ml_tpu.core.params import (
    HasLabelCol,
    HasPredictionCol,
    HasRawPredictionCol,
    ParamDecl,
    Params,
    TypeConverters,
)


def _is_vector_column(dataset, col: str) -> bool:
    """True when ``col`` holds per-row vectors rather than scalars."""
    try:
        probe = np.asarray(as_column(dataset, col))
    except (TypeError, ValueError, KeyError):
        return True  # list/fixed_size_list columns as_column can't flatten
    return probe.ndim > 1 or probe.dtype == object


class Evaluator(Params):
    """evaluate(dataset) -> float. Mirrors org.apache.spark.ml.evaluation."""

    def evaluate(self, dataset) -> float:
        raise NotImplementedError

    def isLargerBetter(self) -> bool:
        return True


class _MetricParams(HasLabelCol, HasPredictionCol):
    metricName = ParamDecl("metricName", "metric to compute", TypeConverters.toString)

    def getMetricName(self) -> str:
        return self.getOrDefault(self.metricName)

    def setMetricName(self, value: str):
        return self._set(metricName=value)

    def _columns(self, dataset):
        y = np.asarray(as_column(dataset, self.getLabelCol()), np.float64)
        p = np.asarray(as_column(dataset, self.getPredictionCol()), np.float64)
        return y, p


class RegressionEvaluator(Evaluator, _MetricParams):
    """rmse (default) | mse | mae | r2 — Spark's metric set."""

    _uid_prefix = "RegressionEvaluator"

    def __init__(self, uid=None):
        super().__init__(uid=uid)
        self.setDefault(metricName="rmse", labelCol="label", predictionCol="prediction")

    def evaluate(self, dataset) -> float:
        y, p = self._columns(dataset)
        err = y - p
        name = self.getMetricName()
        if name == "rmse":
            return float(np.sqrt(np.mean(err**2)))
        if name == "mse":
            return float(np.mean(err**2))
        if name == "mae":
            return float(np.mean(np.abs(err)))
        if name == "r2":
            ss_res = float(np.sum(err**2))
            ss_tot = float(np.sum((y - y.mean()) ** 2))
            return 1.0 - ss_res / ss_tot if ss_tot > 0 else 0.0
        raise ValueError(f"unknown regression metric {name!r}")

    def isLargerBetter(self) -> bool:
        return self.getMetricName() == "r2"


class BinaryClassificationEvaluator(Evaluator, _MetricParams, HasRawPredictionCol):
    """areaUnderROC (default) | areaUnderPR over a score column.

    Like Spark, the score is read from ``rawPredictionCol`` (default
    ``rawPrediction``) — a margin/score column emitted by classifiers
    (LogisticRegressionModel.transform writes it). The column may hold a
    per-class vector (the positive-class component is used) or a scalar
    score. If the dataset has no such column, ``predictionCol`` is used as
    a fallback score (hard 0/1 labels then yield the one-threshold AUC).
    """

    _uid_prefix = "BinaryClassificationEvaluator"

    def __init__(self, uid=None):
        super().__init__(uid=uid)
        self.setDefault(
            metricName="areaUnderROC",
            labelCol="label",
            predictionCol="prediction",
            rawPredictionCol="rawPrediction",
        )

    def _score(self, dataset) -> np.ndarray:
        col = self.getRawPredictionCol()
        if not has_column(dataset, col):
            col = self.getPredictionCol()
        raw = as_matrix(dataset, col) if _is_vector_column(dataset, col) else None
        if raw is not None:
            return np.asarray(raw[:, -1], np.float64)
        return np.asarray(as_column(dataset, col), np.float64)

    def evaluate(self, dataset) -> float:
        y = np.asarray(as_column(dataset, self.getLabelCol()), np.float64)
        score = self._score(dataset)
        pos = y > 0.5
        n_pos, n_neg = int(pos.sum()), int((~pos).sum())
        if n_pos == 0 or n_neg == 0:
            return 0.0
        order = np.argsort(score, kind="stable")
        name = self.getMetricName()
        if name == "areaUnderROC":
            # Mann-Whitney U with midrank tie handling.
            ranks = np.empty_like(score)
            ranks[order] = np.arange(1, len(score) + 1, dtype=np.float64)
            uniq, inv, counts = np.unique(score, return_inverse=True, return_counts=True)
            if len(uniq) != len(score):
                sums = np.zeros(len(uniq))
                np.add.at(sums, inv, ranks)
                ranks = sums[inv] / counts[inv]
            u = ranks[pos].sum() - n_pos * (n_pos + 1) / 2.0
            return float(u / (n_pos * n_neg))
        if name == "areaUnderPR":
            desc = order[::-1]
            tp = np.cumsum(pos[desc])
            precision = tp / np.arange(1, len(score) + 1)
            recall = tp / n_pos
            # Trapezoid over recall, prepending (0, 1) as Spark does.
            r = np.concatenate([[0.0], recall])
            pcs = np.concatenate([[1.0], precision])
            return float(np.sum(np.diff(r) * (pcs[1:] + pcs[:-1]) / 2.0))
        raise ValueError(f"unknown binary metric {name!r}")


class MulticlassClassificationEvaluator(Evaluator, _MetricParams):
    """accuracy (default) | f1 (macro-averaged, Spark's weightedFMeasure
    analogue over hard predictions)."""

    _uid_prefix = "MulticlassClassificationEvaluator"

    def __init__(self, uid=None):
        super().__init__(uid=uid)
        self.setDefault(
            metricName="accuracy", labelCol="label", predictionCol="prediction"
        )

    def evaluate(self, dataset) -> float:
        y, p = self._columns(dataset)
        name = self.getMetricName()
        if name == "accuracy":
            return float(np.mean(y == p))
        if name == "f1":
            classes = np.unique(np.concatenate([y, p]))
            weighted = 0.0
            for c in classes:
                tp = float(np.sum((p == c) & (y == c)))
                fp = float(np.sum((p == c) & (y != c)))
                fn = float(np.sum((p != c) & (y == c)))
                prec = tp / (tp + fp) if tp + fp > 0 else 0.0
                rec = tp / (tp + fn) if tp + fn > 0 else 0.0
                f1 = 2 * prec * rec / (prec + rec) if prec + rec > 0 else 0.0
                weighted += f1 * float(np.sum(y == c)) / len(y)
            return weighted
        raise ValueError(f"unknown multiclass metric {name!r}")
