"""True multi-process distributed runtime test.

Spawns 2 OS processes, each with 2 virtual CPU devices, joined through
``jax.distributed`` (our ``initialize_cluster`` wrapper) into one 4-device
job. Each process feeds only its local row slice
(``process_local_rows`` + the multi-process branch of ``shard_rows``,
which assembles the global array with
``jax.make_array_from_process_local_data``); the fitted PCA must match a
single-process fit of the full dataset. This validates the cross-process
psum path (Gloo collectives here; ICI/DCN on real pods) end to end —
coverage the reference has no analogue of (SURVEY.md §4).
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_pca_matches_single_process():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = os.path.join(repo, "tests", "multiproc_worker.py")
    port = _free_port()
    env = {
        k: v
        for k, v in os.environ.items()
        # the workers set their own backend/device config
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "JAX_ENABLE_X64")
    }
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(i), "2", str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            env=env,
            cwd=repo,
        )
        for i in range(2)
    ]
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=240)
        outs.append((p.returncode, out, err))
    for rc, out, err in outs:
        assert rc == 0, f"worker failed rc={rc}\nstdout={out!r}\nstderr={err[-2000:]!r}"

    result = json.loads(outs[0][1].decode().strip().splitlines()[-1])
    assert result["n_rows"] == 603

    # Single-process oracle over the same data.
    rng = np.random.default_rng(0)
    n, d, k = 603, 16, 3
    x = rng.normal(size=(n, d)) * np.logspace(0, -1.0, d)
    from spark_rapids_ml_tpu.models.pca import fit_pca
    from spark_rapids_ml_tpu.parallel.mesh import make_mesh

    ref = fit_pca(x, k=k, mean_center=True, mesh=make_mesh(data=4, model=1))
    np.testing.assert_allclose(
        np.abs(np.asarray(result["pc"])), np.abs(ref.pc), atol=1e-8
    )
    np.testing.assert_allclose(
        np.asarray(result["ev"]), ref.explained_variance, atol=1e-10
    )

    # Multi-host STREAMED fit (uneven per-process batch counts) must also
    # match — the round-1 gap where fit_pca_stream was single-process only.
    assert result["stream_n_rows"] == 603
    np.testing.assert_allclose(
        np.abs(np.asarray(result["stream_pc"])), np.abs(ref.pc), atol=1e-8
    )

    # Multi-host streamed KMeans / LogReg: full-row coverage and sane fits
    # (exact-match oracles live in the single-process stream tests; here
    # the property is that the lockstep multi-host scans converge on the
    # same data they were given).
    assert result["kmeans_n_rows"] == 603
    assert np.asarray(result["kmeans_centers"]).shape == (3, 16)
    assert result["logreg_n_rows"] == 603
    w_true = np.linspace(-1, 1, 16)
    coef = np.asarray(result["logreg_coef"])
    # learned direction correlates strongly with the generating weights
    cos = coef @ w_true / (np.linalg.norm(coef) * np.linalg.norm(w_true))
    assert cos > 0.9

    # Exact KNN across processes: global ids must match a single-process
    # model over the full database.
    from spark_rapids_ml_tpu.models.knn import NearestNeighbors

    nn = NearestNeighbors(mesh=make_mesh(data=4, model=1)).setK(5).fit(
        {"features": x}
    )
    ref_d, ref_i = nn.kneighbors(x[:7])
    np.testing.assert_array_equal(np.asarray(result["knn_idx"]), ref_i)
    np.testing.assert_allclose(np.asarray(result["knn_d"]), ref_d, atol=1e-8)
