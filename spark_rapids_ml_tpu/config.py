"""Runtime configuration (tier-2 flags).

The reference has a three-tier config system (SURVEY.md §5): algorithm params
(Spark ML Params — see core/params.py), runtime/cluster flags (Spark conf keys
like ``spark.rapids.sql.enabled``), and build flags. This module is the tier-2
equivalent: process-wide runtime knobs, settable programmatically or via
environment variables prefixed ``SRML_TPU_``.

Reference citations: spark conf tier at README.md:103-113 and
RapidsMLTest.scala:23-25 in /root/reference.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, Optional


def _env(name: str, default: Any, cast: Callable[[str], Any],
         prefix: str = "SRML_TPU_") -> Any:
    raw = os.environ.get(prefix + name)
    if raw is None:
        return default
    try:
        return cast(raw)
    except (TypeError, ValueError):
        return default


def _env_named(name: str, default: Any, cast: Callable[[str], Any]) -> Any:
    """Deployment-facing env keys carry their FULL name (no SRML_TPU_
    prefix): SRML_DAEMON_STATE_DIR, SRML_RUN_JOURNAL, SRML_SERVE_* —
    the knobs an operator sets on a daemon host, not a tuning flag."""
    return _env(name, default, cast, prefix="")


def _as_bool(s: str) -> bool:
    return s.strip().lower() in ("1", "true", "yes", "on")


def _as_bool_or_auto(s: str):
    return "auto" if s.strip().lower() == "auto" else _as_bool(s)


def _backend_is_tpu() -> bool:
    try:
        import jax

        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover - backend init failure
        return False


# Lazy resolution of "auto" defaults at get() time: the shipped TPU profile
# IS the measured configuration (bf16 MXU compute / f32 accumulation /
# Pallas kernels) — a fresh checkout on a real TPU reproduces the headline
# bench numbers with zero env vars, while CPU meshes (tests, dev boxes)
# resolve to the portable f32/XLA path unchanged. Explicit values (set()
# or SRML_TPU_* env) always win over "auto".
_AUTO_RESOLVERS: Dict[str, Callable[[], Any]] = {
    "use_pallas": _backend_is_tpu,
    "compute_dtype": lambda: "bfloat16" if _backend_is_tpu() else "float32",
}

# One visible breadcrumb per process when an "auto" key flips to the TPU
# profile (round-4 advisor): default model precision on TPU diverges from
# the float32 path CPU CI validates, and an upgrading user should see that
# happened in the logs rather than discover it in the numerics. Set
# SRML_TPU_COMPUTE_DTYPE=float32 for full-precision parity runs (bench.py
# runs exactly that parity check on the real chip every round).
_auto_announced: set = set()


def _announce_auto(key: str, value: Any) -> None:
    if key in _auto_announced:
        return
    _auto_announced.add(key)
    if (key, value) in (("compute_dtype", "bfloat16"), ("use_pallas", True)):
        from spark_rapids_ml_tpu.utils.logging import get_logger

        get_logger("config").info(
            "config %r auto-resolved to %r (TPU backend detected; the "
            "measured TPU profile). Set SRML_TPU_%s explicitly for the "
            "portable float32/XLA behavior.", key, value, key.upper(),
        )


_DEFAULTS: Dict[str, Any] = {
    # Master switch, analogous to spark.rapids.sql.enabled: when False all
    # estimators run their host (numpy) fallback path.
    "enabled": _env("ENABLED", True, _as_bool),
    # Accumulation dtype for Gram/centroid reductions. float64 gives parity
    # with the reference's double-precision cuBLAS path; float32 is the fast
    # TPU-native mode (MXU). (SURVEY.md §7 hard part (c).)
    "accum_dtype": _env("ACCUM_DTYPE", "float32", str),
    # Compute dtype for the big GEMMs; bfloat16 engages the MXU at full
    # rate. "auto" (default) = bfloat16 on a real TPU backend, float32
    # elsewhere — the measured TPU profile ships as the default. Set
    # "float32" explicitly for full-precision parity runs on TPU.
    "compute_dtype": _env("COMPUTE_DTYPE", "auto", str),
    # Default mesh axis sizes; None = use all local devices on the data axis.
    "mesh_data_axis": _env("MESH_DATA_AXIS", None, int),
    "mesh_model_axis": _env("MESH_MODEL_AXIS", 1, int),
    # On-mesh collective reduce for multi-daemon fits (docs/mesh.md): when
    # every daemon a pass fed is a co-resident mesh member (one JAX
    # runtime), per-shard partials fold on the device plane via the
    # `reduce_mesh` op instead of the driver export/merge hub. False
    # forces the hub path everywhere (the degraded mode the parity tests
    # pin against the collective path bitwise).
    "mesh_collectives": _env("MESH_COLLECTIVES", True, _as_bool),
    # Persistent XLA compilation cache directory (ROADMAP 2b): wired to
    # jax.config.compilation_cache_dir at package init, so identical
    # programs compiled by an earlier process (a restarted daemon, the
    # next bench round, a fleet twin) are disk hits instead of
    # recompiles. None = off. Env key is SRML_COMPILE_CACHE_DIR —
    # deployment-facing like SRML_DAEMON_STATE_DIR, hence no SRML_TPU_
    # prefix. Persistent-cache hits are counted by
    # srml_xla_persistent_cache_hits_total (utils/xprof.py).
    "compile_cache_dir": os.environ.get("SRML_COMPILE_CACHE_DIR") or None,
    # Max rows per device batch when streaming host data to device.
    "stream_batch_rows": _env("STREAM_BATCH_ROWS", 1 << 20, int),
    # Use the native C++ columnar bridge if the shared library is present.
    "use_native_bridge": _env("USE_NATIVE_BRIDGE", True, _as_bool),
    # Emit profiler trace annotations (NVTX-range equivalent; SURVEY.md §5).
    "tracing": _env("TRACING", False, _as_bool),
    # Metrics registry master switch (utils/metrics.py): False turns every
    # counter/gauge/histogram record into an early return. Exposition and
    # snapshots only ever run on demand (the daemon `metrics` op).
    "metrics": _env("METRICS", True, _as_bool),
    # Run-journal output path (utils/journal.py): JSON-lines of run/phase
    # events fed by trace_span. None = off (zero overhead: no event dicts,
    # no I/O). Env key is SRML_RUN_JOURNAL — deployment-facing like
    # SRML_DAEMON_ADDRESS / SRML_FAULT_PLAN, hence no SRML_TPU_ prefix.
    "run_journal": os.environ.get("SRML_RUN_JOURNAL") or None,
    # Journal file rotation (utils/journal.py): when > 0, the journal
    # rotates logrotate-style (path → path.1 → …) before a line would
    # cross the byte cap; run_journal_keep rotated segments are
    # retained. 0 = unbounded append (REQUIRED when several processes
    # share one journal path — rotation is single-writer).
    "run_journal_max_bytes": _env_named("SRML_RUN_JOURNAL_MAX_BYTES", 0, int),
    "run_journal_keep": _env_named("SRML_RUN_JOURNAL_KEEP", 4, int),
    # Jit-ledger device timing mode (utils/xprof.py): every ledgered jit
    # call is bracketed with block_until_ready so per-call execution
    # wall-clock (and thus achieved flops/s and bytes/s) is measurable.
    # OFF by default — it serializes async dispatch, a measurement mode,
    # not a production state. Env key is SRML_DEVICE_TIMING:
    # deployment-facing (an operator flips it on a live daemon host to
    # diagnose), hence no SRML_TPU_ prefix.
    "device_timing": _env_named("SRML_DEVICE_TIMING", False, _as_bool),
    # Use Pallas kernels for hot ops (Gram, pairwise distance) on TPU.
    # "auto" (default) = on iff the backend is a real TPU (the per-kernel
    # shape/dtype gates still apply — see _pallas_backend_ok and friends).
    "use_pallas": _env("USE_PALLAS", "auto", _as_bool_or_auto),
    # Feature-sharded Gram algorithm: "allgather" (one ICI all_gather of the
    # full feature width per device) or "ring" (ppermute pipeline — one
    # block in flight, for feature dims too large to gather). "auto" =
    # allgather (ring wins when m_local*d doesn't fit alongside the data).
    "gram_algorithm": _env("GRAM_ALGORITHM", "auto", str),
    # Where the d×d eigendecomposition finalize runs: "auto" = on-device for
    # CPU meshes, host LAPACK (float64) for TPU ("device"/"host" force it).
    # The Gram reduction — the part that scales with data — always runs on
    # device; eigh on TPU is an iterative algorithm XLA executes poorly for
    # large d, while the d×d Gram is tiny to fetch.
    "finalize": _env("FINALIZE", "auto", str),
    # Eigensolver for the finalize: "full" = exact d×d eigh (host LAPACK on
    # TPU per `finalize`), "randomized" = on-device blocked subspace
    # iteration (Halko-style; MXU matmuls only, nothing but (d, k+p) panels
    # factorized — the TPU-fast path for large d with decaying spectra).
    "solver": _env("SOLVER", "full", str),
    # IVF bucketed-query shortlist multiplier: per-(list, slot) shortlist
    # width = mult·k, exact-rerank pool = 2·mult·k. The recall/speed dial
    # at bfloat16 compute (clustered 128-d measurement, recall@10 vs the
    # f32 scan's 0.99 ceiling): 2 → 0.92 at ~115k q/s/chip; 4 → 0.98 at
    # ~65k. f32 compute reaches the ceiling already at 2.
    "ann_shortlist_mult": _env("ANN_SHORTLIST_MULT", 2, int),
    # IVF bucketed-query exact rerank: re-score the 2·mult·k shortlist from
    # the raw f32 rows. Skipping it ("off") answers straight from the
    # residual-identity scores — measured 1.3–1.8× q/s for 0.005–0.017
    # recall@10 (1.8× / −0.017 at the clustered 768-d bench shape; the
    # (q, R, d) raw-row gather is the single most expensive post-scan op).
    # Keep "on" when bf16 score noise matters more than throughput.
    "ann_rerank": _env("ANN_RERANK", True, lambda v: str(v).lower() not in ("0", "false", "off")),
    # Exact-rerank shortlist width, in units of k: the rerank rescores the
    # R = ann_rerank_width*k best approximate candidates from the raw f32
    # rows ((q, R, d) gather — the dominant rerank cost). 0 = auto:
    # 2*ann_shortlist_mult on the XLA scan (sized for its approx-selection
    # noise), ann_shortlist_mult on the fused kernel (exact selection —
    # the same-run width sweep measured identical recall at half the
    # width; benchmarks/README.md).
    "ann_rerank_width": _env("ANN_RERANK_WIDTH", 0, int),
    # Fused-kernel per-(list, slot) extraction width under rerank:
    # "auto" (default) = ceil(1.2·k) — the round-5 measured frontier
    # point (177k q/s @ recall@10 0.9700 vs "wide"'s 153k @ 0.9706 at
    # the bench shape: the rerank's R = 2k selection caps what wider
    # extraction can feed it). "wide" = shortlist_mult·k, "narrow" = k
    # (183k @ 0.9577), an integer = width in rows. Rerank-off configs
    # always extract k; benchmarks/README.md round-5 frontier.
    "ann_extract": _env("ANN_EXTRACT", "auto", str),
    # Data-plane daemon backpressure watermarks (serve/daemon.py; 0 =
    # unlimited). Past either, the daemon answers heavy ops with `busy` +
    # a retry_after_s hint (graceful degradation) instead of accepting
    # work it will thrash on; pressure-relieving ops always pass.
    "daemon_max_connections": _env("DAEMON_MAX_CONNECTIONS", 0, int),
    "daemon_max_staged_bytes": _env("DAEMON_MAX_STAGED_BYTES", 0, int),
    # The retry hint (seconds) a shed client is told to wait; clients
    # jitter around it so a shed fleet doesn't return as one wave.
    "daemon_retry_after_s": _env("DAEMON_RETRY_AFTER_S", 1.0, float),
    # Durable daemon job state (serve/daemon.py): a directory where the
    # daemon write-ahead-snapshots iterative jobs at pass boundaries
    # (iterate + pass counter + creation params; atomic tmp+rename via
    # core/checkpoint.py) and persists its instance identity, so a
    # crashed-and-restarted daemon resurrects its jobs instead of
    # failing every in-flight fit. None = off — the zero-overhead
    # default (no snapshot writes, no restore lookups). Env key is
    # SRML_DAEMON_STATE_DIR: deployment-facing like SRML_RUN_JOURNAL /
    # SRML_DAEMON_ADDRESS, hence no SRML_TPU_ prefix.
    "daemon_state_dir": os.environ.get("SRML_DAEMON_STATE_DIR") or None,
    # Serving scheduler (serve/scheduler.py; docs/protocol.md "Serving
    # scheduler"): cross-connection micro-batching for transform/
    # kneighbors. ON by default since the fleet PR — batched results are
    # bitwise-identical to solo serving (the PR 5 matrix + the protocol
    # goldens replayed as the burn-in), so the only observable change is
    # higher QPS under concurrency. SRML_SERVE_BATCHING=0 is the
    # documented opt-out for single-caller deployments that prefer zero
    # batching-window latency. Env keys are deployment-facing
    # (SRML_SERVE_*), like SRML_DAEMON_STATE_DIR.
    "serve_batching": _env_named("SRML_SERVE_BATCHING", True, _as_bool),
    # Max milliseconds a queued request waits for co-batchable traffic
    # before its micro-batch dispatches anyway.
    "serve_batch_window_ms": _env_named(
        "SRML_SERVE_BATCH_WINDOW_MS", 2.0, float
    ),
    # Row cap per dispatched micro-batch, floored to a boundary of the
    # bucket ladder below (a batch coalesced past one would pad UP to
    # the next bucket, dispatching more device rows than the cap).
    "serve_max_batch_rows": _env_named("SRML_SERVE_MAX_BATCH_ROWS", 4096, int),
    # The bucket ladder (comma-separated ascending row counts): batches
    # are padded UP to the smallest bucket that fits, so jit
    # compilations per served model are bounded by the ladder length —
    # the padded rows are masked out of every result (bitwise-equal to
    # solo requests). Single requests larger than the top bucket bypass
    # the scheduler and dispatch solo.
    "serve_batch_buckets": _env_named(
        "SRML_SERVE_BATCH_BUCKETS", "64,256,1024,4096", str
    ),
    # Run the scheduler's bucket-ladder warmup pre-compile AT model
    # registration (ensure_model) instead of waiting for an explicit
    # client `warmup` call: first-request compile leaves the latency
    # path entirely. Only meaningful with serve_batching on; a warmup
    # failure degrades to lazy compiles, never fails the registration.
    "serve_warmup_on_register": _env_named(
        "SRML_SERVE_WARMUP_ON_REGISTER", False, _as_bool
    ),
    # True AOT at registration (docs/protocol.md "AOT at registration"):
    # when a warmup runs (warmup-on-register or the `warmup` op), models
    # that publish a `_serve_aot_plan` have their serving programs
    # `lower().compile()`d and the executables HELD on the served
    # instance — nothing executes, no zero-batch dispatches, and a
    # serving call at a primed shape runs the held executable directly
    # (zero compiles, zero jit-cache traces on the latency path). Models
    # without a plan (and shapes outside the ladder) degrade to the
    # trace-warmup/lazy-compile behavior. The warmup ack's `aot` field
    # reports which mode ran.
    "serve_aot": _env_named("SRML_SERVE_AOT", True, _as_bool),
    # Admission bound: max queued requests per served model; overflow
    # (and requests whose deadline the backlog would miss) are shed with
    # the busy/retry_after_s contract instead of queueing to death.
    "serve_queue_depth": _env_named("SRML_SERVE_QUEUE_DEPTH", 256, int),
    # Fleet serving (serve/fleet.py + serve/router.py; docs/protocol.md
    # "Fleet & versioned serving"). Env keys are deployment-facing
    # (SRML_FLEET_* / SRML_SERVE_*), like SRML_DAEMON_STATE_DIR.
    # How stale a replica's polled `health` snapshot may be before the
    # router re-polls it (also the dead-replica re-probe interval).
    "fleet_health_poll_s": _env_named("SRML_FLEET_HEALTH_POLL_S", 1.0, float),
    # Max replicas one request may try before it is declared unroutable
    # (busy/dead replicas are skipped toward the next candidate).
    # 0 = one attempt per fleet member.
    "fleet_failover_attempts": _env_named(
        "SRML_FLEET_FAILOVER_ATTEMPTS", 0, int
    ),
    # Virtual nodes per replica on the consistent-hash ring: more
    # vnodes = smoother key spread, slightly larger ring.
    "fleet_vnodes": _env_named("SRML_FLEET_VNODES", 64, int),
    # How long a rollout waits for the retired version's in-flight
    # requests to finish before dropping its registrations; a timeout
    # leaves them registered (memory) rather than yanking arrays out
    # from under a live request (correctness).
    "fleet_drain_timeout_s": _env_named(
        "SRML_FLEET_DRAIN_TIMEOUT_S", 30.0, float
    ),
    # Fleet gossip plane (serve/gossip.py; docs/protocol.md "Fleet
    # gossip & bootstrap"): daemons exchange FleetViews — replica
    # records + per-model version tables — so fleet state survives any
    # client's death. Env keys are deployment-facing (SRML_GOSSIP_* /
    # SRML_FLEET_*), like SRML_DAEMON_STATE_DIR.
    # Seconds between gossip ticks (each tick pushes this daemon's view
    # to gossip_fanout peers and merges theirs back). 0 (default) = no
    # gossip thread — the view still exists and answers gossip_pull /
    # merges gossip_push, so control planes that push synchronously
    # (ModelFleet) work without any background traffic.
    "gossip_interval_s": _env_named("SRML_GOSSIP_INTERVAL_S", 0.0, float),
    # Peers contacted per tick. Convergence is bounded by
    # gossip_interval_s × ring-diameter; fanout ≥ 2 keeps the diameter
    # O(log N).
    "gossip_fanout": _env_named("SRML_GOSSIP_FANOUT", 2, int),
    # How long retired-replica/version tombstones keep gossiping before
    # they are pruned; must exceed any plausible partition length or a
    # healed island could resurrect a retired record. 0 = keep forever.
    "gossip_tombstone_ttl_s": _env_named(
        "SRML_GOSSIP_TOMBSTONE_TTL_S", 600.0, float
    ),
    # Comma-separated seed daemon addresses ("host:port,...") a client
    # bootstraps its routing table from — ONE reachable seed suffices;
    # the pulled FleetView names the rest of the fleet. None = no
    # seeds configured (FleetClient.from_seeds requires an explicit
    # argument then). Also settable per Spark session via
    # spark.srml.fleet.seed_addresses (spark/daemon_session.py).
    "fleet_seed_addresses": _env_named(
        "SRML_FLEET_SEED_ADDRESSES", None, str
    ),
    # Versioned-serving fence (serve/daemon.py): a serving request
    # whose additive `version` field disagrees with the registration's
    # pinned version is refused (True, default) or answered with a
    # warning (False — debugging only; the answer is the WRONG model's).
    "serve_version_strict": _env_named(
        "SRML_SERVE_VERSION_STRICT", True, _as_bool
    ),
    # Serve autoscaler (serve/autoscaler.py; docs/protocol.md "Serve
    # autoscaler"): a control loop over telemetry the fleet already
    # emits (scheduler queue depth + sheds, replica busy state, routed
    # p99) that scales the replica set through the register→warm→flip→
    # drain rollout — scale-down never drops an in-flight request. Env
    # keys are deployment-facing (SRML_AUTOSCALE_*), like SRML_FLEET_*.
    # Scale UP when queued requests per live replica crosses this.
    "autoscale_high_watermark": _env_named(
        "SRML_AUTOSCALE_HIGH_WATERMARK", 8.0, float
    ),
    # Scale DOWN when queued requests per live replica falls below this
    # (the gap to the high watermark is the hysteresis band — a load
    # that sits between the two never trips an action).
    "autoscale_low_watermark": _env_named(
        "SRML_AUTOSCALE_LOW_WATERMARK", 1.0, float
    ),
    # Minimum seconds between ACTIONS: a load flapping at a watermark
    # trips at most one scale per cooldown window.
    "autoscale_cooldown_s": _env_named("SRML_AUTOSCALE_COOLDOWN_S", 30.0, float),
    # Control-loop poll interval.
    "autoscale_tick_s": _env_named("SRML_AUTOSCALE_TICK_S", 2.0, float),
    # Replica-count floor/ceiling the loop may never cross.
    "autoscale_min_replicas": _env_named("SRML_AUTOSCALE_MIN_REPLICAS", 1, int),
    "autoscale_max_replicas": _env_named("SRML_AUTOSCALE_MAX_REPLICAS", 8, int),
    # Optional latency objective: routed p99 (estimated from the
    # srml_router_request_seconds histogram) above this forces a
    # high-watermark verdict even at a quiet queue. 0 = off.
    "autoscale_p99_deadline_s": _env_named(
        "SRML_AUTOSCALE_P99_DEADLINE_S", 0.0, float
    ),
    # --- Telemetry plane (docs/observability.md, docs/protocol.md
    # "Telemetry plane ops"). Env keys are deployment-facing (SRML_*),
    # like SRML_SERVE_*. ---
    # In-memory journal-event ring the daemon arms at start
    # (utils/journal.py ring_arm): the event source for the `trace_pull`
    # wire op and the flight recorder, independent of any journal FILE.
    # 0 disables both (trace_pull answers empty, incident bundles carry
    # no spans).
    "telemetry_trace_buffer": _env_named(
        "SRML_TELEMETRY_TRACE_BUFFER", 4096, int
    ),
    # Histogram exemplar freshness window (utils/metrics.py): per bucket,
    # the worst exemplared sample of the last window is kept; an older
    # exemplar yields the slot to the next sample regardless of value.
    "telemetry_exemplar_window_s": _env_named(
        "SRML_TELEMETRY_EXEMPLAR_WINDOW_S", 60.0, float
    ),
    # Daemon telemetry-evaluation cadence: the background thread that
    # snapshots metrics, evaluates SLO burn rates (utils/slo.py), and
    # checks flight-recorder trigger conditions. 0 disables the thread
    # (SLO gauges and automatic incident capture off; telemetry_pull /
    # trace_pull still answer).
    "telemetry_eval_interval_s": _env_named(
        "SRML_TELEMETRY_EVAL_INTERVAL_S", 1.0, float
    ),
    # Declared per-op SLOs (utils/slo.py), semicolon-separated:
    # "<op>:<kind>[=<target>]@<budget>" with kind ∈ p99_ms|error|shed,
    # e.g. "transform:p99_ms=50@0.01;transform:error@0.001". Empty = no
    # objectives, nothing evaluated.
    "slo_objectives": _env_named("SRML_SLO_OBJECTIVES", "", str),
    # Multi-window burn-rate windows (SRE convention: BOTH windows must
    # burn above slo_burn_threshold to breach — the fast window catches
    # it quickly, the slow window debounces blips).
    "slo_fast_window_s": _env_named("SRML_SLO_FAST_WINDOW_S", 60.0, float),
    "slo_slow_window_s": _env_named("SRML_SLO_SLOW_WINDOW_S", 300.0, float),
    # Burn-rate breach threshold: burning budget at ≥ this multiple of
    # the sustainable rate in both windows raises srml_slo_breach (and
    # the flight-recorder trigger).
    "slo_burn_threshold": _env_named("SRML_SLO_BURN_THRESHOLD", 14.4, float),
    # Flight recorder (utils/flight.py): incident bundles land in
    # state_dir/incidents/, newest-first, capped at this many (oldest
    # deleted). 0 disables dumping entirely.
    "incident_max_bundles": _env_named("SRML_INCIDENT_MAX_BUNDLES", 16, int),
    # Debounce: minimum seconds between bundles for the SAME trigger
    # reason — a sustained storm yields one bundle per window, not one
    # per tick.
    "incident_min_interval_s": _env_named(
        "SRML_INCIDENT_MIN_INTERVAL_S", 30.0, float
    ),
    # Automatic trigger thresholds, evaluated per telemetry tick as
    # RATES (events/second over the tick window). 0 = trigger off.
    "incident_shed_rate": _env_named("SRML_INCIDENT_SHED_RATE", 0.0, float),
    "incident_deadline_rate": _env_named(
        "SRML_INCIDENT_DEADLINE_RATE", 0.0, float
    ),
    # Dump a bundle on fatal teardown (SIGTERM / atexit while a recorder
    # is armed). Off by default: test daemons exit constantly and a
    # bundle per clean exit is noise; production supervisors flip it on.
    "incident_on_fatal": _env_named("SRML_INCIDENT_ON_FATAL", False, _as_bool),
    # Served-model registry cap (0 = unbounded): past it, the least-
    # recently-used re-creatable registration is evicted (clients
    # re-register on miss); daemon-built KNN indexes are evicted only
    # when nothing re-creatable remains. The LRU twin of the TTL reaper
    # — a long-lived daemon cannot grow its model registry without
    # bound even when no TTL is configured.
    "daemon_max_models": _env("DAEMON_MAX_MODELS", 0, int),
    # Bounded fit-level pass-replay budget for the Spark estimators
    # (spark/estimator.py): how many times one pass-boundary unit (scan
    # + step / finalize) may be replayed after a daemon incarnation
    # change before the failure surfaces. 0 = off: a restart mid-fit
    # fails loudly with the split-brain error instead of healing.
    # Overridable per session via $SRML_FIT_RECOVERY_ATTEMPTS /
    # spark.srml.fit.recovery_attempts (spark/daemon_session.py).
    "fit_recovery_attempts": _env("FIT_RECOVERY_ATTEMPTS", 0, int),
    # Elastic-fit death policy (spark/estimator.py; docs/protocol.md
    # "Permanent daemon loss"): how many PEER daemons one fit may declare
    # permanently dead and amputate — quarantining the daemon, rewinding
    # survivors to the last pass boundary, and rerunning the scan with
    # the dead daemon's partitions rerouted. 0 (default) = off: a lost
    # daemon fails the fit loudly, byte-for-byte today's behavior, and
    # no classification probe ever runs. Overridable per session via
    # $SRML_FIT_DAEMON_LOSS_TOLERANCE / spark.srml.fit.daemon_loss_tolerance
    # (spark/daemon_session.py).
    "fit_daemon_loss_tolerance": _env("FIT_DAEMON_LOSS_TOLERANCE", 0, int),
    # The death deadline: a peer implicated in a failed pass is probed
    # with this as its TOTAL reconnect/healing budget, and escalates from
    # *retrying* to *declared dead* only when the whole budget is
    # exhausted — a slow or busy daemon that answers within it is never
    # amputated on a hunch. Overridable via
    # $SRML_FIT_DAEMON_DEATH_TIMEOUT_S /
    # spark.srml.fit.daemon_death_timeout_s.
    "fit_daemon_death_timeout_s": _env(
        "FIT_DAEMON_DEATH_TIMEOUT_S", 15.0, float
    ),
    # Elastic-fit GROW policy (spark/estimator.py; docs/protocol.md
    # "Mid-fit daemon join") — the inverse direction of the death policy
    # above: whether a daemon that appears MID-FIT (Spark dynamic
    # allocation granting an executor, a spot host coming up) may be
    # admitted into a running fit. "off" (default) keeps today's
    # contract byte-for-byte: an unlisted peer fails its tasks loudly
    # (centers/iterate unseeded) and no discovery probe ever runs.
    # "boundary" admits new daemons at the NEXT pass boundary only —
    # never mid-pass — by seeding them with the ledger's boundary
    # iterate, so grown fits stay bitwise-equal to a static-topology
    # fit. Env keys are deployment-facing (SRML_FIT_*), like
    # SRML_SERVE_*; also via spark.srml.fit.daemon_join_policy.
    "fit_daemon_join_policy": _env_named(
        "SRML_FIT_DAEMON_JOIN_POLICY", "off", str
    ),
    # Join budget: how many daemons one fit may admit mid-fit. A newly
    # configured daemon past the budget fails the fit loudly (the loss-
    # tolerance contract, mirrored) instead of silently staying outside
    # the topology while executors route rows at it. Also via
    # $SRML_FIT_DAEMON_JOIN_LIMIT / spark.srml.fit.daemon_join_limit.
    "fit_daemon_join_limit": _env_named(
        "SRML_FIT_DAEMON_JOIN_LIMIT", 2, int
    ),
    # Histogram tree ensembles (models/random_forest.py; docs/protocol.md
    # "The `rf` job algo"). Env keys are deployment-facing (SRML_FOREST_*),
    # like SRML_SERVE_*.
    # Row cap on the driver-side prefix sample that trains the quantile
    # bin-edge sketch (the kmeans init_sample_rows twin): the edges are
    # part of the model iterate, so every daemon bins identically.
    "forest_seed_sample_rows": _env_named(
        "SRML_FOREST_SEED_SAMPLE_ROWS", 65536, int
    ),
    # Per-device budget (MiB) for one frontier's (tree, node, feature,
    # bin, stat) histogram tensor — over it, the fit refuses at the pass
    # boundary that would allocate it (ForestCapacityError; the forest
    # twin of SRML_GRAM_DEVICE_BUDGET_MB), never a mid-pass OOM. 0 =
    # unbounded.
    "forest_hist_budget_mb": _env_named(
        "SRML_FOREST_HIST_BUDGET_MB", 256, int
    ),
    # Fused Pallas scan+selection kernel for the bucketed IVF query
    # (ops/pallas_kernels.py ivf_scan_select_pallas): the per-list residual
    # GEMM and an EXACT per-slot top-k run in one kernel, scores
    # VMEM-resident. "auto" = on when the backend is TPU and the per-list
    # tile fits VMEM (the XLA einsum+approx_min_k scan is the portable
    # fallback); "on" forces it (interpret mode off-TPU — used by tests);
    # "off" forces the XLA scan. Precision: the kernel's exact selection
    # packs ids into the low mantissa bits of the f32 score key, so with
    # ann_rerank=off the returned DISTANCES are floored to ~24-ceil(log2
    # maxlen) mantissa bits (ids exact; rerank=on recomputes true f32
    # distances). Force "off" for full-f32 rerank-off values.
    "ann_fused_scan": _env("ANN_FUSED_SCAN", "auto", str),
}

_lock = threading.Lock()
_conf: Dict[str, Any] = dict(_DEFAULTS)


def get(key: str) -> Any:
    """Get a runtime config value ("auto" keys resolve per backend)."""
    value = get_raw(key)
    if value == "auto" and key in _AUTO_RESOLVERS:
        value = _AUTO_RESOLVERS[key]()
        _announce_auto(key, value)
    return value


def get_raw(key: str) -> Any:
    """Get the stored value without "auto" resolution (option/save-restore)."""
    with _lock:
        if key not in _conf:
            raise KeyError(f"unknown config key: {key!r} (known: {sorted(_conf)})")
        return _conf[key]


def peek(key: str) -> Any:
    """LOCK-FREE read for per-record hot paths (metrics/journal gates):
    a single dict lookup, atomic under the GIL, no "auto" resolution and
    no unknown-key check. Callers must pass a key that exists and is
    never "auto" — anything else belongs on :func:`get`."""
    return _conf.get(key)


def set(key: str, value: Any) -> None:  # noqa: A003 - mirrors SparkConf.set
    """Set a runtime config value."""
    with _lock:
        if key not in _conf:
            raise KeyError(f"unknown config key: {key!r} (known: {sorted(_conf)})")
        _conf[key] = value


def reset() -> None:
    """Restore defaults (mainly for tests)."""
    with _lock:
        _conf.clear()
        _conf.update(_DEFAULTS)


def fingerprint() -> str:
    """Stable short hash of the CURRENT config (raw values, no "auto"
    resolution — the fingerprint must not touch a backend). Two
    processes answering ``telemetry_pull`` with different fingerprints
    are running different effective configs — the first thing to check
    when one replica of a fleet misbehaves. Incident bundles
    (utils/flight.py) carry it for the same reason."""
    import hashlib
    import json as _json

    with _lock:
        items = sorted(_conf.items())
    blob = _json.dumps(items, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:16]


class option:
    """Context manager to temporarily override a config value."""

    def __init__(self, key: str, value: Any):
        self._key = key
        self._value = value
        self._saved: Optional[Any] = None

    def __enter__(self) -> "option":
        self._saved = get_raw(self._key)  # preserve "auto", don't bake it
        set(self._key, self._value)
        return self

    def __exit__(self, *exc: Any) -> None:
        set(self._key, self._saved)
