"""Native columnar library tests (libsrml_tpu.so via ctypes).

Builds the library with `make -C native` if missing; skips if no toolchain.
Every native function is differential-tested against its NumPy equivalent.
"""

import os
import subprocess

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SO = os.path.join(REPO, "native", "build", "libsrml_tpu.so")


@pytest.fixture(scope="session")
def native_lib():
    if not os.path.exists(SO):
        try:
            subprocess.run(
                ["make", "-C", os.path.join(REPO, "native")],
                check=True,
                capture_output=True,
                timeout=120,
            )
        except (subprocess.SubprocessError, FileNotFoundError) as e:
            pytest.skip(f"cannot build native library: {e}")
    from spark_rapids_ml_tpu.bridge import native

    lib = native.get_lib()
    if lib is None:
        pytest.skip("native library failed to load")
    return native


def test_abi_version(native_lib):
    assert native_lib.get_lib().srml_abi_version() == 1


def test_flatten_f64(native_lib, rng):
    n, d = 1000, 17
    values = rng.normal(size=n * d)
    offsets = np.arange(0, (n + 1) * d, d, dtype=np.int64)
    out = native_lib.flatten_ragged(values, offsets, d)
    np.testing.assert_array_equal(out, values.reshape(n, d))


def test_flatten_f32(native_lib, rng):
    n, d = 64, 5
    values = rng.normal(size=n * d).astype(np.float32)
    offsets = np.arange(0, (n + 1) * d, d, dtype=np.int64)
    out = native_lib.flatten_ragged(values, offsets, d)
    assert out.dtype == np.float32
    np.testing.assert_array_equal(out, values.reshape(n, d))


def test_flatten_with_nonzero_start(native_lib, rng):
    # Offsets not starting at 0 (sliced window into the child buffer).
    d = 4
    values = rng.normal(size=40)
    offsets = np.array([8, 12, 16, 20], dtype=np.int64)  # 3 rows
    out = native_lib.flatten_ragged(values, offsets, d)
    np.testing.assert_array_equal(out, values[8:20].reshape(3, d))


def test_flatten_ragged_rejected(native_lib):
    values = np.arange(7, dtype=np.float64)
    offsets = np.array([0, 3, 7], dtype=np.int64)  # widths 3, 4
    assert native_lib.flatten_ragged(values, offsets, 3) is None


def test_cast_f64_to_f32(native_lib, rng):
    x = rng.normal(size=(501, 33))
    out = native_lib.cast_f64_to_f32(x)
    assert out.dtype == np.float32
    np.testing.assert_array_equal(out, x.astype(np.float32))


def test_concat_chunks(native_lib, rng):
    chunks = [rng.normal(size=(n, 6)) for n in (10, 1, 300)]
    out = native_lib.concat_chunks_f64(chunks)
    np.testing.assert_array_equal(out, np.concatenate(chunks))


def test_concat_chunks_mismatched_width(native_lib, rng):
    assert (
        native_lib.concat_chunks_f64(
            [rng.normal(size=(3, 4)), rng.normal(size=(3, 5))]
        )
        is None
    )


def test_sharding_uses_native_cast(native_lib, mesh8, rng):
    # End-to-end: shard_rows with dtype float32 on float64 input.
    from spark_rapids_ml_tpu.parallel.sharding import shard_rows

    x = rng.normal(size=(100, 8))
    xs, mask, n = shard_rows(x, mesh8, dtype=np.float32)
    assert n == 100
    got = np.asarray(xs)[:100]
    np.testing.assert_array_equal(got, x.astype(np.float32))
