"""Core framework layer: Spark-ML-contract params/estimators, dataset
abstraction, and model persistence.

This is the TPU build's equivalent of the reference's L5/L6 layers
(SURVEY.md §1): the Estimator/Model/Params machinery of
``org.apache.spark.ml`` that RapidsPCA.scala plugs into.
"""

from spark_rapids_ml_tpu.core.params import (
    Param,
    Params,
    Estimator,
    Model,
    TypeConverters,
    HasInputCol,
    HasOutputCol,
    HasLabelCol,
    HasPredictionCol,
    HasFeaturesCol,
    HasSeed,
    HasTol,
    HasMaxIter,
    HasRegParam,
    HasElasticNetParam,
    HasFitIntercept,
)
from spark_rapids_ml_tpu.core.dataset import (
    as_matrix,
    as_column,
    with_column,
    num_rows,
)
from spark_rapids_ml_tpu.core.persistence import (
    DefaultParamsWriter,
    DefaultParamsReader,
    MLWriter,
    MLReader,
)

__all__ = [
    "Param",
    "Params",
    "Estimator",
    "Model",
    "TypeConverters",
    "HasInputCol",
    "HasOutputCol",
    "HasLabelCol",
    "HasPredictionCol",
    "HasFeaturesCol",
    "HasSeed",
    "HasTol",
    "HasMaxIter",
    "HasRegParam",
    "HasElasticNetParam",
    "HasFitIntercept",
    "as_matrix",
    "as_column",
    "with_column",
    "num_rows",
    "DefaultParamsWriter",
    "DefaultParamsReader",
    "MLWriter",
    "MLReader",
]
