"""Executor-fed distributed fit through the TPU-host data-plane daemon.

Emulates N Spark tasks (threads here; real tasks connect over the
network) streaming Arrow partitions, then finalizes PCA on the driver.
Iterative algorithms use the same wire protocol with one scan per
iteration and a step() call at each pass boundary.
"""

import os
import sys

if __package__ in (None, ""):  # runnable without installation
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import threading

import numpy as np

from spark_rapids_ml_tpu.serve import DataPlaneClient, DataPlaneDaemon

rng = np.random.default_rng(0)
data = (rng.normal(size=(200_000, 128)) * np.logspace(0, -1.5, 128)).astype(np.float32)
parts = np.array_split(data, 8)

with DataPlaneDaemon() as daemon:
    host, port = daemon.address

    def task(part):
        with DataPlaneClient(host, port) as c:
            c.feed("demo", part, algo="pca")

    threads = [threading.Thread(target=task, args=(p,)) for p in parts]
    [t.start() for t in threads]
    [t.join() for t in threads]

    with DataPlaneClient(host, port) as c:
        result = c.finalize_pca("demo", k=8)
print("pc:", result["pc"].shape, "ev:", result["explained_variance"][:4])
