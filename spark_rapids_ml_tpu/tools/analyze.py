"""srml-check: AST-based invariant analyzer for the package's contracts.

The system's hardest guarantees — bitwise-equal reduce folds, single-filed
device dispatch through ``_DEVICE_LOCK``, donated-buffer streaming state,
the additive wire contract — were enforced by convention plus grep-shaped
lints (tests/test_lint.py), and each regressed at least once before a
human caught it in review. This module is the mechanical reviewer: it
parses the whole package with ``ast``, resolves a lightweight per-function
context (enclosing ``with`` locks, bound jit handles, call targets), and
runs a registry of rules the regex gates cannot express (a string built by
concatenation or f-string dodges a regex; it cannot dodge the AST).

Since v2 the engine is INTERPROCEDURAL: a whole-package call graph
(:class:`CallGraph` — module-qualified resolution of ``self.``/module/
imported names, method dispatch by attribute name over known classes,
bounded by a generic-name skiplist + receiver↔class affinity + import
visibility) feeds three dataflow fixpoints — may-block (with per-function
witness chains down to the blocking primitive), holds-lock (locks
possibly held at function entry), and thread-reachability — because the
hazards that matter most cross call edges: ``finalize()`` holds the
device lock and delegates twice before anything touches a socket.

Rule catalog (docs/static_analysis.md has the full rationale):

Lock discipline (the PR 13 "compile outside the lock" hardening class,
now followed through the call graph):
  ``device-lock``          device-dispatching calls in serve/daemon.py /
                           serve/scheduler.py must be lexically under
                           ``with _DEVICE_LOCK``.
  ``compile-outside-lock`` compile-path calls (``lower``/``compile``/
                           ``aot_prime``/``cost_analysis``) must NOT hold
                           the device lock — compiles are host work and
                           stall serving traffic.
  ``lock-order``           ``_DEVICE_LOCK`` is innermost by contract:
                           lexically acquiring any other lock under it is
                           a deadlock hazard.
  ``lock-graph-cycle``     whole-program lock-order graph over every
                           named lock (edges from lexical nesting AND
                           from call paths that enter a function with a
                           lock held); any cycle is a finding.
  ``blocking-under-device-lock``
                           no transitively-blocking call (socket I/O,
                           sleep, future/event waits, subprocess, lock
                           contention) while ``_DEVICE_LOCK`` is held;
                           blocking on the DEVICE is the encoded
                           exemption (that is the lock's purpose).

Threading (the planes ROADMAP items 2/3 multiply):
  ``thread-shared-state``  writes to ``self.*``/module globals reachable
                           from ``threading.Thread`` targets with no
                           lock held anywhere on the access path.

Donation (the donated streaming-state contract, ops/gram.py):
  ``use-after-donate``     a name passed at a ``donate_argnums`` position
                           of a ledgered jit is device-donated; reading it
                           again before reassignment is a use-after-free.

Determinism (the PR 7 unsorted-fold class):
  ``unsorted-iter``        iterating an un-``sorted()`` dict/set in the
                           bitwise-contract modules (ops/, models/,
                           parallel/, daemon fold/merge paths).
  ``wallclock-entropy``    ``time.time`` / ``random.*`` / unseeded
                           ``np.random.*`` in the bitwise-contract modules.

Wire contract (AST upgrade of the regex clamp gate):
  ``wire-op-clamp``        every op string the daemon dispatches must be in
                           ``_KNOWN_OPS`` and docs/protocol.md.
  ``ack-contract``         ack-dict fields may only be added, never removed,
                           versus the checked-in snapshot
                           (tools/analyze_contract.json).
  ``wire-schema``          per-op request/ack field schemas (statically
                           extracted from the _dispatch chain, helpers
                           followed through the call graph) may only
                           GROW versus the v2 snapshot, and every op
                           keeps its ``### <op>`` docs/protocol.md
                           catalog entry.

Ported regex gates (test_lint.py test names are preserved as thin
invokers):
  ``bare-print``           no ``print(`` in library code (tools/ and
                           ``__main__`` tails exempt).
  ``bare-collective``      no ``lax.psum``-family call outside parallel/.
  ``socket-timeout``       every ``socket.create_connection`` passes an
                           explicit timeout.
  ``jit-ledger``           every jit entry in ops//models/ is a
                           ledgered_jit with a unique ``<area>.<fn>``
                           name.
  ``hot-path-span``        model fit_*/transform_matrix/kneighbors run
                           under a trace_span.

Suppression: an inline ``# srml: disable=<rule>[,<rule>...]`` pragma on
the finding's line suppresses it (add a justification comment); accepted
legacy findings live in tools/analyze_baseline.json keyed by
(rule, file, enclosing symbol, count) so they survive line drift. The
tier-1 gate is therefore "zero NEW findings"; baseline entries that no
longer match anything are reported as stale warnings so the baseline only
ever shrinks.

CLI::

    python -m spark_rapids_ml_tpu.tools.analyze            # human output
    python -m spark_rapids_ml_tpu.tools.analyze --json     # machine output
    python -m spark_rapids_ml_tpu.tools.analyze --rule device-lock
    python -m spark_rapids_ml_tpu.tools.analyze --write-baseline
    python -m spark_rapids_ml_tpu.tools.analyze --write-contract
    python -m spark_rapids_ml_tpu.tools.analyze --changed-only HEAD

Exit status: 0 = zero unsuppressed findings, 1 = findings, 2 = usage.
This module imports only the standard library (no jax, no package
imports), so it runs in seconds anywhere, CI included; the whole-package
run (parse + call graph + fixpoints + 17 rules) is perf-gated under 10s
in tier-1.
"""

from __future__ import annotations

import argparse
import ast
import json
import re
import sys
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Set, Tuple

PKG_ROOT = Path(__file__).resolve().parent.parent
REPO_ROOT = PKG_ROOT.parent
BASELINE_PATH = Path(__file__).resolve().parent / "analyze_baseline.json"
CONTRACT_PATH = Path(__file__).resolve().parent / "analyze_contract.json"

#: Modules whose device dispatch must single-file through _DEVICE_LOCK.
DEVICE_MODULES = ("serve/daemon.py", "serve/scheduler.py")
#: Directories under the bitwise-determinism contract (identical inputs
#: must fold to identical bits on every host/process).
BITWISE_DIRS = ("ops", "models", "parallel")
#: Daemon/scheduler function-name fragments that put a function on the
#: fold/merge path (the daemon's slice of the bitwise contract).
FOLD_NAME_FRAGMENTS = ("merge", "fold", "reduce", "finalize", "commit", "step")

_PRAGMA_RE = re.compile(r"#\s*srml:\s*disable=([a-z0-9_,\- ]+)")


# ---------------------------------------------------------------------------
# findings, pragmas, baseline
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Finding:
    """One rule violation: id, location, enclosing symbol, one-line why.

    ``family`` groups rules for machine consumers (lock/donation/
    determinism/wire/threads/hygiene); ``chain`` is the call-chain
    witness for interprocedural findings — the path from the reported
    site (e.g. a lock acquisition) to the primitive that makes it a
    violation (e.g. a socket recv three calls deep), as
    ``(file, line, note)`` hops. Both are display/JSON payload only:
    baseline keying stays (rule, file, symbol) so accepted findings
    survive chain drift."""

    rule: str
    file: str
    line: int
    symbol: str
    message: str
    family: str = ""
    chain: Tuple[Tuple[str, int, str], ...] = ()

    def format(self) -> str:
        head = f"{self.file}:{self.line}: [{self.rule}] {self.message} (in {self.symbol})"
        for file, line, note in self.chain:
            head += f"\n    via {file}:{line}: {note}"
        return head

    def as_dict(self) -> Dict[str, Any]:
        return {
            "rule": self.rule,
            "family": self.family,
            "file": self.file,
            "line": self.line,
            "symbol": self.symbol,
            "message": self.message,
            "chain": [
                {"file": f, "line": l, "note": n} for f, l, n in self.chain
            ],
        }


def format_findings(findings: Sequence[Finding]) -> str:
    return "\n".join(f.format() for f in findings)


class Baseline:
    """Accepted legacy findings, keyed (rule, file, symbol) with a count.

    Keying by enclosing symbol instead of line number survives unrelated
    edits above the finding; the count bounds how many findings of one
    rule a symbol may carry, so NEW findings in an already-baselined
    function still fail. ``stale()`` reports entries whose code is gone —
    the baseline is a ratchet and must only ever shrink.
    """

    def __init__(self, entries: Optional[Sequence[Dict[str, Any]]] = None):
        self.entries: Dict[Tuple[str, str, str], int] = {}
        for e in entries or []:
            key = (str(e["rule"]), str(e["file"]), str(e["symbol"]))
            self.entries[key] = self.entries.get(key, 0) + int(e.get("count", 1))
        self._matched: Dict[Tuple[str, str, str], int] = {}

    @classmethod
    def load(cls, path: Path = BASELINE_PATH) -> "Baseline":
        if not path.exists():
            return cls()
        data = json.loads(path.read_text())
        return cls(data.get("entries", []))

    @classmethod
    def from_findings(cls, findings: Sequence[Finding]) -> "Baseline":
        b = cls()
        for f in findings:
            key = (f.rule, f.file, f.symbol)
            b.entries[key] = b.entries.get(key, 0) + 1
        return b

    def as_json(self) -> str:
        entries = [
            {"rule": r, "file": fp, "symbol": s, "count": c}
            for (r, fp, s), c in sorted(self.entries.items())
        ]
        return json.dumps({"version": 1, "entries": entries}, indent=2) + "\n"

    def suppresses(self, f: Finding) -> bool:
        key = (f.rule, f.file, f.symbol)
        if self._matched.get(key, 0) < self.entries.get(key, 0):
            self._matched[key] = self._matched.get(key, 0) + 1
            return True
        return False

    def stale(self) -> List[str]:
        """Entries (or counts) that matched nothing in the last run."""
        out = []
        for key, cap in sorted(self.entries.items()):
            used = self._matched.get(key, 0)
            if used < cap:
                rule, fp, sym = key
                out.append(
                    f"stale baseline entry: {rule} in {fp} ({sym}) — "
                    f"{cap - used} of {cap} accepted finding(s) no longer "
                    "exist; shrink tools/analyze_baseline.json"
                )
        return out


# ---------------------------------------------------------------------------
# module model
# ---------------------------------------------------------------------------


#: Memoized parse results keyed by (relpath, source hash): the real tree
#: is parsed by several independent Projects per pytest session (the
#: engine gate, the lint invokers, seeded-violation scratch copies that
#: share every unchanged file) and re-parsing ~100 modules each time is
#: the analyzer's single biggest cost. Parent-link stamping is
#: idempotent, so sharing one tree across Module instances is safe —
#: rules only ever READ the AST.
_AST_CACHE: Dict[Tuple[str, int, int], ast.AST] = {}
_AST_CACHE_MAX = 512


def _parse_cached(relpath: str, source: str) -> ast.AST:
    import zlib

    key = (relpath, len(source), zlib.crc32(source.encode()))
    tree = _AST_CACHE.get(key)
    if tree is None:
        tree = ast.parse(source, filename=relpath)
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                child._srml_parent = parent  # type: ignore[attr-defined]
        if len(_AST_CACHE) >= _AST_CACHE_MAX:
            _AST_CACHE.clear()  # tests churn tiny fixtures; bound growth
        _AST_CACHE[key] = tree
    return tree


class Module:
    """One parsed source file plus the lazy per-line pragma map."""

    def __init__(self, relpath: str, source: str, display_path: Optional[str] = None):
        self.relpath = relpath.replace("\\", "/")
        self.source = source
        self.display_path = display_path or self.relpath
        self.tree = _parse_cached(self.relpath, source)
        self.lines = source.split("\n")
        self._pragmas: Optional[Dict[int, Set[str]]] = None

    @property
    def pragmas(self) -> Dict[int, Set[str]]:
        if self._pragmas is None:
            self._pragmas = {}
            for i, line in enumerate(self.lines, start=1):
                m = _PRAGMA_RE.search(line)
                if m:
                    rules = {p.strip() for p in m.group(1).split(",") if p.strip()}
                    self._pragmas[i] = rules
        return self._pragmas

    def suppressed(self, rule: str, line: int) -> bool:
        rules = self.pragmas.get(line)
        return rules is not None and (rule in rules or "all" in rules)

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = getattr(node, "_srml_parent", None)
        while cur is not None:
            yield cur
            cur = getattr(cur, "_srml_parent", None)

    def enclosing_symbol(self, node: ast.AST) -> str:
        parts = []
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                parts.append(anc.name)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            parts.insert(0, node.name)
        return ".".join(reversed(parts)) or "<module>"


# ---------------------------------------------------------------------------
# small AST helpers
# ---------------------------------------------------------------------------


def dotted_name(expr: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: List[str] = []
    cur = expr
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return None


def terminal_name(expr: ast.AST) -> Optional[str]:
    """The last identifier of a call target: ``x`` for ``a.b.x`` or ``x``."""
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return None


def const_str(expr: ast.AST) -> Optional[str]:
    """Constant-fold an expression to a string where statically possible —
    plain constants, ``"a" + "b"`` concatenation, and constant-only
    f-strings — so wire-op strings cannot dodge the clamp by being built
    instead of written (the hole the old regex gate had)."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return expr.value
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
        left, right = const_str(expr.left), const_str(expr.right)
        if left is not None and right is not None:
            return left + right
    if isinstance(expr, ast.JoinedStr):
        parts = []
        for v in expr.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            elif isinstance(v, ast.FormattedValue):
                inner = const_str(v.value)
                if inner is None:
                    return None
                parts.append(inner)
            else:
                return None
        return "".join(parts)
    return None


_LOCKISH_RE = re.compile(r"(_lock$|_LOCK$|^lock$|^_cv$|_cond$)")


def lock_name(expr: ast.AST) -> Optional[str]:
    """Normalized lock identity of a ``with`` context expression, or None
    when it does not look like a lock. ``self._models_lock`` →
    ``_models_lock``; ``_DEVICE_LOCK`` → ``_DEVICE_LOCK``."""
    name = terminal_name(expr)
    if name is not None and _LOCKISH_RE.search(name):
        return name
    return None


def in_main_guard(mod: Module, node: ast.AST) -> bool:
    """True when the node sits under ``if __name__ == "__main__":``."""
    for anc in mod.ancestors(node):
        if isinstance(anc, ast.If):
            for sub in ast.walk(anc.test):
                if isinstance(sub, ast.Name) and sub.id == "__name__":
                    return True
    return False


def iter_functions(mod: Module) -> Iterator[ast.AST]:
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def held_locks(mod: Module, node: ast.AST) -> List[str]:
    """Locks lexically held at ``node``, outermost first (item order of a
    multi-item ``with A, B:`` preserved) — the resolved ``with``-stack
    WITHIN the node's own function. The walk stops at the first function
    boundary: a closure defined under ``with _DEVICE_LOCK`` runs later,
    when the lock is long released, so an enclosing function's ``with``
    must not read as held inside the closure."""
    withs: List[ast.With] = []
    for anc in mod.ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            break
        if isinstance(anc, ast.With):
            withs.append(anc)
    stack: List[str] = []
    for w in reversed(withs):  # outermost with first, items left-to-right
        for item in w.items:
            ln = lock_name(item.context_expr)
            if ln is not None:
                stack.append(ln)
    return stack


def node_pos(node: ast.AST) -> Tuple[int, int]:
    return (getattr(node, "lineno", 0), getattr(node, "col_offset", 0))


def node_end(node: ast.AST) -> Tuple[int, int]:
    return (
        getattr(node, "end_lineno", getattr(node, "lineno", 0)),
        getattr(node, "end_col_offset", getattr(node, "col_offset", 0)),
    )


# ---------------------------------------------------------------------------
# jit-handle registry (cross-module semantic context)
# ---------------------------------------------------------------------------


def _ledgered_jit_donate(call: ast.Call) -> Optional[Tuple[int, ...]]:
    """donate_argnums of a ``ledgered_jit(...)`` / ``functools.partial(
    ledgered_jit, ...)`` expression, () when present without donation,
    None when the call is not a ledgered_jit registration at all."""
    fn = terminal_name(call.func)
    args = call.args
    if fn == "partial" and args and terminal_name(args[0]) == "ledgered_jit":
        pass
    elif fn == "ledgered_jit":
        pass
    else:
        return None
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            positions: List[int] = []
            val = kw.value
            elts = val.elts if isinstance(val, (ast.Tuple, ast.List)) else [val]
            for e in elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, int):
                    positions.append(e.value)
            return tuple(positions)
    return ()


def _pkg_module_relpath(dotted: str, known: Set[str]) -> Optional[str]:
    """``spark_rapids_ml_tpu.ops.gram`` (or ``ops.gram``) → the project
    relpath ``ops/gram.py`` when that module is in the analyzed set."""
    parts = dotted.split(".")
    for start in range(len(parts)):
        rel = "/".join(parts[start:]) + ".py"
        if rel in known:
            return rel
    return None


@dataclass
class JitRegistry:
    """Package-wide view of where jit handles come from.

    ``module_handles``: per-module map of MODULE-LEVEL names that ARE a
                   ledgered jit (name → donated arg positions, possibly
                   empty). Scoped per module: the decorated inner ``def
                   update`` every streaming factory carries must not make
                   every ``update`` in the package look like a dispatch.
    ``factories``: functions that RETURN a ledgered jit handle (name →
                   donated positions of the handle they return) — e.g.
                   ``gram.streaming_update(mesh)`` or kmeans'
                   ``_stream_step_fn``. Resolved to a fixpoint so a
                   factory that delegates to another factory (the
                   lru_cache split: ``_stream_softmax_stats_fn`` →
                   ``_stream_softmax_stats_cached``) is still a factory.
                   A call to a factory is host work; a call to what it
                   returned is a device dispatch.
    """

    module_handles: Dict[str, Dict[str, Tuple[int, ...]]] = field(
        default_factory=dict
    )
    factories: Dict[str, Tuple[int, ...]] = field(default_factory=dict)
    #: every handle name at any scope — only for resolving `return <name>`
    #: inside factory detection, never for call-site matching.
    _any_scope: Dict[str, Tuple[int, ...]] = field(default_factory=dict)

    @classmethod
    def build(cls, modules: Sequence[Module]) -> "JitRegistry":
        reg = cls()
        #: (factory-candidate def, its own return values), for the fixpoint.
        candidates: List[Tuple[Module, ast.AST, List[ast.AST]]] = []
        for mod in modules:
            mh = reg.module_handles.setdefault(mod.relpath, {})
            for node in ast.walk(mod.tree):
                # name = ledgered_jit("x", f, donate_argnums=...)
                if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                    don = _ledgered_jit_donate(node.value)
                    if don is not None:
                        for t in node.targets:
                            tn = terminal_name(t)
                            if tn:
                                reg._any_scope[tn] = don
                                if _enclosing_function(mod, node) is None:
                                    mh[tn] = don
                # @functools.partial(ledgered_jit, "x", donate_argnums=...)
                # def update(...): ...   /   @ledgered_jit("x")
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    for dec in node.decorator_list:
                        if isinstance(dec, ast.Call):
                            don = _ledgered_jit_donate(dec)
                            if don is not None:
                                reg._any_scope[node.name] = don
                                if _enclosing_function(mod, node) is None:
                                    mh[node.name] = don
                    returns = [
                        ret.value
                        for ret in ast.walk(node)
                        if isinstance(ret, ast.Return)
                        and ret.value is not None
                        and _enclosing_function(mod, ret) is node
                    ]
                    if returns:
                        candidates.append((mod, node, returns))
        # Factory fixpoint: direct ledgered_jit returns, returns of a known
        # handle name, and returns of a call to an already-known factory.
        changed = True
        while changed:
            changed = False
            for mod, node, returns in candidates:
                if node.name in reg.factories:
                    continue
                for val in returns:
                    don: Optional[Tuple[int, ...]] = None
                    if isinstance(val, ast.Call):
                        don = _ledgered_jit_donate(val)
                        if don is None:
                            fn = terminal_name(val.func)
                            if fn in reg.factories:
                                don = reg.factories[fn]
                    else:
                        rn = terminal_name(val)
                        if rn is not None and rn in reg._any_scope:
                            don = reg._any_scope[rn]
                    if don is not None:
                        reg.factories[node.name] = don
                        changed = True
                        break
        return reg

    def bound_handles(
        self, mod: Module
    ) -> Dict[str, List[Tuple[Optional[ast.AST], Tuple[int, ...]]]]:
        """Dotted names in ``mod`` bound from a factory call or a handle:
        ``self.update = gram_ops.streaming_update(mesh)`` binds
        ``self.update`` as a dispatch handle donating position 0. Bare
        names carry their binding function as a visibility scope (a local
        ``update = _stream_step_fn(...)`` must not make a sibling
        function's unrelated ``update`` look like a dispatch); attribute
        bindings (``self.update``) cross methods and stay module-wide."""
        bound: Dict[str, List[Tuple[Optional[ast.AST], Tuple[int, ...]]]] = {}
        own = self.module_handles.get(mod.relpath, {})
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Assign):
                continue
            value = node.value
            don: Optional[Tuple[int, ...]] = None
            if isinstance(value, ast.Call):
                fn = terminal_name(value.func)
                if fn in self.factories:
                    don = self.factories[fn]
            else:
                vn = terminal_name(value)
                if vn in own:
                    don = own[vn]
            if don is None:
                continue
            for t in node.targets:
                dn = dotted_name(t)
                if dn:
                    scope = (
                        None if "." in dn else _enclosing_function(mod, node)
                    )
                    bound.setdefault(dn, []).append((scope, don))
        return bound

    def imported_handles(self, mod: Module, known_mods: Set[str]) -> Dict[str, Tuple[int, ...]]:
        """Module-level handles visible in ``mod`` through imports:
        ``from ...models.kmeans import apply_lloyd_update`` (direct name)
        and ``from ... import gram as gram_ops`` + ``gram_ops.<handle>``
        (the dotted spelling is resolved at the call site)."""
        out: Dict[str, Tuple[int, ...]] = {}
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ImportFrom) and node.module:
                src = _pkg_module_relpath(node.module, known_mods)
                if src is None:
                    continue
                src_handles = self.module_handles.get(src, {})
                for alias in node.names:
                    if alias.name in src_handles:
                        out[alias.asname or alias.name] = src_handles[alias.name]
        return out

    def module_aliases(self, mod: Module, known_mods: Set[str]) -> Dict[str, str]:
        """Import aliases that name whole analyzed modules:
        ``from spark_rapids_ml_tpu.ops import gram as gram_ops`` →
        ``{"gram_ops": "ops/gram.py"}``."""
        out: Dict[str, str] = {}
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    src = _pkg_module_relpath(
                        f"{node.module}.{alias.name}", known_mods
                    )
                    if src is not None:
                        out[alias.asname or alias.name] = src
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    src = _pkg_module_relpath(alias.name, known_mods)
                    if src is not None:
                        out[alias.asname or alias.name.split(".")[-1]] = src
        return out


def _enclosing_function(mod: Module, node: ast.AST) -> Optional[ast.AST]:
    for anc in mod.ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return anc
    return None


def _enclosing_class(mod: Module, node: ast.AST) -> Optional[ast.ClassDef]:
    for anc in mod.ancestors(node):
        if isinstance(anc, ast.ClassDef):
            return anc
    return None


# ---------------------------------------------------------------------------
# interprocedural engine: whole-package call graph + dataflow fixpoints
# ---------------------------------------------------------------------------
#
# The per-function lexical rules above can see a blocking call only when
# it sits in the same function as the lock that makes it dangerous. The
# package's real hazards cross call edges: `finalize()` holds
# `_DEVICE_LOCK` and delegates to `_finalize_locked()`, which delegates
# again before anything touches a socket. This section builds the
# whole-package call graph (module-qualified resolution of `self.` /
# module / imported names, plus method dispatch by attribute name over
# known classes) and runs the dataflow fixpoints the interprocedural
# rule families consume: MAY-BLOCK (does calling this function possibly
# block on socket/sleep/future/subprocess/lock-acquire?), HOLDS-LOCK
# (which locks may be held when this function is entered?), and
# THREAD-REACHABILITY (can a `threading.Thread` target reach this
# function, and does some path arrive with no lock held?).
#
# Honesty (docs/static_analysis.md has the full list): resolution is
# name-based, not type-based. `self.m()` resolves within the enclosing
# class (plus by-name base classes); `alias.f()` resolves through
# import aliases; a bare `obj.m()` falls back to EVERY known class
# method named `m` — an over-approximation bounded by the generic-name
# skiplist below. Calls through variables holding functions, getattr,
# and callbacks are invisible; jit handles are the JitRegistry's job.

#: Attribute names too generic for by-name method dispatch: linking
#: `d.get(...)` to every class that defines `get` would wire the graph
#: to dict/set/list/logger/metrics traffic and drown the dataflow in
#: false edges. `self.`/`cls.` receivers bypass this list (their class
#: is known).
_GENERIC_ATTR_SKIP = frozenset((
    "get", "set", "add", "pop", "popleft", "append", "appendleft",
    "extend", "remove", "discard", "clear", "copy", "update", "items",
    "keys", "values", "sort", "index", "count", "insert", "reverse",
    "join", "split", "strip", "format", "encode", "decode", "read",
    "write", "readline", "flush", "open",
    "inc", "dec", "observe", "info", "debug", "warning", "error",
    "exception", "log", "search", "match", "group", "findall", "sub",
    "put", "send", "recv", "close", "acquire", "release", "wait",
    "notify", "notify_all", "result", "done", "cancel", "start",
))


@dataclass
class FuncNode:
    """One function/method in the analyzed set."""

    mod: Module
    fn: ast.AST  # FunctionDef | AsyncFunctionDef
    qualname: str  # e.g. "Daemon._op_feed" / "fit_streaming"
    cls: Optional[str]  # enclosing class name, None for module level

    @property
    def key(self) -> Tuple[str, str]:
        return (self.mod.relpath, self.qualname)

    @property
    def name(self) -> str:
        return self.fn.name

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<{self.mod.relpath}:{self.qualname}>"


@dataclass
class CallSite:
    """One resolved call edge: caller → callee at a source location,
    with the lock stack lexically held at the call expression."""

    caller: Tuple[str, str]
    callee: Tuple[str, str]
    mod: Module
    call: ast.Call
    held: Tuple[str, ...]  # lexical lock ids at the call site


def _lock_id(mod: Module, name: str) -> str:
    """Lock identity for the whole-program lock graph. `_DEVICE_LOCK` is
    the one process-global lock shared across modules; everything else
    is scoped per module (the existing lock-order convention) — two
    `self._lock`s in different files never alias, at the cost of not
    linking one lock object passed across modules (documented)."""
    if name == "_DEVICE_LOCK":
        return "_DEVICE_LOCK"
    return f"{mod.relpath}:{name}"


class CallGraph:
    """Whole-package call graph + the fixpoint dataflow facts."""

    #: Fixpoint iteration cap (outer sweeps). Every fact domain here is
    #: finite and monotone, so convergence is guaranteed in at most
    #: O(nodes) sweeps; the cap is a backstop against a future
    #: non-monotone edit looping forever — hitting it is itself a
    #: diagnostic (a loud note, surfaced by the CLI and the perf gate).
    MAX_FIXPOINT_SWEEPS = 64

    def __init__(self, project: "Project"):
        self.project = project
        self.nodes: Dict[Tuple[str, str], FuncNode] = {}
        #: method name → nodes (methods only), for attr-name dispatch
        self.methods_by_name: Dict[str, List[FuncNode]] = {}
        #: (relpath, class) → {method name → node}
        self.class_methods: Dict[Tuple[str, str], Dict[str, FuncNode]] = {}
        #: (relpath, class) → base class names (unresolved strings)
        self.class_bases: Dict[Tuple[str, str], List[str]] = {}
        #: relpath → {module-level def name → node}
        self.module_funcs: Dict[str, Dict[str, FuncNode]] = {}
        #: relpath → {imported name → (src relpath, src name)}
        self.from_imports: Dict[str, Dict[str, Tuple[str, str]]] = {}
        #: relpath → {alias → module relpath} (whole-module imports)
        self.module_aliases: Dict[str, Dict[str, str]] = {}
        #: relpath → every analyzed module it imports anything from
        self.module_imports: Dict[str, Set[str]] = {}
        #: (relpath, id(enclosing fn node)) → {nested def name → node}
        self.local_defs: Dict[Tuple[str, int], Dict[str, FuncNode]] = {}
        #: caller key → outgoing call sites (resolved edges only)
        self.calls_out: Dict[Tuple[str, str], List[CallSite]] = {}
        #: callee key → incoming call sites
        self.calls_in: Dict[Tuple[str, str], List[CallSite]] = {}
        self.notes: List[str] = []
        self._index()
        self._link()
        # dataflow facts, computed by _solve()
        self.may_block: Dict[Tuple[str, str], Tuple[Tuple[str, str, int, str], ...]] = {}
        self.entered_holding: Dict[Tuple[str, str], Set[str]] = {}
        self.thread_entries: List[Tuple[FuncNode, Module, ast.AST]] = []
        self.thread_reachable: Set[Tuple[str, str]] = set()
        self.unlocked_reachable: Set[Tuple[str, str]] = set()
        self._solve()

    # -- indexing ----------------------------------------------------------

    def _index(self) -> None:
        known = self.project._known_mods
        for mod in self.project.modules:
            mf = self.module_funcs.setdefault(mod.relpath, {})
            for node in ast.walk(mod.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qual = mod.enclosing_symbol(node)
                    cls = _enclosing_class(mod, node)
                    fn = FuncNode(mod, node, qual, cls.name if cls else None)
                    self.nodes[fn.key] = fn
                    encl = _enclosing_function(mod, node)
                    if encl is not None:
                        # a nested def is NOT a method/module function:
                        # it resolves only through its enclosing scope
                        # (resolve_call's local-def lookup)
                        self.local_defs.setdefault(
                            (mod.relpath, id(encl)), {}
                        ).setdefault(node.name, fn)
                        continue
                    if cls is not None:
                        cm = self.class_methods.setdefault(
                            (mod.relpath, cls.name), {}
                        )
                        # first def wins (conditional redefs are rare)
                        cm.setdefault(node.name, fn)
                        self.methods_by_name.setdefault(node.name, []).append(fn)
                    else:
                        mf.setdefault(node.name, fn)
                elif isinstance(node, ast.ClassDef):
                    bases = [
                        terminal_name(b) for b in node.bases
                        if terminal_name(b) is not None
                    ]
                    self.class_bases[(mod.relpath, node.name)] = bases
            # import resolution (functions by name, modules by alias)
            imports: Dict[str, Tuple[str, str]] = {}
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.ImportFrom) and node.module:
                    src = _pkg_module_relpath(node.module, known)
                    if src is None:
                        continue
                    for alias in node.names:
                        imports[alias.asname or alias.name] = (src, alias.name)
            self.from_imports[mod.relpath] = imports
            self.module_aliases[mod.relpath] = (
                self.project.registry.module_aliases(mod, known)
            )
            self.module_imports[mod.relpath] = {
                src for src, _name in imports.values()
            } | set(self.module_aliases[mod.relpath].values())

    def _method_in_class(
        self, relpath: str, cls: str, name: str, _seen: Optional[Set] = None
    ) -> Optional[FuncNode]:
        """Method lookup through the by-name MRO: the class itself, then
        base classes resolved within the module (or through imports)."""
        seen = _seen if _seen is not None else set()
        if (relpath, cls) in seen:
            return None
        seen.add((relpath, cls))
        fn = self.class_methods.get((relpath, cls), {}).get(name)
        if fn is not None:
            return fn
        for base in self.class_bases.get((relpath, cls), []):
            base_rel = relpath
            base_name = base
            # an imported base resolves to its ORIGINAL name in the
            # source module, not the local alias it was imported under
            imp = self.from_imports.get(relpath, {}).get(base)
            if imp is not None:
                base_rel, base_name = imp[0], imp[1]
            fn = self._method_in_class(base_rel, base_name, name, seen)
            if fn is not None:
                return fn
        return None

    def resolve_call(
        self, mod: Module, caller_fn: Optional[ast.AST], call: ast.Call
    ) -> List[FuncNode]:
        """Every FuncNode this call may enter (empty = external/opaque)."""
        func = call.func
        if isinstance(func, ast.Name):
            name = func.id
            # nearest enclosing function's directly-nested defs first
            scope = caller_fn
            while scope is not None:
                local = self.local_defs.get((mod.relpath, id(scope)), {})
                if name in local:
                    return [local[name]]
                scope = _enclosing_function(mod, scope)
            fn = self.module_funcs.get(mod.relpath, {}).get(name)
            if fn is not None:
                return [fn]
            imp = self.from_imports.get(mod.relpath, {}).get(name)
            if imp is not None:
                target = self.module_funcs.get(imp[0], {}).get(imp[1])
                return [target] if target else []
            return []
        if not isinstance(func, ast.Attribute):
            return []
        name = func.attr
        recv = func.value
        recv_name = terminal_name(recv)
        # self./cls. → the enclosing class's method (by-name MRO)
        if isinstance(recv, ast.Name) and recv.id in ("self", "cls"):
            cls = _enclosing_class(mod, call)
            if cls is not None:
                fn = self._method_in_class(mod.relpath, cls.name, name)
                return [fn] if fn else []
            return []
        # module alias → that module's function
        src = self.module_aliases.get(mod.relpath, {}).get(recv_name or "")
        if src is not None:
            target = self.module_funcs.get(src, {}).get(name)
            return [target] if target else []
        # by-name method dispatch over known classes (bounded)
        if name in _GENERIC_ATTR_SKIP:
            return []
        # Visibility: a by-name candidate must live in a module the
        # caller's module is import-related to (either direction — the
        # scheduler never imports daemon.py, but daemon.py imports the
        # scheduler and hands it _ServedModel instances). An object of a
        # class from a module neither side references cannot plausibly
        # be this receiver.
        candidates = [
            c
            for c in self.methods_by_name.get(name, [])
            if c.mod.relpath == mod.relpath
            or c.mod.relpath in self.module_imports.get(mod.relpath, ())
            or mod.relpath in self.module_imports.get(c.mod.relpath, ())
        ]
        # Receiver↔class affinity: `timer.stop()` should dispatch to
        # Timer.stop, not every class that defines a stop() — when the
        # receiver name textually matches some candidate's class name
        # (`self._scheduler` ↔ RequestScheduler, `served` ↔
        # _ServedModel), restrict to the matches; with no match (or a
        # too-short receiver like `m`) keep the full over-approximation.
        if recv_name is not None:
            r = re.sub(r"[^a-z]", "", recv_name.lower())
            if len(r) >= 3:
                hits = []
                for c in candidates:
                    cl = re.sub(r"[^a-z]", "", (c.cls or "").lower())
                    if cl and (r in cl or cl in r):
                        hits.append(c)
                if hits:
                    candidates = hits
        # Never self-dispatch by attribute name: `self.model.kneighbors()`
        # inside _ServedModel.kneighbors is a DIFFERENT object's method —
        # a by-name self-edge would feed the holds-lock fixpoint a
        # fictitious recursion under whatever locks the body holds.
        encl = _enclosing_class(mod, call)
        enc_fn = _enclosing_function(mod, call)
        if encl is not None and enc_fn is not None:
            candidates = [
                c
                for c in candidates
                if not (
                    c.mod.relpath == mod.relpath
                    and c.cls == encl.name
                    and c.fn is enc_fn
                )
            ]
        return candidates

    def _link(self) -> None:
        for key, fn in sorted(self.nodes.items()):
            sites = self.calls_out.setdefault(key, [])
            for node in ast.walk(fn.fn):
                if not isinstance(node, ast.Call):
                    continue
                # a call inside a nested def belongs to the nested node
                if _enclosing_function(fn.mod, node) is not fn.fn:
                    continue
                targets = self.resolve_call(fn.mod, fn.fn, node)
                if not targets:
                    continue
                held = tuple(
                    _lock_id(fn.mod, l) for l in held_locks(fn.mod, node)
                )
                for target in targets:
                    site = CallSite(key, target.key, fn.mod, node, held)
                    sites.append(site)
                    self.calls_in.setdefault(target.key, []).append(site)

    # -- blocking primitives ----------------------------------------------

    _SOCKET_METHODS = frozenset(
        ("recv", "recv_into", "recvfrom", "sendall", "accept", "connect")
    )
    _SOCKETISH_RECV_RE = re.compile(r"(sock|conn)", re.IGNORECASE)
    _SUBPROCESS_CALLS = frozenset(
        ("run", "call", "check_call", "check_output", "communicate")
    )

    @classmethod
    def blocking_primitive(
        cls, mod: Module, call: ast.Call
    ) -> Optional[Tuple[str, str]]:
        """(kind, description) when this very call blocks the thread.

        Kinds: sleep | socket | future | thread-join | subprocess |
        lock-acquire. Device waits (`block_until_ready`/`device_get`/
        `device_put`) are deliberately NOT here: blocking on the device
        *is the point* of holding `_DEVICE_LOCK`, so counting them
        would flag every legal dispatch (the encoded exemption the
        blocking-under-device-lock rule documents)."""
        dn = dotted_name(call.func)
        name = terminal_name(call.func)
        if dn == "time.sleep" or (name == "sleep" and dn == "sleep"):
            return ("sleep", "time.sleep() blocks the thread")
        if dn == "select.select":
            return ("socket", "select.select() waits on socket readiness")
        if dn == "socket.create_connection" or (
            name == "create_connection"
            and terminal_name(getattr(call.func, "value", ast.Name(id="")))
            == "socket"
        ):
            return ("socket", "socket.create_connection() performs a TCP handshake")
        if isinstance(call.func, ast.Attribute):
            recv = terminal_name(call.func.value)
            if name in cls._SOCKET_METHODS:
                if recv is not None and cls._SOCKETISH_RECV_RE.search(recv):
                    return ("socket", f"{recv}.{name}() is blocking socket I/O")
            if name == "result":
                return ("future", f"{recv or '<expr>'}.result() waits on a future")
            if name == "wait":
                return (
                    "future",
                    f"{recv or '<expr>'}.wait() parks the thread on an "
                    "event/condition",
                )
            if name == "join" and recv is not None and "thread" in recv.lower():
                return ("thread-join", f"{recv}.join() waits for a thread")
            if name in cls._SUBPROCESS_CALLS and recv == "subprocess":
                return ("subprocess", f"subprocess.{name}() waits on a child process")
            if name == "communicate":
                return ("subprocess", f"{recv or '<expr>'}.communicate() waits on a child")
            if name == "acquire":
                ln = lock_name(call.func.value)
                nonblocking = any(
                    kw.arg == "blocking"
                    and isinstance(kw.value, ast.Constant)
                    and kw.value.value is False
                    for kw in call.keywords
                ) or (
                    call.args
                    and isinstance(call.args[0], ast.Constant)
                    and call.args[0].value is False
                )
                if ln is not None and not nonblocking:
                    return ("lock-acquire", f"{ln}.acquire() blocks on lock contention")
        return None

    # -- fixpoints ---------------------------------------------------------

    def _sweep(self, step, what: str) -> None:
        """Run ``step()`` (returns True while anything changed) to
        convergence, capped and LOUD on cap: a hit means the lattice is
        broken and facts may be incomplete — surfaced as a note so CI
        shows it instead of silently under-reporting."""
        for _ in range(self.MAX_FIXPOINT_SWEEPS):
            if not step():
                return
        self.notes.append(
            f"fixpoint cap hit while solving {what} "
            f"({self.MAX_FIXPOINT_SWEEPS} sweeps): dataflow facts may be "
            "incomplete — this is an analyzer bug, report it"
        )

    def _solve(self) -> None:
        # MAY-BLOCK, round 1: seed with direct primitives, propagate up
        # the graph. The witness chain records (file, symbol, line,
        # note) hops from the function's own call down to the primitive.
        for key, fn in sorted(self.nodes.items()):
            for node in ast.walk(fn.fn):
                if not isinstance(node, ast.Call):
                    continue
                if _enclosing_function(fn.mod, node) is not fn.fn:
                    continue
                prim = self.blocking_primitive(fn.mod, node)
                if prim is not None:
                    self.may_block[key] = (
                        (fn.mod.display_path, fn.qualname, node.lineno, prim[1]),
                    )
                    break

        def block_step() -> bool:
            changed = False
            for key in sorted(self.nodes):
                if key in self.may_block:
                    continue
                for site in self.calls_out.get(key, ()):
                    sub = self.may_block.get(site.callee)
                    if sub is None:
                        continue
                    fn = self.nodes[key]
                    callee = self.nodes[site.callee]
                    hop = (
                        fn.mod.display_path,
                        fn.qualname,
                        site.call.lineno,
                        f"calls {callee.qualname}()",
                    )
                    self.may_block[key] = (hop,) + sub
                    changed = True
                    break
            return changed

        self._sweep(block_step, "may-block")

        # MAY-BLOCK, round 2: contended `with <lock>:` acquisitions.
        # A lock acquisition is the codebase's universal blocking
        # spelling, but flagging EVERY `with lock:` would drown the
        # rules in micro-critical-sections (config.get's registry lock
        # is held for a dict read). The honest middle: a lock is
        # LONG-HELD when some holder's `with` body itself transitively
        # blocks (socket/sleep/future/subprocess — not merely another
        # lock); only acquiring a long-held lock can stall unboundedly,
        # so only those seed may-block. One level deep by design: a
        # lock long-held solely because its body acquires another
        # contended lock is not re-derived (documented honesty gap).
        long_held: Dict[str, Tuple[Tuple[str, str, int, str], ...]] = {}
        for key, fn in sorted(self.nodes.items()):
            for node in ast.walk(fn.fn):
                if not isinstance(node, ast.With):
                    continue
                if _enclosing_function(fn.mod, node) is not fn.fn:
                    continue
                locks_here = [
                    lock_name(item.context_expr)
                    for item in node.items
                    if lock_name(item.context_expr) is not None
                ]
                if not locks_here:
                    continue
                # does the with body block (directly or through calls)?
                witness: Optional[Tuple] = None
                for stmt in node.body:
                    for sub in ast.walk(stmt):
                        if not isinstance(sub, ast.Call):
                            continue
                        # a call inside a nested def runs LATER, after
                        # the lock is released — it must not mark the
                        # lock long-held (same rule as held_locks)
                        if _enclosing_function(fn.mod, sub) is not fn.fn:
                            continue
                        prim = self.blocking_primitive(fn.mod, sub)
                        if prim is not None and prim[0] != "lock-acquire":
                            witness = (
                                (fn.mod.display_path, fn.qualname,
                                 sub.lineno, prim[1]),
                            )
                            break
                        for t in self.resolve_call(fn.mod, fn.fn, sub):
                            w = self.may_block.get(t.key)
                            if w is not None:
                                witness = (
                                    (fn.mod.display_path, fn.qualname,
                                     sub.lineno,
                                     f"calls {t.qualname}() while holding it"),
                                ) + w
                                break
                        if witness:
                            break
                    if witness:
                        break
                if witness is None:
                    continue
                for ln in locks_here:
                    if ln == "_DEVICE_LOCK":
                        continue  # device-lock stalls are their own rules
                    long_held.setdefault(_lock_id(fn.mod, ln), witness)
        if long_held:
            for key, fn in sorted(self.nodes.items()):
                if key in self.may_block:
                    continue
                for node in ast.walk(fn.fn):
                    if not isinstance(node, ast.With):
                        continue
                    if _enclosing_function(fn.mod, node) is not fn.fn:
                        continue
                    hit = None
                    for item in node.items:
                        ln = lock_name(item.context_expr)
                        if ln is None:
                            continue
                        lid = _lock_id(fn.mod, ln)
                        if lid in long_held:
                            hit = (ln, lid)
                            break
                    if hit is not None:
                        ln, lid = hit
                        self.may_block[key] = (
                            (fn.mod.display_path, fn.qualname, node.lineno,
                             f"`with {ln}:` can wait on a holder that "
                             "blocks inside the critical section"),
                        ) + long_held[lid]
                        break
            self._sweep(block_step, "may-block(contended-locks)")

        # HOLDS-LOCK: which locks MAY be held when a function is entered
        # — the union over call sites of (locks lexically held at the
        # site) ∪ (locks held when the CALLER was entered).
        def lock_step() -> bool:
            changed = False
            for key in sorted(self.nodes):
                for site in self.calls_out.get(key, ()):
                    incoming = set(site.held)
                    incoming |= self.entered_holding.get(key, set())
                    have = self.entered_holding.setdefault(site.callee, set())
                    if not incoming <= have:
                        have |= incoming
                        changed = True
            return changed

        self._sweep(lock_step, "holds-lock")

        # THREAD ENTRIES: threading.Thread(target=X) — keyword or the
        # positional form Thread(None, X) — and threading.Timer's
        # callable, which is the POSITIONAL `function` parameter
        # (Timer takes no `target=`): Timer(5.0, X) / function=X.
        for key, fn in sorted(self.nodes.items()):
            for node in ast.walk(fn.fn):
                if not isinstance(node, ast.Call):
                    continue
                ctor = terminal_name(node.func)
                if ctor not in ("Thread", "Timer"):
                    continue
                target = None
                want_kw = "target" if ctor == "Thread" else "function"
                for kw in node.keywords:
                    if kw.arg == want_kw:
                        target = kw.value
                if target is None and len(node.args) >= 2:
                    target = node.args[1]
                if target is None:
                    continue
                fake = ast.Call(func=target, args=[], keywords=[])
                fake._srml_parent = getattr(node, "_srml_parent", None)  # type: ignore[attr-defined]
                for resolved in self.resolve_call(fn.mod, fn.fn, fake):
                    self.thread_entries.append((resolved, fn.mod, node))

        # THREAD REACHABILITY + UNLOCKED REACHABILITY: what a spawned
        # thread can reach, and which of those functions some path
        # reaches with NO lock held anywhere along it.
        for entry, _, _ in self.thread_entries:
            self.thread_reachable.add(entry.key)
            self.unlocked_reachable.add(entry.key)

        def reach_step() -> bool:
            changed = False
            for key in sorted(self.thread_reachable):
                for site in self.calls_out.get(key, ()):
                    if site.callee not in self.thread_reachable:
                        self.thread_reachable.add(site.callee)
                        changed = True
                    if (
                        key in self.unlocked_reachable
                        and not site.held
                        and site.callee not in self.unlocked_reachable
                    ):
                        self.unlocked_reachable.add(site.callee)
                        changed = True
            return changed

        self._sweep(reach_step, "thread-reachability")


# ---------------------------------------------------------------------------
# rule registry
# ---------------------------------------------------------------------------

RULES: Dict[str, "Rule"] = {}


@dataclass
class Rule:
    id: str
    summary: str
    check: Callable[["Project"], List[Finding]]
    family: str = "misc"


def rule(rule_id: str, summary: str, family: str = "misc"):
    def deco(fn: Callable[["Project"], List[Finding]]) -> Callable:
        RULES[rule_id] = Rule(rule_id, summary, fn, family)
        return fn

    return deco


class Project:
    """The analyzed file set plus its cross-module context.

    ``files`` maps package-relative posix paths (``serve/daemon.py``) to
    source text, so tests can assemble synthetic projects; ``from_package``
    loads the real tree. ``protocol_doc``/``contract`` feed the wire rules
    and are optional for fixtures. ``strict_floors`` arms the self-check
    floors (minimum dispatched-op counts etc.) that only make sense
    against the real package.
    """

    def __init__(
        self,
        files: Dict[str, str],
        protocol_doc: Optional[str] = None,
        contract: Optional[Dict[str, Any]] = None,
        strict_floors: bool = False,
        display_prefix: str = "",
    ):
        self.modules: List[Module] = []
        for rel in sorted(files):
            self.modules.append(
                Module(rel, files[rel], display_path=display_prefix + rel)
            )
        self.protocol_doc = protocol_doc
        self.contract = contract
        self.strict_floors = strict_floors
        self.registry = JitRegistry.build(self.modules)
        self._known_mods = {m.relpath for m in self.modules}
        self._jit_views: Dict[str, "ModuleJitView"] = {}
        self._graph: Optional[CallGraph] = None
        #: report scope: when set (package-relative paths/prefixes), only
        #: findings in matching files are reported — analysis itself is
        #: always whole-program.
        self.report_filter: Optional[List[str]] = None
        #: non-fatal remarks (stale baseline entries land here too)
        self.notes: List[str] = []

    @property
    def graph(self) -> CallGraph:
        """The interprocedural engine, built lazily ONCE per Project:
        call graph + may-block/holds-lock/thread-reachability fixpoints.
        Its diagnostics (fixpoint-cap hits) surface through run()'s
        notes."""
        if self._graph is None:
            self._graph = CallGraph(self)
        return self._graph

    def jit_view(self, mod: Module) -> "ModuleJitView":
        view = self._jit_views.get(mod.relpath)
        if view is None:
            view = ModuleJitView(
                mod=mod,
                own=self.registry.module_handles.get(mod.relpath, {}),
                bound=self.registry.bound_handles(mod),
                imported=self.registry.imported_handles(mod, self._known_mods),
                aliases=self.registry.module_aliases(mod, self._known_mods),
                registry=self.registry,
            )
            self._jit_views[mod.relpath] = view
        return view

    @staticmethod
    def package_files(pkg_root: Path = PKG_ROOT) -> Dict[str, str]:
        """The real package's sources keyed by relpath — the raw material
        for from_package and for tests that seed a deliberate violation
        into a scratch copy of one module."""
        files: Dict[str, str] = {}
        for p in sorted(pkg_root.rglob("*.py")):
            rel = p.relative_to(pkg_root).as_posix()
            if "__pycache__" in rel:
                continue
            files[rel] = p.read_text()
        return files

    @classmethod
    def from_package(
        cls,
        pkg_root: Path = PKG_ROOT,
        contract_path: Path = CONTRACT_PATH,
        paths: Optional[Sequence[str]] = None,
    ) -> "Project":
        """The real tree. ``paths`` restricts which files findings are
        REPORTED for — the whole package is still parsed, because the
        rules are whole-program (the jit-factory registry in models//ops/
        is what keeps a serve/-only run from false-positive-flagging
        factory calls)."""
        files = cls.package_files(pkg_root)
        doc_path = pkg_root.parent / "docs" / "protocol.md"
        protocol_doc = doc_path.read_text() if doc_path.exists() else None
        contract = None
        if contract_path.exists():
            contract = json.loads(contract_path.read_text())
        project = cls(
            files,
            protocol_doc=protocol_doc,
            contract=contract,
            strict_floors=True,
            display_prefix=pkg_root.name + "/",
        )
        if paths:
            project.report_filter = list(paths)
        return project

    # -- scoping -----------------------------------------------------------

    def device_modules(self) -> List[Module]:
        return [m for m in self.modules if m.relpath in DEVICE_MODULES]

    def bitwise_scope(self, mod: Module, node: ast.AST) -> bool:
        """Whether ``node`` is under the bitwise-determinism contract:
        anywhere in ops//models//parallel/, or on a daemon/scheduler
        fold/merge path (function name carries a fold fragment)."""
        top = mod.relpath.split("/", 1)[0]
        if top in BITWISE_DIRS:
            return True
        if mod.relpath in DEVICE_MODULES:
            for anc in [node, *mod.ancestors(node)]:
                if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    name = anc.name.lower()
                    if any(f in name for f in FOLD_NAME_FRAGMENTS):
                        return True
        return False

    # -- running -----------------------------------------------------------

    def run_raw(self, rules: Optional[Sequence[str]] = None) -> List[Finding]:
        """All findings before pragma/baseline suppression."""
        selected = sorted(set(rules)) if rules else sorted(RULES)
        unknown = [r for r in selected if r not in RULES]
        if unknown:
            raise KeyError(f"unknown rule(s): {', '.join(unknown)}")
        # Notes are per-run state (rules append as they check): reset so
        # a Project reused across runs reports only this run's notes.
        self.notes = []
        out: List[Finding] = []
        for rid in selected:
            out.extend(RULES[rid].check(self))
        if self._graph is not None:
            self.notes.extend(self._graph.notes)
        if self.report_filter is not None:
            out = [f for f in out if self.in_report_scope(f.file)]
        out.sort(key=lambda f: (f.file, f.line, f.rule))
        return out

    def in_report_scope(self, display_path: str) -> bool:
        if self.report_filter is None:
            return True
        rel = display_path
        for m in self.modules:
            if m.display_path == display_path:
                rel = m.relpath
                break
        return any(
            rel == q or rel.startswith(q.rstrip("/") + "/")
            for q in self.report_filter
        )

    def run(
        self,
        rules: Optional[Sequence[str]] = None,
        baseline: Optional[Baseline] = None,
    ) -> List[Finding]:
        """Findings after inline pragmas and the baseline; stale-baseline
        warnings land in ``self.notes``."""
        raw = self.run_raw(rules)
        if baseline is not None:
            # A Baseline is reusable across runs: matched counts are
            # per-run state, reset here so a second run suppresses again.
            baseline._matched = {}
        by_display = {m.display_path: m for m in self.modules}
        kept: List[Finding] = []
        for f in raw:
            mod = by_display.get(f.file)
            if mod is not None and mod.suppressed(f.rule, f.line):
                continue
            if baseline is not None and baseline.suppresses(f):
                continue
            kept.append(f)
        if baseline is not None:
            self.notes.extend(baseline.stale())
        return kept

    def finding(
        self,
        mod: Module,
        node: ast.AST,
        rule_id: str,
        message: str,
        chain: Sequence[Tuple[str, int, str]] = (),
    ) -> Finding:
        registered = RULES.get(rule_id)
        return Finding(
            rule=rule_id,
            file=mod.display_path,
            line=getattr(node, "lineno", 1),
            symbol=mod.enclosing_symbol(node),
            message=message,
            family=registered.family if registered else "misc",
            chain=tuple(chain),
        )


# ---------------------------------------------------------------------------
# rule family 1: lock discipline
# ---------------------------------------------------------------------------

#: Call targets that always touch the device (dispatch or transfer).
_DEVICE_CALL_NAMES = frozenset(
    ("block_until_ready", "device_get", "device_put")
)
#: Compile-path call targets: host work that must not hold _DEVICE_LOCK.
_COMPILE_CALL_NAMES = frozenset(
    ("lower", "compile", "aot_prime", "cost_analysis")
)


@dataclass
class ModuleJitView:
    """Per-module resolution context for jit-handle call sites."""

    mod: Module
    own: Dict[str, Tuple[int, ...]]
    bound: Dict[str, List[Tuple[Optional[ast.AST], Tuple[int, ...]]]]
    imported: Dict[str, Tuple[int, ...]]
    aliases: Dict[str, str]
    registry: JitRegistry

    def resolve_call(self, call: ast.Call) -> Optional[Tuple[Tuple[int, ...], str]]:
        """(donated positions, why) when this call dispatches a ledgered
        jit handle, else None."""
        dn = dotted_name(call.func)
        if dn is not None and dn in self.bound:
            enclosing: List[ast.AST] = []
            fn = _enclosing_function(self.mod, call)
            while fn is not None:
                enclosing.append(fn)
                fn = _enclosing_function(self.mod, fn)
            for scope, don in self.bound[dn]:
                if scope is None or scope in enclosing:
                    return don, f"{dn} is bound from a jit factory"
        name = terminal_name(call.func)
        if name is None:
            return None
        if isinstance(call.func, ast.Name):
            if name in self.own:
                return self.own[name], f"{name} is a ledgered-jit entry"
            if name in self.imported:
                return self.imported[name], f"{name} is an imported ledgered-jit entry"
        elif isinstance(call.func, ast.Attribute):
            base = terminal_name(call.func.value)
            src = self.aliases.get(base or "")
            if src is not None:
                handles = self.registry.module_handles.get(src, {})
                if name in handles:
                    return handles[name], (
                        f"{base}.{name} is a ledgered-jit entry of {src}"
                    )
        return None


def _in_locked_helper(mod: Module, node: ast.AST) -> bool:
    """Whether the node sits in a ``*_locked``-suffixed function — the
    package convention for "the caller already holds the lock" (e.g.
    ``_Job._finalize_locked`` runs under finalize()'s _DEVICE_LOCK)."""
    fn = _enclosing_function(mod, node)
    while fn is not None:
        if fn.name.endswith("_locked"):
            return True
        fn = _enclosing_function(mod, fn)
    return False


def _is_dispatch_call(
    project: Project, mod: Module, call: ast.Call, view: ModuleJitView
) -> Optional[str]:
    """Why this call is a device dispatch, or None. The semantic model:
    ledgered-jit handles (direct, imported, or factory-bound), ``*_fn``
    jit handles, and the jax device/transfer entry points."""
    name = terminal_name(call.func)
    if name is None:
        return None
    if name in _DEVICE_CALL_NAMES:
        return f"jax.{name} touches the device"
    resolved = view.resolve_call(call)
    if resolved is not None:
        return resolved[1] + " (dispatches a device program)"
    if (
        name.endswith("_fn")
        and name not in project.registry.factories
        and not name.startswith(("init_", "plan_", "make_", "build_"))
    ):
        return f"{name} looks like a jit handle (*_fn convention)"
    return None


@rule(
    "device-lock",
    "device-dispatching calls in serve/daemon.py and serve/scheduler.py "
    "must run lexically under `with _DEVICE_LOCK` (and `*_locked` helpers "
    "must be called with a lock held)",
    family="lock",
)
def _check_device_lock(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for mod in project.device_modules():
        view = project.jit_view(mod)
        # *_locked helpers whose bodies DISPATCH: their call sites need
        # _DEVICE_LOCK specifically, not just some lock — a model lock
        # alone must not smuggle a device dispatch past the gate.
        dispatching_helpers: Set[str] = set()
        for fn_node in iter_functions(mod):
            if not fn_node.name.endswith("_locked"):
                continue
            for sub in ast.walk(fn_node):
                if isinstance(sub, ast.Call) and _is_dispatch_call(
                    project, mod, sub, view
                ):
                    dispatching_helpers.add(fn_node.name)
                    break
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = terminal_name(node.func)
            # The *_locked convention, checked from the caller's side: a
            # helper that documents "caller holds the lock" in its name
            # must see the lock lexically held at its call site — the
            # DEVICE lock when the helper dispatches, any lock otherwise
            # — unless the caller is itself a *_locked helper (legal
            # delegation: ITS caller holds the lock).
            if name is not None and name.endswith("_locked"):
                if _in_locked_helper(mod, node):
                    continue
                held = held_locks(mod, node)
                if name in dispatching_helpers and "_DEVICE_LOCK" not in held:
                    out.append(
                        project.finding(
                            mod,
                            node,
                            "device-lock",
                            f"call to {name}() without _DEVICE_LOCK held — "
                            "the helper dispatches to the device, and its "
                            "_locked suffix makes THIS call site "
                            "responsible for the lock",
                        )
                    )
                elif not held:
                    out.append(
                        project.finding(
                            mod,
                            node,
                            "device-lock",
                            f"call to {name}() with no lock held — the "
                            "_locked suffix documents a caller-holds-the-"
                            "lock contract",
                        )
                    )
                continue
            why = _is_dispatch_call(project, mod, node, view)
            if why is None:
                continue
            if "_DEVICE_LOCK" in held_locks(mod, node):
                continue
            if _in_locked_helper(mod, node):
                continue  # caller holds the lock (checked at its call site)
            out.append(
                project.finding(
                    mod,
                    node,
                    "device-lock",
                    f"device dispatch outside _DEVICE_LOCK: {why}; concurrent "
                    "sharded dispatches can deadlock the backend "
                    "(daemon threading contract)",
                )
            )
    return out


@rule(
    "compile-outside-lock",
    "compile-path calls (lower/compile/aot_prime/cost_analysis) must NOT "
    "hold _DEVICE_LOCK — compiles are host work and would stall serving",
    family="lock",
)
def _check_compile_outside_lock(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for mod in project.device_modules():
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = terminal_name(node.func)
            if name not in _COMPILE_CALL_NAMES:
                continue
            if "_DEVICE_LOCK" not in held_locks(mod, node):
                continue
            out.append(
                project.finding(
                    mod,
                    node,
                    "compile-outside-lock",
                    f"compile-path call .{name}() under _DEVICE_LOCK: compiles "
                    "are pure host work — holding the device lock through one "
                    "stalls every live dispatch for seconds (PR 13 hardening)",
                )
            )
    return out


@rule(
    "lock-order",
    "_DEVICE_LOCK is innermost by contract: lexically acquiring any "
    "other lock under it risks deadlock (interprocedural orderings and "
    "general inversions are lock-graph-cycle's job)",
    family="lock",
)
def _check_lock_order(project: Project) -> List[Finding]:
    # Lexical only, by design: interprocedural orderings (a caller holds
    # _DEVICE_LOCK into a function that locks) are lock-graph-cycle's
    # job — there they are edges, and only a CYCLE is a finding, which
    # keeps the by-name call-resolution over-approximation from flagging
    # every lock ever taken downstream of a device section.
    out: List[Finding] = []
    for mod in project.modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.With):
                continue
            inner_names = [
                lock_name(item.context_expr)
                for item in node.items
                if lock_name(item.context_expr) is not None
            ]
            if not inner_names:
                continue
            enclosing = held_locks(mod, node)
            for i, inner in enumerate(inner_names):
                # `with A, B:` acquires B while holding A — earlier items
                # of the same statement are part of the held stack.
                outer_stack = enclosing + inner_names[:i]
                if "_DEVICE_LOCK" not in outer_stack or inner == "_DEVICE_LOCK":
                    continue
                out.append(
                    project.finding(
                        mod,
                        node,
                        "lock-order",
                        f"acquires {inner} while holding _DEVICE_LOCK; "
                        "_DEVICE_LOCK is the INNERMOST lock by contract "
                        "(after any job/model lock, never before one)",
                    )
                )
    return out


@rule(
    "lock-graph-cycle",
    "whole-program lock-order graph over every named lock (edges from "
    "lexical nesting AND from call paths that enter a function with a "
    "lock held); any cycle is a deadlock an interleaving can reach",
    family="lock",
)
def _check_lock_graph_cycle(project: Project) -> List[Finding]:
    graph = project.graph
    #: edge (outer lock id → inner lock id) → first witnessing site
    edges: Dict[Tuple[str, str], Tuple[Module, ast.AST, str]] = {}
    for mod in project.modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.With):
                continue
            inner_names = [
                lock_name(item.context_expr)
                for item in node.items
                if lock_name(item.context_expr) is not None
            ]
            if not inner_names:
                continue
            enclosing = [_lock_id(mod, l) for l in held_locks(mod, node)]
            fn = _enclosing_function(mod, node)
            entered: Set[str] = set()
            if fn is not None:
                key = (mod.relpath, mod.enclosing_symbol(fn))
                entered = graph.entered_holding.get(key, set())
            for i, inner in enumerate(inner_names):
                inner_id = _lock_id(mod, inner)
                lexical = enclosing + [_lock_id(mod, l) for l in inner_names[:i]]
                for outer_id in lexical:
                    if outer_id != inner_id:
                        edges.setdefault(
                            (outer_id, inner_id), (mod, node, "nested with")
                        )
                for outer_id in sorted(entered):
                    if outer_id != inner_id and outer_id not in lexical:
                        edges.setdefault(
                            (outer_id, inner_id),
                            (mod, node, "lock held by a caller on the path here"),
                        )
    # Cycle detection: iterative DFS over the lock digraph; every back
    # edge closes a cycle. Reported once per cycle (canonicalized by its
    # sorted member set) at the back edge's witness site, with the full
    # edge chain as the finding's witness.
    adj: Dict[str, List[str]] = {}
    for outer, inner in edges:
        adj.setdefault(outer, []).append(inner)
    for vals in adj.values():
        vals.sort()
    out: List[Finding] = []
    seen_cycles: Set[Tuple[str, ...]] = set()

    def bare(lock_id: str) -> str:
        return lock_id.rsplit(":", 1)[-1]

    for start in sorted(adj):
        stack: List[Tuple[str, List[str]]] = [(start, [start])]
        visited_from_start: Set[str] = set()
        while stack:
            node_id, path = stack.pop()
            for nxt in adj.get(node_id, ()):  # sorted → deterministic
                if nxt == start:
                    cycle = tuple(path)
                    canon = tuple(sorted(cycle))
                    if canon in seen_cycles:
                        continue
                    seen_cycles.add(canon)
                    closing = edges[(node_id, start)]
                    chain = []
                    hops = list(zip(cycle, cycle[1:] + (cycle[0],)))
                    for outer, inner in hops:
                        wmod, wnode, how = edges[(outer, inner)]
                        chain.append(
                            (
                                wmod.display_path,
                                getattr(wnode, "lineno", 1),
                                f"{bare(outer)} → {bare(inner)} ({how})",
                            )
                        )
                    mod, node, _ = closing
                    pretty = " → ".join(bare(l) for l in cycle + (cycle[0],))
                    out.append(
                        project.finding(
                            mod,
                            node,
                            "lock-graph-cycle",
                            f"lock-order cycle {pretty}: two threads walking "
                            "this ring from different entry points deadlock; "
                            "break the cycle by ordering the acquisitions",
                            chain=chain,
                        )
                    )
                elif nxt not in path and nxt not in visited_from_start:
                    visited_from_start.add(nxt)
                    stack.append((nxt, path + [nxt]))
    out.sort(key=lambda f: (f.file, f.line, f.message))
    return out


@rule(
    "blocking-under-device-lock",
    "no call that TRANSITIVELY blocks (socket I/O, time.sleep, "
    "future/event waits, subprocess, contended Lock.acquire) may execute "
    "while _DEVICE_LOCK is held — the whole serving plane single-files "
    "on that lock, so one blocked holder stalls every dispatch",
    family="lock",
)
def _check_blocking_under_device_lock(project: Project) -> List[Finding]:
    # Encoded exemption, not a pragma: blocking on the DEVICE
    # (block_until_ready / device_get / device_put and jit-handle
    # dispatches) under _DEVICE_LOCK is the lock's entire purpose —
    # CallGraph.blocking_primitive deliberately excludes device waits,
    # so only host-side blocking (sockets, sleeps, futures, subprocess,
    # lock contention) reaches this rule.
    graph = project.graph
    out: List[Finding] = []
    for mod in project.modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if "_DEVICE_LOCK" not in held_locks(mod, node):
                continue
            prim = CallGraph.blocking_primitive(mod, node)
            if prim is not None:
                kind, why = prim
                out.append(
                    project.finding(
                        mod,
                        node,
                        "blocking-under-device-lock",
                        f"{why} while _DEVICE_LOCK is held ({kind}); every "
                        "device dispatch in the process stalls behind it",
                    )
                )
                continue
            fn = _enclosing_function(mod, node)
            caller_key = (
                (mod.relpath, mod.enclosing_symbol(fn)) if fn is not None else None
            )
            for target in graph.resolve_call(mod, fn, node):
                witness = graph.may_block.get(target.key)
                if witness is None:
                    continue
                # Self-recursive edge: the blocking site is in THIS
                # function and already reported directly above.
                if caller_key is not None and target.key == caller_key:
                    continue
                chain = [(f, l, f"[{q}] {n}") for f, q, l, n in witness]
                out.append(
                    project.finding(
                        mod,
                        node,
                        "blocking-under-device-lock",
                        f"calls {target.qualname}() while _DEVICE_LOCK is "
                        "held, and that call can block on "
                        f"{witness[-1][3].split('(')[0].strip()} (see the "
                        "call-chain witness); host-side blocking under the "
                        "device lock stalls every dispatch in the process",
                        chain=chain,
                    )
                )
                break  # one finding per call site, not per candidate target
    out.sort(key=lambda f: (f.file, f.line))
    return out


@rule(
    "thread-shared-state",
    "a write to self.*/module-global state in code reachable from a "
    "threading.Thread target with NO lock held anywhere on the call path "
    "races every other thread that touches the same attribute",
    family="threads",
)
def _check_thread_shared_state(project: Project) -> List[Finding]:
    graph = project.graph
    out: List[Finding] = []
    #: Concurrency-plane modules: the daemon/scheduler/router/fleet/
    #: membership surfaces that actually run multi-threaded. utils/ and
    #: model code execute on these threads too but under the callers'
    #: locks/single-owner conventions — scoping keeps the rule's
    #: signal/noise honest (docs/static_analysis.md).
    def in_scope(mod: Module) -> bool:
        top = mod.relpath.split("/", 1)[0]
        return top in ("serve", "parallel")

    for key in sorted(graph.thread_reachable):
        fn = graph.nodes.get(key)
        if fn is None or not in_scope(fn.mod):
            continue
        if key not in graph.unlocked_reachable:
            continue  # every path into this function holds some lock
        if fn.name == "__init__" or fn.name.endswith("_locked"):
            # __init__ runs before the object is published to other
            # threads; *_locked helpers document caller-holds-the-lock
            # (their call sites are the device-lock rule's job).
            continue
        mod = fn.mod
        #: module-global names this function declares with `global`
        declared_global: Set[str] = {
            name
            for node in ast.walk(fn.fn)
            if isinstance(node, ast.Global)
            for name in node.names
        }
        for node in ast.walk(fn.fn):
            if _enclosing_function(mod, node) is not fn.fn:
                continue
            target: Optional[str] = None
            if isinstance(node, ast.Attribute) and isinstance(
                node.ctx, (ast.Store, ast.Del)
            ):
                if (
                    isinstance(node.value, ast.Name)
                    and node.value.id == "self"
                ):
                    target = f"self.{node.attr}"
            elif isinstance(node, ast.Name) and isinstance(
                node.ctx, (ast.Store, ast.Del)
            ):
                if node.id in declared_global:
                    target = node.id
            if target is None:
                continue
            if held_locks(mod, node):
                continue  # lexically locked at the write
            out.append(
                project.finding(
                    mod,
                    node,
                    "thread-shared-state",
                    f"unlocked write to {target} in {fn.qualname}(), which "
                    "a threading.Thread target reaches with no lock held "
                    "on the path — concurrent readers/writers race on it; "
                    "hold the owning lock or move the write under one",
                )
            )
    out.sort(key=lambda f: (f.file, f.line))
    return out


# ---------------------------------------------------------------------------
# rule family 2: use-after-donate
# ---------------------------------------------------------------------------


def _donated_arg_names(call: ast.Call, positions: Tuple[int, ...]) -> List[str]:
    names = []
    for p in positions:
        if p < len(call.args):
            dn = dotted_name(call.args[p])
            if dn is not None:
                names.append(dn)
    return names


def _accesses(fn_node: ast.AST, dotted: str) -> List[Tuple[Tuple[int, int], str]]:
    """All ordered (position, "load"|"store") accesses to ``dotted`` in
    the function — plain names and ``self.x``-style attributes."""
    acc: List[Tuple[Tuple[int, int], str]] = []
    for node in ast.walk(fn_node):
        dn = None
        ctx = None
        if isinstance(node, ast.Name):
            dn, ctx = node.id, node.ctx
        elif isinstance(node, ast.Attribute):
            dn, ctx = dotted_name(node), node.ctx
        if dn != dotted or ctx is None:
            continue
        kind = "store" if isinstance(ctx, (ast.Store, ast.Del)) else "load"
        acc.append((node_pos(node), kind))
    acc.sort()
    return acc


def _enclosing_stmt(mod: Module, node: ast.AST) -> ast.stmt:
    for anc in mod.ancestors(node):
        if isinstance(anc, ast.stmt):
            return anc
    return node  # pragma: no cover - a Call always sits in a statement


def _accesses_after_call(
    mod: Module, fn_node: ast.AST, call: ast.Call, dotted: str
) -> List[Tuple[Tuple[int, int], str]]:
    """Accesses to ``dotted`` that can execute AFTER the donating call,
    in execution order: the tail of the call's own statement, then the
    following-sibling statements of each enclosing block up to the
    function. Mutually exclusive branches (the ``else`` arm of the
    ``if`` the call sits in) are NOT after the call — a read there can
    never see the donated buffer dead."""
    end = node_end(call)
    stmt = _enclosing_stmt(mod, call)
    acc = [a for a in _accesses(stmt, dotted) if a[0] > end]

    def scan(stmts) -> None:
        for later in stmts:
            if isinstance(later, ast.stmt):
                acc.extend(_accesses(later, dotted))

    node: ast.AST = stmt
    while node is not fn_node:
        parent = getattr(node, "_srml_parent", None)
        if parent is None:
            break
        for fieldname, value in ast.iter_fields(parent):
            if isinstance(value, list) and node in value:
                scan(value[value.index(node) + 1:])
                # Try semantics: handlers/else/finally execute after the
                # try body; finally executes after handlers and else too.
                if isinstance(parent, ast.Try):
                    if fieldname == "body":
                        for h in parent.handlers:
                            scan(h.body)
                        scan(parent.orelse)
                        scan(parent.finalbody)
                    elif fieldname in ("orelse",):
                        scan(parent.finalbody)
                elif isinstance(parent, (ast.For, ast.While, ast.AsyncFor)):
                    if fieldname == "body":
                        scan(parent.orelse)
        if isinstance(parent, ast.ExceptHandler):
            grand = getattr(parent, "_srml_parent", None)
            if isinstance(grand, ast.Try):
                scan(grand.finalbody)
        if parent is fn_node:
            break
        node = parent
    acc.sort()
    return acc


def _assign_target_names(target: ast.AST) -> Iterator[Optional[str]]:
    """Dotted names bound by one assignment target, unpacking tuples/
    lists/starred elements (``state, n = ...``)."""
    if isinstance(target, (ast.Tuple, ast.List)):
        for elt in target.elts:
            yield from _assign_target_names(elt)
    elif isinstance(target, ast.Starred):
        yield from _assign_target_names(target.value)
    else:
        yield dotted_name(target)


def _healed_by_own_statement(mod: Module, call: ast.Call, donated: str) -> bool:
    """``state = update(state, ...)`` — or the tuple-unpack shape
    ``state, n = update(state, ...)`` — heals the donation in the very
    statement that made it: the canonical streaming-fold shapes."""
    stmt = _enclosing_stmt(mod, call)
    if isinstance(stmt, ast.Assign):
        return any(
            name == donated
            for t in stmt.targets
            for name in _assign_target_names(t)
        )
    if isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        return dotted_name(stmt.target) == donated
    return False


@rule(
    "use-after-donate",
    "a name passed at a donate_argnums position of a ledgered jit is "
    "device-donated; reading it again before reassignment is a "
    "use-after-free of the donated buffer",
    family="donation",
)
def _check_use_after_donate(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for mod in project.modules:
        view = project.jit_view(mod)
        for fn_node in iter_functions(mod):
            for node in ast.walk(fn_node):
                if not isinstance(node, ast.Call):
                    continue
                # One visit per call: nested defs are walked separately.
                if _enclosing_function(mod, node) is not fn_node:
                    continue
                resolved = view.resolve_call(node)
                if resolved is None or not resolved[0]:
                    continue
                positions = resolved[0]
                name = terminal_name(node.func)
                for donated in _donated_arg_names(node, positions):
                    if _healed_by_own_statement(mod, node, donated):
                        continue
                    later = _accesses_after_call(mod, fn_node, node, donated)
                    if later and later[0][1] == "load":
                        out.append(
                            project.finding(
                                mod,
                                node,
                                "use-after-donate",
                                f"{donated} is donated to {name}() "
                                f"(donate_argnums) but read again at line "
                                f"{later[0][0][0]} before reassignment — the "
                                "buffer no longer exists after the dispatch",
                            )
                        )
                        continue
                    # Loop-carried reuse: a donating call inside a loop
                    # whose body never rebinds the donated name re-reads
                    # the dead buffer on the next iteration.
                    loop = None
                    for anc in mod.ancestors(node):
                        if isinstance(anc, (ast.For, ast.While)):
                            loop = anc
                            break
                        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                            break
                    if loop is not None:
                        stores = [
                            pos
                            for pos, kind in _accesses(loop, donated)
                            if kind == "store"
                        ]
                        if not stores:
                            out.append(
                                project.finding(
                                    mod,
                                    node,
                                    "use-after-donate",
                                    f"{donated} is donated to {name}() inside "
                                    "a loop that never rebinds it — the next "
                                    "iteration reads the donated buffer",
                                )
                            )
    return out


# ---------------------------------------------------------------------------
# rule family 3: determinism
# ---------------------------------------------------------------------------

_DICT_ITER_METHODS = frozenset(("items", "keys", "values"))


def _is_local_literal_dict(mod: Module, loop_node: ast.AST, name: str) -> bool:
    """Whether ``name`` is assigned a dict literal in the same function
    before the loop — its iteration order is then fixed by construction
    (identical on every process), not by runtime insertion history."""
    fn = _enclosing_function(mod, loop_node)
    if fn is None:
        return False
    loop_line = getattr(loop_node, "lineno", 0)
    for node in ast.walk(fn):
        if (
            isinstance(node, ast.Assign)
            and getattr(node, "lineno", 0) <= loop_line
            and isinstance(node.value, ast.Dict)
            and any(
                isinstance(t, ast.Name) and t.id == name for t in node.targets
            )
        ):
            return True
    return False


def _is_keyed_rebuild(node: ast.AST, gen: "ast.comprehension") -> bool:
    """``{k: f(v) for k, v in d.items()}`` — a key-addressed dict→dict
    rebuild, not a fold: the result is consumed by key, and any later
    ORDERED iteration of it gets its own finding at that site."""
    if not isinstance(node, ast.DictComp):
        return False
    tgt = gen.target
    if isinstance(tgt, ast.Tuple) and tgt.elts and isinstance(tgt.elts[0], ast.Name):
        return (
            isinstance(node.key, ast.Name) and node.key.id == tgt.elts[0].id
        )
    return False


@rule(
    "unsorted-iter",
    "iterating an un-sorted() dict/set in the bitwise-contract modules "
    "(ops/, models/, parallel/, daemon fold/merge paths) makes fold order "
    "process-dependent — the PR 7 unsorted-fold class",
    family="determinism",
)
def _check_unsorted_iter(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for mod in project.modules:
        iters: List[Tuple[ast.AST, ast.AST, Optional[ast.comprehension]]] = []
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.For, ast.AsyncFor)):
                iters.append((node, node.iter, None))
            elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)):
                for gen in node.generators:
                    iters.append((node, gen.iter, gen))
        for node, it, gen in iters:
            if not project.bitwise_scope(mod, node):
                continue
            what = None
            if isinstance(it, ast.Call):
                fn = it.func
                if (
                    isinstance(fn, ast.Attribute)
                    and fn.attr in _DICT_ITER_METHODS
                    and not it.args
                ):
                    what = f".{fn.attr}()"
                    base = fn.value
                    if isinstance(base, ast.Name) and _is_local_literal_dict(
                        mod, node, base.id
                    ):
                        continue  # literal-ordered by construction
                elif isinstance(fn, ast.Name) and fn.id == "set":
                    what = "set(...)"
            elif isinstance(it, ast.Set):
                what = "a set literal"
            if what is None:
                continue
            if gen is not None and _is_keyed_rebuild(node, gen):
                continue
            out.append(
                project.finding(
                    mod,
                    node,
                    "unsorted-iter",
                    f"iterates {what} without sorted() on a bitwise-contract "
                    "path — insertion/hash order varies across processes, so "
                    "the fold is not reproducible; wrap the iterable in "
                    "sorted()",
                )
            )
    return out


_SEEDED_RNG_CTORS = frozenset(
    ("default_rng", "Generator", "RandomState", "SeedSequence", "PRNGKey", "key")
)


@rule(
    "wallclock-entropy",
    "time.time / random.* / unseeded np.random.* in the bitwise-contract "
    "modules injects wall-clock or global-RNG entropy into paths that must "
    "be bitwise-reproducible",
    family="determinism",
)
def _check_wallclock_entropy(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for mod in project.modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            dn = dotted_name(node.func)
            if dn is None:
                continue
            if not project.bitwise_scope(mod, node):
                continue
            parts = dn.split(".")
            bad = None
            if dn == "time.time":
                bad = "time.time() is wall-clock entropy"
            elif parts[0] == "random" and len(parts) > 1:
                bad = f"{dn}() draws from the global stdlib RNG"
            elif (
                len(parts) >= 3
                and parts[-2] == "random"
                and parts[0] in ("np", "numpy")
                and parts[-1] not in _SEEDED_RNG_CTORS
            ):
                bad = f"{dn}() draws from the global numpy RNG"
            if bad is None:
                continue
            out.append(
                project.finding(
                    mod,
                    node,
                    "wallclock-entropy",
                    f"{bad} on a bitwise-contract path; thread a seeded "
                    "np.random.default_rng(seed) (or jax.random key) through "
                    "instead",
                )
            )
    return out


# ---------------------------------------------------------------------------
# rule family 4: wire contract
# ---------------------------------------------------------------------------


def collect_dispatched_ops(mod: Module) -> Dict[str, int]:
    """op strings the daemon dispatches on: ``op == "x"`` comparisons and
    ``op in ("x", "y")`` membership tests against a name ending in "op",
    with constant folding so concatenation/f-strings can't dodge."""
    ops: Dict[str, int] = {}

    def is_op_name(e: ast.AST) -> bool:
        tn = terminal_name(e)
        return tn is not None and (tn == "op" or tn.endswith("_op"))

    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Compare):
            continue
        operands = [node.left, *node.comparators]
        if not any(is_op_name(o) for o in operands):
            continue
        for o, cmp_op in zip(operands[1:], node.ops):
            if isinstance(cmp_op, (ast.Eq, ast.NotEq)):
                s = const_str(o)
                if s is None and is_op_name(o):
                    s = const_str(node.left)
                if s is not None:
                    ops.setdefault(s, node.lineno)
            elif isinstance(cmp_op, (ast.In, ast.NotIn)) and isinstance(
                o, (ast.Tuple, ast.List, ast.Set)
            ):
                for elt in o.elts:
                    s = const_str(elt)
                    if s is not None:
                        ops.setdefault(s, node.lineno)
    return ops


def collect_known_ops(mod: Module) -> Optional[Set[str]]:
    """The ``_KNOWN_OPS = frozenset((...))`` clamp literal, AST-parsed."""
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Assign):
            continue
        if not any(terminal_name(t) == "_KNOWN_OPS" for t in node.targets):
            continue
        known: Set[str] = set()
        for sub in ast.walk(node.value):
            s = const_str(sub)
            if s is not None:
                known.add(s)
        return known
    return None


@rule(
    "wire-op-clamp",
    "every op string the daemon dispatches must appear in _KNOWN_OPS (the "
    "metrics-label clamp) and docs/protocol.md (the frozen wire contract)",
    family="wire",
)
def _check_wire_op_clamp(project: Project) -> List[Finding]:
    out: List[Finding] = []
    daemons = [m for m in project.modules if m.relpath == "serve/daemon.py"]
    for mod in daemons:
        dispatched = collect_dispatched_ops(mod)
        known = collect_known_ops(mod)
        if project.strict_floors and len(dispatched) < 15:
            out.append(
                Finding(
                    "wire-op-clamp",
                    mod.display_path,
                    1,
                    "<module>",
                    f"only {len(dispatched)} dispatched ops found — the "
                    "dispatch shape or the op collector regressed",
                )
            )
        if known is None:
            out.append(
                Finding(
                    "wire-op-clamp",
                    mod.display_path,
                    1,
                    "<module>",
                    "_KNOWN_OPS frozenset literal not found in serve/daemon.py",
                )
            )
            continue
        for op, line in sorted(dispatched.items()):
            if op not in known:
                out.append(
                    Finding(
                        "wire-op-clamp",
                        mod.display_path,
                        line,
                        "<module>",
                        f'op "{op}" is dispatched but missing from the '
                        "_KNOWN_OPS metrics-label clamp (its telemetry would "
                        'record under op="unknown")',
                    )
                )
            if project.protocol_doc is not None and not re.search(
                rf"\b{re.escape(op)}\b", project.protocol_doc
            ):
                out.append(
                    Finding(
                        "wire-op-clamp",
                        mod.display_path,
                        line,
                        "<module>",
                        f'op "{op}" is dispatched but absent from '
                        "docs/protocol.md (the frozen wire contract)",
                    )
                )
    return out


def _dict_return_keys(mod: Module) -> Dict[str, Set[str]]:
    """def name → constant keys of returned dict literals, for resolving
    ``**helper()`` expansions one level deep."""
    returns: Dict[str, Set[str]] = {}
    for fn_node in iter_functions(mod):
        keys: Set[str] = set()
        for ret in ast.walk(fn_node):
            if isinstance(ret, ast.Return) and isinstance(ret.value, ast.Dict):
                for k in ret.value.keys:
                    s = const_str(k) if k is not None else None
                    if s is not None:
                        keys.add(s)
        if keys:
            returns.setdefault(fn_node.name, set()).update(keys)
    return returns


def _scrape_ack_call(
    mod: Module,
    node: ast.Call,
    returns: Dict[str, Set[str]],
    fields: Set[str],
) -> bool:
    """When ``node`` is an ack send (``send_json`` arg 1 /
    ``_send_arrays_counted`` arg 3), add its constant dict keys to
    ``fields`` and return True. Inline literals AND acks built in a
    local variable first (its dict-literal assignment and
    ``payload["k"] = ...`` grows in the same function) are resolved, plus
    ``**helper()`` expansions one level into same-module helper returns.
    Subscript stores on UNRELATED dicts are deliberately not counted:
    over-collection would mask a removed ack field behind any
    identically-named key (the gate must err toward reporting)."""
    name = terminal_name(node.func)
    if name == "send_json" and len(node.args) >= 2:
        arg = node.args[1]
    elif name == "_send_arrays_counted" and len(node.args) >= 4:
        arg = node.args[3]
    else:
        return False

    def scrape_dict(d: ast.Dict) -> None:
        for k, v in zip(d.keys, d.values):
            if k is None:  # ** expansion
                if isinstance(v, ast.Call):
                    helper = terminal_name(v.func)
                    fields.update(returns.get(helper, set()))
                continue
            s = const_str(k)
            if s is not None:
                fields.add(s)

    if isinstance(arg, ast.Dict):
        scrape_dict(arg)
        return True
    sender = _enclosing_function(mod, node)
    if not isinstance(arg, ast.Name) or sender is None:
        return True
    # Ack built in a local first: scrape its dict-literal assignment
    # and every constant subscript-store on THAT name.
    for sub in ast.walk(sender):
        if (
            isinstance(sub, ast.AnnAssign)
            and isinstance(sub.target, ast.Name)
            and sub.target.id == arg.id
            and isinstance(sub.value, ast.Dict)
        ):
            scrape_dict(sub.value)
        elif isinstance(sub, ast.Assign):
            if (
                any(
                    isinstance(t, ast.Name) and t.id == arg.id
                    for t in sub.targets
                )
                and isinstance(sub.value, ast.Dict)
            ):
                scrape_dict(sub.value)
            elif (
                len(sub.targets) == 1
                and isinstance(sub.targets[0], ast.Subscript)
                and isinstance(sub.targets[0].value, ast.Name)
                and sub.targets[0].value.id == arg.id
            ):
                s = const_str(sub.targets[0].slice)
                if s is not None:
                    fields.add(s)
    return True


def collect_ack_fields(mod: Module) -> Set[str]:
    """Constant ack-dict field names the daemon answers with, module-wide
    (see :func:`_scrape_ack_call` for the resolution rules)."""
    returns = _dict_return_keys(mod)
    fields: Set[str] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call):
            _scrape_ack_call(mod, node, returns, fields)
    return fields


def _contract_ack_union(contract: Dict[str, Any]) -> Set[str]:
    """Every ack field the snapshot promises, across formats: the v1
    flat list, or the union of the v2 per-op + common schemas."""
    want = set(contract.get("ack_fields", []))
    for schema in contract.get("ops", {}).values():
        want.update(schema.get("ack", []))
    want.update(contract.get("common", {}).get("ack", []))
    return want


@rule(
    "ack-contract",
    "ack-dict fields are an additive wire contract: a field in the "
    "checked-in snapshot (tools/analyze_contract.json) may never disappear "
    "from the daemon's answers",
    family="wire",
)
def _check_ack_contract(project: Project) -> List[Finding]:
    out: List[Finding] = []
    if project.contract is None:
        return out
    want = _contract_ack_union(project.contract)
    daemons = [m for m in project.modules if m.relpath == "serve/daemon.py"]
    if not daemons:
        return out
    have: Set[str] = set()
    for mod in daemons:
        have |= collect_ack_fields(mod)
    for fieldname in sorted(want - have):
        out.append(
            Finding(
                "ack-contract",
                daemons[0].display_path,
                1,
                "<module>",
                f'ack field "{fieldname}" is in the wire-contract snapshot '
                "but no longer answered by the daemon — ack fields may only "
                "be ADDED (clients key on them); restore it or version the "
                "protocol",
            )
        )
    new = sorted(have - want)
    if new:
        project.notes.append(
            "new ack field(s) not yet in tools/analyze_contract.json "
            f"(additive, allowed): {', '.join(new)} — run "
            "`python -m spark_rapids_ml_tpu.tools.analyze --write-contract`"
        )
    return out


def _req_reads_in(
    nodes: Sequence[ast.AST], req_names: Set[str], fields: Set[str]
) -> None:
    """Request fields read in ``nodes`` (already-walked AST nodes — this
    does NOT recurse): ``req["k"]``, ``req.get("k")``, and
    ``_opt(req, "k", default)`` for any request-dict alias in
    ``req_names``."""
    for node in nodes:
        if isinstance(node, ast.Subscript):
            base = node.value
            if isinstance(base, ast.Name) and base.id in req_names:
                s = const_str(node.slice)
                if s is not None:
                    fields.add(s)
        elif isinstance(node, ast.Call):
            fn = node.func
            if (
                isinstance(fn, ast.Attribute)
                and fn.attr == "get"
                and isinstance(fn.value, ast.Name)
                and fn.value.id in req_names
                and node.args
            ):
                s = const_str(node.args[0])
                if s is not None:
                    fields.add(s)
            elif (
                terminal_name(fn) == "_opt"
                and len(node.args) >= 2
                and isinstance(node.args[0], ast.Name)
                and node.args[0].id in req_names
            ):
                s = const_str(node.args[1])
                if s is not None:
                    fields.add(s)


def collect_op_schemas(
    project: Project, mod: Module
) -> Tuple[Dict[str, Dict[str, Set[str]]], Dict[str, Set[str]]]:
    """Per-op wire schemas, statically extracted from the daemon's
    ``_dispatch`` chain: for every ``op == "x"`` / ``op in (...)`` arm,
    the request fields the handler READS (``req["k"]`` / ``req.get`` /
    ``_opt``) and the ack fields it ANSWERS (``send_json`` /
    ``_send_arrays_counted`` dicts), followed through helper calls that
    receive ``req``/``conn`` (``self._op_feed(conn, req)``,
    ``_recv_arrays_aligned(conn, req)``, ``self._get_job(req)``, …) to a
    fixpoint over the call graph. Returns ``(ops, common)`` where
    ``common`` holds the pre-dispatch surface every op shares (auth,
    version fence, busy shedding, the error ack)."""
    graph = project.graph
    returns = _dict_return_keys(mod)
    dispatch_fn = None
    for fn_node in iter_functions(mod):
        if fn_node.name == "_dispatch" and _enclosing_class(mod, fn_node):
            dispatch_fn = fn_node
            break
    if dispatch_fn is None:
        return {}, {"req": set(), "ack": set()}

    def scan_scope(
        owner_fn: ast.AST,
        stmts: Sequence[ast.AST],
        req_names: Set[str],
        req_fields: Set[str],
        ack_fields: Set[str],
        visited: Set[Tuple[str, str]],
        depth: int = 0,
    ) -> None:
        """One handler scope: direct reads + acks, then follow helper
        calls that receive the request dict or the connection."""
        all_nodes = [sub for stmt in stmts for sub in ast.walk(stmt)]
        _req_reads_in(all_nodes, req_names, req_fields)
        for node in all_nodes:
            if not isinstance(node, ast.Call):
                continue
            _scrape_ack_call(mod, node, returns, ack_fields)
            if depth >= 6:
                continue
            # Which positional args carry the request dict / conn?
            passed: List[Tuple[int, str]] = []
            for i, arg in enumerate(node.args):
                if isinstance(arg, ast.Name) and (
                    arg.id in req_names or arg.id == "conn"
                ):
                    passed.append((i, arg.id))
            if not passed:
                continue
            for target in graph.resolve_call(mod, owner_fn, node):
                if target.mod.relpath != mod.relpath:
                    continue  # the wire surface lives in the daemon
                if target.key in visited:
                    continue
                visited.add(target.key)
                params = [
                    a.arg for a in target.fn.args.args if a.arg != "self"
                ]
                callee_req: Set[str] = set()
                for pos, argname in passed:
                    if argname == "conn":
                        continue
                    if pos < len(params):
                        callee_req.add(params[pos])
                # default: the package convention names it `req`
                callee_req.add("req")
                scan_scope(
                    target.fn,
                    target.fn.body,
                    callee_req,
                    req_fields,
                    ack_fields,
                    visited,
                    depth + 1,
                )

    # --- the op arms -------------------------------------------------------
    def arm_ops(test: ast.AST) -> List[str]:
        if not isinstance(test, ast.Compare) or len(test.ops) != 1:
            return []
        names = [test.left, *test.comparators]
        if not any(
            (terminal_name(n) or "").split(".")[-1] in ("op",)
            or (terminal_name(n) or "").endswith("_op")
            for n in names
        ):
            return []
        op_strs: List[str] = []
        cmp_op = test.ops[0]
        if isinstance(cmp_op, ast.Eq):
            for side in names:
                s = const_str(side)
                if s is not None:
                    op_strs.append(s)
        elif isinstance(cmp_op, ast.In) and isinstance(
            test.comparators[0], (ast.Tuple, ast.List, ast.Set)
        ):
            for elt in test.comparators[0].elts:
                s = const_str(elt)
                if s is not None:
                    op_strs.append(s)
        return op_strs

    ops: Dict[str, Dict[str, Set[str]]] = {}
    arm_stmt_ids: Set[int] = set()
    for node in ast.walk(dispatch_fn):
        if not isinstance(node, ast.If):
            continue
        if _enclosing_function(mod, node) is not dispatch_fn:
            continue  # _drain_payload-style nested helpers
        for op in arm_ops(node.test):
            schema = ops.setdefault(op, {"req": set(), "ack": set()})
            visited: Set[Tuple[str, str]] = set()
            scan_scope(
                dispatch_fn,
                node.body,
                {"req"},
                schema["req"],
                schema["ack"],
                visited,
            )
        if arm_ops(node.test):
            for stmt in node.body:
                for sub in ast.walk(stmt):
                    arm_stmt_ids.add(id(sub))

    # --- the common pre-dispatch surface -----------------------------------
    common = {"req": set(), "ack": set()}  # type: Dict[str, Set[str]]
    serve_fns = [dispatch_fn]
    for fn_node in iter_functions(mod):
        if fn_node.name in ("_serve_conn_inner", "_op_trace"):
            serve_fns.append(fn_node)
    for fn_node in serve_fns:
        nodes = [
            n
            for n in ast.walk(fn_node)
            if id(n) not in arm_stmt_ids
            and _enclosing_function(mod, n) is fn_node
        ]
        # scan without following calls: the followed helpers belong to
        # the per-op schemas; common is the literal shared preamble
        _req_reads_in(nodes, {"req"}, common["req"])
        for node in nodes:
            if isinstance(node, ast.Call):
                _scrape_ack_call(mod, node, returns, common["ack"])
    return ops, common


@rule(
    "wire-schema",
    "per-op wire schemas (request fields read + ack fields answered by "
    "every daemon op handler) may only ever GROW versus the checked-in "
    "snapshot, and every dispatched op keeps its docs/protocol.md "
    "catalog entry — field removal and doc drift both fail",
    family="wire",
)
def _check_wire_schema(project: Project) -> List[Finding]:
    out: List[Finding] = []
    daemons = [m for m in project.modules if m.relpath == "serve/daemon.py"]
    if not daemons:
        return out
    mod = daemons[0]
    ops, common = collect_op_schemas(project, mod)
    if project.strict_floors and len(ops) < 15:
        out.append(
            Finding(
                "wire-schema",
                mod.display_path,
                1,
                "<module>",
                f"only {len(ops)} op handlers extracted from _dispatch — "
                "the dispatch shape or the schema extractor regressed",
                family="wire",
            )
        )
    # Doc-catalog drift: every dispatched op must keep its own `### <op>`
    # heading in docs/protocol.md (wire-op-clamp only requires a MENTION;
    # deleting the catalog entry while the word survives in prose is the
    # drift this closes).
    if project.protocol_doc is not None:
        for op in sorted(ops):
            if not re.search(
                rf"(?m)^###\s+{re.escape(op)}\b", project.protocol_doc
            ):
                out.append(
                    Finding(
                        "wire-schema",
                        mod.display_path,
                        1,
                        "<module>",
                        f'op "{op}" is dispatched but has no "### {op}" '
                        "catalog entry in docs/protocol.md — the per-op "
                        "contract section third-party clients read",
                        family="wire",
                    )
                )
    contract = project.contract
    if contract is None or "ops" not in contract:
        return out
    snap_common = contract.get("common", {})
    for fieldname in sorted(
        set(snap_common.get("ack", [])) - common["ack"]
    ):
        out.append(
            Finding(
                "wire-schema",
                mod.display_path,
                1,
                "<module>",
                f'common ack field "{fieldname}" (answered on every op\'s '
                "shared path per the snapshot) is no longer emitted",
                family="wire",
            )
        )
    new_bits: List[str] = []
    for op, snap in sorted(contract["ops"].items()):
        if op not in ops:
            out.append(
                Finding(
                    "wire-schema",
                    mod.display_path,
                    1,
                    "<module>",
                    f'op "{op}" is in the wire-schema snapshot but no '
                    "longer dispatched — removing an op breaks every "
                    "client that speaks it; restore it or version the "
                    "protocol",
                    family="wire",
                )
            )
            continue
        have = ops[op]
        for fieldname in sorted(set(snap.get("ack", [])) - have["ack"]):
            out.append(
                Finding(
                    "wire-schema",
                    mod.display_path,
                    1,
                    "<module>",
                    f'op "{op}" no longer answers ack field "{fieldname}" '
                    "(per-op wire-schema snapshot) — ack fields may only "
                    "be ADDED; restore it or version the protocol",
                    family="wire",
                )
            )
        for fieldname in sorted(set(snap.get("req", [])) - have["req"]):
            out.append(
                Finding(
                    "wire-schema",
                    mod.display_path,
                    1,
                    "<module>",
                    f'op "{op}" no longer reads request field '
                    f'"{fieldname}" (per-op wire-schema snapshot) — a '
                    "request option silently became a no-op for every "
                    "client that sets it",
                    family="wire",
                )
            )
        grown_ack = sorted(have["ack"] - set(snap.get("ack", [])))
        grown_req = sorted(have["req"] - set(snap.get("req", [])))
        if grown_ack or grown_req:
            new_bits.append(
                f"{op} (+ack: {', '.join(grown_ack) or '-'}; "
                f"+req: {', '.join(grown_req) or '-'})"
            )
    for op in sorted(set(ops) - set(contract["ops"])):
        new_bits.append(f"new op {op}")
    if new_bits:
        project.notes.append(
            "per-op wire schemas grew (additive, allowed): "
            + "; ".join(new_bits)
            + " — refresh with `python -m spark_rapids_ml_tpu.tools."
            "analyze --write-contract`"
        )
    return out


# ---------------------------------------------------------------------------
# ported regex gates (the engine's first rules)
# ---------------------------------------------------------------------------


@rule(
    "bare-print",
    "library code logs through the package logger, never print() — stdout "
    "belongs to the host application (and Spark's worker protocol); "
    "tools/ and `if __name__ == '__main__'` tails are exempt",
    family="hygiene",
)
def _check_bare_print(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for mod in project.modules:
        if mod.relpath.split("/", 1)[0] == "tools":
            continue
        for node in ast.walk(mod.tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "print"
            ):
                if in_main_guard(mod, node):
                    continue
                out.append(
                    project.finding(
                        mod,
                        node,
                        "bare-print",
                        "bare print() in library code — use the package "
                        "logger (utils/logging.py) or record a metric",
                    )
                )
    return out


_COLLECTIVES = frozenset(
    ("psum", "pmean", "all_gather", "ppermute", "psum_scatter", "all_to_all")
)


@rule(
    "bare-collective",
    "device collectives go through parallel/mapreduce.py — a bare "
    "lax.psum/all_gather outside parallel/ bypasses the collective-trace "
    "booking that audits ICI/DCN movement (docs/mesh.md)",
    family="hygiene",
)
def _check_bare_collective(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for mod in project.modules:
        if mod.relpath.split("/", 1)[0] == "parallel":
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if (
                isinstance(fn, ast.Attribute)
                and fn.attr in _COLLECTIVES
                and terminal_name(fn.value) == "lax"
            ):
                out.append(
                    project.finding(
                        mod,
                        node,
                        "bare-collective",
                        f"bare collective lax.{fn.attr}() outside parallel/ "
                        "— route it through parallel.mapreduce so the "
                        "collective-trace accounting sees it",
                    )
                )
    return out


@rule(
    "socket-timeout",
    "socket.create_connection without an explicit timeout inherits the "
    "global default (None = block forever); one unreachable daemon would "
    "hang its caller instead of failing into the retry/healing path",
    family="hygiene",
)
def _check_socket_timeout(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for mod in project.modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if dotted_name(node.func) != "socket.create_connection" and not (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "create_connection"
                and terminal_name(node.func.value) == "socket"
            ):
                continue
            has_timeout = len(node.args) >= 2 or any(
                kw.arg == "timeout" or kw.arg is None for kw in node.keywords
            )
            if not has_timeout:
                out.append(
                    project.finding(
                        mod,
                        node,
                        "socket-timeout",
                        "socket.create_connection without an explicit "
                        "timeout= — the default (None) blocks forever on an "
                        "unreachable peer",
                    )
                )
    return out


def collect_ledgered_jit_names(mod: Module) -> List[Tuple[str, int]]:
    """(ledger name, line) of every ``ledgered_jit("area.fn", ...)`` /
    ``functools.partial(ledgered_jit, "area.fn", ...)`` registration."""
    out: List[Tuple[str, int]] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = terminal_name(node.func)
        name_arg = None
        if fn == "ledgered_jit" and node.args:
            name_arg = node.args[0]
        elif (
            fn == "partial"
            and len(node.args) >= 2
            and terminal_name(node.args[0]) == "ledgered_jit"
        ):
            name_arg = node.args[1]
        if name_arg is None:
            continue
        s = const_str(name_arg)
        if s is not None:
            out.append((s, node.lineno))
    return out


_LEDGER_NAME_RE = re.compile(r"^[a-z0-9_]+\.[a-z0-9_]+$")


@rule(
    "jit-ledger",
    "every jit entry point in ops/ and models/ registers through "
    "ledgered_jit with a unique `<area>.<fn>` name — a bare jax.jit is "
    "invisible to the compile/flops/bytes attribution every perf PR is "
    "judged with, and a cross-file name collision silently merges two "
    "entry points' accounting",
    family="ledger",
)
def _check_jit_ledger(project: Project) -> List[Finding]:
    out: List[Finding] = []
    names: Dict[str, str] = {}  # ledger name → first registering file
    total = 0
    scoped = [
        m
        for m in project.modules
        if m.relpath.split("/", 1)[0] in ("ops", "models")
    ]
    for mod in scoped:
        for node in ast.walk(mod.tree):
            if (
                isinstance(node, ast.Call)
                and dotted_name(node.func) == "jax.jit"
            ):
                out.append(
                    project.finding(
                        mod,
                        node,
                        "jit-ledger",
                        "bare jax.jit() in ops//models/ — register through "
                        "utils.xprof.ledgered_jit so compile seconds, "
                        "flops, and bytes are attributed to a named entry",
                    )
                )
        for name, line in collect_ledgered_jit_names(mod):
            total += 1
            if not _LEDGER_NAME_RE.match(name):
                out.append(
                    Finding(
                        "jit-ledger",
                        mod.display_path,
                        line,
                        "<module>",
                        f'ledger name "{name}" is not <area>.<fn> — the '
                        "ledger groups and ranks by the dotted convention",
                        family="ledger",
                    )
                )
            first = names.setdefault(name, mod.relpath)
            if first != mod.relpath:
                out.append(
                    Finding(
                        "jit-ledger",
                        mod.display_path,
                        line,
                        "<module>",
                        f'ledger name "{name}" is also registered in '
                        f"{first} — the ledger is process-wide, so a "
                        "cross-file collision merges two unrelated entry "
                        "points' accounting (same-file reuse is the "
                        "deliberate host/device-variant pooling)",
                        family="ledger",
                    )
                )
    if project.strict_floors and len(names) < 35:
        out.append(
            Finding(
                "jit-ledger",
                "spark_rapids_ml_tpu/ops",
                1,
                "<module>",
                f"only {len(names)} ledgered entry points found in ops/ + "
                "models/ — the registration pattern or this collector "
                "regressed",
                family="ledger",
            )
        )
    return out


@rule(
    "hot-path-span",
    "every model hot path (module-level fit_* functions, "
    "transform_matrix/kneighbors methods in models/) runs under a "
    "trace_span — spans are the ONLY source of the per-phase breakdown, "
    "so an unspanned hot path is invisible to every dashboard and every "
    "perf PR",
    family="ledger",
)
def _check_hot_path_span(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for mod in project.modules:
        if mod.relpath.split("/", 1)[0] != "models":
            continue
        if mod.relpath.endswith("__init__.py"):
            continue
        for fn_node in iter_functions(mod):
            cls = _enclosing_class(mod, fn_node)
            is_fit = (
                cls is None
                and _enclosing_function(mod, fn_node) is None
                and fn_node.name.startswith("fit_")
            )
            is_hot_method = cls is not None and fn_node.name in (
                "transform_matrix",
                "kneighbors",
            )
            if not (is_fit or is_hot_method):
                continue
            spanned = any(
                isinstance(sub, ast.Call)
                and terminal_name(sub.func) == "trace_span"
                for sub in ast.walk(fn_node)
            )
            if not spanned:
                out.append(
                    project.finding(
                        mod,
                        fn_node,
                        "hot-path-span",
                        f"model hot path {fn_node.name}() has no "
                        "trace_span — the phase breakdown (metrics "
                        "histogram + run journal) cannot see it",
                    )
                )
    return out


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def rewrite_baseline(
    project: Project,
    old: Optional[Baseline],
    new_findings: Sequence[Finding],
    selected_rules: Optional[Sequence[str]] = None,
) -> Baseline:
    """The --write-baseline merge: this run's new findings become
    accepted, still-live accepted entries keep their MATCHED counts
    (stale ones fall off — the ratchet), and entries a restricted run
    never evaluated (``--rule`` not selecting them, or a path filter
    excluding their file) are preserved verbatim — a partial run must
    not silently un-accept what it did not look at."""
    merged = Baseline.from_findings(new_findings)
    if old is None:
        return merged
    selected = set(selected_rules) if selected_rules else None
    known_files = {m.display_path for m in project.modules}
    for key, cap in old.entries.items():
        rule_id, file_, _sym = key
        if (
            (selected is not None and rule_id not in selected)
            or file_ not in known_files
            or not project.in_report_scope(file_)
        ):
            merged.entries[key] = merged.entries.get(key, 0) + cap
        else:
            used = old._matched.get(key, 0)
            if used:
                merged.entries[key] = merged.entries.get(key, 0) + used
    return merged


def write_contract(project: Project, path: Path = CONTRACT_PATH) -> Dict[str, Any]:
    """Refresh the wire-contract snapshot (v2, per-op): for every daemon
    op, the request fields its handler reads and the ack fields it
    answers; ``common`` is the shared pre-dispatch surface; the flat
    ``ack_fields`` union (the module-wide scrape — a superset of the
    per-op walk, catching sends outside the dispatch chain) stays for
    the ack-contract ratchet."""
    fields: Set[str] = set()
    ops: Dict[str, Dict[str, Set[str]]] = {}
    common: Dict[str, Set[str]] = {"req": set(), "ack": set()}
    for mod in project.modules:
        if mod.relpath == "serve/daemon.py":
            fields |= collect_ack_fields(mod)
            ops, common = collect_op_schemas(project, mod)
    contract = {
        "version": 2,
        "ack_fields": sorted(fields),
        "common": {
            "req": sorted(common["req"]),
            "ack": sorted(common["ack"]),
        },
        "ops": {
            op: {
                "req": sorted(schema["req"]),
                "ack": sorted(schema["ack"]),
            }
            for op, schema in sorted(ops.items())
        },
    }
    path.write_text(json.dumps(contract, indent=2) + "\n")
    return contract


def reverse_dependents(
    project: Project, relpaths: Sequence[str]
) -> List[str]:
    """``relpaths`` plus every module that transitively IMPORTS one of
    them — the reverse import closure. The interprocedural rules read
    whole-program facts, so a change in ops/gram.py can surface a
    finding in serve/daemon.py: restricting a --changed-only run to the
    changed files alone would miss exactly the cross-module findings
    this engine exists to catch."""
    importers: Dict[str, Set[str]] = {}
    for mod_rel, imports in project.graph.module_imports.items():
        for src in imports:
            importers.setdefault(src, set()).add(mod_rel)
    out: Set[str] = {r for r in relpaths if r in project._known_mods}
    work = sorted(out)
    while work:
        cur = work.pop()
        for dep in sorted(importers.get(cur, ())):
            if dep not in out:
                out.add(dep)
                work.append(dep)
    return sorted(out)


def _git_changed_package_files(ref: str, pkg_root: Path = PKG_ROOT) -> List[str]:
    """Package-relative paths of *.py files changed versus ``ref``:
    committed, staged, and unstaged (`git diff <ref>` covers all three
    against the working tree) PLUS untracked files (`git ls-files
    --others`) — the pre-commit loop runs exactly when new modules have
    not been `git add`ed yet, and a brand-new file with a finding must
    not scope itself out of its own report."""
    import subprocess

    out: List[str] = []
    prefix = pkg_root.name + "/"
    for cmd in (
        ["git", "diff", "--name-only", ref, "--", str(pkg_root)],
        ["git", "ls-files", "--others", "--exclude-standard", "--",
         str(pkg_root)],
    ):
        proc = subprocess.run(
            cmd,
            capture_output=True,
            text=True,
            cwd=str(pkg_root.parent),
            timeout=30,
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"{' '.join(cmd[:3])} failed: {proc.stderr.strip()}"
            )
        for line in proc.stdout.splitlines():
            line = line.strip().replace("\\", "/")
            if line.startswith(prefix) and line.endswith(".py"):
                out.append(line[len(prefix):])
    return sorted(set(out))


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m spark_rapids_ml_tpu.tools.analyze",
        description="srml-check: AST invariant analyzer for the "
        "lock/donation/determinism/wire contracts",
    )
    parser.add_argument(
        "paths",
        nargs="*",
        help="package-relative paths to restrict REPORTING to (e.g. "
        "'serve' or 'ops/gram.py'); the whole package is always parsed "
        "for cross-module context. Default: report everything",
    )
    parser.add_argument("--json", action="store_true", help="machine output")
    parser.add_argument(
        "--rule",
        action="append",
        dest="rules",
        metavar="RULE",
        help="run only this rule (repeatable)",
    )
    parser.add_argument(
        "--baseline",
        type=Path,
        default=BASELINE_PATH,
        help="baseline JSON path (default: tools/analyze_baseline.json)",
    )
    parser.add_argument(
        "--no-baseline",
        action="store_true",
        help="report every finding, ignoring the baseline",
    )
    parser.add_argument(
        "--write-baseline",
        action="store_true",
        help="accept the current unsuppressed findings into the baseline",
    )
    parser.add_argument(
        "--write-contract",
        action="store_true",
        help="refresh the wire-contract snapshot (v2: per-op request/ack schemas + the flat ack-field union)",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog"
    )
    parser.add_argument(
        "--changed-only",
        metavar="GIT_REF",
        default=None,
        help="report only findings in modules whose files changed versus "
        "GIT_REF, plus their reverse import-graph dependents (analysis is "
        "still whole-program) — the fast pre-commit mode (CONTRIBUTING.md)",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for rid in sorted(RULES):
            print(f"{rid:26s} [{RULES[rid].family}] {RULES[rid].summary}")
        return 0

    if args.changed_only and args.paths:
        print(
            "srml-check: --changed-only and explicit paths are mutually "
            "exclusive",
            file=sys.stderr,
        )
        return 2

    try:
        project = Project.from_package(paths=args.paths or None)
    except SyntaxError as e:
        print(f"srml-check: cannot parse {e.filename}:{e.lineno}: {e.msg}", file=sys.stderr)
        return 2

    if args.changed_only:
        try:
            changed = _git_changed_package_files(args.changed_only)
        except RuntimeError as e:
            print(f"srml-check: {e}", file=sys.stderr)
            return 2
        scope = reverse_dependents(project, changed)
        project.report_filter = scope
        print(
            f"srml-check: --changed-only {args.changed_only}: "
            f"{len(changed)} changed file(s) → reporting on {len(scope)} "
            "module(s) (changed + reverse dependents)",
            file=sys.stderr,
        )

    if args.write_contract:
        contract = write_contract(project)
        print(
            f"wrote {CONTRACT_PATH} ({len(contract['ack_fields'])} ack fields)"
        )
        project.contract = contract

    baseline = None if args.no_baseline else Baseline.load(args.baseline)
    try:
        findings = project.run(rules=args.rules, baseline=baseline)
    except KeyError as e:
        print(f"srml-check: {e.args[0]}", file=sys.stderr)
        return 2

    if args.write_baseline:
        # run() already consumed the old baseline, so `findings` are
        # exactly the NEW ones; rewrite_baseline keeps still-live accepted
        # entries (and preserves what a --rule/path-restricted run never
        # evaluated), dropping only the stale.
        merged = rewrite_baseline(project, baseline, findings, args.rules)
        args.baseline.write_text(merged.as_json())
        print(f"wrote {args.baseline} ({sum(merged.entries.values())} accepted findings)")
        return 0

    if args.json:
        print(
            json.dumps(
                {
                    "findings": [f.as_dict() for f in findings],
                    "notes": project.notes,
                    "rules": sorted(args.rules or RULES),
                    "ok": not findings,
                },
                indent=2,
            )
        )
    else:
        for f in findings:
            print(f.format())
        for note in project.notes:
            print(f"note: {note}", file=sys.stderr)
        if not findings:
            n = len(args.rules) if args.rules else len(RULES)
            print(
                f"srml-check: OK — {len(project.modules)} files, {n} rules, "
                "zero unsuppressed findings"
            )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
