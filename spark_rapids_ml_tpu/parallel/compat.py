"""JAX version compatibility for the SPMD entry points.

The package targets the jax>=0.7 public API (``jax.shard_map`` with the
``check_vma`` flag). Older runtimes (0.4.x) carry the same transform as
``jax.experimental.shard_map.shard_map`` with the flag spelled
``check_rep``. Every internal call site goes through :func:`shard_map`
here so one interpreter-wide resolution — not 14 scattered try/excepts —
decides which spelling the runtime speaks.
"""

from __future__ import annotations

import inspect

import jax

__all__ = ["shard_map"]


def _resolve():
    native = getattr(jax, "shard_map", None)
    if native is None:
        from jax.experimental.shard_map import shard_map as native
    # Feature-detect the flag SPELLING rather than inferring it from where
    # the function lives: intermediate jax versions promoted jax.shard_map
    # while still spelling the flag check_rep.
    try:
        params = inspect.signature(native).parameters
        flag = "check_vma" if "check_vma" in params else "check_rep"
    except (TypeError, ValueError):  # C-level callable with no signature
        flag = "check_vma"
    return native, flag


_SHARD_MAP, _CHECK_FLAG = _resolve()


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None):
    """``jax.shard_map`` with the 0.7 signature on every supported jax.

    ``check_vma=None`` leaves the runtime default; an explicit bool maps
    to ``check_rep`` on pre-0.7 runtimes (same semantics: skip the
    varying/replication analysis that pallas_call outputs lack).
    """
    kwargs = {"mesh": mesh, "in_specs": in_specs, "out_specs": out_specs}
    if check_vma is not None:
        kwargs[_CHECK_FLAG] = check_vma
    return _SHARD_MAP(f, **kwargs)
