"""Principal Component Analysis — the reference's one shipped algorithm,
rebuilt TPU-native.

Reference call stack being replaced (SURVEY.md §3.1):
``com.nvidia.spark.ml.feature.PCA.fit`` (PCA.scala:27-37) →
``RapidsPCA.fit`` (RapidsPCA.scala:72-80) →
``RapidsRowMatrix.computePrincipalComponentsAndExplainedVariance``
(RapidsRowMatrix.scala:59-102): per-partition cuBLAS Gram (dgemmCov) →
JVM ``RDD.reduce`` → single-GPU cuSOLVER eig (calSVD) → top-k slice.

Here the whole fit is ONE compiled SPMD program: row-sharded fused stats
(count/Σx/XᵀX) → ``psum`` over ICI → eigh + sign-flip + slice on device.
No host round-trip between phases, no per-call device context setup
(the anti-pattern noted at SURVEY.md §3.4), and mean-centering is fused
(fixing the reference's ETL-preprocess stub, SURVEY.md §2.4).

Transform matches ``RapidsPCAModel.transform`` (RapidsPCA.scala:122-166):
y = x @ pc with NO re-centering (the reference's CPU fallback is
``pc.transpose.multiply(v)``, :159 — centering is the caller's concern),
and the principal-components matrix stays device-resident across batches
(avoiding the reference's per-batch host→device PC copy, rapidsml_jni.cu:85).
"""

from __future__ import annotations

import functools
from typing import Any, Iterable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from spark_rapids_ml_tpu import config
from spark_rapids_ml_tpu.core.dataset import as_matrix, with_column
from spark_rapids_ml_tpu.core.params import (
    Estimator,
    HasInputCol,
    HasOutputCol,
    Model,
    ParamDecl,
    ParamValidators,
    TypeConverters,
)
from spark_rapids_ml_tpu.core.persistence import MLReadable, MLWritable
from spark_rapids_ml_tpu.ops import gram as gram_ops
from spark_rapids_ml_tpu.ops.eigh import (
    pca_from_gram,
    pca_from_gram_host,
    pca_from_gram_randomized,
)
from spark_rapids_ml_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    default_mesh,
    make_mesh,
)
from spark_rapids_ml_tpu.parallel.sharding import pad_rows, row_sharding, shard_rows
from spark_rapids_ml_tpu.utils.profiling import trace_span
from spark_rapids_ml_tpu.parallel.compat import shard_map
from spark_rapids_ml_tpu.utils.xprof import ledgered_jit


class PCASolution(NamedTuple):
    """Fit result of the pure-JAX core (host-side numpy)."""

    pc: np.ndarray  # (d, k) principal components, columns descending
    explained_variance: np.ndarray  # (k,) σᵢ/Σσ — reference semantics
    sigma: np.ndarray  # (d,) singular values √λ of the (centered) Gram
    mean: np.ndarray  # (d,) column means observed during fit
    n_rows: int


# ---------------------------------------------------------------------------
# Pure-JAX core
# ---------------------------------------------------------------------------


def _use_host_finalize(mesh: Mesh) -> bool:
    """Host-LAPACK eig finalize on TPU meshes (config ``finalize``).

    eigh is iterative and XLA executes it poorly on TPU for large d; the d×d
    Gram is tiny to fetch, and the reference likewise ran its eig as a
    separate single-device stage (RapidsRowMatrix.scala:70-86)."""
    mode = config.get("finalize")
    if mode == "host":
        return True
    if mode == "device":
        return False
    platform = next(iter(mesh.devices.flat)).platform
    return platform != "cpu"


@functools.lru_cache(maxsize=32)
def _fit_fn(
    mesh: Mesh,
    k: int,
    mean_center: bool,
    two_d: bool,
    cd: str,
    ad: str,
    fuse_finalize: bool = True,
    gram_algo: str = "auto",
    use_pallas: bool = False,
    solver: str = "full",
):
    # `use_pallas` is unused in the body but MUST be in the cache key:
    # local_stats reads config.use_pallas at trace time, so a config flip
    # has to miss the cache and retrace (same reason cd/ad are keys).
    """Compile the fit (stats + psum [+ eig finalize]) once per config.

    ``cd``/``ad`` (compute/accum dtype names) are part of the cache key so a
    config change recompiles rather than silently reusing old-dtype programs.
    With ``fuse_finalize=False`` the program stops at the replicated stats
    (host finalize path).
    """

    def fit(x, mask):
        if two_d:
            if gram_algo == "ring":
                shard_fn = functools.partial(
                    gram_ops._stats_shard_ring,
                    compute_dtype=cd,
                    accum_dtype=ad,
                    n_model=mesh.shape[MODEL_AXIS],
                )
            else:
                shard_fn = lambda xb, mb: gram_ops._stats_shard_2d(xb, mb, cd, ad)
            stats = shard_map(
                shard_fn,
                mesh=mesh,
                in_specs=(P(DATA_AXIS, MODEL_AXIS), P(DATA_AXIS)),
                out_specs=(P(), P(), P(MODEL_AXIS, None)),
                # count/colsum are value-replicated over `model` after the
                # gather/ring, which VMA inference can't prove statically.
                check_vma=False,
            )
        else:
            stats = shard_map(
                lambda xb, mb: gram_ops._stats_shard(xb, mb, cd, ad),
                mesh=mesh,
                in_specs=(P(DATA_AXIS, None), P(DATA_AXIS)),
                out_specs=(P(), P(), P()),
                check_vma=False,  # pallas_call out_shapes carry no vma annotation
            )
        count, colsum, g = stats(x, mask)
        if not fuse_finalize:
            return count, colsum, g
        g, mean = gram_ops.finalize_gram(count, colsum, g, mean_center)
        if solver == "randomized":
            if two_d:
                # Keep the Gram model-sharded through the eigensolve too
                # (docs/mesh.md "Model-parallel Gram/eigh"): for widths
                # over the per-device accumulator budget this is the only
                # shape in which the finalize fits at all.
                from spark_rapids_ml_tpu.ops.eigh import (
                    pca_from_gram_model_sharded,
                )

                pc, ev, s = pca_from_gram_model_sharded(g, k, mesh)
            else:
                pc, ev, s = pca_from_gram_randomized(g, k)
        else:
            pc, ev, s = pca_from_gram(g, k)
        return pc, ev, s, mean, count

    return ledgered_jit("pca.fit", fit)


_SOLVERS = ("full", "randomized")


def _resolve_solver(solver: Optional[str]) -> str:
    """None/"auto" → config ``solver``; otherwise validate explicitly —
    a typo must not silently select the slow exact path."""
    if solver is None or solver == "auto":
        solver = config.get("solver")
    if solver == "auto":  # config itself left at/reset to auto → exact path
        solver = "full"
    if solver not in _SOLVERS:
        raise ValueError(f"solver must be one of {_SOLVERS} or 'auto', got {solver!r}")
    return solver


def _finalize_on_host(count, colsum, gram, mean_center: bool, k: int):
    """Centering + calSVD-equivalent on host float64 (TPU finalize path)."""
    count = float(np.asarray(count))
    colsum = np.asarray(colsum, dtype=np.float64)
    g = np.asarray(gram, dtype=np.float64)
    n = max(count, 1.0)
    mean = colsum / n
    if mean_center:
        g = g - np.outer(mean, colsum)
    pc, ev, s = pca_from_gram_host(g, k)
    return pc, ev, s, mean, count


def fit_pca(
    x: np.ndarray,
    k: int,
    mean_center: bool = True,
    mesh: Optional[Mesh] = None,
    solver: Optional[str] = None,
) -> PCASolution:
    """Fit PCA on a host matrix, sharding rows (and features if the mesh has a
    model axis > 1) across the mesh.

    ``solver``: None → config ``solver``; "full" = exact eigh finalize
    (host LAPACK on TPU), "randomized" = on-device subspace iteration
    (:func:`...ops.eigh.pca_from_gram_randomized`).
    """
    mesh = mesh or default_mesh()
    solver = _resolve_solver(solver)
    d = x.shape[1]
    if not 0 < k <= d:
        # require(k > 0 && k <= n) — RapidsRowMatrix.scala:60
        raise ValueError(f"k = {k} out of range (0, n = {d}]")
    two_d = mesh.shape[MODEL_AXIS] > 1 and d % mesh.shape[MODEL_AXIS] == 0
    # Capacity gate: a (d, d) accumulator over the per-device budget must
    # stay model-sharded end to end (docs/mesh.md) — with a model axis the
    # 2-D path + sharded eigensolve carries it; without one this raises
    # GramCapacityError here instead of OOMing mid-fit.
    must_shard = gram_ops.require_gram_capacity(d, mesh)
    if must_shard and not two_d:
        raise gram_ops.GramCapacityError(
            f"d={d} needs the model-sharded Gram but is not divisible by "
            f"the model axis ({mesh.shape[MODEL_AXIS]}); pick a divisor "
            "mesh_model_axis (docs/mesh.md 'Model-parallel Gram/eigh')"
        )
    with trace_span("compute cov"):  # phase names kept from the reference
        if two_d:
            from jax.sharding import NamedSharding

            n_true = x.shape[0]
            xp, mask_np = pad_rows(np.asarray(x), mesh.shape[DATA_AXIS])
            xs = jax.device_put(xp, NamedSharding(mesh, P(DATA_AXIS, MODEL_AXIS)))
            mask = jax.device_put(mask_np, NamedSharding(mesh, P(DATA_AXIS)))
        else:
            xs, mask, n_true = shard_rows(x, mesh)
        # must_shard forces the exact ("full") eigh onto the HOST: a d×d
        # on-device eigh would re-materialize the over-budget Gram on one
        # device, while the host assembles it from the slabs comfortably.
        # The randomized solver instead stays fused and model-sharded
        # (pca_from_gram_model_sharded) — nothing full-width on any chip.
        host_finalize = (
            _use_host_finalize(mesh) or must_shard
        ) and solver != "randomized"
        fit = _fit_fn(
            mesh,
            k,
            mean_center,
            two_d,
            config.get("compute_dtype"),
            config.get("accum_dtype"),
            fuse_finalize=not host_finalize,
            gram_algo=config.get("gram_algorithm"),
            use_pallas=bool(config.get("use_pallas")),
            solver=solver,
        )
        out = fit(xs, mask)
    with trace_span("eig finalize"):
        if host_finalize:
            count, colsum, g = out
            pc, ev, s, mean, _ = _finalize_on_host(count, colsum, g, mean_center, k)
        else:
            pc, ev, s, mean, count = out
            pc, ev, s, mean = jax.device_get((pc, ev, s, mean))
    return PCASolution(
        pc=np.asarray(pc, dtype=np.float64),
        explained_variance=np.asarray(ev, dtype=np.float64),
        sigma=np.asarray(s, dtype=np.float64),
        mean=np.asarray(mean, dtype=np.float64),
        n_rows=n_true,
    )


def fit_pca_stream(
    batches: Iterable[np.ndarray],
    k: int,
    n_cols: int,
    mean_center: bool = True,
    mesh: Optional[Mesh] = None,
    checkpoint_path: Optional[str] = None,
    checkpoint_every: int = 16,
    solver: Optional[str] = None,
) -> PCASolution:
    """Fit PCA over a stream of host row-batches (dataset ≫ HBM).

    The accumulator state lives on device; each batch is row-sharded,
    reduced with psum, and folded in with buffer donation. This is the
    scale path for BASELINE.json config #2 (100M×2048).

    With ``checkpoint_path``, the O(d²) accumulator is atomically persisted
    every ``checkpoint_every`` batches and the fit RESUMES from it if the
    file exists: callers re-supply the same batch iterator and already-
    consumed batches are skipped. (Preemption safety the reference lacks —
    SURVEY.md §5 "failure detection".)

    **Multi-host** (``jax.process_count() > 1``, e.g. a v5e-16 pod):
    ``batches`` is THIS process's local stream — each host reads only its
    own shard of the dataset. Batches are assembled into global arrays via
    the multi-process branch of ``shard_rows`` and iterated in lockstep
    (``lockstep_batches``: uneven stream lengths are fine — exhausted
    hosts contribute empty batches). Checkpoints are written by process 0
    only and must be resumable by every process (shared filesystem);
    because the accumulator is fully replicated, one file restores all.
    """
    if not 0 < k <= n_cols:
        # require(k > 0 && k <= n) — RapidsRowMatrix.scala:60
        raise ValueError(f"k = {k} out of range (0, n = {n_cols}]")
    if checkpoint_every < 1:
        raise ValueError(f"checkpoint_every must be >= 1, got {checkpoint_every}")
    solver = _resolve_solver(solver)  # fail fast, before consuming batches
    from spark_rapids_ml_tpu.core import checkpoint as ckpt
    from spark_rapids_ml_tpu.parallel.sharding import lockstep_batches, shard_rows

    mesh = mesh or default_mesh()
    if gram_ops.require_gram_capacity(n_cols, mesh):
        # The streaming accumulator is REPLICATED on every device (the
        # donated P() state), so a model axis does not shelter it; the
        # model-sharded accumulate is the in-memory fit's 2-D path.
        raise gram_ops.GramCapacityError(
            f"the ({n_cols}, {n_cols}) streaming accumulator is over the "
            "per-device budget and the streaming path keeps it replicated; "
            "use fit_pca with mesh_model_axis > 1 (docs/mesh.md) or raise "
            "SRML_GRAM_DEVICE_BUDGET_MB"
        )
    multiproc = jax.process_count() > 1
    update = gram_ops.streaming_update(mesh)
    state = gram_ops.init_stats(n_cols)
    n_true = 0
    skip_batches = 0
    if checkpoint_path:
        restored = ckpt.load_state(checkpoint_path)
        ckpt.require_consistent_visibility(restored)
        if restored is not None:
            arrays, meta = restored
            if meta.get("n_cols") != n_cols:
                raise ValueError(
                    f"checkpoint at {checkpoint_path} is for n_cols="
                    f"{meta.get('n_cols')}, not {n_cols}"
                )
            state = (
                jnp.asarray(arrays["count"]),
                jnp.asarray(arrays["colsum"]),
                jnp.asarray(arrays["gram"]),
            )
            n_true = int(meta["n_rows"])
            skip_batches = int(meta["n_batches"])
    with trace_span("compute cov"):
        for i, batch in enumerate(lockstep_batches(batches, n_cols)):
            if i < skip_batches:
                continue
            xs, ms, n_b = shard_rows(batch, mesh)
            n_true += n_b
            state = update(state, xs, ms)
            if checkpoint_path and (i + 1) % checkpoint_every == 0:
                count, colsum, g = jax.device_get(state)
                if not multiproc or jax.process_index() == 0:
                    ckpt.save_state(
                        checkpoint_path,
                        {"count": count, "colsum": colsum, "gram": g},
                        {"n_rows": n_true, "n_batches": i + 1, "n_cols": n_cols},
                    )
    if checkpoint_path and (not multiproc or jax.process_index() == 0):
        # Success: remove the checkpoint so a FUTURE fit against the same
        # path starts fresh instead of silently merging this run's
        # accumulator into different data.
        import os

        if os.path.exists(checkpoint_path):
            os.unlink(checkpoint_path)
    return finalize_pca_stats(state, k, mean_center, mesh, n_true, solver=solver)


def finalize_pca_stats(
    state: gram_ops.Stats,
    k: int,
    mean_center: bool,
    mesh: Mesh,
    n_true: int,
    solver: Optional[str] = None,
) -> PCASolution:
    """(count, colsum, gram) accumulator → PCASolution.

    Shared tail of the streaming fit — also the finalize entry point for
    the data-plane daemon, which accumulates the same state from
    executor-fed Arrow batches."""
    solver = _resolve_solver(solver)
    count, colsum, g = state
    n_cols = int(np.asarray(colsum).shape[0])
    if not 0 < k <= n_cols:
        # require(k > 0 && k <= n) — RapidsRowMatrix.scala:60; without this
        # the top-k slice silently clamps and returns fewer components
        raise ValueError(f"k = {k} out of range (0, n = {n_cols}]")
    with trace_span("eig finalize"):
        if _use_host_finalize(mesh) and solver != "randomized":
            pc, ev, s, mean, _ = _finalize_on_host(count, colsum, g, mean_center, k)
        else:
            finalize_fn = (
                pca_from_gram_randomized if solver == "randomized" else pca_from_gram
            )
            finalize = ledgered_jit(
                "pca.finalize",
                lambda c, cs, gg: finalize_fn(
                    gram_ops.finalize_gram(c, cs, gg, mean_center)[0], k
                )
            )
            pc, ev, s = jax.device_get(finalize(count, colsum, g))
            mean = jax.device_get(colsum / jnp.maximum(count, 1))
    return PCASolution(
        pc=np.asarray(pc, dtype=np.float64),
        explained_variance=np.asarray(ev, dtype=np.float64),
        sigma=np.asarray(s, dtype=np.float64),
        mean=np.asarray(mean, dtype=np.float64),
        n_rows=n_true,
    )


# ---------------------------------------------------------------------------
# Estimator / Model (Spark ML contract — reference RapidsPCA.scala)
# ---------------------------------------------------------------------------


class _PCAParams(HasInputCol, HasOutputCol):
    """Params shared by PCA and PCAModel (RapidsPCAParams, RapidsPCA.scala:34-46)."""

    k = ParamDecl(
        "k",
        "number of principal components (> 0)",
        TypeConverters.toInt,
        validator=ParamValidators.gt(0),
    )
    meanCentering = ParamDecl(
        "meanCentering",
        "whether to center data before computing the covariance "
        "(fused on-device here; the reference stubs this to ETL)",
        TypeConverters.toBoolean,
    )

    solver = ParamDecl(
        "solver",
        'eigensolver for the finalize: "auto" (config), "full" (exact '
        'eigh), or "randomized" (on-device subspace iteration — the '
        "TPU-fast path for large feature dims with decaying spectra)",
        TypeConverters.toString,
    )

    def __init__(self, uid=None):
        super().__init__(uid=uid)
        # default true — RapidsPCA.scala:45-46
        self.setDefault(
            meanCentering=True,
            inputCol="features",
            outputCol="pca_features",
            solver="auto",
        )

    def getK(self) -> int:
        return self.getOrDefault(self.k)

    def getMeanCentering(self) -> bool:
        return self.getOrDefault(self.meanCentering)

    def getSolver(self) -> str:
        return self.getOrDefault(self.solver)


class PCA(Estimator, _PCAParams, MLWritable, MLReadable):
    """PCA estimator: ``PCA().setInputCol("features").setK(3).fit(df)``.

    Drop-in shaped for the reference's public API
    (com.nvidia.spark.ml.feature.PCA, PCA.scala:27-37; input is an
    array-of-floats column, README.md:26-37).
    """

    _uid_prefix = "PCA"

    def __init__(self, uid=None, mesh: Optional[Mesh] = None):
        super().__init__(uid=uid)
        self._mesh = mesh

    def setK(self, value: int) -> "PCA":
        return self._set(k=value)

    def setMeanCentering(self, value: bool) -> "PCA":
        return self._set(meanCentering=value)

    def setSolver(self, value: str) -> "PCA":
        return self._set(solver=value)

    def _copy_extra_state(self, source):
        self._mesh = getattr(source, "_mesh", None)

    def _fit(self, dataset) -> "PCAModel":
        x = as_matrix(dataset, self.getInputCol())
        sol = fit_pca(
            x,
            k=self.getK(),
            mean_center=self.getMeanCentering(),
            mesh=self._mesh,
            solver=self.getSolver(),
        )
        model = PCAModel(
            pc=sol.pc,
            explained_variance=sol.explained_variance,
            mean=sol.mean,
        )
        model.uid = self.uid
        # Parent params flow to the model — Model.copy semantics in Spark.
        self._copy_params_to(model)
        return model


class PCAModel(Model, _PCAParams, MLWritable, MLReadable):
    """Fitted PCA model: pc (d, k), explainedVariance (k,).

    (RapidsPCAModel, RapidsPCA.scala:102-166.)
    """

    _uid_prefix = "PCAModel"

    def __init__(
        self,
        pc: Optional[np.ndarray] = None,
        explained_variance: Optional[np.ndarray] = None,
        mean: Optional[np.ndarray] = None,
        uid=None,
    ):
        super().__init__(uid=uid)
        self.pc = None if pc is None else np.asarray(pc)
        self.explainedVariance = (
            None if explained_variance is None else np.asarray(explained_variance)
        )
        self.mean = None if mean is None else np.asarray(mean)
        self._project_cache: dict = {}

    # -- persistence (PCAModelWriter/Reader, RapidsPCA.scala:193-228) ------
    def _model_data(self):
        data = {"pc": self.pc}
        # Omit-when-None (like mean): a legacy-loaded model re-saved with
        # an explainedVariance=None column would reload as a 0-d nan.
        if self.explainedVariance is not None:
            data["explainedVariance"] = self.explainedVariance
        if self.mean is not None:
            data["mean"] = self.mean
        return data

    @classmethod
    def _from_model_data(cls, uid, data):
        return cls(
            pc=data["pc"],
            # Tolerate saves without explainedVariance — the reference's
            # reader does the same for pre-Spark-1.6 models
            # (RapidsPCA.scala:209-213); transform needs only pc.
            explained_variance=data.get("explainedVariance"),
            mean=data.get("mean"),
            uid=uid,
        )

    def _copy_extra_state(self, source):
        self.pc = source.pc
        self.explainedVariance = source.explainedVariance
        self.mean = source.mean
        self._project_cache = {}

    # -- transform ---------------------------------------------------------
    def _projector(self):
        """Jitted y = x @ pc with the PC matrix resident on device.

        The reference re-uploads the PC matrix host→device on every batch
        (rapidsml_jni.cu:85, flagged in SURVEY.md §7(d)); keeping it as a
        captured device constant amortizes it to once per compile. The cache
        is keyed by the dtype config so later config changes recompile.
        """
        key = (config.get("compute_dtype"), config.get("accum_dtype"))
        if key not in self._project_cache:
            pc_dev = jnp.asarray(self.pc, dtype=jnp.dtype(key[0]))
            accum = jnp.dtype(key[1])

            from spark_rapids_ml_tpu.ops.gram import mm_precision

            @ledgered_jit("pca.project")
            def project(x):
                with mm_precision(pc_dev.dtype):
                    return jax.lax.dot_general(
                        x.astype(pc_dev.dtype),
                        pc_dev,
                        (((1,), (0,)), ((), ())),
                        preferred_element_type=accum,
                    )

            self._project_cache[key] = project
        return self._project_cache[key]

    # Daemon serving contract (serve/daemon.py): wire algo name + role →
    # (param naming the output column, canonical column kind).
    _serve_algo = "pca"
    _serve_outputs = (("output", "outputCol", "vec"),)

    def _serve_aot_plan(self, n_rows, n_cols, dtype="float32", k=None):
        """AOT-at-registration plan (serve/daemon.py): the serving jits
        one padded bucket of ``n_rows`` wire-dtype rows dispatches, with
        their abstract arg specs — ``lower().compile()``d when the model
        registers so the first request pays zero compiles. The primed row
        count is what ``run_bucketed`` will actually dispatch for an
        ``n_rows`` batch (its 256-row floor applies), so small serve
        buckets dedupe onto the one real shape instead of compiling
        unreachable executables."""
        if self.pc is None:
            return None
        if int(n_cols) != int(self.pc.shape[0]):
            # Raise, don't degrade: the trace warmup surfaced a wrong
            # width as a shape error too — acking it would pre-mark a
            # shape no real traffic can produce.
            raise ValueError(
                f"warmup n_cols={int(n_cols)} does not match the "
                f"model's fitted width {int(self.pc.shape[0])}"
            )
        from spark_rapids_ml_tpu.parallel.sharding import bucket_rows

        return [(
            self._projector(),
            (jax.ShapeDtypeStruct(
                (bucket_rows(int(n_rows)), int(self.pc.shape[0])),
                jnp.dtype(dtype),
            ),),
        )]

    def transform_matrix(self, x: np.ndarray) -> dict:
        """Role-keyed transform of a bare (n, d) matrix on device — the
        serving surface the data-plane daemon's ``transform`` op calls
        (the accelerator-resident columnar UDF of the reference,
        RapidsPCA.scala:128-161 → rapidsml_jni.cu:75-107)."""
        if self.pc is None:
            raise RuntimeError("PCAModel has no principal components (unfitted?)")
        from spark_rapids_ml_tpu.parallel.sharding import run_bucketed

        with trace_span("pca transform"):
            return {"output": run_bucketed(self._projector(), x)}

    def _transform(self, dataset):
        x = as_matrix(dataset, self.getInputCol())
        y = self.transform_matrix(x)["output"]
        return with_column(dataset, self.getOutputCol(), y)

    def setOutputCol(self, value: str) -> "PCAModel":
        return self._set(outputCol=value)
