"""Per-config benchmark suite for the BASELINE.json workloads.

``bench.py`` at the repo root is the recorded headline (PCA.fit streaming
throughput); the scripts here cover the remaining BASELINE.json configs —
PCA transform latency, KMeans, LinearRegression/LogisticRegression normal
equations, and IVF-Flat approximate KNN. Each prints ONE JSON line
``{"metric", "value", "unit", "vs_baseline"}``; shapes are scaled to a
single chip's HBM (the multi-chip story is sharding-tested in tests/ and
dry-run-compiled via __graft_entry__.dryrun_multichip) and every script has
``SRML_BENCH_*`` env knobs for smoke-testing on small hosts.

``vs_baseline`` denominators are analytic A100 estimates (GEMM-bound at
~110 TFLOP/s sustained TF32, the same convention as bench.py's module
docstring) — the reference repo publishes no numbers (BASELINE.md).
"""

import json
import os


def setup_platform() -> None:
    """Honor SRML_BENCH_PLATFORM=cpu for smoke runs.

    The TPU image's sitecustomize sets ``jax.config.jax_platforms``
    directly, which beats a ``JAX_PLATFORMS`` env var — only a config
    update before the first backend touch overrides it. Call this at the
    top of every bench ``main()``.
    """
    plat = os.environ.get("SRML_BENCH_PLATFORM")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)


def emit(metric: str, value: float, unit: str, vs_baseline: float) -> None:
    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(value, 4),
                "unit": unit,
                "vs_baseline": round(vs_baseline, 4),
            }
        )
    )
