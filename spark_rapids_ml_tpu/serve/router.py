"""Client-side fleet routing: consistent hashing, failover, version pinning.

One daemon serves one host's devices; millions of users need N of them.
This module is the CLIENT half of the fleet layer (serve/fleet.py is the
control plane): a :class:`FleetClient` that routes each ``transform``/
``kneighbors`` request to one of N replica daemons — the Podracer/Anakin
split of a learner plane from a horizontally-scaled inference plane
(PAPERS.md 2104.06272), with the routing decision pushed into the client
so the fleet needs no load-balancer tier in front of it.

Routing (docs/protocol.md "Fleet & versioned serving"):

* **Consistent hashing.** Replicas are points on a hash ring
  (``fleet_vnodes`` virtual nodes each, keyed by a stable digest — not
  Python's salted ``hash``). A request's ``route_key`` (caller-supplied:
  a user id, a session id; default: a fresh per-request nonce, which
  spreads load uniformly) picks the primary replica. Sticky keys give
  cache affinity (a replica's jit caches and scheduler ladder stay hot
  for the traffic hashed to it); adding or removing a replica moves only
  ~1/N of the key space.
* **Least-loaded failover.** When the primary sheds with ``busy`` or is
  dead, the request fails over to the least-loaded remaining replica —
  load read from polled ``health`` snapshots (``queue_depth`` + the
  scheduler's queued count), refreshed at most every
  ``fleet_health_poll_s`` seconds. A replica that fails at the transport
  level is marked dead and skipped until the same interval re-probes it.
* **Exactly-once.** The serving ops are PURE reads of a registered
  model, so a failover retry of a request whose first attempt may have
  reached a dying daemon cannot double-apply anything — the same
  property the feed path earns with ``feed_id`` dedupe, the serving
  path gets by construction. The router still returns exactly one
  response per request, and the underlying :class:`DataPlaneClient`
  healing (reconnect/backoff/deadline, PR 2) runs per attempt.
* **Version pinning.** Every request captures ONE ``(version, epoch)``
  snapshot of the routing table before it routes and stamps it on the
  wire; replicas echo — and with ``serve_version_strict`` enforce — the
  registered version, so a request is never answered by a mixed-epoch
  replica: retries and failovers of one request stay on the version it
  started on, and a replica holding a different version under the
  routed name refuses instead of answering quietly.

The router journals each routed request as a ``router.<op>`` span
(model/version/replica fields) on the calling thread, so the daemon-side
``daemon.transform`` spans — stamped via the client's ``trace_ctx``
(PR 6) — parent under it and one fleet request traces as one tree.

Thread model: a :class:`FleetClient` is single-threaded like the
:class:`DataPlaneClient` it wraps (one socket per replica); give each
worker thread its own (``ModelFleet.client()`` is cheap — the routing
table and its health view are shared and thread-safe).
"""

from __future__ import annotations

import bisect
import hashlib
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from spark_rapids_ml_tpu.serve import protocol
from spark_rapids_ml_tpu.serve.client import DaemonBusy, DataPlaneClient
from spark_rapids_ml_tpu.utils import journal
from spark_rapids_ml_tpu.utils import metrics as metrics_mod
from spark_rapids_ml_tpu.utils.logging import get_logger

logger = get_logger("serve.router")

__all__ = [
    "ConsistentHashRing",
    "FleetClient",
    "FleetUnavailable",
    "RoutingTable",
    "bootstrap_table",
]

#: Router telemetry (docs/observability.md catalogs all of these).
_M_REQUESTS = metrics_mod.counter(
    "srml_router_requests_total",
    "Fleet-routed serving requests, by op and outcome (ok|unroutable)",
)
_M_REQ_SECONDS = metrics_mod.histogram(
    "srml_router_request_seconds",
    "End-to-end routed request latency (all failover attempts), by op",
)
_M_FAILOVERS = metrics_mod.counter(
    "srml_router_failovers_total",
    "Requests rerouted off a replica, by reason (busy|dead|error)",
)
_M_HEALTH_REFRESHES = metrics_mod.counter(
    "srml_router_health_refreshes_total",
    "Replica health polls issued by the router, by outcome (ok|dead)",
)
_M_REPAIRS = metrics_mod.counter(
    "srml_router_repairs_total",
    "Replicas re-registered in-band after answering 'no such model' "
    "(a restarted replica lost its registry; the routing table re-seeds "
    "it from the fleet's stored model payload)",
)
_M_BOOTSTRAPS = metrics_mod.counter(
    "srml_fleet_bootstraps_total",
    "Client pulls of the gossiped FleetView, by outcome (ok = a "
    "bootstrap built a routing table from one seed; error = a seed "
    "attempt failed; resync = a serving ack's version/epoch mismatch "
    "re-pulled the view mid-traffic)",
)


class FleetUnavailable(RuntimeError):
    """Every candidate replica refused (busy/dead/error) within the
    failover budget. Carries the last per-replica error as context."""


def _h64(s: str) -> int:
    """Stable 64-bit point on the ring. Python's ``hash`` is salted per
    process — two clients would disagree about the whole ring."""
    return int.from_bytes(hashlib.sha1(s.encode()).digest()[:8], "big")


class ConsistentHashRing:
    """The standard fixed ring: each replica key contributes ``vnodes``
    points; a request key routes to the first point clockwise. Immutable
    — membership changes (a dead replica) are handled by SKIPPING at
    route time, not rebuilding, so a flapping daemon cannot churn every
    client's key→replica mapping."""

    def __init__(self, keys, vnodes: int = 64):
        keys = list(keys)
        if not keys:
            raise ValueError("hash ring needs at least one replica key")
        points = []
        for k in keys:
            for i in range(max(int(vnodes), 1)):
                points.append((_h64(f"{k}#{i}"), k))
        points.sort()
        self._hashes = [h for h, _ in points]
        self._keys = [k for _, k in points]
        self._members = tuple(dict.fromkeys(keys))

    @property
    def members(self) -> Tuple[str, ...]:
        return self._members

    def primary(self, key: str) -> str:
        """The replica owning ``key``."""
        return self.ordered(key)[0]

    def ordered(self, key: str) -> List[str]:
        """Every member, in ring order from ``key``'s point (the
        primary first, then the natural successor chain — the order a
        pure ring failover would walk)."""
        i = bisect.bisect_right(self._hashes, _h64(key)) % len(self._keys)
        out: List[str] = []
        seen = set()
        for j in range(len(self._keys)):
            k = self._keys[(i + j) % len(self._keys)]
            if k not in seen:
                seen.add(k)
                out.append(k)
                if len(out) == len(self._members):
                    break
        return out


class _Replica:
    """One fleet member: endpoint + the router-shared liveness/load view.
    Mutated only under the owning table's lock."""

    __slots__ = ("key", "host", "port", "alive", "recheck_at", "health",
                 "health_ts", "last_error", "retired", "inflight")

    def __init__(self, host: str, port: int):
        self.host, self.port = host, int(port)
        self.key = f"{host}:{port}"
        self.alive = True
        self.recheck_at = 0.0  # monotonic: when a dead replica re-probes
        self.health: Dict[str, Any] = {}
        self.health_ts = 0.0
        self.last_error: Optional[str] = None
        # Scale-in tombstone: a retired replica left the ring (no NEW
        # request routes to it) but its entry survives, so an in-flight
        # request that snapshotted the OLD ring can still resolve the
        # key it routed to — removal must never turn a live request
        # into a KeyError.
        self.retired = False
        # Routed requests currently executing against THIS replica
        # (begin_replica/done_replica) — the router's live work-in-system
        # view, distinct from the per-VERSION refcounts the drain
        # barrier uses. The autoscaler's default telemetry reads it as
        # the offered-load signal: health's ``queue_depth`` counts open
        # CONNECTIONS (idle fleet clients keep theirs open), which
        # would read as permanent load and pin the controller at "up".
        self.inflight = 0

    def load(self) -> float:
        """Comparable load score: live in-flight routed requests plus
        the last health snapshot's open connections + queued scheduler
        requests (all grow under pressure); a busy replica sorts after
        every non-busy one."""
        h = self.health
        q = float(self.inflight)
        q += float(h.get("queue_depth", 0) or 0)
        sched = h.get("scheduler") or {}
        q += float(sched.get("queued", 0) or 0)
        if h.get("busy"):
            q += 1e6
        return q


class RoutingTable:
    """The fleet's shared state: replicas + per-model version table.

    One table is shared by the control plane (serve/fleet.py) and every
    :class:`FleetClient`; all access is lock-protected and cheap. The
    version table is the zero-downtime rollout mechanism:

    * ``install`` adds a version's registration (name, payload) without
      routing to it;
    * ``activate`` atomically flips the active version and bumps the
      fleet ``epoch`` — requests snapshot ``(version, epoch)`` ONCE at
      entry, so every request is pinned to exactly one version;
    * ``begin``/``done`` refcount in-flight requests per version, and
      ``wait_drained`` blocks until a retired version's count reaches
      zero — the drain barrier that lets v1 finish before it is dropped.
    """

    def __init__(self, endpoints, vnodes: Optional[int] = None):
        from spark_rapids_ml_tpu import config

        reps = []
        for ep in endpoints:
            if isinstance(ep, str):
                host, _, port = ep.rpartition(":")
                reps.append(_Replica(host or "127.0.0.1", int(port)))
            else:
                reps.append(_Replica(ep[0], int(ep[1])))
        if not reps:
            raise ValueError("a fleet needs at least one replica endpoint")
        keys = [r.key for r in reps]
        if len(set(keys)) != len(keys):
            raise ValueError(f"duplicate replica endpoints: {sorted(keys)}")
        self._replicas: Dict[str, _Replica] = {r.key: r for r in reps}
        self._vnodes = int(
            config.get("fleet_vnodes") if vnodes is None else vnodes
        )
        self.ring = ConsistentHashRing(keys, vnodes=self._vnodes)
        self._lock = threading.Lock()
        self._drained = threading.Condition(self._lock)
        #: model → {"active": int|None, "epoch": int,
        #:          "versions": {int: version-info dict}}
        self._models: Dict[str, Dict[str, Any]] = {}
        # Highest gossiped FleetView epoch this table has merged
        # (apply_view) — the client's convergence probe; 0 until the
        # table first sees a gossiped view.
        self._view_epoch = 0

    # -- replicas ----------------------------------------------------------

    def replicas(self) -> List[_Replica]:
        """The CURRENT fleet members (retired scale-in tombstones are
        excluded — the control plane must not register new versions on
        a replica that already left the ring)."""
        with self._lock:
            return [r for r in self._replicas.values() if not r.retired]

    def replica(self, key: str) -> _Replica:
        return self._replicas[key]

    def _rebuild_ring_locked(self) -> None:
        """Swap in a fresh ring over the non-retired members. The ring
        object itself stays immutable — readers grab ``self.ring`` once
        (one atomic attribute load) and route against a consistent
        snapshot; membership changes move only ~1/N of the key space."""
        keys = [k for k, r in self._replicas.items() if not r.retired]
        self.ring = ConsistentHashRing(keys, vnodes=self._vnodes)

    def add_replica(self, endpoint) -> str:
        """Elastic scale-UP (serve/autoscaler.py): admit a new replica
        into the ring. The caller (ModelFleet.scale_out) registers and
        warms every active model version on it FIRST — admission is the
        flip, so the first request routed here finds a warm
        registration, never a cold daemon. Re-admitting a retired key
        clears its tombstone. Returns the replica key."""
        if isinstance(endpoint, str):
            host, _, port = endpoint.rpartition(":")
            r = _Replica(host or "127.0.0.1", int(port))
        else:
            r = _Replica(endpoint[0], int(endpoint[1]))
        with self._lock:
            existing = self._replicas.get(r.key)
            if existing is not None and not existing.retired:
                raise ValueError(f"replica {r.key} is already in the fleet")
            # A re-admitted endpoint gets a FRESH entry: the tombstone's
            # stale health/dead-state must not haunt the newcomer.
            self._replicas[r.key] = r
            self._rebuild_ring_locked()
        return r.key

    def remove_replica(self, key: str) -> None:
        """Elastic scale-DOWN: retire a replica from the ring so no NEW
        request routes to it. In-flight requests that already routed
        there finish normally (the entry survives as a tombstone; the
        daemon itself is only stopped after the version-drain barrier —
        ModelFleet.scale_in). The last live replica cannot be removed:
        an empty ring would make every request unroutable."""
        with self._lock:
            r = self._replicas.get(key)
            if r is None or r.retired:
                raise KeyError(f"no live replica {key!r} in the fleet")
            live = sum(
                1 for rep in self._replicas.values() if not rep.retired
            )
            if live <= 1:
                raise ValueError(
                    f"cannot remove {key!r}: it is the last replica in "
                    "the ring"
                )
            r.retired = True
            self._rebuild_ring_locked()

    def mark_dead(self, key: str, error: str, recheck_s: float) -> None:
        with self._lock:
            r = self._replicas[key]
            r.alive = False
            r.last_error = error
            r.recheck_at = time.monotonic() + max(recheck_s, 0.05)

    def mark_alive(self, key: str, health: Optional[Dict[str, Any]] = None
                   ) -> None:
        with self._lock:
            r = self._replicas[key]
            r.alive = True
            r.last_error = None
            if health is not None:
                r.health = health
                r.health_ts = time.monotonic()

    # -- gossiped fleet view (serve/gossip.py; docs/protocol.md) -----------

    @property
    def view_epoch(self) -> int:
        with self._lock:
            return self._view_epoch

    def apply_view(self, wire: Dict[str, Any]) -> Dict[str, int]:
        """Merge a gossiped FleetView wire dict into this table: admit
        unknown live replicas, retire tombstoned ones (never the last
        live member), and adopt each model's active version/epoch when
        the view's fleet epoch is AHEAD of the local one — the fleet
        epoch only ever moves forward, so a stale island's view can
        never rewind a table past a flip it already saw.

        Version entries created here are PAYLOAD-LESS (``arrays=None``):
        the client can route to them — the replicas already hold the
        registration — but in-band repair refuses, because there is
        nothing local to re-seed a replica from; the client resyncs
        instead. Tolerant by design: this is the bootstrap/resync path
        and must never throw on a half-converged view."""
        out = {"replicas_added": 0, "replicas_retired": 0, "models": 0}
        wire = wire or {}
        with self._lock:
            self._view_epoch = max(
                self._view_epoch, int(wire.get("epoch", 0) or 0)
            )
            for rec in (wire.get("replicas") or {}).values():
                addr = str(rec.get("addr") or "")
                if ":" not in addr:
                    continue
                liveness = rec.get("liveness")
                existing = self._replicas.get(addr)
                if liveness == "tombstone":
                    if existing is not None and not existing.retired:
                        live = sum(
                            1 for r in self._replicas.values()
                            if not r.retired
                        )
                        if live > 1:
                            existing.retired = True
                            out["replicas_retired"] += 1
                elif liveness == "up":
                    if existing is None or existing.retired:
                        host, _, port = addr.rpartition(":")
                        self._replicas[addr] = _Replica(
                            host or "127.0.0.1", int(port)
                        )
                        out["replicas_added"] += 1
                # liveness == "down": keep the member — gossip decides
                # MEMBERSHIP; the router's own health probes decide
                # moment-to-moment aliveness.
            if out["replicas_added"] or out["replicas_retired"]:
                self._rebuild_ring_locked()
            for name, rec in (wire.get("models") or {}).items():
                entry = self._models.setdefault(
                    name, {"active": None, "epoch": 0, "versions": {}}
                )
                # Lamport-dominance per record: a record this table
                # already merged (or wrote) at a higher gossip epoch
                # wins over a stale island's copy.
                ge = int(rec.get("epoch", 0) or 0)
                if ge < int(entry.get("_gossip_epoch", 0)):
                    continue
                entry["_gossip_epoch"] = ge
                out["models"] += 1
                active = rec.get("active_version")
                active = None if active is None else int(active)
                fe = int(rec.get("fleet_epoch", 0) or 0)
                if (
                    active is not None
                    and active not in entry["versions"]
                    and fe >= entry["epoch"]
                ):
                    entry["versions"][active] = {
                        "reg_name": self.reg_name(name, active),
                        "algo": None, "arrays": None, "params": {},
                        "inflight": 0,
                    }
                for vs in (rec.get("tombstones") or {}):
                    v = int(vs)
                    info = entry["versions"].get(v)
                    if (
                        v != active and v != entry["active"]
                        and info is not None and info["inflight"] <= 0
                    ):
                        entry["versions"].pop(v, None)
                if fe > entry["epoch"] or (
                    fe == entry["epoch"] and entry["active"] is None
                ):
                    entry["active"] = active
                    entry["epoch"] = fe
                entry["intent"] = rec.get("intent")
        return out

    def intent(self, model: str) -> Optional[Dict[str, Any]]:
        """The model's gossiped rollout-intent record, or None — what a
        successor controller reads to complete or abort an interrupted
        rollout (ModelFleet.resume_rollout)."""
        with self._lock:
            entry = self._models.get(model)
            return None if entry is None else entry.get("intent")

    def set_intent(self, model: str,
                   intent: Optional[Dict[str, Any]]) -> None:
        with self._lock:
            entry = self._models.setdefault(
                model, {"active": None, "epoch": 0, "versions": {}}
            )
            entry["intent"] = intent

    def intents(self) -> Dict[str, Dict[str, Any]]:
        """Every model with a live rollout intent — what the
        autoscaler's orphan-adoption sweep iterates. Includes models
        with NO active version (a rollout interrupted while
        registering a brand-new model)."""
        with self._lock:
            return {
                m: dict(e["intent"]) for m, e in self._models.items()
                if e.get("intent")
            }

    # -- version table -----------------------------------------------------

    @staticmethod
    def reg_name(model: str, version: int) -> str:
        """The daemon-side registration name of one model version. The
        '@v' convention IS the isolation mechanism: two versions are two
        registry entries, so an in-flight v1 request addressed to
        ``m@v1`` can never be answered from v2's arrays."""
        return f"{model}@v{int(version)}"

    def install(self, model: str, version: int, algo: str,
                arrays: Dict[str, np.ndarray],
                params: Optional[Dict[str, Any]] = None) -> str:
        """Add (or refresh) a version entry without routing to it.
        Returns the daemon registration name."""
        version = int(version)
        with self._lock:
            entry = self._models.setdefault(
                model, {"active": None, "epoch": 0, "versions": {}}
            )
            # Re-installing an existing version (an operator re-seeding a
            # fleet) refreshes the payload but PRESERVES the in-flight
            # refcount: resetting it to 0 would let a later drain declare
            # "drained" while those requests still fly — exactly the
            # yanked-arrays failure the barrier exists to prevent.
            prev = entry["versions"].get(version)
            entry["versions"][version] = {
                "reg_name": self.reg_name(model, version),
                "algo": str(algo),
                "arrays": dict(arrays),
                "params": dict(params or {}),
                "inflight": 0 if prev is None else prev["inflight"],
            }
        return self.reg_name(model, version)

    def ensure_version(self, model: str, version: int) -> str:
        """Make sure a version ENTRY exists, creating a payload-less
        one (``arrays=None`` — routable, not repairable) when absent.
        A successor controller completing a gossiped rollout intent
        needs the to-version activatable even though the payload died
        with its predecessor: the replicas still hold the registration.
        Returns the registration name."""
        version = int(version)
        with self._lock:
            entry = self._models.setdefault(
                model, {"active": None, "epoch": 0, "versions": {}}
            )
            if version not in entry["versions"]:
                entry["versions"][version] = {
                    "reg_name": self.reg_name(model, version),
                    "algo": None, "arrays": None, "params": {},
                    "inflight": 0,
                }
        return self.reg_name(model, version)

    def activate(self, model: str, version: int) -> int:
        """Atomically flip the model's active version; bumps and returns
        the fleet epoch. Requests that snapshotted before the flip keep
        their old (version, epoch) pin to completion."""
        version = int(version)
        with self._lock:
            entry = self._models[model]
            if version not in entry["versions"]:
                raise KeyError(
                    f"version {version} of {model!r} was never installed"
                )
            entry["active"] = version
            entry["epoch"] += 1
            return entry["epoch"]

    def retire(self, model: str, version: int) -> None:
        with self._lock:
            entry = self._models.get(model)
            if entry is None:
                return
            if entry.get("active") == int(version):
                raise ValueError(
                    f"cannot retire the ACTIVE version {version} of "
                    f"{model!r}; activate a successor first"
                )
            entry["versions"].pop(int(version), None)

    def snapshot(self, model: str) -> Tuple[int, int, str]:
        """(active version, epoch, daemon registration name) — a
        read-only view for control-plane callers. Requests must use
        :meth:`acquire` instead: a snapshot alone does not hold the
        version against a concurrent drain."""
        with self._lock:
            return self._snapshot_locked(model)

    def _snapshot_locked(self, model: str) -> Tuple[int, int, str]:
        entry = self._models.get(model)
        if entry is None or entry["active"] is None:
            raise KeyError(
                f"no active version for model {model!r} (register it "
                "through the fleet first)"
            )
        v = entry["active"]
        return v, entry["epoch"], entry["versions"][v]["reg_name"]

    def acquire(self, model: str) -> Tuple[int, int, str]:
        """Atomically snapshot the active (version, epoch, reg_name) AND
        take an in-flight reference on that version — ONE lock
        acquisition, so a concurrent rollout can never flip-drain-retire
        the version between a request's read and its refcount (the
        zero-downtime contract's linchpin). Pair with :meth:`done`."""
        with self._lock:
            v, epoch, reg = self._snapshot_locked(model)
            self._models[model]["versions"][v]["inflight"] += 1
            return v, epoch, reg

    def version_info(self, model: str, version: int) -> Dict[str, Any]:
        """Registration payload of one version (the in-band repair
        source). Returns a shallow copy; arrays are shared read-only."""
        with self._lock:
            info = self._models[model]["versions"][int(version)]
            return {k: v for k, v in info.items() if k != "inflight"}

    def versions(self, model: str) -> List[int]:
        with self._lock:
            entry = self._models.get(model)
            return sorted(entry["versions"]) if entry else []

    def models(self) -> List[str]:
        """Model names with an ACTIVE version — the set a scale-out
        must re-seed on a joining replica (ModelFleet.scale_out)."""
        with self._lock:
            return sorted(
                m for m, e in self._models.items()
                if e["active"] is not None
            )

    def begin_replica(self, key: str) -> None:
        """Count a routed request in on ``key`` (see _Replica.inflight);
        unknown keys no-op — a replica removed mid-request still gets
        its ``done_replica`` via the same tolerant path."""
        with self._lock:
            r = self._replicas.get(key)
            if r is not None:
                r.inflight += 1

    def done_replica(self, key: str) -> None:
        with self._lock:
            r = self._replicas.get(key)
            if r is not None and r.inflight > 0:
                r.inflight -= 1

    def begin(self, model: str, version: int) -> None:
        with self._lock:
            self._models[model]["versions"][int(version)]["inflight"] += 1

    def done(self, model: str, version: int) -> None:
        with self._lock:
            entry = self._models.get(model)
            info = entry and entry["versions"].get(int(version))
            if info is None:
                return  # retired while we flew — drain already gave up on us
            info["inflight"] -= 1
            if info["inflight"] <= 0:
                self._drained.notify_all()

    def inflight(self, model: str, version: int) -> int:
        with self._lock:
            entry = self._models.get(model)
            info = entry and entry["versions"].get(int(version))
            return 0 if info is None else int(info["inflight"])

    def wait_drained(self, model: str, version: int,
                     timeout_s: float) -> bool:
        """Block until no request is in flight on ``version`` (True) or
        the timeout passes (False) — the rollout's drain barrier."""
        deadline = time.monotonic() + float(timeout_s)
        with self._lock:
            while True:
                entry = self._models.get(model)
                info = entry and entry["versions"].get(int(version))
                if info is None or info["inflight"] <= 0:
                    return True
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._drained.wait(timeout=remaining)


def _seed_list(seeds) -> List[str]:
    """Normalize a seeds argument — None (fall back to the
    ``fleet_seed_addresses`` config/env/Spark-conf ladder), one
    comma-separated string, or an iterable — into a list of
    ``host:port`` strings."""
    from spark_rapids_ml_tpu import config

    if seeds is None:
        seeds = config.get("fleet_seed_addresses")
    if isinstance(seeds, str):
        seeds = [s.strip() for s in seeds.split(",") if s.strip()]
    out: List[str] = []
    for s in seeds or []:
        if isinstance(s, str):
            out.append(s)
        else:  # ("host", port) pairs — daemon.address and friends
            out.append(f"{s[0]}:{int(s[1])}")
    return out


def bootstrap_table(
    seeds=None,
    token: Optional[str] = None,
    vnodes: Optional[int] = None,
    client_kwargs: Optional[Dict[str, Any]] = None,
    passes: int = 3,
) -> RoutingTable:
    """Build a :class:`RoutingTable` from ONE reachable seed daemon.

    The fleet's membership and version tables live IN the daemons
    (gossiped FleetView, serve/gossip.py), so a fresh client needs no
    endpoint roster and no surviving predecessor: it pulls the view
    from the first seed that answers and builds its ring from the live
    replicas in it. Seeds are tried in order; after each full failed
    pass the client backs off on the decorrelated-jitter ladder
    (utils/retry.py) before the next, up to ``passes`` passes. Each
    attempt crosses the ``fleet.bootstrap`` fault site first, so chaos
    tests can fail seeds deterministically (docs/fault_injection.md).

    Raises :class:`FleetUnavailable` when no seed yields a usable view.
    """
    from spark_rapids_ml_tpu.utils import faults
    from spark_rapids_ml_tpu.utils.retry import decorrelated_jitter

    seeds = _seed_list(seeds)
    if not seeds:
        raise ValueError(
            "fleet bootstrap needs at least one seed address: pass "
            "seeds=, or set fleet_seed_addresses / "
            "SRML_FLEET_SEED_ADDRESSES / spark.srml.fleet.seed_addresses"
        )
    kw: Dict[str, Any] = {
        "timeout": 5.0, "op_deadline_s": 10.0, "max_op_attempts": 1,
    }
    kw.update(client_kwargs or {})
    last_err: Optional[BaseException] = None
    delay = 0.0
    for p in range(max(int(passes), 1)):
        if p:
            delay = decorrelated_jitter(delay, 0.05, 2.0)
            time.sleep(delay)
        for addr in seeds:
            host, _, port = str(addr).rpartition(":")
            try:
                faults.checkpoint("fleet.bootstrap")
                with DataPlaneClient(
                    host or "127.0.0.1", int(port), token=token, **kw
                ) as c:
                    view = c.gossip_pull()
                endpoints = sorted(
                    r["addr"] for r in (view.get("replicas") or {}).values()
                    if r.get("liveness") == "up" and r.get("addr")
                )
                if not endpoints:
                    raise FleetUnavailable(
                        f"seed {addr} answered with no live replicas in "
                        "its view"
                    )
                table = RoutingTable(endpoints, vnodes=vnodes)
                table.apply_view(view)
                _M_BOOTSTRAPS.inc(outcome="ok")
                logger.info(
                    "bootstrapped fleet from seed %s: %d replica(s), "
                    "%d model(s), view epoch %d",
                    addr, len(endpoints), len(table.models()),
                    table.view_epoch,
                )
                return table
            except (OSError, ValueError, protocol.ProtocolError,
                    RuntimeError) as e:
                last_err = e
                _M_BOOTSTRAPS.inc(outcome="error")
                logger.warning("fleet bootstrap via seed %s failed: %s",
                               addr, e)
    raise FleetUnavailable(
        f"no seed of {seeds} yielded a usable fleet view "
        f"(last error: {last_err})"
    ) from last_err


class FleetClient:
    """Route serving requests across a fleet's replicas (module
    docstring has the routing contract). Constructed from a shared
    :class:`RoutingTable` — usually via ``ModelFleet.client()``, or
    bootstrapped from one seed daemon via :meth:`from_seeds`."""

    def __init__(
        self,
        table: RoutingTable,
        token: Optional[str] = None,
        health_poll_s: Optional[float] = None,
        failover_attempts: Optional[int] = None,
        client_kwargs: Optional[Dict[str, Any]] = None,
    ):
        from spark_rapids_ml_tpu import config

        self._table = table
        self._token = token
        self._poll_s = float(
            config.get("fleet_health_poll_s")
            if health_poll_s is None else health_poll_s
        )
        n = int(
            config.get("fleet_failover_attempts")
            if failover_attempts is None else failover_attempts
        )
        # 0 = one attempt per replica: every CURRENT member gets exactly
        # one chance before the request is declared unroutable — read
        # per request, not frozen at construction, so a client created
        # before an autoscaler grew the fleet failovers across the
        # grown membership too.
        self._attempts = n if n > 0 else None
        # Inner-client defaults tuned for FAILOVER, not solo healing: a
        # busy shed must surface immediately (max_busy_wait_s=0 — the
        # router's reroute IS the retry), and a dead replica must fail
        # in seconds, not socket-default minutes. Callers can override
        # any of these per fleet.
        kw: Dict[str, Any] = {
            "timeout": 10.0,
            "op_deadline_s": 15.0,
            "max_op_attempts": 2,
            "max_busy_wait_s": 0.0,
        }
        kw.update(client_kwargs or {})
        self._client_kwargs = kw
        self._clients: Dict[str, DataPlaneClient] = {}
        self._nonce = uuid.uuid4().hex[:12]
        self._seq = 0
        #: replica key → requests this client had ANSWERED there — the
        #: per-client routing distribution (chaos tests and affinity
        #: debugging read it; the process-wide aggregate lives in the
        #: srml_router_* registry metrics).
        self.stats: Dict[str, int] = {}

    @classmethod
    def from_seeds(
        cls,
        seeds=None,
        token: Optional[str] = None,
        health_poll_s: Optional[float] = None,
        failover_attempts: Optional[int] = None,
        client_kwargs: Optional[Dict[str, Any]] = None,
        vnodes: Optional[int] = None,
    ) -> "FleetClient":
        """A fully routable client from ONE seed address (or the
        ``fleet_seed_addresses`` ladder) — no endpoint roster, no
        surviving predecessor client: the table comes from the seed's
        gossiped FleetView (:func:`bootstrap_table`)."""
        table = bootstrap_table(
            seeds, token=token, vnodes=vnodes,
            client_kwargs=client_kwargs,
        )
        return cls(
            table, token=token, health_poll_s=health_poll_s,
            failover_attempts=failover_attempts,
            client_kwargs=client_kwargs,
        )

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        for c in self._clients.values():
            c.close()
        self._clients.clear()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- replica selection -------------------------------------------------

    def _client(self, key: str) -> DataPlaneClient:
        c = self._clients.get(key)
        if c is None:
            r = self._table.replica(key)
            c = DataPlaneClient(
                r.host, r.port, token=self._token, **self._client_kwargs
            )
            self._clients[key] = c
        return c

    def _refresh_health(self, key: str) -> None:
        """Poll one replica's health when its snapshot is stale; a
        failed poll marks it dead until the next poll interval."""
        r = self._table.replica(key)
        now = time.monotonic()
        if r.alive and now - r.health_ts < self._poll_s:
            return
        if not r.alive and now < r.recheck_at:
            return
        try:
            health = self._client(key).health()
        except (OSError, protocol.ProtocolError, RuntimeError) as e:
            _M_HEALTH_REFRESHES.inc(outcome="dead")
            self._table.mark_dead(key, str(e), self._poll_s)
            return
        _M_HEALTH_REFRESHES.inc(outcome="ok")
        self._table.mark_alive(key, health)

    def _candidates(self, route_key: str) -> List[str]:
        """Attempt order for one request: the ring primary first (cache
        affinity), then every other live replica least-loaded-first —
        the failover half of the contract. Dead replicas past their
        recheck time still appear (at the end): the router must be able
        to REDISCOVER a healed replica without an operator poke."""
        order = self._table.ring.ordered(route_key)
        for k in order:
            self._refresh_health(k)
        now = time.monotonic()
        primary = order[0]
        rest = order[1:]
        live = [k for k in rest if self._table.replica(k).alive]
        live.sort(key=lambda k: self._table.replica(k).load())
        dead = [
            k for k in rest
            if not self._table.replica(k).alive
            and now >= self._table.replica(k).recheck_at
        ]
        head = [primary] if (
            self._table.replica(primary).alive
            or now >= self._table.replica(primary).recheck_at
        ) else []
        return (head + live + dead) if head else (live + dead + [primary])

    def _route_key(self, route_key: Optional[str]) -> str:
        if route_key is not None:
            return str(route_key)
        self._seq += 1
        return f"{self._nonce}-{self._seq}"

    # -- serving ops -------------------------------------------------------

    def transform(
        self,
        model: str,
        data,
        route_key: Optional[str] = None,
        input_col: str = "features",
        n_cols: Optional[int] = None,
        deadline_s: Optional[float] = None,
    ) -> Dict[str, np.ndarray]:
        """Routed :meth:`DataPlaneClient.transform` against the model's
        ACTIVE version. Returns the role-keyed output arrays."""
        return self._request(
            "transform", model, route_key,
            lambda c, reg, v, e: c.transform(
                reg, data, input_col=input_col, n_cols=n_cols,
                deadline_s=deadline_s, version=v, fleet_epoch=e,
            ),
        )

    def kneighbors(
        self,
        model: str,
        queries,
        k: Optional[int] = None,
        route_key: Optional[str] = None,
        input_col: str = "features",
        n_cols: Optional[int] = None,
        deadline_s: Optional[float] = None,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Routed :meth:`DataPlaneClient.kneighbors`: (distances,
        indices) from the model's ACTIVE version."""
        return self._request(
            "kneighbors", model, route_key,
            lambda c, reg, v, e: c.kneighbors(
                reg, queries, k=k, input_col=input_col, n_cols=n_cols,
                deadline_s=deadline_s, version=v, fleet_epoch=e,
            ),
        )

    def _repair(self, key: str, model: str, version: int) -> bool:
        """Re-register a version on a replica that answered "no such
        model" — a restarted replica lost its (re-creatable) registry.
        The payload comes from the routing table; failure just means the
        failover continues."""
        try:
            info = self._table.version_info(model, version)
        except KeyError:
            return False
        if info.get("arrays") is None:
            # A PAYLOAD-LESS entry adopted from a gossiped view
            # (RoutingTable.apply_view) — nothing local to re-seed the
            # replica from; the caller falls through to a resync.
            return False
        try:
            self._client(key).ensure_model(
                info["reg_name"], info["algo"], info["arrays"],
                params=info["params"], version=version,
            )
        except (OSError, protocol.ProtocolError, RuntimeError) as e:
            logger.warning(
                "in-band repair of %s v%d on %s failed: %s",
                model, version, key, e,
            )
            return False
        _M_REPAIRS.inc()
        logger.warning(
            "re-registered %s v%d on replica %s (it had lost the "
            "registration)", model, version, key,
        )
        return True

    def _resync(self, key: str, model: str) -> bool:
        """Re-pull the gossiped FleetView from the ANSWERING replica
        after a ``version mismatch`` ack or an unrepairable "no such
        model" — the replica that refused KNOWS the fleet state this
        client's table missed (a rollout it slept through), so resyncing
        from it beats erroring out (docs/protocol.md "Fleet gossip &
        bootstrap"). Never raises; False just continues the failover."""
        try:
            view = self._client(key).gossip_pull()
        except (OSError, protocol.ProtocolError, RuntimeError) as e:
            logger.warning("fleet resync from %s failed: %s", key, e)
            return False
        if not view:
            return False
        self._table.apply_view(view)
        _M_BOOTSTRAPS.inc(outcome="resync")
        logger.info(
            "resynced routing table from %s for model %r (view epoch %d)",
            key, model, self._table.view_epoch,
        )
        return True

    def _request(self, kind: str, model: str, route_key, attempt_fn):
        # ONE atomic snapshot-and-refcount pins this request — and every
        # failover retry of it — to a single version (docs/protocol.md
        # "Fleet & versioned serving"); taken in one lock acquisition so
        # a concurrent rollout cannot drain-and-retire the version
        # between the read and the refcount.
        version, epoch, reg_name = self._table.acquire(model)
        t0 = time.perf_counter()
        key = self._route_key(route_key)
        last_err: Optional[BaseException] = None
        tried = 0
        resynced = False
        attempts = self._attempts or len(self._table.ring.members)
        try:
            with journal.span(
                f"router.{kind}", model=model, version=version, epoch=epoch,
            ):
                for rk in self._candidates(key):
                    if tried >= attempts:
                        break
                    tried += 1
                    repaired = False
                    self._table.begin_replica(rk)
                    try:
                        while True:
                            try:
                                out = attempt_fn(
                                    self._client(rk), reg_name, version, epoch
                                )
                                self._table.mark_alive(rk)
                                self.stats[rk] = self.stats.get(rk, 0) + 1
                                _M_REQUESTS.inc(op=kind, outcome="ok")
                                return out
                            except DaemonBusy as e:
                                last_err = e
                                _M_FAILOVERS.inc(reason="busy")
                                break
                            except (OSError, protocol.ProtocolError) as e:
                                last_err = e
                                _M_FAILOVERS.inc(reason="dead")
                                self._table.mark_dead(
                                    rk, str(e), self._poll_s
                                )
                                break
                            except RuntimeError as e:
                                last_err = e
                                msg = str(e)
                                if (
                                    not repaired
                                    and "no such model" in msg
                                    and self._repair(rk, model, version)
                                ):
                                    repaired = True
                                    continue  # retry THIS replica once
                                if (
                                    not resynced
                                    and ("version mismatch" in msg
                                         or "no such model" in msg)
                                    and self._resync(rk, model)
                                ):
                                    # The replica refused because OUR
                                    # pin is stale (a rollout flipped
                                    # while this client slept). Re-pin
                                    # on the resynced table — acquire
                                    # the NEW version before releasing
                                    # the old, so the drain refcounts
                                    # stay exactly-once — and retry
                                    # this replica on the fresh pin.
                                    resynced = True
                                    try:
                                        nv, ne, nr = (
                                            self._table.acquire(model)
                                        )
                                    except KeyError:
                                        _M_FAILOVERS.inc(reason="error")
                                        break
                                    self._table.done(model, version)
                                    version, epoch, reg_name = nv, ne, nr
                                    continue
                                _M_FAILOVERS.inc(reason="error")
                                break
                    finally:
                        self._table.done_replica(rk)
            _M_REQUESTS.inc(op=kind, outcome="unroutable")
            raise FleetUnavailable(
                f"no replica could serve {kind} for {model!r} v{version} "
                f"({tried} attempt(s); last error: {last_err})"
            ) from last_err
        finally:
            self._table.done(model, version)
            _M_REQ_SECONDS.observe(time.perf_counter() - t0, op=kind)
